"""Elastic ComputeDomains e2e tier (ISSUE 14).

The acceptance scenario: a 4-host v5e-16 domain loses a host via the
``sim.tpu.google.com/node-down`` chaos annotation, heals to 3 hosts
through a full resize epoch (recompiled mesh bundle at a bumped revision,
exact loss parity at the new size, DomainDegraded -> DomainResizing ->
DomainHealed event chain, zero leaked ICI partitions by StubPartitionClient
ledger read-back), then grows back to 4 hosts when the node returns — and
a fault-injected crash mid-resize rolls back to the exact prior placement.
Plus the WAL crash/restore satellite (kill the store between quiesce and
re-place, restore, resume) and the clique re-join idempotency regression
the rollback path depends on.
"""

import json
import os
import subprocess
import sys

import pytest

from k8s_dra_driver_tpu.k8s.core import (
    COMPUTE_DOMAIN,
    COMPUTE_DOMAIN_CLIQUE,
    NODE,
    POD,
    RESOURCE_CLAIM,
)
from k8s_dra_driver_tpu.pkg.meshgen import MESH_BUNDLE_ENV, MeshBundle
from k8s_dra_driver_tpu.plugins.checkpoint import (
    MIGRATION_CHECKPOINTED,
    PREPARE_COMPLETED,
)
from k8s_dra_driver_tpu.sim import SimCluster
from k8s_dra_driver_tpu.sim.cluster import CHAOS_NODE_DOWN_ANNOTATION
from k8s_dra_driver_tpu.sim.kubectl import describe_object, load_manifests

ELASTIC_GATES = ("ElasticComputeDomains=true,ICIPartitioning=true,"
                 "DynamicSubslice=true")


@pytest.fixture(autouse=True)
def boot_id(tmp_path, monkeypatch):
    p = tmp_path / "boot_id"
    p.write_text("boot-1\n")
    monkeypatch.setenv("ALT_TPU_BOOT_ID_PATH", str(p))


CD_MANIFEST = """
apiVersion: v1
kind: Namespace
metadata: {name: grid}
---
apiVersion: resource.tpu.google.com/v1beta1
kind: ComputeDomain
metadata: {name: dom, namespace: grid}
spec:
  numNodes: 4
  channel:
    resourceClaimTemplate: {name: dom-channel}
---
apiVersion: resource.k8s.io/v1
kind: ResourceClaimTemplate
metadata: {name: whole-host, namespace: grid}
spec:
  spec:
    devices:
      requests: [{name: tpus, exactly: {deviceClassName: tpu.google.com, allocationMode: All}}]
---
apiVersion: resource.k8s.io/v1
kind: ResourceClaimTemplate
metadata: {name: sub12, namespace: grid}
spec:
  spec:
    devices:
      requests: [{name: t, exactly: {deviceClassName: subslice.tpu.google.com, count: 1, selectors: ["profile=1x2"]}}]
"""

CD_WORKER = """
apiVersion: v1
kind: Pod
metadata: {name: dom-worker-%(i)d, namespace: grid}
spec:
  containers: [{name: jax, image: x}]
  resourceClaims:
  - {name: tpus, resourceClaimTemplateName: whole-host}
  - {name: channel, resourceClaimTemplateName: dom-channel}
"""

# A bystander pod holding a carved ICI partition (DynamicSubslice 1x2) on
# a NON-member host: its partition must survive every kill/heal/grow cycle
# untouched, and the StubPartitionClient read-back across ALL nodes is what
# proves the resize epochs leak nothing.
BYSTANDER = """
apiVersion: v1
kind: Pod
metadata: {name: bystander, namespace: grid}
spec:
  nodeName: %(node)s
  containers: [{name: c, image: x}]
  resourceClaims: [{name: t, resourceClaimTemplateName: sub12}]
"""


def _apply(sim, text):
    for obj in load_manifests(text):
        sim.api.create(obj)


def _events(sim, reason, namespace=None):
    evs = (sim.api.list("Event", namespace=namespace) if namespace
           else sim.api.list("Event"))
    return [e for e in evs if e.reason == reason]


def _set_node_down(sim, node, down):
    def mutate(obj, down=down):
        if down:
            obj.meta.annotations[CHAOS_NODE_DOWN_ANNOTATION] = "true"
        else:
            obj.meta.annotations.pop(CHAOS_NODE_DOWN_ANNOTATION, None)
    sim.api.update_with_retry(NODE, node, "", mutate)


def _domain(sim):
    return sim.api.get(COMPUTE_DOMAIN, "dom", "grid")


def _assemble(sim):
    _apply(sim, CD_MANIFEST)
    for i in range(4):
        _apply(sim, CD_WORKER % {"i": i})
    assert sim.wait_for(
        lambda s: _domain(s).status.status == "Ready"
        and all(p.phase == "Running"
                for p in s.api.list(POD, namespace="grid")
                if p.meta.name.startswith("dom-worker")),
        max_steps=40), [
            (p.meta.name, p.phase) for p in sim.api.list(POD,
                                                         namespace="grid")]
    return _domain(sim)


def _ledger_matches_live_claims(sim):
    """The StubPartitionClient read-back: every node's active partitions
    correspond 1:1 to its PREPARE_COMPLETED subslice claims, and no
    checkpoint holds MigrationCheckpoint residue. Returns (ok, detail)."""
    for name, node in sim.nodes.items():
        state = node.tpu_driver.state
        active = state.partitions.active_partitions()
        entries = state.prepared_claims()
        migration = [uid for uid, e in entries.items()
                     if e.state == MIGRATION_CHECKPOINTED]
        if migration:
            return False, f"{name}: MigrationCheckpoint residue {migration}"
        completed_subslices = sum(
            1 for e in entries.values()
            if e.state == PREPARE_COMPLETED
            and any(d.device_type == "subslice" for d in e.devices))
        if len(active) != completed_subslices:
            return False, (f"{name}: {len(active)} active partition(s) vs "
                           f"{completed_subslices} completed subslice "
                           f"claim(s)")
    return True, ""


def _loss_parity_at_size(bundle: MeshBundle) -> float:
    """Exact loss parity at the healed size, in a child process with
    exactly ``bundle.num_devices`` virtual CPU devices: the same tiny
    forward pass computed on a bundle-ordered mesh and on the plain
    enumeration-order mesh must produce bit-identical losses (reordering
    devices must never change training semantics)."""
    n = bundle.num_devices
    data, model = bundle.axis_sizes[0], bundle.axis_sizes[-1]
    script = f"""
import json, os, sys
sys.path.insert(0, {json.dumps(os.path.dirname(os.path.dirname(os.path.abspath(__file__))))})
from __graft_entry__ import _ensure_devices
_ensure_devices({n})
import jax, jax.numpy as jnp, numpy as np
from jax.sharding import NamedSharding, PartitionSpec as P
from k8s_dra_driver_tpu.parallel.mesh import family_mesh, load_bundle
b = load_bundle()
assert b is not None and b.num_devices == {n}, b
devs = jax.devices()

def loss_with(bundle):
    m = family_mesh(devs, ({data}, {model}), ("data", "model"),
                    bundle=bundle)
    x = (np.arange({n} * 4, dtype=np.float32).reshape({n}, 4)
         / float({n} * 4))
    w = np.linspace(0.0, 1.0, 4 * {2 * model},
                    dtype=np.float32).reshape(4, {2 * model})
    xs = jax.device_put(x, NamedSharding(m, P("data", None)))
    ws = jax.device_put(w, NamedSharding(m, P(None, "model")))
    y = jnp.tanh(xs @ ws)
    return float(jnp.mean(y * y))

print(json.dumps({{"bundle": loss_with(b), "naive": loss_with(None)}}))
"""
    env = dict(os.environ)
    env["JAX_PLATFORMS"] = "cpu"
    # The parent's XLA_FLAGS already pins an 8-device count (conftest);
    # the child needs exactly the healed size.
    env["XLA_FLAGS"] = f"--xla_force_host_platform_device_count={n}"
    env[MESH_BUNDLE_ENV] = bundle.to_json()
    proc = subprocess.run([sys.executable, "-c", script],
                          capture_output=True, text=True, env=env,
                          timeout=300)
    assert proc.returncode == 0, proc.stderr[-800:]
    doc = json.loads(proc.stdout.strip().splitlines()[-1])
    return abs(doc["bundle"] - doc["naive"])


def test_node_down_heal_and_grow_back(tmp_path):
    """THE acceptance scenario: kill one host of an assembled 4-host
    v5e-16 domain, heal to 3 through a full resize epoch, grow back to 4
    when the host returns — bundle revisions bumped each way, event chain
    in order, worker slots stable, ledgers clean."""
    sim = SimCluster(workdir=str(tmp_path), profile="v5e-16", num_hosts=8,
                     gates=ELASTIC_GATES)
    sim.start()
    try:
        cd = _assemble(sim)
        block_nodes = list(cd.status.placement.nodes)
        assert cd.status.placement.block_shape == "2x2"
        rev0 = cd.status.mesh_bundle.revision
        assert cd.status.epoch == 0
        # Bystander partition on the spare slice: the leak canary.
        spare = next(n for n in sorted(sim.nodes) if n not in block_nodes)
        _apply(sim, BYSTANDER % {"node": spare})
        assert sim.wait_for(
            lambda s: s.api.get(POD, "bystander", "grid").phase == "Running",
            max_steps=20)
        assert len(sim.nodes[spare].tpu_driver.state
                   .partitions.active_partitions()) == 1
        ok, why = _ledger_matches_live_claims(sim)
        assert ok, why
        clique0 = next(c for c in sim.api.list(COMPUTE_DOMAIN_CLIQUE,
                                               namespace="grid")
                       if c.domain_uid == cd.uid)
        victim = block_nodes[1]
        victim_slot = clique0.node_info(victim).index

        # -- kill one member host ------------------------------------------
        _set_node_down(sim, victim, True)
        assert sim.wait_for(
            lambda s: _domain(s).status.epoch == 1
            and _domain(s).status.status == "Ready"
            and _domain(s).status.resize is None, max_steps=60), (
                _domain(sim).status.resize,
                _domain(sim).status.status)
        healed = _domain(sim)
        survivors = [n for n in block_nodes if n != victim]
        assert list(healed.status.placement.nodes) == survivors
        assert healed.status.placement.block_shape == "1x3"
        assert healed.status.desired_nodes == 3
        bundle = healed.status.mesh_bundle
        assert bundle.revision > rev0
        assert {d.node for d in bundle.device_order} == set(survivors)
        assert bundle.num_devices == 12

        # The event chain, in causal order on their first timestamps.
        chain = {}
        for reason in ("DomainDegraded", "DomainResizing", "DomainHealed"):
            evs = _events(sim, reason, namespace="grid")
            assert evs, f"missing {reason}"
            chain[reason] = min(e.first_timestamp for e in evs)
        assert (chain["DomainDegraded"] <= chain["DomainResizing"]
                <= chain["DomainHealed"])

        # Surviving workers restarted INTO the new mesh: their injected
        # env carries the recompiled bundle at the bumped revision.
        for p in sim.api.list(POD, namespace="grid"):
            if not p.meta.name.startswith("dom-worker"):
                continue
            assert p.node_name in survivors
            assert p.phase == "Running"
            env_bundle = MeshBundle.from_json(
                p.injected_env[MESH_BUNDLE_ENV])
            assert env_bundle.revision == bundle.revision
            assert env_bundle.num_devices == 12
        # The dead host's worker was evicted.
        assert sim.api.try_get(POD, f"dom-worker-{block_nodes.index(victim)}",
                               "grid") is None

        # Ledger read-back on every LIVE node: member nodes hold no
        # partitions (whole-host claims), no MigrationCheckpoint residue
        # anywhere, and the bystander's partition is untouched.
        for name in survivors:
            state = sim.nodes[name].tpu_driver.state
            assert state.partitions.active_partitions() == [], name
            assert not any(e.state == MIGRATION_CHECKPOINTED
                           for e in state.prepared_claims().values()), name
        assert len(sim.nodes[spare].tpu_driver.state
                   .partitions.active_partitions()) == 1

        # Exact loss parity at the new size (12 devices, data=2 x model=6).
        assert _loss_parity_at_size(bundle) == 0.0

        # Describe renders the elastic surface.
        out = describe_object(sim.api, COMPUTE_DOMAIN, "dom",
                              namespace="grid")
        assert "Epoch:     1 (membership 3/4 desired)" in out

        # -- the host returns ----------------------------------------------
        _set_node_down(sim, victim, False)
        assert sim.wait_for(
            lambda s: _domain(s).status.epoch == 2
            and _domain(s).status.status == "Ready"
            and _domain(s).status.resize is None, max_steps=80), (
                _domain(sim).status.resize, _domain(sim).status.status)
        grown = _domain(sim)
        assert set(grown.status.placement.nodes) == set(block_nodes)
        assert grown.status.placement.block_shape == "2x2"
        assert grown.status.desired_nodes == 4
        assert grown.status.mesh_bundle.revision > bundle.revision
        assert {d.node for d in grown.status.mesh_bundle.device_order} \
            == set(block_nodes)

        # Idempotent re-join: the returned host reclaimed its worker slot.
        clique1 = next(c for c in sim.api.list(COMPUTE_DOMAIN_CLIQUE,
                                               namespace="grid")
                       if c.domain_uid == cd.uid)
        assert clique1.node_info(victim).index == victim_slot

        # The returned host swept its stale pre-failure state: zero leaked
        # partitions anywhere, ledgers matching live claims exactly.
        sim.settle(max_steps=10)
        ok, why = _ledger_matches_live_claims(sim)
        assert ok, why

        # A re-created worker (the Job controller's move) lands on the
        # returned host and runs in the grown mesh.
        _apply(sim, CD_WORKER % {"i": block_nodes.index(victim)})
        assert sim.wait_for(
            lambda s: all(
                p.phase == "Running"
                for p in s.api.list(POD, namespace="grid")
                if p.meta.name.startswith("dom-worker")), max_steps=30)
        ok, why = _ledger_matches_live_claims(sim)
        assert ok, why
    finally:
        sim.stop()


def test_resize_crash_rolls_back_exact_prior_placement(tmp_path):
    """Fault-injected crash mid-resize: the epoch raises right after the
    quiesce checkpointed the survivors, and must roll back to the EXACT
    prior placement — same nodes, same allocations, partitions active on
    their source hosts, ResizeFailed narrated — then complete on the
    backoff-paced retry once the fault clears."""
    sim = SimCluster(workdir=str(tmp_path), profile="v5e-16", num_hosts=4,
                     gates=ELASTIC_GATES)
    sim.start()
    try:
        cd = _assemble(sim)
        block_nodes = list(cd.status.placement.nodes)
        allocs_before = {
            c.meta.name: (c.allocation.node_name,
                          [r.device for r in c.allocation.devices])
            for c in sim.api.list(RESOURCE_CLAIM, namespace="grid")
            if c.allocation is not None
        }

        boom = {"count": 0}

        def crash(point):
            if point == "resize:quiesced":
                boom["count"] += 1
                raise RuntimeError("injected mid-resize crash")

        sim.elastic.fault_hook = crash
        victim = block_nodes[1]
        _set_node_down(sim, victim, True)
        assert sim.wait_for(lambda s: boom["count"] >= 1, max_steps=20)
        # Rolled back: prior placement verbatim (dead member included),
        # epoch unchanged, no resize record, survivors re-prepared on
        # their sources with their partitions re-activated.
        assert sim.wait_for(
            lambda s: _domain(s).status.resize is None, max_steps=20)
        rolled = _domain(sim)
        assert list(rolled.status.placement.nodes) == block_nodes
        assert rolled.status.epoch == 0
        fails = _events(sim, "ResizeFailed", namespace="grid")
        assert fails and "rolled back" in fails[0].message
        assert sim.elastic.metrics.epochs_total.value(
            "heal", "rolled_back") >= 1.0
        survivors = [n for n in block_nodes if n != victim]
        for name in survivors:
            state = sim.nodes[name].tpu_driver.state
            assert not any(e.state == MIGRATION_CHECKPOINTED
                           for e in state.prepared_claims().values()), name
            assert all(e.state == PREPARE_COMPLETED
                       for e in state.prepared_claims().values()), name
        allocs_after = {
            c.meta.name: (c.allocation.node_name,
                          [r.device for r in c.allocation.devices])
            for c in sim.api.list(RESOURCE_CLAIM, namespace="grid")
            if c.allocation is not None
        }
        for name, before in allocs_before.items():
            if before[0] == victim:
                continue  # the dead host's worker is evicted by the NEXT epoch
            assert allocs_after.get(name) == before, name

        # Clear the fault: the backoff-paced retry completes the heal.
        sim.elastic.fault_hook = None
        assert sim.wait_for(
            lambda s: _domain(s).status.epoch == 1
            and _domain(s).status.status == "Ready", max_steps=60), (
                _domain(sim).status.resize, _domain(sim).status.status)
        assert (sim.elastic.metrics.epochs_total.value("heal", "completed")
                >= 1.0)
    finally:
        sim.stop()


class _StoreKilled(BaseException):
    """Out-of-band crash: NOT an Exception, so no rollback path runs —
    the epoch record stays exactly as persisted, like a controller whose
    store died under it."""


def test_wal_crash_restore_mid_resize_epoch(tmp_path):
    """Satellite: kill the store between quiesce and re-place, restore
    from the WAL, and assert the controller RESUMES the epoch to a
    fingerprint-consistent end state with the partition ledger matching
    live claims."""
    persist = str(tmp_path / "store")
    work = str(tmp_path / "work")
    sim = SimCluster(workdir=work, profile="v5e-16", num_hosts=4,
                     gates=ELASTIC_GATES, persist_dir=persist)
    sim.start()
    try:
        cd = _assemble(sim)
        block_nodes = list(cd.status.placement.nodes)
        victim = block_nodes[1]

        def kill(point):
            if point == "resize:quiesced":
                raise _StoreKilled()

        sim.elastic.fault_hook = kill
        _set_node_down(sim, victim, True)
        crashed = False
        for _ in range(20):
            try:
                sim.step()
            except _StoreKilled:
                crashed = True
                break
        assert crashed, "epoch never reached the quiesce point"
        # The epoch record is durable at Quiescing, the survivors' claims
        # are MigrationCheckpoint'd on disk.
        mid = _domain(sim)
        assert mid.status.resize is not None
        assert mid.status.resize.phase == "Quiescing"
    finally:
        sim.stop()

    # Restore: same workdir (plugin checkpoints), same WAL dir. The dead
    # host's agent comes back too (the failure annotation lives on the
    # Node object, but the chaos pass re-applies from scratch) — the
    # controller must still drive the recorded epoch to completion and
    # then grow back, ending fingerprint-consistent.
    sim2 = SimCluster(workdir=work, profile="v5e-16", num_hosts=4,
                      gates=ELASTIC_GATES, persist_dir=persist)
    sim2.start()
    try:
        restored = _domain(sim2)
        assert restored.status.resize is not None, "epoch record lost"
        assert sim2.wait_for(
            lambda s: _domain(s).status.resize is None
            and _domain(s).status.status == "Ready", max_steps=80), (
                _domain(sim2).status.resize, _domain(sim2).status.status)
        final = _domain(sim2)
        assert final.status.epoch >= 1
        # Placement and bundle agree on one membership...
        members = set(final.status.placement.nodes)
        assert {d.node for d in final.status.mesh_bundle.device_order} \
            == members
        # ...and the partition ledgers match the live claims exactly.
        sim2.settle(max_steps=10)
        ok, why = _ledger_matches_live_claims(sim2)
        assert ok, why
    finally:
        sim2.stop()


def test_spec_shrink_and_grow_epochs(tmp_path):
    """Operator intent: editing spec.numNodes on a healthy placed domain
    runs the same epoch machinery — shrink picks the survivors' most
    compact sub-block (an axis-aligned 1x2 of the 2x2), grow returns to
    the full block; removed-healthy members are evicted and unlabeled."""
    sim = SimCluster(workdir=str(tmp_path), profile="v5e-16", num_hosts=4,
                     gates=ELASTIC_GATES)
    sim.start()
    try:
        cd = _assemble(sim)
        block_nodes = list(cd.status.placement.nodes)

        def set_nodes(obj, n=2):
            obj.spec.num_nodes = n
        sim.api.update_with_retry(COMPUTE_DOMAIN, "dom", "grid", set_nodes)
        assert sim.wait_for(
            lambda s: _domain(s).status.epoch == 1
            and _domain(s).status.status == "Ready", max_steps=60), (
                _domain(sim).status.resize, _domain(sim).status.status)
        shrunk = _domain(sim)
        assert len(shrunk.status.placement.nodes) == 2
        # A true axis-aligned sub-block, not a chain: 2 of a 2x2 grid.
        assert shrunk.status.placement.block_shape in ("1x2", "2x1")
        kept = set(shrunk.status.placement.nodes)
        assert kept < set(block_nodes)
        # Evicted members lost their worker pods and node labels.
        for name in set(block_nodes) - kept:
            node = sim.api.get(NODE, name)
            assert "resource.tpu.google.com/computeDomain" \
                not in node.meta.labels, name

        def grow(obj):
            obj.spec.num_nodes = 4
        sim.api.update_with_retry(COMPUTE_DOMAIN, "dom", "grid", grow)
        assert sim.wait_for(
            lambda s: _domain(s).status.epoch == 2
            and _domain(s).status.status == "Ready", max_steps=80), (
                _domain(sim).status.resize, _domain(sim).status.status)
        grown = _domain(sim)
        assert set(grown.status.placement.nodes) == set(block_nodes)
        assert grown.status.desired_nodes == 4
    finally:
        sim.stop()


def test_clique_rejoin_reclaims_worker_slot():
    """Satellite regression: a node deregistered from an assembled clique
    (lease expiry) re-joins into the SAME worker slot via the released-
    index memory; a DIFFERENT node never inherits a released slot while
    its owner can still claim it — but the memory is best-effort, so a
    slot already re-allocated degrades to normal lowest-free."""
    from k8s_dra_driver_tpu.daemon.cliquemanager import CliqueManager
    from k8s_dra_driver_tpu.k8s import APIServer

    api = APIServer()
    mgr = CliqueManager(api, "default", "cd-uid", "ici-0")
    assert mgr.register("node-a", "10.0.0.1") == 0
    assert mgr.register("node-b", "10.0.0.2") == 1
    assert mgr.register("node-c", "10.0.0.3") == 2

    mgr.deregister("node-b")
    clique = mgr.get()
    assert clique.released == {"node-b": 1}

    # Same node -> same slot.
    assert mgr.register("node-b", "10.0.0.9") == 1
    assert mgr.get().released == {}

    # Best-effort: once ANOTHER member took the freed slot, the returning
    # node degrades to normal allocation instead of colliding.
    mgr.deregister("node-c")
    assert mgr.register("node-d", "10.0.0.4") == 2  # lowest free
    assert mgr.register("node-c", "10.0.0.3") == 3  # old slot taken


def test_heal_latency_feeds_slo_plane_with_deduped_incident(tmp_path):
    """Satellite (ISSUE 15): time-to-healed is a burn-rate objective.
    With FleetTelemetry on, every completed resize epoch observes its
    latency into the ``domain-time-to-healed`` SLO; declaring a bound
    tighter than the real heal latency must trip a deduplicated
    SLOBurnRate incident on the domain, and the burn gauge must appear
    on the scrape."""
    from k8s_dra_driver_tpu.pkg.slo import (
        TIME_TO_HEALED_SLO,
        heal_time_objective,
    )

    sim = SimCluster(workdir=str(tmp_path), profile="v5e-16", num_hosts=8,
                     gates=ELASTIC_GATES + ",FleetTelemetry=true")
    sim.start()
    try:
        # The default objective (30 virtual s) is wired by the sim;
        # tighten it so a perfectly ordinary ~7-step heal reads as a
        # violation the burn-rate machinery must catch.
        assert sim.slo.has(TIME_TO_HEALED_SLO)
        sim.slo.add(heal_time_objective(
            bound_s=1.0, target=0.5, windows=((60.0, 15.0),),
            burn_threshold=1.0))
        cd = _assemble(sim)
        victim = cd.status.placement.nodes[0]
        epoch0 = cd.status.epoch
        _set_node_down(sim, victim, True)
        assert sim.wait_for(
            lambda s: _domain(s).status.epoch == epoch0 + 1
            and _domain(s).status.status == "Ready", max_steps=60)
        # A few telemetry passes evaluate the freshly-observed sample.
        for _ in range(3):
            sim.step()
        incidents = [e for e in _events(sim, "SLOBurnRate",
                                        namespace="grid")
                     if TIME_TO_HEALED_SLO in e.message]
        assert len(incidents) == 1, [
            (e.meta.name, e.message) for e in incidents]
        assert incidents[0].involved_object.name == "dom"
        assert incidents[0].count >= 1
        text = sim.metrics_registry.expose()
        assert f'tpu_dra_slo_burn_rate{{slo="{TIME_TO_HEALED_SLO}"' in text
        alerts = [a for a in sim.slo.active_alerts()
                  if a.slo == TIME_TO_HEALED_SLO]
        assert alerts and alerts[0].subject == ("grid", "dom")
    finally:
        sim.stop()
