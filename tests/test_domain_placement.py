"""Host-grid-aligned ComputeDomain placement — the north-star sim e2e.

A multi-host ComputeDomain must land on a *host-grid-contiguous* block of
hosts inside ONE ICI domain, with the workers' allocated chips ICI-
contiguous (bitmask-verified against the slice grid) — even when free
hosts are scattered across several slices, where the un-steered
emptiest-first/name-order scheduler would happily assemble a cross-slice
"domain" with no real ICI connectivity.
"""

import pytest

from k8s_dra_driver_tpu.k8s.core import COMPUTE_DOMAIN, POD, RESOURCE_CLAIM
from k8s_dra_driver_tpu.k8s.core import Pod, PodResourceClaimRef
from k8s_dra_driver_tpu.k8s.objects import new_meta
from k8s_dra_driver_tpu.sim import SimCluster
from k8s_dra_driver_tpu.sim.kubectl import load_manifests
from k8s_dra_driver_tpu.tpulib.types import parse_topology


@pytest.fixture(autouse=True)
def boot_id(tmp_path, monkeypatch):
    p = tmp_path / "boot_id"
    p.write_text("boot-1\n")
    monkeypatch.setenv("ALT_TPU_BOOT_ID_PATH", str(p))


WHOLE_RCT = """
apiVersion: resource.k8s.io/v1
kind: ResourceClaimTemplate
metadata: {name: whole, namespace: default}
spec:
  spec:
    devices:
      requests: [{name: tpus, exactly: {deviceClassName: tpu.google.com, allocationMode: All}}]
"""

CD_MANIFEST = """
apiVersion: v1
kind: Namespace
metadata: {name: grid}
---
apiVersion: resource.tpu.google.com/v1beta1
kind: ComputeDomain
metadata: {name: jax-domain, namespace: grid}
spec:
  numNodes: %(num_nodes)d
  channel:
    resourceClaimTemplate: {name: jax-domain-channel}
---
apiVersion: resource.k8s.io/v1
kind: ResourceClaimTemplate
metadata: {name: whole-host, namespace: grid}
spec:
  spec:
    devices:
      requests: [{name: tpus, exactly: {deviceClassName: tpu.google.com, allocationMode: All}}]
"""

WORKER = """
apiVersion: v1
kind: Pod
metadata: {name: worker-%(i)d, namespace: grid}
spec:
  containers: [{name: jax, image: x}]
  resourceClaims:
  - {name: tpus, resourceClaimTemplateName: whole-host}
  - {name: channel, resourceClaimTemplateName: jax-domain-channel}
"""


def _block_node(sim, node_name: str, index: int) -> None:
    """Pin a whole-host pod to one node (scatters the free-host set)."""
    pod = Pod(
        meta=new_meta(f"blocker-{index}", "default"),
        node_name=node_name,
        containers=[],
        resource_claims=[PodResourceClaimRef(
            name="tpus", resource_claim_template_name="whole")],
    )
    sim.api.create(pod)


def _worker_chip_coords(sim, pod) -> set:
    """Global slice-grid coords of every chip allocated to one worker."""
    coords = set()
    node = sim.nodes[pod.node_name]
    by_index = {c.index: c for c in node.tpulib.enumerate().chips}
    for claim in sim.api.list(RESOURCE_CLAIM, namespace=pod.namespace):
        if not any(r.uid == pod.uid for r in claim.reserved_for):
            continue
        if claim.allocation is None:
            continue
        for r in claim.allocation.devices:
            if r.driver != "tpu.google.com":
                continue
            dev = node.tpu_driver.state.allocatable[r.device]
            for idx in dev.chip_indices:
                coords.add(tuple(by_index[idx].coords))
    return coords


def test_domain_lands_on_contiguous_host_grid_block(tmp_path):
    """4-host v5e-16 domain on a 12-host cluster (3 slices) with the free
    hosts scattered: slice 0 and slice 1 each have a blocked host, so only
    slice 2 holds a full 2x2 host-grid block. The domain must land there
    entirely — not on the lexicographically-first free hosts across
    slices — and its chip set must tile the whole 4x4 slice grid."""
    sim = SimCluster(workdir=str(tmp_path), profile="v5e-16", num_hosts=12)
    sim.start()
    try:
        for obj in load_manifests(WHOLE_RCT):
            sim.api.create(obj)
        # Slice 0 = nodes 0-3, slice 1 = 4-7, slice 2 = 8-11.
        _block_node(sim, "tpu-node-1", 0)
        _block_node(sim, "tpu-node-6", 1)
        sim.settle(max_steps=8)
        blockers = [p for p in sim.api.list(POD, namespace="default")]
        assert all(p.phase == "Running" for p in blockers), [
            (p.meta.name, p.phase) for p in blockers]

        for obj in load_manifests(CD_MANIFEST % {"num_nodes": 4}):
            sim.api.create(obj)
        for i in range(4):
            for obj in load_manifests(WORKER % {"i": i}):
                sim.api.create(obj)
        sim.settle(max_steps=30)
        workers = [p for p in sim.api.list(POD, namespace="grid")]
        assert len(workers) == 4
        assert all(p.phase == "Running" for p in workers), [
            (p.meta.name, p.phase, p.meta.annotations.get("failure"))
            for p in workers]

        # The whole domain sits on slice 2's full host grid.
        nodes = {p.node_name for p in workers}
        assert nodes == {f"tpu-node-{i}" for i in range(8, 12)}, nodes
        ici_domains = {sim.nodes[p.node_name].tpulib.enumerate().ici_domain
                       for p in workers}
        assert len(ici_domains) == 1, ici_domains

        # Recorded placement: a 2x2 block at the grid origin.
        cd = sim.api.get(COMPUTE_DOMAIN, "jax-domain", "grid")
        assert cd.status.placement is not None
        assert cd.status.placement.block_shape == "2x2"
        assert cd.status.placement.block_origin == "0x0"
        assert set(cd.status.placement.nodes) == nodes
        assert cd.status.placement.ici_domain == next(iter(ici_domains))

        # Bitmask-verified ICI contiguity: the union of all allocated
        # chips' global coords tiles the ENTIRE 4x4 slice grid — one
        # contiguous ICI mesh, no holes, no foreign-slice chips.
        coords = set()
        for p in workers:
            got = _worker_chip_coords(sim, p)
            assert len(got) == 4, (p.meta.name, got)  # whole host each
            coords |= got
        dims = parse_topology("4x4")
        mask = 0
        for c in coords:
            assert 0 <= c[0] < dims[0] and 0 <= c[1] < dims[1], c
            mask |= 1 << (c[0] * dims[1] + c[1])
        assert mask == (1 << (dims[0] * dims[1])) - 1, bin(mask)

        # The controller's status aggregation must carry the placement,
        # and the domain must assemble Ready on it.
        assert sim.wait_for(
            lambda s: s.api.get(COMPUTE_DOMAIN, "jax-domain", "grid")
            .status.status == "Ready")
        cd = sim.api.get(COMPUTE_DOMAIN, "jax-domain", "grid")
        assert cd.status.placement is not None  # not wiped by aggregation
    finally:
        sim.stop()


def test_two_host_domain_picks_compact_block(tmp_path):
    """num_nodes=2 on one 4-host slice: the planner prefers the most
    compact free block — deterministically the 1x2 at the grid origin —
    and records it before the first worker binds."""
    sim = SimCluster(workdir=str(tmp_path), profile="v5e-16")
    sim.start()
    try:
        for obj in load_manifests(CD_MANIFEST % {"num_nodes": 2}):
            sim.api.create(obj)
        for i in range(2):
            for obj in load_manifests(WORKER % {"i": i}):
                sim.api.create(obj)
        sim.settle(max_steps=30)
        workers = [p for p in sim.api.list(POD, namespace="grid")]
        assert all(p.phase == "Running" for p in workers), [
            (p.meta.name, p.phase) for p in workers]
        assert {p.node_name for p in workers} == {"tpu-node-0", "tpu-node-1"}
        cd = sim.api.get(COMPUTE_DOMAIN, "jax-domain", "grid")
        assert cd.status.placement is not None
        assert cd.status.placement.block_shape == "1x2"
        assert cd.status.placement.nodes == ["tpu-node-0", "tpu-node-1"]
    finally:
        sim.stop()


def test_multi_host_domain_parks_instead_of_binding_unaligned(tmp_path):
    """A multi-host domain with host-grid info but NO contiguous free
    block must park its workers as unschedulable — even when exactly ONE
    feasible host remains (the pre-fix early return bound the worker
    there unaligned, stranding the host: its channel claim pins it
    against live repack and the domain can never assemble). v5e-4 hosts
    are single-host slices (1x1 host grid), so a 2-node domain can never
    be ICI-contiguous at all."""
    sim = SimCluster(workdir=str(tmp_path), profile="v5e-4", num_hosts=2)
    sim.start()
    try:
        for obj in load_manifests(WHOLE_RCT):
            sim.api.create(obj)
        _block_node(sim, "tpu-node-1", 0)  # exactly one free host remains
        sim.settle(max_steps=8)
        for obj in load_manifests(CD_MANIFEST % {"num_nodes": 2}):
            sim.api.create(obj)
        for i in range(2):
            for obj in load_manifests(WORKER % {"i": i}):
                sim.api.create(obj)
        sim.settle(max_steps=15)
        workers = [p for p in sim.api.list(POD, namespace="grid")]
        assert len(workers) == 2
        assert all(p.phase == "Pending" and not p.node_name
                   for p in workers), [
            (p.meta.name, p.phase, p.node_name) for p in workers]
        cd = sim.api.get(COMPUTE_DOMAIN, "jax-domain", "grid")
        assert cd.status.placement is None
        events = [e for e in sim.api.list("Event", namespace="grid")
                  if e.reason == "FailedScheduling"]
        assert events and any("grid block" in e.message for e in events), [
            e.message for e in events]
    finally:
        sim.stop()


def test_domain_placed_event_and_describe(tmp_path):
    """The chosen block is narrated: a DomainPlaced event on the CD and a
    Placement line in `describe computedomains`."""
    from k8s_dra_driver_tpu.sim.kubectl import describe_object

    sim = SimCluster(workdir=str(tmp_path), profile="v5e-16")
    sim.start()
    try:
        for obj in load_manifests(CD_MANIFEST % {"num_nodes": 4}):
            sim.api.create(obj)
        for i in range(4):
            for obj in load_manifests(WORKER % {"i": i}):
                sim.api.create(obj)
        sim.settle(max_steps=30)
        events = [e for e in sim.api.list("Event", namespace="grid")
                  if e.reason == "DomainPlaced"]
        assert len(events) == 1, [(e.reason, e.message) for e in events]
        assert "2x2@0x0" in events[0].message
        out = describe_object(sim.api, COMPUTE_DOMAIN, "jax-domain", "grid")
        assert "Placement:" in out and "2x2@0x0" in out
    finally:
        sim.stop()


def _global_chip_coords(sim, node_name):
    """host-local chip index -> global slice-grid coords, from the node's
    own tpulib enumeration (the ground truth the bitmasks record)."""
    return {c.index: tuple(c.coords)
            for c in sim.nodes[node_name].tpulib.enumerate().chips}


def test_mesh_bundle_injected_and_ring_adjacent(tmp_path):
    """ISSUE 10 acceptance: a 4-host v5e-16 ComputeDomain assembles and the
    claiming pods' env carries a mesh bundle whose device order tiles the
    recorded chip bitmasks with ring-adjacent mesh-axis neighbors —
    verified against the REAL per-node tpulib chip coordinates (bitmask)
    and by recomputing the hop count from them (hop-count)."""
    import json

    from k8s_dra_driver_tpu.pkg.meshgen import MESH_BUNDLE_ENV, PROCESS_BOUNDS_ENV

    sim = SimCluster(workdir=str(tmp_path), profile="v5e-16")
    sim.start()
    try:
        for obj in load_manifests(CD_MANIFEST % {"num_nodes": 4}):
            sim.api.create(obj)
        for i in range(4):
            for obj in load_manifests(WORKER % {"i": i}):
                sim.api.create(obj)
        sim.settle(max_steps=40)
        workers = [p for p in sim.api.list(POD, namespace="grid")]
        assert len(workers) == 4
        assert all(p.phase == "Running" for p in workers), [
            (p.meta.name, p.phase) for p in workers]

        cd = sim.api.get(COMPUTE_DOMAIN, "jax-domain", "grid")
        assert cd.status.mesh_bundle is not None
        assert cd.status.placement is not None

        # Every claiming pod got the SAME bundle + process bounds env.
        raws = set()
        for p in workers:
            env = p.injected_env
            assert MESH_BUNDLE_ENV in env, (p.meta.name, sorted(env))
            raws.add(env[MESH_BUNDLE_ENV])
            assert env[PROCESS_BOUNDS_ENV] == "2,2,1"
        assert len(raws) == 1
        bundle = json.loads(raws.pop())
        assert bundle["axisNames"] == ["data", "model"]
        assert bundle["axisSizes"] == [4, 4]
        assert bundle["revision"] == cd.status.mesh_bundle.revision

        # Ground truth: resolve every deviceOrder slot to the REAL global
        # chip coordinate its node's tpulib records.
        coords_by_node = {n: _global_chip_coords(sim, n)
                          for n in cd.status.placement.nodes}
        order = [coords_by_node[d["node"]][d["chip"]]
                 for d in bundle["deviceOrder"]]
        # Worker slots tile the recorded placement nodes exactly.
        assert ({d["node"] for d in bundle["deviceOrder"]}
                == set(cd.status.placement.nodes))

        # Bitmask-verified: the order covers the whole 4x4 slice grid,
        # every chip exactly once.
        dims = parse_topology("4x4")
        mask = 0
        for c in order:
            bit = 1 << (c[0] * dims[1] + c[1])
            assert not mask & bit, f"chip {c} appears twice"
            mask |= bit
        assert mask == (1 << (dims[0] * dims[1])) - 1, bin(mask)

        # Hop-count-verified: innermost (model) axis neighbors are ONE ICI
        # hop apart in real coordinates, and the recomputed score matches
        # the bundle's gated hopScore — strictly better than naive.
        def hops(a, b):
            return sum(abs(x - y) for x, y in zip(a, b))

        total = 0
        for row in range(4):
            for col in range(3):
                h = hops(order[row * 4 + col], order[row * 4 + col + 1])
                assert h == 1, (row, col, h)
                total += h
        for col in range(4):  # data-axis neighbors
            for row in range(3):
                total += hops(order[row * 4 + col], order[(row + 1) * 4 + col])
        assert total == bundle["hopScore"]
        assert bundle["hopScore"] < bundle["naiveHopScore"]
    finally:
        sim.stop()


def test_degraded_link_reroutes_bundle(tmp_path):
    """Regression (ISSUE satellite): an `ici-link-unhealthy` taint landing
    mid-domain regenerates the bundle with the ring order routed AROUND
    the dead link — revision bumped, brokenLinks recorded, no mesh-ring
    step traversing the dead pair — and healing re-emits a clean bundle."""
    from k8s_dra_driver_tpu.k8s.core import NODE
    from k8s_dra_driver_tpu.sim.cluster import CHAOS_LINK_HEALTH_ANNOTATION

    sim = SimCluster(workdir=str(tmp_path), profile="v5e-16",
                     gates="TPUDeviceHealthCheck=true")
    sim.start()
    try:
        for obj in load_manifests(CD_MANIFEST % {"num_nodes": 4}):
            sim.api.create(obj)
        for i in range(4):
            for obj in load_manifests(WORKER % {"i": i}):
                sim.api.create(obj)
        sim.settle(max_steps=40)

        def bundle():
            return sim.api.get(COMPUTE_DOMAIN, "jax-domain",
                               "grid").status.mesh_bundle

        assert bundle() is not None
        rev0 = bundle().revision
        assert bundle().broken_links == []

        def annotate(obj):
            obj.meta.annotations[CHAOS_LINK_HEALTH_ANNOTATION] = "0-1=unhealthy"
        sim.api.update_with_retry(NODE, "tpu-node-1", "", annotate)
        assert sim.wait_for(lambda s: bundle().revision > rev0,
                            max_steps=30), "bundle never re-emitted"
        b = bundle()
        assert b.broken_links == [["tpu-node-1", 0, 1]]

        # The re-routed ring: no innermost-axis step crosses the dead link.
        coords = _global_chip_coords(sim, "tpu-node-1")
        dead = frozenset((coords[0], coords[1]))
        order = [_global_chip_coords(sim, d.node)[d.chip]
                 for d in b.device_order]
        inner = b.axis_sizes[-1]
        for row in range(len(order) // inner):
            for col in range(inner - 1):
                pair = frozenset((order[row * inner + col],
                                  order[row * inner + col + 1]))
                assert pair != dead, (row, col)

        # The degradation is narrated alongside (DomainDegraded fires from
        # the taint pass; MeshBundleUpdated from the re-emit).
        reasons = {e.reason for e in sim.api.list("Event", namespace="grid")}
        assert "MeshBundleUpdated" in reasons
        assert "DomainDegraded" in reasons

        # Heal: a THIRD bundle, clean again.
        rev1 = b.revision

        def heal(obj):
            obj.meta.annotations[CHAOS_LINK_HEALTH_ANNOTATION] = "0-1=healthy"
        sim.api.update_with_retry(NODE, "tpu-node-1", "", heal)
        assert sim.wait_for(lambda s: bundle().revision > rev1, max_steps=30)
        assert bundle().broken_links == []
    finally:
        sim.stop()
