"""Allocator incremental consumed-counter accounting: the per-node cache
built in begin_pass() and maintained by commit()/rollback() must agree
device-for-device and counter-for-counter with the from-scratch
_consumed_counters rescan (kept as the oracle) after any allocate /
rollback / re-allocate sequence.
"""

import pytest

from k8s_dra_driver_tpu.k8s import APIServer
from k8s_dra_driver_tpu.k8s.core import (
    DeviceClass,
    DeviceRequest,
    ResourceClaim,
)
from k8s_dra_driver_tpu.k8s.objects import fresh_uid, new_meta
from k8s_dra_driver_tpu.plugins.tpu.allocatable import enumerate_allocatable
from k8s_dra_driver_tpu.plugins.tpu.deviceinfo import build_resource_slice
from k8s_dra_driver_tpu.sim.allocator import AllocationError, Allocator
from k8s_dra_driver_tpu.tpulib import MockTpuLib

TPU_CLASS = "tpu.google.com"
SUB_CLASS = "subslice.tpu.google.com"


def _normalize(consumed):
    """Nested counter dicts -> plain dicts with zero entries dropped (the
    cache may carry explicit zeros after a rollback; the oracle never
    materializes them)."""
    return {
        cs: {c: v for c, v in counters.items() if v}
        for cs, counters in consumed.items()
        if any(counters.values())
    }


@pytest.fixture
def api():
    api = APIServer()
    api.create(DeviceClass(meta=new_meta(TPU_CLASS), driver="tpu.google.com",
                           match_attributes={"type": "tpu"}))
    api.create(DeviceClass(meta=new_meta(SUB_CLASS), driver="tpu.google.com",
                           match_attributes={"type": "subslice"}))
    for node in ("n0", "n1"):
        inv = MockTpuLib("v5e-4").enumerate()
        devices = enumerate_allocatable(inv, with_subslices=True)
        api.create(build_resource_slice(node, "tpu.google.com", devices, inv))
    return api


def _claim(name, class_name=TPU_CLASS, count=1, selectors=()):
    c = ResourceClaim(
        meta=new_meta(name, "default"),
        requests=[DeviceRequest(name="r", device_class_name=class_name,
                                count=count, selectors=list(selectors))],
    )
    c.meta.uid = fresh_uid()
    return c


def _check_cache_matches_oracle(alloc, nodes=("n0", "n1")):
    for node in nodes:
        cache = _normalize(alloc._consumed_for_node(node))
        oracle = _normalize(alloc._consumed_counters(node))
        assert cache == oracle, f"{node}: cache {cache} != rescan {oracle}"


def test_allocate_rollback_reallocate_matches_rescan(api):
    """The satellite property check: a pass that allocates, rolls back, and
    re-allocates agrees with the from-scratch rescan at every step."""
    alloc = Allocator(api)
    alloc.begin_pass()
    try:
        a1 = alloc.allocate_on_node(_claim("c1", count=2), "n0")
        assert a1 is not None
        alloc.commit(a1)
        _check_cache_matches_oracle(alloc)

        a2 = alloc.allocate_on_node(_claim("c2", SUB_CLASS), "n0")
        assert a2 is not None
        alloc.commit(a2)
        _check_cache_matches_oracle(alloc)

        # Scheduler changed its mind: withdraw c2.
        alloc.rollback(a2)
        _check_cache_matches_oracle(alloc)

        # Re-allocate on the other node, plus more churn on n0.
        a2b = alloc.allocate_on_node(_claim("c2b", SUB_CLASS), "n1")
        assert a2b is not None
        alloc.commit(a2b)
        a3 = alloc.allocate_on_node(_claim("c3", count=2), "n0")
        assert a3 is not None
        alloc.commit(a3)
        _check_cache_matches_oracle(alloc)

        # n0 is now full (2 + 2 chips): a chip claim must not fit, and the
        # cache-backed answer must agree with what a rescan would say.
        assert alloc.allocate_on_node(_claim("c4"), "n0") is None
        # The rolled-back c2 freed its chips: a subslice fits on n0 again
        # only where counters allow; n1 still has room.
        assert alloc.allocate_on_node(_claim("c5"), "n1") is not None
    finally:
        alloc.end_pass()


def test_rollback_then_reallocate_same_devices(api):
    """After rollback the exact same devices are allocatable again —
    device-for-device equality with the pre-allocation answer."""
    alloc = Allocator(api)
    alloc.begin_pass()
    try:
        first = alloc.allocate_on_node(_claim("c1", count=4), "n0")
        assert first is not None
        alloc.commit(first)
        # Node full: nothing else fits.
        assert alloc.allocate_on_node(_claim("c2"), "n0") is None
        alloc.rollback(first)
        _check_cache_matches_oracle(alloc)
        again = alloc.allocate_on_node(_claim("c3", count=4), "n0")
        assert again is not None
        assert [r.device for r in again.devices] == \
            [r.device for r in first.devices]
    finally:
        alloc.end_pass()


def test_in_flight_overlay_does_not_dirty_cache(api):
    """Probing with in_flight siblings must not mutate the pass-wide
    cache: an uncommitted probe leaves no trace."""
    alloc = Allocator(api)
    alloc.begin_pass()
    try:
        probe = alloc.allocate_on_node(_claim("p1", count=2), "n0")
        assert probe is not None
        # Probe a sibling with p1 in flight, then walk away from both.
        sibling = alloc.allocate_on_node(_claim("p2", count=2), "n0",
                                         in_flight=[probe])
        assert sibling is not None
        _check_cache_matches_oracle(alloc)  # nothing committed, cache clean
        # With both in flight the node is full.
        assert alloc.allocate_on_node(
            _claim("p3"), "n0", in_flight=[probe, sibling]) is None
        # Without them it is empty again.
        assert alloc.allocate_on_node(_claim("p4", count=4), "n0") is not None
    finally:
        alloc.end_pass()


def test_incremental_matches_fresh_pass(api):
    """Counters committed during a pass equal a brand-new pass built from
    the API state after the allocations are actually written."""
    alloc = Allocator(api)
    claim = _claim("c1", count=3)
    api.create(claim)
    alloc.begin_pass()
    a = alloc.allocate_on_node(claim, "n0")
    assert a is not None
    alloc.commit(a)
    end_state = _normalize(alloc._consumed_for_node("n0"))
    alloc.end_pass()

    stored = api.get("ResourceClaim", claim.meta.name, "default", copy=True)
    stored.allocation = a
    api.update(stored)
    alloc.begin_pass()
    try:
        fresh = _normalize(alloc._consumed_for_node("n0"))
        assert fresh == end_state
    finally:
        alloc.end_pass()


def test_match_plan_rejects_malformed_selector_once(api):
    """The per-request match plan compiles selectors up front: a malformed
    legacy selector fails the request with AllocationError (not a silent
    zero-device match)."""
    alloc = Allocator(api)
    alloc.begin_pass()
    try:
        with pytest.raises(AllocationError, match="malformed legacy selector"):
            alloc.allocate_on_node(
                _claim("bad", selectors=["no-equals-sign"]), "n0")
        # Valid legacy selectors still work through the plan.
        got = alloc.allocate_on_node(
            _claim("ok", selectors=["type=tpu"]), "n0")
        assert got is not None
    finally:
        alloc.end_pass()


def test_legacy_selector_value_may_contain_equals():
    """Round-5 advisor nit: PR 1's regex demanded a bare value, so
    "key=a=b" (flag-shaped or base64ish attribute values) started
    raising; pre-PR-1 partition("=") semantics are restored — split on
    the FIRST '=', value keeps the rest — while CEL operators leaking in
    as strings still fail loudly."""
    from k8s_dra_driver_tpu.k8s.core import Device
    from k8s_dra_driver_tpu.sim.allocator import _device_matches

    d = Device(name="tpu-0", attributes={"flags": "a=b", "type": "tpu",
                                         "blob": "x==y"})
    assert _device_matches(d, {}, ["flags=a=b"])
    assert not _device_matches(d, {}, ["flags=a=c"])
    assert _device_matches(d, {}, ["type=tpu", "flags=a=b"])
    # a DOUBLE '=' straight after the key is CEL equality, not a value
    with pytest.raises(AllocationError, match="malformed legacy selector"):
        _device_matches(d, {}, ["blob==y"])
    # CEL comparison shapes still rejected loudly
    for sel in ('device.driver == "tpu.google.com"', "a!=b", "a<=b", "a>=b",
                "=leading", "no-equals-sign", "   =x"):
        with pytest.raises(AllocationError, match="malformed legacy selector"):
            _device_matches(d, {}, [sel])
