"""Docs consistency: the reference pages generated-by-hand from code
registries must not drift from those registries."""

import os
import re

from k8s_dra_driver_tpu.pkg import featuregates as fg

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
DOCS = os.path.join(REPO, "docs")


def _read(rel: str) -> str:
    with open(os.path.join(DOCS, rel), encoding="utf-8") as f:
        return f.read()


def test_feature_gates_doc_lists_every_gate():
    body = _read(os.path.join("reference", "feature-gates.md"))
    for spec in fg.FEATURES:
        row = re.search(rf"^\| `{spec.name}` \| (\w+) \| (\w+) \|", body, re.M)
        assert row, f"gate {spec.name} missing from feature-gates.md"
        assert row.group(1) == str(spec.default).lower(), (
            f"{spec.name}: documented default {row.group(1)!r} != {spec.default}"
        )
        assert row.group(2) == spec.stage.value, (
            f"{spec.name}: documented stage {row.group(2)!r} != {spec.stage.value}"
        )
        for dep in spec.requires:
            assert dep in body, f"{spec.name} dependency {dep} undocumented"


def test_metrics_doc_lists_every_metric():
    from k8s_dra_driver_tpu.pkg.metrics import (
        ComputeDomainStatusMetric,
        DRARequestMetrics,
        Registry,
    )

    reg = Registry()
    DRARequestMetrics(driver="tpu.google.com", registry=reg)
    ComputeDomainStatusMetric(reg)
    names = set(reg._metrics)
    body = _read(os.path.join("reference", "metrics.md"))
    for name in names:
        assert f"`{name}`" in body, f"metric {name} missing from metrics.md"


def test_resourceslice_attributes_doc_matches_code():
    from k8s_dra_driver_tpu.plugins.tpu.driver import UNHEALTHY_TAINT_KEY

    body = _read(os.path.join("reference", "resourceslice-attributes.md"))
    for attr in ("tpu.google.com/gen", "tpu.google.com/acceleratorType",
                 "tpu.google.com/iciDomain", "tpu.google.com/sliceTopology",
                 "tpu.google.com/hostTopology", "tpu.google.com/workerId"):
        assert attr in body
    assert UNHEALTHY_TAINT_KEY in body


def test_docs_index_links_resolve():
    body = _read("README.md")
    for rel in re.findall(r"\]\(([^)#]+\.md)\)", body):
        assert os.path.exists(os.path.join(DOCS, rel)), f"dead docs link {rel}"
