"""Federation unit surface: WAL streaming, replica apply, global placement.

The acceptance bar mirrors persistence's: a converged follower is
fingerprint-TOKEN identical to the leader, a reconnecting follower
resumes exactly at its watermark (no duplicate, no gap), torn tails are
held back while in-flight and dropped loudly once their epoch rotates,
and a follower older than the leader's snapshot re-bootstraps through
the normal restore path. Cross-cluster placement reuses the WFQ
water-fill and records provenance under the federation rules."""

import json
import logging
import os
import threading
import types

import pytest

from k8s_dra_driver_tpu.federation import (
    ClusterView,
    GlobalScheduler,
    PlacementRequest,
    ReplicaStore,
    ReplicationError,
    ReplicationSource,
)
from k8s_dra_driver_tpu.k8s import APIServer
from k8s_dra_driver_tpu.k8s.core import EVENT, POD, Pod
from k8s_dra_driver_tpu.k8s.objects import new_meta
from k8s_dra_driver_tpu.k8s.persist import (
    discover_wal_files,
    open_persistent_store,
)
from k8s_dra_driver_tpu.k8s.store import ReadOnlyStoreError
from k8s_dra_driver_tpu.pkg.history import RULE_FED_PLACE, RULE_FED_SPILL


def _leader(tmp_path, **kw):
    kw.setdefault("compact_every", 100_000)
    return open_persistent_store(str(tmp_path / "leader"), **kw)


def _pods(api, n, prefix="p", start=0):
    for i in range(start, start + n):
        api.create(Pod(meta=new_meta(f"{prefix}{i}", "default")))


def wait_for(cond, timeout=10.0, msg="condition"):
    import time

    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        if cond():
            return
        time.sleep(0.01)
    raise AssertionError(f"timed out waiting for {msg}")


# -- WAL discovery (the one shared helper) -----------------------------------


def test_discover_wal_files_numeric_order_and_zero_length_skip(
        tmp_path, caplog):
    d = str(tmp_path)
    # Lexicographic order would put epoch 10 before epoch 9.
    for name in ("wal.9.jsonl", "wal.10.jsonl", "wal-1.9.jsonl"):
        with open(os.path.join(d, name), "w") as f:
            f.write('{"seq": 1}\n')
    stray = os.path.join(d, "wal.11.jsonl")
    open(stray, "w").close()  # zero-length: crash between open and append
    with caplog.at_level(logging.WARNING):
        found = discover_wal_files(d)
    assert [(e, s) for e, s, _ in found] == [(9, -1), (9, 1), (10, -1)]
    assert any("zero-length WAL file" in r.message for r in caplog.records)
    # The warning is loud ONCE per path — a tailer re-sweeping several
    # times a second must not spam it.
    caplog.clear()
    with caplog.at_level(logging.WARNING):
        discover_wal_files(d)
    assert not any("zero-length" in r.message for r in caplog.records)
    # Compaction's deletion sweep still sees the husk.
    with_empty = discover_wal_files(d, include_empty=True)
    assert stray in [p for _, _, p in with_empty]


# -- source: fetch / tail edge cases -----------------------------------------


def test_fetch_resumes_at_watermark_no_dup_no_gap(tmp_path):
    api = _leader(tmp_path)
    src = ReplicationSource(api)
    _pods(api, 10)
    first, w = src.fetch(-1, 0)
    assert len(first) == 10
    seqs = [json.loads(ln)["seq"] for ln in first]
    assert seqs == sorted(seqs) and len(set(seqs)) == 10
    assert w == max(seqs)
    # Reconnect semantics: asking from the watermark returns exactly the
    # new records — nothing replayed, nothing missing.
    _pods(api, 5, start=10)
    second, w2 = src.fetch(-1, w)
    seqs2 = [json.loads(ln)["seq"] for ln in second]
    assert len(second) == 5 and min(seqs2) > w
    assert sorted(set(seqs + seqs2)) == list(range(min(seqs), w2 + 1))
    api._wal.close()


def test_fetch_holds_back_torn_tail_until_completed(tmp_path):
    api = _leader(tmp_path)
    src = ReplicationSource(api)
    _pods(api, 3)
    _, w = src.fetch(-1, 0)
    files = [p for _, s, p in discover_wal_files(src.dirpath) if s == -1]
    rec = json.dumps({"seq": w + 1, "op": "DEL",
                      "key": ["Pod", "default", "p0"], "fp": [2, w + 1],
                      "obj": None})
    with open(files[-1], "a") as f:
        f.write(rec[: len(rec) // 2])  # in-flight append: no newline
    held, w_held = src.fetch(-1, w)
    assert held == [] and w_held == w  # incomplete line held back
    with open(files[-1], "a") as f:
        f.write(rec[len(rec) // 2:] + "\n")
    done, w_done = src.fetch(-1, w)
    assert [json.loads(ln)["seq"] for ln in done] == [w + 1]
    assert w_done == w + 1
    api._wal.close()


def test_corrupt_complete_line_fails_loudly(tmp_path):
    api = _leader(tmp_path)
    src = ReplicationSource(api)
    _pods(api, 1)
    files = [p for _, s, p in discover_wal_files(src.dirpath) if s == -1]
    with open(files[-1], "a") as f:
        f.write("{this is not json}\n")  # complete (newline) but corrupt
    with pytest.raises(ReplicationError, match="corrupt WAL record"):
        src.fetch(-1, 0)
    api._wal.close()


def _collect_tail(src, stream, from_seq, want_records, timeout=10.0):
    """Drive src.tail() until ``want_records`` record lines arrived (or a
    SNAPSHOT ctl ends the stream); returns (records, ctls)."""
    records, ctls = [], []
    stop = threading.Event()

    def run():
        for line in src.tail(stream, from_seq, stop=stop,
                             poll_s=0.002, heartbeat_s=0.05):
            doc = json.loads(line)
            if "ctl" in doc:
                ctls.append(doc)
                if doc["ctl"] == "SNAPSHOT":
                    return
                continue
            records.append(doc)
            if len(records) >= want_records:
                stop.set()

    t = threading.Thread(target=run, daemon=True)
    t.start()
    t.join(timeout=timeout)
    stop.set()
    t.join(timeout=2)
    assert not t.is_alive(), "tail did not stop"
    return records, ctls


def test_tail_follows_epoch_rotation_mid_stream(tmp_path):
    """Epoch rotation racing an active tail: the tailer drains the
    rotated file to EOF (open fd survives the unlink), switches to the
    new epoch, and the merged stream has every seq exactly once."""
    api = _leader(tmp_path)
    src = ReplicationSource(api)
    _pods(api, 8)
    got = []
    stop = threading.Event()
    started = threading.Event()

    def run():
        for line in src.tail(-1, 0, stop=stop, poll_s=0.002,
                             heartbeat_s=0.05):
            doc = json.loads(line)
            if "ctl" in doc:
                continue
            got.append(doc["seq"])
            started.set()

    t = threading.Thread(target=run, daemon=True)
    t.start()
    try:
        wait_for(started.is_set, msg="tail consuming pre-rotation records")
        api._wal.compact(api)  # rotates the epoch, deletes the old file
        _pods(api, 8, start=8)
        wait_for(lambda: len(got) >= 16, msg="records across the rotation")
    finally:
        stop.set()
        t.join(timeout=5)
    assert got == sorted(got) and len(set(got)) == len(got)
    assert len(got) == 16
    api._wal.close()


def test_tail_drops_torn_tail_in_rotated_epoch_loudly(tmp_path, caplog):
    """A rotated epoch can never complete its partial last line — it is
    a crash artifact. The tailer drops it with a warning and moves to
    the next epoch without stalling or raising."""
    d = str(tmp_path / "wal")
    os.makedirs(d)
    rec = lambda seq: json.dumps(  # noqa: E731 — local record factory
        {"seq": seq, "op": "PUT", "key": ["Pod", "default", f"p{seq}"],
         "fp": [seq, seq], "obj": None})
    with open(os.path.join(d, "wal.0.jsonl"), "w") as f:
        f.write(rec(1) + "\n" + rec(2) + "\n" + rec(3)[:20])  # torn tail
    with open(os.path.join(d, "wal.1.jsonl"), "w") as f:
        f.write(rec(4) + "\n")
    api = APIServer(shards=2)
    wal = types.SimpleNamespace(dirpath=d, _epoch=1, fsync=False)
    src = ReplicationSource(api, wal)
    with caplog.at_level(logging.WARNING):
        records, _ = _collect_tail(src, -1, 0, want_records=3)
    assert [r["seq"] for r in records] == [1, 2, 4]  # 3 dropped, no stall
    assert any("dropping torn tail" in r.message for r in caplog.records)


def test_tail_hands_snapshot_ctl_to_stale_follower(tmp_path):
    """A follower whose watermark predates the leader snapshot cannot be
    caught up from files (those records were compacted away): it gets
    one SNAPSHOT control line and the stream ends."""
    api = _leader(tmp_path)
    src = ReplicationSource(api)
    _pods(api, 6)
    api._wal.compact(api)  # folds everything into the snapshot
    records, ctls = _collect_tail(src, -1, 0, want_records=1, timeout=5)
    assert records == []
    assert ctls and ctls[0]["ctl"] == "SNAPSHOT"
    assert ctls[0]["watermark"] == src.status()["snapshot_watermark"]
    api._wal.close()


# -- replica store -----------------------------------------------------------


def test_replica_converges_and_is_read_only(tmp_path):
    api = _leader(tmp_path)
    _pods(api, 12)
    rep = ReplicaStore(ReplicationSource(api), cluster="r1").start()
    try:
        # Bootstrap is synchronous: the snapshot contents are visible on
        # return; live records then stream in.
        _pods(api, 4, start=12)
        wait_for(lambda: (rep.api.kind_fingerprint(POD)
                          == api.kind_fingerprint(POD)),
                 msg="fingerprint-token convergence")
        assert {p.meta.name for p in rep.api.list(POD)} \
            == {p.meta.name for p in api.list(POD)}
        # Leader stamps arrive verbatim — same rv on both sides.
        assert (rep.api.get(POD, "p0", "default").meta.resource_version
                == api.get(POD, "p0", "default").meta.resource_version)
        with pytest.raises(ReadOnlyStoreError):
            rep.api.create(Pod(meta=new_meta("nope", "default")))
        assert rep.watermark() > 0
        assert rep.status()["lag_records"] == 0
    finally:
        rep.stop()
        api._wal.close()


def test_replica_watch_and_informer_see_replicated_stream(tmp_path):
    """The whole point of applying through the normal publish path: a
    watch subscriber on the REPLICA sees ADDED/DELETED for leader-side
    mutations, unmodified."""
    api = _leader(tmp_path)
    rep = ReplicaStore(ReplicationSource(api), cluster="r2").start()
    q = rep.api.watch(POD)
    try:
        _pods(api, 3)
        api.delete(POD, "p1", "default")
        events = []

        def drained():
            while not q.empty():
                events.append(q.get_nowait())
            types_ = [e.type for e in events]
            return types_.count("ADDED") == 3 and "DELETED" in types_

        wait_for(drained, msg="replicated watch events")
    finally:
        rep.api.stop_watch(POD, q)
        rep.stop()
        api._wal.close()


def test_replica_rebootstraps_when_leader_compacts_past_it(tmp_path):
    """Partition long enough for the leader to compact past the
    follower's watermark: reconnect gets SNAPSHOT, the follower resyncs
    through the restore path, and informers survive (diff-apply, not a
    store teardown)."""
    api = _leader(tmp_path)
    _pods(api, 5)
    rep = ReplicaStore(ReplicationSource(api), cluster="r3").start()
    try:
        wait_for(lambda: (rep.api.kind_fingerprint(POD)
                          == api.kind_fingerprint(POD)), msg="initial sync")
        resyncs = rep.status()["resyncs"]
        rep.stop()  # the "partition": follower off the stream entirely
        _pods(api, 5, start=5)
        api.delete(POD, "p0", "default")
        api._wal.compact(api)  # leader moves its snapshot past the follower
        _pods(api, 2, start=10)
        rep._stop.clear()
        rep.start(bootstrap=False)  # reconnect path, not a fresh bootstrap
        wait_for(lambda: (rep.api.kind_fingerprint(POD)
                          == api.kind_fingerprint(POD)),
                 msg="post-compaction resync")
        st = rep.status()
        assert st["resyncs"] > resyncs
        assert rep.api.try_get(POD, "p0", "default") is None  # diff DEL
    finally:
        rep.stop()
        api._wal.close()


def test_promote_flips_writable_and_records_failover(tmp_path):
    api = _leader(tmp_path)
    _pods(api, 3)
    rep = ReplicaStore(ReplicationSource(api), cluster="r4").start()
    wait_for(lambda: rep.watermark() > 0, msg="replica caught up")
    promoted = rep.promote()
    api._wal.close()
    assert promoted is rep.api and rep.promoted
    assert not promoted.read_only
    # Failover events land in the replica's OWN store — the leader may
    # be gone, that is why promote ran.
    reasons = {e.reason for e in promoted.list(EVENT)}
    assert {"FailoverStarted", "FailoverCompleted"} <= reasons
    # rv continuity: post-failover writes never reuse a replicated rv.
    top = max(p.meta.resource_version for p in promoted.list(POD))
    fresh = promoted.create(Pod(meta=new_meta("fresh", "default")))
    assert fresh.meta.resource_version > top


def test_apply_replicated_preserves_leader_stamps(tmp_path):
    rep = APIServer(shards=2)
    rep.read_only = True
    meta = new_meta("x", "ns")
    meta.resource_version = 41
    meta.uid = "uid-from-leader"
    obj = Pod(meta=meta)
    rep.apply_replicated("PUT", obj, (POD, "ns", "x"), (1, 41))
    got = rep.get(POD, "x", "ns")
    assert got.meta.resource_version == 41 and got.meta.uid == "uid-from-leader"
    assert rep.kind_fingerprint(POD) == (1, 41)
    rep.apply_replicated("DEL", None, (POD, "ns", "x"), (0, 42))
    assert rep.try_get(POD, "x", "ns") is None
    assert rep.kind_fingerprint(POD) == (0, 42)


# -- kubectl --cluster routing -----------------------------------------------


def test_resolve_cluster_urls_names_and_unknown(monkeypatch):
    from k8s_dra_driver_tpu.sim.kubectl import _resolve_cluster

    assert _resolve_cluster("http://h:1") == "http://h:1"
    monkeypatch.setenv("TPU_KUBECTL_CLUSTERS",
                       "leader=http://h:1, follower = http://h:2")
    assert _resolve_cluster("follower") == "http://h:2"
    with pytest.raises(SystemExit, match="follower, leader"):
        _resolve_cluster("staging")


# -- global scheduler --------------------------------------------------------


class _Decisions:
    def __init__(self):
        self.rows = []

    def decide(self, **kw):
        self.rows.append(kw)


def _views(a=64, b=32, wa=1.0, wb=1.0):
    return [
        ClusterView(name="a", free_chips=lambda: a, weight=wa),
        ClusterView(name="b", free_chips=lambda: b, weight=wb),
    ]


def test_place_packs_within_headroom_and_records_provenance():
    hist = _Decisions()
    sched = GlobalScheduler(_views(a=64, b=32), history=hist)
    reqs = [PlacementRequest(name=f"d{i}", chips=c)
            for i, c in enumerate((48, 16, 16, 8))]
    res = sched.place(reqs)
    assert not res.unplaced
    placed_chips = {"a": 0, "b": 0}
    for p in res.placements:
        placed_chips[p.cluster] += p.request.chips
    assert placed_chips["a"] <= 64 and placed_chips["b"] <= 32
    assert res.cluster_of("d0") == "a"  # only a holds 48 chips
    assert all(r["rule"] == RULE_FED_PLACE and r["controller"] == "federation"
               for r in hist.rows)
    assert all("headroom" in r["inputs"] for r in hist.rows)


def test_place_reports_unplaced_when_no_cluster_has_room():
    sched = GlobalScheduler(_views(a=16, b=8))
    res = sched.place([PlacementRequest(name="big", chips=64),
                       PlacementRequest(name="ok", chips=8)])
    assert [r.name for r in res.unplaced] == ["big"]
    assert res.cluster_of("ok") is not None


def test_place_weight_skews_fair_share():
    # Equal headroom; b's weight 3x — the water-fill should send the
    # bulk of an even request load to b.
    sched = GlobalScheduler(_views(a=64, b=64, wa=1.0, wb=3.0))
    res = sched.place([PlacementRequest(name=f"d{i}", chips=8)
                       for i in range(8)])
    per = {"a": 0, "b": 0}
    for p in res.placements:
        per[p.cluster] += p.request.chips
    assert per["b"] > per["a"]


def test_headroom_probe_failure_means_zero_not_crash():
    def boom():
        raise ConnectionError("partitioned")

    sched = GlobalScheduler([
        ClusterView(name="dead", free_chips=boom),
        ClusterView(name="ok", free_chips=lambda: 16),
    ])
    assert sched.headroom() == {"dead": 0, "ok": 16}
    res = sched.place([PlacementRequest(name="d", chips=8)])
    assert res.cluster_of("d") == "ok"


class _Alert:
    def __init__(self, burn):
        self.burn_rate = burn


class _SLO:
    def __init__(self, burn):
        self._burn = burn

    def active_alerts(self):
        return [_Alert(self._burn)] if self._burn else []


def test_spill_is_burn_proportional_with_max_headroom_target():
    hist = _Decisions()
    slo = _SLO(burn=5.5)
    sched = GlobalScheduler([
        ClusterView(name="hot", free_chips=lambda: 0, slo=slo),
        ClusterView(name="small", free_chips=lambda: 8),
        ClusterView(name="big", free_chips=lambda: 64),
    ], history=hist)
    frac, target = sched.spill("hot")
    # Linear: burn 1.0 -> 0, SPILL_FULL_BURN (10) -> MAX_SPILL (0.9).
    assert frac == pytest.approx(0.9 * 4.5 / 9.0)
    assert target == "big"
    assert hist.rows and hist.rows[0]["rule"] == RULE_FED_SPILL
    # Healthy SLO: no spill, no decision row.
    slo._burn = 0.0
    assert sched.spill("hot") == (0.0, None)


def test_spill_refuses_when_no_peer_has_headroom():
    sched = GlobalScheduler([
        ClusterView(name="hot", free_chips=lambda: 4, slo=_SLO(burn=20.0)),
        ClusterView(name="full", free_chips=lambda: 0),
    ])
    assert sched.spill("hot") == (0.0, None)
