"""SLO burn-rate evaluator: rule math, multi-window AND, event dedup.

Pins docs/reference/telemetry.md's SLO layer: burn rate =
bad_fraction / (1 - target) per window, an alert needs BOTH windows of a
(long, short) pair above threshold, violation minutes accumulate only
while burning, SLOBurnRate events dedup through the recorder correlator,
and per-subject state is time- and LRU-bounded.
"""

import pytest

from k8s_dra_driver_tpu.k8s import APIServer
from k8s_dra_driver_tpu.k8s.core import EVENT, ResourceClaim
from k8s_dra_driver_tpu.k8s.objects import new_meta
from k8s_dra_driver_tpu.pkg.events import REASON_SLO_BURN_RATE, EventRecorder
from k8s_dra_driver_tpu.pkg.metrics import Registry
from k8s_dra_driver_tpu.pkg.slo import SLOEvaluator, SLObjective


def _evaluator(recorder=None, **kw):
    return SLOEvaluator(Registry(), recorder=recorder, **kw)


WINDOWS = ((100.0, 20.0),)


def _objective(**kw):
    defaults = dict(name="duty", target=0.90, bound=0.95, op="gt",
                    windows=WINDOWS, burn_threshold=2.0)
    defaults.update(kw)
    return SLObjective(**defaults)


def test_objective_validation():
    assert _objective().is_bad(0.96) and not _objective().is_bad(0.95)
    lt = _objective(name="ttr", op="lt", bound=5.0)
    assert lt.is_bad(4.0) and not lt.is_bad(5.0)
    with pytest.raises(ValueError):
        _objective(op="between")
    with pytest.raises(ValueError):
        _objective(target=1.0)
    with pytest.raises(ValueError):
        _objective(target=0.0)


def test_observe_unknown_slo_raises():
    ev = _evaluator()
    with pytest.raises(KeyError):
        ev.observe("nope", 1.0, 0.5)


def test_burn_rate_math():
    """20 samples in the window, 4 bad, target 0.90: burn =
    (4/20) / 0.10 = 2.0 exactly."""
    ev = _evaluator()
    ev.add(_objective())
    for i in range(20):
        value = 0.99 if i % 5 == 0 else 0.5   # 4 of 20 bad
        ev.observe("duty", 80.0 + i, value, subject=("ns", "c"))
    alerts = ev.evaluate(100.0)
    # Both the 100s window (all 20 samples) and the 20s window (samples
    # at t>=80... all 20) burn at 2.0 -> fires at threshold.
    assert alerts and alerts[0].burn_rate == pytest.approx(2.0)
    assert ev.burn_gauge.value("duty", "100/20") == pytest.approx(2.0)


def test_burn_gauge_decays_after_subject_goes_quiet():
    """Regression: the burn gauge must fall back to 0 once a subject's
    samples age out (claim unprepared, incident over) — the last
    alert-level value must not stick on /metrics forever."""
    ev = _evaluator()
    ev.add(_objective())
    for i in range(20):
        ev.observe("duty", 80.0 + i, 0.99, subject=("ns", "c"))  # all bad
    assert ev.evaluate(100.0)
    assert ev.burn_gauge.value("duty", "100/20") == pytest.approx(10.0)
    # No further observations; everything ages past the longest window.
    assert ev.evaluate(300.0) == []
    assert ev.burn_gauge.value("duty", "100/20") == 0.0


def test_alert_requires_both_windows():
    """Long window still polluted, short window recovered: no alert —
    the incident is over and alerting must stop immediately."""
    ev = _evaluator()
    ev.add(_objective())
    for i in range(50):
        ev.observe("duty", float(i), 0.99, subject=("ns", "c"))   # all bad
    for i in range(50, 100):
        ev.observe("duty", float(i), 0.50, subject=("ns", "c"))   # recovered
    alerts = ev.evaluate(100.0)
    assert alerts == []
    # And the gauge publishes the (low) effective burn, not the long
    # window's scary one.
    assert ev.burn_gauge.value("duty", "100/20") == 0.0


def test_blip_never_alerts():
    """One bad sample in an otherwise clean stream: the short window may
    spike but the long window stays calm -> no alert."""
    ev = _evaluator()
    ev.add(_objective())
    for i in range(99):
        ev.observe("duty", float(i), 0.5, subject=("ns", "c"))
    ev.observe("duty", 99.0, 0.99, subject=("ns", "c"))
    assert ev.evaluate(100.0) == []


def test_violation_minutes_accumulate_only_while_burning():
    ev = _evaluator()
    ev.add(_objective())
    for i in range(160):
        ev.observe("duty", float(i), 0.99, subject=("ns", "c"))
    ev.evaluate(100.0)                      # first eval: dt unknown -> 0
    ev.evaluate(160.0)                      # 1 minute burning
    assert ev.violation_minutes.value("duty") == pytest.approx(1.0)
    # Recovery: stream turns good, burn drops, minutes freeze.
    for i in range(160, 260):
        ev.observe("duty", float(i), 0.5, subject=("ns", "c"))
    ev.evaluate(260.0)
    ev.evaluate(320.0)
    assert ev.violation_minutes.value("duty") == pytest.approx(1.0)


def test_burnrate_event_dedup():
    """A sustained violation across many evaluate() passes lands as ONE
    stored SLOBurnRate Event with a rising count — the message carries no
    live numbers precisely so the correlator can aggregate it."""
    api = APIServer()
    claim = api.create(ResourceClaim(meta=new_meta("hot", "default")))
    rec = EventRecorder(api, "telemetry", burst=1000)
    ev = _evaluator(recorder=rec)
    ev.add(_objective())
    for tick in range(100):
        ev.observe("duty", float(tick), 0.99, subject=("default", "hot"),
                   ref=claim)
    for t in (100.0, 101.0, 102.0, 103.0):
        assert ev.evaluate(t), "sustained overload must keep alerting"
    events = [e for e in api.list(EVENT, namespace="default")
              if e.reason == REASON_SLO_BURN_RATE]
    assert len(events) == 1, [e.message for e in events]
    assert events[0].count == 4
    assert "duty" in events[0].message


def test_one_event_per_subject_even_if_both_pairs_fire():
    api = APIServer()
    claim = api.create(ResourceClaim(meta=new_meta("hot", "default")))
    rec = EventRecorder(api, "telemetry", burst=1000)
    ev = _evaluator(recorder=rec)
    ev.add(_objective(windows=((100.0, 20.0), (50.0, 10.0))))
    for tick in range(100):
        ev.observe("duty", float(tick), 0.99, subject=("default", "hot"),
                   ref=claim)
    alerts = ev.evaluate(100.0)
    assert len(alerts) == 2                 # both pairs above threshold
    events = [e for e in api.list(EVENT, namespace="default")
              if e.reason == REASON_SLO_BURN_RATE]
    assert len(events) == 1 and events[0].count == 1


def test_history_pruned_to_longest_window():
    ev = _evaluator()
    ev.add(_objective(windows=((30.0, 10.0),)))
    for i in range(200):
        ev.observe("duty", float(i), 0.5, subject=("ns", "c"))
    state = ev._subjects[("duty", ("ns", "c"))]
    assert all(t >= 199.0 - 30.0 for t, _ in state.samples)


def test_subject_lru_bound():
    ev = _evaluator(max_subjects=4)
    ev.add(_objective())
    for i in range(10):
        ev.observe("duty", 1.0, 0.5, subject=("ns", f"c{i}"))
    assert len(ev._subjects) <= 4
    # Most recent subjects survive.
    assert ("duty", ("ns", "c9")) in ev._subjects


# -- active_alerts(): the controller-facing incident snapshot -----------------


def test_active_alerts_snapshot_and_since_stability():
    """Firing incidents appear in active_alerts() with a `since` pinned
    to the FIRST evaluation that saw them, stable across later passes
    while the incident persists."""
    ev = _evaluator()
    ev.add(_objective())
    for i in range(20):
        ev.observe("duty", 80.0 + i, 0.99, subject=("ns", "hot"))
    assert ev.active_alerts() == []          # nothing evaluated yet
    ev.evaluate(100.0)
    alerts = ev.active_alerts()
    assert len(alerts) == 1
    a = alerts[0]
    assert (a.slo, a.subject) == ("duty", ("ns", "hot"))
    assert a.burn_rate >= 2.0 and a.since == 100.0
    # Still burning two passes later: same incident, same since.
    ev.observe("duty", 101.0, 0.99, subject=("ns", "hot"))
    ev.evaluate(101.0)
    ev.observe("duty", 102.0, 0.99, subject=("ns", "hot"))
    ev.evaluate(102.0)
    again = ev.active_alerts()
    assert len(again) == 1 and again[0].since == 100.0


def test_active_alerts_recovered_incident_disappears_immediately():
    """The satellite pin: a recovered incident is gone from the very
    next snapshot — the autoscaler must never scale on stale alerts."""
    ev = _evaluator()
    ev.add(_objective())
    for i in range(20):
        ev.observe("duty", 80.0 + i, 0.99, subject=("ns", "hot"))
    ev.evaluate(100.0)
    assert ev.active_alerts()
    # Recovery: the short window fills with good samples, so the
    # multi-window AND stops the alert immediately.
    for i in range(25):
        ev.observe("duty", 100.0 + i, 0.1, subject=("ns", "hot"))
    ev.evaluate(125.0)
    assert ev.active_alerts() == []
    # Re-offending later is a NEW incident with a fresh since.
    for i in range(30):
        ev.observe("duty", 126.0 + i, 0.99, subject=("ns", "hot"))
    ev.evaluate(156.0)
    fresh = ev.active_alerts()
    assert len(fresh) == 1 and fresh[0].since == 156.0


def test_active_alerts_one_entry_per_subject_worst_burn():
    """A subject firing on BOTH window pairs collapses to one snapshot
    entry carrying the worst effective burn."""
    ev = _evaluator()
    ev.add(_objective(windows=((100.0, 20.0), (50.0, 10.0))))
    for i in range(100):
        ev.observe("duty", float(i), 0.99, subject=("ns", "hot"))
    alerts = ev.evaluate(100.0)
    assert len(alerts) == 2                  # both pairs fire
    snapshot = ev.active_alerts()
    assert len(snapshot) == 1
    assert snapshot[0].burn_rate == max(a.burn_rate for a in alerts)
