"""Chart-as-executed: the rendered Helm chart's container specs run.

Without docker/kind, render-validation alone can't prove the chart's
command/args/env/mount composition actually starts a working driver (the
reference proves it with its mock-NVML kind e2e,
.github/workflows/mock-nvml-e2e.yaml:42-83). This harness closes that
gap: it renders the chart with MiniHelm, extracts the kubelet-plugin
DaemonSet and controller Deployment container specs, and launches the
EXACT commands with the EXACT env as local OS processes against the
conformance apiserver — playing only the roles the platform would
(kubelet mounts hostPath volumes under a sandbox root, the downward API
resolves NODE_NAME, the service account provides the API endpoint).

Editing a chart command, module path, env var name, or default value
breaks this test — not just a live cluster.
"""

import os
import subprocess
import sys
import tempfile
import time

import pytest
import yaml

from tests.test_helm_chart import CHART, MiniHelm
from tests.test_kubelet_grpc import FakeKubelet

from k8s_dra_driver_tpu.api.computedomain import ComputeDomain, ComputeDomainSpec
from k8s_dra_driver_tpu.api.configs import TPU_DRIVER_NAME
from k8s_dra_driver_tpu.k8s.core import (
    DAEMON_SET,
    RESOURCE_SLICE,
    DeviceClass,
    DeviceRequest,
    Node,
    ResourceClaim,
)
from k8s_dra_driver_tpu.k8s.kubeclient import KubernetesAPIServer
from k8s_dra_driver_tpu.k8s.objects import new_meta
from k8s_dra_driver_tpu.sim.allocator import Allocator

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
NODE_NAME = "chart-node-0"
RELEASE = "exec"
NAMESPACE = "tpu-dra-driver"


def _wait(cond, timeout=45.0, msg="condition", procs=()):
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        for p in procs:
            if p.poll() is not None:
                tail = ""
                log = getattr(p, "chart_log", "")
                if log and os.path.exists(log):
                    with open(log, encoding="utf-8") as f:
                        tail = f.read()[-3000:]
                raise AssertionError(
                    f"{getattr(p, 'chart_name', '?')} died:\n{tail}")
        v = cond()
        if v:
            return v
        time.sleep(0.2)
    raise AssertionError(f"timed out waiting for {msg}")


def _render(template, values):
    with open(os.path.join(CHART, "templates", template), encoding="utf-8") as f:
        text = MiniHelm(values, release=RELEASE, namespace=NAMESPACE).render(f.read())
    return [d for d in yaml.safe_load_all(text) if d]


def _find(docs, kind, name):
    for d in docs:
        if d["kind"] == kind and d["metadata"]["name"] == name:
            return d
    raise AssertionError(f"{kind}/{name} not in render: "
                         f"{[(d['kind'], d['metadata']['name']) for d in docs]}")


class ChartProcessLauncher:
    """Launches a rendered container spec as a local process, standing in
    for exactly what the platform provides: the image's interpreter, the
    hostPath mounts (sandboxed), the downward API, and in-cluster API
    access (API_SERVER_URL, read by the same flag the service-account
    path feeds)."""

    def __init__(self, sandbox, api_url):
        self.sandbox = sandbox
        self.api_url = api_url
        self.procs = []
        self._log_files = []

    def launch(self, container, extra_env=None):
        cmd = list(container["command"]) + list(container.get("args", []))
        assert cmd[0] == "python", f"unexpected interpreter in chart: {cmd}"
        cmd[0] = sys.executable
        env = {}
        for e in container.get("env", []):
            if "value" in e:
                env[e["name"]] = e["value"]
            elif (e.get("valueFrom", {}).get("fieldRef", {}).get("fieldPath")
                  == "spec.nodeName"):
                env[e["name"]] = NODE_NAME
            else:
                raise AssertionError(f"unsupported env source in chart: {e}")
        # Kubelet's job: hostPath mounts materialize under the sandbox, so
        # every absolute path the chart passes is remapped wholesale.
        for k, v in env.items():
            if v.startswith("/"):
                env[k] = self.sandbox + v
                os.makedirs(env[k], exist_ok=True)
        env.update({
            "PATH": os.environ.get("PATH", "/usr/bin:/bin"),
            "HOME": os.environ.get("HOME", "/root"),
            "PYTHONPATH": REPO,
            "PYTHONUNBUFFERED": "1",
            "API_SERVER_URL": self.api_url,
            **(extra_env or {}),
        })
        # Log to a file, not a PIPE: nothing drains the pipe while the
        # process runs, so a chatty container would block on a full
        # buffer and fail the test with an undiagnostic timeout.
        log_path = os.path.join(self.sandbox, f"{container['name']}.log")
        log_f = open(log_path, "w", encoding="utf-8")
        p = subprocess.Popen(cmd, env=env, cwd=REPO, stdout=log_f,
                             stderr=subprocess.STDOUT, text=True)
        p.chart_name = container["name"]
        p.chart_env = env
        p.chart_log = log_path
        self._log_files.append(log_f)
        self.procs.append(p)
        return p

    def stop(self):
        for p in reversed(self.procs):
            if p.poll() is None:
                p.terminate()
        for p in self.procs:
            try:
                p.wait(timeout=10)
            except subprocess.TimeoutExpired:
                p.kill()
        for f in self._log_files:
            f.close()


@pytest.fixture
def harness():
    # Unix socket paths cap at ~107 bytes; pytest tmp paths are too long
    # once the chart's /var/lib/kubelet/... prefix lands on top.
    sandbox = tempfile.mkdtemp(prefix="chart-")
    apiserver = subprocess.Popen(
        [sys.executable, "-m", "k8s_dra_driver_tpu.k8s.k8sapiserver",
         "--port", "0"],
        env={**os.environ, "PYTHONPATH": REPO}, cwd=REPO,
        stdout=subprocess.PIPE, stderr=subprocess.STDOUT, text=True,
    )
    line = apiserver.stdout.readline()
    assert "serving k8s wire on " in line, line
    url = line.strip().split()[-1]
    launcher = ChartProcessLauncher(sandbox, url)
    try:
        yield launcher, KubernetesAPIServer(base_url=url)
    finally:
        launcher.stop()
        apiserver.terminate()
        try:
            apiserver.wait(timeout=10)
        except subprocess.TimeoutExpired:
            apiserver.kill()
        import shutil

        shutil.rmtree(sandbox, ignore_errors=True)  # mkdtemp: caller cleans up


def _chart_values():
    with open(os.path.join(CHART, "values.yaml"), encoding="utf-8") as f:
        values = yaml.safe_load(f)
    # User-facing values choices, not spec rewrites: the chart's own mock
    # seam (the mock-NVML driver-root analog) and ephemeral metrics ports
    # so parallel CI runs don't collide.
    values["kubeletPlugin"]["altTpuTopology"] = "v5e-4"
    values["kubeletPlugin"]["metricsPort"] = 0
    values["controller"]["metricsPort"] = 0
    return values


def test_chart_daemonset_containers_run_and_prepare(harness):
    """The DaemonSet's two plugin containers, launched verbatim from the
    render, register over the chart-configured kubelet dirs, publish
    ResourceSlices, and serve a Prepare whose CDI spec lands under the
    chart's cdiRoot."""
    launcher, kube = harness
    values = _chart_values()
    ds = _find(_render("kubeletplugin.yaml", values),
               "DaemonSet", f"{RELEASE}-kubelet-plugin")
    containers = {c["name"]: c for c in ds["spec"]["template"]["spec"]["containers"]}
    assert set(containers) == {"tpu-kubelet-plugin", "compute-domain-kubelet-plugin"}

    kube.create(Node(meta=new_meta(NODE_NAME)))
    kube.create(DeviceClass(meta=new_meta("tpu.google.com"),
                            driver=TPU_DRIVER_NAME,
                            match_attributes={"type": "tpu"}))

    by_name = {name: launcher.launch(c) for name, c in containers.items()}
    procs = list(by_name.values())

    # Both drivers publish their node's slices through the chart env alone.
    _wait(lambda: len({s.driver for s in kube.list(RESOURCE_SLICE)
                       if s.node_name == NODE_NAME}) >= 2,
          msg="ResourceSlices from both chart containers", procs=procs)

    # The kubelet seam: the registration socket appears under the chart's
    # REGISTRAR_DIR (sandboxed hostPath), exactly where kubelet watches.
    tpu_env = by_name["tpu-kubelet-plugin"].chart_env
    registrar = tpu_env["REGISTRAR_DIR"]
    kubelet = FakeKubelet(registrar)
    _wait(lambda: kubelet.discover_sockets(), msg="registration sockets",
          procs=procs)
    socks = kubelet.discover_sockets()
    tpu_sock = next(s for s in socks if "tpu.google.com" in s
                    and "compute-domain" not in s)
    endpoint = kubelet.get_info(tpu_sock).endpoint
    assert endpoint.startswith(tpu_env["KUBELET_PLUGIN_DIR"]), (
        "DRA socket must live under the chart's pluginDir")
    kubelet.notify_registered(tpu_sock)

    # A claim prepared over that socket materializes its CDI spec under
    # the chart's cdiRoot.
    claim = kube.create(ResourceClaim(
        meta=new_meta("chart-claim", "default"),
        requests=[DeviceRequest(name="tpus", device_class_name="tpu.google.com",
                                count=1)],
    ))
    alloc = Allocator(kube).allocate_on_node(claim, NODE_NAME)
    assert alloc is not None

    def set_alloc(obj):
        obj.allocation = alloc

    claim = kube.update_with_retry("ResourceClaim", "chart-claim", "default",
                                   set_alloc)
    resp = kubelet.node_prepare(endpoint, [claim], "v1")
    assert resp.claims[claim.uid].error == "", resp.claims[claim.uid].error
    cdi_root = tpu_env["CDI_ROOT"]
    specs = os.listdir(cdi_root)
    assert any(claim.uid in f for f in specs), (cdi_root, specs)


def test_chart_controller_container_reconciles(harness):
    """The controller Deployment's container, launched verbatim from the
    render (including --driver-namespace derived from the release
    namespace), reconciles a ComputeDomain into a slice-agent DaemonSet."""
    launcher, kube = harness
    values = _chart_values()
    dep = _find(_render("controller.yaml", values),
                "Deployment", f"{RELEASE}-controller")
    (container,) = dep["spec"]["template"]["spec"]["containers"]
    assert f"--driver-namespace={NAMESPACE}" in container["args"]

    proc = launcher.launch(container)
    kube.create(ComputeDomain(meta=new_meta("cd-chart", "default"),
                              spec=ComputeDomainSpec(num_nodes=1)))
    _wait(lambda: kube.try_get(DAEMON_SET, "cd-chart-slice-agent", NAMESPACE),
          msg="controller rendered the slice-agent DaemonSet", procs=[proc])
