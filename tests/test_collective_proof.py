"""The cluster-assembly function proof: real OS processes, configured ONLY
by the env the driver's CDI specs injected, initialize a jax.distributed
cluster and agree on a cross-process psum.

This is the correctness half of the BASELINE north star (the reference's
nvbandwidth-test-job run on an assembled IMEX domain,
demo/specs/imex/nvbandwidth-test-job.yaml): not "the env looks
consistent" but "the cluster the driver assembles actually initializes
and reduces". The fabric half (ICI line rate) needs multi-host TPU
hardware; here the collective rides the CPU backend's TCP runtime.
"""

import json
import os
import subprocess
import sys

import pytest

from k8s_dra_driver_tpu.e2e import SPECS_DIR
from k8s_dra_driver_tpu.k8s.core import POD
from k8s_dra_driver_tpu.sim import SimCluster
from k8s_dra_driver_tpu.sim.kubectl import apply_file

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _collect_worker_envs(tmp_path):
    """Run the allreduce-job scenario on a loopback sim cluster and return
    each running worker's injected env, exactly as CDI materialized it."""
    sim = SimCluster(
        workdir=str(tmp_path),
        gates="SliceAgentsWithDNSNames=false",
        loopback_agents=True,
    )
    sim.start()
    try:
        apply_file(sim.api, os.path.join(SPECS_DIR, "computedomain/allreduce-job.yaml"))
        sim.settle()
        pods = [p for p in sim.api.list(POD)
                if p.namespace == "allreduce" and p.phase == "Running"]
        assert len(pods) == 4, [(p.meta.name, p.phase) for p in sim.api.list(POD)]
        return [dict(p.injected_env) for p in pods]
    finally:
        sim.stop()


def _multiprocess_impl() -> str:
    """The CPU collectives implementation the psum workers should use, or
    "" when none works. The workers below are pinned to JAX_PLATFORMS=cpu
    regardless of the parent's backend, and XLA:CPU rejects multi-process
    computations unless a collectives implementation (gloo/mpi) is
    configured — bare XLA:CPU raises 'Multiprocess computations aren't
    implemented on the CPU backend'.

    An explicitly configured implementation wins; otherwise gloo is probed
    EMPIRICALLY (a 2-process jax.distributed.initialize on an ephemeral
    port) so the proof runs — instead of skipping — on any jaxlib that
    ships gloo without the env var being set."""
    impl = os.environ.get("JAX_CPU_COLLECTIVES_IMPLEMENTATION", "")
    if not impl:
        try:
            import jax

            impl = getattr(jax.config, "jax_cpu_collectives_implementation",
                           None) or ""
            if not impl and getattr(jax.config,
                                    "jax_cpu_enable_gloo_collectives", False):
                impl = "gloo"
        except Exception:  # noqa: BLE001 — fall through to the probe
            impl = ""
    if impl:
        return "" if impl == "none" else impl
    return "gloo" if _gloo_probe_works() else ""


def _gloo_probe_works() -> bool:
    import socket

    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        port = s.getsockname()[1]
    # The probe must run a REAL cross-process collective, not just
    # initialize: some jaxlibs initialize fine and then reject the
    # computation ("Multiprocess computations aren't implemented on the
    # CPU backend") when the collectives impl didn't actually bind.
    code = (
        "import os, jax\n"
        "try:\n"
        "    jax.config.update('jax_cpu_collectives_implementation',"
        " 'gloo')\n"
        "except (AttributeError, ValueError):\n"
        "    pass\n"
        "jax.distributed.initialize("
        f"coordinator_address='127.0.0.1:{port}', num_processes=2, "
        "process_id=int(os.environ['PROBE_PID']))\n"
        "import numpy as np\n"
        "from jax.sharding import Mesh, NamedSharding, "
        "PartitionSpec as P\n"
        "mesh = Mesh(np.array(jax.devices()), ('d',))\n"
        "arr = jax.make_array_from_process_local_data("
        "NamedSharding(mesh, P('d')), "
        "np.ones(jax.local_device_count()))\n"
        "out = jax.jit(lambda a: a.sum(), "
        "out_shardings=NamedSharding(mesh, P()))(arr)\n"
        "assert float(jax.device_get(out)) == len(jax.devices())\n"
    )
    procs = [
        subprocess.Popen(
            [sys.executable, "-c", code],
            env={**os.environ, "PROBE_PID": str(i), "JAX_PLATFORMS": "cpu",
                 "JAX_CPU_COLLECTIVES_IMPLEMENTATION": "gloo"},
            stdout=subprocess.DEVNULL, stderr=subprocess.DEVNULL,
        )
        for i in range(2)
    ]
    ok = True
    for p in procs:
        try:
            ok = p.wait(timeout=90) == 0 and ok
        except subprocess.TimeoutExpired:
            for q in procs:
                q.kill()
            return False
    return ok


def test_multiprocess_psum_from_injected_env(tmp_path):
    impl = _multiprocess_impl()
    if not impl:
        pytest.skip(
            "CPU backend has no working multiprocess collectives "
            "implementation (gloo probe failed and none configured)"
        )
    envs = _collect_worker_envs(tmp_path)

    # The driver-injected identities must already be a coherent cluster
    # spec before anything launches.
    ids = sorted(int(e["TPU_WORKER_ID"]) for e in envs)
    assert ids == [0, 1, 2, 3]
    coords = {e["MEGASCALE_COORDINATOR_ADDRESS"] for e in envs}
    assert len(coords) == 1
    coord = coords.pop()
    assert coord.startswith("127.0.0.1:")
    # Loopback sims allocate the coordinator port dynamically at DaemonSet
    # render (bound free on THIS host), so the proof never has to skip
    # because some unrelated process holds the fixed well-known port.
    port = int(coord.rpartition(":")[2])
    assert port > 0

    procs = []
    for env in envs:
        # The worker's ONLY configuration is the injected env; the
        # harness adds interpreter hygiene (PATH/PYTHONPATH) and pins the
        # CPU backend — a real slice would use the TPU backend the same
        # env bootstraps.
        penv = dict(env)
        penv.update({
            "PATH": os.environ.get("PATH", "/usr/bin:/bin"),
            "HOME": os.environ.get("HOME", "/root"),
            "PYTHONPATH": REPO,
            "JAX_PLATFORMS": "cpu",
        })
        # The capability the probe above established must reach the
        # workers (the probe may have selected gloo without any env set).
        penv["JAX_CPU_COLLECTIVES_IMPLEMENTATION"] = impl
        procs.append(subprocess.Popen(
            [sys.executable, "-m", "k8s_dra_driver_tpu.ops.psum_proof"],
            env=penv, stdout=subprocess.PIPE, stderr=subprocess.PIPE,
            text=True, cwd=REPO,
        ))

    results = []
    for p in procs:
        try:
            out, err = p.communicate(timeout=240)
        except subprocess.TimeoutExpired:
            for q in procs:
                q.kill()
            pytest.fail("worker timed out: cluster never initialized")
        assert p.returncode == 0, f"worker failed:\n{err[-2000:]}"
        results.append(json.loads(out.strip().splitlines()[-1]))

    # Every process initialized the same 4-process cluster and the psum
    # agrees everywhere: sum over workers of (id+1) * local_devices.
    assert {r["num_processes"] for r in results} == {4}
    expected = sum(
        (r["process_id"] + 1) * r["local_devices"] for r in results
    )
    assert {r["psum"] for r in results} == {float(expected)}, results
    assert {r["global_devices"] for r in results} == {
        sum(r["local_devices"] for r in results)
    }
    # The proof now self-verifies: every worker derived the same expected
    # value in-process and stamped ok=true (exit 0 already asserted above).
    assert {r["expected"] for r in results} == {float(expected)}, results
    assert all(r["ok"] for r in results), results


# -- self-verification: a corrupted reduction must FAIL the job --------------


def test_psum_proof_self_verifies_good_result(monkeypatch, capsys):
    from k8s_dra_driver_tpu.ops import psum_proof

    good = {"process_id": 0, "num_processes": 4, "local_devices": 1,
            "global_devices": 4, "psum": 10.0, "expected": 10.0,
            "ok": True, "platform": "cpu"}
    monkeypatch.setattr(psum_proof, "run_proof", lambda: good)
    assert psum_proof.main() == 0
    assert json.loads(capsys.readouterr().out)["ok"] is True


def test_psum_proof_corrupted_reduction_fails_the_job(monkeypatch, capsys):
    """Round-5 advisor nit: a wrong psum used to print and exit 0 — the
    harness would read a broken collective as success. Now the mismatch
    is detected in-process and the job exits nonzero."""
    from k8s_dra_driver_tpu.ops import psum_proof

    bad = {"process_id": 0, "num_processes": 4, "local_devices": 1,
           "global_devices": 4, "psum": 7.0, "expected": 10.0,
           "ok": False, "platform": "cpu"}
    monkeypatch.setattr(psum_proof, "run_proof", lambda: bad)
    assert psum_proof.main() == 1
    captured = capsys.readouterr()
    assert "psum proof FAILED" in captured.err
    assert json.loads(captured.out)["ok"] is False


def test_psum_proof_expected_derivation_single_process(monkeypatch):
    """run_proof's expected-value formula on the degenerate 1-process
    cluster: psum == expected == local_device_count * 1 — exercised
    in-process (no subprocess fleet) via a single-process initialize."""
    if "TPU_WORKER_HOSTNAMES" in os.environ:  # pragma: no cover
        pytest.skip("running inside a driver-assembled slice")
    import jax

    from k8s_dra_driver_tpu.ops import psum_proof

    monkeypatch.setenv("TPU_WORKER_HOSTNAMES", "localhost")
    monkeypatch.setenv("TPU_WORKER_ID", "0")
    monkeypatch.setenv("MEGASCALE_COORDINATOR_ADDRESS", "127.0.0.1:8477")
    # Single-process "distributed" init is a no-op cluster; keep it local.
    monkeypatch.setattr(jax.distributed, "initialize",
                        lambda **kw: None)
    result = psum_proof.run_proof()
    devs = jax.local_device_count()
    assert result["expected"] == float(devs)
    assert result["psum"] == result["expected"]
    assert result["ok"] is True
