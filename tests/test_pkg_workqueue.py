"""WorkQueue: dedupe/coalesce, retry with backoff, jitter limiter bounds."""

import random
import threading
import time

from k8s_dra_driver_tpu.pkg.workqueue import (
    ExponentialRateLimiter,
    JitterRateLimiter,
    WorkQueue,
)


def test_exponential_rate_limiter_doubles_and_caps():
    rl = ExponentialRateLimiter(base=1.0, cap=8.0)
    assert [rl.when("k") for _ in range(5)] == [1.0, 2.0, 4.0, 8.0, 8.0]
    rl.forget("k")
    assert rl.when("k") == 1.0
    # Keys are independent.
    assert rl.when("other") == 1.0


def test_jitter_limiter_stays_within_factor():
    rl = JitterRateLimiter(ExponentialRateLimiter(base=10.0, cap=10.0), factor=0.2,
                           rng=random.Random(42))
    for _ in range(200):
        d = rl.when("k")
        assert 8.0 <= d <= 12.0


def test_workqueue_processes_and_coalesces():
    seen = []
    done = threading.Event()

    def handler(key, obj):
        seen.append((key, obj))
        if obj == "final":
            done.set()
        time.sleep(0.05)

    q = WorkQueue(handler, name="t")
    q.start(workers=1)
    try:
        q.enqueue("a", "v1")
        # These land while "a" may be queued/processing; they coalesce.
        q.enqueue("a", "v2")
        q.enqueue("a", "final")
        assert done.wait(timeout=5)
        assert q.drain(timeout=5)
    finally:
        q.stop()
    # First run sees some version, a coalesced re-run sees the latest.
    assert seen[-1] == ("a", "final")
    assert len(seen) <= 3


def test_workqueue_retries_on_failure_then_succeeds():
    attempts = []
    done = threading.Event()

    def handler(key, obj):
        attempts.append(time.monotonic())
        if len(attempts) < 3:
            raise RuntimeError("transient")
        done.set()

    q = WorkQueue(handler, rate_limiter=ExponentialRateLimiter(base=0.01, cap=0.05), name="t")
    q.start(workers=1)
    try:
        q.enqueue("k", None)
        assert done.wait(timeout=5)
    finally:
        q.stop()
    assert len(attempts) == 3


def test_workqueue_drops_after_max_retries():
    n = [0]

    def handler(key, obj):
        n[0] += 1
        raise RuntimeError("permanent")

    q = WorkQueue(handler, rate_limiter=ExponentialRateLimiter(base=0.005, cap=0.01),
                  name="t", max_retries=2)
    q.start(workers=1)
    try:
        q.enqueue("k", None)
        assert q.drain(timeout=5)
    finally:
        q.stop()
    assert n[0] == 3  # initial + 2 retries


def test_workqueue_multiple_keys_parallel_workers():
    seen = set()
    lock = threading.Lock()

    def handler(key, obj):
        with lock:
            seen.add(key)

    q = WorkQueue(handler, name="t")
    q.start(workers=4)
    try:
        for i in range(50):
            q.enqueue(f"k{i}")
        assert q.drain(timeout=5)
    finally:
        q.stop()
    assert seen == {f"k{i}" for i in range(50)}
