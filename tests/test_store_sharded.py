"""Sharded store vs single-lock oracle under randomized threaded churn.

The scale-out store re-partitioned every index and moved watch fan-out
off-lock; none of that may change WHAT the store does. These tests pin:

- final contents after a randomized multi-threaded workload match a
  brute-force replay of the same per-key operation streams,
- fingerprint tokens stay unique per kind-content history under churn,
- per-kind watch ordering survives batched off-lock fan-out (every
  subscription sees each key's ADDED/MODIFIED/DELETED sequence in write
  order, resourceVersions non-decreasing),
- bounded-queue drop accounting stays EXACT under batching,
- kind-to-shard assignment gives distinct hot kinds distinct locks, and
  the `shards=1` baseline flag still serves the full API.
"""

import queue
import random
import threading

import pytest

from k8s_dra_driver_tpu.k8s import APIServer, ConflictError, NotFoundError
from k8s_dra_driver_tpu.k8s.core import (
    COMPUTE_DOMAIN,
    DAEMON_SET,
    NODE,
    POD,
    RESOURCE_CLAIM,
    RESOURCE_SLICE,
)
from k8s_dra_driver_tpu.k8s.core import Pod, ResourceClaim
from k8s_dra_driver_tpu.k8s.objects import new_meta
from k8s_dra_driver_tpu.k8s.serialize import kind_registry

KINDS = (POD, RESOURCE_CLAIM, NODE, RESOURCE_SLICE, DAEMON_SET,
         COMPUTE_DOMAIN)


def _churn(api, kind, seed, ops, log):
    """One writer thread: random create/update/delete churn over a small
    name space of its own kind, recording the op outcomes. Per-kind
    ordering is what the store guarantees, so one thread per kind makes
    the recorded log THE oracle stream for that kind."""
    rng = random.Random(seed)
    cls = kind_registry()[kind]
    names = [f"{kind.lower()}-{i}" for i in range(8)]
    for _ in range(ops):
        name = rng.choice(names)
        r = rng.random()
        try:
            if r < 0.5:
                obj = cls(meta=new_meta(name, "default",
                                        labels={"step": str(rng.random())}))
                api.create(obj)
                log.append(("PUT", name))
            elif r < 0.8:
                got = api.get(kind, name, "default", copy=True)
                got.meta.labels["touched"] = "1"
                api.update(got)
                log.append(("PUT", name))
            else:
                api.delete(kind, name, "default")
                log.append(("DEL", name))
        except (NotFoundError, ConflictError, Exception) as e:
            if e.__class__.__name__ not in (
                    "NotFoundError", "AlreadyExistsError", "ConflictError"):
                raise


@pytest.mark.parametrize("shards", [1, 8, 16])
def test_threaded_churn_matches_per_kind_oracle(shards):
    api = APIServer(shards=shards)
    watchers = {kind: api.watch(kind, maxsize=65536) for kind in KINDS}
    logs = {kind: [] for kind in KINDS}
    threads = [
        threading.Thread(target=_churn,
                         args=(api, kind, 1000 + i, 400, logs[kind]))
        for i, kind in enumerate(KINDS)
    ]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    api.flush_watchers()

    for kind in KINDS:
        # Oracle: replay this kind's recorded op log (one writer per kind,
        # so the log IS the serialized history).
        alive = set()
        for op, name in logs[kind]:
            if op == "PUT":
                alive.add(name)
            else:
                alive.discard(name)
        got = {o.meta.name for o in api.list(kind)}
        assert got == alive, (kind, got, alive)
        # Fingerprint count component equals the live count.
        assert api.kind_fingerprint(kind)[0] == len(alive)

        # The watch stream replays to the same final state, in write
        # order: stamped events (ADDED/MODIFIED consume an rv) arrive
        # with strictly increasing resourceVersions per kind, and every
        # key's own sequence is type-consistent with non-decreasing rv
        # (a DELETED re-carries its key's last stamp, which may trail
        # another key's newer one).
        state = {}
        last_stamp = 0
        key_rv = {}
        q = watchers[kind]
        while True:
            try:
                ev = q.get_nowait()
            except queue.Empty:
                break
            rv = ev.obj.meta.resource_version
            name = ev.obj.meta.name
            assert rv >= key_rv.get(name, 0), (
                f"{kind}/{name}: rv went backwards under batched fan-out")
            key_rv[name] = rv
            if ev.type == "ADDED":
                assert rv > last_stamp, f"{kind}: stamped rv not increasing"
                last_stamp = rv
                assert name not in state, f"{kind}/{name}: ADDED while live"
                state[name] = ev.obj
            elif ev.type == "MODIFIED":
                assert rv > last_stamp, f"{kind}: stamped rv not increasing"
                last_stamp = rv
                assert name in state, f"{kind}/{name}: MODIFIED while absent"
                state[name] = ev.obj
            else:
                assert name in state, f"{kind}/{name}: DELETED while absent"
                del state[name]
        assert set(state) == alive, (kind, set(state), alive)


def test_fingerprint_tokens_unique_under_threaded_churn():
    """No (count, rv) token may ever repeat for different content — the
    single-lock PR 3 proof, re-pinned against the sharded write paths by
    sampling tokens while six writer threads churn."""
    api = APIServer()
    stop = threading.Event()
    seen = {}

    def sample():
        while not stop.is_set():
            for kind in KINDS:
                fp = api.kind_fingerprint(kind)
                content = seen.setdefault(kind, {})
                content.setdefault(fp, 0)

    sampler = threading.Thread(target=sample)
    sampler.start()
    logs = {kind: [] for kind in KINDS}
    threads = [
        threading.Thread(target=_churn, args=(api, kind, 7 + i, 300, logs[kind]))
        for i, kind in enumerate(KINDS)
    ]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    stop.set()
    sampler.join()
    for kind in KINDS:
        # rv component strictly increases per stamp, so distinct tokens —
        # and every sampled token must be internally consistent: count
        # never negative, rv monotone within the sample set per count...
        # the cheap global invariant: tokens are unique by construction.
        tokens = list(seen.get(kind, {}))
        assert len(tokens) == len(set(tokens))
        for count, rv in tokens:
            assert count >= 0
            assert rv >= 0


def test_exact_drop_accounting_under_batched_fanout():
    """A stalled watcher's oldest-drop accounting must stay exact when a
    burst is delivered as one batch: queue bound 8, 30 creates from two
    threads -> exactly 22 dropped, newest 8 retained in order."""
    api = APIServer()
    q = api.watch(POD, maxsize=8)

    def burst(base):
        for i in range(15):
            api.create(Pod(meta=new_meta(f"p{base + i}", "default")))

    t1 = threading.Thread(target=burst, args=(0,))
    t2 = threading.Thread(target=burst, args=(100,))
    t1.start(); t2.start()
    t1.join(); t2.join()
    api.flush_watchers()
    assert q.qsize() == 8
    assert api.stats.watch_events_dropped == 22
    # Retained events are the 8 newest in delivery order: rv increasing.
    rvs = [q.get_nowait().obj.meta.resource_version for _ in range(8)]
    assert rvs == sorted(rvs)


def test_hot_kinds_get_distinct_shards():
    api = APIServer()
    hot = [POD, RESOURCE_CLAIM, RESOURCE_SLICE, NODE, COMPUTE_DOMAIN,
           DAEMON_SET, "ResourceClaimTemplate", "Event"]
    shards = {kind: api._shard(kind).idx for kind in hot}
    assert len(set(shards.values())) == len(hot), shards
    # Sticky: the same kind always resolves to the same shard.
    assert all(api._shard(k).idx == v for k, v in shards.items())


def test_single_lock_baseline_flag_serves_full_api():
    api = APIServer(shards=1)
    q = api.watch(POD)
    api.create(Pod(meta=new_meta("a", "default")))
    obj = api.get(POD, "a", "default", copy=True)
    obj.node_name = "n"
    api.update(obj)
    api.delete(POD, "a", "default")
    assert [q.get_nowait().type for _ in range(3)] == [
        "ADDED", "MODIFIED", "DELETED"]
    assert api.kind_fingerprint(POD)[0] == 0


def test_list_and_watch_no_duplicate_no_gap_under_concurrent_writes():
    """Informer bootstrap atomicity across shards: snapshot + subscription
    must tile the history — every object is either in the snapshot or
    arrives as an event, never both (ADDED after snapshot containing it)
    and never neither."""
    api = APIServer()
    stop = threading.Event()
    created = []

    def writer():
        i = 0
        while not stop.is_set():
            api.create(ResourceClaim(meta=new_meta(f"c{i}", "default")))
            created.append(f"c{i}")
            i += 1

    w = threading.Thread(target=writer)
    w.start()
    try:
        while len(created) < 50:
            pass
        objs, q = api.list_and_watch(RESOURCE_CLAIM)
    finally:
        stop.set()
        w.join()
    api.flush_watchers()
    snap = {o.meta.name for o in objs}
    events = []
    while True:
        try:
            events.append(q.get_nowait())
        except queue.Empty:
            break
    for ev in events:
        assert ev.type == "ADDED"
        assert ev.obj.meta.name not in snap, (
            f"{ev.obj.meta.name} delivered as ADDED and present in the "
            f"list_and_watch snapshot — duplicate bootstrap delivery")
    assert snap | {e.obj.meta.name for e in events} == set(created)
