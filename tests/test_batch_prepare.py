"""Batched node-prepare pipeline: one flock/checkpoint session per batch,
two fsync'd writes for N claims, concurrent CDI materialization, and
crash-consistency between the two batch writes (per-claim PrepareStarted
tombstones recovered on restart, no leaked ICI partitions).

The write-amplification guards are deliberately exact: a regression that
re-introduces per-claim checkpoint writes fails here long before a bench
run notices the latency.
"""

import pytest

from k8s_dra_driver_tpu.api.configs import API_VERSION, TPU_DRIVER_NAME
from k8s_dra_driver_tpu.k8s import APIServer
from k8s_dra_driver_tpu.pkg import featuregates as fg
from k8s_dra_driver_tpu.pkg.partitioner import StubPartitionClient
from k8s_dra_driver_tpu.plugins.checkpoint import (
    PREPARE_COMPLETED,
    PREPARE_STARTED,
)
from k8s_dra_driver_tpu.plugins.tpu import device_state as ds_mod
from k8s_dra_driver_tpu.plugins.tpu.device_state import (
    FAULT_PRE_COMPLETED,
    FAULT_STARTED_PERSISTED,
    OverlapError,
    PrepareError,
)
from k8s_dra_driver_tpu.plugins.tpu.driver import TpuDriver
from k8s_dra_driver_tpu.tpulib import MockTpuLib
from k8s_dra_driver_tpu.tpulib.profiles import SliceProfile
from k8s_dra_driver_tpu.tpulib.types import TpuGen

from tests.test_tpu_plugin import make_claim

# Dense single-host mock shape: 16 non-overlapping single-chip claims on
# one node (real v5e hosts carry 4 chips; this is a control-plane shape).
DENSE16 = SliceProfile(
    name="test-v5e-16x1", gen=TpuGen.V5E, accelerator_type="v5litepod-16",
    slice_topology="4x4", host_topology="4x4",
)


@pytest.fixture
def boot_id(tmp_path, monkeypatch):
    p = tmp_path / "boot_id"
    p.write_text("boot-batch-1\n")
    monkeypatch.setenv("ALT_TPU_BOOT_ID_PATH", str(p))
    return p


def _driver(tmp_path, profile=DENSE16, gates=""):
    driver = TpuDriver(
        api=APIServer(), node_name="node-0", tpulib=MockTpuLib(profile),
        plugin_dir=str(tmp_path / "plugin"), cdi_root=str(tmp_path / "cdi"),
        gates=fg.parse(gates),
    )
    driver.start()
    return driver


class _Boom(Exception):
    pass


# -- write amplification ------------------------------------------------------

def test_batch_prepare_16_claims_two_checkpoint_writes(tmp_path, boot_id,
                                                       monkeypatch):
    """The fast CI guard: a 16-claim batch issues <= 2 checkpoint writes
    (and exactly 2 checkpoint fsyncs — one persisting every PrepareStarted,
    one persisting every PrepareCompleted)."""
    driver = _driver(tmp_path)
    try:
        import os

        cp_fsyncs = []
        real_fsync = os.fsync

        def counting_fsync(fd):
            # os is shared by every module: attribute the fsync to its
            # target file so CDI spec writes don't pollute the count.
            try:
                target = os.readlink(f"/proc/self/fd/{fd}")
            except OSError:
                target = ""
            if "checkpoint.json" in target:
                cp_fsyncs.append(target)
            return real_fsync(fd)

        monkeypatch.setattr(os, "fsync", counting_fsync)
        mgr = driver.state._store.manager
        claims = [make_claim([f"tpu-{i}"], name=f"c{i}") for i in range(16)]
        before = mgr.save_count
        res = driver.prepare_resource_claims(claims)
        assert all(not isinstance(r, Exception) for r in res.values())
        assert len(res) == 16
        writes = mgr.save_count - before
        assert writes <= 2, f"batched prepare issued {writes} checkpoint writes"
        assert len(cp_fsyncs) == 2, \
            f"expected exactly 2 checkpoint fsyncs, got {len(cp_fsyncs)}"

        # Unprepare of the whole batch: a single write.
        before = mgr.save_count
        errs = driver.unprepare_resource_claims([c.uid for c in claims])
        assert all(e is None for e in errs.values())
        assert mgr.save_count - before == 1
    finally:
        driver.shutdown()


def test_batch_all_completed_is_read_only(tmp_path, boot_id):
    """Re-preparing an already-completed batch returns cached results with
    ZERO checkpoint writes (idempotency without write amplification)."""
    driver = _driver(tmp_path)
    try:
        claims = [make_claim([f"tpu-{i}"], name=f"c{i}") for i in range(4)]
        first = driver.prepare_resource_claims(claims)
        mgr = driver.state._store.manager
        before = mgr.save_count
        second = driver.prepare_resource_claims(claims)
        assert mgr.save_count == before
        for c in claims:
            assert first[c.uid].cdi_device_ids == second[c.uid].cdi_device_ids
    finally:
        driver.shutdown()


# -- batch semantics ----------------------------------------------------------

def test_batch_sibling_overlap_rejected(tmp_path, boot_id):
    """Two claims in one batch wanting the same chip: first wins, second
    fails with OverlapError — without poisoning disjoint siblings."""
    driver = _driver(tmp_path)
    try:
        a = make_claim(["tpu-0"], name="a")
        b = make_claim(["tpu-0"], name="b")
        c = make_claim(["tpu-1"], name="c")
        res = driver.prepare_resource_claims([a, b, c])
        assert not isinstance(res[a.uid], Exception)
        assert isinstance(res[b.uid], OverlapError)
        assert "sibling" in str(res[b.uid])
        assert not isinstance(res[c.uid], Exception)
        cp = driver.state.prepared_claims()
        assert cp[a.uid].state == PREPARE_COMPLETED
        assert b.uid not in cp
        assert cp[c.uid].state == PREPARE_COMPLETED
    finally:
        driver.shutdown()


def test_batch_partial_failure_isolated(tmp_path, boot_id):
    """A claim that fails validation (unknown device) reports its own error;
    every sibling still prepares, and the checkpoint holds no residue for
    the failed claim."""
    driver = _driver(tmp_path)
    try:
        good = [make_claim([f"tpu-{i}"], name=f"g{i}") for i in range(3)]
        bad = make_claim(["tpu-99"], name="bad")
        res = driver.prepare_resource_claims(good + [bad])
        assert isinstance(res[bad.uid], PrepareError)
        for g in good:
            assert not isinstance(res[g.uid], Exception)
            assert driver.state.cdi.claim_spec_exists(g.uid)
        assert bad.uid not in driver.state.prepared_claims()
        assert not driver.state.cdi.claim_spec_exists(bad.uid)
    finally:
        driver.shutdown()


def test_batch_metrics_observed(tmp_path, boot_id):
    """track_batch: requests_total counts claims, prepare_batch_size and
    prepare_seconds see one observation per call, and per-claim failures
    land in request_errors_total."""
    driver = _driver(tmp_path)
    try:
        m = driver.metrics
        claims = [make_claim([f"tpu-{i}"], name=f"c{i}") for i in range(4)]
        claims.append(make_claim(["tpu-99"], name="bad"))
        driver.prepare_resource_claims(claims)
        d = driver.driver_name
        assert m.requests_total.value(d, "PrepareResourceClaims") == 5
        assert m.request_errors_total.value(d, "PrepareResourceClaims") == 1
        assert m.prepare_batch_size.count(d, "PrepareResourceClaims") == 1
        assert m.prepare_seconds.count(d, "PrepareResourceClaims") == 1
        assert m.in_flight.value(d) == 0
    finally:
        driver.shutdown()


# -- crash consistency --------------------------------------------------------

GATES_DYN = "DynamicSubslice=true,ICIPartitioning=true"

# v5e-4 subslice devices on disjoint chip pairs.
SUBSLICE_A = "tpu-subslice-1x2-at-0x0"
SUBSLICE_B = "tpu-subslice-1x2-at-1x0"


def _shared_stub(monkeypatch):
    """Route every DeviceState at a single StubPartitionClient, so partition
    state survives a simulated crash/restart the way the native ledger (or
    the hardware itself) would."""
    stub = StubPartitionClient()
    monkeypatch.setattr(ds_mod, "StubPartitionClient", lambda: stub)
    return stub


def test_crash_between_batch_writes_recovers_all_claims(tmp_path, boot_id,
                                                        monkeypatch):
    """Kill the pipeline between the PrepareStarted and PrepareCompleted
    writes: every claim must be left as a PrepareStarted tombstone on disk,
    the restarted plugin must free the leaked ICI partitions, and
    re-preparing must succeed for every claim via the stale-entry path."""
    stub = _shared_stub(monkeypatch)
    d1 = _driver(tmp_path, profile="v5e-4", gates=GATES_DYN)
    claims = [make_claim([SUBSLICE_A], name="a"), make_claim([SUBSLICE_B], name="b")]

    def boom(point):
        if point == FAULT_PRE_COMPLETED:
            raise _Boom(point)
    d1.state.fault_hook = boom
    res = d1.prepare_resource_claims(claims)
    assert all(isinstance(r, _Boom) for r in res.values())
    # The dying process had activated both partitions (hardware state).
    assert len(stub.active) == 2
    # On-disk checkpoint: per-claim PrepareStarted tombstones.
    cp = d1.state._store.get()
    assert {e.state for e in cp.claims.values()} == {PREPARE_STARTED}
    assert set(cp.claims) == {c.uid for c in claims}
    d1.shutdown()

    # Restart: startup reconcile must free the partitions no completed
    # claim holds, then the stale-entry path re-prepares cleanly.
    d2 = _driver(tmp_path, profile="v5e-4", gates=GATES_DYN)
    try:
        assert stub.active == {}, "leaked ICI partitions after restart"
        res = d2.prepare_resource_claims(claims)
        assert all(not isinstance(r, Exception) for r in res.values())
        cp = d2.state.prepared_claims()
        assert {e.state for e in cp.values()} == {PREPARE_COMPLETED}
        # Exactly the two re-prepared partitions are active again.
        assert len(stub.active) == 2
    finally:
        d2.shutdown()


def test_crash_right_after_started_write_recovers(tmp_path, boot_id,
                                                  monkeypatch):
    """Crash immediately after write #1 (no device touched yet): tombstones
    on disk, nothing leaked, restart re-prepares."""
    stub = _shared_stub(monkeypatch)
    d1 = _driver(tmp_path, profile="v5e-4", gates=GATES_DYN)
    claim = make_claim([SUBSLICE_A], name="a")

    def boom(point):
        if point == FAULT_STARTED_PERSISTED:
            raise _Boom(point)
    d1.state.fault_hook = boom
    res = d1.prepare_resource_claims([claim])
    assert isinstance(res[claim.uid], _Boom)
    assert stub.active == {}  # crashed before any partition work
    assert d1.state._store.get().claims[claim.uid].state == PREPARE_STARTED
    d1.shutdown()

    d2 = _driver(tmp_path, profile="v5e-4", gates=GATES_DYN)
    try:
        res = d2.prepare_resource_claims([claim])
        assert not isinstance(res[claim.uid], Exception)
        assert d2.state.prepared_claims()[claim.uid].state == PREPARE_COMPLETED
    finally:
        d2.shutdown()


# -- compute-domain plugin ----------------------------------------------------

def _daemon_claim(api, name, domain_uid, ns="default"):
    from k8s_dra_driver_tpu.api.configs import COMPUTE_DOMAIN_DRIVER_NAME
    from k8s_dra_driver_tpu.k8s.core import (
        AllocationResult,
        DeviceClaimConfig,
        DeviceRequestAllocationResult,
        OpaqueDeviceConfig,
        ResourceClaim,
    )
    from k8s_dra_driver_tpu.k8s.objects import fresh_uid, new_meta

    claim = ResourceClaim(meta=new_meta(name, ns))
    claim.meta.uid = fresh_uid()
    claim.allocation = AllocationResult(
        devices=[DeviceRequestAllocationResult(
            request="d", driver=COMPUTE_DOMAIN_DRIVER_NAME,
            pool="n0", device="daemon",
        )],
        node_name="n0",
    )
    claim.config = [DeviceClaimConfig(
        source="claim",
        opaque=OpaqueDeviceConfig(
            driver=COMPUTE_DOMAIN_DRIVER_NAME,
            parameters={
                "apiVersion": API_VERSION,
                "kind": "ComputeDomainDaemonConfig",
                "domain_id": domain_uid,
            },
        ),
    )]
    return claim


def test_cd_batch_prepare_two_checkpoint_writes(tmp_path, boot_id):
    """The compute-domain plugin runs the same batched pipeline: N daemon
    claims in one call -> 2 checkpoint writes, batched unprepare -> 1."""
    from k8s_dra_driver_tpu.k8s.core import Node
    from k8s_dra_driver_tpu.k8s.objects import new_meta
    from k8s_dra_driver_tpu.plugins.computedomain.driver import ComputeDomainDriver

    api = APIServer()
    api.create(Node(meta=new_meta("n0")))
    driver = ComputeDomainDriver(
        api=api, node_name="n0", tpulib=MockTpuLib("v5e-4"),
        plugin_dir=str(tmp_path / "cd-plugin"), cdi_root=str(tmp_path / "cdi"),
    )
    driver.start()
    try:
        claims = [_daemon_claim(api, f"d{i}", f"dom-{i}") for i in range(4)]
        mgr = driver._store.manager
        before = mgr.save_count
        res = driver.prepare_resource_claims(claims)
        assert all(not isinstance(r, Exception) for r in res.values()), res
        assert mgr.save_count - before == 2
        for c in claims:
            assert driver.cdi.claim_spec_exists(c.uid)

        before = mgr.save_count
        errs = driver.unprepare_resource_claims([c.uid for c in claims])
        assert all(e is None for e in errs.values())
        assert mgr.save_count - before == 1
        for c in claims:
            assert not driver.cdi.claim_spec_exists(c.uid)
    finally:
        driver.shutdown()
