"""Event plane unit tier: recorder aggregation, burst limiting, backlog
bounds, condition monotonicity, and the reason-catalog CI gate."""

import re
import subprocess
import sys

from k8s_dra_driver_tpu.k8s import APIServer
from k8s_dra_driver_tpu.k8s.conditions import (
    CONDITION_FALSE,
    CONDITION_TRUE,
    Condition,
    condition_true,
    get_condition,
    set_condition,
)
from k8s_dra_driver_tpu.k8s.core import EVENT, Pod
from k8s_dra_driver_tpu.k8s.objects import new_meta
from k8s_dra_driver_tpu.k8s.serialize import from_wire, to_wire
from k8s_dra_driver_tpu.pkg import events as events_mod
from k8s_dra_driver_tpu.pkg.events import (
    EventRecorder,
    REASON_FAILED_SCHEDULING,
    events_for,
)
from k8s_dra_driver_tpu.pkg.metrics import Registry


class FakeClock:
    def __init__(self, t=1000.0):
        self.t = t

    def __call__(self):
        return self.t

    def tick(self, dt=1.0):
        self.t += dt


def _pod(api, name="p0", ns="default"):
    return api.create(Pod(meta=new_meta(name, ns)))


def test_storm_collapses_to_one_event_with_count_and_timestamps():
    """The satellite contract: a 100x repeated FailedScheduling storm is ONE
    Event with count=100 and first/last timestamps spanning the storm."""
    api = APIServer()
    clock = FakeClock()
    reg = Registry()
    rec = EventRecorder(api, "scheduler", metrics_registry=reg, clock=clock)
    pod = _pod(api)
    msg = "0/4 nodes can place the pod: tpu-node-0: no device matches"
    for _ in range(100):
        rec.warning(pod, REASON_FAILED_SCHEDULING, msg)
        clock.tick(1.0)
    evs = events_for(api, pod)
    assert len(evs) == 1
    ev = evs[0]
    assert ev.count == 100
    assert ev.reason == REASON_FAILED_SCHEDULING
    assert ev.type == "Warning"
    assert ev.first_timestamp == 1000.0
    assert ev.last_timestamp == 1099.0
    assert rec.emitted_total.value("scheduler", REASON_FAILED_SCHEDULING) == 100
    assert rec.suppressed_total.value("scheduler", REASON_FAILED_SCHEDULING) == 0


def test_dedup_is_cross_recorder():
    """Deterministic Event names: two recorder instances (two processes in
    real life) aggregate into the same stored object."""
    api = APIServer()
    pod = _pod(api)
    r1 = EventRecorder(api, "scheduler")
    r2 = EventRecorder(api, "scheduler")
    r1.warning(pod, REASON_FAILED_SCHEDULING, "same message")
    r2.warning(pod, REASON_FAILED_SCHEDULING, "same message")
    evs = events_for(api, pod)
    assert len(evs) == 1 and evs[0].count == 2


def test_distinct_messages_are_distinct_series():
    api = APIServer()
    pod = _pod(api)
    rec = EventRecorder(api, "scheduler")
    rec.warning(pod, REASON_FAILED_SCHEDULING, "reason A")
    rec.warning(pod, REASON_FAILED_SCHEDULING, "reason B")
    assert len(events_for(api, pod)) == 2


def test_burst_limiter_suppresses_and_counts():
    """New-series creation consumes tokens; suppression is itself counted
    (the satellite's 'burst limiter suppression is itself counted')."""
    api = APIServer()
    clock = FakeClock()
    rec = EventRecorder(api, "scheduler", clock=clock, burst=3,
                        refill_per_s=0.0)
    pod = _pod(api)
    for i in range(5):
        rec.warning(pod, REASON_FAILED_SCHEDULING, f"distinct message {i}")
    assert len(events_for(api, pod)) == 3
    assert rec.suppressed_total.value("scheduler", REASON_FAILED_SCHEDULING) == 2
    # Aggregation updates stay free even with an empty bucket.
    rec.warning(pod, REASON_FAILED_SCHEDULING, "distinct message 0")
    evs = {e.message: e for e in events_for(api, pod)}
    assert evs["distinct message 0"].count == 2


def test_burst_limiter_refills():
    api = APIServer()
    clock = FakeClock()
    rec = EventRecorder(api, "scheduler", clock=clock, burst=1,
                        refill_per_s=1.0)
    pod = _pod(api)
    assert rec.warning(pod, REASON_FAILED_SCHEDULING, "m1") is not None
    assert rec.warning(pod, REASON_FAILED_SCHEDULING, "m2") is None
    clock.tick(2.0)  # refill
    assert rec.warning(pod, REASON_FAILED_SCHEDULING, "m3") is not None


def test_per_object_backlog_is_bounded_and_evicts_stalest():
    api = APIServer()
    clock = FakeClock()
    rec = EventRecorder(api, "scheduler", clock=clock, burst=100,
                        max_events_per_object=4)
    pod = _pod(api)
    for i in range(6):
        rec.warning(pod, REASON_FAILED_SCHEDULING, f"series {i}")
        clock.tick(1.0)
    evs = events_for(api, pod)
    assert len(evs) == 4
    # The two oldest series were evicted; the newest survive.
    assert {e.message for e in evs} == {f"series {i}" for i in range(2, 6)}


def test_backlog_is_per_object_not_global():
    api = APIServer()
    rec = EventRecorder(api, "scheduler", burst=100, max_events_per_object=2)
    p0, p1 = _pod(api, "p0"), _pod(api, "p1")
    for i in range(3):
        rec.warning(p0, REASON_FAILED_SCHEDULING, f"m{i}")
        rec.warning(p1, REASON_FAILED_SCHEDULING, f"m{i}")
    assert len(events_for(api, p0)) == 2
    assert len(events_for(api, p1)) == 2


def test_tracked_object_state_is_bounded(monkeypatch):
    """Per-object correlator state (token buckets, series gates) is LRU-
    evicted past the cap — narrating short-lived objects forever must not
    grow a long-lived recorder's memory."""
    monkeypatch.setattr(events_mod, "MAX_TRACKED_OBJECTS", 8)
    api = APIServer()
    clock = FakeClock()
    rec = EventRecorder(api, "scheduler", clock=clock, burst=5)
    for i in range(40):
        rec.normal(_pod(api, f"p{i}"), "Scheduled", f"assigned p{i}")
        clock.tick(1.0)
    assert len(rec._buckets) <= 8
    assert len(rec._series_seen) <= 8


def test_cluster_scoped_object_events_land_in_default_namespace():
    """Node events are filed under "default" (matching real Kubernetes) so
    `get events` shows DeviceDegraded rows without -A."""
    from k8s_dra_driver_tpu.k8s.core import Node

    api = APIServer()
    node = api.create(Node(meta=new_meta("n0")))
    rec = EventRecorder(api, "tpu-kubelet-plugin")
    rec.warning(node, "DeviceDegraded", "ICI link 0-1 is unhealthy")
    stored = api.list(EVENT, namespace="default")
    assert len(stored) == 1
    assert stored[0].involved_object.kind == "Node"
    assert events_for(api, node)[0].reason == "DeviceDegraded"


def test_event_round_trips_through_wire_codec():
    api = APIServer()
    pod = _pod(api)
    rec = EventRecorder(api, "scheduler")
    rec.normal(pod, "Scheduled", "assigned default/p0 to tpu-node-0")
    ev = api.list(EVENT, namespace="default")[0]
    back = from_wire(to_wire(ev))
    assert back.kind == EVENT
    assert back.involved_object.uid == pod.uid
    assert back.reason == "Scheduled"
    assert back.count == 1


def test_recorder_never_raises(monkeypatch):
    """A recorder failure must not break the emitting actor."""
    api = APIServer()
    pod = _pod(api)
    rec = EventRecorder(api, "scheduler")
    monkeypatch.setattr(rec, "_record",
                        lambda *a, **k: (_ for _ in ()).throw(RuntimeError()))
    assert rec.normal(pod, "Scheduled", "boom") is None


# -- reason catalog ----------------------------------------------------------


def test_all_reason_constants_are_camelcase():
    camel = re.compile(r"^[A-Z][A-Za-z0-9]*$")
    reasons = [v for k, v in vars(events_mod).items()
               if k.startswith("REASON_")]
    assert reasons, "no reason constants found"
    for r in reasons:
        assert camel.match(r), f"reason {r!r} is not CamelCase"


def test_check_event_reasons_gate_passes():
    proc = subprocess.run(
        [sys.executable, "hack/check_event_reasons.py"],
        capture_output=True, text=True,
        cwd=__import__("os").path.dirname(__import__("os").path.dirname(
            __import__("os").path.abspath(__file__))),
    )
    assert proc.returncode == 0, proc.stdout + proc.stderr


def test_check_event_reasons_gate_fails_on_undocumented(tmp_path):
    """The checker actually bites: an emitted reason absent from events.md
    (or not CamelCase) fails the run. Now served by tpulint's
    `event-reasons` rule; hack/check_event_reasons.py stays as the shim
    this test drives against a seeded repo."""
    import os

    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    work = tmp_path / "repo"
    pkg = work / "k8s_dra_driver_tpu"
    pkg.mkdir(parents=True)
    (pkg / "thing.py").write_text(
        'REASON_BAD = "not_camel_case"\n'
        'rec.warning(x, reason="Undocumented", message="m")\n')
    docs = work / "docs" / "reference"
    docs.mkdir(parents=True)
    (docs / "events.md").write_text("# Events\n\nonly `SomethingElse` here\n")
    proc = subprocess.run(
        [sys.executable, os.path.join(repo, "hack", "check_event_reasons.py"),
         "--repo-root", str(work), "--baseline", "none"],
        capture_output=True, text=True, cwd=repo,
    )
    assert proc.returncode == 1, proc.stderr
    assert "not CamelCase" in proc.stdout
    assert "Undocumented" in proc.stdout
    assert "[event-reasons]" in proc.stdout


# -- conditions --------------------------------------------------------------


def test_set_condition_monotonic_transition_time():
    conds = []
    assert set_condition(conds, "Ready", CONDITION_FALSE, "Waiting", "0/4",
                         now=10.0)
    c = get_condition(conds, "Ready")
    assert c.last_transition_time == 10.0
    # Same status, new message: refreshed, but the transition time holds.
    assert set_condition(conds, "Ready", CONDITION_FALSE, "Waiting", "2/4",
                         now=20.0)
    assert c.last_transition_time == 10.0 and c.message == "2/4"
    # No-op write returns False (the change gates rely on it).
    assert not set_condition(conds, "Ready", CONDITION_FALSE, "Waiting", "2/4",
                             now=30.0)
    # Status flip: the transition time finally moves.
    assert set_condition(conds, "Ready", CONDITION_TRUE, "AllReady", "4/4",
                         now=40.0)
    assert c.last_transition_time == 40.0
    assert condition_true(conds, "Ready")


def test_condition_round_trips_through_wire_codec():
    from k8s_dra_driver_tpu.api.computedomain import (
        ComputeDomain,
        ComputeDomainStatus,
    )

    cd = ComputeDomain(meta=new_meta("d", "ns"))
    cd.status = ComputeDomainStatus(
        status="Ready",
        conditions=[Condition(type="Ready", status=CONDITION_TRUE,
                              reason="AllNodesReady", message="4/4",
                              last_transition_time=5.0)],
    )
    back = from_wire(to_wire(cd))
    assert back.status.conditions[0].type == "Ready"
    assert back.status.conditions[0].last_transition_time == 5.0
