"""Fleet-wide lens: the global query plane + cross-cluster stitching.

Three tiers:

1. Pure transforms (federation/query.py): cluster-label injection,
   multi-scrape merge, federation-status rows — no HTTP.
2. Wire plumbing against a served FederatedFleet: the staleness header
   pair, the -o json staleness envelope, /metrics and
   /federation/metrics routes, decisions_by_trace over the wire.
3. kubectl fan-out degradation (the ISSUE's satellite): a partitioned
   peer or a pre-flight-recorder peer yields a loud SKIPPED row, never
   a whole-command failure."""

import json
import os

import pytest

from k8s_dra_driver_tpu.federation.query import (
    federation_status_rows,
    inject_cluster_label,
    merge_metrics_texts,
)
from k8s_dra_driver_tpu.k8s.core import ResourceClaim
from k8s_dra_driver_tpu.k8s.httpapi import RemoteAPIServer
from k8s_dra_driver_tpu.k8s.objects import new_meta
from k8s_dra_driver_tpu.pkg import tracing
from k8s_dra_driver_tpu.sim import kubectl
from k8s_dra_driver_tpu.sim.federation import FederatedFleet


# -- tier 1: pure transforms -------------------------------------------------


def test_inject_cluster_label_bare_and_braced():
    text = ("# HELP x help\n"
            "# TYPE x gauge\n"
            "x 1.0\n"
            'y{chip="0"} 2.0\n')
    out = inject_cluster_label(text, "west")
    assert '# HELP x help' in out
    assert 'x{cluster="west"} 1.0' in out
    assert 'y{cluster="west",chip="0"} 2.0' in out


def test_inject_cluster_label_existing_label_wins():
    out = inject_cluster_label('x{cluster="east"} 1\n', "west")
    assert 'cluster="east"' in out
    assert 'cluster="west"' not in out


def test_inject_cluster_label_malformed_passes_through():
    out = inject_cluster_label("}{garbage\n", "west")
    assert "}{garbage" in out


def test_merge_metrics_texts_dedups_headers_sorts_clusters():
    merged = merge_metrics_texts({
        "b": "# HELP x h\nx 2\n",
        "a": "# HELP x h\nx 1\n",
    })
    lines = merged.splitlines()
    assert lines.count("# HELP x h") == 1
    assert lines.index('x{cluster="a"} 1') < lines.index('x{cluster="b"} 2')


def test_federation_status_rows_roles_and_heartbeat():
    rows = federation_status_rows({
        "leader": None,
        "replica": {"watermark": 42, "lag_records": 3, "reconnects": 1,
                    "promoted": False, "last_heartbeat": 90.0},
        "promoted": {"watermark": 7, "lag_records": 0, "reconnects": 0,
                     "promoted": True, "last_heartbeat": 0.0},
    }, now=100.0)
    by_peer = {r[0]: r for r in rows}
    assert by_peer["leader"][1:] == ["leader", "-", "-", "-", "-"]
    assert by_peer["replica"][1] == "replica"
    assert by_peer["replica"][2] == "42"
    assert by_peer["replica"][5] == "10.0s ago"
    assert by_peer["promoted"][1] == "promoted"
    assert by_peer["promoted"][5] == "never"


# -- tier 2/3: a served fleet ------------------------------------------------


@pytest.fixture(scope="module")
def fleet(tmp_path_factory):
    fl = FederatedFleet(str(tmp_path_factory.mktemp("lens")),
                        follower_region=True)
    try:
        # A claim on the leader so explain/top have something to read,
        # plus one trace-stamped decision for the stitching read.
        fl.leader.api.create(ResourceClaim(meta=new_meta("probe", "default")))
        with tracing.span("lens.test"):
            ctx = tracing.current()
            fl.leader.history.decide(
                controller="test", rule="RULE_SCHED_BIND", outcome="ok",
                kind="ResourceClaim", namespace="default", name="probe")
        for _ in range(3):
            fl.step()
        assert fl.wait_converged(timeout_s=10.0)
        urls = fl.serve_http()
        yield fl, urls, ctx.trace_id
    finally:
        fl.stop()


@pytest.fixture
def clusters_env(fleet, monkeypatch):
    _, urls, _ = fleet
    monkeypatch.setenv("TPU_KUBECTL_CLUSTERS", ",".join(
        f"{name}={url}" for name, url in sorted(urls.items())))
    return urls


def test_replica_answers_carry_staleness_headers(fleet):
    _, urls, _ = fleet
    replica = RemoteAPIServer(urls["leader-replica"])
    replica.list("ResourceClaim", namespace="default")
    assert replica.last_staleness is not None
    assert set(replica.last_staleness) == {"watermark", "lag_records"}
    assert replica.last_staleness["watermark"] > 0
    leader = RemoteAPIServer(urls["leader"])
    leader.list("ResourceClaim", namespace="default")
    assert leader.last_staleness is None


def test_kubectl_json_envelope_only_on_stale_answers(fleet, clusters_env,
                                                     capsys):
    kubectl.main(["--cluster", "leader-replica", "get", "resourceclaims",
                  "-o", "json"])
    doc = json.loads(capsys.readouterr().out)
    assert isinstance(doc, dict)
    assert {o["meta"]["name"] for o in doc["items"]} >= {"probe"}
    assert doc["staleness"]["watermark"] > 0
    kubectl.main(["--cluster", "leader", "get", "resourceclaims",
                  "-o", "json"])
    doc = json.loads(capsys.readouterr().out)
    assert isinstance(doc, list)  # wire-compat: leaders stay a bare array


def test_metrics_routes_per_cluster_and_federated(fleet):
    _, urls, _ = fleet
    leader = RemoteAPIServer(urls["leader"])
    text = leader.metrics_text()
    assert text and "# HELP" in text
    fed = leader.federation_metrics_text()
    assert 'cluster="leader"' in fed
    assert 'cluster="follower"' in fed
    # Any peer answers the fleet-merged scrape, not just the leader.
    follower = RemoteAPIServer(urls["follower"])
    assert 'cluster="leader"' in follower.federation_metrics_text()


def test_decisions_by_trace_over_the_wire(fleet):
    _, urls, trace_id = fleet
    hist = RemoteAPIServer(urls["leader"]).history
    assert hist is not None
    recs = hist.decisions_by_trace([trace_id])
    assert recs and all(r.trace_id == trace_id for r in recs)
    assert recs[0].name == "probe"
    assert hist.decisions_by_trace([]) == []
    assert hist.decisions_by_trace(["no-such-trace"]) == []


def test_federation_status_cli(fleet, clusters_env, capsys):
    assert kubectl.main(["federation", "status"]) == 0
    out = capsys.readouterr().out
    assert "PEER" in out and "WATERMARK" in out
    lines = {ln.split()[0]: ln for ln in out.splitlines()[1:] if ln.strip()}
    assert "leader-replica" in lines and "replica" in lines["leader-replica"]
    assert "leader" in lines and "follower" in lines


def test_top_all_clusters(fleet, clusters_env, capsys):
    assert kubectl.main(["top", "claims", "--all-clusters"]) == 0
    out = capsys.readouterr().out
    assert "CLUSTER" in out and "DUTY-P95" in out
    assert kubectl.main(["top", "nodes", "--all-clusters"]) == 0
    out = capsys.readouterr().out
    assert "CLUSTER" in out


def test_explain_all_clusters_merges_and_degrades(fleet, clusters_env,
                                                  monkeypatch, capsys):
    """The fan-out degradation satellite: an unreachable peer and a
    history-less peer (the read replica serves no /history routes — a
    pre-flight-recorder surface) each produce a loud SKIPPED row while
    the reachable clusters still merge."""
    _, urls, _ = fleet
    monkeypatch.setenv("TPU_KUBECTL_CLUSTERS", ",".join(
        [f"{n}={u}" for n, u in sorted(urls.items())]
        + ["ghost=http://127.0.0.1:1"]))
    assert kubectl.main(["explain", "resourceclaim", "probe",
                         "--all-clusters"]) == 0
    out = capsys.readouterr().out
    assert "Clusters:" in out and "skipped" in out
    assert "SKIPPED" in out
    assert "unreachable" in out             # the dead port
    assert "pre-flight-recorder" in out     # the history-less replica
    assert "RULE_SCHED_BIND" in out         # leader rows still merged


def test_explain_all_clusters_latency_not_profiled(fleet, clusters_env,
                                                   capsys):
    assert kubectl.main(["explain", "resourceclaim", "probe",
                         "--all-clusters", "--latency"]) == 0
    out = capsys.readouterr().out
    assert "Latency:" in out


def test_cluster_map_parses_env(monkeypatch):
    monkeypatch.setenv("TPU_KUBECTL_CLUSTERS",
                       "a=http://x:1, b=http://y:2")
    assert kubectl._cluster_map() == {"a": "http://x:1", "b": "http://y:2"}
    monkeypatch.delenv("TPU_KUBECTL_CLUSTERS")
    assert kubectl._cluster_map() == {}


def test_federation_status_requires_clusters_env(monkeypatch):
    monkeypatch.delenv("TPU_KUBECTL_CLUSTERS", raising=False)
    monkeypatch.delenv("TPU_KUBECTL_SERVER", raising=False)
    with pytest.raises(SystemExit):
        kubectl.main(["federation", "status"])
