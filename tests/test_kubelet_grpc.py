"""The kubelet gRPC seam, driven by a fake kubelet over unix sockets.

Plays the real kubelet's role end to end: scan the registrar dir for a
registration socket, GetInfo, NotifyRegistrationStatus, then dial the
advertised DRA endpoint and run NodePrepareResources /
NodeUnprepareResources — for both the v1 and v1beta1 service names, like
the upstream pluginwatcher + DRA manager (reference seam:
/root/reference/vendor/k8s.io/dynamic-resource-allocation/kubeletplugin/
draplugin.go, used at cmd/gpu-kubelet-plugin/driver.go:131-149).
"""

import os
import subprocess
import sys
import time

import grpc
import pytest

from k8s_dra_driver_tpu.api.configs import TPU_DRIVER_NAME
from k8s_dra_driver_tpu.k8s import APIServer
from k8s_dra_driver_tpu.k8s.core import (
    AllocationResult,
    DeviceRequestAllocationResult,
    ResourceClaim,
)
from k8s_dra_driver_tpu.k8s.objects import fresh_uid, new_meta
from k8s_dra_driver_tpu.kubelet import dra_v1_pb2, dra_v1beta1_pb2
from k8s_dra_driver_tpu.kubelet import pluginregistration_pb2 as reg_pb2
from k8s_dra_driver_tpu.kubelet.draserver import (
    DRA_SOCKET_NAME,
    DRAGrpcServer,
    SUPPORTED_VERSIONS,
)
from k8s_dra_driver_tpu.plugins.tpu.driver import TpuDriver
from k8s_dra_driver_tpu.tpulib import MockTpuLib

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
NODE = "grpc-node-0"

_PB_BY_VERSION = {"v1": dra_v1_pb2, "v1beta1": dra_v1beta1_pb2}


class FakeKubelet:
    """Minimal stand-in for the kubelet's pluginwatcher + DRA manager."""

    def __init__(self, registrar_dir: str):
        self.registrar_dir = registrar_dir

    def discover_sockets(self):
        if not os.path.isdir(self.registrar_dir):
            return []
        return sorted(
            os.path.join(self.registrar_dir, f)
            for f in os.listdir(self.registrar_dir)
            if f.endswith("-reg.sock")
        )

    def _call(self, socket_path, method, request, response_cls):
        with grpc.insecure_channel(f"unix://{socket_path}") as ch:
            rpc = ch.unary_unary(
                method,
                request_serializer=type(request).SerializeToString,
                response_deserializer=response_cls.FromString,
            )
            return rpc(request, timeout=10)

    def get_info(self, reg_socket):
        return self._call(
            reg_socket, "/pluginregistration.Registration/GetInfo",
            reg_pb2.InfoRequest(), reg_pb2.PluginInfo,
        )

    def notify_registered(self, reg_socket, ok=True, error=""):
        return self._call(
            reg_socket,
            "/pluginregistration.Registration/NotifyRegistrationStatus",
            reg_pb2.RegistrationStatus(plugin_registered=ok, error=error),
            reg_pb2.RegistrationStatusResponse,
        )

    def node_prepare(self, dra_socket, claims, version="v1"):
        pb = _PB_BY_VERSION[version]
        req = pb.NodePrepareResourcesRequest(claims=[
            pb.Claim(namespace=c.namespace, uid=c.uid, name=c.name)
            for c in claims
        ])
        service = f"k8s.io.kubelet.pkg.apis.dra.{version}.DRAPlugin"
        return self._call(
            dra_socket, f"/{service}/NodePrepareResources",
            req, pb.NodePrepareResourcesResponse,
        )

    def node_unprepare(self, dra_socket, claims, version="v1"):
        pb = _PB_BY_VERSION[version]
        req = pb.NodeUnprepareResourcesRequest(claims=[
            pb.Claim(namespace=c.namespace, uid=c.uid, name=c.name)
            for c in claims
        ])
        service = f"k8s.io.kubelet.pkg.apis.dra.{version}.DRAPlugin"
        return self._call(
            dra_socket, f"/{service}/NodeUnprepareResources",
            req, pb.NodeUnprepareResourcesResponse,
        )


@pytest.fixture
def boot_id(tmp_path, monkeypatch):
    p = tmp_path / "boot_id"
    p.write_text("boot-grpc-1\n")
    monkeypatch.setenv("ALT_TPU_BOOT_ID_PATH", str(p))
    return p


@pytest.fixture
def env(tmp_path, boot_id):
    api = APIServer()
    driver = TpuDriver(
        api=api, node_name=NODE, tpulib=MockTpuLib("v5e-4"),
        plugin_dir=str(tmp_path / "plugin"), cdi_root=str(tmp_path / "cdi"),
    )
    driver.start()
    server = DRAGrpcServer(
        driver, api,
        plugin_data_dir=str(tmp_path / "kubelet-plugin"),
        registrar_dir=str(tmp_path / "registry"),
    ).start()
    kubelet = FakeKubelet(str(tmp_path / "registry"))
    yield api, driver, server, kubelet, tmp_path
    server.stop()
    driver.shutdown()


def make_claim(devices, name="claim-grpc", ns="default"):
    claim = ResourceClaim(meta=new_meta(name, ns))
    claim.meta.uid = fresh_uid()
    claim.allocation = AllocationResult(
        devices=[
            DeviceRequestAllocationResult(
                request="tpus", driver=TPU_DRIVER_NAME, pool=NODE, device=d)
            for d in devices
        ],
        node_name=NODE,
    )
    return claim


def test_registration_handshake(env):
    api, driver, server, kubelet, _ = env
    socks = kubelet.discover_sockets()
    assert socks == [server.registration_socket_path]
    info = kubelet.get_info(socks[0])
    assert info.type == "DRAPlugin"
    assert info.name == TPU_DRIVER_NAME
    assert info.endpoint == server.dra_socket_path
    assert info.endpoint.endswith(DRA_SOCKET_NAME)
    assert list(info.supported_versions) == SUPPORTED_VERSIONS
    assert not server.registered
    kubelet.notify_registered(socks[0], ok=True)
    assert server.registered
    kubelet.notify_registered(socks[0], ok=False, error="kubelet restarting")
    assert not server.registered


@pytest.mark.parametrize("version", ["v1", "v1beta1"])
def test_prepare_unprepare_over_grpc(env, version):
    api, driver, server, kubelet, tmp_path = env
    claim = api.create(make_claim(["tpu-0", "tpu-1"]))
    resp = kubelet.node_prepare(server.dra_socket_path, [claim], version)
    result = resp.claims[claim.uid]
    assert result.error == ""
    assert len(result.devices) == 2
    by_dev = {d.device_name: d for d in result.devices}
    assert set(by_dev) == {"tpu-0", "tpu-1"}
    for d in result.devices:
        assert d.pool_name == NODE
        assert d.request_names == ["tpus"]
        assert d.cdi_device_ids, d
    # The prepare wrote a claim-scoped CDI spec to disk.
    assert any(claim.uid in f for f in os.listdir(tmp_path / "cdi"))

    resp = kubelet.node_unprepare(server.dra_socket_path, [claim], version)
    assert resp.claims[claim.uid].error == ""
    assert not any(claim.uid in f for f in os.listdir(tmp_path / "cdi"))


def test_prepare_is_idempotent_across_versions(env):
    """The same claim prepared via v1beta1 then v1 returns identical CDI ids
    (one checkpoint behind both service names)."""
    api, driver, server, kubelet, _ = env
    claim = api.create(make_claim(["tpu-2"]))
    first = kubelet.node_prepare(server.dra_socket_path, [claim], "v1beta1")
    second = kubelet.node_prepare(server.dra_socket_path, [claim], "v1")
    ids = lambda r: [  # noqa: E731
        list(d.cdi_device_ids) for d in r.claims[claim.uid].devices
    ]
    assert ids(first) == ids(second)
    kubelet.node_unprepare(server.dra_socket_path, [claim], "v1")


def test_unknown_claim_reports_per_claim_error(env):
    api, driver, server, kubelet, _ = env
    ghost = make_claim(["tpu-0"], name="never-created")  # not in the API server
    resp = kubelet.node_prepare(server.dra_socket_path, [ghost])
    assert "resolve claim" in resp.claims[ghost.uid].error
    # A transport-level success with a per-claim error, per the DRA contract.


def test_uid_mismatch_is_refused(env):
    api, driver, server, kubelet, _ = env
    claim = api.create(make_claim(["tpu-0"], name="uid-mismatch"))
    stale = make_claim(["tpu-0"], name="uid-mismatch")  # same name, new uid
    resp = kubelet.node_prepare(server.dra_socket_path, [stale])
    assert "uid mismatch" in resp.claims[stale.uid].error


def test_overlap_error_surfaces_over_wire(env):
    api, driver, server, kubelet, _ = env
    a = api.create(make_claim(["tpu-3"], name="holder"))
    b = api.create(make_claim(["tpu-3"], name="thief"))
    assert kubelet.node_prepare(server.dra_socket_path, [a]).claims[a.uid].error == ""
    resp = kubelet.node_prepare(server.dra_socket_path, [b])
    err = resp.claims[b.uid].error
    assert "permanent" in err and "overlap" in err
    kubelet.node_unprepare(server.dra_socket_path, [a])


def test_binary_serves_grpc_sockets(tmp_path):
    """The tpu-kubelet-plugin binary, started with the flag pair, brings up
    both sockets and answers GetInfo — the wiring the round-2 verdict found
    missing."""
    boot = tmp_path / "boot_id"
    boot.write_text("boot-bin-1\n")
    plugin_dir = tmp_path / "kubelet-plugin"
    registry = tmp_path / "registry"
    env = {
        **os.environ,
        "ALT_TPU_TOPOLOGY": "v5e-4",
        "ALT_TPU_BOOT_ID_PATH": str(boot),
        "PYTHONPATH": REPO,
    }
    proc = subprocess.Popen(
        [sys.executable, "-m", "k8s_dra_driver_tpu.cmd.tpu_kubelet_plugin",
         "--api-backend", "sim",
         "--node-name", NODE,
         "--plugin-dir", str(tmp_path / "plugin"),
         "--cdi-root", str(tmp_path / "cdi"),
         "--kubelet-plugin-dir", str(plugin_dir),
         "--registrar-dir", str(registry)],
        env=env, cwd=REPO,
        stdout=subprocess.PIPE, stderr=subprocess.STDOUT,
    )
    try:
        reg_sock = registry / f"{TPU_DRIVER_NAME}-reg.sock"
        deadline = time.monotonic() + 30
        while time.monotonic() < deadline:
            if reg_sock.exists() or proc.poll() is not None:
                break
            time.sleep(0.1)
        if proc.poll() is not None:
            raise AssertionError(
                "binary died:\n" + proc.stdout.read().decode())
        assert reg_sock.exists()
        kubelet = FakeKubelet(str(registry))
        info = kubelet.get_info(str(reg_sock))
        assert info.name == TPU_DRIVER_NAME
        assert info.endpoint == str(plugin_dir / DRA_SOCKET_NAME)
        kubelet.notify_registered(str(reg_sock))
    finally:
        proc.terminate()
        try:
            proc.wait(timeout=10)
        except subprocess.TimeoutExpired:
            proc.kill()


def test_flag_pair_must_be_set_together(tmp_path):
    from k8s_dra_driver_tpu.cmd import tpu_kubelet_plugin as bin_mod

    with pytest.raises(SystemExit):
        bin_mod.main([
            "--api-backend", "sim",
            "--plugin-dir", str(tmp_path / "p"),
            "--kubelet-plugin-dir", str(tmp_path / "kp"),  # no --registrar-dir
        ])
