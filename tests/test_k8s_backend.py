"""Real-Kubernetes backend conformance: wire codec + REST adapter.

Exercises `KubernetesAPIServer` (the client-go analog every binary uses
with --api-backend kubernetes) against `K8sAPIServer` (the conformance
apiserver speaking the real k8s REST wire), so both sides of the codec and
the REST/watch plumbing that will face a live cluster run in CI — the
mock-NVML-kind-cluster pattern applied to the API seam
(/root/reference/.github/workflows/mock-nvml-e2e.yaml).
"""

import pytest

from k8s_dra_driver_tpu.api.computedomain import (
    ComputeDomain,
    ComputeDomainChannelSpec,
    ComputeDomainClique,
    ComputeDomainDaemonInfo,
    ComputeDomainNode,
    ComputeDomainSpec,
    ComputeDomainStatus,
)
from k8s_dra_driver_tpu.k8s import APIServer, Informer
from k8s_dra_driver_tpu.k8s.core import (
    POD,
    AllocationResult,
    Container,
    Counter,
    CounterSet,
    DaemonSet,
    Device,
    DeviceClaimConfig,
    DeviceClass,
    DeviceCounterConsumption,
    DeviceRequest,
    DeviceRequestAllocationResult,
    DeviceTaint,
    Node,
    NodeTaint,
    OpaqueDeviceConfig,
    Pod,
    PodResourceClaimRef,
    PodTemplate,
    ResourceClaim,
    ResourceClaimConsumer,
    ResourceClaimTemplate,
    ResourcePool,
    ResourceSlice,
)
from k8s_dra_driver_tpu.k8s.k8sapiserver import K8sAPIServer
from k8s_dra_driver_tpu.k8s.k8swire import api_path, from_k8s_wire, to_k8s_wire
from k8s_dra_driver_tpu.k8s.kubeclient import KubeAuth, KubernetesAPIServer
from k8s_dra_driver_tpu.k8s.objects import (
    ConflictError,
    NotFoundError,
    new_meta,
)
from k8s_dra_driver_tpu.pkg.leaderelection import Lease

from tests.test_computedomain import wait_for


# -- codec round-trips -------------------------------------------------------


def _roundtrip(obj):
    wire = to_k8s_wire(obj)
    back = to_k8s_wire(from_k8s_wire(wire))
    assert wire == back, f"unstable k8s wire for {obj.kind}"
    return from_k8s_wire(wire)


def test_wire_pod_roundtrip():
    pod = Pod(
        meta=new_meta("p", "ns", labels={"app": "x"}),
        node_name="node-1",
        containers=[Container(
            name="main", image="img", command=["run"],
            env={"A": "1"}, downward_env={"POD_IP": "status.podIP"},
            readiness_probe=["check"],
        )],
        resource_claims=[PodResourceClaimRef(
            name="tpus", resource_claim_template_name="tmpl")],
        phase="Running", pod_ip="10.0.0.1", ready=True,
    )
    back = _roundtrip(pod)
    assert back.node_name == "node-1"
    assert back.containers[0].downward_env == {"POD_IP": "status.podIP"}
    assert back.ready and back.phase == "Running"
    wire = to_k8s_wire(pod)
    assert wire["apiVersion"] == "v1"
    assert wire["spec"]["containers"][0]["env"][1]["valueFrom"][
        "fieldRef"]["fieldPath"] == "status.podIP"


def test_wire_resourceslice_roundtrip():
    rs = ResourceSlice(
        meta=new_meta("node-0-tpu"),
        driver="tpu.google.com",
        node_name="node-0",
        pool=ResourcePool(name="node-0", generation=3),
        devices=[Device(
            name="tpu-0",
            attributes={"tpu.google.com/coords": "0,0,0", "index": 0,
                        "healthy": True},
            capacity={"hbm": "16Gi"},
            taints=[DeviceTaint(key="k", value="v", effect="NoExecute")],
            consumes_counters=[DeviceCounterConsumption(
                counter_set="chips", counters={"chip": Counter(1)})],
        )],
        shared_counters=[CounterSet(name="chips",
                                    counters={"chip": Counter(4)})],
    )
    back = _roundtrip(rs)
    assert back.devices[0].attributes == {
        "tpu.google.com/coords": "0,0,0", "index": 0, "healthy": True}
    assert back.shared_counters[0].counters["chip"].value == 4
    wire = to_k8s_wire(rs)
    # v1 (preferred) flattens the device payload; v1beta1 wraps in "basic".
    assert wire["apiVersion"] == "resource.k8s.io/v1"
    assert "basic" not in wire["spec"]["devices"][0]
    assert "attributes" in wire["spec"]["devices"][0]
    wire_beta = to_k8s_wire(rs, "v1beta1")
    assert wire_beta["apiVersion"] == "resource.k8s.io/v1beta1"
    assert "basic" in wire_beta["spec"]["devices"][0]
    from k8s_dra_driver_tpu.k8s.k8swire import from_k8s_wire
    assert from_k8s_wire(wire_beta).devices[0].attributes == \
        back.devices[0].attributes


def test_wire_claim_roundtrip():
    rc = ResourceClaim(
        meta=new_meta("c", "ns"),
        requests=[DeviceRequest(name="tpus", device_class_name="tpu.google.com",
                                allocation_mode="ExactCount", count=4)],
        config=[DeviceClaimConfig(
            requests=["tpus"],
            opaque=OpaqueDeviceConfig(driver="tpu.google.com",
                                      parameters={"kind": "TpuConfig"}))],
        allocation=AllocationResult(
            devices=[DeviceRequestAllocationResult(
                request="tpus", driver="tpu.google.com", pool="node-0",
                device="tpu-0")],
            node_name="node-0"),
        reserved_for=[ResourceClaimConsumer(name="pod-1", uid="u1")],
    )
    back = _roundtrip(rc)
    assert back.allocation.node_name == "node-0"
    assert back.config[0].opaque.parameters == {"kind": "TpuConfig"}
    assert back.reserved_for[0].uid == "u1"


def test_wire_claim_conditions_roundtrip():
    """Typed claim conditions survive the real k8s wire (the drift class
    tpulint's wire-drift rule found: the codec silently dropped them)."""
    from k8s_dra_driver_tpu.k8s.conditions import Condition

    rc = ResourceClaim(
        meta=new_meta("c", "ns"),
        conditions=[
            Condition(type="Allocated", status="True", reason="Scheduled",
                      message="on node-0", last_transition_time=1700000000.0),
            Condition(type="Prepared", status="False"),
        ],
    )
    wire = to_k8s_wire(rc)
    docs = wire["status"]["conditions"]
    assert docs[0] == {"type": "Allocated", "status": "True",
                       "reason": "Scheduled", "message": "on node-0",
                       "lastTransitionTime": "2023-11-14T22:13:20Z"}
    assert docs[1] == {"type": "Prepared", "status": "False"}
    back = _roundtrip(rc)
    assert back.conditions[0].type == "Allocated"
    assert back.conditions[0].last_transition_time == 1700000000.0
    assert back.conditions[1].status == "False"
    assert back.conditions[1].last_transition_time == 0.0


def test_wire_cel_selectors_roundtrip_and_legacy_refused():
    """cel_selectors survive the wire; legacy attr=value selectors have
    NO wire form and must fail encoding loudly — silently dropping them
    would let a round-tripped claim over-match (the constraint just
    vanishes)."""
    rc = ResourceClaim(
        meta=new_meta("c2", "ns"),
        requests=[DeviceRequest(
            name="tpus", device_class_name="tpu.google.com", count=1,
            cel_selectors=['device.attributes["tpu.google.com"].index == 2'])],
    )
    back = _roundtrip(rc)
    assert back.requests[0].cel_selectors == [
        'device.attributes["tpu.google.com"].index == 2']
    assert back.requests[0].selectors == []

    legacy = ResourceClaim(
        meta=new_meta("c3", "ns"),
        requests=[DeviceRequest(name="tpus",
                                device_class_name="tpu.google.com",
                                count=1, selectors=["kind=tpu-chip"])],
    )
    with pytest.raises(ValueError, match="legacy attr=value"):
        _roundtrip(legacy)


def test_wire_deviceclass_cel_roundtrip():
    """Legacy match_attributes encode into one CEL expression; decode keeps
    the raw expression (celmini evaluates it), so the roundtrip is
    *semantic*: the decoded class selects exactly what the original did."""
    from types import SimpleNamespace

    from k8s_dra_driver_tpu.k8s import celmini

    dc = DeviceClass(
        meta=new_meta("tpu.google.com"),
        driver="tpu.google.com",
        match_attributes={"tpu.google.com/type": "chip", "count": 4,
                          "healthy": True},
    )
    wire = to_k8s_wire(dc)
    expr = wire["spec"]["selectors"][0]["cel"]["expression"]
    assert 'device.driver == "tpu.google.com"' in expr
    back = from_k8s_wire(wire)
    assert back.driver == "tpu.google.com"
    assert back.cel_selectors == [expr]
    good = SimpleNamespace(
        driver="tpu.google.com",
        attributes={"tpu.google.com/type": "chip", "count": 4, "healthy": True},
        capacity={})
    bad = SimpleNamespace(
        driver="tpu.google.com",
        attributes={"tpu.google.com/type": "chip", "count": 2, "healthy": True},
        capacity={})
    assert celmini.matches(back.cel_selectors, good)
    assert not celmini.matches(back.cel_selectors, bad)


def test_wire_deviceclass_raw_expression_roundtrips_verbatim():
    dc = DeviceClass(
        meta=new_meta("vfio.tpu.google.com"),
        driver="tpu.google.com",
        cel_selectors=['device.driver == "tpu.google.com" && '
                       'device.attributes["type"] == "vfio"'],
    )
    wire = to_k8s_wire(dc)
    back = from_k8s_wire(wire)
    assert back.cel_selectors == dc.cel_selectors
    assert back.driver == "tpu.google.com"


def test_wire_deviceclass_driver_survives_without_driver_clause():
    """A class whose expressions never mention device.driver must still
    round-trip its driver (the allocator's slice lookup needs it)."""
    dc = DeviceClass(
        meta=new_meta("attr-only"),
        driver="tpu.google.com",
        cel_selectors=['device.attributes["type"] == "vfio"'],
    )
    back = from_k8s_wire(to_k8s_wire(dc))
    assert back.driver == "tpu.google.com"
    assert 'device.attributes["type"] == "vfio"' in back.cel_selectors


def test_wire_deviceclass_single_quoted_driver():
    back = from_k8s_wire({
        "apiVersion": "resource.k8s.io/v1", "kind": "DeviceClass",
        "metadata": {"name": "sq"},
        "spec": {"selectors": [
            {"cel": {"expression": "device.driver == 'tpu.google.com'"}},
        ]},
    })
    assert back.driver == "tpu.google.com"


def test_wire_computedomain_roundtrip():
    cd = ComputeDomain(
        meta=new_meta("dom", "ns"),
        spec=ComputeDomainSpec(
            num_nodes=4, topology="4x4",
            channel=ComputeDomainChannelSpec(
                resource_claim_template_name="chan")),
        status=ComputeDomainStatus(status="Ready", nodes=[
            ComputeDomainNode(name="n0", ip_address="10.0.0.1",
                              ici_domain="slice-0", worker_id=0,
                              status="Ready")]),
    )
    back = _roundtrip(cd)
    assert back.spec.topology == "4x4"
    assert back.status.nodes[0].worker_id == 0
    wire = to_k8s_wire(cd)
    assert wire["apiVersion"] == "resource.tpu.google.com/v1beta1"
    assert wire["status"]["nodes"][0]["iciDomain"] == "slice-0"


def test_wire_computedomain_conditions_roundtrip():
    """ComputeDomain status conditions survive the real k8s wire — on a
    real cluster the controller's Validated/Ready/Degraded history was
    silently dropped by the codec before tpulint's wire-drift rule."""
    from k8s_dra_driver_tpu.k8s.conditions import Condition

    cd = ComputeDomain(
        meta=new_meta("dom", "ns"),
        spec=ComputeDomainSpec(num_nodes=2),
        status=ComputeDomainStatus(status="Ready", conditions=[
            Condition(type="Validated", status="True", reason="SpecValid",
                      last_transition_time=1700000000.0),
            Condition(type="Degraded", status="False",
                      reason="AllDevicesHealthy", message="2/2 nodes clean"),
        ]),
    )
    wire = to_k8s_wire(cd)
    assert [c["type"] for c in wire["status"]["conditions"]] == [
        "Validated", "Degraded"]
    back = _roundtrip(cd)
    assert back.status.conditions[0].reason == "SpecValid"
    assert back.status.conditions[0].last_transition_time == 1700000000.0
    assert back.status.conditions[1].message == "2/2 nodes clean"


def test_wire_clique_daemonset_lease_roundtrip():
    cl = ComputeDomainClique(
        meta=new_meta("uid.hash", "ns"), domain_uid="uid",
        ici_domain="slice-0",
        nodes=[ComputeDomainDaemonInfo(node_name="n0", ip_address="10.0.0.1",
                                       dns_name="0.x.internal", index=0,
                                       ready=True)])
    back = _roundtrip(cl)
    assert back.nodes[0].dns_name == "0.x.internal"

    ds = DaemonSet(
        meta=new_meta("cd-daemon", "ns"),
        selector={"app": "d"}, node_selector={"cd": "uid"},
        template=PodTemplate(labels={"app": "d"},
                             containers=[Container(name="agent", image="i")],
                             resource_claims=[PodResourceClaimRef(
                                 name="dc", resource_claim_template_name="t")]),
        desired=4, ready=2)
    back = _roundtrip(ds)
    assert back.node_selector == {"cd": "uid"} and back.desired == 4

    lease = Lease(meta=new_meta("controller", "kube-system"),
                  holder="me", acquired_at=1000.0, renewed_at=2000.5,
                  lease_duration_s=15.0)
    back = _roundtrip(lease)
    assert back.holder == "me" and back.renewed_at == 2000.5


def test_wire_claim_template_and_node_roundtrip():
    t = ResourceClaimTemplate(
        meta=new_meta("tmpl", "ns"),
        spec_meta_labels={"x": "y"},
        requests=[DeviceRequest(name="r", device_class_name="c",
                                allocation_mode="All", count=1)],
        config=[DeviceClaimConfig(opaque=OpaqueDeviceConfig(
            driver="d", parameters={"kind": "K"}))])
    back = _roundtrip(t)
    assert back.spec_meta_labels == {"x": "y"}
    assert back.requests[0].allocation_mode == "All"

    n = Node(meta=new_meta("node-0"),
             taints=[NodeTaint(key="k", effect="NoSchedule")],
             addresses={"InternalIP": "10.0.0.1"},
             allocatable={"tpu": 4})
    back = _roundtrip(n)
    assert back.addresses == {"InternalIP": "10.0.0.1"}
    assert back.allocatable == {"tpu": 4}


def test_api_path():
    assert api_path("Pod", "ns", "p") == "/api/v1/namespaces/ns/pods/p"
    assert api_path("ResourceSlice") == "/apis/resource.k8s.io/v1/resourceslices"
    assert api_path("ResourceSlice", api_version="v1beta1") == \
        "/apis/resource.k8s.io/v1beta1/resourceslices"
    assert (api_path("ComputeDomain", "ns")
            == "/apis/resource.tpu.google.com/v1beta1/namespaces/ns/computedomains")
    assert api_path("Lease", "kube-system", "x") == (
        "/apis/coordination.k8s.io/v1/namespaces/kube-system/leases/x")


# -- adapter vs conformance server ------------------------------------------


@pytest.fixture
def kube():
    srv = K8sAPIServer().start()
    try:
        yield KubernetesAPIServer(base_url=srv.url), srv.api
    finally:
        srv.stop()


def test_kube_crud(kube):
    api, _ = kube
    api.create(Pod(meta=new_meta("p", "ns"), containers=[Container()]))
    got = api.get(POD, "p", "ns")
    assert got.meta.name == "p" and got.meta.uid
    assert api.try_get(POD, "missing", "ns") is None
    with pytest.raises(NotFoundError):
        api.get(POD, "missing", "ns")
    assert [p.meta.name for p in api.list(POD, namespace="ns")] == ["p"]
    api.delete(POD, "p", "ns")
    assert api.try_get(POD, "p", "ns") is None


def test_kube_cas_conflict(kube):
    api, _ = kube
    api.create(ComputeDomain(meta=new_meta("cd", "ns"),
                             spec=ComputeDomainSpec(num_nodes=2)))
    a = api.get("ComputeDomain", "cd", "ns")
    b = api.get("ComputeDomain", "cd", "ns")
    a.spec.topology = "2x2"
    api.update(a)
    b.spec.topology = "4x4"
    with pytest.raises(ConflictError):
        api.update(b)
    api.update_with_retry("ComputeDomain", "cd", "ns",
                          lambda o: setattr(o.spec, "num_nodes", 8))
    merged = api.get("ComputeDomain", "cd", "ns")
    assert merged.spec.num_nodes == 8 and merged.spec.topology == "2x2"


def test_kube_status_subresource_split(kube):
    """A real apiserver drops status edits on the main resource; the
    adapter's two-phase update must land both spec and status."""
    api, store = kube
    api.create(ComputeDomain(meta=new_meta("cd", "ns"),
                             spec=ComputeDomainSpec(num_nodes=2)))
    cd = api.get("ComputeDomain", "cd", "ns")
    cd.spec.topology = "2x2"
    cd.status.status = "Ready"
    api.update(cd)
    back = api.get("ComputeDomain", "cd", "ns")
    assert back.spec.topology == "2x2"
    assert back.status.status == "Ready"
    # The conformance server enforces the split: a raw main-resource PUT
    # (no /status leg) must NOT change status.
    raw = store.get("ComputeDomain", "cd", "ns", copy=True)
    raw.status.status = "NotReady"
    import urllib.request, json as _json  # noqa: E401
    wire = to_k8s_wire(raw)
    req = urllib.request.Request(
        api.auth.server + api_path("ComputeDomain", "ns", "cd"),
        data=_json.dumps(wire).encode(), method="PUT",
        headers={"Content-Type": "application/json"})
    with urllib.request.urlopen(req, timeout=5):
        pass
    assert api.get("ComputeDomain", "cd", "ns").status.status == "Ready"


def test_kube_labels_and_selectors(kube):
    api, _ = kube
    api.create(Pod(meta=new_meta("a", "ns1", labels={"app": "x"})))
    api.create(Pod(meta=new_meta("b", "ns2", labels={"app": "y"})))
    assert {p.meta.name for p in api.list(POD)} == {"a", "b"}
    assert [p.meta.name for p in api.list(POD, namespace="ns1")] == ["a"]
    assert [p.meta.name
            for p in api.list(POD, label_selector={"app": "y"})] == ["b"]


def test_kube_finalizer_gated_delete(kube):
    api, _ = kube
    cd = ComputeDomain(meta=new_meta("cd", "ns"), spec=ComputeDomainSpec())
    cd.meta.finalizers = ["keep"]
    api.create(cd)
    api.delete("ComputeDomain", "cd", "ns")
    lingering = api.get("ComputeDomain", "cd", "ns")
    assert lingering.deleting

    def drop(obj):
        obj.meta.finalizers = []
    api.update_with_retry("ComputeDomain", "cd", "ns", drop)
    assert api.try_get("ComputeDomain", "cd", "ns") is None


def test_kube_watch_and_informer(kube):
    api, _ = kube
    events = []
    q = api.watch(POD)
    api.create(Pod(meta=new_meta("w", "ns")))
    api.update_with_retry(POD, "w", "ns",
                          lambda o: setattr(o, "phase", "Running"))
    api.delete(POD, "w", "ns")
    # The adapter's two-phase update (main + /status) emits two MODIFIED
    # events; require the ordered envelope, not an exact count.
    def seen():
        events.extend(q.get_nowait() for _ in range(q.qsize()))
        types = [e.type for e in events]
        return (types and types[0] == "ADDED" and types[-1] == "DELETED"
                and all(t == "MODIFIED" for t in types[1:-1]))
    wait_for(seen, msg="k8s watch events")
    api.stop_watch(POD, q)

    inf = Informer(api, POD)
    adds = []
    inf.add_event_handler(on_add=lambda old, new: adds.append(new.meta.name))
    api.create(Pod(meta=new_meta("i1", "ns")))
    inf.start()
    try:
        wait_for(lambda: "i1" in adds, msg="informer add from snapshot")
        api.create(Pod(meta=new_meta("i2", "ns")))
        wait_for(lambda: "i2" in adds, msg="informer add from stream")
    finally:
        inf.stop()


def test_kube_watch_survives_apiserver_restart():
    store = APIServer()
    srv = K8sAPIServer(store).start()
    port = srv.port
    api = KubernetesAPIServer(base_url=srv.url)
    q = api.watch(POD)
    store.create(Pod(meta=new_meta("victim", "ns")))
    events = []

    def drain(want):
        def check():
            while not q.empty():
                events.append(q.get_nowait())
            return want(events)
        wait_for(check, msg=f"events: {[(e.type, e.obj.meta.name) for e in events]}")

    drain(lambda evs: any(e.obj.meta.name == "victim" for e in evs))
    srv.stop()
    store.delete(POD, "victim", "ns")
    store.create(Pod(meta=new_meta("newcomer", "ns")))
    events.clear()
    srv2 = K8sAPIServer(store, port=port).start()
    try:
        drain(lambda evs: any(e.type == "DELETED" and e.obj.meta.name == "victim"
                              for e in evs)
              and any(e.type == "ADDED" and e.obj.meta.name == "newcomer"
                      for e in evs))
    finally:
        api.stop_watch(POD, q)
        srv2.stop()


# -- kubeconfig resolution ---------------------------------------------------


def test_kube_discovery_and_v1_negotiation(kube):
    """Client discovers resource.k8s.io versions and speaks v1 (GA) with the
    `exactly:` request shape; the server also still serves v1beta1 paths."""
    import json as _json
    import urllib.request as _rq

    api, store = kube
    # Discovery endpoints answer like a real apiserver.
    with _rq.urlopen(api.auth.server + "/apis", timeout=5) as r:
        groups = {g["name"]: g for g in _json.loads(r.read())["groups"]}
    assert groups["resource.k8s.io"]["preferredVersion"]["version"] == "v1"
    assert {v["version"] for v in groups["resource.k8s.io"]["versions"]} == \
        {"v1", "v1beta1"}

    # The adapter negotiated v1 and round-trips a claim with requests.
    claim = ResourceClaim(
        meta=new_meta("neg", "ns"),
        requests=[DeviceRequest(name="tpus",
                                device_class_name="tpu.google.com", count=2)],
    )
    api.create(claim)
    assert api._group_version.get("resource.k8s.io") == "v1"
    back = api.get("ResourceClaim", "neg", "ns")
    assert back.requests[0].count == 2

    # Raw v1 GET shows the exactly: shape; raw v1beta1 GET the flat shape.
    with _rq.urlopen(api.auth.server +
                     "/apis/resource.k8s.io/v1/namespaces/ns/resourceclaims/neg",
                     timeout=5) as r:
        v1doc = _json.loads(r.read())
    assert "exactly" in v1doc["spec"]["devices"]["requests"][0]
    with _rq.urlopen(api.auth.server +
                     "/apis/resource.k8s.io/v1beta1/namespaces/ns/resourceclaims/neg",
                     timeout=5) as r:
        betadoc = _json.loads(r.read())
    req = betadoc["spec"]["devices"]["requests"][0]
    assert "exactly" not in req and req["deviceClassName"] == "tpu.google.com"
    assert betadoc["apiVersion"] == "resource.k8s.io/v1beta1"

    # Unserved version -> 404, like upstream.
    import urllib.error as _err
    with pytest.raises(_err.HTTPError) as exc:
        _rq.urlopen(api.auth.server +
                    "/apis/resource.k8s.io/v9/resourceclaims", timeout=5)
    assert exc.value.code == 404


def test_kube_falls_back_to_v1beta1_only_server(kube):
    """Against a server whose discovery offers only v1beta1 (a 1.32-era
    cluster), negotiation itself downgrades and round-trips still work."""
    api, _ = kube
    real_request = api._request

    def request_with_old_discovery(method, path, body=None):
        if method == "GET" and path == "/apis/resource.k8s.io":
            return {"kind": "APIGroup", "name": "resource.k8s.io",
                    "versions": [{"groupVersion": "resource.k8s.io/v1beta1",
                                  "version": "v1beta1"}],
                    "preferredVersion": {"version": "v1beta1"}}
        return real_request(method, path, body)

    api._request = request_with_old_discovery
    claim = ResourceClaim(
        meta=new_meta("beta", "ns"),
        requests=[DeviceRequest(name="r", device_class_name="tpu.google.com")],
    )
    api.create(claim)
    assert api._group_version["resource.k8s.io"] == "v1beta1"
    back = api.get("ResourceClaim", "beta", "ns")
    assert back.requests[0].device_class_name == "tpu.google.com"


def test_wrong_group_paths_404(kube):
    """A known plural under the wrong group must not route (upstream
    behavior): /api/v1/resourceclaims and /apis/apps/v1/resourceclaims."""
    import urllib.error as _err
    import urllib.request as _rq

    api, _ = kube
    for path in ("/api/v1/resourceclaims", "/apis/apps/v1/resourceclaims"):
        with pytest.raises(_err.HTTPError) as exc:
            _rq.urlopen(api.auth.server + path, timeout=5)
        assert exc.value.code == 404, path


def test_kubeauth_from_kubeconfig(tmp_path):
    kc = tmp_path / "config"
    kc.write_text("""
apiVersion: v1
kind: Config
current-context: test
clusters:
- name: c1
  cluster:
    server: https://10.0.0.1:6443
    insecure-skip-tls-verify: true
contexts:
- name: test
  context: {cluster: c1, user: u1}
users:
- name: u1
  user:
    token: sekret
""")
    auth = KubeAuth.from_kubeconfig(str(kc))
    assert auth.server == "https://10.0.0.1:6443"
    assert auth.token == "sekret"
    assert auth.insecure
    ctx = auth.ssl_context()
    assert ctx is not None and ctx.verify_mode.name == "CERT_NONE"


def test_kubeauth_in_cluster(tmp_path, monkeypatch):
    sa = tmp_path / "sa"
    sa.mkdir()
    (sa / "token").write_text("tok-123\n")
    (sa / "ca.crt").write_text("cert")
    monkeypatch.setenv("KUBERNETES_SERVICE_HOST", "10.96.0.1")
    monkeypatch.setenv("KUBERNETES_SERVICE_PORT", "443")
    auth = KubeAuth.in_cluster(sa_dir=str(sa))
    assert auth.server == "https://10.96.0.1:443"
    assert auth.token == "tok-123"
    assert auth.ca_file == str(sa / "ca.crt")
