"""ServingGroupController units: stamping, policy, events, victims.

Drives the controller against a bare APIServer (no sim): the traffic
engine senses, the controller actuates, and every policy edge —
cooldowns, the stabilization window, alert gating, the deferred path,
victim ranking, vertical re-tier, orphan GC, the cordon race with the
rebalancer, and the zero-list steady pass — is pinned in isolation.
"""

import pytest

from k8s_dra_driver_tpu.api.servinggroup import (
    SERVING_GROUP,
    SERVING_GROUP_LABEL,
    SERVING_REPLICA_ANNOTATION,
    SERVING_TIER_LABEL,
    ServingGroup,
    ServingGroupSpec,
    ServingScalingPolicy,
    ServingSLO,
    ServingTraffic,
)
from k8s_dra_driver_tpu.autoscaler import ServingGroupController, TrafficEngine
from k8s_dra_driver_tpu.k8s import APIServer
from k8s_dra_driver_tpu.k8s.core import (
    EVENT,
    POD,
    RESOURCE_CLAIM,
    UtilizationSummary,
)
from k8s_dra_driver_tpu.k8s.objects import new_meta
from k8s_dra_driver_tpu.pkg.events import (
    REASON_SCALE_DEFERRED,
    REASON_SCALE_DOWN,
    REASON_SCALE_UP,
)
from k8s_dra_driver_tpu.pkg.metrics import Registry
from k8s_dra_driver_tpu.pkg.slo import ActiveAlert
from k8s_dra_driver_tpu.rebalancer.controller import (
    CORDON_ANNOTATION,
    release_cordon,
)

KEY = ("serve", "chat")


def _group(replicas=2, trace="constant:level=0.3", peak=400.0,
           qps_per_chip=100.0, tiers=None, profile="",
           policy=None) -> ServingGroup:
    return ServingGroup(
        meta=new_meta("chat", "serve"),
        spec=ServingGroupSpec(
            replicas=replicas, profile=profile, tiers=list(tiers or []),
            traffic=ServingTraffic(trace=trace, peak_qps=peak,
                                   qps_per_chip=qps_per_chip,
                                   base_latency_ms=10.0),
            slo=ServingSLO(latency_p95_ms=50.0),
            policy=policy or ServingScalingPolicy(
                min_replicas=1, max_replicas=16, target_duty=0.6,
                scale_up_cooldown_s=2.0, scale_down_cooldown_s=5.0,
                stabilization_window_s=10.0, down_tier_duty=0.3,
                tier_cooldown_s=5.0)))


class _Harness:
    """Engine + controller + an allocator/kubelet stand-in that marks
    stamped replicas allocated and Running on demand."""

    def __init__(self, group=None):
        self.api = APIServer()
        self.registry = Registry()
        self.sink_calls = []
        if group is not None:
            self.api.create(group)
        self.engine = TrafficEngine(
            self.api, self.registry, None,
            claim_load_sink=lambda n, u, d: self.sink_calls.append((n, u, d)))
        self.ctl = ServingGroupController(self.api, self.registry,
                                          self.engine)

    def close(self):
        self.engine.close()

    def tick(self, now, alerts=None, summaries=None):
        samples = self.engine.step(now)
        return self.ctl.step(now, samples, alerts=alerts,
                             claim_summaries=summaries)

    def run_pods(self, node_by_pod=None):
        """Pretend scheduler+kubelet: allocate each replica claim and
        flip its pod Running."""
        from k8s_dra_driver_tpu.k8s.core import (
            AllocationResult,
            DeviceRequestAllocationResult,
        )

        for pod in self.api.list(POD, namespace="serve"):
            if pod.phase == "Running":
                continue
            node = (node_by_pod or {}).get(pod.meta.name, "node-0")
            claim_name = pod.resource_claims[0].resource_claim_name

            def alloc(obj, node=node):
                if obj.allocation is None:
                    obj.allocation = AllocationResult(
                        devices=[DeviceRequestAllocationResult(
                            request="tpus", driver="tpu.google.com",
                            pool=node, device="tpu-0")],
                        node_name=node)
            self.api.update_with_retry(RESOURCE_CLAIM, claim_name, "serve",
                                       alloc)

            def run(obj):
                obj.phase = "Running"
                obj.ready = True
            self.api.update_with_retry(POD, pod.meta.name, "serve", run)

    def pods(self):
        return sorted(self.api.list(POD, namespace="serve"),
                      key=lambda p: p.meta.name)

    def group(self):
        return self.api.get(SERVING_GROUP, "chat", "serve")

    def events(self, reason):
        return [e for e in self.api.list(EVENT, namespace="serve")
                if e.reason == reason]


def _alert(burn=5.0, since=0.0):
    from k8s_dra_driver_tpu.autoscaler.traffic import SERVING_LATENCY_SLO

    return [ActiveAlert(slo=SERVING_LATENCY_SLO, subject=KEY,
                        burn_rate=burn, window=(30.0, 10.0), since=since)]


# -- stamping -----------------------------------------------------------------


def test_stamps_replicas_with_labels_owners_and_indices():
    h = _Harness(_group(replicas=3))
    try:
        h.tick(1.0)
        pods = h.pods()
        assert [p.meta.name for p in pods] == [
            "chat-rep-0", "chat-rep-1", "chat-rep-2"]
        claims = sorted(h.api.list(RESOURCE_CLAIM, namespace="serve"),
                        key=lambda c: c.meta.name)
        assert [c.meta.name for c in claims] == [
            "chat-rep-0-tpus", "chat-rep-1-tpus", "chat-rep-2-tpus"]
        for pod in pods:
            assert pod.meta.labels[SERVING_GROUP_LABEL] == "chat"
            assert pod.meta.labels[SERVING_TIER_LABEL] == ""
            assert pod.meta.annotations[SERVING_REPLICA_ANNOTATION] in \
                ("0", "1", "2")
            assert pod.meta.owner_references[0].kind == SERVING_GROUP
        for claim in claims:
            # Pod-owned: ownerRef GC collects the claim with its pod.
            assert claim.meta.owner_references[0].kind == POD
        # Idempotent: a second pass creates nothing new.
        h.tick(2.0)
        assert len(h.pods()) == 3
    finally:
        h.close()


def test_single_chip_and_subslice_claim_shapes():
    h = _Harness(_group(replicas=1))
    try:
        h.tick(1.0)
        claim = h.api.list(RESOURCE_CLAIM, namespace="serve")[0]
        req = claim.requests[0]
        assert req.device_class_name == "tpu.google.com" and req.count == 1
    finally:
        h.close()
    h2 = _Harness(_group(replicas=1, profile="1x2"))
    try:
        h2.tick(1.0)
        claim = h2.api.list(RESOURCE_CLAIM, namespace="serve")[0]
        req = claim.requests[0]
        assert req.device_class_name == "subslice.tpu.google.com"
        assert req.cel_selectors == [
            'device.attributes["tpu.google.com"].profile == "1x2"']
    finally:
        h2.close()


# -- horizontal policy --------------------------------------------------------


def test_demand_scale_up_and_cooldown():
    # 0.3*400=120 qps at 100 qps/chip, target 0.6 -> demand 2. Raise the
    # trace to 0.9 -> 360 qps -> demand 6.
    h = _Harness(_group(replicas=2, trace="constant:level=0.9"))
    try:
        h.tick(1.0)
        h.run_pods()
        decisions = h.tick(2.0)
        assert decisions[0].direction == "up"
        assert h.group().spec.replicas == 6
        assert h.group().status.last_scale_up == 2.0
        assert h.events(REASON_SCALE_UP)
        # Immediately wanting more is cooldown-blocked -> deferred.
        def grow(obj):
            obj.spec.traffic.peak_qps = 1600.0
        h.api.update_with_retry(SERVING_GROUP, "chat", "serve", grow)
        decisions = h.tick(3.0)
        assert decisions[0].direction == "deferred"
        assert h.events(REASON_SCALE_DEFERRED)
    finally:
        h.close()


def test_alert_forces_step_up_when_demand_formula_is_satisfied():
    """A too-tight target_duty leaves the demand formula happy while the
    latency model violates: only the burn-alert path can fix it — and it
    steps exactly while the current sample still violates."""
    policy = ServingScalingPolicy(min_replicas=1, max_replicas=16,
                                  target_duty=0.9, scale_up_cooldown_s=1.0,
                                  scale_down_cooldown_s=5.0,
                                  stabilization_window_s=10.0)
    # 0.425*400 = 170 qps over 2 replicas: rho 0.85, ratio 1.33 (> 1)
    # but demand = ceil(170/90) = 2 == replicas.
    h = _Harness(_group(replicas=2, trace="constant:level=0.425",
                        policy=policy))
    try:
        h.tick(1.0)
        h.run_pods()
        decisions = h.tick(4.0, alerts=_alert())
        assert decisions[0].direction == "up"
        assert h.group().spec.replicas == 3   # cur + 1, SLO keeps pushing
        h.run_pods()
        # 3 replicas: rho 0.57, ratio 0.46 — recovered. A (trailing)
        # alert no longer pushes: stepping on recovered samples would
        # overshoot to max_replicas before the alert's window drains.
        decisions = h.tick(6.0, alerts=_alert())
        assert decisions[0].direction != "up"
        assert h.group().spec.replicas == 3
    finally:
        h.close()


def test_scale_down_waits_out_full_observation_window():
    """A pre-provisioned group (replicas above demand from birth) is not
    torn down until the controller has observed it for a FULL
    stabilization window — the operator's headroom survives the first
    low samples, and a controller restart re-arms the protection."""
    h = _Harness(_group(replicas=6))        # demand 2 at 120 qps
    try:
        h.tick(1.0)                          # first seen at t=1
        h.run_pods()
        # Wants down from tick 2, but the observation window
        # (stabilization 10s from first sight) holds: deferred.
        for t in range(2, 11):
            d = h.tick(float(t))
            assert d[0].direction == "deferred"
            assert h.group().spec.replicas == 6
        d = h.tick(11.0)
        assert d[0].direction == "down"
        assert h.group().spec.replicas == 2
        assert h.group().status.last_scale_down == 11.0
        assert h.events(REASON_SCALE_DOWN)
        # The blocked trough deferred repeatedly: ONE deduped series
        # with a rising count, not a row per tick.
        deferred = h.events(REASON_SCALE_DEFERRED)
        assert len(deferred) == 1 and deferred[0].count >= 3
    finally:
        h.close()


def test_stabilization_window_remembers_burst_demand():
    """A burst that ends does not trigger an immediate scale-down: the
    effective desired count is the max over the stabilization window —
    the anti-flap semantics the bench's bursty segment gates."""
    import json

    policy = ServingScalingPolicy(min_replicas=1, max_replicas=32,
                                  target_duty=0.6, scale_up_cooldown_s=1.0,
                                  scale_down_cooldown_s=1.0,
                                  stabilization_window_s=8.0)
    import tempfile, os
    tmp = tempfile.mkdtemp()
    trace = os.path.join(tmp, "burst.json")
    with open(trace, "w") as f:
        json.dump([[0, 120], [9, 120], [10, 600], [14, 600],
                   [15, 120], [60, 120]], f)
    h = _Harness(_group(replicas=2, trace=f"playback:file={trace}",
                        peak=1.0, policy=policy))
    try:
        h.tick(1.0)
        h.run_pods()
        for t in range(2, 10):
            h.tick(float(t))
        assert h.group().spec.replicas == 2
        h.tick(10.0)              # burst: demand 10
        assert h.group().spec.replicas == 10
        h.run_pods()
        # Burst over at t=15, but the window (8s) still remembers the
        # t=14 burst-demand sample until t > 22: no down before that.
        for t in range(11, 22):
            d = h.tick(float(t))
            assert d[0].direction in ("deferred", "none", "up")
            assert h.group().spec.replicas == 10
        for t in range(22, 25):
            h.tick(float(t))
        assert h.group().spec.replicas == 2
    finally:
        h.close()


def test_scale_down_blocked_while_alerting():
    """An active alert over a currently-healthy sample neither steps up
    (no overshoot) nor lets the trough tear capacity down (no fresh
    incident): the group HOLDS until the alert clears."""
    h = _Harness(_group(replicas=6))        # demand 2 at 120 qps
    try:
        h.tick(1.0)
        h.run_pods()
        for t in range(2, 20):
            h.tick(float(t), alerts=_alert())
        assert h.group().spec.replicas == 6
        assert not h.events(REASON_SCALE_DOWN)
        # Alert gone: the down path resumes.
        for t in range(20, 24):
            h.tick(float(t))
        assert h.group().spec.replicas == 2
    finally:
        h.close()


def test_max_replicas_clamp_defers():
    policy = ServingScalingPolicy(min_replicas=1, max_replicas=2,
                                  target_duty=0.6, scale_up_cooldown_s=0.0,
                                  scale_down_cooldown_s=5.0,
                                  stabilization_window_s=10.0)
    h = _Harness(_group(replicas=2, trace="constant:level=0.9",
                        policy=policy))
    try:
        h.tick(1.0)
        h.run_pods()
        d = h.tick(2.0)
        assert d[0].direction == "deferred"
        assert h.group().spec.replicas == 2
    finally:
        h.close()


# -- scale-down mechanics -----------------------------------------------------


def test_victims_picked_on_emptiest_nodes_and_claims_deleted():
    h = _Harness(_group(replicas=4, trace="constant:level=0.1"))
    try:
        h.tick(1.0)
        # node-a hosts three replicas, node-b one: node-b is emptiest,
        # so the single replica there goes first.
        h.run_pods(node_by_pod={
            "chat-rep-0": "node-a", "chat-rep-1": "node-a",
            "chat-rep-2": "node-a", "chat-rep-3": "node-b"})
        def shrink(obj):
            obj.spec.replicas = 3
        h.api.update_with_retry(SERVING_GROUP, "chat", "serve", shrink)
        h.engine.drain()
        h.tick(2.0)
        names = [p.meta.name for p in h.pods()]
        assert "chat-rep-3" not in names and len(names) == 3
        claims = {c.meta.name
                  for c in h.api.list(RESOURCE_CLAIM, namespace="serve")}
        assert "chat-rep-3-tpus" not in claims
    finally:
        h.close()


def test_cordoned_replica_survives_drain_until_released():
    """The rebalancer race: a claim mid-migration (cordoned) cannot be
    drained; the controller retries after the cordon clears."""
    h = _Harness(_group(replicas=2, trace="constant:level=0.1"))
    try:
        h.tick(1.0)
        h.run_pods()  # both on node-0: victim ranking is name order
        # rep-0 is the deterministic victim; mark it mid-migration.
        def cordon(obj):
            obj.meta.annotations[CORDON_ANNOTATION] = "true"
        h.api.update_with_retry(RESOURCE_CLAIM, "chat-rep-0-tpus", "serve",
                                cordon)
        def shrink(obj):
            obj.spec.replicas = 1
        h.api.update_with_retry(SERVING_GROUP, "chat", "serve", shrink)
        h.engine.drain()
        h.tick(2.0)
        # Drain blocked: both replicas (and both claims) survive.
        assert len(h.pods()) == 2
        assert "chat-rep-0-tpus" in {
            c.meta.name for c in h.api.list(RESOURCE_CLAIM,
                                            namespace="serve")}
        claim = h.api.get(RESOURCE_CLAIM, "chat-rep-0-tpus", "serve")
        release_cordon(h.api, claim)
        h.engine.drain()
        h.tick(3.0)
        assert [p.meta.name for p in h.pods()] == ["chat-rep-1"]
    finally:
        h.close()


def test_orphan_replicas_drained_after_group_delete():
    h = _Harness(_group(replicas=2))
    try:
        h.tick(1.0)
        assert len(h.pods()) == 2
        h.api.delete(SERVING_GROUP, "chat", "serve")
        h.engine.drain()
        h.ctl.step(2.0, {}, alerts=None)
        assert h.pods() == []
        assert h.api.list(RESOURCE_CLAIM, namespace="serve") == []
    finally:
        h.close()


# -- vertical re-tier ---------------------------------------------------------


def test_down_tier_rolls_replicas_to_smaller_profile():
    """The over-tiered case vertical scaling exists for: replicas pinned
    at the min_replicas floor (horizontal can't shrink further) and
    measurably idle — the tier shrinks instead."""
    policy = ServingScalingPolicy(min_replicas=2, max_replicas=16,
                                  target_duty=0.6, scale_up_cooldown_s=2.0,
                                  scale_down_cooldown_s=5.0,
                                  stabilization_window_s=10.0,
                                  down_tier_duty=0.3, tier_cooldown_s=5.0)
    # 0.05*400 = 20 qps over 2 replicas of 200 qps capacity: duty 0.05.
    h = _Harness(_group(replicas=2, profile="1x2", tiers=["1x1", "1x2"],
                        trace="constant:level=0.05", qps_per_chip=100.0,
                        policy=policy))
    try:
        h.tick(1.0)
        h.run_pods()
        # Telemetry says every replica is nearly idle.
        summaries = {
            ("serve", c.meta.name): UtilizationSummary(duty_cycle_p95=0.1)
            for c in h.api.list(RESOURCE_CLAIM, namespace="serve")}
        # tier_cooldown_s=5 measured from last_retier=0.
        decisions = h.tick(6.0, summaries=summaries)
        assert decisions[0].direction == "tier-down"
        sg = h.group()
        assert sg.spec.profile == "1x1"
        assert sg.status.last_retier == 6.0
        # Surge: replacements created at the new tier while the old
        # tier keeps serving.
        tiers = [p.meta.labels[SERVING_TIER_LABEL] for p in h.pods()]
        assert tiers.count("1x1") == 2 and tiers.count("1x2") == 2
        # New-tier claims carry the smaller profile selector.
        new_claims = [c for c in h.api.list(RESOURCE_CLAIM,
                                            namespace="serve")
                      if c.meta.labels[SERVING_TIER_LABEL] == "1x1"]
        assert all('profile == "1x1"' in c.requests[0].cel_selectors[0]
                   for c in new_claims)
        # Old tier drains once the replacements run.
        h.run_pods()
        h.tick(7.0, summaries=summaries)
        tiers = {p.meta.labels[SERVING_TIER_LABEL] for p in h.pods()}
        assert tiers == {"1x1"}
        assert h.group().status.profile == "1x1"
        down = h.events(REASON_SCALE_DOWN)
        assert any("down-tiering" in e.message for e in down)
    finally:
        h.close()


def test_stalled_retier_falls_back_to_rolling_drain():
    """On a capacity-tight cluster the surge wedges (the old tier holds
    the chips the replacements need): after a full stabilization window
    without the new tier coming up, the controller yields capacity one
    old replica per pass instead of sitting in surge forever."""
    policy = ServingScalingPolicy(min_replicas=2, max_replicas=16,
                                  target_duty=0.6, scale_up_cooldown_s=2.0,
                                  scale_down_cooldown_s=5.0,
                                  stabilization_window_s=10.0,
                                  down_tier_duty=0.3, tier_cooldown_s=5.0)
    h = _Harness(_group(replicas=2, profile="1x2", tiers=["1x1", "1x2"],
                        trace="constant:level=0.05", qps_per_chip=100.0,
                        policy=policy))
    try:
        h.tick(1.0)
        h.run_pods()
        summaries = {
            ("serve", c.meta.name): UtilizationSummary(duty_cycle_p95=0.1)
            for c in h.api.list(RESOURCE_CLAIM, namespace="serve")}
        d = h.tick(6.0, summaries=summaries)
        assert d[0].direction == "tier-down"
        # New-tier pods exist but NEVER become ready (no capacity); the
        # old tier keeps serving through the whole window.
        def old_tier_count():
            return sum(1 for p in h.pods()
                       if p.meta.labels[SERVING_TIER_LABEL] == "1x2")
        for t in range(7, 16):
            h.tick(float(t), summaries=summaries)
            assert old_tier_count() == 2, t
        # Past last_retier + stabilization window: one old replica per
        # pass yields its chips so the roll can progress.
        h.tick(17.0, summaries=summaries)
        assert old_tier_count() == 1
        h.tick(18.0, summaries=summaries)
        assert old_tier_count() == 0
    finally:
        h.close()


def test_down_tier_blocked_at_smallest_or_partial_telemetry():
    h = _Harness(_group(replicas=2, profile="1x1", tiers=["1x1", "1x2"],
                        trace="constant:level=0.1"))
    try:
        h.tick(1.0)
        h.run_pods()
        summaries = {
            ("serve", c.meta.name): UtilizationSummary(duty_cycle_p95=0.1)
            for c in h.api.list(RESOURCE_CLAIM, namespace="serve")}
        d = h.tick(6.0, summaries=summaries)
        assert d[0].direction != "tier-down"   # already smallest
        assert h.group().spec.profile == "1x1"
    finally:
        h.close()


# -- steady state -------------------------------------------------------------


def test_steady_pass_issues_zero_store_lists():
    h = _Harness(_group(replicas=2))
    try:
        h.tick(1.0)
        h.run_pods()
        h.tick(2.0)
        before = h.api.stats.list_calls
        for t in range(3, 10):
            h.tick(float(t))
        assert h.api.stats.list_calls == before, \
            "steady serving+autoscaler passes must ride the watch caches"
    finally:
        h.close()


def test_metrics_families_exposed():
    h = _Harness(_group(replicas=1))
    try:
        h.tick(1.0)
        text = h.registry.expose()
        for fam in ("tpu_dra_autoscaler_desired_replicas",
                    "tpu_dra_autoscaler_ready_replicas",
                    "tpu_dra_autoscaler_group_qps",
                    "tpu_dra_autoscaler_group_latency_ratio",
                    "tpu_dra_autoscaler_group_utilization",
                    "tpu_dra_autoscaler_pass_seconds"):
            assert fam in text, fam
    finally:
        h.close()


# -- multi-group fairness (contention-plane satellite) ------------------------


def _group_in(ns, name, replicas=1):
    g = ServingGroup(
        meta=new_meta(name, ns),
        spec=ServingGroupSpec(
            replicas=replicas,
            traffic=ServingTraffic(trace="constant:level=1.0",
                                   peak_qps=400.0, qps_per_chip=100.0,
                                   base_latency_ms=10.0),
            slo=ServingSLO(latency_p95_ms=50.0),
            policy=ServingScalingPolicy(
                min_replicas=1, max_replicas=16, target_duty=0.6,
                scale_up_cooldown_s=2.0, scale_down_cooldown_s=5.0,
                stabilization_window_s=10.0)))
    return g


def test_scale_up_apportioned_by_tenant_weight_under_headroom():
    """When the fleet cannot satisfy the sum of desired scale-ups, the
    headroom splits by tenant weight (weighted max-min) instead of
    first-writer-wins: the heavy tenant's group steps up with its share,
    the light tenant's group defers visibly (ScaleDeferred)."""
    api = APIServer()
    registry = Registry()
    api.create(_group_in("heavy", "h-chat"))
    api.create(_group_in("light", "l-chat"))
    engine = TrafficEngine(api, registry, None,
                           claim_load_sink=lambda n, u, d: None)
    weights = {"heavy": 3.0, "light": 1.0}
    ctl = ServingGroupController(
        api, registry, engine,
        headroom_fn=lambda: 3.0,
        tenant_weight_fn=lambda ns: weights.get(ns, 1.0))
    try:
        samples = engine.step(10.0)
        assert set(samples) == {("heavy", "h-chat"), ("light", "l-chat")}
        decisions = {d.key: d for d in ctl.step(10.0, samples)}
        # Both want 7 replicas (400 qps / (100 * 0.6)); 3 free chips
        # split 3:1 -> heavy gets 2 more replicas, light gets 0.
        heavy = decisions[("heavy", "h-chat")]
        light = decisions[("light", "l-chat")]
        assert heavy.direction == "up" and heavy.applied == 3
        assert light.direction == "deferred"
        deferred = [e for e in api.list(EVENT, namespace="light")
                    if e.reason == REASON_SCALE_DEFERRED]
        assert deferred, "the clamped loser must surface as ScaleDeferred"
    finally:
        engine.close()


def test_scale_up_unconstrained_when_headroom_suffices():
    """Headroom above the summed demand leaves every group's step
    untouched — the fairness hook only engages under contention."""
    api = APIServer()
    registry = Registry()
    api.create(_group_in("heavy", "h-chat"))
    api.create(_group_in("light", "l-chat"))
    engine = TrafficEngine(api, registry, None,
                           claim_load_sink=lambda n, u, d: None)
    ctl = ServingGroupController(api, registry, engine,
                                 headroom_fn=lambda: 1000.0)
    try:
        samples = engine.step(10.0)
        decisions = {d.key: d for d in ctl.step(10.0, samples)}
        assert decisions[("heavy", "h-chat")].applied == 7
        assert decisions[("light", "l-chat")].applied == 7
    finally:
        engine.close()
