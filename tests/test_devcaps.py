"""Slice-channel char-device discovery + multi-channel allocation.

Reference models: /proc/devices major parsing with the ALT seam
(internal/common/nvcaps.go:33-120, ConfigureProcDevicesPath test hook),
per-channel allocation conflict (compute-domain-kubelet-plugin/
device_state.go:878-906), AllocationMode All CDI injection (690-733).
"""

import pytest

from k8s_dra_driver_tpu.api import API_VERSION
from k8s_dra_driver_tpu.api.configs import COMPUTE_DOMAIN_DRIVER_NAME
from k8s_dra_driver_tpu.daemon import SliceAgent
from k8s_dra_driver_tpu.k8s.core import DeviceClaimConfig, OpaqueDeviceConfig
from k8s_dra_driver_tpu.pkg import devcaps
from k8s_dra_driver_tpu.plugins.computedomain.computedomain import PermanentError

from tests.test_computedomain import (  # noqa: F401
    NS,
    boot_id,
    cd_env,
    channel_claim,
    make_cd,
)

PROC_DEVICES = """Character devices:
  1 mem
  5 /dev/tty
136 pts
195 nvidia
511 tpu-slice-channels

Block devices:
259 blkext
"""


@pytest.fixture
def proc_devices(tmp_path):
    p = tmp_path / "proc_devices"
    p.write_text(PROC_DEVICES)
    devcaps.configure_proc_devices_path(str(p))
    yield p
    devcaps.configure_proc_devices_path(None)


def test_channel_major_parsed(proc_devices):
    assert devcaps.get_char_device_major() == 511
    assert devcaps.using_alt_proc_devices()


def test_missing_class_yields_none(tmp_path):
    p = tmp_path / "proc_devices"
    p.write_text("Character devices:\n  1 mem\n\nBlock devices:\n259 blkext\n")
    devcaps.configure_proc_devices_path(str(p))
    try:
        assert devcaps.get_char_device_major() is None
        assert devcaps.enumerate_channels(4) == []
    finally:
        devcaps.configure_proc_devices_path(None)


def test_block_section_not_scanned(tmp_path):
    # A class name appearing only under "Block devices:" must not match.
    p = tmp_path / "proc_devices"
    p.write_text("Character devices:\n  1 mem\n\nBlock devices:\n  8 tpu-slice-channels\n")
    devcaps.configure_proc_devices_path(str(p))
    try:
        assert devcaps.get_char_device_major() is None
    finally:
        devcaps.configure_proc_devices_path(None)


def test_channel_device_shape(proc_devices):
    chans = devcaps.enumerate_channels(3)
    assert [c.channel_id for c in chans] == [0, 1, 2]
    c = chans[1]
    assert c.path == "/dev/tpu-slice-channels/chan1"
    assert c.major == 511 and c.minor == 1
    node = c.to_cdi_node()
    assert node == {
        "path": "/dev/tpu-slice-channels/chan1",
        "type": "c",
        "major": 511,
        "minor": 1,
        "permissions": "rw",
    }


# -- multi-channel prepare ----------------------------------------------------


def _ready_agent(api, lib, cd, tmp_path):
    agent = SliceAgent(api, NS, cd.uid, "n0", "10.0.0.1", lib, str(tmp_path / "agent"))
    agent.startup()
    agent.sync()
    assert agent.check()
    return agent


def _with_channel(claim, channel_id, allocation_mode="All"):
    params = dict(claim.config[0].opaque.parameters)
    params["channel_id"] = channel_id
    params["allocation_mode"] = allocation_mode
    claim.config = [DeviceClaimConfig(
        source="claim",
        opaque=OpaqueDeviceConfig(driver=COMPUTE_DOMAIN_DRIVER_NAME, parameters=params),
    )]
    return claim


def test_prepare_injects_all_channel_nodes(cd_env, tmp_path, proc_devices):
    api, lib, driver, _ = cd_env
    cd = make_cd(api)
    agent = _ready_agent(api, lib, cd, tmp_path)
    try:
        claim = channel_claim(cd)
        res = driver.prepare_resource_claims([claim])[claim.uid]
        assert not isinstance(res, Exception), res
        spec = driver.cdi.read_claim_spec(claim.uid)
        nodes = spec["devices"][0]["containerEdits"]["deviceNodes"]
        assert len(nodes) == driver.max_channel_count
        assert nodes[0]["path"] == "/dev/tpu-slice-channels/chan0"
        assert nodes[0]["major"] == 511
        env = dict(e.split("=", 1) for e in spec["devices"][0]["containerEdits"]["env"])
        assert env["TPU_SLICE_CHANNEL_ID"] == "0"
    finally:
        agent.shutdown()


def test_prepare_single_mode_injects_one_node(cd_env, tmp_path, proc_devices):
    api, lib, driver, _ = cd_env
    cd = make_cd(api)
    agent = _ready_agent(api, lib, cd, tmp_path)
    try:
        claim = _with_channel(channel_claim(cd), 3, "Single")
        res = driver.prepare_resource_claims([claim])[claim.uid]
        assert not isinstance(res, Exception), res
        spec = driver.cdi.read_claim_spec(claim.uid)
        nodes = spec["devices"][0]["containerEdits"]["deviceNodes"]
        assert [n["path"] for n in nodes] == ["/dev/tpu-slice-channels/chan3"]
    finally:
        agent.shutdown()


def test_channel_conflict_across_claims(cd_env, tmp_path, proc_devices):
    api, lib, driver, _ = cd_env
    cd = make_cd(api)
    agent = _ready_agent(api, lib, cd, tmp_path)
    try:
        first = channel_claim(cd, name="wl-1")
        res = driver.prepare_resource_claims([first])[first.uid]
        assert not isinstance(res, Exception), res
        # Second claim on the same channel id: refused.
        second = channel_claim(cd, name="wl-2")
        res = driver.prepare_resource_claims([second])[second.uid]
        assert isinstance(res, PermanentError)
        assert "already allocated" in str(res)
        # A different channel id succeeds.
        third = _with_channel(channel_claim(cd, name="wl-3"), 1)
        res = driver.prepare_resource_claims([third])[third.uid]
        assert not isinstance(res, Exception), res
        # Releasing the first frees channel 0.
        driver.unprepare_resource_claims([first.uid])
        res = driver.prepare_resource_claims([second])[second.uid]
        assert not isinstance(res, Exception), res
    finally:
        agent.shutdown()


def test_channel_id_beyond_max_rejected(cd_env, tmp_path, proc_devices):
    api, lib, driver, _ = cd_env
    cd = make_cd(api)
    claim = _with_channel(channel_claim(cd), driver.max_channel_count)
    res = driver.prepare_resource_claims([claim])[claim.uid]
    assert isinstance(res, PermanentError)
    assert "max channel count" in str(res)


def test_no_kernel_class_degrades_to_env_only(cd_env, tmp_path):
    """Under the mock seam, a missing char class degrades to env-only."""
    api, lib, driver, _ = cd_env
    cd = make_cd(api)
    p = tmp_path / "proc_devices_empty"
    p.write_text("Character devices:\n  1 mem\n")
    devcaps.configure_proc_devices_path(str(p))
    agent = _ready_agent(api, lib, cd, tmp_path)
    try:
        claim = channel_claim(cd)
        res = driver.prepare_resource_claims([claim])[claim.uid]
        assert not isinstance(res, Exception), res
        spec = driver.cdi.read_claim_spec(claim.uid)
        assert "deviceNodes" not in spec["devices"][0]["containerEdits"]
    finally:
        devcaps.configure_proc_devices_path(None)
        agent.shutdown()


def test_missing_class_on_real_node_is_retryable(cd_env, tmp_path, monkeypatch):
    """Without the mock seam, a missing kernel channel class must fail the
    prepare retryably — never start a workload missing its channel device."""
    from k8s_dra_driver_tpu.plugins.computedomain.computedomain import RetryableError

    api, lib, driver, _ = cd_env
    cd = make_cd(api)
    agent = _ready_agent(api, lib, cd, tmp_path)
    monkeypatch.delenv(devcaps.ALT_PROC_DEVICES_ENV, raising=False)
    try:
        claim = channel_claim(cd)
        res = driver.prepare_resource_claims([claim])[claim.uid]
        assert isinstance(res, RetryableError)
        assert "not registered" in str(res)
    finally:
        agent.shutdown()


def test_legacy_checkpoint_entry_holds_channel_zero(cd_env, tmp_path, proc_devices):
    """Entries checkpointed before channel ids existed implicitly hold
    channel 0 — a post-upgrade claim must not double-allocate it."""
    api, lib, driver, _ = cd_env
    cd = make_cd(api)
    agent = _ready_agent(api, lib, cd, tmp_path)
    try:
        first = channel_claim(cd, name="old-claim")
        res = driver.prepare_resource_claims([first])[first.uid]
        assert not isinstance(res, Exception), res
        # Simulate a pre-upgrade checkpoint: no channel_id key in extra.
        cp = driver._get_checkpoint()
        for d in cp.claims[first.uid].devices:
            d.extra.pop("channel_id", None)
        driver._save_checkpoint(cp)
        second = channel_claim(cd, name="new-claim")
        res = driver.prepare_resource_claims([second])[second.uid]
        assert isinstance(res, PermanentError)
        assert "already allocated" in str(res)
    finally:
        agent.shutdown()
