#!/usr/bin/env bash
# Bring up a GKE cluster with real TPU node pools for the driver
# (reference demo/clusters/gke/create-cluster.sh analog, TPU-native):
# a single-host v5e pool for the quickstart specs and a multi-host
# v5e-16 pod-slice pool (4 hosts x 4 chips, --tpu-topology 4x4) for the
# ComputeDomain demos. DRA APIs are enabled on the control plane.
#
#   PROJECT_NAME=my-proj demo/clusters/gke/create-cluster.sh
#
# Env overrides: CLUSTER_NAME, REGION, NODE_VERSION, SINGLE_HOST_POOL_SIZE.
# Requires: gcloud with TPU quota in the chosen region.

set -euo pipefail

: "${PROJECT_NAME:=$(gcloud config list --format 'value(core.project)' 2>/dev/null)}"
if [[ -z ${PROJECT_NAME} ]]; then
  echo "Project name could not be determined; run 'gcloud config set project'"
  exit 1
fi

CLUSTER_NAME="${CLUSTER_NAME:-tpu-dra-cluster}"
NETWORK_NAME="${NETWORK_NAME:-${CLUSTER_NAME}-net}"
# v5e pod-slice machine types live in these zones; see
# https://cloud.google.com/tpu/docs/regions-zones
REGION="${REGION:-us-west4-a}"
NODE_VERSION="${NODE_VERSION:-1.34}"
SINGLE_HOST_POOL_SIZE="${SINGLE_HOST_POOL_SIZE:-1}"

gcloud compute networks create "${NETWORK_NAME}" \
  --quiet \
  --project="${PROJECT_NAME}" \
  --description="Network for the TPU DRA demo cluster" \
  --subnet-mode=auto \
  --bgp-routing-mode=regional

# resource.k8s.io is GA (v1) from 1.34; older control planes need the
# unstable-API enablement for the v1beta1 group the driver also speaks.
gcloud container clusters create "${CLUSTER_NAME}" \
  --quiet \
  --project "${PROJECT_NAME}" \
  --enable-kubernetes-unstable-apis="resource.k8s.io/v1beta1/deviceclasses,resource.k8s.io/v1beta1/resourceclaims,resource.k8s.io/v1beta1/resourceclaimtemplates,resource.k8s.io/v1beta1/resourceslices" \
  --release-channel=rapid \
  --no-enable-autorepair \
  --enable-autoupgrade \
  --region "${REGION}" \
  --num-nodes "1" \
  --network "${NETWORK_NAME}" \
  --cluster-version "${NODE_VERSION}" \
  --node-version "${NODE_VERSION}"

# Single-host v5e pool (ct5lp-hightpu-4t = 4 chips, 2x2): quickstart specs
# tpu-test1..5. The gke-no-default label keeps GKE's bundled TPU device
# plugin off these nodes so the DRA driver owns them.
gcloud container node-pools create "tpu-v5e-single" \
  --quiet \
  --project "${PROJECT_NAME}" \
  --cluster "${CLUSTER_NAME}" \
  --region "${REGION}" \
  --node-version "${NODE_VERSION}" \
  --machine-type "ct5lp-hightpu-4t" \
  --num-nodes "${SINGLE_HOST_POOL_SIZE}" \
  --enable-autoupgrade \
  --no-enable-autorepair \
  --node-labels=gke-no-default-tpu-device-plugin=true,tpu.google.com/present=true

# Multi-host v5e-16 pod slice (4 hosts x 4 chips, ICI-connected): the
# ComputeDomain demos. --tpu-topology makes GKE carve an ICI-coherent
# slice; node count must equal hosts-in-topology (16 chips / 4 per host).
gcloud container node-pools create "tpu-v5e-16-slice" \
  --quiet \
  --project "${PROJECT_NAME}" \
  --cluster "${CLUSTER_NAME}" \
  --region "${REGION}" \
  --node-version "${NODE_VERSION}" \
  --machine-type "ct5lp-hightpu-4t" \
  --tpu-topology "4x4" \
  --num-nodes "4" \
  --enable-autoupgrade \
  --no-enable-autorepair \
  --node-labels=gke-no-default-tpu-device-plugin=true,tpu.google.com/present=true

# NAT so TPU nodes (no external IPs) can pull images.
gcloud compute routers create "${NETWORK_NAME}-nat-router" \
  --quiet \
  --project "${PROJECT_NAME}" \
  --network "${NETWORK_NAME}" \
  --region "${REGION%-*}"

gcloud compute routers nats create "${NETWORK_NAME}-nat-config" \
  --quiet \
  --project "${PROJECT_NAME}" \
  --router "${NETWORK_NAME}-nat-router" \
  --router-region "${REGION%-*}" \
  --auto-allocate-nat-external-ips \
  --nat-all-subnet-ip-ranges

gcloud container clusters get-credentials "${CLUSTER_NAME}" \
  --project "${PROJECT_NAME}" --region "${REGION}"

echo "==> cluster ${CLUSTER_NAME} up; install the driver with:"
echo "    demo/clusters/gke/install-dra-driver-tpu.sh"
