#!/usr/bin/env bash
# Install the driver chart onto the current kubectl context (reference
# demo/clusters/gke/install-dra-driver-gpu.sh analog). Real TPU nodes: no
# mock seam; node selection and tolerations come from values.yaml
# (cloud.google.com/gke-tpu-accelerator selector, google.com/tpu toleration).
#
#   IMAGE_REGISTRY=gcr.io/my-proj IMAGE_TAG=0.1.0 \
#     demo/clusters/gke/install-dra-driver-tpu.sh

set -euo pipefail

REPO="$(cd "$(dirname "${BASH_SOURCE[0]}")/../../.." && pwd)"

: "${IMAGE_REGISTRY:=tpu-dra-driver}"   # registry/name prefix
: "${IMAGE_NAME:=tpu-dra-driver}"
: "${IMAGE_TAG:=0.1.0}"
: "${RELEASE:=tpu-dra}"
: "${NAMESPACE:=tpu-dra-driver}"
: "${FEATURE_GATES:=}"                  # e.g. "DynamicSubslice=true,ICIPartitioning=true"

repository="${IMAGE_REGISTRY}"
[[ "${IMAGE_REGISTRY}" != */* ]] || repository="${IMAGE_REGISTRY}/${IMAGE_NAME}"

helm upgrade --install "${RELEASE}" \
  "${REPO}/deployments/helm/tpu-dra-driver" \
  --namespace "${NAMESPACE}" --create-namespace \
  --set image.repository="${repository}" \
  --set image.tag="${IMAGE_TAG}" \
  --set image.pullPolicy=Always \
  --set featureGates="${FEATURE_GATES}"

kubectl -n "${NAMESPACE}" rollout status ds -l app.kubernetes.io/instance="${RELEASE}" --timeout=300s || true
kubectl get deviceclasses
echo "==> try: kubectl apply -f ${REPO}/demo/specs/quickstart/tpu-test1.yaml"
