#!/usr/bin/env bash
# Tear down everything create-cluster.sh made (reference
# demo/clusters/gke/delete-cluster.sh analog).

set -euo pipefail

: "${PROJECT_NAME:=$(gcloud config list --format 'value(core.project)' 2>/dev/null)}"
if [[ -z ${PROJECT_NAME} ]]; then
  echo "Project name could not be determined; run 'gcloud config set project'"
  exit 1
fi

CLUSTER_NAME="${CLUSTER_NAME:-tpu-dra-cluster}"
NETWORK_NAME="${NETWORK_NAME:-${CLUSTER_NAME}-net}"
REGION="${REGION:-us-west4-a}"

gcloud container clusters delete "${CLUSTER_NAME}" \
  --quiet --project "${PROJECT_NAME}" --region "${REGION}" || true

gcloud compute routers nats delete "${NETWORK_NAME}-nat-config" \
  --quiet --project "${PROJECT_NAME}" \
  --router "${NETWORK_NAME}-nat-router" --router-region "${REGION%-*}" || true

gcloud compute routers delete "${NETWORK_NAME}-nat-router" \
  --quiet --project "${PROJECT_NAME}" --region "${REGION%-*}" || true

gcloud compute networks delete "${NETWORK_NAME}" \
  --quiet --project "${PROJECT_NAME}" || true
