#!/usr/bin/env bash
# One command from clone to a Running claimed pod, no docker/kind needed:
# boots the simulated cluster (mock TPU hosts + the real driver control
# loops), applies quickstart tpu-test1 with tpu-kubectl, and waits for the
# claimed pod to run with its injected TPU devices/env. The hardware-free
# twin of demo/clusters/kind/create-cluster.sh.
#
#   demo/clusters/local/up.sh                 # v5e-4, one host
#   PROFILE=v5e-16 demo/clusters/local/up.sh  # 4 mock hosts
#   KEEP=1 .../up.sh                          # leave the cluster running

set -euo pipefail

REPO="$(cd "$(dirname "${BASH_SOURCE[0]}")/../../.." && pwd)"
export PYTHONPATH="$REPO"
PY="${PYTHON:-python}"
PROFILE="${PROFILE:-v5e-4}"

# Mock slice-channel char class (the reference CI's ALT_PROC_DEVICES seam).
procdev="$(mktemp)"
printf 'Character devices:\n511 tpu-slice-channels\n\nBlock devices:\n' > "$procdev"
export TPU_DRA_ALT_PROC_DEVICES="$procdev"

logf="$(mktemp)"
$PY -m k8s_dra_driver_tpu.sim --port 0 --profile "$PROFILE" > "$logf" 2>&1 &
SIM_PID=$!
cleanup() {
  if [ -z "${KEEP:-}" ]; then
    kill "$SIM_PID" 2>/dev/null || true
    rm -f "$procdev" "$logf"
  fi
}
trap cleanup EXIT

for _ in $(seq 1 100); do
  grep -q "cluster up at" "$logf" && break
  kill -0 "$SIM_PID" 2>/dev/null || { echo "cluster died:"; cat "$logf"; exit 1; }
  sleep 0.1
done
# Same extraction the shell-tier harness uses (tests/shell/helpers.sh).
SERVER="$(grep -o 'http://[^ ]*' "$logf" | head -1)"
if [ -z "$SERVER" ]; then
  echo "cluster did not come up in time:"; cat "$logf"; exit 1
fi
export TPU_KUBECTL_SERVER="$SERVER"
echo "==> cluster up at $SERVER ($PROFILE)"

KUBECTL="$PY -m k8s_dra_driver_tpu.sim.kubectl"
$KUBECTL get resourceslices
echo "==> applying quickstart tpu-test1"
$KUBECTL apply -f "$REPO/demo/specs/quickstart/tpu-test1.yaml"
$KUBECTL wait pod pod0 -n tpu-test1 --for=Running --timeout=60
echo "==> claimed pod:"
$KUBECTL get pods -n tpu-test1
$KUBECTL get pod pod0 -n tpu-test1 -o json | $PY -c '
import json, sys
pod = json.load(sys.stdin)[0]
print("injected devices:", pod.get("injected_devices"))
env = pod.get("injected_env", {})
print("injected env:", {k: env[k] for k in sorted(env) if k.startswith("TPU_")})
'
echo "OK: claimed pod Running"
if [ -n "${KEEP:-}" ]; then
  echo "cluster left running at $SERVER (pid $SIM_PID); kill it when done"
fi
