#!/usr/bin/env bash
# One command from clone to a Running claimed pod on a kind cluster with
# mock TPUs — the reference's demo/clusters/kind + hack/ci/mock-nvml
# bring-up (/root/reference/hack/ci/mock-nvml/e2e-test.sh analog).
#
#   demo/clusters/kind/create-cluster.sh            # build, install, test
#   CLUSTER_NAME=x PROFILE=v5e-16 .../create-cluster.sh
#
# Requires: docker, kind, kubectl, helm. Kubernetes >= 1.34 (resource.k8s.io
# v1) or 1.32+ with the v1beta1 feature gates; DRA must be enabled.

set -euo pipefail

REPO="$(cd "$(dirname "${BASH_SOURCE[0]}")/../../.." && pwd)"
CLUSTER_NAME="${CLUSTER_NAME:-tpu-dra}"
IMAGE="${IMAGE:-tpu-dra-driver:0.1.0}"
PROFILE="${PROFILE:-v5e-4}"      # mock topology each "TPU node" reports
RELEASE="${RELEASE:-tpu-dra}"
NAMESPACE="${NAMESPACE:-tpu-dra-driver}"

echo "==> building driver image ${IMAGE}"
docker build -t "${IMAGE}" -f "${REPO}/deployments/container/Dockerfile" "${REPO}"

if ! kind get clusters 2>/dev/null | grep -qx "${CLUSTER_NAME}"; then
  echo "==> creating kind cluster ${CLUSTER_NAME} (DRA enabled)"
  kind create cluster --name "${CLUSTER_NAME}" --config \
    "${REPO}/demo/clusters/kind/kind-config.yaml"
fi

echo "==> loading image into kind"
kind load docker-image "${IMAGE}" --name "${CLUSTER_NAME}"

echo "==> installing chart with the mock TPU seam (${PROFILE})"
# Last-colon split so registry-qualified names (localhost:5000/x:tag) work.
IMAGE_TAG="${IMAGE##*:}"
IMAGE_REPO="${IMAGE%:*}"
helm upgrade --install "${RELEASE}" \
  "${REPO}/deployments/helm/tpu-dra-driver" \
  --namespace "${NAMESPACE}" --create-namespace \
  --set image.repository="${IMAGE_REPO}" \
  --set image.tag="${IMAGE_TAG}" \
  --set kubeletPlugin.altTpuTopology="${PROFILE}" \
  --set nodeSelector=null \
  --wait --timeout 5m

echo "==> waiting for published ResourceSlices"
ok=""
for _ in $(seq 1 60); do
  n="$(kubectl get resourceslices -o name 2>/dev/null | wc -l)"
  if [ "${n}" -ge 1 ]; then ok=1; break; fi
  sleep 2
done
if [ -z "${ok}" ]; then
  echo "ERROR: driver published no ResourceSlices; plugin logs:"
  kubectl logs -n "${NAMESPACE}" -l app.kubernetes.io/component=kubelet-plugin \
    --tail=50 || true
  exit 1
fi
kubectl get resourceslices

echo "==> running the mock quickstart (claimed pod -> Succeeded)"
kubectl apply -f "${REPO}/demo/clusters/kind/tpu-test-mock.yaml"
kubectl wait --for=jsonpath='{.status.phase}'=Succeeded pod/pod0 \
  -n tpu-test-mock --timeout=300s
kubectl logs pod0 -n tpu-test-mock || true
echo "OK: claimed pod ran to completion on ${CLUSTER_NAME}"
echo "    (on real TPU nodes, apply demo/specs/quickstart/tpu-test1.yaml"
echo "     with a jax-equipped image instead)"
