#!/usr/bin/env bash
# Run the full CI gate locally — the same steps the GitHub workflows
# declare (.github/workflows/), so "CI passes" is reproducible without
# GitHub (reference precedent: hack/ci/mock-nvml/e2e-test.sh is runnable
# both ways).
#
#   hack/ci/run-local.sh                 # native + unit + sim e2e + shell + helm
#   RUN_KIND=1 hack/ci/run-local.sh      # also the kind mock-cluster tier
#   hack/ci/run-local.sh unit-tests helm-render   # just these steps
set -euo pipefail
HERE="$(cd "$(dirname "${BASH_SOURCE[0]}")" && pwd)"

DEFAULT_STEPS=(basic-checks native unit-tests sim-e2e shell-e2e helm-render)
if [ "${RUN_KIND:-0}" = "1" ]; then
  DEFAULT_STEPS+=(kind-mock-e2e)
fi
if [ "$#" -gt 0 ]; then
  STEPS=("$@")
else
  STEPS=("${DEFAULT_STEPS[@]}")
fi

failed=()
for step in "${STEPS[@]}"; do
  script="${HERE}/steps/${step}.sh"
  if [ ! -f "${script}" ]; then
    echo "ERROR: unknown step '${step}' (have: $(ls "${HERE}/steps" | sed 's/\.sh$//' | tr '\n' ' '))"
    exit 2
  fi
  echo
  echo "=== CI step: ${step} ==="
  if ! bash "${script}"; then
    failed+=("${step}")
    echo "FAIL: ${step}"
  fi
done

echo
if [ "${#failed[@]}" -gt 0 ]; then
  echo "CI FAILED: ${failed[*]}"
  exit 1
fi
echo "CI PASSED: ${STEPS[*]}"
