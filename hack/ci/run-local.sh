#!/usr/bin/env bash
# Run the full CI gate locally — the same steps the GitHub workflows
# declare (.github/workflows/), so "CI passes" is reproducible without
# GitHub (reference precedent: hack/ci/mock-nvml/e2e-test.sh is runnable
# both ways).
#
#   hack/ci/run-local.sh                 # native + unit + sim e2e + shell + helm
#   RUN_KIND=1 hack/ci/run-local.sh      # also the kind mock-cluster tier
#   hack/ci/run-local.sh unit-tests helm-render   # just these steps
set -euo pipefail
HERE="$(cd "$(dirname "${BASH_SOURCE[0]}")" && pwd)"

DEFAULT_STEPS=(basic-checks native unit-tests sim-e2e shell-e2e helm-render)
if [ "${RUN_KIND:-0}" = "1" ]; then
  DEFAULT_STEPS+=(kind-mock-e2e)
fi
if [ "$#" -gt 0 ]; then
  STEPS=("$@")
else
  STEPS=("${DEFAULT_STEPS[@]}")
fi

failed=()
skipped=()
for step in "${STEPS[@]}"; do
  script="${HERE}/steps/${step}.sh"
  if [ ! -f "${script}" ]; then
    echo "ERROR: unknown step '${step}' (have: $(ls "${HERE}/steps" | sed 's/\.sh$//' | tr '\n' ' '))"
    exit 2
  fi
  echo
  echo "=== CI step: ${step} ==="
  rc=0
  bash "${script}" || rc=$?
  if [ "${rc}" -eq 75 ]; then
    # EX_TEMPFAIL: the step declined to run (missing prerequisites).
    # Reported distinctly — a pass line that hides unrun tiers is how
    # "green CI" stops meaning anything.
    skipped+=("${step}")
    echo "SKIP: ${step}"
  elif [ "${rc}" -ne 0 ]; then
    failed+=("${step}")
    echo "FAIL: ${step}"
  fi
done

echo
if [ "${#failed[@]}" -gt 0 ]; then
  echo "CI FAILED: ${failed[*]}"
  [ "${#skipped[@]}" -gt 0 ] && echo "CI SKIPPED (did not run): ${skipped[*]}"
  exit 1
fi
if [ "${#skipped[@]}" -gt 0 ]; then
  ran=()
  for step in "${STEPS[@]}"; do
    case " ${skipped[*]} " in *" ${step} "*) ;; *) ran+=("${step}");; esac
  done
  echo "CI PASSED WITH SKIPS — ran: ${ran[*]:-none}; SKIPPED (did not run): ${skipped[*]}"
else
  echo "CI PASSED: ${STEPS[*]}"
fi
