#!/usr/bin/env bash
# CI step: the kind mock-cluster e2e — image build, DRA-enabled kind
# cluster, Helm install with the ALT_TPU_TOPOLOGY mock seam, claimed pod
# runs to completion (the reference's mock-NVML kind e2e,
# /root/reference/.github/workflows/mock-nvml-e2e.yaml:42-83 +
# hack/ci/mock-nvml/e2e-test.sh).
set -euo pipefail
REPO="$(cd "$(dirname "${BASH_SOURCE[0]}")/../../.." && pwd)"

for tool in docker kind kubectl helm; do
  if ! command -v "${tool}" >/dev/null 2>&1; then
    echo "SKIP: ${tool} not installed (kind tier needs docker+kind+kubectl+helm)"
    exit 0
  fi
done

export CLUSTER_NAME="${KIND_CLUSTER_NAME:-tpu-dra-ci}"
cleanup() {
  if [ "${KEEP_CLUSTER:-}" != "1" ]; then
    "${REPO}/demo/clusters/kind/delete-cluster.sh" || true
  fi
}
trap cleanup EXIT
"${REPO}/demo/clusters/kind/create-cluster.sh"
echo "OK: kind mock e2e"
