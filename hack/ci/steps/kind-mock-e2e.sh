#!/usr/bin/env bash
# CI step: the kind mock-cluster e2e — image build, DRA-enabled kind
# cluster, Helm install with the ALT_TPU_TOPOLOGY mock seam, claimed pod
# runs to completion (the reference's mock-NVML kind e2e,
# /root/reference/.github/workflows/mock-nvml-e2e.yaml:42-83 +
# hack/ci/mock-nvml/e2e-test.sh).
set -euo pipefail
REPO="$(cd "$(dirname "${BASH_SOURCE[0]}")/../../.." && pwd)"

# Missing prerequisites are a LOUD skip (exit 75, EX_TEMPFAIL): the
# runner reports the step as SKIPPED — never as green — so a CI pass
# can't silently mean "the kind tier didn't run" (it did exactly that
# until round 5). The chart-as-executed pytest tier
# (tests/test_chart_executed.py, in the unit-tests step) covers the
# chart command/env composition without docker meanwhile.
for tool in docker kind kubectl helm; do
  if ! command -v "${tool}" >/dev/null 2>&1; then
    echo "SKIPPED: ${tool} not installed (kind tier needs docker+kind+kubectl+helm)" >&2
    exit 75
  fi
done

export CLUSTER_NAME="${KIND_CLUSTER_NAME:-tpu-dra-ci}"
cleanup() {
  if [ "${KEEP_CLUSTER:-}" != "1" ]; then
    "${REPO}/demo/clusters/kind/delete-cluster.sh" || true
  fi
}
trap cleanup EXIT
"${REPO}/demo/clusters/kind/create-cluster.sh"
echo "OK: kind mock e2e"
