#!/usr/bin/env bash
# CI step: build the native pieces (libtpulib / libtpupart / tpu-slice-ctl).
set -euo pipefail
REPO="$(cd "$(dirname "${BASH_SOURCE[0]}")/../../.." && pwd)"
GEN="${CMAKE_GENERATOR:-Ninja}"
command -v ninja >/dev/null 2>&1 || GEN="Unix Makefiles"
cmake -S "${REPO}/native" -B "${REPO}/native/build" -G "${GEN}"
cmake --build "${REPO}/native/build"
echo "OK: native build"
