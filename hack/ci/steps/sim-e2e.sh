#!/usr/bin/env bash
# CI step: the simulated-cluster e2e tier — every shipped quickstart and
# ComputeDomain manifest against real plugin/controller/daemon code over
# mock tpulib (the mock-NVML kind run's cheaper sibling; the kind step
# covers the containerized path).
set -euo pipefail
REPO="$(cd "$(dirname "${BASH_SOURCE[0]}")/../../.." && pwd)"
cd "${REPO}"
"${PYTHON:-python}" -m k8s_dra_driver_tpu.e2e
echo "OK: sim e2e"
