#!/usr/bin/env bash
# CI step: the shell scenario tier (tests/shell/*.sh, the bats-suite
# analog) plus the local cluster bring-up — run through their pytest
# wrapper so skips/timeouts behave identically to `make test`.
set -euo pipefail
REPO="$(cd "$(dirname "${BASH_SOURCE[0]}")/../../.." && pwd)"
cd "${REPO}"
"${PYTHON:-python}" -m pytest tests/test_shell_e2e.py -x -q
echo "OK: shell e2e"
