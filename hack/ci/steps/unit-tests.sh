#!/usr/bin/env bash
# CI step: the unit/integration pytest tier (SURVEY.md §4.1 analog).
set -euo pipefail
REPO="$(cd "$(dirname "${BASH_SOURCE[0]}")/../../.." && pwd)"
cd "${REPO}"
"${PYTHON:-python}" -m pytest tests/ -x -q
echo "OK: unit tests"
