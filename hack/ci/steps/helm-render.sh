#!/usr/bin/env bash
# CI step: Helm chart validation. Always runs the in-repo renderer
# (tests/test_helm_chart.py — works without a helm binary); when `helm` is
# installed, also lints and templates the chart for real.
set -euo pipefail
REPO="$(cd "$(dirname "${BASH_SOURCE[0]}")/../../.." && pwd)"
cd "${REPO}"
"${PYTHON:-python}" -m pytest tests/test_helm_chart.py -x -q
if command -v helm >/dev/null 2>&1; then
  helm lint deployments/helm/tpu-dra-driver
  helm template tpu-dra deployments/helm/tpu-dra-driver >/dev/null
  echo "OK: helm lint+template"
else
  echo "OK: chart render-validated (helm binary not present; skipped lint)"
fi
