#!/usr/bin/env bash
# CI step: fast hygiene — Python byte-compiles, shell parses, YAML loads,
# VERSION is a valid semver. No test execution; see unit-tests.sh for
# that (and native.sh for the cmake configure/build).
#
#   basic-checks.sh            # everything
#   basic-checks.sh version    # just the VERSION semver check (used by
#                              # the release-automation workflow so the
#                              # regex lives in exactly one place)
set -euo pipefail
REPO="$(cd "$(dirname "${BASH_SOURCE[0]}")/../../.." && pwd)"
cd "${REPO}"

check_version() {
  grep -Eq '^v[0-9]+\.[0-9]+\.[0-9]+(-[0-9A-Za-z.-]+)?$' VERSION \
    || { echo "VERSION '$(cat VERSION)' is not vX.Y.Z[-suffix]"; exit 1; }
}

if [ "${1:-}" = "version" ]; then
  check_version
  echo "OK: VERSION format"
  exit 0
fi

echo "-- python compiles"
"${PYTHON:-python}" -m compileall -q k8s_dra_driver_tpu tests bench.py __graft_entry__.py

echo "-- shell parses"
find tests/shell hack demo/clusters -name '*.sh' -print0 \
  | xargs -0 -n1 bash -n

echo "-- yaml loads"
"${PYTHON:-python}" - <<'EOF'
import glob
import sys

import yaml

paths = (glob.glob("demo/specs/**/*.yaml", recursive=True)
         + glob.glob(".github/workflows/*.yaml")
         + glob.glob("deployments/helm/*/crds/*.yaml"))
assert paths, "no YAML found — glob roots moved?"
for p in paths:
    with open(p, encoding="utf-8") as f:
        list(yaml.safe_load_all(f))
print(f"   {len(paths)} files ok")
EOF

echo "-- tpulint invariants (incl. metrics/event-reason docs)"
"${PYTHON:-python}" -m k8s_dra_driver_tpu.analysis

echo "-- tpusan concurrency sanitizer (fixture self-test + scenario sweep)"
env JAX_PLATFORMS=cpu "${PYTHON:-python}" -m k8s_dra_driver_tpu.analysis.sanitizer --seeds 3

echo "-- VERSION is semver"
check_version

echo "OK: basic checks"
