#!/usr/bin/env python
"""tpulint alias — the AST invariant analyzer lives in
``k8s_dra_driver_tpu/analysis``; this shim only fixes up sys.path so
``python hack/tpulint.py`` works from anywhere in the checkout.

    python hack/tpulint.py               # whole package, committed baseline
    python hack/tpulint.py --list-rules
    python hack/tpulint.py --select store-scan k8s_dra_driver_tpu/sim
"""

from __future__ import annotations

import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from k8s_dra_driver_tpu.analysis.cli import main  # noqa: E402

if __name__ == "__main__":
    sys.exit(main())
