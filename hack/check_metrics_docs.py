#!/usr/bin/env python
"""Compatibility shim — the metrics/docs consistency check is now the
``metrics-docs`` rule of the tpulint engine (k8s_dra_driver_tpu/analysis),
which parses registrations with ``ast`` instead of regex and reports
file:line findings. Kept so existing muscle memory and CI references keep
working:

    python hack/check_metrics_docs.py    ==    hack/tpulint.py --select metrics-docs
"""

from __future__ import annotations

import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from k8s_dra_driver_tpu.analysis.cli import main  # noqa: E402

if __name__ == "__main__":
    sys.exit(main(["--select", "metrics-docs"] + sys.argv[1:]))
