#!/usr/bin/env python
"""Fail CI when a registered metric is missing from docs/reference/metrics.md.

Scans every Python file in the package for Counter/Gauge/Histogram
constructions with a literal metric name (the only way metrics are
registered in this codebase) and asserts each name appears in the metrics
reference page. The inverse direction — documented names no code
registers — is reported as a warning, not a failure: prose may legitimately
reference derived series (`*_bucket`, `*_sum`, `*_count`).

Run directly or via `make verify`:

    python hack/check_metrics_docs.py
"""

from __future__ import annotations

import os
import re
import sys

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
PACKAGE = os.path.join(REPO, "k8s_dra_driver_tpu")
DOC = os.path.join(REPO, "docs", "reference", "metrics.md")

# A metric registration: Counter("name", ...), Gauge("name", ...),
# Histogram("name", ...) — first positional arg is always the literal name.
METRIC_RE = re.compile(
    r"\b(?:Counter|Gauge|Histogram)\(\s*[\"']([a-zA-Z_:][a-zA-Z0-9_:]*)[\"']"
)

# Documented metric names: every `backtick_quoted_identifier` that looks
# like a metric (our namespace prefix).
DOC_NAME_RE = re.compile(r"`(tpu_dra_[a-zA-Z0-9_:]*)`")


def registered_metrics() -> dict:
    """metric name -> [files that register it]."""
    found: dict = {}
    for dirpath, _dirnames, filenames in os.walk(PACKAGE):
        for fn in filenames:
            if not fn.endswith(".py"):
                continue
            path = os.path.join(dirpath, fn)
            with open(path, encoding="utf-8") as f:
                src = f.read()
            for name in METRIC_RE.findall(src):
                found.setdefault(name, []).append(os.path.relpath(path, REPO))
    return found


def main() -> int:
    registered = registered_metrics()
    if not registered:
        print("error: no metric registrations found — scanner broken?",
              file=sys.stderr)
        return 2
    with open(DOC, encoding="utf-8") as f:
        body = f.read()
    documented = set(DOC_NAME_RE.findall(body))

    missing = {
        name: files for name, files in sorted(registered.items())
        if f"`{name}`" not in body
    }
    if missing:
        print(f"error: {len(missing)} metric(s) registered in the package "
              f"but missing from docs/reference/metrics.md:", file=sys.stderr)
        for name, files in missing.items():
            print(f"  {name}  (registered in {', '.join(sorted(set(files)))})",
                  file=sys.stderr)
        return 1

    base = set(registered)
    derived_suffixes = ("_bucket", "_sum", "_count")
    stale = {
        name for name in documented
        if name not in base
        and not any(name.endswith(s) and name[: -len(s)] in base
                    for s in derived_suffixes)
    }
    if stale:
        print(f"warning: {len(stale)} documented metric name(s) no code "
              f"registers: {', '.join(sorted(stale))}")

    print(f"ok: {len(registered)} registered metric(s), all documented")
    return 0


if __name__ == "__main__":
    sys.exit(main())
