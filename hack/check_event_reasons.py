#!/usr/bin/env python
"""Compatibility shim — the event-reason audit is now the
``event-reasons`` rule of the tpulint engine (k8s_dra_driver_tpu/analysis):
AST-parsed REASON_* constants and literal ``reason=`` kwargs, CamelCase +
documented in docs/reference/events.md. Kept so existing muscle memory
and CI references keep working:

    python hack/check_event_reasons.py   ==    hack/tpulint.py --select event-reasons
"""

from __future__ import annotations

import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from k8s_dra_driver_tpu.analysis.cli import main  # noqa: E402

if __name__ == "__main__":
    sys.exit(main(["--select", "event-reasons"] + sys.argv[1:]))
