#!/usr/bin/env python
"""Fail CI when an Event reason string is malformed or undocumented.

Same contract as check_metrics_docs.py, for the event plane: every reason
an actor can emit must be (a) CamelCase — the kubectl-ecosystem convention
Events are grepped and alerted on — and (b) catalogued in
docs/reference/events.md so operators can look a reason up.

Reasons are found two ways:
- the canonical ``REASON_* = "..."`` constants in ``pkg/events.py`` (the
  only sanctioned source for recorder calls), and
- any literal ``reason="..."`` keyword argument anywhere in the package,
  catching call sites that bypass the catalog.

Run directly or via `make verify`:

    python hack/check_event_reasons.py
"""

from __future__ import annotations

import os
import re
import sys

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
PACKAGE = os.path.join(REPO, "k8s_dra_driver_tpu")
DOC = os.path.join(REPO, "docs", "reference", "events.md")

CONSTANT_RE = re.compile(r"^REASON_[A-Z0-9_]+\s*=\s*[\"']([^\"']+)[\"']",
                         re.MULTILINE)
KWARG_RE = re.compile(r"\breason=[\"']([^\"']+)[\"']")
CAMEL_RE = re.compile(r"^[A-Z][A-Za-z0-9]*$")


def emitted_reasons() -> dict:
    """reason string -> [files that emit/define it]."""
    found: dict = {}
    for dirpath, _dirnames, filenames in os.walk(PACKAGE):
        for fn in filenames:
            if not fn.endswith(".py"):
                continue
            path = os.path.join(dirpath, fn)
            with open(path, encoding="utf-8") as f:
                src = f.read()
            rel = os.path.relpath(path, REPO)
            for rx in (CONSTANT_RE, KWARG_RE):
                for name in rx.findall(src):
                    found.setdefault(name, []).append(rel)
    return found


def main() -> int:
    reasons = emitted_reasons()
    if not reasons:
        print("error: no event reasons found — scanner broken?",
              file=sys.stderr)
        return 2
    try:
        with open(DOC, encoding="utf-8") as f:
            body = f.read()
    except FileNotFoundError:
        print(f"error: {DOC} missing", file=sys.stderr)
        return 2

    bad = 0
    for name, files in sorted(reasons.items()):
        where = ", ".join(sorted(set(files)))
        if not CAMEL_RE.match(name):
            print(f"error: reason {name!r} is not CamelCase ({where})",
                  file=sys.stderr)
            bad += 1
        if f"`{name}`" not in body:
            print(f"error: reason {name!r} missing from "
                  f"docs/reference/events.md ({where})", file=sys.stderr)
            bad += 1
    if bad:
        return 1
    print(f"ok: {len(reasons)} event reason(s), all CamelCase and documented")
    return 0


if __name__ == "__main__":
    sys.exit(main())
