"""Real tpulib backend — C++ shim over /dev + sysfs, GKE TPU VM env conventions.

The native library does the kernel-facing scan (native/tpulib.cc); this
module binds it with ctypes (the cgo analog, explicit library path like the
reference's nvml.New(libpath), /root/reference/cmd/gpu-kubelet-plugin/
nvlib.go:57-103), merges in slice identity from the TPU VM environment
(TPU_ACCELERATOR_TYPE, TPU_TOPOLOGY, TPU_WORKER_ID, TPU_WORKER_HOSTNAMES —
the conventions libtpu itself consumes), and falls back to a pure-Python
scan when the shared library isn't built.
"""

from __future__ import annotations

import ctypes
import json
import logging
import os
import re
import threading
import time
from typing import Callable, Dict, List, Optional

from k8s_dra_driver_tpu.tpulib.profiles import GENS, compute_subslice_profiles
from k8s_dra_driver_tpu.tpulib.types import (
    ChipHealth,
    ChipInfo,
    HostInventory,
    TpuGen,
    format_topology,
    parse_topology,
    topology_chips,
)

log = logging.getLogger(__name__)

TPULIB_PATH_ENV = "TPULIB_PATH"
ALT_TPU_DEV_ROOT_ENV = "ALT_TPU_DEV_ROOT"
ALT_TPU_SYSFS_ROOT_ENV = "ALT_TPU_SYSFS_ROOT"
HEALTH_POLL_SECONDS_ENV = "TPU_HEALTH_POLL_SECONDS"
DEFAULT_HEALTH_POLL_S = 5.0

_DEFAULT_LIB_LOCATIONS = (
    os.path.join(os.path.dirname(__file__), "..", "..", "native", "build", "libtpulib.so"),
    "/usr/local/lib/libtpulib.so",
    "libtpulib.so",
)


def _load_shim(path: Optional[str] = None) -> Optional[ctypes.CDLL]:
    candidates = [path] if path else [os.environ.get(TPULIB_PATH_ENV), *_DEFAULT_LIB_LOCATIONS]
    for cand in candidates:
        if not cand:
            continue
        try:
            lib = ctypes.CDLL(os.path.abspath(cand) if os.path.sep in cand else cand)
        except OSError:
            continue
        lib.tpulib_enumerate.restype = ctypes.c_int
        lib.tpulib_enumerate.argtypes = [
            ctypes.c_char_p, ctypes.c_char_p, ctypes.c_char_p, ctypes.c_int,
        ]
        lib.tpulib_chip_health.restype = ctypes.c_int
        lib.tpulib_chip_health.argtypes = [ctypes.c_char_p, ctypes.c_int]
        lib.tpulib_version.restype = ctypes.c_char_p
        return lib
    return None


_ACCEL_RE = re.compile(r"^accel(\d+)$")


def _py_scan(dev_root: str, sysfs_root: str) -> List[dict]:
    """Pure-Python fallback mirroring native/tpulib.cc ScanChips."""
    chips = []
    try:
        entries = os.listdir(dev_root)
    except OSError:
        return chips
    for name in entries:
        m = _ACCEL_RE.match(name)
        if not m:
            continue
        idx = int(m.group(1))
        dev_path = os.path.join(dev_root, name)
        pci_dir = os.path.join(sysfs_root, "class", "accel", f"accel{idx}", "device")
        pci_address, numa, serial, vendor = "", 0, "", ""
        if os.path.exists(pci_dir):
            real = os.path.realpath(pci_dir)
            pci_address = os.path.basename(real)
            for fname, cast in (("numa_node", int), ("unique_id", str), ("vendor", str)):
                p = os.path.join(real, fname)
                if os.path.exists(p):
                    with open(p) as f:
                        v = f.read().strip()
                    if fname == "numa_node":
                        numa = max(0, cast(v))
                    elif fname == "unique_id":
                        serial = v
                    else:
                        vendor = v
        chips.append(
            {
                "index": idx,
                "dev_path": dev_path,
                "pci_address": pci_address,
                "numa_node": numa,
                "vendor": vendor,
                "serial": serial or pci_address or name,
                "vfio_group": "",
                # Existence, not readability: a busy/permission-denied node
                # is a live chip (single-open semantics).
                "openable": os.path.exists(dev_path),
            }
        )
    chips.sort(key=lambda c: c["index"])
    return chips


def _gen_from_accelerator_type(acc: str) -> TpuGen:
    acc = acc.lower()
    if acc.startswith("v5litepod") or acc.startswith("v5e"):
        return TpuGen.V5E
    if acc.startswith("v5p"):
        return TpuGen.V5P
    if acc.startswith("v6e") or acc.startswith("trillium"):
        return TpuGen.V6E
    if acc.startswith("v4"):
        return TpuGen.V4
    log.warning("unknown accelerator type %r, assuming v5e", acc)
    return TpuGen.V5E


class RealTpuLib:
    """Enumerates the actual host. Slice identity comes from the TPU VM env;
    a host with no slice env is treated as a single-host slice."""

    is_mock = False

    def __init__(
        self,
        lib_path: Optional[str] = None,
        dev_root: Optional[str] = None,
        sysfs_root: Optional[str] = None,
        env: Optional[Dict[str, str]] = None,
    ):
        self._lib = _load_shim(lib_path)
        self.dev_root = dev_root or os.environ.get(ALT_TPU_DEV_ROOT_ENV, "/dev")
        self.sysfs_root = sysfs_root or os.environ.get(ALT_TPU_SYSFS_ROOT_ENV, "/sys")
        self.env = dict(env) if env is not None else dict(os.environ)
        self.native = self._lib is not None
        self._health_listeners: List[Callable[[int, ChipHealth], None]] = []
        self._health_thread: Optional[threading.Thread] = None
        self._health_stop = threading.Event()
        self._health_known: Dict[int, ChipHealth] = {}
        self._enumerated_indexes: List[int] = []

    def shim_version(self) -> str:
        if self._lib is None:
            return "python-fallback"
        return self._lib.tpulib_version().decode()

    def _scan(self) -> List[dict]:
        if self._lib is None:
            return _py_scan(self.dev_root, self.sysfs_root)
        cap = 1 << 16
        while True:
            buf = ctypes.create_string_buffer(cap)
            n = self._lib.tpulib_enumerate(
                self.dev_root.encode(), self.sysfs_root.encode(), buf, cap
            )
            if n >= 0:
                return json.loads(buf.value.decode())["chips"]
            needed = -n
            if needed <= cap:
                raise RuntimeError(f"tpulib_enumerate error: {buf.value[:200]!r}")
            cap = needed

    def chip_health(self, index: int) -> ChipHealth:
        if self._lib is not None:
            rc = self._lib.tpulib_chip_health(self.dev_root.encode(), index)
            return ChipHealth.HEALTHY if rc == 0 else ChipHealth.UNHEALTHY
        path = os.path.join(self.dev_root, f"accel{index}")
        return ChipHealth.HEALTHY if os.path.exists(path) else ChipHealth.UNHEALTHY

    # -- utilization counters (libtpu runtime-metrics shim stubs) -----------

    def read_counters(self, now: Optional[float] = None) -> List["ChipCounters"]:
        """Per-chip HBM/duty/power/ICI counters from the native shim.

        The native seam is ``tpulib_read_counters`` (one JSON doc, same
        buffer-resize protocol as enumerate); until native/tpulib.cc grows
        it — it needs the libtpu runtime-metrics API or the device-tree
        performance counters, neither of which exists in this container —
        the symbol is absent and this returns ``[]``: "no telemetry", which
        samplers must treat as no data rather than zero load."""
        from k8s_dra_driver_tpu.tpulib.types import ChipCounters

        if self._lib is None or not hasattr(self._lib, "tpulib_read_counters"):
            return []
        cap = 1 << 16
        while True:
            buf = ctypes.create_string_buffer(cap)
            n = self._lib.tpulib_read_counters(self.dev_root.encode(), buf, cap)
            if n < 0:
                needed = -n
                if needed <= cap:
                    log.warning("tpulib_read_counters error: %r", buf.value[:200])
                    return []
                cap = needed
                continue
            docs = json.loads(buf.value.decode()).get("chips", [])
            ts = now if now is not None else time.time()
            return [
                ChipCounters(
                    index=int(d["index"]), timestamp=ts,
                    hbm_used_bytes=int(d.get("hbm_used_bytes", 0)),
                    hbm_total_bytes=int(d.get("hbm_total_bytes", 0)),
                    duty_cycle=float(d.get("duty_cycle", 0.0)),
                    power_watts=float(d.get("power_watts", 0.0)),
                )
                for d in docs
            ]

    # -- health events (NVML event-set analog) -------------------------------

    def watch_health(
        self,
        callback: Callable[[int, ChipHealth], None],
        poll_interval_s: Optional[float] = None,
    ) -> None:
        """Register callback(chip_index, health) and start the poller on
        first registration. The reference blocks on an NVML event set
        (device_health.go:103-274); the TPU kernel driver has no equivalent
        event fd, so this polls tpulib_chip_health for each enumerated chip
        (native shim when loaded) and fires callbacks on transitions.
        Interval from TPU_HEALTH_POLL_SECONDS (default 5s)."""
        self._health_listeners.append(callback)
        if self._health_thread is not None:
            return
        if poll_interval_s is None:
            try:
                poll_interval_s = float(
                    self.env.get(HEALTH_POLL_SECONDS_ENV, DEFAULT_HEALTH_POLL_S)
                )
            except ValueError:
                poll_interval_s = DEFAULT_HEALTH_POLL_S
        # Baseline every known chip as HEALTHY regardless of current state:
        # a chip that is already dead at watch start then fires an UNHEALTHY
        # transition on the first poll, so it gets tainted instead of being
        # silently grandfathered in as schedulable. The union with the last
        # enumeration covers chips whose device node vanished entirely
        # (they no longer appear in a fresh scan).
        indexes = {c["index"] for c in self._scan()} | set(self._enumerated_indexes)
        self._health_known = {i: ChipHealth.HEALTHY for i in indexes}
        self._health_stop.clear()
        self._health_thread = threading.Thread(
            target=self._health_poll_loop, args=(poll_interval_s,),
            name="tpu-health-watch", daemon=True,
        )
        self._health_thread.start()

    def stop_health_watch(self) -> None:
        self._health_stop.set()
        if self._health_thread is not None:
            self._health_thread.join(timeout=5)
            self._health_thread = None
        # Drop listeners: a later watch_health must not re-fire into
        # already-shut-down owners.
        self._health_listeners = []

    def _health_poll_loop(self, interval_s: float) -> None:
        # Immediate first pass so startup-dead chips surface without waiting
        # a full interval.
        while True:
            try:
                self._health_poll_once()
            except Exception:  # noqa: BLE001 — keep polling
                log.exception("health poll failed")
            if self._health_stop.wait(interval_s):
                return

    def _health_poll_once(self) -> None:
        for index, prev in list(self._health_known.items()):
            cur = self.chip_health(index)
            if cur == prev:
                continue
            log.warning("chip %d health %s -> %s", index, prev.value, cur.value)
            delivered = True
            for cb in list(self._health_listeners):
                try:
                    cb(index, cur)
                except Exception:  # noqa: BLE001 — isolate listeners
                    log.exception("health listener failed for chip %d", index)
                    delivered = False
            # Commit only after every listener accepted the event; a failed
            # delivery (e.g. apiserver briefly unreachable during the taint
            # republish) keeps the old state so the transition re-fires
            # next poll. Listeners must therefore be idempotent.
            if delivered:
                self._health_known[index] = cur

    def enumerate(self) -> HostInventory:
        raw = self._scan()
        self._enumerated_indexes = [c["index"] for c in raw]
        n_local = len(raw)

        acc_type = self.env.get("TPU_ACCELERATOR_TYPE", "")
        slice_topology = self.env.get("TPU_TOPOLOGY", "")
        worker_id = int(self.env.get("TPU_WORKER_ID", "0") or "0")
        hostnames = [h for h in self.env.get("TPU_WORKER_HOSTNAMES", "").split(",") if h]
        num_hosts = max(len(hostnames), 1)

        gen = _gen_from_accelerator_type(acc_type) if acc_type else TpuGen.V5E
        gspec = GENS[gen]

        if slice_topology:
            total = topology_chips(slice_topology)
            if n_local and total % n_local == 0 and num_hosts == 1:
                num_hosts = total // n_local
        else:
            # Host-only view: model the local chips as the entire slice.
            if n_local in (0, 1):
                slice_topology = "1x1"
            else:
                dims = (2, n_local // 2) if n_local % 2 == 0 else (1, n_local)
                slice_topology = format_topology(dims)
            num_hosts = 1

        host_topology = self._host_topology(slice_topology, n_local, num_hosts)

        chips: List[ChipInfo] = []
        for i, c in enumerate(raw):
            coords = self._local_coords(host_topology, i, worker_id, slice_topology)
            chips.append(
                ChipInfo(
                    index=c["index"],
                    dev_path=c["dev_path"],
                    pci_address=c["pci_address"],
                    gen=gen,
                    coords=coords,
                    serial=c["serial"],
                    hbm_bytes=gspec.hbm_bytes,
                    cores=gspec.cores_per_chip,
                    numa_node=c["numa_node"],
                    health=ChipHealth.HEALTHY if c.get("openable", True) else ChipHealth.UNHEALTHY,
                )
            )
        slice_uid = self.env.get("TPU_SLICE_UID", "") or (
            f"host-{chips[0].serial}" if chips else "host-empty"
        )
        return HostInventory(
            gen=gen,
            accelerator_type=acc_type or f"{gen.value}-{n_local}",
            slice_topology=slice_topology,
            host_topology=host_topology,
            worker_id=worker_id,
            num_hosts=num_hosts,
            chips=chips,
            links=[],
            subslice_profiles=compute_subslice_profiles(host_topology) if n_local else [],
            ici_domain=f"{slice_uid}.0",
            vfio_devices={
                c["index"]: f"/dev/vfio/{c['vfio_group']}" for c in raw if c.get("vfio_group")
            },
        )

    @staticmethod
    def _host_topology(slice_topology: str, n_local: int, num_hosts: int) -> str:
        if num_hosts == 1:
            return slice_topology
        if n_local == 4:
            return "2x2" if len(parse_topology(slice_topology)) == 2 else "2x2x1"
        if n_local == 1:
            return "1x1"
        if n_local == 8:
            return "2x4"
        return format_topology((1, max(n_local, 1)))

    @staticmethod
    def _local_coords(host_topology: str, i: int, worker_id: int, slice_topology: str):
        from k8s_dra_driver_tpu.tpulib.mock import _host_block_origin
        from k8s_dra_driver_tpu.tpulib.profiles import SliceProfile, host_chip_coords

        dims = parse_topology(host_topology)
        local = host_chip_coords(dims)[min(i, len(host_chip_coords(dims)) - 1)]
        local3 = local + (0,) * (3 - len(local))
        try:
            prof = SliceProfile("adhoc", TpuGen.V5E, "adhoc", slice_topology, host_topology)
            origin = _host_block_origin(prof, worker_id)
        except Exception:  # noqa: BLE001 — fall back to host-local coords
            origin = (0, 0, 0)
        origin3 = tuple(origin) + (0,) * (3 - len(origin))
        return tuple(o + c for o, c in zip(origin3, local3))
