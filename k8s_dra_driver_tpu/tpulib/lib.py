"""TpuLib interface + backend factory.

``new_tpulib()`` is the single construction point every binary uses
(plugins, daemon, CLI): mock when ``ALT_TPU_TOPOLOGY`` is set, real
otherwise — mirroring how the reference flips between real NVML and
mock-NVML via the driver root + ALT_PROC_DEVICES_PATH seams without any
code change (SURVEY.md §4.2).
"""

from __future__ import annotations

import os
from typing import List, Optional, Protocol, runtime_checkable

from k8s_dra_driver_tpu.tpulib.types import ChipCounters, ChipHealth, HostInventory

ALT_TPU_TOPOLOGY_ENV = "ALT_TPU_TOPOLOGY"


@runtime_checkable
class TpuLib(Protocol):
    def enumerate(self) -> HostInventory: ...

    def read_counters(self, now: Optional[float] = None) -> List[ChipCounters]:
        """Per-chip utilization counters (HBM used/total, compute duty
        cycle, power draw, per-ICI-link tx/rx/error counters) at sample
        time ``now`` (default: the backend's own clock). A backend with
        no counter source returns ``[]`` — samplers treat that as "no
        telemetry", never as zero load."""
        ...


def using_mock_tpulib(env: Optional[dict] = None) -> bool:
    env = env if env is not None else os.environ
    return bool(env.get(ALT_TPU_TOPOLOGY_ENV))


def new_tpulib(env: Optional[dict] = None) -> TpuLib:
    env = dict(env) if env is not None else dict(os.environ)
    profile = env.get(ALT_TPU_TOPOLOGY_ENV)
    if profile:
        from k8s_dra_driver_tpu.tpulib.mock import MockTpuLib

        return MockTpuLib(profile, env=env)
    from k8s_dra_driver_tpu.tpulib.real import RealTpuLib

    return RealTpuLib(env=env)
