"""tpulib — L0 device enumeration: the deviceLib analog.

The reference binds NVML via cgo (`deviceLib`,
/root/reference/cmd/gpu-kubelet-plugin/nvlib.go:43-103) with a mock-NVML
seam for CPU-only CI. Here the same split is:

- ``RealTpuLib``: backed by the C++ shim (native/tpulib.cc -> libtpulib.so,
  ctypes) that scans ``/dev/accel*`` / ``/dev/vfio`` and sysfs for Google
  TPU PCI functions, plus the GKE TPU VM environment conventions
  (TPU_ACCELERATOR_TYPE, TPU_TOPOLOGY, TPU_WORKER_ID, ...).
- ``MockTpuLib``: driven by named topology profiles (v5e-4, v5e-16, ...)
  selected via the ``ALT_TPU_TOPOLOGY`` env seam — the equivalent of the
  reference's ALT_PROC_DEVICES_PATH + mock-NVML profiles (SURVEY.md §4.2).

``new_tpulib()`` picks the backend: mock iff ALT_TPU_TOPOLOGY is set.
"""

from k8s_dra_driver_tpu.tpulib.types import (  # noqa: F401
    ChipCounters,
    ChipHealth,
    ChipInfo,
    HostInventory,
    LinkCounters,
    SubslicePlacement,
    SubsliceProfile,
    TpuGen,
)
from k8s_dra_driver_tpu.tpulib.loadtrace import (  # noqa: F401
    LoadTrace,
    LoadTraceError,
    parse_load_trace,
)
from k8s_dra_driver_tpu.tpulib.profiles import GENS, PROFILES, SliceProfile  # noqa: F401
from k8s_dra_driver_tpu.tpulib.lib import ALT_TPU_TOPOLOGY_ENV, TpuLib, new_tpulib  # noqa: F401
from k8s_dra_driver_tpu.tpulib.mock import MockTpuLib  # noqa: F401
from k8s_dra_driver_tpu.tpulib.real import RealTpuLib  # noqa: F401
