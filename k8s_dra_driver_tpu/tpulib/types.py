"""Core tpulib data model.

The TPU-native re-design of the reference's GpuInfo/MigDeviceInfo world
(/root/reference/cmd/gpu-kubelet-plugin/deviceinfo.go): chips instead of
GPUs, ICI subslices instead of MIG partitions, the ICI domain id instead of
the NVLink clique (clusterUUID.cliqueID,
/root/reference/cmd/compute-domain-kubelet-plugin/nvlib.go:196-364).
"""

from __future__ import annotations

import re
from dataclasses import dataclass, field
from enum import Enum
from typing import Dict, List, Optional, Tuple

Coord = Tuple[int, int, int]


class TpuGen(str, Enum):
    V4 = "v4"
    V5E = "v5e"
    V5P = "v5p"
    V6E = "v6e"


class ChipHealth(str, Enum):
    HEALTHY = "healthy"
    DEGRADED = "degraded"   # e.g. ICI link flap, correctable HBM errors
    UNHEALTHY = "unhealthy"  # device lost / uncorrectable


@dataclass(frozen=True)
class GenSpec:
    """Per-generation silicon facts (public numbers)."""

    gen: TpuGen
    hbm_bytes: int
    cores_per_chip: int
    topology_dims: int          # 2 for v5e/v6e meshes, 3 for v4/v5p tori
    peak_bf16_tflops: float
    ici_gbps_per_link: float    # per-direction per-link
    idle_watts: float = 50.0    # per-chip draw at zero duty
    peak_watts: float = 200.0   # per-chip draw at full duty


@dataclass(frozen=True)
class ChipInfo:
    """One TPU chip on this host."""

    index: int                   # host-local index; /dev/accel<index>
    dev_path: str                # /dev/accel0 ...
    pci_address: str             # 0000:00:04.0 style
    gen: TpuGen
    coords: Coord                # global coords within the slice
    serial: str
    hbm_bytes: int
    cores: int
    numa_node: int = 0
    health: ChipHealth = ChipHealth.HEALTHY

    @property
    def uuid(self) -> str:
        """Stable canonical identity, GPU-UUID analog."""
        return f"tpu-{self.gen.value}-{self.serial}"


@dataclass(frozen=True)
class LinkCounters:
    """Cumulative traffic/error counters for one intra-host ICI link,
    keyed by host-local chip endpoints (``a < b``). tx/rx are monotone
    byte counters; ``errors`` is the monotone CRC/replay error counter
    whose *rate* the health monitor thresholds into link degradation."""

    a: int
    b: int
    tx_bytes: int = 0
    rx_bytes: int = 0
    errors: int = 0

    @property
    def link_id(self) -> str:
        return f"{min(self.a, self.b)}-{max(self.a, self.b)}"


@dataclass(frozen=True)
class ChipCounters:
    """One chip's utilization counters at a sampling instant — the
    ``read_counters`` unit. Gauges (hbm/duty/power) are instantaneous;
    the per-link counters are cumulative so samplers compute rates from
    deltas like any hardware counter consumer."""

    index: int                   # host-local chip index
    timestamp: float             # trace/sample time the values describe
    hbm_used_bytes: int = 0
    hbm_total_bytes: int = 0
    duty_cycle: float = 0.0      # [0, 1] compute duty over the last tick
    power_watts: float = 0.0
    links: Tuple[LinkCounters, ...] = ()  # links this chip terminates (a == index)


@dataclass(frozen=True)
class IciLink:
    """A physical ICI link between two chips (by global coords)."""

    a: Coord
    b: Coord
    gbps: float
    wraparound: bool = False


@dataclass(frozen=True)
class SubslicePlacement:
    """A concrete placement of a subslice profile on this host's chip grid —
    the MIG placement analog (/root/reference/cmd/gpu-kubelet-plugin/mig.go:111-223).
    """

    profile: str                 # e.g. "1x2"
    start: Coord                 # host-local origin
    chip_indices: Tuple[int, ...]  # host-local chip indices consumed

    @property
    def name_suffix(self) -> str:
        # Keep as many origin coords as the profile has dims so 3D hosts
        # (v4/v5p) don't mint colliding names for placements differing in z.
        ndim = len(self.profile.split("x"))
        coords = "x".join(str(c) for c in self.start[:ndim])
        return f"{self.profile}-at-{coords}"


@dataclass(frozen=True)
class SubsliceProfile:
    """A subslice shape this host topology can carve out (MIG profile analog)."""

    name: str                    # "1x1", "1x2", "2x2", ...
    shape: Tuple[int, ...]
    chips: int
    placements: Tuple[SubslicePlacement, ...] = ()


@dataclass
class HostInventory:
    """Everything tpulib knows about this host — the result of enumeration,
    `GetPerGpuAllocatableDevices` analog (/root/reference/cmd/gpu-kubelet-plugin/nvlib.go:205-348).
    """

    gen: TpuGen
    accelerator_type: str        # e.g. "v5litepod-16"
    slice_topology: str          # e.g. "4x4" — the whole (multi-host) slice
    host_topology: str           # e.g. "2x2" — this host's chips
    worker_id: int               # index of this host within the slice
    num_hosts: int
    chips: List[ChipInfo] = field(default_factory=list)
    links: List[IciLink] = field(default_factory=list)
    subslice_profiles: List[SubsliceProfile] = field(default_factory=list)
    ici_domain: str = ""         # sliceUUID.partition — clique-id analog
    vfio_devices: Dict[int, str] = field(default_factory=dict)  # chip idx -> /dev/vfio/<grp>

    @property
    def chips_per_host(self) -> int:
        return len(self.chips)

    def chip_by_index(self, index: int) -> ChipInfo:
        for c in self.chips:
            if c.index == index:
                return c
        raise KeyError(f"no chip with index {index}")


_TOPO_RE = re.compile(r"^\d+x\d+(x\d+)?$")


def parse_topology(topology: str) -> Tuple[int, ...]:
    """'4x4' -> (4, 4); '2x2x2' -> (2, 2, 2)."""
    if not _TOPO_RE.match(topology):
        raise ValueError(f"malformed topology {topology!r}")
    return tuple(int(d) for d in topology.split("x"))


def topology_chips(topology: str) -> int:
    n = 1
    for d in parse_topology(topology):
        n *= d
    return n


def format_topology(dims: Tuple[int, ...]) -> str:
    return "x".join(str(d) for d in dims)
