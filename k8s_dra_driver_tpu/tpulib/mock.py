"""Mock tpulib backend — CPU-only CI's stand-in for real TPU hosts.

Equivalent of the reference's mock-NVML (SURVEY.md §4.2): a named profile
(``ALT_TPU_TOPOLOGY=v5e-16``) plus a worker id (``ALT_TPU_WORKER_ID=1``)
fully determine what this "host" sees. Health can be injected per chip for
taint/republish tests (``ALT_TPU_UNHEALTHY_CHIPS=0,2`` or ``set_health``).
"""

from __future__ import annotations

import hashlib
import os
from typing import Dict, List, Optional, Tuple

from k8s_dra_driver_tpu.tpulib.profiles import (
    GENS,
    PROFILES,
    SliceProfile,
    compute_subslice_profiles,
    host_chip_coords,
    host_grid_coord,
)
from k8s_dra_driver_tpu.tpulib.types import (
    ChipHealth,
    ChipInfo,
    HostInventory,
    IciLink,
    parse_topology,
)

ALT_TPU_WORKER_ID_ENV = "ALT_TPU_WORKER_ID"
ALT_TPU_SLICE_UID_ENV = "ALT_TPU_SLICE_UID"
ALT_TPU_UNHEALTHY_CHIPS_ENV = "ALT_TPU_UNHEALTHY_CHIPS"


def _host_block_origin(profile: SliceProfile, worker_id: int) -> Tuple[int, ...]:
    """Global coords of this host's chip block: the canonical row-major
    host-grid coordinate (profiles.host_grid_coord — also published as the
    ``hostCoord`` ResourceSlice attribute) scaled to chip units."""
    grid = profile.host_grid
    host_dims = parse_topology(profile.host_topology)
    host_dims = host_dims + (1,) * (len(grid) - len(host_dims))
    pos = host_grid_coord(profile.slice_topology, profile.host_topology,
                          worker_id)
    return tuple(p * h for p, h in zip(pos, host_dims))


class MockTpuLib:
    """A fake host within a fake slice."""

    is_mock = True  # backends consult this to pick their test doubles

    def __init__(
        self,
        profile: str | SliceProfile,
        worker_id: Optional[int] = None,
        slice_uid: Optional[str] = None,
        unhealthy: Optional[List[int]] = None,
        env: Optional[Dict[str, str]] = None,
    ):
        env = dict(env) if env is not None else dict(os.environ)
        if isinstance(profile, str):
            if profile not in PROFILES:
                raise ValueError(
                    f"unknown topology profile {profile!r}; known: {sorted(PROFILES)}"
                )
            profile = PROFILES[profile]
        self.profile = profile
        if worker_id is None:
            worker_id = int(env.get(ALT_TPU_WORKER_ID_ENV, "0"))
        if not 0 <= worker_id < profile.num_hosts:
            raise ValueError(
                f"worker_id {worker_id} out of range for {profile.name} "
                f"({profile.num_hosts} hosts)"
            )
        self.worker_id = worker_id
        self.slice_uid = slice_uid or env.get(
            ALT_TPU_SLICE_UID_ENV, f"mock-slice-{profile.name}"
        )
        self._health: Dict[int, ChipHealth] = {}
        env_unhealthy = env.get(ALT_TPU_UNHEALTHY_CHIPS_ENV, "")
        for tok in filter(None, (t.strip() for t in env_unhealthy.split(","))):
            self._health[int(tok)] = ChipHealth.UNHEALTHY
        for idx in unhealthy or ():
            self._health[idx] = ChipHealth.UNHEALTHY
        self._health_listeners: List = []
        self._link_health: Dict[Tuple[int, int], ChipHealth] = {}
        self._link_listeners: List = []

    # -- health injection ---------------------------------------------------

    def set_health(self, chip_index: int, health: ChipHealth) -> None:
        self._health[chip_index] = health
        for cb in list(self._health_listeners):
            cb(chip_index, health)

    def watch_health(self, callback) -> None:
        """Register callback(chip_index, health) — the NVML event-set analog
        (/root/reference/cmd/gpu-kubelet-plugin/device_health.go:103-274)."""
        self._health_listeners.append(callback)

    def set_link_health(self, a: int, b: int, health: ChipHealth) -> None:
        """Inject ICI-link health between two host-local chips (order
        insensitive) — the per-link fault the chip-level NVML analog has no
        equivalent for; TPU meshes lose individual ICI links while both
        endpoint chips stay up."""
        key = (min(a, b), max(a, b))
        self._link_health[key] = health
        for cb in list(self._link_listeners):
            cb(key[0], key[1], health)

    def watch_link_health(self, callback) -> None:
        """Register callback(chip_a, chip_b, health) for link transitions."""
        self._link_listeners.append(callback)

    def link_health(self) -> Dict[Tuple[int, int], ChipHealth]:
        return dict(self._link_health)

    # -- enumeration --------------------------------------------------------

    def _serial(self, global_coords: Tuple[int, ...]) -> str:
        h = hashlib.sha1(
            f"{self.slice_uid}:{global_coords}".encode(), usedforsecurity=False
        ).hexdigest()
        return h[:12]

    def enumerate(self) -> HostInventory:
        p = self.profile
        gen = GENS[p.gen]
        host_dims = parse_topology(p.host_topology)
        origin = _host_block_origin(p, self.worker_id)
        chips: List[ChipInfo] = []
        local_coords = host_chip_coords(host_dims)
        for idx, lc in enumerate(local_coords):
            lc3 = lc + (0,) * (3 - len(lc))
            gc = tuple(o + c for o, c in zip(origin + (0,) * (3 - len(origin)), lc3))
            chips.append(
                ChipInfo(
                    index=idx,
                    dev_path=f"/dev/accel{idx}",
                    pci_address=f"0000:00:{4 + idx:02x}.0",
                    gen=p.gen,
                    coords=gc,  # type: ignore[arg-type]
                    serial=self._serial(gc),
                    hbm_bytes=gen.hbm_bytes,
                    cores=gen.cores_per_chip,
                    numa_node=0 if idx < len(local_coords) // 2 or len(local_coords) == 1 else 1,
                    health=self._health.get(idx, ChipHealth.HEALTHY),
                )
            )
        links = self._intra_host_links(chips, gen.ici_gbps_per_link)
        return HostInventory(
            gen=p.gen,
            accelerator_type=p.accelerator_type,
            slice_topology=p.slice_topology,
            host_topology=p.host_topology,
            worker_id=self.worker_id,
            num_hosts=p.num_hosts,
            chips=chips,
            links=links,
            subslice_profiles=compute_subslice_profiles(p.host_topology),
            ici_domain=f"{self.slice_uid}.0",
        )

    @staticmethod
    def _intra_host_links(chips: List[ChipInfo], gbps: float) -> List[IciLink]:
        by_coords = {c.coords: c for c in chips}
        links: List[IciLink] = []
        for c in chips:
            for axis in range(3):
                nb = list(c.coords)
                nb[axis] += 1
                nb_t = tuple(nb)
                if nb_t in by_coords:
                    links.append(IciLink(a=c.coords, b=nb_t, gbps=gbps))  # type: ignore[arg-type]
        return links

    # -- identity / bootstrap ----------------------------------------------

    def worker_hostnames(self) -> List[str]:
        """Stable DNS-ish names of every host in the slice."""
        return [
            f"worker-{i}.{self.slice_uid}.tpu.internal" for i in range(self.profile.num_hosts)
        ]
