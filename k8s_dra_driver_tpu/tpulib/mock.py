"""Mock tpulib backend — CPU-only CI's stand-in for real TPU hosts.

Equivalent of the reference's mock-NVML (SURVEY.md §4.2): a named profile
(``ALT_TPU_TOPOLOGY=v5e-16``) plus a worker id (``ALT_TPU_WORKER_ID=1``)
fully determine what this "host" sees. Health can be injected per chip for
taint/republish tests (``ALT_TPU_UNHEALTHY_CHIPS=0,2`` or ``set_health``).
"""

from __future__ import annotations

import hashlib
import os
import threading
import time
from typing import Dict, List, Optional, Tuple

from k8s_dra_driver_tpu.tpulib.loadtrace import LoadTrace, parse_load_trace
from k8s_dra_driver_tpu.tpulib.profiles import (
    GENS,
    PROFILES,
    SliceProfile,
    compute_subslice_profiles,
    host_chip_coords,
    host_grid_coord,
)
from k8s_dra_driver_tpu.tpulib.types import (
    ChipCounters,
    ChipHealth,
    ChipInfo,
    HostInventory,
    IciLink,
    LinkCounters,
    parse_topology,
)

ALT_TPU_WORKER_ID_ENV = "ALT_TPU_WORKER_ID"
ALT_TPU_SLICE_UID_ENV = "ALT_TPU_SLICE_UID"
ALT_TPU_UNHEALTHY_CHIPS_ENV = "ALT_TPU_UNHEALTHY_CHIPS"
# Load trace seam, the env twin of the sim.tpu.google.com/load-trace
# annotation (tests that build the lib directly set this instead).
ALT_TPU_LOAD_TRACE_ENV = "ALT_TPU_LOAD_TRACE"

# Load applied to chips with a registered workload when no trace is set:
# a plausibly-busy steady state, so prepared chips never read as idle.
DEFAULT_BUSY_TRACE = LoadTrace(kind="constant", level=0.6)
# Duty floor on idle chips (background runtime activity, never exactly 0).
IDLE_DUTY = 0.01
IDLE_HBM_FRACTION = 0.02


def _host_block_origin(profile: SliceProfile, worker_id: int) -> Tuple[int, ...]:
    """Global coords of this host's chip block: the canonical row-major
    host-grid coordinate (profiles.host_grid_coord — also published as the
    ``hostCoord`` ResourceSlice attribute) scaled to chip units."""
    grid = profile.host_grid
    host_dims = parse_topology(profile.host_topology)
    host_dims = host_dims + (1,) * (len(grid) - len(host_dims))
    pos = host_grid_coord(profile.slice_topology, profile.host_topology,
                          worker_id)
    return tuple(p * h for p, h in zip(pos, host_dims))


class MockTpuLib:
    """A fake host within a fake slice."""

    is_mock = True  # backends consult this to pick their test doubles

    def __init__(
        self,
        profile: str | SliceProfile,
        worker_id: Optional[int] = None,
        slice_uid: Optional[str] = None,
        unhealthy: Optional[List[int]] = None,
        env: Optional[Dict[str, str]] = None,
    ):
        env = dict(env) if env is not None else dict(os.environ)
        if isinstance(profile, str):
            if profile not in PROFILES:
                raise ValueError(
                    f"unknown topology profile {profile!r}; known: {sorted(PROFILES)}"
                )
            profile = PROFILES[profile]
        self.profile = profile
        if worker_id is None:
            worker_id = int(env.get(ALT_TPU_WORKER_ID_ENV, "0"))
        if not 0 <= worker_id < profile.num_hosts:
            raise ValueError(
                f"worker_id {worker_id} out of range for {profile.name} "
                f"({profile.num_hosts} hosts)"
            )
        self.worker_id = worker_id
        self.slice_uid = slice_uid or env.get(
            ALT_TPU_SLICE_UID_ENV, f"mock-slice-{profile.name}"
        )
        self._health: Dict[int, ChipHealth] = {}
        env_unhealthy = env.get(ALT_TPU_UNHEALTHY_CHIPS_ENV, "")
        for tok in filter(None, (t.strip() for t in env_unhealthy.split(","))):
            self._health[int(tok)] = ChipHealth.UNHEALTHY
        for idx in unhealthy or ():
            self._health[idx] = ChipHealth.UNHEALTHY
        self._health_listeners: List = []
        self._link_health: Dict[Tuple[int, int], ChipHealth] = {}
        self._link_listeners: List = []
        # -- telemetry state (all guarded: counters are read from sampler
        # threads while prepare paths register workloads) ------------------
        self._tel_mu = threading.Lock()
        self._load_trace: Optional[LoadTrace] = None  # tpulint: guarded-by=_tel_mu
        trace_spec = env.get(ALT_TPU_LOAD_TRACE_ENV, "")
        if trace_spec:
            self._load_trace = parse_load_trace(trace_spec)
        self._workloads: Dict[str, Tuple[int, ...]] = {}  # tpulint: guarded-by=_tel_mu
        # Per-workload duty override (serving traffic engine): a registered
        # workload with an explicit load follows it instead of the node
        # trace, so two replicas on one host can run at different duty.
        self._workload_loads: Dict[str, float] = {}  # tpulint: guarded-by=_tel_mu
        self._link_error_rates: Dict[Tuple[int, int], float] = {}  # tpulint: guarded-by=_tel_mu
        # Per-link cumulative accumulators: [tx, rx, errors], advanced by
        # rate * dt at every read so counters integrate the load between
        # sampling instants (the hardware-counter contract).
        self._link_acc: Dict[Tuple[int, int], List[float]] = {}  # tpulint: guarded-by=_tel_mu
        self._counters_last_t: Optional[float] = None  # tpulint: guarded-by=_tel_mu
        # Static per-profile topology, computed once: read_counters must
        # not rebuild the coordinate map per sample inside _tel_mu.
        _host_dims = parse_topology(self.profile.host_topology)
        self._counter_chips = len(host_chip_coords(_host_dims))
        self._counter_link_pairs = self._host_link_pairs(
            self._counter_chips, _host_dims)

    # -- health injection ---------------------------------------------------

    def set_health(self, chip_index: int, health: ChipHealth) -> None:
        self._health[chip_index] = health
        for cb in list(self._health_listeners):
            cb(chip_index, health)

    def watch_health(self, callback) -> None:
        """Register callback(chip_index, health) — the NVML event-set analog
        (/root/reference/cmd/gpu-kubelet-plugin/device_health.go:103-274)."""
        self._health_listeners.append(callback)

    def set_link_health(self, a: int, b: int, health: ChipHealth) -> None:
        """Inject ICI-link health between two host-local chips (order
        insensitive) — the per-link fault the chip-level NVML analog has no
        equivalent for; TPU meshes lose individual ICI links while both
        endpoint chips stay up."""
        key = (min(a, b), max(a, b))
        self._link_health[key] = health
        for cb in list(self._link_listeners):
            cb(key[0], key[1], health)

    def watch_link_health(self, callback) -> None:
        """Register callback(chip_a, chip_b, health) for link transitions."""
        self._link_listeners.append(callback)

    def link_health(self) -> Dict[Tuple[int, int], ChipHealth]:
        return dict(self._link_health)

    # -- telemetry ----------------------------------------------------------

    def set_load_trace(self, trace: "Optional[LoadTrace | str]") -> None:
        """Install the synthetic load generator (a LoadTrace, a spec
        string, or None to clear) — the load-trace chaos annotation's
        target. Applies to chips with a registered workload; idle chips
        stay at the idle floor regardless."""
        if isinstance(trace, str):
            trace = parse_load_trace(trace)
        with self._tel_mu:
            self._load_trace = trace

    def load_trace(self) -> Optional[LoadTrace]:
        with self._tel_mu:
            return self._load_trace

    def register_workload(self, owner: str, chip_indices) -> None:
        """Mark ``chip_indices`` busy on behalf of ``owner`` (a claim uid:
        the plugin registers at PrepareCompleted, unregisters at
        unprepare/rollback) so counters reflect what is actually placed."""
        with self._tel_mu:
            self._workloads[owner] = tuple(sorted(chip_indices))

    def unregister_workload(self, owner: str) -> None:
        with self._tel_mu:
            self._workloads.pop(owner, None)
            self._workload_loads.pop(owner, None)

    def set_workload_load(self, owner: str, duty: Optional[float]) -> None:
        """Install a per-workload duty override in [0, 1] (None clears).
        The serving traffic engine's feed: per-replica utilization from
        the queueing model lands here per claim uid, so chip counters —
        and everything telemetry rolls up from them — reflect serving
        load with a deterministic ground truth. Unknown owners are
        accepted (the engine may race a prepare); the override applies
        once the workload registers."""
        with self._tel_mu:
            if duty is None:
                self._workload_loads.pop(owner, None)
            else:
                self._workload_loads[owner] = min(1.0, max(0.0, float(duty)))

    def workloads(self) -> Dict[str, Tuple[int, ...]]:
        with self._tel_mu:
            return dict(self._workloads)

    def workload_loads(self) -> Dict[str, float]:
        with self._tel_mu:
            return dict(self._workload_loads)

    def set_link_error_rate(self, a: int, b: int, errors_per_s: float) -> None:
        """Inject a sustained ICI error rate on one link (order
        insensitive; 0 clears) — the fault the telemetry sampler must
        threshold into link *degradation*, distinct from the hard
        set_link_health kill."""
        key = (min(a, b), max(a, b))
        with self._tel_mu:
            if errors_per_s <= 0:
                self._link_error_rates.pop(key, None)
            else:
                self._link_error_rates[key] = float(errors_per_s)

    def read_counters(self, now: Optional[float] = None) -> List[ChipCounters]:
        """Synthesize per-chip counters at trace-time ``now``.

        Busy chips (any registered workload) follow the installed load
        trace (or DEFAULT_BUSY_TRACE); idle chips sit at the idle floor.
        Link tx/rx/error counters are cumulative: each read advances the
        accumulators by rate x elapsed-trace-time, so two reads bracket
        the integrated traffic between them."""
        if now is None:
            now = time.time()
        inv_gen = GENS[self.profile.gen]
        n_chips = self._counter_chips
        # Lock hold is the accumulator arithmetic ONLY: the prepare path
        # takes this same mutex per claim (register_workload), so object
        # construction for chips x links must not serialize against it
        # (bench_telemetry's prepare-storm gate measures exactly that).
        with self._tel_mu:
            busy = {i for chips in self._workloads.values() for i in chips}
            trace = self._load_trace or DEFAULT_BUSY_TRACE
            last_t = self._counters_last_t
            dt = max(0.0, now - last_t) if last_t is not None else 0.0
            self._counters_last_t = now
            load = trace.value(now)
            # Per-chip duty: a workload with an explicit load override
            # (serving traffic engine) pins its chips to that duty; chips
            # shared by several overridden workloads take the max.
            chip_loads: Dict[int, float] = {}
            for owner, chips in self._workloads.items():
                ov = self._workload_loads.get(owner)
                if ov is None:
                    continue
                for i in chips:
                    chip_loads[i] = max(chip_loads.get(i, 0.0), ov)
            # Advance cumulative link accumulators. A link carries
            # collective traffic when both endpoints are busy, at the
            # slower endpoint's duty.
            link_snap: List[Tuple[int, int, int, int, int]] = []
            for (a, b) in self._counter_link_pairs:
                acc = self._link_acc.setdefault((a, b), [0.0, 0.0, 0.0])
                if dt > 0:
                    active = a in busy and b in busy
                    util = (min(chip_loads.get(a, load), chip_loads.get(b, load))
                            if active else 0.0)
                    byte_rate = util * inv_gen.ici_gbps_per_link * 1e9 / 8.0
                    acc[0] += byte_rate * dt
                    acc[1] += byte_rate * dt
                    acc[2] += self._link_error_rates.get((a, b), 0.0) * dt
                link_snap.append((a, b, int(acc[0]), int(acc[1]), int(acc[2])))
        links_by_chip: Dict[int, List[LinkCounters]] = {}
        for a, b, tx, rx, errs in link_snap:
            links_by_chip.setdefault(a, []).append(LinkCounters(
                a=a, b=b, tx_bytes=tx, rx_bytes=rx, errors=errs))
        from k8s_dra_driver_tpu.tpulib.loadtrace import (
            HBM_ACTIVE_FRACTION,
            HBM_FLOOR_FRACTION,
        )

        out: List[ChipCounters] = []
        for idx in range(n_chips):
            if idx in busy:
                duty = chip_loads.get(idx, load)
                # Same HBM model the traces use: resident floor plus an
                # activation share tracking instantaneous duty.
                used = int((HBM_FLOOR_FRACTION + HBM_ACTIVE_FRACTION * duty)
                           * inv_gen.hbm_bytes)
            else:
                duty = IDLE_DUTY
                used = int(IDLE_HBM_FRACTION * inv_gen.hbm_bytes)
            power = (inv_gen.idle_watts
                     + (inv_gen.peak_watts - inv_gen.idle_watts) * duty)
            out.append(ChipCounters(
                index=idx, timestamp=now,
                hbm_used_bytes=used, hbm_total_bytes=inv_gen.hbm_bytes,
                duty_cycle=duty, power_watts=power,
                links=tuple(links_by_chip.get(idx, ())),
            ))
        return out

    @staticmethod
    def _host_link_pairs(n_chips: int, host_dims) -> List[Tuple[int, int]]:
        """Intra-host ICI link endpoints as (a, b) host-local index pairs,
        a < b — the same adjacency _intra_host_links derives in coords."""
        coords = host_chip_coords(host_dims)
        index_of = {c: i for i, c in enumerate(coords)}
        pairs: List[Tuple[int, int]] = []
        for c, i in index_of.items():
            for axis in range(len(host_dims)):
                nb = list(c)
                nb[axis] += 1
                j = index_of.get(tuple(nb))
                if j is not None:
                    pairs.append((min(i, j), max(i, j)))
        return sorted(set(pairs))

    # -- enumeration --------------------------------------------------------

    def _serial(self, global_coords: Tuple[int, ...]) -> str:
        h = hashlib.sha1(
            f"{self.slice_uid}:{global_coords}".encode(), usedforsecurity=False
        ).hexdigest()
        return h[:12]

    def enumerate(self) -> HostInventory:
        p = self.profile
        gen = GENS[p.gen]
        host_dims = parse_topology(p.host_topology)
        origin = _host_block_origin(p, self.worker_id)
        chips: List[ChipInfo] = []
        local_coords = host_chip_coords(host_dims)
        for idx, lc in enumerate(local_coords):
            lc3 = lc + (0,) * (3 - len(lc))
            gc = tuple(o + c for o, c in zip(origin + (0,) * (3 - len(origin)), lc3))
            chips.append(
                ChipInfo(
                    index=idx,
                    dev_path=f"/dev/accel{idx}",
                    pci_address=f"0000:00:{4 + idx:02x}.0",
                    gen=p.gen,
                    coords=gc,  # type: ignore[arg-type]
                    serial=self._serial(gc),
                    hbm_bytes=gen.hbm_bytes,
                    cores=gen.cores_per_chip,
                    numa_node=0 if idx < len(local_coords) // 2 or len(local_coords) == 1 else 1,
                    health=self._health.get(idx, ChipHealth.HEALTHY),
                )
            )
        links = self._intra_host_links(chips, gen.ici_gbps_per_link)
        return HostInventory(
            gen=p.gen,
            accelerator_type=p.accelerator_type,
            slice_topology=p.slice_topology,
            host_topology=p.host_topology,
            worker_id=self.worker_id,
            num_hosts=p.num_hosts,
            chips=chips,
            links=links,
            subslice_profiles=compute_subslice_profiles(p.host_topology),
            ici_domain=f"{self.slice_uid}.0",
        )

    @staticmethod
    def _intra_host_links(chips: List[ChipInfo], gbps: float) -> List[IciLink]:
        by_coords = {c.coords: c for c in chips}
        links: List[IciLink] = []
        for c in chips:
            for axis in range(3):
                nb = list(c.coords)
                nb[axis] += 1
                nb_t = tuple(nb)
                if nb_t in by_coords:
                    links.append(IciLink(a=c.coords, b=nb_t, gbps=gbps))  # type: ignore[arg-type]
        return links

    # -- identity / bootstrap ----------------------------------------------

    def worker_hostnames(self) -> List[str]:
        """Stable DNS-ish names of every host in the slice."""
        return [
            f"worker-{i}.{self.slice_uid}.tpu.internal" for i in range(self.profile.num_hosts)
        ]
