"""Parameterized synthetic load traces for the mock tpulib.

Sim clusters need realistic utilization without real hardware: the
``sim.tpu.google.com/load-trace`` chaos annotation carries a trace spec
the mock backend turns into per-chip counters, the same way the
chip/link-health annotations drive the health chain. Three generator
families, all **deterministic from their seed** — the telemetry e2e
compares measured p95s against ground truth recomputed from the very
same generator, so no wall-clock randomness is allowed anywhere:

- ``constant:level=0.6`` — flat load at ``level``.
- ``diurnal:period=240,low=0.1,high=0.9,phase=0`` — sinusoidal
  day/night cycle over ``period`` seconds.
- ``bursty:seed=3,period=60,base=0.15,peak=0.95,duty=0.3`` — square
  bursts: each ``period``-second slot is either a burst (``peak``) or
  quiet (``base``); whether slot *k* bursts is a pure hash of
  ``(seed, k)`` thinned to the ``duty`` fraction.
- ``playback:file=/path/trace.json,loop=1`` — replay a recorded trace:
  the JSON file holds ``[{"t": seconds, "v": value}, ...]`` samples
  (``"qps"`` accepted as an alias for ``"v"``), linearly interpolated
  between sample times. Before the first sample the first value holds;
  past the last sample the last value holds, or with ``loop=1`` time
  wraps modulo the recorded span. Real traffic traces (QPS exports)
  drive the serving traffic engine through exactly this kind — samples
  load ONCE at parse time, so the frozen trace stays deterministic and
  hashable like the generator kinds.

``value(t)`` is the compute duty cycle in [0, 1] at trace-time ``t``;
``raw_value(t)`` is the same curve unclamped (playback samples may be
raw QPS, which the traffic engine consumes directly);
``hbm_fraction(t)`` derives the HBM footprint from duty (weights stay
resident, so there is a floor under the activations that track duty).
"""

from __future__ import annotations

import hashlib
import json
import math
from dataclasses import dataclass, field
from typing import Dict, List, Tuple

LOAD_TRACE_KINDS = ("constant", "diurnal", "bursty", "playback")

# HBM model: resident fraction (weights/optimizer state) plus an
# activation share that tracks instantaneous duty.
HBM_FLOOR_FRACTION = 0.30
HBM_ACTIVE_FRACTION = 0.55


class LoadTraceError(ValueError):
    pass


def _slot_hash(seed: int, slot: int) -> float:
    """Uniform [0,1) from (seed, slot), stable across processes (no
    PYTHONHASHSEED dependence)."""
    h = hashlib.sha1(f"{seed}:{slot}".encode(), usedforsecurity=False)
    return int.from_bytes(h.digest()[:8], "big") / 2**64


@dataclass(frozen=True)
class LoadTrace:
    """One parsed trace spec. Frozen so a trace can key caches and be
    shared across chips without copy."""

    kind: str = "constant"
    seed: int = 0
    level: float = 0.6       # constant
    period: float = 240.0    # diurnal / bursty slot length
    low: float = 0.1         # diurnal trough
    high: float = 0.9        # diurnal crest
    phase: float = 0.0       # diurnal offset seconds
    base: float = 0.15       # bursty quiet level
    peak: float = 0.95       # bursty burst level
    duty: float = 0.3        # bursty fraction of slots bursting
    loop: float = 0.0        # playback: 1 = wrap time modulo the span
    # Playback samples, (t, v) sorted by t — loaded once at parse time so
    # the frozen trace stays hashable and file reads never hit value().
    points: Tuple[Tuple[float, float], ...] = ()
    file: str = field(default="", compare=False)
    spec: str = field(default="", compare=False)

    def value(self, t: float) -> float:
        """Compute duty cycle in [0, 1] at trace-time ``t`` seconds."""
        return _clamp(self.raw_value(t))

    def raw_value(self, t: float) -> float:
        """The trace curve at ``t``, unclamped: generator kinds already
        live in [0, 1], playback samples keep their recorded units (raw
        QPS traces feed the serving traffic engine through this)."""
        if self.kind == "constant":
            return self.level
        if self.kind == "diurnal":
            x = 0.5 - 0.5 * math.cos(2 * math.pi * (t + self.phase) / self.period)
            return self.low + (self.high - self.low) * x
        if self.kind == "playback":
            return self._interpolate(t)
        slot = int(t // self.period)
        bursting = _slot_hash(self.seed, slot) < self.duty
        return self.peak if bursting else self.base

    def _interpolate(self, t: float) -> float:
        pts = self.points
        if not pts:
            return 0.0
        t0, tn = pts[0][0], pts[-1][0]
        if self.loop and tn > t0:
            t = t0 + (t - t0) % (tn - t0)
        if t <= t0:
            return pts[0][1]
        if t >= tn:
            return pts[-1][1]
        # Bisect the sorted sample times, then lerp the bracket.
        lo, hi = 0, len(pts) - 1
        while hi - lo > 1:
            mid = (lo + hi) // 2
            if pts[mid][0] <= t:
                lo = mid
            else:
                hi = mid
        (ta, va), (tb, vb) = pts[lo], pts[hi]
        if tb <= ta:
            return vb
        return va + (vb - va) * (t - ta) / (tb - ta)

    def hbm_fraction(self, t: float) -> float:
        """Fraction of HBM in use at ``t``: resident floor + activations."""
        return _clamp(HBM_FLOOR_FRACTION + HBM_ACTIVE_FRACTION * self.value(t))

    def ground_truth(self, times: List[float]) -> Tuple[float, float]:
        """(duty p95, hbm-fraction p95) over exactly ``times`` — what a
        sampler reading this trace at those instants must converge to;
        the telemetry e2e's oracle."""
        if not times:
            return 0.0, 0.0
        return (percentile([self.value(t) for t in times], 0.95),
                percentile([self.hbm_fraction(t) for t in times], 0.95))


def _clamp(v: float) -> float:
    return min(1.0, max(0.0, v))


def percentile(values: List[float], q: float) -> float:
    """Nearest-rank percentile on a copy; the one rule shared by the ring
    buffers, the rollup summaries, and the trace ground truth so they can
    be compared exactly."""
    if not values:
        return 0.0
    ordered = sorted(values)
    rank = max(0, min(len(ordered) - 1, math.ceil(q * len(ordered)) - 1))
    return ordered[rank]


_FLOAT_PARAMS = {"level", "period", "low", "high", "phase", "base", "peak",
                 "duty", "loop"}


def load_playback_points(path: str) -> Tuple[Tuple[float, float], ...]:
    """Load and validate a playback trace file: a JSON array of
    ``{"t": seconds, "v": value}`` objects (``"qps"`` accepted for
    ``"v"``; bare ``[t, v]`` pairs too). Samples are sorted by time;
    duplicate times keep the last value (the export-tool convention)."""
    try:
        with open(path, "r", encoding="utf-8") as f:
            doc = json.load(f)
    except OSError as e:
        raise LoadTraceError(f"cannot read playback trace {path!r}: {e}") from e
    except json.JSONDecodeError as e:
        raise LoadTraceError(f"playback trace {path!r} is not JSON: {e}") from e
    if isinstance(doc, dict):
        doc = doc.get("samples", None)
    if not isinstance(doc, list) or not doc:
        raise LoadTraceError(
            f"playback trace {path!r} must be a non-empty JSON array of "
            f"samples (or {{\"samples\": [...]}})")
    by_t: Dict[float, float] = {}
    for i, item in enumerate(doc):
        try:
            if isinstance(item, dict):
                t = float(item["t"])
                v = float(item["v"] if "v" in item else item["qps"])
            else:
                t, v = float(item[0]), float(item[1])
        except (KeyError, IndexError, TypeError, ValueError) as e:
            raise LoadTraceError(
                f"playback trace {path!r} sample #{i} malformed: {item!r}"
            ) from e
        by_t[t] = v
    return tuple(sorted(by_t.items()))


def parse_load_trace(spec: str) -> LoadTrace:
    """Parse an annotation value like ``bursty:seed=3,period=60``.

    Unknown kinds/params and malformed numbers raise :class:`LoadTraceError`
    (the chaos pass logs and skips, mirroring the health annotations'
    bad-token handling)."""
    spec = (spec or "").strip()
    if not spec:
        raise LoadTraceError("empty load-trace spec")
    kind, _, rest = spec.partition(":")
    kind = kind.strip().lower()
    if kind not in LOAD_TRACE_KINDS:
        raise LoadTraceError(
            f"unknown load-trace kind {kind!r}; known: {LOAD_TRACE_KINDS}")
    params: Dict[str, float] = {}
    seed = 0
    file_path = ""
    for tok in filter(None, (t.strip() for t in rest.split(","))):
        key, eq, val = tok.partition("=")
        key = key.strip().lower()
        if not eq:
            raise LoadTraceError(f"malformed load-trace param {tok!r}")
        try:
            if key == "seed":
                seed = int(val)
            elif key == "file":
                file_path = val.strip()
            elif key in _FLOAT_PARAMS:
                params[key] = float(val)
            else:
                raise LoadTraceError(f"unknown load-trace param {key!r}")
        except ValueError as e:
            raise LoadTraceError(f"bad load-trace value {tok!r}") from e
    if params.get("period", 240.0) <= 0:
        raise LoadTraceError("load-trace period must be > 0")
    if kind == "playback":
        if not file_path:
            raise LoadTraceError("playback trace needs file=<path>")
        return LoadTrace(kind=kind, seed=seed, spec=spec, file=file_path,
                         points=load_playback_points(file_path), **params)
    if file_path:
        raise LoadTraceError(f"file= only applies to playback, not {kind!r}")
    return LoadTrace(kind=kind, seed=seed, spec=spec, **params)
