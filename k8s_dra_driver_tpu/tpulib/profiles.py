"""Generation facts and named slice profiles.

The mock backend's profile table plays the role of the reference's mock-NVML
GPU profiles (a100/h100/gb200..., /root/reference/hack/ci/mock-nvml/
setup-mock-gpu.sh:16-35): a named catalog of hardware shapes CI can
impersonate. Subslice profiles are computed, not listed — legality is
"axis-aligned block whose dims divide the host topology", generalized from
the MIG profile+placement walk (/root/reference/cmd/gpu-kubelet-plugin/
nvlib.go:466-642).
"""

from __future__ import annotations

import itertools
from dataclasses import dataclass
from typing import Dict, List, Tuple

from k8s_dra_driver_tpu.tpulib.types import (
    GenSpec,
    SubslicePlacement,
    SubsliceProfile,
    TpuGen,
    format_topology,
    parse_topology,
    topology_chips,
)

GiB = 1024**3

GENS: Dict[TpuGen, GenSpec] = {
    TpuGen.V4: GenSpec(TpuGen.V4, hbm_bytes=32 * GiB, cores_per_chip=2,
                       topology_dims=3, peak_bf16_tflops=275.0, ici_gbps_per_link=50.0,
                       idle_watts=55.0, peak_watts=192.0),
    TpuGen.V5E: GenSpec(TpuGen.V5E, hbm_bytes=16 * GiB, cores_per_chip=1,
                        topology_dims=2, peak_bf16_tflops=197.0, ici_gbps_per_link=45.0,
                        idle_watts=40.0, peak_watts=170.0),
    TpuGen.V5P: GenSpec(TpuGen.V5P, hbm_bytes=95 * GiB, cores_per_chip=2,
                        topology_dims=3, peak_bf16_tflops=459.0, ici_gbps_per_link=90.0,
                        idle_watts=90.0, peak_watts=350.0),
    TpuGen.V6E: GenSpec(TpuGen.V6E, hbm_bytes=32 * GiB, cores_per_chip=1,
                        topology_dims=2, peak_bf16_tflops=918.0, ici_gbps_per_link=90.0,
                        idle_watts=60.0, peak_watts=260.0),
}


@dataclass(frozen=True)
class SliceProfile:
    """A named whole-slice shape the mock can impersonate."""

    name: str               # "v5e-16"
    gen: TpuGen
    accelerator_type: str   # GKE-style name, e.g. "v5litepod-16"
    slice_topology: str     # "4x4"
    host_topology: str      # "2x2" — chips on one host

    @property
    def num_chips(self) -> int:
        return topology_chips(self.slice_topology)

    @property
    def chips_per_host(self) -> int:
        return topology_chips(self.host_topology)

    @property
    def num_hosts(self) -> int:
        assert self.num_chips % self.chips_per_host == 0
        return self.num_chips // self.chips_per_host

    @property
    def host_grid(self) -> Tuple[int, ...]:
        """How host blocks tile the slice grid."""
        return host_grid_dims(self.slice_topology, self.host_topology)


def _p(name: str, gen: TpuGen, acc: str, slice_topo: str, host_topo: str) -> SliceProfile:
    return SliceProfile(name, gen, acc, slice_topo, host_topo)


PROFILES: Dict[str, SliceProfile] = {
    p.name: p
    for p in (
        _p("v5e-1", TpuGen.V5E, "v5litepod-1", "1x1", "1x1"),
        _p("v5e-4", TpuGen.V5E, "v5litepod-4", "2x2", "2x2"),
        _p("v5e-8", TpuGen.V5E, "v5litepod-8", "2x4", "2x2"),
        _p("v5e-16", TpuGen.V5E, "v5litepod-16", "4x4", "2x2"),
        _p("v5e-32", TpuGen.V5E, "v5litepod-32", "4x8", "2x2"),
        _p("v5e-64", TpuGen.V5E, "v5litepod-64", "8x8", "2x2"),
        _p("v6e-4", TpuGen.V6E, "v6e-4", "2x2", "2x2"),
        _p("v6e-16", TpuGen.V6E, "v6e-16", "4x4", "2x2"),
        _p("v4-8", TpuGen.V4, "v4-8", "2x2x2", "2x2x1"),
        _p("v4-16", TpuGen.V4, "v4-16", "2x2x4", "2x2x1"),
        _p("v5p-8", TpuGen.V5P, "v5p-8", "2x2x2", "2x2x1"),
        _p("v5p-16", TpuGen.V5P, "v5p-16", "2x2x4", "2x2x1"),
    )
}


def host_chip_coords(host_topo: Tuple[int, ...]) -> List[Tuple[int, ...]]:
    """Host-local chip coords, row-major; chip index == position in list."""
    return [c for c in itertools.product(*(range(d) for d in host_topo))]


def host_grid_dims(slice_topology: str, host_topology: str) -> Tuple[int, ...]:
    """THE canonical host-tiling rule (pad host dims with 1s to the slice
    rank, every slice dim must divide evenly): how host blocks tile the
    slice grid, in host units. SliceProfile.host_grid, host_grid_coord,
    and the placement engine all resolve through this one function."""
    s = parse_topology(slice_topology)
    h = parse_topology(host_topology)
    h = h + (1,) * (len(s) - len(h))
    if any(hd <= 0 or sd % hd for sd, hd in zip(s, h)):
        raise ValueError(
            f"host topology {host_topology!r} does not tile slice "
            f"{slice_topology!r}")
    return tuple(sd // hd for sd, hd in zip(s, h))


def host_grid_coord(slice_topology: str, host_topology: str,
                    worker_id: int) -> Tuple[int, ...]:
    """Grid coordinate of host ``worker_id`` within the slice's host grid,
    hosts tiling row-major — the mock/real tpulibs derive chip-block
    origins from it and the kubelet plugin publishes it as the
    ``hostCoord`` ResourceSlice attribute the host-grid-aligned domain
    placer consumes."""
    grid = host_grid_dims(slice_topology, host_topology)
    rem = worker_id
    pos = []
    for g in reversed(grid):
        pos.append(rem % g)
        rem //= g
    pos.reverse()
    return tuple(pos)


def compute_subslice_profiles(host_topology: str) -> List[SubsliceProfile]:
    """All proper subslice shapes of a host topology, with placements.

    A shape is legal when each dim divides the host dim (so placements tile
    the grid without overlap — the scheduler-enforced counter model needs
    placements at fixed offsets, like MIG memory-slice placements,
    /root/reference/cmd/gpu-kubelet-plugin/partitions.go:53-246).
    The whole-host shape is excluded: that's just the host device itself.
    """
    dims = parse_topology(host_topology)
    coords = host_chip_coords(dims)
    index_of = {c: i for i, c in enumerate(coords)}

    def divisors(n: int) -> List[int]:
        return [d for d in range(1, n + 1) if n % d == 0]

    profiles: List[SubsliceProfile] = []
    for shape in itertools.product(*(divisors(d) for d in dims)):
        if shape == dims:
            continue  # whole host
        name = format_topology(shape)
        placements = []
        origins = itertools.product(
            *(range(0, d, s) for d, s in zip(dims, shape))
        )
        for origin in origins:
            cells = itertools.product(
                *(range(o, o + s) for o, s in zip(origin, shape))
            )
            chip_indices = tuple(sorted(index_of[c] for c in cells))
            start = tuple(origin) + (0,) * (3 - len(origin))
            placements.append(
                SubslicePlacement(profile=name, start=start, chip_indices=chip_indices)  # type: ignore[arg-type]
            )
        profiles.append(
            SubsliceProfile(
                name=name,
                shape=shape,
                chips=topology_chips(name),
                placements=tuple(placements),
            )
        )
    # Largest first: nicer for humans, and dedupes nothing.
    profiles.sort(key=lambda p: (-p.chips, p.name))
    return profiles
