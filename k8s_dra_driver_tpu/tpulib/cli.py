"""tpu-info — the nvidia-smi-style debug CLI over tpulib.

Usage:
    python -m k8s_dra_driver_tpu.tpulib.cli info [--json]
    python -m k8s_dra_driver_tpu.tpulib.cli health <chip-index>
    python -m k8s_dra_driver_tpu.tpulib.cli topo [--json]
    python -m k8s_dra_driver_tpu.tpulib.cli partitions [--ledger PATH]

(Reference role: nvidia-smi as invoked for debug/persistence-mode at
/root/reference/cmd/gpu-kubelet-plugin/root.go:57; `topo` is the
`nvidia-smi topo -m` analog over ICI links, `partitions` inspects the
DynamicSubslice activation ledger the way `nvidia-smi -q` shows MIG
devices.)
"""

from __future__ import annotations

import argparse
import dataclasses
import json
import sys
from enum import Enum

from k8s_dra_driver_tpu.tpulib.lib import new_tpulib, using_mock_tpulib


def _to_jsonable(obj):
    if dataclasses.is_dataclass(obj) and not isinstance(obj, type):
        return {k: _to_jsonable(v) for k, v in dataclasses.asdict(obj).items()}
    if isinstance(obj, Enum):
        return obj.value
    if isinstance(obj, dict):
        return {str(k): _to_jsonable(v) for k, v in obj.items()}
    if isinstance(obj, (list, tuple)):
        return [_to_jsonable(v) for v in obj]
    return obj


def cmd_info(args: argparse.Namespace) -> int:
    lib = new_tpulib()
    inv = lib.enumerate()
    if args.json:
        print(json.dumps(_to_jsonable(inv), indent=2))
        return 0
    backend = "mock" if using_mock_tpulib() else "real"
    print(f"backend: {backend}")
    print(f"accelerator: {inv.accelerator_type} ({inv.gen.value})")
    print(f"slice: {inv.slice_topology} over {inv.num_hosts} host(s); "
          f"this host: worker {inv.worker_id}, {inv.host_topology}")
    print(f"ici domain: {inv.ici_domain}")
    print(f"{'IDX':<4}{'DEVICE':<14}{'COORDS':<12}{'HBM':<8}{'NUMA':<6}{'HEALTH':<10}SERIAL")
    for c in inv.chips:
        hbm = f"{c.hbm_bytes // (1024**3)}G"
        print(f"{c.index:<4}{c.dev_path:<14}{str(c.coords):<12}{hbm:<8}"
              f"{c.numa_node:<6}{c.health.value:<10}{c.serial}")
    if inv.subslice_profiles:
        profs = ", ".join(
            f"{p.name}({len(p.placements)} placements)" for p in inv.subslice_profiles
        )
        print(f"subslice profiles: {profs}")
    return 0


def cmd_health(args: argparse.Namespace) -> int:
    from k8s_dra_driver_tpu.tpulib.real import RealTpuLib

    from k8s_dra_driver_tpu.tpulib.types import ChipHealth

    lib = new_tpulib()
    if isinstance(lib, RealTpuLib):
        h = lib.chip_health(args.chip)
    else:
        inv = lib.enumerate()
        try:
            h = inv.chip_by_index(args.chip).health
        except KeyError:
            h = ChipHealth.UNHEALTHY
    print(h.value)
    return 0 if h.value == "healthy" else 1


def cmd_topo(args: argparse.Namespace) -> int:
    """ICI link matrix between this host's chips (nvidia-smi topo -m
    analog): cell = link bandwidth in GB/s, '.' = no direct link."""
    lib = new_tpulib()
    inv = lib.enumerate()
    by_coords = {c.coords: c.index for c in inv.chips}
    links = {}
    for ln in inv.links:
        a, b = by_coords.get(ln.a), by_coords.get(ln.b)
        if a is None or b is None:
            continue  # inter-host link: peer chip not on this host
        links[(a, b)] = links[(b, a)] = ln.gbps
    if args.json:
        print(json.dumps({
            "host_topology": inv.host_topology,
            "links": [
                {"a": a, "b": b, "gbps": g}
                for (a, b), g in sorted(links.items()) if a < b
            ],
        }, indent=2))
        return 0
    idxs = [c.index for c in inv.chips]
    print(f"host {inv.host_topology}; cells are ICI GB/s per direction per link")
    print("     " + "".join(f"chip{j:<4}" for j in idxs))
    for i in idxs:
        row = []
        for j in idxs:
            if i == j:
                row.append("x")
            else:
                g = links.get((i, j))
                row.append("." if g is None else f"{g:g}")
        print(f"chip{i:<3}" + "".join(f"{v:<8}" for v in row))
    return 0


def cmd_partitions(args: argparse.Namespace) -> int:
    """Show the DynamicSubslice activation ledger (the flock'd on-disk
    state behind Prepare-time carving; empty/missing = nothing carved).
    Reads both ledger formats: the native partitioner's newline-separated
    id list (native/partitioner.cc) and JSON {"partitions": [...]}."""
    import os

    path = args.ledger
    if not os.path.exists(path):
        print(f"no ledger at {path} (no partitions active, or "
              f"DynamicSubslice disabled)")
        return 0
    with open(path, encoding="utf-8") as f:
        raw = f.read()
    try:
        doc = json.loads(raw)
        parts = doc.get("partitions", doc if isinstance(doc, list) else [])
    except json.JSONDecodeError:
        # Native id-per-line ledger: resolve chips via this host's
        # placement map so the output matches the JSON form.
        placements = {}
        try:
            inv = new_tpulib().enumerate()
            for prof in inv.subslice_profiles:
                for pl in prof.placements:
                    placements[pl.name_suffix] = pl
        except Exception as e:  # noqa: BLE001 — enumeration is best-effort here
            print(f"warning: placement enumeration unavailable: {e}",
                  file=sys.stderr)
        parts = []
        for pid in filter(None, (ln.strip() for ln in raw.splitlines())):
            pl = placements.get(pid)
            parts.append({
                "id": pid,
                "profile": pl.profile if pl else pid.split("-at-")[0],
                "chips": list(pl.chip_indices) if pl else [],
            })
    if args.json:
        print(json.dumps(parts, indent=2))
        return 0
    if not parts:
        print("no active partitions")
        return 0
    print(f"{'ID':<20}{'PROFILE':<10}CHIPS")
    for p in parts:
        chips = ",".join(str(c) for c in p.get("chips", p.get("chip_indices", [])))
        print(f"{p.get('id', '?'):<20}{p.get('profile', '?'):<10}{chips}")
    return 0


DEFAULT_LEDGER = "/var/lib/kubelet/plugins/tpu.google.com/partitions.json"


def main(argv=None) -> int:
    parser = argparse.ArgumentParser(prog="tpu-info")
    sub = parser.add_subparsers(dest="cmd", required=True)
    p_info = sub.add_parser("info", help="enumerate this host")
    p_info.add_argument("--json", action="store_true")
    p_info.set_defaults(fn=cmd_info)
    p_health = sub.add_parser("health", help="probe one chip")
    p_health.add_argument("chip", type=int)
    p_health.set_defaults(fn=cmd_health)
    p_topo = sub.add_parser("topo", help="ICI link matrix (topo -m analog)")
    p_topo.add_argument("--json", action="store_true")
    p_topo.set_defaults(fn=cmd_topo)
    p_parts = sub.add_parser("partitions", help="DynamicSubslice ledger")
    p_parts.add_argument("--ledger", default=DEFAULT_LEDGER,
                         help=f"ledger path [default: {DEFAULT_LEDGER}]")
    p_parts.add_argument("--json", action="store_true")
    p_parts.set_defaults(fn=cmd_partitions)
    args = parser.parse_args(argv)
    return args.fn(args)


if __name__ == "__main__":
    sys.exit(main())
