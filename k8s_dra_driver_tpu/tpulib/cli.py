"""tpu-info — the nvidia-smi-style debug CLI over tpulib.

Usage:
    python -m k8s_dra_driver_tpu.tpulib.cli info [--json]
    python -m k8s_dra_driver_tpu.tpulib.cli health <chip-index>

(Reference role: nvidia-smi as invoked for debug/persistence-mode at
/root/reference/cmd/gpu-kubelet-plugin/root.go:57.)
"""

from __future__ import annotations

import argparse
import dataclasses
import json
import sys
from enum import Enum

from k8s_dra_driver_tpu.tpulib.lib import new_tpulib, using_mock_tpulib


def _to_jsonable(obj):
    if dataclasses.is_dataclass(obj) and not isinstance(obj, type):
        return {k: _to_jsonable(v) for k, v in dataclasses.asdict(obj).items()}
    if isinstance(obj, Enum):
        return obj.value
    if isinstance(obj, dict):
        return {str(k): _to_jsonable(v) for k, v in obj.items()}
    if isinstance(obj, (list, tuple)):
        return [_to_jsonable(v) for v in obj]
    return obj


def cmd_info(args: argparse.Namespace) -> int:
    lib = new_tpulib()
    inv = lib.enumerate()
    if args.json:
        print(json.dumps(_to_jsonable(inv), indent=2))
        return 0
    backend = "mock" if using_mock_tpulib() else "real"
    print(f"backend: {backend}")
    print(f"accelerator: {inv.accelerator_type} ({inv.gen.value})")
    print(f"slice: {inv.slice_topology} over {inv.num_hosts} host(s); "
          f"this host: worker {inv.worker_id}, {inv.host_topology}")
    print(f"ici domain: {inv.ici_domain}")
    print(f"{'IDX':<4}{'DEVICE':<14}{'COORDS':<12}{'HBM':<8}{'NUMA':<6}{'HEALTH':<10}SERIAL")
    for c in inv.chips:
        hbm = f"{c.hbm_bytes // (1024**3)}G"
        print(f"{c.index:<4}{c.dev_path:<14}{str(c.coords):<12}{hbm:<8}"
              f"{c.numa_node:<6}{c.health.value:<10}{c.serial}")
    if inv.subslice_profiles:
        profs = ", ".join(
            f"{p.name}({len(p.placements)} placements)" for p in inv.subslice_profiles
        )
        print(f"subslice profiles: {profs}")
    return 0


def cmd_health(args: argparse.Namespace) -> int:
    from k8s_dra_driver_tpu.tpulib.real import RealTpuLib

    from k8s_dra_driver_tpu.tpulib.types import ChipHealth

    lib = new_tpulib()
    if isinstance(lib, RealTpuLib):
        h = lib.chip_health(args.chip)
    else:
        inv = lib.enumerate()
        try:
            h = inv.chip_by_index(args.chip).health
        except KeyError:
            h = ChipHealth.UNHEALTHY
    print(h.value)
    return 0 if h.value == "healthy" else 1


def main(argv=None) -> int:
    parser = argparse.ArgumentParser(prog="tpu-info")
    sub = parser.add_subparsers(dest="cmd", required=True)
    p_info = sub.add_parser("info", help="enumerate this host")
    p_info.add_argument("--json", action="store_true")
    p_info.set_defaults(fn=cmd_info)
    p_health = sub.add_parser("health", help="probe one chip")
    p_health.add_argument("chip", type=int)
    p_health.set_defaults(fn=cmd_health)
    args = parser.parse_args(argv)
    return args.fn(args)


if __name__ == "__main__":
    sys.exit(main())
