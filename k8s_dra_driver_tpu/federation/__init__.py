"""Federated fleet: WAL-streamed replication and cross-cluster placement.

``replication`` turns one cluster's store into a streamable change feed
(leader :class:`ReplicationSource`) and keeps a full follower store
converged from it (:class:`ReplicaStore`) so reads, scrapes and
``tpu-kubectl`` offload to a replica; ``scheduler`` places workloads
across clusters by fleet headroom and spills serving traffic when a
region's SLO burns. See ``docs/reference/federation.md``.
"""

from k8s_dra_driver_tpu.federation.query import (
    federation_status_rows,
    inject_cluster_label,
    merge_metrics_texts,
)
from k8s_dra_driver_tpu.federation.replication import (
    ReplicaStore,
    ReplicationError,
    ReplicationSource,
)
from k8s_dra_driver_tpu.federation.scheduler import (
    ClusterView,
    GlobalScheduler,
    Placement,
    PlacementRequest,
    PlacementResult,
)

__all__ = [
    "ClusterView",
    "GlobalScheduler",
    "Placement",
    "PlacementRequest",
    "PlacementResult",
    "ReplicaStore",
    "ReplicationError",
    "ReplicationSource",
    "federation_status_rows",
    "inject_cluster_label",
    "merge_metrics_texts",
]
