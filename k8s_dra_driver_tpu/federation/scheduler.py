"""Cross-cluster global scheduler: fleet-level placement + SLO spill.

One cluster is one failure domain; the fleet is several clusters (each
its own store/allocator/autoscaler stack, possibly a read replica of a
peer) federated behind this thin placement layer. It deliberately does
NOT re-implement per-cluster scheduling — node fit, topology tiers, and
queue discipline stay inside each cluster's allocator. The global layer
answers exactly two questions:

1. **Which cluster takes this workload?** ``place()`` apportions the
   demanded chips across clusters with the same weighted max-min
   water-filling the in-cluster WFQ uses (``scheduling.fair_apportion``
   — demand = per-cluster free headroom, weight = the operator's
   per-cluster weight), then greedily packs requests largest-first into
   the granted budgets. Headroom comes from the same callable contract
   the autoscaler's ``headroom_fn`` uses, so the sim, a live allocator
   overview, or a telemetry rollup all plug in unchanged.

2. **When do we spill serving traffic?** ``spill()`` watches a
   cluster's SLO evaluator; while error-budget burn alerts fire it
   shifts a burn-proportional fraction of serving traffic to the
   healthiest peer (max headroom), so a follower region absorbs load
   precisely when the local region is eating its budget.

Placement decisions land in the history store
(``controller="federation"``) so ``tpu-kubectl explain`` can answer
*why* a domain runs where it runs.
"""

from __future__ import annotations

import logging
import math
import time
from dataclasses import dataclass, field
from typing import Callable, Dict, List, Optional, Sequence, Tuple

from k8s_dra_driver_tpu.pkg import tracing
from k8s_dra_driver_tpu.pkg.history import RULE_FED_PLACE, RULE_FED_SPILL
from k8s_dra_driver_tpu.scheduling import fair_apportion

log = logging.getLogger(__name__)

# Spill is proportional to how hard the worst alert burns: burn 1.0 is
# break-even (no spill), SPILL_FULL_BURN and beyond shifts MAX_SPILL of
# traffic. Linear in between — smooth handoff, no flapping cliff.
SPILL_FULL_BURN = 10.0
MAX_SPILL = 0.9


@dataclass
class ClusterView:
    """One cluster as the global scheduler sees it.

    ``free_chips`` follows the autoscaler ``headroom_fn`` contract: a
    zero-arg callable returning currently-unallocated chips (the sim
    wires ``SimCluster._fleet_free_chips``; production wires the
    allocator's placement overview). ``slo`` is an optional
    ``pkg.slo.SLOEvaluator`` whose ``active_alerts()`` drives serving
    spill. ``api`` is whatever answers reads for the cluster — the
    leader store, a ``ReplicaStore.api``, or a ``RemoteAPIServer``."""

    name: str
    api: object = None
    free_chips: Callable[[], int] = lambda: 0
    weight: float = 1.0
    slo: object = None


@dataclass(frozen=True)
class PlacementRequest:
    """One workload asking the fleet for room."""

    name: str
    chips: int
    kind: str = "ComputeDomain"
    namespace: str = "default"


@dataclass(frozen=True)
class Placement:
    request: PlacementRequest
    cluster: str


@dataclass
class PlacementResult:
    placements: List[Placement] = field(default_factory=list)
    unplaced: List[PlacementRequest] = field(default_factory=list)
    headroom: Dict[str, int] = field(default_factory=dict)
    # The fleet-level trace this placement round ran under. Stamp it
    # onto the objects you create from the placements
    # (tracing.inject_context) and the target cluster's scheduler binds
    # under the same trace — the cross-cluster causal chain explain
    # stitches back together.
    trace_id: str = ""
    span_context: Optional[tracing.SpanContext] = None

    def cluster_of(self, name: str) -> Optional[str]:
        for p in self.placements:
            if p.request.name == name:
                return p.cluster
        return None


class GlobalScheduler:
    """Fleet-level placement over :class:`ClusterView` rows."""

    def __init__(self, clusters: Sequence[ClusterView],
                 recorder=None, history=None,
                 metrics_registry=None,
                 clock: Callable[[], float] = time.time):
        if not clusters:
            raise ValueError("GlobalScheduler needs at least one cluster")
        names = [c.name for c in clusters]
        if len(set(names)) != len(names):
            raise ValueError(f"duplicate cluster names: {names}")
        self.clusters: Dict[str, ClusterView] = {c.name: c for c in clusters}
        self.recorder = recorder
        self.history = history
        self.clock = clock
        # Context of the most recent spill decision that fired — what a
        # caller applying the spill stamps onto the migrated workload.
        self.last_spill_context: Optional[tracing.SpanContext] = None
        self._metrics = None
        if metrics_registry is not None:
            self.attach_metrics(metrics_registry)

    def attach_metrics(self, registry) -> None:
        from k8s_dra_driver_tpu.pkg.metrics import Counter, Gauge

        self._metrics = {
            "headroom": registry.register(Gauge(
                "tpu_dra_federation_headroom_chips",
                "Free chips per federated cluster at the last placement "
                "or spill evaluation.",
                label_names=("cluster",))),
            "placements": registry.register(Counter(
                "tpu_dra_federation_placements_total",
                "Cross-cluster placement decisions, by target cluster "
                "and outcome (placed/unplaced).",
                label_names=("cluster", "outcome"))),
            "spill": registry.register(Gauge(
                "tpu_dra_federation_spill_fraction",
                "Fraction of serving traffic spilling away from a "
                "burning cluster toward its healthiest peer.",
                label_names=("cluster",))),
        }

    # -- headroom ------------------------------------------------------------

    def headroom(self) -> Dict[str, int]:
        """Free chips per cluster right now. A cluster whose headroom
        callable raises (partitioned, leader down) reports 0 — it simply
        attracts no placements until it answers again."""
        out: Dict[str, int] = {}
        for name, c in self.clusters.items():
            try:
                out[name] = max(0, int(c.free_chips()))
            except Exception:  # noqa: BLE001 — unreachable cluster = no room
                log.warning("cluster %s headroom probe failed", name,
                            exc_info=True)
                out[name] = 0
        if self._metrics is not None:
            for name, free in out.items():
                self._metrics["headroom"].set(name, value=float(free))
        return out

    # -- placement -----------------------------------------------------------

    def place(self, requests: Sequence[PlacementRequest]) -> PlacementResult:
        """Place each request on exactly one cluster.

        Budgeting is the WFQ water-fill: the demanded chip total is
        apportioned across clusters (demand = headroom, weight =
        operator weight), so no cluster is asked for more than it has
        free and a weighted cluster soaks proportionally more of the
        fleet's load. Packing is greedy largest-first into the budgets
        (whole requests never split across clusters — a ComputeDomain's
        ICI mesh lives in one failure domain), with a best-fit fallback
        onto raw headroom so a request bigger than its fair share still
        lands when some cluster has genuine room."""
        # One span per placement round: the DecisionRecords written in
        # _note() inherit its trace id, and callers propagate it onto
        # the placed objects (result.span_context) so the target
        # cluster's bind/prepare spans join the same fleet-level trace.
        with tracing.span("federation.place",
                          clusters=sorted(self.clusters),
                          requests=len(requests)) as sp:
            result = PlacementResult(headroom=self.headroom(),
                                     trace_id=sp.trace_id,
                                     span_context=sp.context)
            budgets = fair_apportion(
                demands={n: float(h) for n, h in result.headroom.items()},
                weights={n: c.weight for n, c in self.clusters.items()},
                capacity=float(sum(r.chips for r in requests)),
            )
            remaining = dict(result.headroom)
            for req in sorted(requests, key=lambda r: (-r.chips, r.name)):
                target = self._pick(req.chips, budgets, remaining)
                if target is None:
                    result.unplaced.append(req)
                    self._note(req, None, result.headroom)
                    continue
                budgets[target] = budgets.get(target, 0.0) - req.chips
                remaining[target] -= req.chips
                result.placements.append(
                    Placement(request=req, cluster=target))
                self._note(req, target, result.headroom)
        return result

    def _pick(self, chips: int, budgets: Dict[str, float],
              remaining: Dict[str, int]) -> Optional[str]:
        # First choice: the cluster with the most unused fair-share
        # budget that can actually hold the request.
        fits = [n for n, free in remaining.items() if free >= chips]
        if not fits:
            return None
        by_budget = sorted(fits, key=lambda n: (-budgets.get(n, 0.0), n))
        if budgets.get(by_budget[0], 0.0) >= chips:
            return by_budget[0]
        # Fallback: best fit on raw headroom (tightest cluster that
        # holds it) — fair share is advisory once budgets run dry.
        return min(fits, key=lambda n: (remaining[n], n))

    def _note(self, req: PlacementRequest, cluster: Optional[str],
              headroom: Dict[str, int]) -> None:
        outcome = f"placed:{cluster}" if cluster else "unplaced"
        if self._metrics is not None:
            self._metrics["placements"].inc(cluster or "none",
                                            "placed" if cluster
                                            else "unplaced")
        if self.history is not None:
            self.history.decide(
                controller="federation", rule=RULE_FED_PLACE, outcome=outcome,
                kind=req.kind, namespace=req.namespace, name=req.name,
                message=(f"{req.chips} chips -> {cluster}" if cluster else
                         f"{req.chips} chips unplaced: no cluster has room"),
                inputs={"chips": req.chips, "headroom": dict(headroom)},
                now=self.clock())

    # -- serving spill -------------------------------------------------------

    def spill(self, cluster: str) -> Tuple[float, Optional[str]]:
        """(fraction, target): how much of ``cluster``'s serving traffic
        should run against a peer right now, and which peer. Zero while
        the local SLO holds (or no evaluator is wired); while burn
        alerts fire the fraction climbs linearly with the worst burn
        rate (break-even burn 1.0 → 0, ``SPILL_FULL_BURN`` →
        ``MAX_SPILL``) and the target is the peer with the most free
        chips. No peer with headroom → no spill: degraded local serving
        beats sending traffic to a full cluster."""
        view = self.clusters[cluster]
        burn = 0.0
        if view.slo is not None:
            try:
                alerts = view.slo.active_alerts()
            except Exception:  # noqa: BLE001 — SLO eval must not break spill
                alerts = []
            burn = max((a.burn_rate for a in alerts), default=0.0)
        frac = 0.0
        if burn > 1.0:
            frac = min(MAX_SPILL,
                       MAX_SPILL * (burn - 1.0) / (SPILL_FULL_BURN - 1.0))
        target: Optional[str] = None
        if frac > 0.0:
            peers = {n: h for n, h in self.headroom().items()
                     if n != cluster and h > 0}
            if peers:
                target = max(sorted(peers), key=lambda n: peers[n])
            else:
                frac = 0.0
        if self._metrics is not None:
            self._metrics["spill"].set(cluster, value=frac)
        if frac > 0.0:
            # The spill decision opens the fleet-level trace: its id
            # lands on the DecisionRecord, and last_spill_context lets
            # the caller stamp the spilled workload's annotations
            # (tracing.inject_context) so the receiving cluster's bind
            # joins the same trace across the replication boundary.
            with tracing.span("federation.spill", cluster=cluster,
                              target=target, burn=round(burn, 3)) as sp:
                self.last_spill_context = sp.context
                if self.history is not None:
                    self.history.decide(
                        controller="federation", rule=RULE_FED_SPILL,
                        outcome=f"spill:{target}",
                        kind="Cluster", name=cluster,
                        message=(f"burn {burn:.2f}: spilling "
                                 f"{math.floor(frac * 100)}% of serving "
                                 f"traffic to {target}"),
                        inputs={"burn_rate": burn, "fraction": frac,
                                "target": target},
                        now=self.clock())
        return frac, target
