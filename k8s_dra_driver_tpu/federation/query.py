"""Global query plane: fleet-wide metric and status aggregation.

The federation layer's read side. Every cluster already exposes the
same surfaces — /metrics text, /replica/watermark staleness, /history
flight-recorder routes — so the fleet-wide view is a *merge*, not a new
protocol: scrape each peer, stamp every sample with a ``cluster``
label, and let the existing consumers (``tpu-kubectl top
--all-clusters``, the /federation/metrics HTTP route, dashboards) read
the union exactly as they read one cluster.

Pure text/dict transforms live here (stdlib only, no HTTP): the HTTP
fan-out stays in ``k8s.httpapi`` and ``sim.kubectl`` where the
transports already are.
"""

from __future__ import annotations

from typing import Dict, List, Optional

# Label injected into every merged sample. A peer that already carries
# a label with this name keeps its own value (it knows better).
CLUSTER_LABEL = "cluster"


def _escape_label_value(value: str) -> str:
    return (value.replace("\\", "\\\\").replace('"', '\\"')
            .replace("\n", "\\n"))


def inject_cluster_label(text: str, cluster: str) -> str:
    """Rewrite one cluster's Prometheus text exposition so every sample
    carries ``cluster="<name>"``. Comment lines (# HELP / # TYPE) pass
    through untouched; malformed lines pass through untouched too — an
    aggregator must degrade, never censor."""
    label = f'{CLUSTER_LABEL}="{_escape_label_value(cluster)}"'
    out: List[str] = []
    for line in text.splitlines():
        stripped = line.strip()
        if not stripped or stripped.startswith("#"):
            out.append(line)
            continue
        brace = stripped.find("{")
        if brace >= 0:
            close = stripped.rfind("}")
            if close <= brace:
                out.append(line)  # malformed: forward verbatim
                continue
            inner = stripped[brace + 1:close]
            if f'{CLUSTER_LABEL}="' in inner:
                out.append(line)
                continue
            merged = f"{label},{inner}" if inner else label
            out.append(stripped[:brace] + "{" + merged + "}"
                       + stripped[close + 1:])
        else:
            # Bare `name value`: split on first whitespace.
            name, _, rest = stripped.partition(" ")
            if not rest:
                out.append(line)
                continue
            out.append(f"{name}{{{label}}} {rest}")
    return "\n".join(out) + "\n"


def merge_metrics_texts(texts: Dict[str, str]) -> str:
    """Merge per-cluster scrapes into one exposition: each cluster's
    samples get the ``cluster`` label; duplicate # HELP/# TYPE headers
    (every peer emits the same families) are kept once, first writer
    wins."""
    seen_comments: set = set()
    out: List[str] = []
    for cluster in sorted(texts):
        for line in inject_cluster_label(texts[cluster],
                                         cluster).splitlines():
            if line.startswith("#"):
                if line in seen_comments:
                    continue
                seen_comments.add(line)
            out.append(line)
    return "\n".join(out) + "\n"


def federation_status_rows(
        statuses: Dict[str, Optional[dict]],
        now: Optional[float] = None) -> List[List[str]]:
    """`tpu-kubectl federation status` table rows from per-peer
    /replica/watermark answers (None = the peer answered but is not a
    replica; missing entries are the caller's SKIPPED rows). Columns:
    PEER, ROLE, WATERMARK, LAG, RECONNECTS, LAST-HEARTBEAT."""
    rows: List[List[str]] = []
    for peer in sorted(statuses):
        st = statuses[peer]
        if st is None:
            rows.append([peer, "leader", "-", "-", "-", "-"])
            continue
        beat = st.get("last_heartbeat", 0.0) or 0.0
        if now is not None and beat > 0.0:
            heartbeat = f"{max(0.0, now - beat):.1f}s ago"
        elif beat > 0.0:
            heartbeat = f"@{beat:.1f}"
        else:
            heartbeat = "never"
        role = "promoted" if st.get("promoted") else "replica"
        rows.append([
            peer, role,
            str(st.get("watermark", 0)),
            str(st.get("lag_records", 0)),
            str(st.get("reconnects", 0)),
            heartbeat,
        ])
    return rows
