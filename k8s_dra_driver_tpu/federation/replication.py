"""WAL-streamed store replication: leader source + follower replica.

The PR 8 write-ahead log already IS an ordered, fingerprint-tokened
change stream — every mutation appends ``{"seq","op","key","fp","obj"}``
with the object body spliced from the snapshot's cached wire encoding
(serialize-once, k8s.serialize.wire_json). Replication therefore never
invents a second change feed: the leader-side :class:`ReplicationSource`
*tails the WAL files on disk* and forwards the raw record lines, and the
follower-side :class:`ReplicaStore` applies them through the store's
normal publish/freeze path (``APIServer.apply_replicated``), so the
replica's informers, watch fan-out, telemetry rollups and ``tpu-kubectl``
all run unmodified against it.

Protocol (transport-agnostic; k8s.httpapi carries it over chunked HTTP):

- ``status()`` — current epoch, ring watermark (the global dispatch-ring
  sequence), snapshot watermark, stream ids (-1 = the shared group-commit
  file; durable mode streams one file per shard) and the per-kind
  fingerprint tokens.
- ``snapshot()`` — the leader's on-disk snapshot document (the exact
  format ``k8s.persist`` writes and replays): bootstrap AND resync are
  the restore path, not a third code path.
- ``tail(stream, from_seq)`` — raw WAL record lines with seq strictly
  above ``from_seq``, then live-tailing. Control lines:
  ``{"ctl": "SNAPSHOT", ...}`` (the follower's watermark predates the
  leader's snapshot — those records are compacted away; re-bootstrap),
  ``{"ctl": "HEARTBEAT", "watermark": N}`` (keepalive + the leader's
  head position, the follower's lag denominator).

Watermark semantics: the dispatch-ring ``seq`` is globally monotone and
every record carries it, so "resume at the watermark" is exact — a
reconnecting follower asks for ``from_seq = last applied`` and can
neither duplicate (seq <= watermark is skipped) nor gap (every record
above the snapshot watermark still lives in an on-disk epoch file until
a compaction folds it into the snapshot, and a follower older than the
snapshot watermark is told to re-bootstrap). Epoch rotation mid-tail is
seamless: the tail drains the rotated file to EOF (a POSIX unlink does
not invalidate an open descriptor), then switches to the next epoch.
The per-kind fingerprint tokens ride every record and are installed
verbatim, so leader and converged follower are fingerprint-TOKEN
identical — the same O(1) equality the restore acceptance test pins.
"""

from __future__ import annotations

import json
import logging
import os
import threading
import time
from typing import Callable, Dict, Iterator, List, Optional, Tuple

from k8s_dra_driver_tpu.k8s.persist import (
    SNAPSHOT_FILE,
    StoreWAL,
    discover_wal_files,
)
from k8s_dra_driver_tpu.k8s.serialize import from_wire
from k8s_dra_driver_tpu.k8s.store import APIServer

log = logging.getLogger(__name__)

# Tail cadence: how often the source re-polls its files for new bytes and
# the ceiling on control-line silence (heartbeats let a blocked reader
# notice a stop/partition within one beat).
TAIL_POLL_S = 0.02
TAIL_HEARTBEAT_S = 1.0

# Follower supervisor: reconnect backoff after a severed stream.
RECONNECT_BACKOFF_S = 0.2

# Records of head-vs-applied lag past which the follower is considered
# lagging (ReplicaLagging event through the injected recorder).
DEFAULT_LAG_ALERT_RECORDS = 5000


class ReplicationError(RuntimeError):
    """A WAL stream violated the protocol (corrupt mid-file record)."""


class ReplicationSource:
    """Leader half: serves snapshot handoffs and tails WAL files.

    Attach to the hosting store as ``api.replication = source`` — the
    HTTPAPIServer probes exactly that attribute (the same 404-degrade
    seam as ``api.history``) to decide whether the ``/replication/*``
    routes exist. The source only ever READS the leader: snapshot bytes
    come off disk, record lines are forwarded verbatim (the spliced
    cached encodings — the object graph is never re-walked here), and
    the one mutation it may trigger is an initial ``wal.compact`` when
    no snapshot exists yet."""

    def __init__(self, api: APIServer, wal: Optional[StoreWAL] = None):
        self._api = api
        self._wal = wal if wal is not None else api._wal
        if self._wal is None:
            raise ValueError("ReplicationSource needs a store with an "
                             "attached WAL (open_persistent_store)")
        self._metrics = None

    # -- wiring --------------------------------------------------------------

    @property
    def dirpath(self) -> str:
        return self._wal.dirpath

    def attach_metrics(self, registry) -> None:
        from k8s_dra_driver_tpu.pkg.metrics import Counter

        self._metrics = {
            "records": registry.register(Counter(
                "tpu_dra_replication_stream_records_total",
                "WAL records streamed to replication followers, by "
                "stream (-1 = the shared group-commit file).",
                label_names=("stream",))),
            "snapshots": registry.register(Counter(
                "tpu_dra_replication_snapshots_served_total",
                "Snapshot handoffs served to bootstrapping or resyncing "
                "followers.")),
        }

    # -- protocol ------------------------------------------------------------

    def _ring_watermark(self) -> int:
        with self._api._ring_mu:
            return self._api._ring_seq

    def _snapshot_head(self) -> Tuple[int, int]:
        """(snapshot watermark, snapshot epoch) from the on-disk snapshot
        head, or (0, 0) when none exists. Reads only the head line's
        fields — the objects array is not materialized here."""
        path = os.path.join(self.dirpath, SNAPSHOT_FILE)
        try:
            with open(path, encoding="utf-8") as f:
                doc = json.load(f)
        except (OSError, json.JSONDecodeError):
            return (0, 0)
        return (int(doc.get("watermark", 0)), int(doc.get("epoch", 0)))

    def status(self) -> dict:
        snap_w, snap_epoch = self._snapshot_head()
        if self._wal.fsync:
            streams = list(range(len(self._api._shards)))
        else:
            streams = [-1]
        with self._api._locked_all():
            fps = {}
            for shard in self._api._shards:
                fps.update(shard.fp)
        return {
            "epoch": self._wal._epoch,
            "watermark": self._ring_watermark(),
            "snapshot_watermark": snap_w,
            "snapshot_epoch": snap_epoch,
            "streams": streams,
            "fps": {kind: list(fp) for kind, fp in fps.items()},
        }

    def snapshot(self) -> dict:
        """The snapshot document for a bootstrap/resync handoff. One is
        guaranteed to exist (open_persistent_store compacts at open); a
        bare StoreWAL attach without one triggers a single compaction so
        the handoff always has a restore point."""
        path = os.path.join(self.dirpath, SNAPSHOT_FILE)
        if not os.path.exists(path):
            self._wal.compact(self._api)
        with open(path, encoding="utf-8") as f:
            doc = json.load(f)
        if self._metrics is not None:
            self._metrics["snapshots"].inc()
        return doc

    def fetch(self, stream: int, from_seq: int) -> Tuple[List[str], int]:
        """One non-blocking sweep: every currently-complete record line
        for ``stream`` with seq > ``from_seq``, in order, plus the new
        watermark. The bounded sibling of :meth:`tail` for tests and the
        sanitizer's explored schedules (no sleeps, no threads)."""
        out: List[str] = []
        last = from_seq
        for epoch, shard, path in discover_wal_files(self.dirpath):
            if shard != stream:
                continue
            for line, complete in _read_lines(path):
                if not complete:
                    break  # torn/in-flight tail: next sweep retries
                seq = _record_seq(line)
                if seq <= last:
                    continue
                out.append(line)
                last = seq
        if self._metrics is not None and out:
            self._metrics["records"].inc(str(stream), by=float(len(out)))
        return out, last

    def tail(self, stream: int, from_seq: int,
             stop: Optional[threading.Event] = None,
             poll_s: float = TAIL_POLL_S,
             heartbeat_s: float = TAIL_HEARTBEAT_S) -> Iterator[str]:
        """Stream raw record lines for one WAL stream from ``from_seq``,
        live-tailing until ``stop`` is set. Yields control lines (see
        module docstring) interleaved; record lines are the on-disk bytes
        verbatim. Epoch rotation is followed (drain old epoch to EOF,
        switch to the next); a follower older than the on-disk snapshot
        is handed ``{"ctl": "SNAPSHOT"}`` and the stream ends."""
        snap_w, _ = self._snapshot_head()
        if from_seq < snap_w:
            yield json.dumps({"ctl": "SNAPSHOT", "watermark": snap_w})
            return
        last = from_seq
        done_epoch = -1          # epochs fully consumed for this stream
        cur: Optional[Tuple[int, str]] = None   # (epoch, path) being tailed
        fobj = None
        buf = ""
        last_beat = time.monotonic()
        try:
            while stop is None or not stop.is_set():
                progressed = False
                if fobj is None:
                    for epoch, shard, path in discover_wal_files(self.dirpath):
                        if shard == stream and epoch > done_epoch:
                            cur = (epoch, path)
                            fobj = open(path, encoding="utf-8")
                            buf = ""
                            break
                if fobj is not None:
                    chunk = fobj.read()
                    if chunk:
                        buf += chunk
                        lines = buf.split("\n")
                        buf = lines.pop()  # empty iff chunk ended on "\n"
                        for line in lines:
                            if not line.strip():
                                continue
                            seq = _record_seq(line)
                            if seq <= last:
                                continue
                            last = seq
                            progressed = True
                            if self._metrics is not None:
                                self._metrics["records"].inc(str(stream))
                            yield line
                    else:
                        rotated = self._wal._epoch > cur[0]
                        if rotated and not buf:
                            fobj.close()
                            fobj, done_epoch = None, cur[0]
                            continue
                        if rotated and buf:
                            # A rotated epoch can never complete its
                            # partial last line: it is a crash artifact
                            # (torn tail). Same policy as replay: drop it
                            # loudly and move on.
                            log.warning(
                                "dropping torn tail (%d bytes) at end of "
                                "rotated WAL epoch %d stream %d",
                                len(buf), cur[0], stream)
                            fobj.close()
                            fobj, done_epoch, buf = None, cur[0], ""
                            continue
                if not progressed:
                    nowm = time.monotonic()
                    if nowm - last_beat >= heartbeat_s:
                        last_beat = nowm
                        yield json.dumps({"ctl": "HEARTBEAT",
                                          "watermark": self._ring_watermark()})
                    if stop is not None:
                        stop.wait(poll_s)
                    else:
                        time.sleep(poll_s)
        finally:
            if fobj is not None:
                fobj.close()


def _read_lines(path: str) -> Iterator[Tuple[str, bool]]:
    """Yield (line, complete) for one WAL file; the final element is
    marked incomplete when the file does not end in a newline (torn or
    in-flight append)."""
    try:
        with open(path, encoding="utf-8") as f:
            data = f.read()
    except OSError:
        return
    if not data:
        return
    complete_tail = data.endswith("\n")
    lines = data.split("\n")
    if lines and lines[-1] == "":
        lines.pop()
    for i, line in enumerate(lines):
        if not line.strip():
            continue
        yield line, (i < len(lines) - 1) or complete_tail


def _record_seq(line: str) -> int:
    """The seq of one raw record line. Parses the JSON head only via the
    standard decoder; a complete line that does not parse is corruption,
    not a torn tail, and must fail loudly (the torn-tail case never
    reaches here — incomplete lines are held back by the tailer)."""
    try:
        return int(json.loads(line)["seq"])
    except (json.JSONDecodeError, KeyError, TypeError, ValueError) as e:
        raise ReplicationError(
            f"corrupt WAL record line ({e}): {line[:120]!r}") from None


class ReplicaStore:
    """Follower half: a full APIServer kept converged with a leader by
    applying its WAL stream through ``apply_replicated``.

    ``source`` is anything implementing the protocol trio
    status()/snapshot()/tail() — the in-process
    :class:`ReplicationSource` or ``k8s.httpapi.RemoteReplicationSource``
    over the wire. The replica's ``api`` is ``read_only`` (mutating verbs
    raise ReadOnlyStoreError) until :meth:`promote` flips it writable on
    leader failover. The replica hangs itself off the store as
    ``api.replica`` — the watermark-stamping seam tpu-kubectl and the
    ``/replica/watermark`` HTTP route read."""

    def __init__(self, source, shards: Optional[int] = None,
                 cluster: str = "follower",
                 poll_s: float = TAIL_POLL_S,
                 metrics_registry=None,
                 recorder=None,
                 history=None,
                 lag_alert_records: int = DEFAULT_LAG_ALERT_RECORDS,
                 clock: Callable[[], float] = time.time):
        from k8s_dra_driver_tpu.k8s.store import DEFAULT_STORE_SHARDS

        self.source = source
        self.cluster = cluster
        self.poll_s = poll_s
        self.recorder = recorder
        # Optional flight recorder for the failover DecisionRecord
        # (federation/failover). The fleet harness wires the leader's
        # history store; standalone replicas run without one.
        self.history = history
        self.lag_alert_records = lag_alert_records
        self.clock = clock
        self.api = APIServer(shards=shards or DEFAULT_STORE_SHARDS)
        self.api.read_only = True
        self.api.replica = self
        self._mu = threading.Lock()
        self._watermarks: Dict[int, int] = {}  # tpulint: guarded-by=_mu
        self._head = 0  # tpulint: guarded-by=_mu (leader watermark last seen)
        self._applied = 0  # tpulint: guarded-by=_mu
        self._resyncs = 0  # tpulint: guarded-by=_mu
        self._reconnects = 0  # tpulint: guarded-by=_mu
        self._lagging = False  # tpulint: guarded-by=_mu
        self._last_heartbeat = 0.0  # tpulint: guarded-by=_mu (clock time)
        self.promoted = False
        self._stop = threading.Event()
        self._supervisor: Optional[threading.Thread] = None
        self._metrics = None
        if metrics_registry is not None:
            self.attach_metrics(metrics_registry)

    # -- wiring --------------------------------------------------------------

    def attach_metrics(self, registry) -> None:
        from k8s_dra_driver_tpu.pkg.metrics import (
            REPLICATION_LATENCY_BUCKETS,
            Counter,
            Gauge,
            Histogram,
        )

        self._metrics = {
            "applied": registry.register(Counter(
                "tpu_dra_replication_applied_total",
                "Replicated WAL records applied to this replica store, "
                "by op (PUT/DEL).",
                label_names=("op",))),
            "apply_latency": registry.register(Histogram(
                "tpu_dra_replication_apply_seconds",
                "Per-record apply cost on the replica (wire decode + "
                "store install + watch fan-out).",
                buckets=REPLICATION_LATENCY_BUCKETS)),
            "watermark": registry.register(Gauge(
                "tpu_dra_replication_watermark",
                "Highest leader WAL sequence applied, by stream.",
                label_names=("stream",))),
            "lag": registry.register(Gauge(
                "tpu_dra_replication_lag_records",
                "Leader head watermark minus this replica's applied "
                "watermark (records the replica still has to apply).")),
            "resyncs": registry.register(Counter(
                "tpu_dra_replication_resyncs_total",
                "Snapshot re-bootstraps (first bootstrap, or the leader "
                "compacted past this replica's watermark).")),
            "reconnects": registry.register(Counter(
                "tpu_dra_replication_reconnects_total",
                "Severed replication streams re-established (partition "
                "heal, leader restart).")),
        }

    # -- observability -------------------------------------------------------

    def watermark(self) -> int:
        """Highest leader WAL seq applied across streams — what follower
        answers are stamped with so staleness is visible."""
        with self._mu:
            return max(self._watermarks.values(), default=0)

    def lag_records(self) -> int:
        with self._mu:
            return max(0, self._head - max(self._watermarks.values(),
                                           default=0))

    def status(self) -> dict:
        with self._mu:
            applied_w = max(self._watermarks.values(), default=0)
            return {
                "cluster": self.cluster,
                "watermark": applied_w,
                "watermarks": {str(s): w
                               for s, w in sorted(self._watermarks.items())},
                "head": self._head,
                "lag_records": max(0, self._head - applied_w),
                "applied": self._applied,
                "resyncs": self._resyncs,
                "reconnects": self._reconnects,
                "promoted": self.promoted,
                "last_heartbeat": self._last_heartbeat,
            }

    def heartbeat_age(self) -> Optional[float]:
        """Seconds since the leader last answered (heartbeat line,
        status poll, or applied record), or None before first contact.
        The `tpu-kubectl federation status` freshness column."""
        with self._mu:
            if self._last_heartbeat <= 0.0:
                return None
            last = self._last_heartbeat
        return max(0.0, self.clock() - last)

    def _mark_heartbeat(self) -> None:
        now = self.clock()
        with self._mu:
            self._last_heartbeat = max(self._last_heartbeat, now)

    # -- lifecycle -----------------------------------------------------------

    def start(self, bootstrap: bool = True) -> "ReplicaStore":
        """Bootstrap from the leader snapshot (synchronously, so callers
        observe a populated replica on return) and start the streaming
        supervisor."""
        if bootstrap:
            self._bootstrap()
        self._supervisor = threading.Thread(
            target=self._run, name=f"replica-{self.cluster}", daemon=True)
        self._supervisor.start()
        return self

    def stop(self) -> None:
        self._stop.set()
        if self._supervisor is not None:
            self._supervisor.join(timeout=10)
            self._supervisor = None

    def promote(self) -> APIServer:
        """Leader failover: stop replicating, flip the store writable,
        and resume the rv counter past everything replicated. The
        FailoverStarted/FailoverCompleted events land in the replica's
        OWN store (the leader may be gone — that is why we are here)."""
        from k8s_dra_driver_tpu.pkg import tracing
        from k8s_dra_driver_tpu.pkg.events import (
            REASON_FAILOVER_COMPLETED,
            REASON_FAILOVER_STARTED,
        )
        from k8s_dra_driver_tpu.pkg.history import RULE_FED_FAILOVER

        self.stop()
        self.api.read_only = False
        # One failover trace: the Started/Completed events and the
        # federation/failover DecisionRecord all carry its id, so a
        # cross-cluster explain stitches the whole promotion — which
        # cluster, at what watermark — into one causal chain.
        with tracing.span("federation.failover", cluster=self.cluster,
                          watermark=self.watermark()):
            rec = self._failover_recorder()
            if rec is not None:
                rec.normal(self._cluster_ref(), REASON_FAILOVER_STARTED,
                           f"promoting replica of cluster "
                           f"{self.cluster!r} at watermark "
                           f"{self.watermark()}")
            self.api.resume_rv()
            self.promoted = True
            if rec is not None:
                rec.normal(self._cluster_ref(), REASON_FAILOVER_COMPLETED,
                           f"replica {self.cluster!r} serving writes "
                           f"(watermark {self.watermark()})")
            if self.history is not None:
                try:
                    self.history.decide(
                        controller="federation", rule=RULE_FED_FAILOVER,
                        outcome="promoted",
                        kind="Cluster", name=self.cluster,
                        message=(f"replica {self.cluster!r} promoted to "
                                 f"writable at watermark "
                                 f"{self.watermark()}"),
                        inputs={"watermark": self.watermark(),
                                "applied": self.status()["applied"]},
                        now=self.clock())
                except Exception:  # noqa: BLE001 — provenance must not block failover
                    log.exception("failover decision record failed")
        return self.api

    def _failover_recorder(self):
        try:
            from k8s_dra_driver_tpu.pkg.events import EventRecorder

            return EventRecorder(self.api, "federation", clock=self.clock)
        except Exception:  # noqa: BLE001 — telemetry must not block failover
            log.exception("failover event recorder unavailable")
            return None

    def _cluster_ref(self):
        from k8s_dra_driver_tpu.k8s.core import ObjectReference

        return ObjectReference(kind="Cluster", name=self.cluster,
                               namespace="", uid="")

    # -- bootstrap / resync --------------------------------------------------

    def _bootstrap(self) -> None:
        """Snapshot handoff, applied as a DIFF against current replica
        contents: unchanged revisions (same stamped resourceVersion) are
        skipped, changed/new objects are upserted, local keys absent from
        the snapshot get synthesized deletes — so a RE-bootstrap (resync
        after the leader compacted past us) keeps the replica's informers
        and watch subscribers alive instead of tearing the store down.
        Fingerprint tokens then land wholesale, token-identical to the
        snapshot head."""
        doc = self.source.snapshot()
        watermark = int(doc.get("watermark", 0))
        fps = {k: (int(v[0]), int(v[1]))
               for k, v in doc.get("fps", {}).items()}
        live: set = set()
        for obj_doc in doc.get("objects", ()):
            obj = from_wire(obj_doc)
            key = (obj.kind, obj.meta.namespace, obj.meta.name)
            live.add(key)
            cur = self.api.try_get(key[0], key[2], key[1])
            if (cur is not None
                    and cur.meta.resource_version == obj.meta.resource_version):
                continue
            self.api.apply_replicated("PUT", obj, key, None)
            self._count_apply("PUT")
        # One pass over the replica's own shards (it owns them — nothing
        # else writes a read-only store) instead of a per-kind list().
        with self.api._locked_all():
            local_keys = [k for shard in self.api._shards
                          for k in shard.objects]
        for key in local_keys:
            if key not in live:
                self.api.apply_replicated("DEL", None, key, None)
                self._count_apply("DEL")
        self.api.install_fingerprints(fps)
        with self._mu:
            self._resyncs += 1
            self._head = max(self._head, watermark)
            for s in list(self._watermarks) or []:
                self._watermarks[s] = max(self._watermarks[s], watermark)
            self._bootstrap_watermark = watermark
        if self._metrics is not None:
            self._metrics["resyncs"].inc()
        log.info("replica %s bootstrapped: %d objects, watermark %d",
                 self.cluster, len(doc.get("objects", ())), watermark)

    # -- streaming -----------------------------------------------------------

    def _run(self) -> None:
        backoff = RECONNECT_BACKOFF_S
        first_round = True
        while not self._stop.is_set():
            try:
                st = self.source.status()
            except Exception:  # noqa: BLE001 — partition/leader-down: retry
                self._stop.wait(backoff)
                continue
            if not first_round:
                # A round is starting after a severed one: the stream is
                # re-established (counted here, where the leader answered
                # again — not per failed probe during a partition).
                with self._mu:
                    self._reconnects += 1
                if self._metrics is not None:
                    self._metrics["reconnects"].inc()
            first_round = False
            streams = [int(s) for s in st.get("streams") or [-1]]
            with self._mu:
                self._head = max(self._head, int(st.get("watermark", 0)))
                base = getattr(self, "_bootstrap_watermark", 0)
                for s in streams:
                    self._watermarks.setdefault(s, base)
            round_stop = threading.Event()
            need_resync = threading.Event()
            threads = [
                threading.Thread(
                    target=self._tail_one, args=(s, round_stop, need_resync),
                    name=f"replica-{self.cluster}-tail-{s}", daemon=True)
                for s in streams
            ]
            for t in threads:
                t.start()
            # Monitor: poll leader head for the lag gauge until any tail
            # exits (error/partition) or we are stopped.
            while (not self._stop.is_set() and not round_stop.is_set()
                   and any(t.is_alive() for t in threads)):
                round_stop.wait(TAIL_HEARTBEAT_S)
                self._poll_head()
            round_stop.set()
            for t in threads:
                t.join(timeout=10)
            if self._stop.is_set():
                return
            if need_resync.is_set():
                try:
                    self._bootstrap()
                except Exception:  # noqa: BLE001 — retry next round
                    log.exception("replica %s resync failed; retrying",
                                  self.cluster)
            self._stop.wait(backoff)

    def _poll_head(self) -> None:
        try:
            st = self.source.status()
        except Exception:  # noqa: BLE001 — head poll is best-effort
            return
        self._mark_heartbeat()
        with self._mu:
            self._head = max(self._head, int(st.get("watermark", 0)))
        self._note_lag()

    def _tail_one(self, stream: int, round_stop: threading.Event,
                  need_resync: threading.Event) -> None:
        with self._mu:
            from_seq = self._watermarks.get(stream, 0)
        try:
            for line in self.source.tail(stream, from_seq, stop=round_stop):
                doc = json.loads(line) if isinstance(line, str) else line
                ctl = doc.get("ctl")
                if ctl == "SNAPSHOT":
                    need_resync.set()
                    round_stop.set()
                    return
                if ctl == "HEARTBEAT":
                    self._mark_heartbeat()
                    with self._mu:
                        self._head = max(self._head,
                                         int(doc.get("watermark", 0)))
                    self._note_lag()
                    continue
                self._apply(stream, doc)
        except Exception as e:  # noqa: BLE001 — severed stream: supervisor retries
            if not round_stop.is_set() and not self._stop.is_set():
                # Expected under partition/leader-down — one line, no
                # traceback (the supervisor reconnects; a stack here
                # reads like a crash in chaos/bench output).
                log.warning("replica %s stream %d severed (%s); will "
                            "reconnect", self.cluster, stream, e)
        finally:
            round_stop.set()

    def _apply(self, stream: int, rec: dict) -> None:
        seq = int(rec["seq"])
        with self._mu:
            if seq <= self._watermarks.get(stream, 0):
                return  # duplicate after reconnect replay
        t0 = time.perf_counter()
        obj_doc = rec.get("obj")
        obj = from_wire(obj_doc) if obj_doc is not None else None
        fp = rec.get("fp") or (0, 0)
        self.api.apply_replicated(rec["op"], obj, tuple(rec["key"]),
                                  (int(fp[0]), int(fp[1])))
        if self._metrics is not None:
            self._metrics["apply_latency"].observe(
                value=time.perf_counter() - t0)
        with self._mu:
            self._watermarks[stream] = seq
            self._head = max(self._head, seq)
        self._mark_heartbeat()
        self._count_apply(rec["op"], stream=stream, seq=seq)
        self._note_lag()

    def _count_apply(self, op: str, stream: Optional[int] = None,
                     seq: Optional[int] = None) -> None:
        with self._mu:
            self._applied += 1
        if self._metrics is not None:
            self._metrics["applied"].inc(op)
            if stream is not None and seq is not None:
                self._metrics["watermark"].set(str(stream), value=float(seq))

    def _note_lag(self) -> None:
        lag = self.lag_records()
        if self._metrics is not None:
            self._metrics["lag"].set(value=float(lag))
        with self._mu:
            was = self._lagging
            self._lagging = lag > self.lag_alert_records
            fire = self._lagging and not was
        if fire and self.recorder is not None:
            from k8s_dra_driver_tpu.pkg.events import REASON_REPLICA_LAGGING

            self.recorder.warning(
                self._cluster_ref(), REASON_REPLICA_LAGGING,
                f"replica {self.cluster!r} is {lag} WAL records behind "
                f"the leader head")
