"""Validating admission webhook for opaque device configs."""

from k8s_dra_driver_tpu.webhook.admission import (  # noqa: F401
    AdmissionRequest,
    AdmissionResponse,
    AdmissionWebhook,
)
