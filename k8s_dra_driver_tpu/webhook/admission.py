"""Validating admission: strict-decode opaque configs at CREATE/UPDATE.

Reference: /root/reference/cmd/webhook/main.go:131-230 + resource.go:82-151.
Bad configs fail at admission with a precise message instead of surfacing
later as a node-side Prepare error. Also served over HTTP with the k8s
AdmissionReview JSON shapes so it can sit behind a real apiserver webhook.
"""

from __future__ import annotations

import http.server
import json
import logging
import threading
from dataclasses import dataclass, field
from typing import Any, Dict, List, Optional

from k8s_dra_driver_tpu.api.configs import (
    API_GROUP,
    COMPUTE_DOMAIN_DRIVER_NAME,
    DecodeError,
    TPU_DRIVER_NAME,
    ValidationError,
    strict_decode,
)
from k8s_dra_driver_tpu.k8s.core import (
    RESOURCE_CLAIM,
    RESOURCE_CLAIM_TEMPLATE,
    ResourceClaim,
    ResourceClaimTemplate,
)

log = logging.getLogger(__name__)

OUR_DRIVERS = (TPU_DRIVER_NAME, COMPUTE_DOMAIN_DRIVER_NAME)


@dataclass
class AdmissionRequest:
    uid: str = ""
    kind: str = ""
    operation: str = "CREATE"
    object: Optional[object] = None  # ResourceClaim | ResourceClaimTemplate


@dataclass
class AdmissionResponse:
    uid: str = ""
    allowed: bool = True
    message: str = ""


class AdmissionWebhook:
    """Validates every opaque config owned by one of our drivers."""

    def admit(self, req: AdmissionRequest) -> AdmissionResponse:
        if req.kind not in (RESOURCE_CLAIM, RESOURCE_CLAIM_TEMPLATE):
            return AdmissionResponse(uid=req.uid, allowed=True)
        obj = req.object
        if obj is None:
            return AdmissionResponse(uid=req.uid, allowed=False, message="no object")
        errors: List[str] = []
        for i, cc in enumerate(getattr(obj, "config", [])):
            if cc.opaque is None or cc.opaque.driver not in OUR_DRIVERS:
                continue
            try:
                cfg = strict_decode(cc.opaque.parameters)
                cfg.validate()
            except (DecodeError, ValidationError) as e:
                errors.append(f"config[{i}] ({cc.opaque.driver}): {e}")
        if errors:
            return AdmissionResponse(
                uid=req.uid, allowed=False, message="; ".join(errors)
            )
        return AdmissionResponse(uid=req.uid, allowed=True)

    # -- AdmissionReview (JSON, HTTP) ---------------------------------------

    def review(self, body: Dict[str, Any]) -> Dict[str, Any]:
        """Consume/produce k8s AdmissionReview JSON."""
        req = body.get("request", {})
        raw_obj = req.get("object") or {}
        kind = req.get("kind", {}).get("kind", "")
        obj = _object_from_json(kind, raw_obj)
        resp = self.admit(
            AdmissionRequest(
                uid=req.get("uid", ""), kind=kind,
                operation=req.get("operation", "CREATE"), object=obj,
            )
        )
        out: Dict[str, Any] = {
            "apiVersion": "admission.k8s.io/v1",
            "kind": "AdmissionReview",
            "response": {"uid": resp.uid, "allowed": resp.allowed},
        }
        if not resp.allowed:
            out["response"]["status"] = {"message": resp.message, "code": 400}
        return out

    def serve(self, host: str = "127.0.0.1", port: int = 0,
              cert_file: Optional[str] = None,
              key_file: Optional[str] = None) -> "WebhookServer":
        return WebhookServer(self, host, port, cert_file=cert_file,
                             key_file=key_file)


def _object_from_json(kind: str, raw: Dict[str, Any]):
    """Minimal JSON -> object mapping for the config fields we validate."""
    from k8s_dra_driver_tpu.k8s.manifest import (
        device_configs_from_spec,
        unwrap_template_spec,
    )

    if kind == RESOURCE_CLAIM:
        obj: Any = ResourceClaim()
        spec = raw.get("spec", {})
    elif kind == RESOURCE_CLAIM_TEMPLATE:
        obj = ResourceClaimTemplate()
        spec = unwrap_template_spec(raw.get("spec", {}))
    else:
        return None
    obj.config = device_configs_from_spec(spec)
    return obj


class WebhookServer:
    """Serves /validate-resource-claim-parameters (+ /readyz). With
    cert_file/key_file it speaks HTTPS — required to sit behind a real
    apiserver's ValidatingWebhookConfiguration, which refuses plain HTTP
    (reference: ListenAndServeTLS at cmd/webhook/main.go:104-106)."""

    def __init__(self, webhook: AdmissionWebhook, host: str, port: int,
                 cert_file: Optional[str] = None,
                 key_file: Optional[str] = None):
        hook = webhook
        if bool(cert_file) != bool(key_file):
            raise ValueError("cert_file and key_file must be given together")

        class Handler(http.server.BaseHTTPRequestHandler):
            def do_GET(self) -> None:  # noqa: N802
                if self.path.rstrip("/") == "/readyz":
                    self.send_response(200)
                    self.send_header("Content-Type", "text/plain")
                    self.send_header("Content-Length", "2")
                    self.end_headers()
                    self.wfile.write(b"ok")
                else:
                    self.send_error(404)

            def do_POST(self) -> None:  # noqa: N802
                if self.path.rstrip("/") != "/validate-resource-claim-parameters":
                    self.send_error(404)
                    return
                length = int(self.headers.get("Content-Length", "0"))
                try:
                    body = json.loads(self.rfile.read(length) or b"{}")
                    out = hook.review(body)
                except Exception as e:  # noqa: BLE001 — malformed review
                    self.send_error(400, str(e)[:200])
                    return
                data = json.dumps(out).encode()
                self.send_response(200)
                self.send_header("Content-Type", "application/json")
                self.send_header("Content-Length", str(len(data)))
                self.end_headers()
                self.wfile.write(data)

            def log_message(self, *args: object) -> None:
                pass

        self._httpd = http.server.ThreadingHTTPServer((host, port), Handler)
        self.tls = bool(cert_file)
        if cert_file:
            import ssl

            ctx = ssl.SSLContext(ssl.PROTOCOL_TLS_SERVER)
            ctx.load_cert_chain(certfile=cert_file, keyfile=key_file)
            self._httpd.socket = ctx.wrap_socket(
                self._httpd.socket, server_side=True
            )
        self._thread: Optional[threading.Thread] = None

    @property
    def port(self) -> int:
        return self._httpd.server_address[1]

    def start(self) -> None:
        self._thread = threading.Thread(target=self._httpd.serve_forever, daemon=True)
        self._thread.start()

    def stop(self) -> None:
        self._httpd.shutdown()
        self._httpd.server_close()
        if self._thread:
            self._thread.join(timeout=5)
