"""compute-domain-controller binary (reference cmd analog): leader-elected
cluster reconciler for the ComputeDomain CRD."""

from __future__ import annotations

import logging
import signal
import socket
import sys
import threading

from k8s_dra_driver_tpu.cmd import add_api_backend_flag, resolve_api
from k8s_dra_driver_tpu.controller import Controller
from k8s_dra_driver_tpu.pkg import flags as flagpkg
from k8s_dra_driver_tpu.pkg.metrics import MetricsServer, Registry
from k8s_dra_driver_tpu.utils import start_debug_signal_handlers, version_string

log = logging.getLogger("compute-domain-controller")


def main(argv=None) -> int:
    parser = flagpkg.build_parser(
        "compute-domain-controller",
        "cluster-scoped ComputeDomain reconciler",
        [flagpkg.LoggingFlags(), flagpkg.FeatureGateFlags(),
         flagpkg.LeaderElectionFlags(), flagpkg.KubeClientFlags(),
         flagpkg.SliceConfigFlags()],
    )
    add_api_backend_flag(parser)
    parser.add_argument("--driver-namespace", default="tpu-dra-driver")
    parser.add_argument(
        "--additional-namespaces",
        default=flagpkg._env_default("ADDITIONAL_NAMESPACES", "", str),
        help="comma list of additional namespaces where per-CD DaemonSets "
        "are managed (the reference --additional-namespaces, "
        "main.go:183-188) [ADDITIONAL_NAMESPACES]",
    )
    parser.add_argument("--metrics-port", type=int,
                        default=flagpkg._env_default("METRICS_PORT", 0, int),
                        help="serve Prometheus metrics here; 0 disables "
                        "[METRICS_PORT]")
    parser.add_argument(
        "--pprof-path", default=flagpkg._env_default("PPROF_PATH", "", str),
        help="serve thread-stack/runtime-stat debug endpoints under this "
        "path on the metrics port (reference --pprof-path, "
        "main.go:423-431); empty disables [PPROF_PATH]",
    )
    parser.add_argument(
        "--max-nodes-per-domain", type=int,
        default=flagpkg._env_default("MAX_NODES_PER_DOMAIN", 0, int),
        help="reject domains over this many nodes; 0 = topology-derived "
        "default (reference caps IMEX domains at 18, main.go:55-60) "
        "[MAX_NODES_PER_DOMAIN]",
    )
    parser.add_argument("--version", action="store_true")
    args = parser.parse_args(argv)
    if args.version:
        print(version_string("compute-domain-controller"))
        return 0
    if args.max_nodes_per_domain < 0:
        parser.error("--max-nodes-per-domain must be >= 0 (0 = default)")
    flagpkg.LoggingFlags.configure(args)
    flagpkg.log_startup_config(args, log)
    gates = flagpkg.FeatureGateFlags.resolve(args, exit_on_error=True)
    slice_config = flagpkg.SliceConfigFlags.resolve(args, gates, exit_on_error=True)
    start_debug_signal_handlers()

    from k8s_dra_driver_tpu.controller.controller import DEFAULT_MAX_NODES_PER_DOMAIN

    api = resolve_api(args)
    registry = Registry()
    controller = Controller(
        api, driver_namespace=args.driver_namespace,
        identity=f"{socket.gethostname()}-controller",
        leader_elect=args.leader_elect, metrics_registry=registry,
        max_nodes_per_domain=args.max_nodes_per_domain or DEFAULT_MAX_NODES_PER_DOMAIN,
        slice_config=slice_config,
        additional_namespaces=[
            ns.strip() for ns in args.additional_namespaces.split(",")
            if ns.strip()
        ],
    )
    controller.start()
    log.info("%s running (leader_elect=%s)",
             version_string("compute-domain-controller"), args.leader_elect)

    metrics_srv = None
    if args.metrics_port:
        metrics_srv = MetricsServer(registry, host="0.0.0.0",
                                    port=args.metrics_port,
                                    debug_path=args.pprof_path)
        metrics_srv.start()

    stop = threading.Event()
    for sig in (signal.SIGINT, signal.SIGTERM):
        signal.signal(sig, lambda *a: stop.set())
    stop.wait()
    controller.stop()
    if metrics_srv:
        metrics_srv.stop()
    return 0


if __name__ == "__main__":
    sys.exit(main())
