"""tpu-kubelet-plugin binary.

Mirrors the reference gpu-kubelet-plugin main (SURVEY.md §3.1): flags ->
feature gates -> debug handlers -> metrics server -> driver start ->
publish -> serve until signalled.
"""

from __future__ import annotations

import logging
import signal
import socket
import sys
import threading

from k8s_dra_driver_tpu.cmd import (
    add_api_backend_flag,
    add_kubelet_grpc_flags,
    maybe_start_dra_grpc,
    resolve_api,
    validate_kubelet_grpc_flags,
)
from k8s_dra_driver_tpu.pkg import flags as flagpkg
from k8s_dra_driver_tpu.pkg.metrics import MetricsServer, Registry
from k8s_dra_driver_tpu.plugins.health import Healthcheck
from k8s_dra_driver_tpu.plugins.server import DRAPluginServer
from k8s_dra_driver_tpu.plugins.tpu.driver import TpuDriver
from k8s_dra_driver_tpu.tpulib import new_tpulib
from k8s_dra_driver_tpu.utils import start_debug_signal_handlers, version_string

log = logging.getLogger("tpu-kubelet-plugin")


def main(argv=None) -> int:
    parser = flagpkg.build_parser(
        "tpu-kubelet-plugin",
        "DRA kubelet plugin for tpu.google.com devices",
        [flagpkg.LoggingFlags(), flagpkg.FeatureGateFlags(), flagpkg.PluginFlags(),
         flagpkg.KubeClientFlags()],
    )
    add_api_backend_flag(parser)
    add_kubelet_grpc_flags(parser)
    parser.add_argument(
        "--dra-port", type=int, default=flagpkg._env_default("DRA_PORT", 0, int),
        help="serve the DRA Prepare/Unprepare endpoint on this local port "
        "(0 = ephemeral; registration file written to the plugin dir)",
    )
    parser.add_argument(
        "--health-events-to-ignore",
        default=flagpkg._env_default("HEALTH_EVENTS_TO_IGNORE", "", str),
        help="comma list of chip health states (degraded, unhealthy) that "
        "never taint devices — the reference's benign-XID skip list "
        "(--additional-xids-to-ignore) [HEALTH_EVENTS_TO_IGNORE]",
    )
    parser.add_argument("--version", action="store_true")
    args = parser.parse_args(argv)
    if args.version:
        print(version_string("tpu-kubelet-plugin"))
        return 0
    validate_kubelet_grpc_flags(parser, args)
    flagpkg.LoggingFlags.configure(args)
    flagpkg.log_startup_config(args, log)
    gates = flagpkg.FeatureGateFlags.resolve(args, exit_on_error=True)
    start_debug_signal_handlers()

    from k8s_dra_driver_tpu.tpulib import ChipHealth

    try:
        ignored = frozenset(
            ChipHealth(tok.strip().lower())
            for tok in args.health_events_to_ignore.split(",") if tok.strip()
        )
    except ValueError:
        parser.error(
            f"--health-events-to-ignore: unknown state in "
            f"{args.health_events_to_ignore!r}; valid: "
            f"{', '.join(h.value for h in ChipHealth if h != ChipHealth.HEALTHY)}"
        )
    if ChipHealth.HEALTHY in ignored:
        # Ignoring recovery events would leave taints stuck forever.
        parser.error("--health-events-to-ignore: 'healthy' cannot be "
                     "ignored (recovery events clear taints)")

    api = resolve_api(args)
    node_name = args.node_name or socket.gethostname()
    registry = Registry()
    driver = TpuDriver(
        api=api, node_name=node_name, tpulib=new_tpulib(),
        plugin_dir=args.plugin_dir, cdi_root=args.cdi_root,
        gates=gates, metrics_registry=registry,
        ignored_health_states=ignored,
    )
    driver.start()
    dra_srv = DRAPluginServer(
        driver, args.plugin_dir, node_name, port=args.dra_port
    ).start()
    grpc_srv = maybe_start_dra_grpc(args, driver, api)
    log.info("%s serving on %s%s; %d allocatable devices published",
             version_string("tpu-kubelet-plugin"), dra_srv.endpoint,
             f" + gRPC {grpc_srv.dra_socket_path}" if grpc_srv else "",
             len(driver.state.allocatable))

    metrics_srv = None
    if args.metrics_port:
        metrics_srv = MetricsServer(registry, host="0.0.0.0",
                                    port=args.metrics_port,
                                    debug_path=args.pprof_path)
        metrics_srv.start()
    health_srv = None
    if args.healthcheck_port >= 0:
        health_srv = Healthcheck(driver, host="0.0.0.0", port=args.healthcheck_port)
        health_srv.start()

    stop = threading.Event()
    for sig in (signal.SIGINT, signal.SIGTERM):
        signal.signal(sig, lambda *a: stop.set())
    stop.wait()
    if grpc_srv:
        grpc_srv.stop()
    dra_srv.stop()
    if health_srv:
        health_srv.stop()
    driver.shutdown()
    if metrics_srv:
        metrics_srv.stop()
    return 0


if __name__ == "__main__":
    sys.exit(main())
