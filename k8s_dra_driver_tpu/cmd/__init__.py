"""Binary entrypoints — the cmd/ tier (five binaries, SURVEY.md §2.1):

    python -m k8s_dra_driver_tpu.cmd.tpu_kubelet_plugin
    python -m k8s_dra_driver_tpu.cmd.compute_domain_kubelet_plugin
    python -m k8s_dra_driver_tpu.cmd.compute_domain_controller
    python -m k8s_dra_driver_tpu.cmd.compute_domain_daemon
    python -m k8s_dra_driver_tpu.cmd.webhook

Each wires the shared flag bundles (pkg/flags), logging, feature gates,
metrics, and debug handlers around the corresponding component. The
``--api-backend sim`` mode runs against an in-process API server (demo /
development); ``kubernetes`` mode is the seam where a real client-go-style
adapter implements the same APIServer interface.
"""

from __future__ import annotations

import argparse

from k8s_dra_driver_tpu.k8s import APIServer


def resolve_api(args: argparse.Namespace) -> APIServer:
    if args.api_backend == "sim":
        return APIServer()
    if args.api_backend == "http":
        from k8s_dra_driver_tpu.k8s.httpapi import RemoteAPIServer

        if not args.api_server_url:
            raise SystemExit("error: --api-backend http requires --api-server-url")
        return RemoteAPIServer(args.api_server_url)  # type: ignore[return-value]
    if args.api_backend == "kubernetes":
        from k8s_dra_driver_tpu.k8s.kubeclient import (
            KubeAuth,
            KubeConfigError,
            KubernetesAPIServer,
        )

        # --api-server-url points at a plain-HTTP apiserver (the conformance
        # server / a kubectl proxy); otherwise resolve kubeconfig/in-cluster
        # credentials exactly like the reference's kubeclient flag bundle
        # (/root/reference/pkg/flags/kubeclient.go).
        try:
            if args.api_server_url:
                return KubernetesAPIServer(  # type: ignore[return-value]
                    base_url=args.api_server_url
                )
            auth = KubeAuth.resolve(
                kubeconfig=getattr(args, "kubeconfig", ""),
                context=getattr(args, "kube_context", ""),
            )
            return KubernetesAPIServer(auth=auth)  # type: ignore[return-value]
        except (KubeConfigError, OSError) as e:
            raise SystemExit(
                f"error: api-backend 'kubernetes': {e} "
                "(provide --kubeconfig, run in-cluster, or point "
                "--api-server-url at an apiserver/kubectl-proxy URL)"
            ) from None
    raise SystemExit(f"error: unknown api-backend {args.api_backend!r}")


def add_api_backend_flag(parser: argparse.ArgumentParser) -> None:
    import os

    parser.add_argument(
        "--api-backend", choices=("sim", "http", "kubernetes"),
        default=os.environ.get("API_BACKEND", "sim"),
        help="API server backend: in-process sim, http (shared "
        "tpu-dra-apiserver), or a real cluster adapter",
    )
    parser.add_argument(
        "--api-server-url", default=os.environ.get("API_SERVER_URL", ""),
        help="base URL for --api-backend http, or a plain-HTTP k8s apiserver "
        "endpoint (conformance server / kubectl proxy) for "
        "--api-backend kubernetes",
    )
    # --kubeconfig / --kube-context live in flags.KubeClientFlags — every
    # binary that calls this also wires that bundle (round-2 regression:
    # registering them here too crashed argparse at import).
