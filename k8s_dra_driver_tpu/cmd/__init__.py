"""Binary entrypoints — the cmd/ tier (five binaries, SURVEY.md §2.1):

    python -m k8s_dra_driver_tpu.cmd.tpu_kubelet_plugin
    python -m k8s_dra_driver_tpu.cmd.compute_domain_kubelet_plugin
    python -m k8s_dra_driver_tpu.cmd.compute_domain_controller
    python -m k8s_dra_driver_tpu.cmd.compute_domain_daemon
    python -m k8s_dra_driver_tpu.cmd.webhook

Each wires the shared flag bundles (pkg/flags), logging, feature gates,
metrics, and debug handlers around the corresponding component. The
``--api-backend sim`` mode runs against an in-process API server (demo /
development); ``kubernetes`` mode is the seam where a real client-go-style
adapter implements the same APIServer interface.
"""

from __future__ import annotations

import argparse

from k8s_dra_driver_tpu.k8s import APIServer


def resolve_api(args: argparse.Namespace) -> APIServer:
    if args.api_backend == "sim":
        return APIServer()
    if args.api_backend == "http":
        from k8s_dra_driver_tpu.k8s.httpapi import RemoteAPIServer

        if not args.api_server_url:
            raise SystemExit("error: --api-backend http requires --api-server-url")
        return RemoteAPIServer(args.api_server_url)  # type: ignore[return-value]
    if args.api_backend == "kubernetes":
        from k8s_dra_driver_tpu.k8s.kubeclient import (
            KubeAuth,
            KubeConfigError,
            KubernetesAPIServer,
        )

        # --api-server-url points at a plain-HTTP apiserver (the conformance
        # server / a kubectl proxy); otherwise resolve kubeconfig/in-cluster
        # credentials exactly like the reference's kubeclient flag bundle
        # (/root/reference/pkg/flags/kubeclient.go).
        try:
            if args.api_server_url:
                return KubernetesAPIServer(  # type: ignore[return-value]
                    base_url=args.api_server_url
                )
            auth = KubeAuth.resolve(
                kubeconfig=getattr(args, "kubeconfig", ""),
                context=getattr(args, "kube_context", ""),
            )
            return KubernetesAPIServer(auth=auth)  # type: ignore[return-value]
        except (KubeConfigError, OSError) as e:
            raise SystemExit(
                f"error: api-backend 'kubernetes': {e} "
                "(provide --kubeconfig, run in-cluster, or point "
                "--api-server-url at an apiserver/kubectl-proxy URL)"
            ) from None
    raise SystemExit(f"error: unknown api-backend {args.api_backend!r}")


def add_api_backend_flag(parser: argparse.ArgumentParser) -> None:
    import os

    parser.add_argument(
        "--api-backend", choices=("sim", "http", "kubernetes"),
        default=os.environ.get("API_BACKEND", "sim"),
        help="API server backend: in-process sim, http (shared "
        "tpu-dra-apiserver), or a real cluster adapter",
    )
    parser.add_argument(
        "--api-server-url", default=os.environ.get("API_SERVER_URL", ""),
        help="base URL for --api-backend http, or a plain-HTTP k8s apiserver "
        "endpoint (conformance server / kubectl proxy) for "
        "--api-backend kubernetes",
    )
    # --kubeconfig / --kube-context live in flags.KubeClientFlags — every
    # binary that calls this also wires that bundle (round-2 regression:
    # registering them here too crashed argparse at import).


def add_kubelet_grpc_flags(parser: argparse.ArgumentParser) -> None:
    """Flags for the real kubelet-facing gRPC seam (registration socket +
    DRA plugin socket; reference kubeletplugin.Start at
    cmd/gpu-kubelet-plugin/driver.go:131-149)."""
    import os

    parser.add_argument(
        "--kubelet-plugin-dir",
        default=os.environ.get("KUBELET_PLUGIN_DIR", ""),
        help="serve the DRA gRPC socket as <dir>/dra.sock (the kubelet "
        "plugin data dir, e.g. /var/lib/kubelet/plugins/<driver>); "
        "requires --registrar-dir [KUBELET_PLUGIN_DIR]",
    )
    parser.add_argument(
        "--registrar-dir",
        default=os.environ.get("REGISTRAR_DIR", ""),
        help="kubelet plugin registry dir for the registration socket "
        "(e.g. /var/lib/kubelet/plugins_registry) [REGISTRAR_DIR]",
    )


def validate_kubelet_grpc_flags(parser: argparse.ArgumentParser,
                                args: argparse.Namespace) -> None:
    """Call right after parse_args — before any component starts."""
    if bool(args.kubelet_plugin_dir) != bool(args.registrar_dir):
        parser.error("--kubelet-plugin-dir and --registrar-dir must be set together")


def maybe_start_dra_grpc(args: argparse.Namespace, driver, api):
    """Start the kubelet gRPC seam when the flag pair is set; returns the
    running server or None."""
    if not (args.kubelet_plugin_dir and args.registrar_dir):
        return None
    from k8s_dra_driver_tpu.kubelet.draserver import DRAGrpcServer

    return DRAGrpcServer(
        driver,
        api,
        plugin_data_dir=args.kubelet_plugin_dir,
        registrar_dir=args.registrar_dir,
    ).start()
