"""Binary entrypoints — the cmd/ tier (five binaries, SURVEY.md §2.1):

    python -m k8s_dra_driver_tpu.cmd.tpu_kubelet_plugin
    python -m k8s_dra_driver_tpu.cmd.compute_domain_kubelet_plugin
    python -m k8s_dra_driver_tpu.cmd.compute_domain_controller
    python -m k8s_dra_driver_tpu.cmd.compute_domain_daemon
    python -m k8s_dra_driver_tpu.cmd.webhook

Each wires the shared flag bundles (pkg/flags), logging, feature gates,
metrics, and debug handlers around the corresponding component. The
``--api-backend sim`` mode runs against an in-process API server (demo /
development); ``kubernetes`` mode is the seam where a real client-go-style
adapter implements the same APIServer interface.
"""

from __future__ import annotations

import argparse

from k8s_dra_driver_tpu.k8s import APIServer


def resolve_api(args: argparse.Namespace) -> APIServer:
    if args.api_backend == "sim":
        return APIServer()
    if args.api_backend == "http":
        from k8s_dra_driver_tpu.k8s.httpapi import RemoteAPIServer

        if not args.api_server_url:
            raise SystemExit("error: --api-backend http requires --api-server-url")
        return RemoteAPIServer(args.api_server_url)  # type: ignore[return-value]
    # Operator-facing: a clean error, not a traceback.
    raise SystemExit(
        "error: api-backend 'kubernetes' requires a real-cluster adapter "
        "implementing k8s_dra_driver_tpu.k8s.APIServer's interface "
        "(create/get/list/update/delete/watch); run with --api-backend sim, "
        "--api-backend http against tpu-dra-apiserver, or embed the "
        "components with your own APIServer"
    )


def add_api_backend_flag(parser: argparse.ArgumentParser) -> None:
    import os

    parser.add_argument(
        "--api-backend", choices=("sim", "http", "kubernetes"),
        default=os.environ.get("API_BACKEND", "sim"),
        help="API server backend: in-process sim, http (shared "
        "tpu-dra-apiserver), or a real cluster adapter",
    )
    parser.add_argument(
        "--api-server-url", default=os.environ.get("API_SERVER_URL", ""),
        help="base URL for --api-backend http",
    )
