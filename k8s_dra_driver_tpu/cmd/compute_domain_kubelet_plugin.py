"""compute-domain-kubelet-plugin binary (reference cmd analog)."""

from __future__ import annotations

import logging
import os
import signal
import socket
import sys
import threading

from k8s_dra_driver_tpu.cmd import (
    add_api_backend_flag,
    add_kubelet_grpc_flags,
    maybe_start_dra_grpc,
    resolve_api,
    validate_kubelet_grpc_flags,
)
from k8s_dra_driver_tpu.pkg import flags as flagpkg
from k8s_dra_driver_tpu.pkg.metrics import MetricsServer, Registry
from k8s_dra_driver_tpu.plugins.computedomain.driver import (
    DEFAULT_MAX_CHANNEL_COUNT,
    ComputeDomainDriver,
)
from k8s_dra_driver_tpu.plugins.health import Healthcheck
from k8s_dra_driver_tpu.plugins.server import DRAPluginServer
from k8s_dra_driver_tpu.tpulib import new_tpulib
from k8s_dra_driver_tpu.utils import start_debug_signal_handlers, version_string

log = logging.getLogger("compute-domain-kubelet-plugin")


def main(argv=None) -> int:
    parser = flagpkg.build_parser(
        "compute-domain-kubelet-plugin",
        "DRA kubelet plugin for compute-domain.tpu.google.com",
        [flagpkg.LoggingFlags(), flagpkg.FeatureGateFlags(), flagpkg.PluginFlags(),
         flagpkg.KubeClientFlags(), flagpkg.SliceConfigFlags()],
    )
    add_api_backend_flag(parser)
    add_kubelet_grpc_flags(parser)
    parser.add_argument("--version", action="store_true")
    try:
        max_channels_default = int(
            os.environ.get("MAX_SLICE_CHANNEL_COUNT", DEFAULT_MAX_CHANNEL_COUNT)
        )
    except ValueError:
        max_channels_default = DEFAULT_MAX_CHANNEL_COUNT
    parser.add_argument(
        "--max-slice-channel-count",
        type=int,
        default=max_channels_default,
        help="slice channels CDI-injected under AllocationMode All "
        "(the reference's maxImexChannelCount)",
    )
    parser.add_argument(
        "--dra-port", type=int, default=flagpkg._env_default("DRA_PORT", 0, int),
        help="serve the DRA Prepare/Unprepare endpoint on this local port "
        "(0 = ephemeral; registration file written to the plugin dir)",
    )
    args = parser.parse_args(argv)
    if args.max_slice_channel_count < 1:
        parser.error("--max-slice-channel-count must be >= 1")
    if args.version:
        print(version_string("compute-domain-kubelet-plugin"))
        return 0
    validate_kubelet_grpc_flags(parser, args)
    flagpkg.LoggingFlags.configure(args)
    flagpkg.log_startup_config(args, log)
    gates = flagpkg.FeatureGateFlags.resolve(args, exit_on_error=True)
    slice_config = flagpkg.SliceConfigFlags.resolve(args, gates, exit_on_error=True)
    start_debug_signal_handlers()

    api = resolve_api(args)
    registry = Registry()
    driver = ComputeDomainDriver(
        api=api, node_name=args.node_name or socket.gethostname(),
        tpulib=new_tpulib(), plugin_dir=args.plugin_dir,
        cdi_root=args.cdi_root, gates=gates, metrics_registry=registry,
        max_channel_count=args.max_slice_channel_count,
        slice_config=slice_config,
    )
    driver.start()
    dra_srv = DRAPluginServer(
        driver, args.plugin_dir, args.node_name or socket.gethostname(),
        port=args.dra_port,
    ).start()
    grpc_srv = maybe_start_dra_grpc(args, driver, api)
    log.info("%s serving on %s%s",
             version_string("compute-domain-kubelet-plugin"), dra_srv.endpoint,
             f" + gRPC {grpc_srv.dra_socket_path}" if grpc_srv else "")

    metrics_srv = None
    if args.metrics_port:
        metrics_srv = MetricsServer(registry, host="0.0.0.0",
                                    port=args.metrics_port,
                                    debug_path=args.pprof_path)
        metrics_srv.start()
    health_srv = None
    if args.healthcheck_port >= 0:
        health_srv = Healthcheck(driver, host="0.0.0.0", port=args.healthcheck_port)
        health_srv.start()

    stop = threading.Event()
    for sig in (signal.SIGINT, signal.SIGTERM):
        signal.signal(sig, lambda *a: stop.set())
    stop.wait()
    if grpc_srv:
        grpc_srv.stop()
    dra_srv.stop()
    if health_srv:
        health_srv.stop()
    driver.shutdown()
    if metrics_srv:
        metrics_srv.stop()
    return 0


if __name__ == "__main__":
    sys.exit(main())
