"""webhook binary: serves the validating admission endpoint."""

from __future__ import annotations

import logging
import signal
import sys
import threading

from k8s_dra_driver_tpu.pkg import flags as flagpkg
from k8s_dra_driver_tpu.utils import start_debug_signal_handlers, version_string
from k8s_dra_driver_tpu.webhook import AdmissionWebhook

log = logging.getLogger("webhook")


def main(argv=None) -> int:
    parser = flagpkg.build_parser(
        "webhook", "validating admission webhook for opaque device configs",
        [flagpkg.LoggingFlags()],
    )
    parser.add_argument("--bind", default="0.0.0.0")
    parser.add_argument("--port", type=int, default=8443)
    parser.add_argument(
        "--tls-cert-file", default=flagpkg._env_default("TLS_CERT_FILE", ""),
        help="PEM serving cert; with --tls-private-key-file the webhook "
        "serves HTTPS (required behind a real apiserver) [TLS_CERT_FILE]",
    )
    parser.add_argument(
        "--tls-private-key-file",
        default=flagpkg._env_default("TLS_PRIVATE_KEY_FILE", ""),
        help="PEM private key for --tls-cert-file [TLS_PRIVATE_KEY_FILE]",
    )
    parser.add_argument("--version", action="store_true")
    args = parser.parse_args(argv)
    if args.version:
        print(version_string("webhook"))
        return 0
    if bool(args.tls_cert_file) != bool(args.tls_private_key_file):
        parser.error("--tls-cert-file and --tls-private-key-file "
                     "must be set together")
    flagpkg.LoggingFlags.configure(args)
    start_debug_signal_handlers()

    srv = AdmissionWebhook().serve(
        host=args.bind, port=args.port,
        cert_file=args.tls_cert_file or None,
        key_file=args.tls_private_key_file or None,
    )
    srv.start()
    if not srv.tls:
        log.warning("serving PLAIN HTTP — a real apiserver refuses non-TLS "
                    "webhooks; pass --tls-cert-file/--tls-private-key-file")
    log.info("%s listening on %s:%d (tls=%s)",
             version_string("webhook"), args.bind, srv.port, srv.tls)
    stop = threading.Event()
    for sig in (signal.SIGINT, signal.SIGTERM):
        signal.signal(sig, lambda *a: stop.set())
    stop.wait()
    srv.stop()
    return 0


if __name__ == "__main__":
    sys.exit(main())
