"""webhook binary: serves the validating admission endpoint."""

from __future__ import annotations

import logging
import signal
import sys
import threading

from k8s_dra_driver_tpu.pkg import flags as flagpkg
from k8s_dra_driver_tpu.utils import start_debug_signal_handlers, version_string
from k8s_dra_driver_tpu.webhook import AdmissionWebhook

log = logging.getLogger("webhook")


def main(argv=None) -> int:
    parser = flagpkg.build_parser(
        "webhook", "validating admission webhook for opaque device configs",
        [flagpkg.LoggingFlags()],
    )
    parser.add_argument("--bind", default="0.0.0.0")
    parser.add_argument("--port", type=int, default=8443)
    parser.add_argument("--version", action="store_true")
    args = parser.parse_args(argv)
    if args.version:
        print(version_string("webhook"))
        return 0
    flagpkg.LoggingFlags.configure(args)
    start_debug_signal_handlers()

    srv = AdmissionWebhook().serve(host=args.bind, port=args.port)
    srv.start()
    log.info("%s listening on %s:%d", version_string("webhook"), args.bind, srv.port)
    stop = threading.Event()
    for sig in (signal.SIGINT, signal.SIGTERM):
        signal.signal(sig, lambda *a: stop.set())
    stop.wait()
    srv.stop()
    return 0


if __name__ == "__main__":
    sys.exit(main())
