"""compute-domain-daemon binary: runs the SliceAgent inside the per-CD
DaemonSet pod (reference cmd/compute-domain-daemon, SURVEY.md §3.4).

Subcommands:
    run    — the agent loop (default)
    check  — readiness probe; exit 0 iff the local agent reports READY
             (the nvidia-imex-ctl -q analog, main.go:433-459)
"""

from __future__ import annotations

import logging
import os
import shutil
import signal
import subprocess
import sys
import threading
import time

from k8s_dra_driver_tpu.cmd import add_api_backend_flag, resolve_api
from k8s_dra_driver_tpu.daemon import SliceAgent
from k8s_dra_driver_tpu.pkg import flags as flagpkg
from k8s_dra_driver_tpu.tpulib import new_tpulib
from k8s_dra_driver_tpu.utils import start_debug_signal_handlers, version_string

log = logging.getLogger("compute-domain-daemon")

READY_FILE = "ready"


def _find_slice_ctl() -> str:
    """Locate the native tpu-slice-ctl probe: explicit env, PATH, or the
    in-repo native build; empty when not built (Python fallback applies)."""
    explicit = os.environ.get("TPU_SLICE_CTL", "")
    if explicit:
        return explicit if os.access(explicit, os.X_OK) else ""
    found = shutil.which("tpu-slice-ctl")
    if found:
        return found
    local = os.path.join(
        os.path.dirname(__file__), "..", "..", "native", "build", "tpu-slice-ctl"
    )
    return os.path.abspath(local) if os.access(local, os.X_OK) else ""


def main(argv=None) -> int:
    parser = flagpkg.build_parser(
        "compute-domain-daemon", "per-domain slice agent",
        [flagpkg.LoggingFlags(), flagpkg.FeatureGateFlags(),
         flagpkg.KubeClientFlags(), flagpkg.SliceConfigFlags()],
    )
    add_api_backend_flag(parser)
    parser.add_argument("command", nargs="?", default="run", choices=("run", "check"))
    parser.add_argument("--workdir", default=os.environ.get("SLICE_AGENT_WORKDIR",
                                                            "/var/run/tpu-slice-agent"))
    parser.add_argument("--metrics-port", type=int,
                        default=flagpkg._env_default("METRICS_PORT", 0, int),
                        help="serve /metrics + /debug/traces (clique assembly "
                        "spans) on this port; 0 disables [METRICS_PORT]")
    parser.add_argument("--stale-seconds", type=int,
                        default=int(os.environ.get("SLICE_READY_STALE_SECONDS", "10")),
                        help="ready file older than this probes NOT_READY; 0 disables")
    parser.add_argument("--version", action="store_true")
    args = parser.parse_args(argv)
    if args.version:
        print(version_string("compute-domain-daemon"))
        return 0
    flagpkg.LoggingFlags.configure(args)

    if args.command == "check":
        # Probe the running agent via its ready file (written by run loop).
        # Prefer the native tpu-slice-ctl when built (the nvidia-imex-ctl
        # analog); same semantics in the Python fallback: READY content AND
        # a fresh mtime — a dead run loop's leftover file is NOT_READY.
        path = os.path.join(args.workdir, READY_FILE)
        ctl = _find_slice_ctl()
        if ctl:
            proc = subprocess.run(
                [ctl, "-q", "-f", path, "-t", str(args.stale_seconds)],
                capture_output=True, text=True, timeout=10, check=False,
            )
            sys.stdout.write(proc.stdout)
            return proc.returncode
        ready = False
        try:
            st = os.stat(path)
            fresh = (
                args.stale_seconds <= 0
                or time.time() - st.st_mtime <= args.stale_seconds
            )
            with open(path, "r", encoding="utf-8") as f:
                ready = fresh and f.read().strip() == "READY"
        except OSError:
            ready = False
        print("READY" if ready else "NOT_READY")
        return 0 if ready else 1

    gates = flagpkg.FeatureGateFlags.resolve(args, exit_on_error=True)
    slice_config = flagpkg.SliceConfigFlags.resolve(args, gates, exit_on_error=True)
    start_debug_signal_handlers()
    domain_uid = os.environ.get("COMPUTE_DOMAIN_UUID", "")
    if not domain_uid:
        # Guard: without the CDI-injected env the daemon claim wasn't
        # prepared (reference main.go:217-219).
        log.error("COMPUTE_DOMAIN_UUID not set; was the daemon claim prepared?")
        return 1

    from k8s_dra_driver_tpu.pkg.metrics import MetricsServer, Registry

    registry = Registry()
    api = resolve_api(args)
    agent = SliceAgent(
        api=api,
        namespace=os.environ.get("COMPUTE_DOMAIN_NAMESPACE", "default"),
        domain_uid=domain_uid,
        node_name=os.environ.get("NODE_NAME", os.uname().nodename),
        pod_ip=os.environ.get("POD_IP", "127.0.0.1"),
        tpulib=new_tpulib(),
        workdir=args.workdir,
        gates=gates,
        pod_name=os.environ.get("POD_NAME", ""),
        pod_namespace=os.environ.get("POD_NAMESPACE", ""),
        isolation=slice_config.isolation.value,
        metrics_registry=registry,
    )
    agent.startup()
    log.info("%s registered: index=%d ici=%s",
             version_string("compute-domain-daemon"), agent.index, agent.ici_domain)

    metrics_srv = None
    if args.metrics_port:
        metrics_srv = MetricsServer(registry, host="0.0.0.0",
                                    port=args.metrics_port, debug_path="/debug")
        metrics_srv.start()

    stop = threading.Event()
    for sig in (signal.SIGINT, signal.SIGTERM):
        signal.signal(sig, lambda *a: stop.set())
    ready_path = os.path.join(args.workdir, READY_FILE)
    while not stop.wait(1.0):
        try:
            agent.sync()
            status = "READY" if agent.check() else "NOT_READY"
        except Exception:  # noqa: BLE001 — retry next tick
            log.exception("agent sync failed")
            status = "NOT_READY"
        with open(ready_path, "w", encoding="utf-8") as f:
            f.write(status)
    # Invalidate readiness on the way out so probes exec'd against a dead
    # run loop don't read a stale READY.
    try:
        os.remove(ready_path)
    except OSError:
        pass
    agent.shutdown()
    if metrics_srv:
        metrics_srv.stop()
    return 0


if __name__ == "__main__":
    sys.exit(main())
