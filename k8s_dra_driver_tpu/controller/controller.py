"""The ComputeDomain reconciler.

Reference: /root/reference/cmd/compute-domain-controller/ (SURVEY.md §3.3).
Per ComputeDomain it owns: the per-CD slice-agent DaemonSet (node-selected
on the CD label so it follows the workload), the daemon + workload
ResourceClaimTemplates, aggregated status from cliques, stale node-label
removal, orphan cleanup, and leader election around the whole loop.
"""

from __future__ import annotations

import copy
import logging
import threading
from typing import Dict, List, Optional, Sequence, Tuple

from k8s_dra_driver_tpu.api.computedomain import (
    CD_COND_DEGRADED,
    CD_COND_READY,
    CD_COND_VALIDATED,
    CD_STATUS_NOT_READY,
    CD_STATUS_READY,
    CD_STATUS_REJECTED,
    COMPUTE_DOMAIN_FINALIZER,
    COMPUTE_DOMAIN_NODE_LABEL,
    COORDINATOR_PORT_ANNOTATION,
    ComputeDomain,
    ComputeDomainNode,
    ComputeDomainStatus,
)
from k8s_dra_driver_tpu.controller.templates import (
    daemon_resource_claim_template,
    daemon_set_for_domain,
    workload_resource_claim_template,
)
from k8s_dra_driver_tpu.k8s import APIServer, Informer, NotFoundError
from k8s_dra_driver_tpu.k8s.conditions import (
    CONDITION_FALSE,
    CONDITION_TRUE,
    condition_true,
    set_condition,
)
from k8s_dra_driver_tpu.k8s.core import (
    COMPUTE_DOMAIN,
    COMPUTE_DOMAIN_CLIQUE,
    DAEMON_SET,
    ICI_LINK_TAINT_KEY,
    NODE,
    RESOURCE_CLAIM_TEMPLATE,
    RESOURCE_SLICE,
)
from k8s_dra_driver_tpu.pkg import meshgen, tracing
from k8s_dra_driver_tpu.pkg import placement as placement_lib
from k8s_dra_driver_tpu.pkg.events import (
    EventRecorder,
    REASON_DOMAIN_DEGRADED,
    REASON_DOMAIN_READY,
    REASON_DOMAIN_RECOVERED,
    REASON_DOMAIN_REJECTED,
    REASON_MESH_BUNDLE_UPDATED,
)
from k8s_dra_driver_tpu.pkg.leaderelection import LeaderElector
from k8s_dra_driver_tpu.pkg.metrics import (
    ComputeDomainStatusMetric,
    Counter,
    Histogram,
    MeshgenMetrics,
    Registry,
)
from k8s_dra_driver_tpu.pkg.sliceconfig import SliceAgentConfig
from k8s_dra_driver_tpu.pkg.workqueue import (
    WORKQUEUE_SECONDS_BUCKETS,
    WorkQueue,
    default_controller_rate_limiter,
)
from k8s_dra_driver_tpu.tpulib.types import topology_chips

log = logging.getLogger(__name__)

# Largest supported domain: a v5e-256 pod is 64 hosts of 4 chips — the
# topology-derived analog of the reference's 18-node IMEX cap
# (cmd/compute-domain-controller/main.go:55-60).
DEFAULT_MAX_NODES_PER_DOMAIN = 64


class Controller:
    def __init__(
        self,
        api: APIServer,
        driver_namespace: str = "tpu-dra-driver",
        identity: str = "controller-0",
        leader_elect: bool = False,
        metrics_registry: Optional[Registry] = None,
        cleanup_interval_s: float = 600.0,
        max_nodes_per_domain: int = DEFAULT_MAX_NODES_PER_DOMAIN,
        slice_config: Optional[SliceAgentConfig] = None,
        additional_namespaces: Sequence[str] = (),
        dynamic_coordinator_port: bool = False,
    ):
        self.api = api
        self.driver_namespace = driver_namespace
        # Loopback/sim deployments share the host's port space, so the
        # coordinator port each domain advertises is allocated free at
        # DaemonSet render time instead of the fixed well-known 8476 (which
        # any unrelated process may hold — the old collective-proof flake).
        self.dynamic_coordinator_port = dynamic_coordinator_port
        # Per-CD DaemonSets are managed across the driver namespace PLUS
        # these (the reference's MultiNamespaceDaemonSetManager,
        # mnsdaemonset.go:29-119): a DS already living in any managed
        # namespace — e.g. placed there by a previous install — is kept
        # and managed there instead of duplicated; deletion and orphan
        # sweeps span all of them. New DSes are created in the driver
        # namespace. Deduplicated, driver namespace first.
        seen = {driver_namespace}
        self.managed_namespaces: List[str] = [driver_namespace]
        for ns in additional_namespaces:
            if ns and ns not in seen:
                seen.add(ns)
                self.managed_namespaces.append(ns)
        self.identity = identity
        self.max_nodes_per_domain = max_nodes_per_domain
        self.slice_config = slice_config or SliceAgentConfig()
        registry = metrics_registry or Registry()
        self.metric = ComputeDomainStatusMetric(registry)
        self.meshgen_metrics = MeshgenMetrics(registry)
        self.recorder = EventRecorder(api, "cd-controller",
                                      metrics_registry=registry)
        self.reconciles_total = registry.register(Counter(
            "tpu_dra_reconciles_total",
            "Reconcile passes, by outcome (success/error).",
            ("controller", "outcome"),
        ))
        self.reconcile_seconds = registry.register(Histogram(
            "tpu_dra_reconcile_seconds",
            "Wall time of one reconcile pass.",
            ("controller",),
            buckets=WORKQUEUE_SECONDS_BUCKETS,
        ))
        self._queue = WorkQueue(
            self._reconcile_key, default_controller_rate_limiter(registry),
            name="cd-controller", metrics_registry=registry,
        )
        self._cd_informer = Informer(api, COMPUTE_DOMAIN)
        self._clique_informer = Informer(api, COMPUTE_DOMAIN_CLIQUE)
        self._cd_informer.add_event_handler(
            on_add=lambda old, new: self._enqueue(new),
            on_update=lambda old, new: self._enqueue(new),
            on_delete=lambda old, new: self._enqueue(new),
        )
        self._clique_informer.add_event_handler(
            on_add=lambda old, new: self._enqueue_for_clique(new),
            on_update=lambda old, new: self._enqueue_for_clique(new),
            on_delete=lambda old, new: self._enqueue_for_clique(new),
        )
        # Device health rides on ResourceSlice taints: a (re)publish must
        # re-evaluate the Degraded condition of domains spanning that
        # node. The handler maintains an O(1) node->tainted map (no store
        # scan per reconcile) and enqueues only domains whose member set
        # contains the slice's node.
        self._taint_mu = threading.Lock()
        self._slice_taints: Dict[str, Tuple[str, bool]] = {}  # slice -> (node, tainted)
        self._tainted_nodes: Dict[str, int] = {}  # node -> tainted-slice count
        # Mesh-compiler inputs folded from the same slice events (all under
        # _taint_mu): per-node host topology (sticky — topology never
        # changes while a node lives) and per-slice dead intra-host ICI
        # links, so a link-health transition re-enqueues the domains whose
        # bundle must re-route around it.
        self._node_host_topo: Dict[str, str] = {}
        self._slice_links: Dict[str, Tuple[str, frozenset]] = {}  # slice -> (node, {(a,b)})
        self._slice_informer = Informer(api, RESOURCE_SLICE)
        self._slice_informer.add_event_handler(
            on_add=lambda old, new: self._on_slice_event(new, deleted=False),
            on_update=lambda old, new: self._on_slice_event(new, deleted=False),
            on_delete=lambda old, new: self._on_slice_event(new, deleted=True),
        )
        self._elector: Optional[LeaderElector] = None
        if leader_elect:
            self._elector = LeaderElector(
                api, "tpu-dra-compute-domain-controller", identity,
                on_started_leading=self._start_workers,
                on_stopped_leading=self._stop_workers,
                metrics_registry=registry,
            )
        self._cleanup_interval = cleanup_interval_s
        self._stop = threading.Event()
        self._cleanup_thread: Optional[threading.Thread] = None
        self._workers_running = False

    # -- lifecycle -----------------------------------------------------------

    def start(self) -> None:
        self._cd_informer.start()
        self._clique_informer.start()
        self._slice_informer.start()
        if self._elector is not None:
            self._elector.start()
        else:
            self._start_workers()
        self._cleanup_thread = threading.Thread(
            target=self._cleanup_loop, name="cd-cleanup", daemon=True
        )
        self._cleanup_thread.start()

    def stop(self) -> None:
        self._stop.set()
        if self._elector is not None:
            self._elector.stop()
        self._stop_workers()
        self._cd_informer.stop()
        self._clique_informer.stop()
        self._slice_informer.stop()
        if self._cleanup_thread:
            self._cleanup_thread.join(timeout=5)

    def _start_workers(self) -> None:
        if not self._workers_running:
            self._queue.start(workers=1)
            self._workers_running = True
            # Reconcile everything known at takeover.
            for cd in self._cd_informer.list():
                self._enqueue(cd)

    def _stop_workers(self) -> None:
        if self._workers_running:
            self._queue.stop()
            self._workers_running = False

    @property
    def is_leader(self) -> bool:
        return self._elector.is_leader if self._elector else True

    def drain(self, timeout: float = 10.0) -> bool:
        return self._queue.drain(timeout)

    # -- queue plumbing --------------------------------------------------------

    def _enqueue(self, cd) -> None:
        self._queue.enqueue((cd.namespace, cd.name))

    def _enqueue_for_clique(self, clique) -> None:
        for cd in self._cd_informer.list(namespace=clique.meta.namespace):
            if cd.uid == getattr(clique, "domain_uid", None):
                self._enqueue(cd)

    @staticmethod
    def _slice_broken_links(rs) -> frozenset:
        """Dead intra-host ICI links a slice's taints witness, as host-
        local chip index pairs. The taint pass marks every device SPANNING
        a dead link; the 2-chip spanning devices name its endpoints
        exactly (larger spanners are supersets and carry no extra
        information)."""
        links = set()
        for d in getattr(rs, "devices", []):
            if not any(t.key == ICI_LINK_TAINT_KEY for t in d.taints):
                continue
            bits = placement_lib.chip_bits_of_device(d)
            if placement_lib.popcount(bits) == 2:
                a = (bits & -bits).bit_length() - 1
                b = (bits ^ (1 << a)).bit_length() - 1
                links.add((min(a, b), max(a, b)))
        return frozenset(links)

    def _on_slice_event(self, rs, deleted: bool) -> None:
        """Fold one ResourceSlice event into the node->tainted map and the
        mesh-compiler inputs (host topology, dead ICI links); enqueue only
        the domains that span the slice's node, and only when the node's
        taint verdict or link set actually moved (a quiet republish — pool
        generation bump, no taint change — enqueues nothing)."""
        node = getattr(rs, "node_name", "")
        if not node:
            return
        tainted = (not deleted) and any(
            d.taints for d in getattr(rs, "devices", []))
        links = frozenset() if deleted else self._slice_broken_links(rs)
        host_topo = ""
        for d in getattr(rs, "devices", []):
            host_topo = d.attributes.get("tpu.google.com/hostTopology", "")
            if host_topo:
                break
        key = rs.meta.name
        with self._taint_mu:
            prev_node, prev_tainted = self._slice_taints.get(key, ("", False))
            _, prev_links = self._slice_links.get(key, ("", frozenset()))
            prev_topo = self._node_host_topo.get(node, "")
            if prev_tainted:
                self._tainted_nodes[prev_node] = self._tainted_nodes.get(prev_node, 1) - 1
                if self._tainted_nodes[prev_node] <= 0:
                    del self._tainted_nodes[prev_node]
            if deleted:
                self._slice_taints.pop(key, None)
                self._slice_links.pop(key, None)
                # Last slice of the node gone (node removed): drop its
                # sticky topology too, or autoscaler churn grows the map
                # without bound and a reused name serves stale geometry.
                if all(n != node for n, _ in self._slice_taints.values()):
                    self._node_host_topo.pop(node, None)
            else:
                self._slice_taints[key] = (node, tainted)
                if tainted:
                    self._tainted_nodes[node] = self._tainted_nodes.get(node, 0) + 1
                if links or key in self._slice_links:
                    self._slice_links[key] = (node, links)
                if host_topo:
                    self._node_host_topo[node] = host_topo
            # Topology ARRIVAL is a compile input too: a domain reconciled
            # before its members' slices folded would otherwise stay
            # bundle-less until an unrelated taint event (controller
            # restart: CD reconcile can beat the slice informer's adds).
            changed = (prev_tainted != tainted or prev_links != links
                       or (bool(host_topo) and host_topo != prev_topo))
        if not changed:
            return
        for cd in self._cd_informer.list():
            members = {n.name for n in cd.status.nodes}
            if cd.status.placement is not None:
                members.update(cd.status.placement.nodes)
            if node in members:
                self._enqueue(cd)

    def _mesh_inputs(self, member_nodes) -> Tuple[str, List[Tuple[str, int, int]]]:
        """(host topology, dead links) for a domain's member set, read
        from the maps the slice informer maintains. Host topology is
        whichever member published one (members of one block share a
        shape); an empty string means no member's slice carried topology
        attributes and no bundle can compile."""
        members = set(member_nodes)
        with self._taint_mu:
            topo = ""
            for n in member_nodes:
                topo = self._node_host_topo.get(n, "")
                if topo:
                    break
            links = sorted({
                (node, a, b)
                for node, linkset in self._slice_links.values()
                if node in members
                for a, b in linkset
            })
        return topo, links

    def _reconcile_key(self, key, _obj) -> None:
        namespace, name = key
        cd = self.api.try_get(COMPUTE_DOMAIN, name, namespace)
        if cd is None:
            self._cleanup_orphans()
            return
        self.reconcile(cd)  # type: ignore[arg-type]

    # -- reconcile -------------------------------------------------------------

    def reconcile(self, cd: ComputeDomain) -> None:
        """One instrumented reconcile pass: a ``cd.reconcile`` span (the
        root of the controller half of a claim's lifecycle trace) plus
        outcome counter + duration histogram. Errors propagate to the
        workqueue for backoff-retry after being counted."""
        with self.reconcile_seconds.time("cd-controller"), \
                tracing.span("cd.reconcile", namespace=cd.namespace,
                             domain=cd.name, uid=cd.uid) as sp:
            try:
                self._reconcile_inner(cd)
            except Exception:
                self.reconciles_total.inc("cd-controller", "error")
                raise
            self.reconciles_total.inc("cd-controller", "success")
            sp.attrs["deleting"] = cd.deleting

    def _reconcile_inner(self, cd: ComputeDomain) -> None:
        if cd.deleting:
            self._teardown(cd)
            return
        # Finalizer first — even a Rejected domain must flow through
        # _teardown on delete (metric forget, label sweep).
        self._ensure_finalizer(cd)
        reason = self._validate_bounds(cd)
        if reason:
            self._set_rejected(cd, reason)
            return
        self._ensure_owned_objects(cd)
        self._update_status(cd)

    # -- domain bounds ---------------------------------------------------------

    def _validate_bounds(self, cd: ComputeDomain) -> str:
        """Reject domains over the node cap — flag-set, and tightened by the
        requested topology when given (a domain cannot span more hosts than
        its slice has chips). Reference caps IMEX domains at 18 nodes
        (main.go:55-60); TPU slices are bounded by the pod topology."""
        limit = self.max_nodes_per_domain
        reason = f"exceeds --max-nodes-per-domain {limit}"
        if cd.spec.topology:
            try:
                chips = topology_chips(cd.spec.topology)
            except ValueError:
                return f"malformed spec.topology {cd.spec.topology!r}"
            if chips < limit:
                limit, reason = chips, (
                    f"exceeds the {chips}-chip bound of topology "
                    f"{cd.spec.topology} (>=1 chip per host)"
                )
        if cd.spec.num_nodes > limit:
            return f"spec.numNodes {cd.spec.num_nodes} {reason}"
        return ""

    def _set_rejected(self, cd: ComputeDomain, reason: str) -> None:
        log.warning("ComputeDomain %s rejected: %s", cd.key, reason)
        # A domain can turn Rejected after being reconciled (spec mutated
        # over the limit): the contract is that no owned objects exist for
        # a Rejected domain, so tear them down.
        self._delete_owned_objects(cd)
        self._remove_node_labels(cd.uid)

        def mutate(obj, reason=reason):
            conds = copy.deepcopy(obj.status.conditions)
            set_condition(conds, CD_COND_VALIDATED, CONDITION_FALSE,
                          "BoundsExceeded", reason)
            set_condition(conds, CD_COND_READY, CONDITION_FALSE,
                          "Rejected", "domain spec failed validation")
            obj.status = ComputeDomainStatus(
                status=CD_STATUS_REJECTED, nodes=[], conditions=conds)

        fresh = self.api.try_get(COMPUTE_DOMAIN, cd.name, cd.namespace)
        if fresh is not None and fresh.status.status != CD_STATUS_REJECTED:
            try:
                self.api.update_with_retry(COMPUTE_DOMAIN, cd.name, cd.namespace, mutate)
            except NotFoundError:
                return
            self.recorder.warning(fresh, REASON_DOMAIN_REJECTED,
                                  f"domain rejected: {reason}")
        self.metric.set(cd.namespace, cd.name, CD_STATUS_REJECTED)

    def _ensure_finalizer(self, cd: ComputeDomain) -> None:
        if COMPUTE_DOMAIN_FINALIZER in cd.meta.finalizers:
            return
        def mutate(obj):
            if COMPUTE_DOMAIN_FINALIZER not in obj.meta.finalizers:
                obj.meta.finalizers.append(COMPUTE_DOMAIN_FINALIZER)
        self.api.update_with_retry(COMPUTE_DOMAIN, cd.name, cd.namespace, mutate)

    def _ensure_coordinator_port(self, cd: ComputeDomain) -> None:
        """Dynamic coordinator-port allocation at DaemonSet render: bind an
        ephemeral port to find a free one, record it on the CD so the
        channel bootstrap env advertises a port actually bindable on this
        host. First allocation wins (setdefault under CAS) — every worker
        of the domain must agree."""
        if (not self.dynamic_coordinator_port
                or COORDINATOR_PORT_ANNOTATION in cd.meta.annotations):
            return
        import socket

        with socket.socket() as s:
            s.bind(("127.0.0.1", 0))
            port = s.getsockname()[1]

        def mutate(obj, port=port):
            obj.meta.annotations.setdefault(
                COORDINATOR_PORT_ANNOTATION, str(port))
        try:
            self.api.update_with_retry(
                COMPUTE_DOMAIN, cd.name, cd.namespace, mutate)
        except NotFoundError:
            pass

    def _ensure_owned_objects(self, cd: ComputeDomain) -> None:
        self._ensure_coordinator_port(cd)
        cd = self.api.get(COMPUTE_DOMAIN, cd.name, cd.namespace)  # fresh uid/rv
        rct_daemon = daemon_resource_claim_template(cd, self.driver_namespace)
        rct_workload = workload_resource_claim_template(cd)
        owned = [rct_daemon, rct_workload]
        if not self.slice_config.host_managed:
            # Host-managed agents (pkg/sliceconfig Mode.HOST_MANAGED): the
            # node image runs the slice agent, so no DaemonSet is deployed —
            # the reference's HostManagedIMEXDaemon behavior.
            self._ensure_daemon_set(cd)
        for obj in owned:
            existing = self.api.try_get(obj.kind, obj.meta.name, obj.meta.namespace)
            if existing is None:
                self.api.create(obj)
            elif not existing.owned_by(cd):
                raise RuntimeError(
                    f"{obj.kind} {obj.key} exists but is not owned by ComputeDomain "
                    f"{cd.key} — refusing to adopt"
                )

    def _ensure_daemon_set(self, cd: ComputeDomain) -> None:
        """The MultiNamespaceDaemonSetManager.Create semantics
        (mnsdaemonset.go:81-97): a DS for this CD already living in ANY
        managed namespace is kept there (it keeps working; no duplicate);
        otherwise the DS is created in the driver namespace. The
        anti-spoof check is unchanged: a same-named object NOT owned by
        this CD is never adopted, in any namespace."""
        ds = daemon_set_for_domain(cd, self.driver_namespace)
        kept = None
        for ns in self.managed_namespaces:
            existing = self.api.try_get(DAEMON_SET, ds.meta.name, ns)
            if existing is None:
                continue
            if not existing.owned_by(cd):
                raise RuntimeError(
                    f"DaemonSet {ns}/{ds.meta.name} exists but is not owned "
                    f"by ComputeDomain {cd.key} — refusing to adopt"
                )
            if kept is None:
                kept = ns  # managed where it already lives (driver ns wins)
            else:
                # Owned duplicate from a namespace migration (e.g. the
                # driver-ns copy was created before --additional-namespaces
                # was configured): converge to one DS per CD.
                log.warning("removing duplicate slice-agent DS %s/%s "
                            "(kept %s)", ns, ds.meta.name, kept)
                try:
                    self.api.delete(DAEMON_SET, ds.meta.name, ns)
                except NotFoundError:
                    pass
        if kept is None:
            self.api.create(ds)

    # -- status ---------------------------------------------------------------

    def _collect_nodes(self, cd: ComputeDomain) -> List[ComputeDomainNode]:
        nodes: List[ComputeDomainNode] = []
        for clique in self.api.list(COMPUTE_DOMAIN_CLIQUE, namespace=cd.namespace):
            if clique.domain_uid != cd.uid:
                continue
            for info in clique.nodes:
                nodes.append(
                    ComputeDomainNode(
                        name=info.node_name,
                        ip_address=info.ip_address,
                        ici_domain=clique.ici_domain,
                        worker_id=info.index,
                        status=CD_STATUS_READY if info.ready else CD_STATUS_NOT_READY,
                    )
                )
        nodes.sort(key=lambda n: (n.ici_domain, n.worker_id))
        return nodes

    def _calculate_global_status(self, cd: ComputeDomain, nodes: List[ComputeDomainNode]) -> str:
        ready = [n for n in nodes if n.status == CD_STATUS_READY]
        # Elastic domains: the CURRENT epoch's membership target (set by
        # the resize orchestrator — smaller than spec.numNodes after a
        # heal-shrink) governs readiness, so a healed 3-host domain
        # reports Ready instead of waiting forever for its dead fourth.
        want = cd.status.desired_nodes or cd.spec.num_nodes
        if want > 0:
            return CD_STATUS_READY if len(ready) >= want else CD_STATUS_NOT_READY
        # Size-follows-workload: ready when at least one node exists and all
        # registered nodes are ready.
        return (
            CD_STATUS_READY
            if nodes and len(ready) == len(nodes)
            else CD_STATUS_NOT_READY
        )

    def _degraded_member_nodes(self, member_names) -> List[str]:
        """Member nodes whose published ResourceSlices carry tainted
        (unhealthy / ICI-link-broken) devices — what flips the domain's
        Degraded condition so schedulers and operators can route around a
        bad host before jobs land on it. Reads the O(1) node map the slice
        informer maintains; no store scan per reconcile."""
        if not member_names:
            return []
        with self._taint_mu:
            return sorted(set(member_names) & self._tainted_nodes.keys())

    def _compile_mesh_bundle(self, placement, prev):
        """Desired mesh bundle for a domain's recorded placement, evolved
        from the previous one: identical geometry keeps the old bundle
        (revision stable — a no-op reconcile must not bump), any change
        (first placement, link-health transition) compiles a fresh bundle
        at revision+1. Returns (bundle-or-None, trigger-or-"")."""
        if placement is None:
            return None, ""
        host_topo, links = self._mesh_inputs(placement.nodes)
        if not host_topo:
            return prev, ""  # no topology surface: keep whatever exists
        if prev is not None and prev.matches_inputs(
                placement.block_shape, host_topo, placement.nodes, links):
            return prev, ""  # unchanged inputs: skip the compile entirely
        cand = meshgen.compile_for_placement(
            placement, host_topo, broken_links=links,
            revision=(prev.revision if prev is not None else 0) + 1)
        if cand is None:
            return prev, ""
        if prev is not None and prev.same_geometry(cand):
            return prev, ""
        trigger = ("link-health" if prev is not None
                   and [list(b) for b in cand.broken_links]
                   != [list(b) for b in prev.broken_links]
                   else "placement")
        return cand, trigger

    def _update_status(self, cd: ComputeDomain) -> None:
        nodes = self._collect_nodes(cd)
        # Only write on change: an unconditional write emits MODIFIED, which
        # re-enqueues this CD, which writes again — a full-speed loop.
        # Conditions are evolved from the live object so lastTransitionTime
        # stays monotonic and a steady state compares equal.
        fresh = self.api.try_get(COMPUTE_DOMAIN, cd.name, cd.namespace)
        if fresh is None:
            return
        # Readiness judged against the LIVE desired_nodes: the resize
        # orchestrator may have moved the membership target since this
        # reconcile's informer copy was taken.
        status = self._calculate_global_status(fresh, nodes)
        ready_count = sum(1 for n in nodes if n.status == CD_STATUS_READY)
        want = fresh.status.desired_nodes or cd.spec.num_nodes or len(nodes)
        degraded_nodes = self._degraded_member_nodes({n.name for n in nodes})
        conds = copy.deepcopy(fresh.status.conditions)
        set_condition(conds, CD_COND_VALIDATED, CONDITION_TRUE,
                      "SpecValid", "")
        if status == CD_STATUS_READY:
            set_condition(conds, CD_COND_READY, CONDITION_TRUE,
                          "AllNodesReady",
                          f"{ready_count}/{want} member nodes ready")
        else:
            set_condition(conds, CD_COND_READY, CONDITION_FALSE,
                          "WaitingForNodes",
                          f"{ready_count}/{want} member nodes ready")
        if degraded_nodes:
            set_condition(conds, CD_COND_DEGRADED, CONDITION_TRUE,
                          "UnhealthyDevices",
                          "tainted devices on node(s): "
                          + ",".join(degraded_nodes))
        else:
            set_condition(conds, CD_COND_DEGRADED, CONDITION_FALSE,
                          "AllDevicesHealthy", "")
        # The scheduler owns status.placement (the chosen host-grid
        # block); the controller's aggregation must carry it, not wipe it.
        # The controller OWNS status.meshBundle: compiled from the
        # placement plus the link-health state the slice informer folds,
        # re-emitted (revision bump) when either moves.
        bundle, trigger = self._compile_mesh_bundle(
            fresh.status.placement, fresh.status.mesh_bundle)
        # status.utilization is owned by the telemetry aggregator and is
        # change-gated there: if the aggregation wiped it here, steady
        # load would never be re-written and the summary would vanish on
        # the first reconcile after a rollup (same silent-loss class the
        # placement carry above guards against).
        # epoch / desired_nodes / resize are owned by the resize
        # orchestrator (controller/elastic.py); like placement and
        # utilization, the aggregation must carry them, never wipe them.
        desired = ComputeDomainStatus(status=status, nodes=nodes,
                                      conditions=conds,
                                      placement=copy.deepcopy(
                                          fresh.status.placement),
                                      mesh_bundle=copy.deepcopy(bundle),
                                      utilization=fresh.status.utilization,
                                      epoch=fresh.status.epoch,
                                      desired_nodes=fresh.status.desired_nodes,
                                      resize=copy.deepcopy(
                                          fresh.status.resize))
        if fresh.status == desired:
            self.metric.set(cd.namespace, cd.name, status)
            if bundle is not None:
                # Gauges, not just build events: a restarted/failed-over
                # leader must re-export revision + hop scores for stable
                # domains, not leave the series blank until geometry moves.
                self.meshgen_metrics.record(cd.namespace, cd.name, bundle)
            return
        was_ready = condition_true(fresh.status.conditions, CD_COND_READY)
        was_degraded = condition_true(fresh.status.conditions, CD_COND_DEGRADED)
        emitted: Dict[str, object] = {"bundle": bundle, "trigger": trigger}

        def mutate(obj):
            # Placement is re-read from the LIVE object, not the pre-read
            # copy: a CAS retry against a scheduler that just recorded the
            # block must not revert it to the stale (None) value — and the
            # bundle recompiles against THAT placement (pure in-memory
            # compile, safe under the CAS-retry contract). The elastic
            # fields ride the same rule: a resize orchestrator mid-epoch
            # must never have its phase pointer reverted by a racing
            # aggregation.
            new = copy.deepcopy(desired)
            new.placement = copy.deepcopy(obj.status.placement)
            new.utilization = obj.status.utilization
            new.epoch = obj.status.epoch
            new.desired_nodes = obj.status.desired_nodes
            new.resize = copy.deepcopy(obj.status.resize)
            b, trig = self._compile_mesh_bundle(
                new.placement, obj.status.mesh_bundle)
            new.mesh_bundle = copy.deepcopy(b)
            emitted["bundle"], emitted["trigger"] = b, trig
            obj.status = new

        try:
            self.api.update_with_retry(COMPUTE_DOMAIN, cd.name, cd.namespace, mutate)
        except NotFoundError:
            return
        new_bundle, new_trigger = emitted["bundle"], emitted["trigger"]
        if new_bundle is not None and new_trigger:
            self.meshgen_metrics.built(
                cd.namespace, cd.name, new_bundle, new_trigger)
            self.recorder.normal(
                fresh, REASON_MESH_BUNDLE_UPDATED,
                f"mesh bundle rev {new_bundle.revision}: axes "
                + "x".join(f"{n}={s}" for n, s in zip(
                    new_bundle.axis_names, new_bundle.axis_sizes))
                + f", hop score {new_bundle.hop_score} "
                  f"(naive {new_bundle.naive_hop_score})"
                + (f", routed around {len(new_bundle.broken_links)} dead "
                   f"link(s)" if new_bundle.broken_links else ""))
        elif new_bundle is not None:
            # Carried-forward bundle (status moved for other reasons):
            # keep the gauges populated without counting a build.
            self.meshgen_metrics.record(cd.namespace, cd.name, new_bundle)
        if status == CD_STATUS_READY and not was_ready:
            self.recorder.normal(
                fresh, REASON_DOMAIN_READY,
                f"domain ready: {ready_count}/{want} member nodes ready")
        if degraded_nodes and not was_degraded:
            self.recorder.warning(
                fresh, REASON_DOMAIN_DEGRADED,
                "domain degraded: tainted devices on node(s) "
                + ",".join(degraded_nodes))
        elif was_degraded and not degraded_nodes:
            self.recorder.normal(
                fresh, REASON_DOMAIN_RECOVERED,
                "domain recovered: all member devices healthy")
        self.metric.set(cd.namespace, cd.name, status)

    # -- deletion --------------------------------------------------------------

    def _delete_owned_objects(self, cd: ComputeDomain) -> None:
        # The DS may live in any managed namespace (mnsdaemonset.go Delete
        # spans all of them).
        targets = [
            (DAEMON_SET, f"{cd.name}-slice-agent", ns)
            for ns in self.managed_namespaces
        ]
        targets += [
            (RESOURCE_CLAIM_TEMPLATE, f"{cd.name}-daemon-claim", self.driver_namespace),
            (RESOURCE_CLAIM_TEMPLATE,
             cd.spec.channel.resource_claim_template_name or f"{cd.name}-channel",
             cd.namespace),
        ]
        for kind, name, ns in targets:
            obj = self.api.try_get(kind, name, ns)
            if obj is not None and obj.owned_by(cd):
                try:
                    self.api.delete(kind, name, ns)
                except NotFoundError:
                    pass

    def _teardown(self, cd: ComputeDomain) -> None:
        self._delete_owned_objects(cd)
        for clique in self.api.list(COMPUTE_DOMAIN_CLIQUE, namespace=cd.namespace):
            if clique.domain_uid == cd.uid:
                try:
                    self.api.delete(COMPUTE_DOMAIN_CLIQUE, clique.name, clique.namespace)
                except NotFoundError:
                    pass
        self._delete_agent_leases(cd.uid, cd.namespace)
        self._remove_node_labels(cd.uid)
        self.metric.forget(cd.namespace, cd.name)
        self.meshgen_metrics.forget(cd.namespace, cd.name)

        def drop_finalizer(obj):
            obj.meta.finalizers = [
                f for f in obj.meta.finalizers if f != COMPUTE_DOMAIN_FINALIZER
            ]

        try:
            self.api.update_with_retry(COMPUTE_DOMAIN, cd.name, cd.namespace, drop_finalizer)
        except NotFoundError:
            pass

    def _delete_agent_leases(self, cd_uid: str,
                             namespace: Optional[str] = None) -> None:
        """Drop the slice agents' liveness Leases for a domain (named
        ``slice-agent.<uid>.<node>``) — a killed agent cannot delete its
        own, so domain teardown and the orphan sweep must."""
        from k8s_dra_driver_tpu.pkg.leaderelection import LEASE

        prefix = f"slice-agent.{cd_uid}."
        leases = (self.api.list(LEASE, namespace=namespace)
                  if namespace else self.api.list(LEASE))
        for ls in leases:
            if ls.meta.name.startswith(prefix):
                try:
                    self.api.delete(LEASE, ls.meta.name, ls.namespace)
                except NotFoundError:
                    pass

    def _remove_node_labels(self, cd_uid: str) -> None:
        for node in self.api.list(NODE, label_selector={COMPUTE_DOMAIN_NODE_LABEL: cd_uid}):
            def mutate(obj):
                if obj.meta.labels.get(COMPUTE_DOMAIN_NODE_LABEL) == cd_uid:
                    del obj.meta.labels[COMPUTE_DOMAIN_NODE_LABEL]
            try:
                self.api.update_with_retry(NODE, node.name, "", mutate)
            except NotFoundError:
                pass

    # -- orphan cleanup -----------------------------------------------------------

    def _cleanup_orphans(self) -> int:
        """Remove DS/RCTs/cliques/labels whose owning CD is gone — the
        CleanupManager[T] analog (cleanup.go:35-146)."""
        live_uids = {cd.uid for cd in self.api.list(COMPUTE_DOMAIN)}
        removed = 0
        for kind in (DAEMON_SET, RESOURCE_CLAIM_TEMPLATE):
            for obj in self.api.list(kind):  # tpulint: disable=store-scan -- iterates a fixed 2-kind tuple: exactly one scan per kind, not per item
                refs = [r for r in obj.meta.owner_references if r.kind == COMPUTE_DOMAIN]
                if refs and all(r.uid not in live_uids for r in refs):
                    try:
                        self.api.delete(kind, obj.meta.name, obj.meta.namespace)
                        removed += 1
                    except NotFoundError:
                        pass
        for clique in self.api.list(COMPUTE_DOMAIN_CLIQUE):
            if clique.domain_uid and clique.domain_uid not in live_uids:
                try:
                    self.api.delete(COMPUTE_DOMAIN_CLIQUE, clique.name, clique.namespace)
                    removed += 1
                except NotFoundError:
                    pass
        from k8s_dra_driver_tpu.pkg.leaderelection import LEASE

        for ls in self.api.list(LEASE):
            if not ls.meta.name.startswith("slice-agent."):
                continue
            # Name shape: slice-agent.<uid>.<node>. The uid (uuid hex)
            # never contains a dot, but NODE names can (FQDNs) — split
            # from the LEFT or a dotted node name corrupts the uid and
            # the sweep eats live domains' leases.
            rest = ls.meta.name[len("slice-agent."):]
            uid = rest.split(".", 1)[0]
            if uid and uid not in live_uids:
                try:
                    self.api.delete(LEASE, ls.meta.name, ls.namespace)
                    removed += 1
                except NotFoundError:
                    pass
        for node in self.api.list(NODE):
            uid = node.meta.labels.get(COMPUTE_DOMAIN_NODE_LABEL)
            if uid and uid not in live_uids:
                def mutate(obj, uid=uid):
                    if obj.meta.labels.get(COMPUTE_DOMAIN_NODE_LABEL) == uid:
                        del obj.meta.labels[COMPUTE_DOMAIN_NODE_LABEL]
                try:
                    self.api.update_with_retry(NODE, node.name, "", mutate)
                    removed += 1
                except NotFoundError:
                    pass
        return removed

    def _cleanup_loop(self) -> None:
        while not self._stop.wait(self._cleanup_interval):
            if not self.is_leader:
                continue
            try:
                self._cleanup_orphans()
            except Exception:  # noqa: BLE001
                log.exception("orphan cleanup failed")
