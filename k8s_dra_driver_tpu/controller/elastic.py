"""ElasticDomainController — controller-orchestrated resize epochs.

ComputeDomain membership becomes a first-class mutable dimension, driven
by two signals:

- **operator intent**: editing ``spec.numNodes`` on a placed domain;
- **failure**: a member's slice-agent liveness Lease expires (the node
  went down), triggering a heal-shrink to the survivors — and, once the
  host returns (its agent re-registers and its lease renews), a grow
  epoch back toward ``spec.numNodes``.

Each transition is one **resize epoch**, a crash-resumable state machine
persisted in ``ComputeDomainStatus.resize`` (every phase pointer is
CAS-written BEFORE its side effects, so a controller restarted from the
WAL resumes — or rolls back — a half-done epoch instead of forgetting it):

    (detect) --> Quiescing --> Placing --> Restarting --> (epoch += 1)
                     |            |            |
                     +------- rollback to the prior placement ----------+

- **Quiescing**: every surviving worker's claims are cordoned with the
  owner-tagged cordon CAS (``rebalancer.try_cordon(owner="resize")`` — of
  the resize epoch and a live-repack migration racing on an overlapping
  host, exactly one wins) and checkpointed through the same
  ``MigrationCheckpoint`` handshake live repack uses: state fsync'd
  before any release, so leaked ICI partitions are impossible by
  construction. Worker pods on dead hosts are deleted (the kubelet
  eviction analog); their claims fall to ownerRef GC.
- **Placing**: the new membership — chosen at epoch start: shrink keeps
  the survivors' most compact sub-block (falling back to a row-major
  chain when no axis-aligned sub-block of the target size exists), grow
  claims adjacent hosts via ``placement.iter_host_blocks`` preferring
  blocks containing the current members — is recorded in ONE CAS along
  with ``desired_nodes`` and the phase pointer.
- **Restarting**: stale clique members are deregistered (their worker
  slot is remembered for an idempotent re-join), added nodes get the
  domain's node label (the DaemonSet follows), the controller's meshgen
  path recompiles the bundle for the NEW geometry at a bumped revision,
  and the surviving worker pods restart into it (re-prepare clears the
  MigrationCheckpoint entries and re-materializes the CDI env).

Any mid-epoch failure — or a stalled phase — rolls back to the exact
prior placement: quiesced claims re-prepare on their source nodes, the
prior placement/desired size is restored, and the next attempt waits out
a capped-exponential deterministic-jitter backoff (``pkg.backoff``).
"""

from __future__ import annotations

import copy
import logging
import time
from dataclasses import dataclass
from typing import Callable, Dict, List, Optional, Set

from k8s_dra_driver_tpu.api.computedomain import (
    CD_STATUS_REJECTED,
    ComputeDomain,
    ComputeDomainPlacement,
    ComputeDomainResize,
    RESIZE_PLACING,
    RESIZE_QUIESCING,
    RESIZE_RESTARTING,
    RESIZE_TRIGGER_GROW,
    RESIZE_TRIGGER_HEAL,
    RESIZE_TRIGGER_SPEC,
    COMPUTE_DOMAIN_NODE_LABEL,
)
from k8s_dra_driver_tpu.api.configs import TPU_DRIVER_NAME, channel_domain_uid
from k8s_dra_driver_tpu.daemon.agent import agent_lease_name
from k8s_dra_driver_tpu.k8s.core import (
    COMPUTE_DOMAIN,
    COMPUTE_DOMAIN_CLIQUE,
    NODE,
    POD,
    RESOURCE_CLAIM,
)
from k8s_dra_driver_tpu.k8s.objects import NotFoundError
from k8s_dra_driver_tpu.pkg import placement as placement_lib
from k8s_dra_driver_tpu.pkg import tracing
from k8s_dra_driver_tpu.pkg.backoff import Backoff, BackoffMetrics
from k8s_dra_driver_tpu.pkg.events import (
    EventRecorder,
    REASON_DOMAIN_DEGRADED,
    REASON_DOMAIN_HEALED,
    REASON_DOMAIN_RESIZING,
    REASON_RESIZE_FAILED,
)
from k8s_dra_driver_tpu.pkg.history import (
    RULE_RESIZE_HEALED,
    RULE_RESIZE_PHASE,
    RULE_RESIZE_ROLLBACK,
    RULE_RESIZE_START,
)
from k8s_dra_driver_tpu.pkg.leaderelection import LEASE
from k8s_dra_driver_tpu.pkg.metrics import Counter, Gauge, Histogram, Registry
from k8s_dra_driver_tpu.plugins.checkpoint import MIGRATION_CHECKPOINTED
from k8s_dra_driver_tpu.rebalancer.controller import (
    release_cordon,
    try_cordon,
)

log = logging.getLogger(__name__)

# Owner tag for the atomic cordon CAS — distinct from the rebalancer's and
# the autoscaler's, so of the actor roles racing on one claim exactly one
# wins (same-owner re-acquisition is this controller's crash-resume path).
CORDON_OWNER = "resize"

# Virtual-seconds envelope for the time-to-healed histogram: 1s .. ~4min.
RESIZE_SECONDS_BUCKETS = tuple(float(2 ** k) for k in range(9))


@dataclass
class ElasticConfig:
    """Policy knobs (docs/reference/elastic-domains.md)."""

    # Extra grace past a lease's own duration before a member counts lost.
    lease_grace_s: float = 0.0
    # Backoff between failed epoch attempts on one (domain, target).
    backoff_base_s: float = 2.0
    backoff_cap_s: float = 60.0
    # A phase making no progress for this long rolls the epoch back (a
    # bundle that never recompiles, an agent that never re-registers).
    stall_timeout_s: float = 120.0


class ElasticMetrics:
    def __init__(self, registry: Registry):
        self.epochs_total = registry.register(Counter(
            "tpu_dra_resize_epochs_total",
            "Resize epochs finished, by trigger (spec/heal/grow) and "
            "outcome (completed/rolled_back).",
            ("trigger", "outcome")))
        self.domain_epoch = registry.register(Gauge(
            "tpu_dra_domain_epoch",
            "Completed resize epochs per ComputeDomain (0 = never "
            "resized).",
            ("namespace", "domain")))
        self.time_to_healed = registry.register(Histogram(
            "tpu_dra_resize_time_to_healed_seconds",
            "Start-to-completion latency of resize epochs on the "
            "orchestrator clock (virtual seconds in the sim), by trigger.",
            ("trigger",),
            buckets=RESIZE_SECONDS_BUCKETS))


def _prepared(plugin) -> Dict[str, object]:
    """The plugin's checkpoint view: the TPU plugin keeps it behind
    ``.state`` (DeviceState), the compute-domain plugin exposes it
    directly — ONE probe for that seam, not three copies."""
    if hasattr(plugin, "state"):
        return plugin.state.prepared_claims()
    return plugin.prepared_claims()


@dataclass
class _Unit:
    """One domain worker: the consumer pod plus its claims, keyed to the
    node the claims are allocated on."""

    pod: object
    node: str
    tpu_claims: List[object]
    channel_claims: List[object]

    @property
    def claims(self) -> List[object]:
        return self.tpu_claims + self.channel_claims


class _EpochAbort(Exception):
    """Raised inside an epoch step to trigger rollback with a reason."""


class ElasticDomainController:
    """``plugin_resolver(node_name)`` must return an object exposing the
    kubelet-plugin surface (prepare_resource_claims / migrate_claim_out /
    migrate_claim_end) for LIVE nodes and None for unknown or down ones —
    the same seam the rebalancer uses. ``cd_plugin_resolver`` is the
    compute-domain-plugin half (channel claims)."""

    def __init__(
        self,
        api,
        allocator,
        plugin_resolver: Callable[[str], object],
        cd_plugin_resolver: Callable[[str], object],
        config: Optional[ElasticConfig] = None,
        metrics_registry: Optional[Registry] = None,
        clock: Callable[[], float] = time.time,
    ):
        self.api = api
        self.allocator = allocator
        self.resolve_plugin = plugin_resolver
        self.resolve_cd_plugin = cd_plugin_resolver
        self.config = config or ElasticConfig()
        registry = metrics_registry or Registry()
        self.metrics = ElasticMetrics(registry)
        self.recorder = EventRecorder(api, "elastic-domains",
                                      metrics_registry=registry)
        self.clock = clock
        # Optional flight recorder (pkg/history.py HistoryStore): every
        # epoch transition emits a DecisionRecord with the inputs that
        # drove it (trigger, lost hosts, target geometry).
        self.history = None
        self.backoff = Backoff(
            base=self.config.backoff_base_s, cap=self.config.backoff_cap_s,
            jitter=0.2, clock=clock,
            metrics=BackoffMetrics(registry), source="resize")
        # Epochs currently in flight, as of the last step() — the sim
        # folds this into its quiescence token so a waiting phase (bundle
        # recompile, agent re-join) keeps the clock stepping.
        self.in_flight = 0

    # -- pass entry -----------------------------------------------------------

    def step(self) -> int:
        """One orchestration pass; returns how many domains advanced an
        epoch phase (0 on a quiet cluster). One listing per kind per
        pass — per-domain work reads the shared snapshot."""
        domains = [cd for cd in self.api.list(COMPUTE_DOMAIN)
                   if not cd.deleting
                   and cd.status.status != CD_STATUS_REJECTED
                   and cd.spec.num_nodes > 1
                   and cd.status.placement is not None]
        if not domains:
            self.in_flight = 0
            return 0
        self.in_flight = sum(1 for cd in domains
                             if cd.status.resize is not None)
        leases = {(ls.namespace, ls.meta.name): ls
                  for ls in self.api.list(LEASE)}
        claims = self.api.list(RESOURCE_CLAIM)
        pods_by_uid = {p.uid: p for p in self.api.list(POD)}
        advanced = 0
        for cd in domains:
            units = self._worker_units(cd, claims, pods_by_uid)
            try:
                if cd.status.resize is not None:
                    advanced += self._advance(cd, units)
                else:
                    advanced += self._detect(cd, units, leases)
            except Exception:  # noqa: BLE001 — one domain must not wedge the pass
                log.exception("elastic pass failed for %s", cd.key)
        return advanced

    def pending_retries(self) -> int:
        """Backoff-blocked epoch attempts — folded into the sim's
        quiescence token so a deterministic run keeps stepping while a
        retry is still owed instead of settling early."""
        return self.backoff.pending()

    # -- snapshot helpers -----------------------------------------------------

    @staticmethod
    def _worker_units(cd, claims, pods_by_uid) -> List[_Unit]:
        """The domain's worker pods with their claims, from the pass's
        shared claim/pod listings (no per-domain scans)."""
        by_pod: Dict[str, _Unit] = {}
        channel_uids = set()
        for c in claims:
            if channel_domain_uid(c) != cd.uid:
                continue
            for r in c.reserved_for:
                if r.kind != POD:
                    continue
                pod = pods_by_uid.get(r.uid)
                if pod is None:
                    continue
                unit = by_pod.setdefault(pod.uid, _Unit(
                    pod=pod, node=pod.node_name, tpu_claims=[],
                    channel_claims=[]))
                unit.channel_claims.append(c)
                channel_uids.add(c.uid)
        if not by_pod:
            return []
        for c in claims:
            if c.uid in channel_uids or c.allocation is None:
                continue
            if not any(r.driver == TPU_DRIVER_NAME
                       for r in c.allocation.devices):
                continue
            for r in c.reserved_for:
                if r.kind == POD and r.uid in by_pod:
                    by_pod[r.uid].tpu_claims.append(c)
        return list(by_pod.values())

    def _member_lost(self, cd, node: str, leases) -> bool:
        """A member counts lost when its slice agent's liveness lease
        exists and has expired (plus grace). A missing lease is NOT
        failure — agents create theirs at startup, so absence means
        'not started yet', and teardown deletes it cleanly."""
        ls = leases.get((cd.namespace, agent_lease_name(cd.uid, node)))
        if ls is None:
            return False
        return (self.clock() - ls.renewed_at
                > ls.lease_duration_s + self.config.lease_grace_s)

    # -- detection ------------------------------------------------------------

    def _detect(self, cd: ComputeDomain, units, leases) -> int:
        placement = cd.status.placement
        current = list(placement.nodes)
        lost = [n for n in current if self._member_lost(cd, n, leases)]
        spec_target = cd.spec.num_nodes
        if lost:
            target = len(current) - len(lost)
            trigger = RESIZE_TRIGGER_HEAL
            if target < 1:
                # Every member is dead: nothing to shrink TO. Narrate once
                # per backoff period and wait for a host to return.
                key = (cd.uid, 0)
                if self.backoff.ready(key):
                    self.backoff.failure(key)
                    self.recorder.warning(
                        cd, REASON_RESIZE_FAILED,
                        "cannot heal: every member host's lease expired")
                return 0
        elif spec_target != len(current):
            target = spec_target
            trigger = (RESIZE_TRIGGER_GROW if target > len(current)
                       else RESIZE_TRIGGER_SPEC)
            # A grow right after a heal is the host-returned recovery
            # path; require the epoch machinery to be the one that shrank
            # us OR an explicit spec edit — both land here.
        else:
            return 0
        key = (cd.uid, target)
        if not self.backoff.ready(key):
            return 0
        new_placement = self._plan_membership(cd, current, lost, target)
        if new_placement is None:
            # No feasible geometry (grow with no free adjacent block):
            # wait for capacity/churn — the rebalancer's demand signal,
            # not a failure of this controller.
            return 0
        return self._start_epoch(cd, units, trigger, target, lost,
                                 new_placement)

    # -- membership planning --------------------------------------------------

    def _plan_membership(self, cd, current: List[str], lost: List[str],
                         target: int) -> Optional[ComputeDomainPlacement]:
        """The new membership geometry, decided ONCE at epoch start and
        recorded on the resize record so a crash replays the same
        decision. Shrink prefers the most compact axis-aligned sub-block
        of the survivors (``iter_host_blocks`` yields compact-first),
        degrading to a row-major chain (1-D block) when none of the
        target size exists — e.g. 3 survivors of a 2x2 block. Grow claims
        adjacent hosts via the same enumeration, preferring the block
        that keeps the most current members."""
        placement = cd.status.placement
        survivors = [n for n in current if n not in lost]
        topologies = self.allocator.node_topologies()
        if target <= len(survivors):
            block = next(placement_lib.iter_host_blocks(
                topologies, survivors, target), None)
            if block is not None:
                return ComputeDomainPlacement(
                    ici_domain=block.ici_domain,
                    block_origin=block.origin_str,
                    block_shape=block.shape_str,
                    nodes=list(block.nodes))
            kept = survivors[:target]
            if not kept:
                return None
            # Row-major chain: no axis-aligned sub-block of this size
            # exists among the survivors (3 of a 2x2 block), so the
            # domain degrades to a 1xN host chain — a rectangular grid
            # meshgen still compiles, trading block adjacency for
            # availability until the host returns.
            return ComputeDomainPlacement(
                ici_domain=placement.ici_domain,
                block_origin=placement.block_origin,
                block_shape=f"1x{len(kept)}",
                nodes=kept)
        # Grow: survivors plus fully-free live hosts, best block = most
        # current members kept (ties: the enumeration's compact-first
        # deterministic order).
        overview = self.allocator.placement_overview(TPU_DRIVER_NAME)
        candidates = list(survivors)
        for name, entry in sorted(overview.items()):
            if name in survivors or entry["used_mask"]:
                continue
            if self.resolve_plugin(name) is None:
                continue  # unknown or down host
            candidates.append(name)
        best = None
        best_kept = -1
        for block in placement_lib.iter_host_blocks(
                topologies, candidates, target):
            kept = len(set(block.nodes) & set(survivors))
            if kept > best_kept:
                best, best_kept = block, kept
                if kept == len(survivors):
                    break
        if best is None or best_kept < len(survivors):
            # Never grow through a block that evicts current members —
            # that is a migration (the rebalancer's job), not a resize.
            return None
        return ComputeDomainPlacement(
            ici_domain=best.ici_domain, block_origin=best.origin_str,
            block_shape=best.shape_str, nodes=list(best.nodes))

    # -- epoch start ----------------------------------------------------------

    def _start_epoch(self, cd, units, trigger: str, target: int,
                     lost: List[str], new_placement) -> int:
        """Cordon first, record second: the owner-tagged cordon CAS on
        every live unit claim is the arbitration point against the
        rebalancer — losing ANY claim means another actor is mid-flight
        on this domain's hosts, so back off whole without writing."""
        live_units = [u for u in units if u.node not in lost]
        acquired = []
        for u in live_units:
            for c in u.claims:
                if try_cordon(self.api, c, owner=CORDON_OWNER):
                    acquired.append(c)
                    continue
                for got in acquired:
                    release_cordon(self.api, got)
                self.backoff.failure((cd.uid, target))
                return 0
        prior = copy.deepcopy(cd.status.placement)
        record = ComputeDomainResize(
            phase=RESIZE_QUIESCING, trigger=trigger, target_nodes=target,
            lost_nodes=list(lost),
            new_placement=new_placement,
            prior_placement=prior,
            prior_desired=cd.status.desired_nodes or len(prior.nodes),
            attempts=self.backoff.failures((cd.uid, target)) + 1,
            started_at=self.clock(),
        )

        def mutate(obj, record=record):
            if obj.status.resize is None:
                obj.status.resize = copy.deepcopy(record)
        try:
            self.api.update_with_retry(COMPUTE_DOMAIN, cd.name, cd.namespace,
                                       mutate)
        except NotFoundError:
            for got in acquired:
                release_cordon(self.api, got)
            return 0
        if trigger == RESIZE_TRIGGER_HEAL:
            self.recorder.warning(
                cd, REASON_DOMAIN_DEGRADED,
                "member host lease(s) expired: " + ",".join(sorted(lost)))
        self.recorder.normal(
            cd, REASON_DOMAIN_RESIZING,
            f"resize epoch started ({trigger}): {len(prior.nodes)} -> "
            f"{target} hosts")
        if self.history is not None:
            self.history.decide(
                controller="elastic", rule=RULE_RESIZE_START,
                outcome="epoch-started", obj=cd,
                message=(f"resize epoch ({trigger}): {len(prior.nodes)} -> "
                         f"{target} hosts"),
                inputs={"trigger": trigger, "target_nodes": target,
                        "lost_nodes": sorted(lost),
                        "prior_nodes": len(prior.nodes),
                        "attempt": record.attempts},
                now=self.clock())
        fresh = self.api.try_get(COMPUTE_DOMAIN, cd.name, cd.namespace)
        if fresh is not None and fresh.status.resize is not None:
            return self._advance(fresh, units)
        return 1

    # -- epoch advance --------------------------------------------------------

    def _advance(self, cd: ComputeDomain, units) -> int:
        r = cd.status.resize
        with tracing.span("resize.advance", domain=cd.key, phase=r.phase,
                          target=r.target_nodes, trigger=r.trigger):
            try:
                if (self.clock() - r.started_at
                        > self.config.stall_timeout_s):
                    raise _EpochAbort(
                        f"epoch stalled in {r.phase} past "
                        f"{self.config.stall_timeout_s:g}s")
                if r.phase == RESIZE_QUIESCING:
                    return self._phase_quiesce(cd, units)
                if r.phase == RESIZE_PLACING:
                    return self._phase_place(cd)
                if r.phase == RESIZE_RESTARTING:
                    return self._phase_restart(cd, units)
                raise _EpochAbort(f"unknown resize phase {r.phase!r}")
            except _EpochAbort as e:
                self._rollback(cd, units, str(e))
                return 1
            except Exception as e:  # noqa: BLE001 — any escape rolls back; leaked partitions are impossible (MigrationCheckpoint is fsync'd before release)
                log.exception("resize epoch for %s failed in %s",
                              cd.key, r.phase)
                self._rollback(cd, units, f"{r.phase}: {e}")
                return 1

    def _survivor_units(self, cd, units) -> List[_Unit]:
        r = cd.status.resize
        keep = set(r.new_placement.nodes) if r.new_placement else set()
        return [u for u in units if u.node in keep]

    def _phase_quiesce(self, cd, units) -> int:
        """Survivors' claims -> MigrationCheckpoint (idempotent: entries
        already checkpointed are skipped, so a WAL-restored controller
        re-runs this phase safely); dead/removed members' worker pods are
        deleted. Then the phase pointer moves."""
        r = cd.status.resize
        keep = set(r.new_placement.nodes)
        for u in self._survivor_units(cd, units):
            tpu = self.resolve_plugin(u.node)
            cdp = self.resolve_cd_plugin(u.node)
            if tpu is None or cdp is None:
                raise _EpochAbort(f"survivor node {u.node} has no live "
                                  f"plugin; cannot quiesce")
            self._quiesce_claims(tpu, u.tpu_claims)
            self._quiesce_claims(cdp, u.channel_claims)
        self._fire_fault("resize:quiesced")
        # Workers on dead or removed hosts: delete the pods (kubelet
        # eviction analog); ownerRef GC collects their generated claims
        # and frees the capacity.
        for u in units:
            if u.node in keep:
                continue
            try:
                self.api.delete(POD, u.pod.meta.name, u.pod.namespace)
            except NotFoundError:
                pass
        self._set_phase(cd, RESIZE_PLACING)
        return 1

    @staticmethod
    def _quiesce_claims(plugin, claims) -> None:
        prepared = _prepared(plugin)
        for c in claims:
            entry = prepared.get(c.uid)
            if entry is None:
                continue  # never prepared here (pod still pending)
            if entry.state == MIGRATION_CHECKPOINTED:
                continue  # resume path: already quiesced
            plugin.migrate_claim_out(c.uid)

    def _phase_place(self, cd) -> int:
        """Record the new geometry: placement + desired_nodes + phase in
        ONE CAS — the point of no return for this epoch (rollback from
        later phases restores the prior placement the record carries)."""
        def mutate(obj):
            r = obj.status.resize
            if r is None or r.phase != RESIZE_PLACING:
                return
            obj.status.placement = copy.deepcopy(r.new_placement)
            obj.status.desired_nodes = r.target_nodes
            r.phase = RESIZE_RESTARTING
        try:
            self.api.update_with_retry(COMPUTE_DOMAIN, cd.name, cd.namespace,
                                       mutate)
        except NotFoundError:
            return 0
        if self.history is not None:
            self.history.decide(
                controller="elastic", rule=RULE_RESIZE_PHASE,
                outcome=RESIZE_RESTARTING, obj=cd,
                message=("new placement committed; restarting survivors "
                         "onto the new geometry"),
                inputs={"phase_from": RESIZE_PLACING,
                        "phase_to": RESIZE_RESTARTING,
                        "target_nodes": cd.status.resize.target_nodes},
                now=self.clock())
        self._fire_fault("resize:placed")
        return 1

    def _phase_restart(self, cd, units) -> int:
        """Converge the runtime onto the new geometry: clique membership
        first (stale members deregistered with their slot remembered,
        added nodes labeled so the DaemonSet follows), then wait for the
        meshgen recompile, then restart surviving workers into it, then
        finalize. Every step here is idempotent — this phase re-enters
        every pass until the completion predicate holds."""
        r = cd.status.resize
        keep = set(r.new_placement.nodes)
        self._sync_clique_membership(cd, keep)
        self._sync_node_labels(cd, keep, set(r.prior_placement.nodes),
                               set(r.lost_nodes))
        bundle = cd.status.mesh_bundle
        if bundle is None or {d.node for d in bundle.device_order} != keep:
            return 0  # meshgen hasn't recompiled for the new geometry yet
        # Restart survivors whose claims are still checkpoint-quiesced:
        # dropping the pod to Pending makes the kubelet re-run the
        # (idempotent) prepare, which clears the MigrationCheckpoint
        # entries and re-materializes the CDI env from the NEW bundle.
        waiting = False
        for u in self._survivor_units(cd, units):
            tpu = self.resolve_plugin(u.node)
            cdp = self.resolve_cd_plugin(u.node)
            if tpu is None or cdp is None:
                raise _EpochAbort(f"survivor node {u.node} lost its plugin "
                                  f"mid-restart")
            quiesced = any(
                e.state == MIGRATION_CHECKPOINTED
                for plugin in (tpu, cdp)
                for uid, e in _prepared(plugin).items()
                if uid in {c.uid for c in u.claims})
            if quiesced:
                waiting = True
                self._rebind_pod(u)
                continue
            pod = self.api.try_get(POD, u.pod.meta.name, u.pod.namespace)
            if pod is None or pod.phase != "Running":
                waiting = True
        if waiting or not self._members_ready(cd, keep):
            return 0
        self._finalize(cd, units)
        return 1

    def _members_ready(self, cd, keep: Set[str]) -> bool:
        ready = {n.name for n in cd.status.nodes
                 if n.status == "Ready"}
        return keep <= ready

    def _sync_clique_membership(self, cd, keep: Set[str]) -> None:
        """Deregister clique members outside the new placement; their
        worker slot is recorded in the clique's released map so a
        returning host re-joins into the SAME slot (the idempotent
        re-join contract rollback depends on)."""
        for clique in self.api.list(COMPUTE_DOMAIN_CLIQUE,
                                    namespace=cd.namespace):
            if clique.domain_uid != cd.uid:
                continue
            stale = [n.node_name for n in clique.nodes
                     if n.node_name not in keep]
            if not stale:
                continue

            def mutate(obj, stale=stale):
                for name in stale:
                    info = obj.node_info(name)
                    if info is not None and info.index >= 0:
                        obj.released[name] = info.index
                obj.nodes = [n for n in obj.nodes
                             if n.node_name not in stale]
            try:
                self.api.update_with_retry(
                    COMPUTE_DOMAIN_CLIQUE, clique.name, clique.namespace,
                    mutate)
            except NotFoundError:
                continue

    def _sync_node_labels(self, cd, keep: Set[str], prior: Set[str],
                          lost: Set[str]) -> None:
        """Grow: plant the domain label on ADDED nodes so the slice-agent
        DaemonSet follows before any workload lands there. Operator-shrunk
        HEALTHY nodes lose theirs (the DaemonSet leaves with the member).
        Dead members keep their label deliberately — a returning host's
        agent restarts immediately and its re-join is what the grow-back
        path waits on."""
        for name in sorted(keep - prior):
            def mutate(node, uid=cd.uid):
                current = node.meta.labels.get(COMPUTE_DOMAIN_NODE_LABEL)
                if current is None:
                    node.meta.labels[COMPUTE_DOMAIN_NODE_LABEL] = uid
            try:
                self.api.update_with_retry(NODE, name, "", mutate)
            except NotFoundError:
                continue
        for name in sorted(prior - keep - lost):
            def unlabel(node, uid=cd.uid):
                if node.meta.labels.get(COMPUTE_DOMAIN_NODE_LABEL) == uid:
                    del node.meta.labels[COMPUTE_DOMAIN_NODE_LABEL]
            try:
                self.api.update_with_retry(NODE, name, "", unlabel)
            except NotFoundError:
                continue

    def _rebind_pod(self, unit: _Unit) -> None:
        """Drop a survivor worker to Pending so the kubelet re-prepares.
        Change-gated on the live pod (a pod already Pending is not
        re-written every pass while the prepare retries)."""
        live = self.api.try_get(POD, unit.pod.meta.name, unit.pod.namespace)
        if live is None or live.phase == "Pending":
            return

        def mutate(obj):
            obj.phase = "Pending"
            obj.ready = False
        try:
            self.api.update_with_retry(POD, unit.pod.meta.name,
                                       unit.pod.namespace, mutate)
        except NotFoundError:
            pass

    def _release_our_cordons(self, claims) -> None:
        """Release ONLY cordons this controller owns: release_cordon is
        owner-blind, and stripping another actor's in-flight cordon
        (a rebalancer migration on a claim this epoch never acquired)
        would re-open exactly the double-handle race the owner-tagged
        CAS exists to prevent."""
        from k8s_dra_driver_tpu.rebalancer.controller import (
            CORDON_ANNOTATION,
        )

        for c in claims:
            live = self.api.try_get(RESOURCE_CLAIM, c.meta.name, c.namespace)
            if (live is not None
                    and live.meta.annotations.get(CORDON_ANNOTATION)
                    == CORDON_OWNER):
                release_cordon(self.api, live)

    def _finalize(self, cd, units) -> None:
        """Side effects FIRST, record-clear LAST: a crash between them
        leaves the Restarting record in place and this phase re-enters
        idempotently — clearing the record first would strand released-
        but-unreleased cordons with no resume pointer."""
        r = cd.status.resize
        key = (cd.uid, r.target_nodes)
        for u in self._survivor_units(cd, units):
            self._release_our_cordons(u.claims)

        def mutate(obj):
            rec = obj.status.resize
            if rec is None:
                return
            obj.status.epoch += 1
            obj.status.desired_nodes = rec.target_nodes
            obj.status.resize = None
        try:
            self.api.update_with_retry(COMPUTE_DOMAIN, cd.name, cd.namespace,
                                       mutate)
        except NotFoundError:
            return
        self.backoff.reset(key)
        elapsed = max(0.0, self.clock() - r.started_at)
        self.metrics.epochs_total.inc(r.trigger, "completed")
        self.metrics.time_to_healed.observe(r.trigger, value=elapsed)
        if self.heal_observer is not None:
            # SLO-plane feed: time-to-healed as a burn-rate objective
            # (pkg/slo.py TIME_TO_HEALED_SLO). Best-effort — the SLO
            # layer must never fail a finalize.
            try:
                self.heal_observer(r.trigger, elapsed, cd)
            except Exception:  # noqa: BLE001 — observability must not break the epoch
                log.exception("heal observer failed for %s", cd.key)
        fresh = self.api.try_get(COMPUTE_DOMAIN, cd.name, cd.namespace)
        if fresh is not None:
            self.metrics.domain_epoch.set(cd.namespace, cd.name,
                                          value=float(fresh.status.epoch))
        self.recorder.normal(
            cd, REASON_DOMAIN_HEALED,
            f"resize epoch complete ({r.trigger}): domain now spans "
            f"{r.target_nodes} host(s)")
        if self.history is not None:
            self.history.decide(
                controller="elastic", rule=RULE_RESIZE_HEALED,
                outcome="healed", obj=cd,
                message=(f"resize epoch complete ({r.trigger}): domain "
                         f"now spans {r.target_nodes} host(s)"),
                inputs={"trigger": r.trigger,
                        "target_nodes": r.target_nodes,
                        "elapsed_s": round(elapsed, 3),
                        "attempt": r.attempts},
                now=self.clock())

    # -- rollback -------------------------------------------------------------

    def _rollback(self, cd, units, why: str) -> None:
        """Restore the exact prior epoch: prior placement + desired size
        back in one CAS (the meshgen path recompiles the bundle back),
        quiesced survivor claims re-prepared on their source nodes (the
        prepare path clears MigrationCheckpoint entries and re-activates
        the source partitions — the ledger reads exactly as before), all
        cordons released, and the next attempt paced by the backoff."""
        r = cd.status.resize
        key = (cd.uid, r.target_nodes if r is not None else 0)
        with tracing.span("resize.rollback", domain=cd.key, why=why):
            # Side effects FIRST (all idempotent), record-clear LAST: a
            # crash mid-rollback leaves the phase record in place, the
            # next pass retries the phase, fails the same way, and rolls
            # back again — nothing is ever stranded without a resume
            # pointer.
            for u in units:
                tpu = self.resolve_plugin(u.node)
                cdp = self.resolve_cd_plugin(u.node)
                for plugin, claims in ((tpu, u.tpu_claims),
                                       (cdp, u.channel_claims)):
                    if plugin is None:
                        continue
                    self._restore_claims(plugin, claims)
                self._rebind_pod(u)
                self._release_our_cordons(u.claims)

            def mutate(obj):
                rec = obj.status.resize
                if rec is None:
                    return
                if rec.prior_placement is not None:
                    obj.status.placement = copy.deepcopy(rec.prior_placement)
                obj.status.desired_nodes = rec.prior_desired
                obj.status.resize = None
            try:
                self.api.update_with_retry(COMPUTE_DOMAIN, cd.name,
                                           cd.namespace, mutate)
            except NotFoundError:
                return
        self.backoff.failure(key)
        self.metrics.epochs_total.inc(
            r.trigger if r is not None else "", "rolled_back")
        self.recorder.warning(
            cd, REASON_RESIZE_FAILED,
            f"resize epoch rolled back to the prior placement: {why}")
        if self.history is not None:
            self.history.decide(
                controller="elastic", rule=RULE_RESIZE_ROLLBACK,
                outcome="rolled-back", obj=cd,
                message=f"resize epoch rolled back: {why}",
                inputs={"why": why,
                        "trigger": r.trigger if r is not None else "",
                        "phase": r.phase if r is not None else "",
                        "target_nodes": (r.target_nodes
                                         if r is not None else 0)},
                now=self.clock())

    def _restore_claims(self, plugin, claims) -> None:
        prepared = _prepared(plugin)
        quiesced = [c for c in claims
                    if prepared.get(c.uid) is not None
                    and prepared[c.uid].state == MIGRATION_CHECKPOINTED]
        if not quiesced:
            return
        fresh = [self.api.try_get(RESOURCE_CLAIM, c.meta.name, c.namespace)
                 for c in quiesced]
        results = plugin.prepare_resource_claims(
            [c for c in fresh if c is not None])
        for uid, res in results.items():
            if isinstance(res, Exception):
                # The pod's kubelet retry loop owns recovery from here;
                # the checkpoint holds no migration entry either way.
                log.error("rollback re-prepare of %s failed: %s", uid, res)

    # -- phase bookkeeping ----------------------------------------------------

    def _set_phase(self, cd, phase: str) -> None:
        def mutate(obj, phase=phase):
            if obj.status.resize is not None:
                obj.status.resize.phase = phase
        self.api.update_with_retry(COMPUTE_DOMAIN, cd.name, cd.namespace,
                                   mutate)
        if self.history is not None and cd.status.resize is not None:
            self.history.decide(
                controller="elastic", rule=RULE_RESIZE_PHASE,
                outcome=phase, obj=cd,
                message=f"resize epoch advanced to {phase}",
                inputs={"phase_from": cd.status.resize.phase,
                        "phase_to": phase,
                        "target_nodes": cd.status.resize.target_nodes},
                now=self.clock())

    # Crash-injection seam (tests raise from here to simulate a controller
    # dying between phases; same shape as the plugins' fault hooks).
    fault_hook: Optional[Callable[[str], None]] = None

    # SLO-plane sink for completed epochs: (trigger, elapsed_s, domain).
    # The sim wires this to observe TIME_TO_HEALED_SLO on its evaluator.
    heal_observer: Optional[Callable[[str, float, object], None]] = None

    def _fire_fault(self, point: str) -> None:
        if self.fault_hook is not None:
            self.fault_hook(point)
