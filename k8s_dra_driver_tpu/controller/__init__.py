"""compute-domain-controller (L5) — the cluster-scoped ComputeDomain reconciler."""

from k8s_dra_driver_tpu.controller.controller import Controller  # noqa: F401
from k8s_dra_driver_tpu.controller.templates import (  # noqa: F401
    daemon_resource_claim_template,
    daemon_set_for_domain,
    workload_resource_claim_template,
)
