"""Rendered objects the controller creates per ComputeDomain.

Code-level equivalents of the reference's Go templates
(/root/reference/templates/compute-domain-daemon.tmpl.yaml and the RCT
construction in cmd/compute-domain-controller/resourceclaimtemplate.go):
the per-CD DaemonSet node-selected on the CD label, its daemon
ResourceClaimTemplate, and the workload channel ResourceClaimTemplate.
"""

from __future__ import annotations

from k8s_dra_driver_tpu.api.computedomain import (
    COMPUTE_DOMAIN_NODE_LABEL,
    ComputeDomain,
)
from k8s_dra_driver_tpu.api.configs import (
    API_VERSION,
    COMPUTE_DOMAIN_DRIVER_NAME,
)
from k8s_dra_driver_tpu.k8s.core import (
    Container,
    DaemonSet,
    DeviceClaimConfig,
    DeviceRequest,
    OpaqueDeviceConfig,
    PodResourceClaimRef,
    PodTemplate,
    ResourceClaimTemplate,
)
from k8s_dra_driver_tpu.k8s.objects import new_meta

# User-facing DeviceClass names (the reference's deviceclass-*.yaml set).
DEVICE_CLASS_TPU = "tpu.google.com"
DEVICE_CLASS_CHANNEL = "compute-domain-default-channel.tpu.google.com"
DEVICE_CLASS_DAEMON = "compute-domain-daemon.tpu.google.com"

DAEMON_SET_LABEL = "resource.tpu.google.com/slice-agent"


def _opaque(kind: str, cd: ComputeDomain) -> DeviceClaimConfig:
    return DeviceClaimConfig(
        source="claim",
        opaque=OpaqueDeviceConfig(
            driver=COMPUTE_DOMAIN_DRIVER_NAME,
            parameters={"apiVersion": API_VERSION, "kind": kind, "domain_id": cd.uid},
        ),
    )


def daemon_resource_claim_template(cd: ComputeDomain, driver_namespace: str) -> ResourceClaimTemplate:
    rct = ResourceClaimTemplate(
        meta=new_meta(f"{cd.name}-daemon-claim", driver_namespace),
        requests=[DeviceRequest(name="daemon", device_class_name=DEVICE_CLASS_DAEMON)],
        config=[_opaque("ComputeDomainDaemonConfig", cd)],
    )
    rct.add_owner(cd)
    return rct


def workload_resource_claim_template(cd: ComputeDomain) -> ResourceClaimTemplate:
    name = cd.spec.channel.resource_claim_template_name or f"{cd.name}-channel"
    rct = ResourceClaimTemplate(
        meta=new_meta(name, cd.namespace),
        requests=[DeviceRequest(name="channel", device_class_name=DEVICE_CLASS_CHANNEL)],
        config=[_opaque("ComputeDomainChannelConfig", cd)],
    )
    rct.add_owner(cd)
    return rct


def daemon_set_for_domain(cd: ComputeDomain, driver_namespace: str) -> DaemonSet:
    """The slice-agent DaemonSet that follows the workload via the CD node
    label the plugin sets at Prepare time."""
    labels = {DAEMON_SET_LABEL: cd.uid}
    ds = DaemonSet(
        meta=new_meta(f"{cd.name}-slice-agent", driver_namespace, labels=labels),
        selector=dict(labels),
        node_selector={COMPUTE_DOMAIN_NODE_LABEL: cd.uid},
        template=PodTemplate(
            labels=dict(labels),
            containers=[
                Container(
                    name="slice-agent",
                    image="tpu-dra-driver:latest",
                    command=["compute-domain-daemon"],
                    readiness_probe=["compute-domain-daemon", "check"],
                    env={
                        "COMPUTE_DOMAIN_UUID": cd.uid,
                        "COMPUTE_DOMAIN_NAMESPACE": cd.namespace,
                        "COMPUTE_DOMAIN_NAME": cd.name,
                    },
                    # Own-pod identity for the kubelet-verdict readiness
                    # mirror (PodManager); without these the agent falls
                    # back to self-assessed readiness.
                    downward_env={
                        "POD_NAME": "metadata.name",
                        "POD_NAMESPACE": "metadata.namespace",
                        "POD_IP": "status.podIP",
                    },
                )
            ],
            resource_claims=[
                PodResourceClaimRef(
                    name="daemon",
                    resource_claim_template_name=f"{cd.name}-daemon-claim",
                )
            ],
        ),
    )
    ds.add_owner(cd)
    return ds
