"""End-to-end scenarios over the simulated cluster — the e2e/bats tier.

Runs the shipped demo manifests (demo/specs/) against a SimCluster whose
plugins/controller/daemons are the real code, printing what each workload
pod actually received. Mirrors the reference's quickstart walkthrough
(gpu-test1..5 + ComputeDomain single/multi, SURVEY.md §4 tiers 2-4).

Usage:
    python -m k8s_dra_driver_tpu.e2e                 # run every scenario
    python -m k8s_dra_driver_tpu.e2e tpu-test1 cd-multi-host
    python -m k8s_dra_driver_tpu.e2e --list
"""

from __future__ import annotations

import argparse
import os
import sys
import tempfile
from dataclasses import dataclass
from typing import Callable, Dict, List

from k8s_dra_driver_tpu.k8s.core import POD
from k8s_dra_driver_tpu.sim import SimCluster
from k8s_dra_driver_tpu.sim.kubectl import apply_file

SPECS_DIR = os.path.join(os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
                         "demo", "specs")


@dataclass
class Scenario:
    name: str
    spec: str                 # path under demo/specs/
    profile: str = "v5e-16"
    gates: str = ""
    check: Callable[["SimCluster", List], None] = lambda sim, pods: None


class E2EFailure(AssertionError):
    pass


def _expect(cond: bool, msg: str) -> None:
    if not cond:
        raise E2EFailure(msg)


def _running_pods(sim: SimCluster, ns: str) -> List:
    pods = sim.api.list(POD, namespace=ns)
    _expect(bool(pods), f"no pods found in {ns}")
    not_running = [(p.meta.name, p.phase, p.meta.annotations.get("failure", ""))
                   for p in pods if p.phase != "Running"]
    _expect(not not_running, f"pods not Running: {not_running}")
    return pods


def check_test1(sim: SimCluster, _pods) -> None:
    pods = _running_pods(sim, "tpu-test1")
    p = pods[0]
    _expect(len(p.injected_devices) == 1 and p.injected_devices[0].startswith("/dev/accel"),
            f"expected one accel device, got {p.injected_devices}")
    _expect(p.injected_env.get("TPU_VISIBLE_CHIPS", "").isdigit(),
            f"bad TPU_VISIBLE_CHIPS: {p.injected_env.get('TPU_VISIBLE_CHIPS')}")


def check_test2(sim: SimCluster, _pods) -> None:
    pods = _running_pods(sim, "tpu-test2")
    _expect(len(pods) == 2, f"want 2 pods, got {len(pods)}")
    _expect(pods[0].node_name == pods[1].node_name,
            "shared claim must pin both pods to one node")
    _expect(pods[0].injected_devices == pods[1].injected_devices,
            "both pods must see the same chip")


def check_test3(sim: SimCluster, _pods) -> None:
    pods = _running_pods(sim, "tpu-test3")
    env = pods[0].injected_env
    _expect(env.get("TPU_CHIPS_PER_PROCESS_BOUNDS") == "1,2,1",
            f"bad subslice bounds: {env.get('TPU_CHIPS_PER_PROCESS_BOUNDS')}")
    _expect(len(pods[0].injected_devices) == 2, "1x2 subslice = 2 device nodes")


def check_test3_dynamic(sim: SimCluster, _pods) -> None:
    check_test3(sim, _pods)  # same workload-visible contract...
    # ...plus the Prepare really carved an ICI partition in the ledger
    # (the DynamicMIG-analog path, reference nvlib.go:971-1199).
    pods = sim.api.list(POD, namespace="tpu-test3")
    node = sim.nodes[pods[0].node_name]
    partitions = node.tpu_driver.state.partitions
    _expect(partitions is not None, "DynamicSubslice gate must wire a manager")
    active = partitions.active_partitions()
    _expect(any(p.profile == "1x2" for p in active),
            f"no active 1x2 partition in the ledger: {active}")


def check_test4(sim: SimCluster, _pods) -> None:
    pods = _running_pods(sim, "tpu-test4")
    for p in pods:
        _expect(p.injected_env.get("TPU_TIMESLICE_US") == "2000",
                f"{p.meta.name}: missing time-slice env: {p.injected_env.get('TPU_TIMESLICE_US')}")


def check_test5(sim: SimCluster, _pods) -> None:
    pods = _running_pods(sim, "tpu-test5")
    env = pods[0].injected_env
    _expect(len(pods[0].injected_devices) == 4, "whole host = 4 device nodes")
    _expect(env.get("TPU_TOPOLOGY") == "4x4", f"bad topology {env.get('TPU_TOPOLOGY')}")


def check_test6(sim: SimCluster, _pods) -> None:
    pods = _running_pods(sim, "tpu-test6")
    p = pods[0]
    _expect(len(p.injected_devices) == 2, f"two distinct chips: {p.injected_devices}")
    chips = p.injected_env.get("TPU_VISIBLE_CHIPS", "")
    _expect(len(set(chips.split(","))) == 2, f"distinct chip ids: {chips}")


def check_test7(sim: SimCluster, _pods) -> None:
    pods = {p.meta.name: p for p in sim.api.list(POD, namespace="tpu-test7")}
    _expect(set(pods) == {"pod0", "pod1", "hog"}, f"pods: {sorted(pods)}")
    for name in ("pod0", "pod1"):
        p = pods[name]
        _expect(p.phase == "Running", f"{name} is {p.phase}")
        _expect(p.injected_env.get("TPU_PREMAPPED_BUFFER_BYTES") == "4294967296",
                f"{name} premapped env: {p.injected_env.get('TPU_PREMAPPED_BUFFER_BYTES')}")
    _expect(pods["pod0"].injected_devices == pods["pod1"].injected_devices,
            "premapped sharers must see the same chip")
    hog = pods["hog"]
    _expect(hog.phase == "Failed", f"over-budget pod is {hog.phase}, want Failed")
    _expect("exceeds HBM" in hog.meta.annotations.get("failure", ""),
            f"hog failure: {hog.meta.annotations.get('failure')!r}")


def check_vfio(sim: SimCluster, _pods) -> None:
    pods = _running_pods(sim, "tpu-test-vfio")
    p = pods[0]
    addr = p.injected_env.get("TPU_VFIO_PCI_ADDRESS", "")
    _expect(addr.startswith("0000:"), f"bad TPU_VFIO_PCI_ADDRESS {addr!r}")
    groups = [d for d in p.injected_devices if "/vfio/" in d]
    _expect(len(groups) == 1, f"want one /dev/vfio node, got {p.injected_devices}")
    _expect(os.path.exists(groups[0]), f"vfio node {groups[0]} missing on disk")
    # The spec's iommu_mode is auto and the sim kernel exposes iommufd,
    # so the injected handle is the per-device cdev, not the group fd.
    _expect("/vfio/devices/" in groups[0],
            f"auto mode should prefer the iommufd cdev, got {groups[0]}")
    _expect(p.injected_env.get("TPU_VFIO_IOMMU_MODE") == "iommufd",
            f"iommu mode env: {p.injected_env.get('TPU_VFIO_IOMMU_MODE')!r}")
    _expect(not any(d.endswith("accel0") for d in p.injected_devices),
            "passthrough pod must not also get the accel node")
    # The rebind really happened in the node's sysfs fixture.
    mgr = sim.nodes[p.node_name].tpu_driver.state.vfio
    _expect(mgr.current_driver(addr) == "vfio-pci",
            f"chip driver is {mgr.current_driver(addr)!r}, want vfio-pci")


def check_vfio_part(sim: SimCluster, _pods) -> None:
    """Multi-chip passthrough: partition activate -> bind -> (delete) ->
    unbind -> release, with the legacy backend and the IOMMU API device."""
    pods = _running_pods(sim, "tpu-test-vfio-part")
    p = pods[0]
    node = sim.nodes[p.node_name].tpu_driver.state
    # Two group fds (legacy mode) + the /dev/vfio/vfio API container.
    group_fds = [d for d in p.injected_devices
                 if "/vfio/" in d and "/devices/" not in d
                 and not d.endswith("/vfio/vfio")]
    _expect(len(group_fds) == 2, f"want two group fds, got {p.injected_devices}")
    _expect(any(d.endswith("/vfio/vfio") for d in p.injected_devices),
            f"missing IOMMU API device: {p.injected_devices}")
    _expect(p.injected_env.get("TPU_VFIO_IOMMU_MODE") == "legacy",
            f"iommu mode env: {p.injected_env.get('TPU_VFIO_IOMMU_MODE')!r}")
    # Both functions are discoverable: the claim-wide address list names
    # every member (per-device TPU_VFIO_PCI_ADDRESS is last-wins).
    addrs = p.injected_env.get("TPU_VFIO_PCI_ADDRESSES", "").split(",")
    _expect(len(addrs) == 2 and all(a.startswith("0000:") for a in addrs),
            f"bad TPU_VFIO_PCI_ADDRESSES: {addrs}")
    # The group's isolating ICI partition is live while the claim holds it.
    active = [q.id for q in node.partitions.active_partitions()]
    _expect(len(active) == 1, f"want exactly one active partition, got {active}")
    # Release path: deleting the pod unprepares — drivers return to accel
    # and the partition is released.
    addr = p.injected_env.get("TPU_VFIO_PCI_ADDRESS", "")
    sim.delete_pod(p.meta.name, p.namespace)
    sim.settle()
    _expect(node.partitions.active_partitions() == [],
            "partition must be released on unprepare")
    _expect(node.vfio.current_driver(addr) == "accel-tpu",
            f"chip driver is {node.vfio.current_driver(addr)!r} after release")


def check_cd_single(sim: SimCluster, _pods) -> None:
    pods = _running_pods(sim, "cd-single")
    env = pods[0].injected_env
    _expect(env.get("TPU_WORKER_ID") == "0", f"worker id {env.get('TPU_WORKER_ID')}")
    _expect(env.get("MEGASCALE_COORDINATOR_ADDRESS", "").endswith(":8476"),
            "missing coordinator address")


def check_cd_multi(sim: SimCluster, _pods) -> None:
    pods = _running_pods(sim, "cd-multi")
    workers = sorted(
        (p for p in pods if p.meta.name.startswith("worker-")),
        key=lambda p: p.meta.name,
    )
    _expect(len(workers) == 4, f"want 4 workers, got {len(workers)}")
    ids = sorted(int(p.injected_env["TPU_WORKER_ID"]) for p in workers)
    _expect(ids == [0, 1, 2, 3], f"worker ids {ids}")
    hostnames = {p.injected_env["TPU_WORKER_HOSTNAMES"] for p in workers}
    _expect(len(hostnames) == 1, "all workers must agree on the hostname list")
    _expect(len(next(iter(hostnames)).split(",")) == 4, "4 hostnames expected")
    coords = {p.injected_env["MEGASCALE_COORDINATOR_ADDRESS"] for p in workers}
    _expect(len(coords) == 1, "all workers must agree on the coordinator")
    nodes = {p.node_name for p in workers}
    _expect(len(nodes) == 4, f"workers must spread over 4 hosts, got {nodes}")
    for p in workers:
        accel = [d for d in p.injected_devices if d.startswith("/dev/accel")]
        chans = [d for d in p.injected_devices if d.startswith("/dev/tpu-slice-channels/")]
        _expect(len(accel) == 4, "each worker holds its whole host")
        _expect(len(chans) > 0, "slice channel char devices injected")
        _expect(p.injected_env.get("TPU_TOPOLOGY") == "4x4", "slice topology")


def check_selectors(sim: SimCluster, _pods) -> None:
    pods = {p.meta.name: p for p in _running_pods(sim, "selectors")}
    _expect(set(pods) == {"pinned", "roomy"}, f"pods: {sorted(pods)}")
    _expect(pods["pinned"].injected_env.get("TPU_VISIBLE_CHIPS") == "2",
            f"request selector must pin chip 2, got "
            f"{pods['pinned'].injected_env.get('TPU_VISIBLE_CHIPS')}")
    _expect(bool(pods["roomy"].injected_env.get("TPU_VISIBLE_CHIPS")),
            "capacity-selected pod must hold a chip")


def check_subslice_sharing(sim: SimCluster, _pods) -> None:
    pods = {p.meta.name: p for p in _running_pods(sim, "subslice-sharing")}
    _expect(set(pods) == {"sharer-0", "sharer-1", "neighbor"},
            f"pods: {sorted(pods)}")
    s0, s1 = pods["sharer-0"], pods["sharer-1"]
    _expect(s0.injected_devices == s1.injected_devices and
            len(s0.injected_devices) == 2,
            f"sharers must see the same two chips: "
            f"{s0.injected_devices} vs {s1.injected_devices}")
    for p in (s0, s1):
        _expect(p.injected_env.get("TPU_TIMESLICE_US") == "10000",
                f"{p.meta.name}: Medium interval env missing: "
                f"{p.injected_env.get('TPU_TIMESLICE_US')}")
        _expect(p.injected_env.get("TPU_CHIPS_PER_PROCESS_BOUNDS") == "1,2,1",
                "subslice bounds env missing")
    shared = set(s0.injected_env["TPU_VISIBLE_CHIPS"].split(","))
    neighbor_chips = set(pods["neighbor"].injected_env["TPU_VISIBLE_CHIPS"].split(","))
    _expect(not (shared & neighbor_chips),
            f"neighbor must not overlap the shared subslice: "
            f"{shared} vs {neighbor_chips}")


def check_allreduce_job(sim: SimCluster, _pods) -> None:
    """The nvbandwidth-analog proof job: every indexed worker must land on
    its own host with the full env allreduce_bench needs to bootstrap
    jax.distributed over the assembled slice."""
    pods = sorted(_running_pods(sim, "allreduce"), key=lambda p: p.meta.name)
    _expect(len(pods) == 4, f"want 4 indexed workers, got {len(pods)}")
    _expect({p.node_name for p in pods} == {f"tpu-node-{i}" for i in range(4)},
            "workers must spread over all 4 hosts")
    ids = sorted(int(p.injected_env["TPU_WORKER_ID"]) for p in pods)
    _expect(ids == [0, 1, 2, 3], f"worker ids {ids}")
    for p in pods:
        cmd = p.containers[0].command
        _expect("k8s_dra_driver_tpu.ops.allreduce_bench" in cmd,
                f"job must run the allreduce proof, got {cmd}")
        _expect(p.containers[0].env.get("JOB_COMPLETION_INDEX", "").isdigit(),
                "indexed-job completion index missing")
        env = p.injected_env
        for key in ("TPU_WORKER_HOSTNAMES", "MEGASCALE_COORDINATOR_ADDRESS",
                    "TPU_TOPOLOGY", "TPU_VISIBLE_CHIPS"):
            _expect(bool(env.get(key)), f"{p.meta.name}: missing {key}")
        _expect(len(env["TPU_WORKER_HOSTNAMES"].split(",")) == 4,
                "hostnames must list all 4 workers")


def check_psum_proof(sim: SimCluster, _pods) -> None:
    """The cluster-initialization proof job: every worker gets the exact
    env set psum_proof derives its whole configuration from, forming one
    coherent 4-process cluster spec."""
    pods = sorted(_running_pods(sim, "psum-proof"), key=lambda p: p.meta.name)
    _expect(len(pods) == 4, f"want 4 indexed workers, got {len(pods)}")
    ids = sorted(int(p.injected_env["TPU_WORKER_ID"]) for p in pods)
    _expect(ids == [0, 1, 2, 3], f"worker ids {ids}")
    coords = {p.injected_env.get("MEGASCALE_COORDINATOR_ADDRESS") for p in pods}
    _expect(len(coords) == 1 and None not in coords,
            f"coordinator must be identical everywhere, got {coords}")
    # One coherent cluster spec: every worker sees the SAME ordered peer
    # list, and the workers actually spread over distinct hosts.
    peer_lists = {p.injected_env.get("TPU_WORKER_HOSTNAMES", "") for p in pods}
    _expect(len(peer_lists) == 1,
            f"peer lists must be identical everywhere, got {peer_lists}")
    _expect(len(peer_lists.pop().split(",")) == 4,
            "hostnames must list all 4 workers")
    _expect(len({p.node_name for p in pods}) == 4,
            "workers must spread over 4 distinct hosts")
    for p in pods:
        cmd = p.containers[0].command
        _expect("k8s_dra_driver_tpu.ops.psum_proof" in cmd,
                f"job must run the psum proof, got {cmd}")
    # test_collective_proof.py executes this exact derivation with real OS
    # processes (loopback sim) and asserts the psum agrees.


SCENARIOS: Dict[str, Scenario] = {
    s.name: s
    for s in (
        Scenario("tpu-test1", "quickstart/tpu-test1.yaml", check=check_test1),
        Scenario("tpu-test2", "quickstart/tpu-test2.yaml", check=check_test2),
        Scenario("tpu-test3", "quickstart/tpu-test3.yaml", check=check_test3),
        Scenario("tpu-test3-dynamic", "quickstart/tpu-test3.yaml",
                 gates="DynamicSubslice=true,ICIPartitioning=true",
                 check=check_test3_dynamic),
        Scenario("tpu-test4", "quickstart/tpu-test4.yaml",
                 gates="TimeSlicingSettings=true", check=check_test4),
        Scenario("tpu-test5", "quickstart/tpu-test5.yaml", check=check_test5),
        Scenario("tpu-test6", "quickstart/tpu-test6.yaml", check=check_test6),
        Scenario("tpu-test7", "quickstart/tpu-test7.yaml",
                 gates="TimeSlicingSettings=true,PremappedBufferSharing=true",
                 check=check_test7),
        Scenario("tpu-test-vfio", "quickstart/tpu-test-vfio.yaml",
                 gates="PassthroughSupport=true", check=check_vfio),
        Scenario("tpu-test-vfio-part", "quickstart/tpu-test-vfio-part.yaml",
                 profile="v5e-4",
                 gates="PassthroughSupport=true,ICIPartitioning=true",
                 check=check_vfio_part),
        Scenario("cd-single-host", "computedomain/cd-single-host.yaml",
                 profile="v5e-4", check=check_cd_single),
        Scenario("cd-multi-host", "computedomain/cd-multi-host.yaml",
                 check=check_cd_multi),
        Scenario("allreduce-job", "computedomain/allreduce-job.yaml",
                 check=check_allreduce_job),
        Scenario("psum-proof", "computedomain/psum-proof-job.yaml",
                 check=check_psum_proof),
        Scenario("selectors", "selectors/selectors.yaml",
                 profile="v5e-4", check=check_selectors),
        Scenario("subslice-sharing", "subslice-sharing/sharing.yaml",
                 profile="v5e-4", gates="TimeSlicingSettings=true",
                 check=check_subslice_sharing),
    )
}


def run_scenario(scenario: Scenario, workdir: str, verbose: bool = True) -> None:
    sim = SimCluster(workdir=workdir, profile=scenario.profile, gates=scenario.gates)
    sim.start()
    try:
        created = apply_file(sim.api, os.path.join(SPECS_DIR, scenario.spec))
        sim.settle()
        scenario.check(sim, created)
        if verbose:
            for pod in sim.api.list(POD):
                if pod.namespace.startswith(("tpu-test", "cd-")):
                    env_keys = ",".join(sorted(k for k in pod.injected_env
                                               if k.startswith(("TPU_", "MEGASCALE"))))
                    print(f"    {pod.namespace}/{pod.meta.name} on {pod.node_name}: "
                          f"{pod.phase}; devices={len(pod.injected_devices)}; env[{env_keys}]")
    finally:
        sim.stop()


def main(argv=None) -> int:
    parser = argparse.ArgumentParser(prog="tpu-dra-e2e", description=__doc__)
    parser.add_argument("scenarios", nargs="*", default=[])
    parser.add_argument("--list", action="store_true")
    args = parser.parse_args(argv)
    # Standalone runs self-provision the slice-channel mock seam (conftest
    # and the shell helpers do the same for their tiers): without a real
    # tpu-slice-channels char class, CD channel prepares retry forever.
    if "TPU_DRA_ALT_PROC_DEVICES" not in os.environ:
        from k8s_dra_driver_tpu.pkg import devcaps
        if devcaps.get_char_device_major() is None:
            import atexit
            seam = os.path.join(tempfile.gettempdir(), f"e2e-procdev-{os.getpid()}")
            with open(seam, "w", encoding="utf-8") as f:
                f.write("Character devices:\n511 tpu-slice-channels\n\nBlock devices:\n")
            os.environ["TPU_DRA_ALT_PROC_DEVICES"] = seam
            atexit.register(lambda: os.path.exists(seam) and os.unlink(seam))
    if args.list:
        for name in SCENARIOS:
            print(name)
        return 0
    names = args.scenarios or list(SCENARIOS)
    failed = []
    for name in names:
        if name not in SCENARIOS:
            print(f"unknown scenario {name!r}; --list shows options")
            return 2
        print(f"=== {name} ===")
        with tempfile.TemporaryDirectory() as tmp:
            try:
                run_scenario(SCENARIOS[name], tmp)
                print(f"    PASS {name}")
            except Exception as e:  # noqa: BLE001
                failed.append(name)
                print(f"    FAIL {name}: {e}")
    if failed:
        print(f"FAILED: {failed}")
        return 1
    print(f"all {len(names)} scenario(s) passed")
    return 0


if __name__ == "__main__":
    sys.exit(main())
