"""PreemptionController: checkpoint-aware eviction for higher-tier demand.

The third half of the contention plane. When a claim with an effective
priority tier above its would-be victims parks unschedulable (no free
placement / no contiguous host block), this controller plans the
minimal *blocking set by victim priority* over the same bitmask node
views the rebalancer uses, and evicts each victim unit through the
generalized ``evict_unit`` path — the rebalancer's migration unit with
the re-place half replaced by a requeue:

    owner-tagged cordon CAS (owner="preempt")
    -> checkpoint-aware unprepare on the source
       (DeviceState.migrate_out: state fsync'd BEFORE any release, so a
       crash can never leak an ICI partition)
    -> requeue the pod as Pending (node cleared) with its claims
       deallocated — the tenant's WFQ virtual time is preserved, so
       eviction is fairness-neutral
    -> close the MigrationCheckpoint entries -> uncordon.

Any mid-eviction failure rolls back to the exact prior placement: the
source re-prepare clears the MigrationCheckpoint entries and re-carves
the original partitions, the allocations are restored verbatim, and the
pod stays bound where it was.

Victim selection invariants (docs/reference/preemption.md):

- a unit is evictable only when its effective tier is STRICTLY below
  the preemptor's — equal-or-higher tiers are untouchable;
- assembled ComputeDomains are untouchable by construction (their
  workers carry channel claims, which pin the unit in the shared
  planner's movability rules);
- units cordoned by ANY owner (an in-flight rebalancer migration, a
  resize epoch, an autoscaler drain) are excluded — and symmetrically,
  those actors' planners skip units cordoned ``preempt``.

Evictions are budgeted (per-pass cap + token bucket), per-unit retries
are paced by ``pkg/backoff``, and the controller narrates through
``Preempted`` / ``PreemptionFailed`` events.
"""

from __future__ import annotations

import logging
import time
from dataclasses import dataclass
from typing import Callable, Dict, List, Optional, Set, Tuple

from k8s_dra_driver_tpu.api.configs import (
    TPU_DRIVER_NAME,
    channel_domain_uid,
)
from k8s_dra_driver_tpu.k8s.conditions import CONDITION_FALSE, set_condition
from k8s_dra_driver_tpu.k8s.core import (
    CLAIM_COND_ALLOCATED,
    COMPUTE_DOMAIN,
    POD,
    RESOURCE_CLAIM,
)
from k8s_dra_driver_tpu.k8s.objects import NotFoundError
from k8s_dra_driver_tpu.pkg import tracing
from k8s_dra_driver_tpu.pkg.backoff import Backoff, BackoffMetrics
from k8s_dra_driver_tpu.pkg.events import (
    EventRecorder,
    REASON_PREEMPTED,
    REASON_PREEMPTION_FAILED,
)
from k8s_dra_driver_tpu.pkg.history import RULE_EVICT, RULE_EVICT_FAILED
from k8s_dra_driver_tpu.pkg.metrics import Counter, Gauge, Registry
from k8s_dra_driver_tpu.rebalancer.controller import (
    CORDON_ANNOTATION,
    release_cordon,
    try_cordon,
)
from k8s_dra_driver_tpu.rebalancer.planner import (
    WHOLE_HOST,
    NodeView,
    build_node_views,
    plan_domain_block,
    plan_profile,
    profile_placeable,
)
from k8s_dra_driver_tpu.scheduling.tiers import request_profile

log = logging.getLogger(__name__)

# Owner tag for the atomic cordon CAS — distinct from "rebalancer",
# "autoscaler", and "resize", so of the actor roles racing on one claim
# exactly one wins (same-owner re-acquisition is this controller's
# crash-resume path).
CORDON_OWNER_PREEMPT = "preempt"

# Constant messages: a victim evicted (or an eviction failing) twice
# dedups into one Event series with a rising count.
MSG_PREEMPTED = ("claim checkpointed out and requeued by the preemption "
                 "engine to admit higher-priority demand")


@dataclass
class PreemptionConfig:
    """Policy knobs (docs/reference/preemption.md)."""

    # Hard cap on victim units evicted in one pass.
    max_evictions_per_pass: int = 8
    # Token bucket across passes: a tier storm cannot turn the
    # preemption engine into its own churn storm.
    eviction_burst: int = 32
    eviction_refill_per_s: float = 2.0
    # Per-unit retry pacing after a failed/rolled-back eviction.
    retry_backoff_base_s: float = 2.0
    retry_backoff_cap_s: float = 60.0


class PreemptionMetrics:
    def __init__(self, registry: Registry):
        self.preemptions_total = registry.register(Counter(
            "tpu_dra_preemptions_total",
            "Victim-unit evictions attempted, by outcome "
            "(evicted / failed — failed includes rolled-back).",
            ("outcome",)))
        self.victim_chips_total = registry.register(Counter(
            "tpu_dra_preemption_victim_chips_total",
            "Chips freed by completed evictions."))
        self.deferred_total = registry.register(Counter(
            "tpu_dra_preemption_deferred_total",
            "Planned evictions deferred by the per-pass cap or the "
            "token-bucket budget."))
        self.last_pass = registry.register(Gauge(
            "tpu_dra_preemption_last_pass_evictions",
            "Victim units evicted by the last preemption pass "
            "(0 when no higher-tier demand was parked)."))


class PreemptionController:
    """``plugin_resolver(node_name)`` returns the node's TpuDriver (the
    object exposing prepare_resource_claims / migrate_claim_out /
    migrate_claim_end), or None for unknown/down nodes — the same seam
    the rebalancer and the elastic orchestrator use. ``manager`` is the
    ContentionManager supplying tiers, quotas, and the WFQ bookkeeping
    hooks."""

    def __init__(
        self,
        api,
        allocator,
        plugin_resolver: Callable[[str], object],
        manager,
        config: Optional[PreemptionConfig] = None,
        metrics_registry: Optional[Registry] = None,
        clock: Callable[[], float] = time.monotonic,
    ):
        self.api = api
        self.allocator = allocator
        self.resolve_plugin = plugin_resolver
        self.manager = manager
        self.config = config or PreemptionConfig()
        registry = metrics_registry or Registry()
        self.metrics = PreemptionMetrics(registry)
        self.recorder = EventRecorder(api, "preemption",
                                      metrics_registry=registry)
        self.clock = clock
        # Optional flight recorder (pkg/history.py HistoryStore): plan-
        # level decisions on the demanding object (with the blocking
        # set) and per-victim eviction records both land here.
        self.history = None
        self._tokens = float(self.config.eviction_burst)
        self._tokens_at = clock()
        self.retry_backoff = Backoff(
            base=self.config.retry_backoff_base_s,
            cap=self.config.retry_backoff_cap_s,
            jitter=0.2, clock=clock,
            metrics=BackoffMetrics(registry), source="preemption")

    # Crash-injection seam (tests raise from here to simulate the
    # controller dying mid-eviction; same shape as the plugins' hooks).
    fault_hook: Optional[Callable[[str], None]] = None

    def _fire_fault(self, point: str) -> None:
        if self.fault_hook is not None:
            self.fault_hook(point)

    # -- budget ---------------------------------------------------------------

    def _take_token(self) -> bool:
        now = self.clock()
        self._tokens = min(
            float(self.config.eviction_burst),
            self._tokens + max(0.0, now - self._tokens_at)
            * self.config.eviction_refill_per_s)
        self._tokens_at = now
        if self._tokens < 1.0:
            return False
        self._tokens -= 1.0
        return True

    # -- the pass -------------------------------------------------------------

    def step(self) -> int:
        """One preemption pass; returns how many victim units were
        evicted. One claim + pod + domain listing per pass."""
        with tracing.span("preempt.pass") as sp:
            pods_by_uid = {p.uid: p for p in self.api.list(POD)}
            self.manager.refresh_quotas()
            # Cheap pre-gate: tiered demand needs a Pending pod that is
            # tiered by its own spec or its namespace floor. Without
            # one, skip the claim listing + view build entirely — the
            # common quiet-cluster (and pure-WFQ) case. Documented
            # asymmetry: a tier declared ONLY on a claim still protects
            # it as a victim and raises the pod's effective tier inside
            # the full pass, but does not by itself trigger one — tier
            # the pod or the namespace floor to demand preemption
            # (docs/reference/preemption.md).
            if not any(p.phase == "Pending"
                       and (p.priority_tier > 0
                            or self.manager.floor_for(p.meta.namespace) > 0)
                       for p in pods_by_uid.values()):
                self.metrics.last_pass.set(value=0.0)
                return 0
            claims = list(self.api.list(RESOURCE_CLAIM))
            self.manager.begin_pass(claims)
            demand = self._demand_targets(claims, pods_by_uid)
            if not demand:
                self.metrics.last_pass.set(value=0.0)
                return 0
            overview = self.allocator.placement_overview(TPU_DRIVER_NAME)
            device_types = {
                (node, name): t
                for node, entry in overview.items()
                for name, t in entry["dev_type"].items()
            }
            views = build_node_views(
                overview, claims, pods_by_uid, TPU_DRIVER_NAME, device_types,
                is_cordoned=lambda c: CORDON_ANNOTATION in c.meta.annotations,
                unit_tier=self.manager.tier_of,
            )
            evicted = 0
            budget = self.config.max_evictions_per_pass
            rank = (lambda u: u.tier)
            # Highest-tier demand plans first. Consumption (evicted
            # units removed, freed placements marked reserved for their
            # preemptor) is applied to BOTH the per-target filtered
            # copies and the shared base views, so a storm of k
            # same-shape pending claims frees k distinct placements in
            # one pass AND a later target can never double-count a spot
            # an earlier target reserved or re-plan its victims.
            for tier, kind, payload, involved in sorted(
                    demand, key=lambda d: (-d[0], str(d[3]))):
                if evicted >= budget:
                    break
                filtered = self._filter_views(views, tier)
                if kind == "profile":
                    profile, count = payload
                    remaining = count
                    while remaining > 0 and evicted < budget:
                        spot = self._reserve_free_placement(
                            filtered, profile)
                        if spot is not None:
                            # A free placement already exists (or one
                            # just got freed): that pending claim needs
                            # no eviction — reserve it for them.
                            node, mask = spot
                            views[node].used_mask |= mask
                            remaining -= 1
                            continue
                        plan = plan_profile(filtered, profile, rank=rank)
                        if plan is None:
                            break  # nothing evictable for this shape
                        got = self._execute(plan, budget - evicted, tier)
                        evicted += got
                        if got < len(plan.units):
                            break  # stuck or out of budget mid-plan
                        self._note_plan(involved, tier, plan, profile)
                        self._consume_plan(filtered, plan)
                        self._consume_plan(views, plan)
                        remaining -= 1
                else:
                    num_nodes, cd = payload
                    plan = plan_domain_block(
                        filtered, self.allocator.node_topologies(),
                        num_nodes, rank=rank,
                        target=f"host block for ComputeDomain {cd.key} "
                               f"({num_nodes} nodes)")
                    got = self._execute(plan, budget - evicted, tier)
                    evicted += got
                    if plan is not None and got == len(plan.units):
                        self._note_plan(involved, tier, plan,
                                        f"{num_nodes}-node block")
                        self._consume_plan(views, plan)
            sp.attrs["evicted"] = evicted
            self.metrics.last_pass.set(value=float(evicted))
            return evicted

    @staticmethod
    def _reserve_free_placement(views: Dict[str, NodeView],
                                profile: str):
        """Mark one currently-free placement of ``profile`` as used in
        the given views and return ``(node, mask)`` (None when no free
        placement exists) — the accounting that stops one pending
        claim's free spot from being counted against every other
        pending claim of the same shape. Callers mirror the mark into
        the base views."""
        if not profile_placeable(views, profile):
            return None
        for name in sorted(views):
            view = views[name]
            if profile == WHOLE_HOST:
                indices = (view.tables.whole_host_index,)
            else:
                indices = view.tables.by_profile.get(profile, ())
            for idx in indices:
                if not (view.available >> idx) & 1:
                    continue
                mask = view.tables.placements[idx].mask
                if not (mask & view.used_mask):
                    view.used_mask |= mask
                    return name, mask
        return None

    @staticmethod
    def _consume_plan(views: Dict[str, NodeView], plan) -> None:
        """Fold an executed plan into a view dict: evicted units vanish
        (their chips free), and the freed placement reads as used —
        reserved for the preemptor it was freed for."""
        named = {(u.pod_namespace, u.pod_name) for u in plan.units}
        for node in plan.nodes:
            view = views.get(node)
            if view is None:
                continue
            for u in list(view.units):
                if (u.pod_namespace, u.pod_name) in named:
                    view.units.remove(u)
                    view.used_mask &= ~u.chip_mask
            if plan.placement_mask:
                view.used_mask |= plan.placement_mask
            else:
                # Domain-block plans reserve the whole host.
                view.used_mask |= view.tables.placements[
                    view.tables.whole_host_index].mask

    # -- demand detection -----------------------------------------------------

    def _demand_targets(self, all_claims, pods_by_uid):
        """Parked higher-tier demand: Pending pods whose claims cannot
        allocate, with an effective tier above zero (tier-0 demand never
        preempts — victims must be STRICTLY lower). Over-quota tenants
        are skipped: their pods are blocked by policy, not capacity, and
        evicting for them would free chips the quota forbids using.
        Returns [(tier, kind, payload, involved)]."""
        targets = []
        domains_by_uid = {cd.uid: cd
                          for cd in self.api.list(COMPUTE_DOMAIN)}
        claims_by_key = {(c.meta.namespace, c.meta.name): c
                         for c in all_claims}
        # (tier, profile) -> [count, first involved claim]: a storm of k
        # same-shape pending claims is ONE target that frees k
        # placements, not k passes.
        profiles: Dict[Tuple[int, str], list] = {}
        seen_domains: Set[str] = set()
        for pod in pods_by_uid.values():
            if pod.phase != "Pending":
                continue
            claims = []
            for ref in pod.resource_claims:
                name = (ref.resource_claim_name
                        or f"{pod.meta.name}-{ref.name}")
                c = claims_by_key.get((pod.meta.namespace, name))
                if c is not None:
                    claims.append(c)
            if not claims or all(c.allocation is not None for c in claims):
                continue
            tier = self.manager.tier_of(pod, claims)
            if tier <= 0:
                continue
            if self.manager.quota_blocked(pod, claims):
                continue
            cd = None
            for c in claims:
                uid = channel_domain_uid(c)
                if uid:
                    cd = domains_by_uid.get(uid)
                    break
            if cd is not None and cd.spec.num_nodes > 1:
                if cd.uid not in seen_domains:
                    seen_domains.add(cd.uid)
                    targets.append(
                        (tier, "domain", (cd.spec.num_nodes, cd), cd))
                continue
            for c in claims:
                if c.allocation is not None:
                    continue
                for req in c.requests:
                    profile = (WHOLE_HOST if req.allocation_mode == "All"
                               else request_profile(req))
                    if profile is None:
                        continue  # count-based: fragmentation-free shape
                    entry = profiles.setdefault((tier, profile), [0, c])
                    # A count=k profile request needs k placements, not
                    # one (mode=All carries no count).
                    entry[0] += (1 if req.allocation_mode == "All"
                                 else max(1, req.count))
        for (tier, profile), (count, involved) in profiles.items():
            targets.append((tier, "profile", (profile, count), involved))
        return targets

    # -- tier filtering -------------------------------------------------------

    @staticmethod
    def _filter_views(views: Dict[str, NodeView],
                      preemptor_tier: int) -> Dict[str, NodeView]:
        """Per-target copies where equal-or-higher-tier units are
        immovable: their chips fold into the pinned mask, so no plan can
        ever name them as victims."""
        out: Dict[str, NodeView] = {}
        for name, v in views.items():
            evictable = [u for u in v.units if u.tier < preemptor_tier]
            pinned = v.pinned_mask
            for u in v.units:
                if u.tier >= preemptor_tier:
                    pinned |= u.chip_mask
            out[name] = NodeView(
                name=v.name, tables=v.tables, available=v.available,
                used_mask=v.used_mask, pinned_mask=pinned, units=evictable)
        return out

    # -- plan execution -------------------------------------------------------

    def _note_plan(self, involved, tier: int, plan, target: str) -> None:
        """Plan-level provenance on the DEMANDING object: which victims
        blocked it (the blocking set) and under what rank inputs — the
        victim side gets its own per-unit records inside ``_evict``."""
        if self.history is None or plan is None:
            return
        self.history.decide(
            controller="preemption", rule=RULE_EVICT,
            outcome="blocking-set-evicted", obj=involved,
            message=f"evicted {len(plan.units)} lower-tier unit(s) "
                    f"blocking {target}",
            inputs={"preemptor_tier": tier,
                    "blocking_set": sorted(
                        f"{u.pod_namespace}/{u.pod_name}"
                        for u in plan.units),
                    "victim_tiers": sorted(u.tier for u in plan.units),
                    "nodes": sorted(plan.nodes)},
            now=self.clock())

    def _execute(self, plan, budget: int, preemptor_tier: int = 0) -> int:
        if plan is None or not plan.units or budget <= 0:
            return 0
        # The full blocking set rides into each per-victim decision so
        # `explain pod/<victim>` shows the rank context it lost under.
        blocking = tuple(sorted(f"{u.pod_namespace}/{u.pod_name}"
                                for u in plan.units))
        evicted = 0
        for i, unit in enumerate(plan.units):
            if evicted >= budget:
                self.metrics.deferred_total.inc(
                    by=float(len(plan.units) - i))
                break
            outcome = self._evict_unit(unit, preemptor_tier, blocking)
            if outcome == "no-token":
                self.metrics.deferred_total.inc(
                    by=float(len(plan.units) - i))
                break
            if outcome == "evicted":
                evicted += 1
            else:
                # One stuck victim means this placement cannot be freed
                # this pass; don't churn the remaining units for nothing.
                break
        return evicted

    def _evict_unit(self, unit, preemptor_tier: int = 0,
                    blocking: tuple = ()) -> str:
        retry_key = (unit.pod_namespace, unit.pod_name)
        if not self.retry_backoff.ready(retry_key):
            return "skip"  # failed recently: wait out the backoff
        outcome = self._evict_unit_inner(unit, preemptor_tier, blocking)
        if outcome == "failed":
            self.retry_backoff.failure(retry_key)
        elif outcome == "evicted":
            self.retry_backoff.reset(retry_key)
        return outcome

    def _evict_unit_inner(self, unit, preemptor_tier: int = 0,
                          blocking: tuple = ()) -> str:
        with tracing.span("preempt.evict",
                          pod=f"{unit.pod_namespace}/{unit.pod_name}",
                          source=unit.node) as sp:
            claims = []
            for ns, name in unit.claim_keys:
                c = self.api.try_get(RESOURCE_CLAIM, name, ns)
                if (c is None or c.allocation is None
                        or c.allocation.node_name != unit.node):
                    return "skip"  # stale plan: the world moved on
                claims.append(c)
            pod = self.api.try_get(POD, unit.pod_name, unit.pod_namespace)
            if pod is None or pod.node_name != unit.node:
                return "skip"
            src_plugin = self.resolve_plugin(unit.node)
            if src_plugin is None:
                return "skip"
            # Atomic cordon BEFORE the budget token, exactly like the
            # rebalancer: losing any claim means another role owns part
            # of the unit — back off whole, costing neither.
            acquired = []
            for c in claims:
                if try_cordon(self.api, c, owner=CORDON_OWNER_PREEMPT):
                    acquired.append(c)
                    continue
                for got in acquired:
                    release_cordon(self.api, got)
                return "skip"
            if not self._take_token():
                for got in acquired:
                    release_cordon(self.api, got)
                return "no-token"
            sp.attrs["chips"] = unit.num_chips
            try:
                ok = self._evict(unit, claims, src_plugin, preemptor_tier,
                                 blocking)
            except Exception:  # noqa: BLE001 — one bad unit must not kill the pass
                # _evict is rollback-safe internally; anything reaching
                # here escaped its guarded windows. Count it failed and
                # let the pass continue — the next pass's refetch plus
                # checkpoint recovery own any residue.
                log.exception("eviction of %s/%s failed unexpectedly",
                              unit.pod_namespace, unit.pod_name)
                self._release(claims)
                self.metrics.preemptions_total.inc("failed")
                return "failed"
            return "evicted" if ok else "failed"

    # -- the eviction itself --------------------------------------------------

    def _evict(self, unit, claims, src_plugin,
               preemptor_tier: int = 0, blocking: tuple = ()) -> bool:
        """checkpoint-aware unprepare -> requeue pod -> deallocate ->
        close checkpoint entries -> uncordon, rolling back to the exact
        source placement on any failure."""
        old_allocs = {c.uid: c.allocation for c in claims}
        migrated_out: List[str] = []
        with tracing.span("preempt.unprepare", node=unit.node):
            try:
                for c in claims:
                    src_plugin.migrate_claim_out(c.uid)
                    migrated_out.append(c.uid)
            except Exception as e:  # noqa: BLE001 — roll straight back
                log.warning("migrate_out of %s failed: %s",
                            unit.pod_name, e)
                self._restore_source(unit, claims, src_plugin)
                self._record_failure(claims, unit,
                                     f"source unprepare: {e}")
                self._release(claims)
                return False
        try:
            self._fire_fault("quiesced")
            # Requeue FIRST, deallocate after: a crash between the two
            # leaves a Pending pod whose still-allocated claims steer it
            # back to its source node — a benign revert the ordinary
            # scheduler/kubelet loop completes (re-prepare clears the
            # MigrationCheckpoint entries), never a stranded pod.
            self._requeue_pod(unit)
            for c in claims:
                def clear(obj):
                    obj.allocation = None
                    set_condition(obj.conditions, CLAIM_COND_ALLOCATED,
                                  CONDITION_FALSE, "Preempted",
                                  "deallocated by the preemption engine")
                try:
                    self.api.update_with_retry(
                        RESOURCE_CLAIM, c.meta.name, c.namespace, clear)
                except NotFoundError:
                    continue
        except Exception as e:  # noqa: BLE001 — source already unprepared: ANY escape must restore it
            log.exception("unexpected error mid-eviction of %s/%s",
                          unit.pod_namespace, unit.pod_name)
            self._rollback(unit, claims, old_allocs, src_plugin,
                           f"unexpected mid-eviction error: {e}")
            return False
        # Past this point the eviction HAS succeeded: the closing steps
        # are individually best-effort, mirroring the rebalancer's
        # post-success discipline.
        for uid in migrated_out:
            try:
                src_plugin.migrate_claim_end(uid)
            except Exception:  # noqa: BLE001 — benign residue: the entry holds no devices and clears on the next prepare/unprepare/restart
                log.exception("migrate_claim_end(%s) on %s failed", uid,
                              unit.node)
        self._release(claims)
        if self.manager is not None:
            self.manager.note_evicted((unit.pod_namespace, unit.pod_name))
        for c in claims:
            self.recorder.warning(c, REASON_PREEMPTED, MSG_PREEMPTED)
        if self.history is not None:
            self.history.decide(
                controller="preemption", rule=RULE_EVICT,
                outcome="evicted", kind=POD,
                namespace=unit.pod_namespace, name=unit.pod_name,
                message=f"evicted off {unit.node} for tier-"
                        f"{preemptor_tier} demand, requeued Pending",
                inputs={"node": unit.node, "chips": unit.num_chips,
                        "victim_tier": unit.tier,
                        "preemptor_tier": preemptor_tier,
                        "blocking_set": list(blocking),
                        "claims": sorted(
                            f"{ns}/{n}" for ns, n in unit.claim_keys)},
                now=self.clock())
        self.metrics.preemptions_total.inc("evicted")
        self.metrics.victim_chips_total.inc(by=float(unit.num_chips))
        return True

    def _requeue_pod(self, unit) -> None:
        """Drop the victim pod back to Pending with no node: the
        scheduler re-places it wherever room exists, ordered by its
        tenant's PRESERVED WFQ position (aging restarts — it just ran)."""
        with tracing.span("preempt.requeue", pod=unit.pod_name):
            def mutate(obj):
                obj.node_name = ""
                obj.phase = "Pending"
                obj.ready = False
            try:
                self.api.update_with_retry(
                    POD, unit.pod_name, unit.pod_namespace, mutate)
            except NotFoundError:
                pass

    # -- rollback -------------------------------------------------------------

    def _rollback(self, unit, claims, old_allocs, src_plugin,
                  why: str) -> None:
        """Mid-eviction failure: restore the SOURCE placement exactly —
        allocations verbatim, pod bound back, source re-prepare clearing
        the MigrationCheckpoint entries and re-carving the original
        partitions."""
        with tracing.span("preempt.rollback", pod=unit.pod_name):
            for c in claims:
                def restore(obj, alloc=old_allocs.get(c.uid)):
                    obj.allocation = alloc
                try:
                    self.api.update_with_retry(
                        RESOURCE_CLAIM, c.meta.name, c.namespace, restore)
                except NotFoundError:
                    continue
            self._restore_source(unit, claims, src_plugin)

            def rebind(obj, node=unit.node):
                obj.node_name = node
                obj.phase = "Pending"  # kubelet re-prepares, then Running
                obj.ready = False
            try:
                self.api.update_with_retry(
                    POD, unit.pod_name, unit.pod_namespace, rebind)
            except NotFoundError:
                pass
        self._record_failure(claims, unit, why)
        self._release(claims)

    def _restore_source(self, unit, claims, src_plugin) -> None:
        """Re-prepare the claims on their source node; the prepare path
        clears MigrationCheckpoint entries, so after this the checkpoint
        and the partition ledger read exactly as before the eviction."""
        fresh = [self.api.try_get(RESOURCE_CLAIM, c.meta.name, c.namespace)
                 for c in claims]
        results = src_plugin.prepare_resource_claims(
            [c for c in fresh if c is not None])
        for uid, r in results.items():
            if isinstance(r, Exception):
                log.error("rollback re-prepare of %s on %s failed: %s",
                          uid, unit.node, r)

    def _record_failure(self, claims, unit, why: str) -> None:
        for c in claims:
            self.recorder.warning(
                c, REASON_PREEMPTION_FAILED,
                f"eviction off {unit.node} failed; claim rolled back to "
                f"its source placement: {why}")
        if self.history is not None:
            self.history.decide(
                controller="preemption", rule=RULE_EVICT_FAILED,
                outcome="rolled-back", kind=POD,
                namespace=unit.pod_namespace, name=unit.pod_name,
                message=f"eviction off {unit.node} failed: {why}",
                inputs={"node": unit.node, "chips": unit.num_chips,
                        "victim_tier": unit.tier},
                now=self.clock())
        self.metrics.preemptions_total.inc("failed")

    def _release(self, claims) -> None:
        for c in claims:
            release_cordon(self.api, c)
