"""ContentionManager: the scheduler-side half of the contention plane.

Owns the WFQ queue, the per-tenant quota/tier configuration (read from
TenantQuota objects once per scheduler pass), the pending-wait tracking
that drives starvation aging, and the change-gated TenantQuota status
write-back. The sim scheduler calls:

- :meth:`begin_pass` at the top of a dirty-batch pass (one TenantQuota
  listing + per-tenant chip usage derived from the claim listing);
- :meth:`order` to turn the dirty Pending set into the WFQ admission
  order;
- :meth:`quota_veto` per pod before probing nodes — an over-quota
  tenant's pod parks unschedulable with a ``QuotaExceeded`` event
  instead of consuming feasibility work;
- :meth:`charge` when a pod binds (advances the tenant's virtual time);
- :meth:`end_pass` to publish gauges and write TenantQuota status.

Eviction (``preemption.py``) notifies :meth:`note_evicted` so a victim's
aging clock restarts — the tenant's WFQ virtual time is deliberately
NOT touched: the deficit survives requeue, which is what makes
preemption fairness-neutral.
"""

from __future__ import annotations

import logging
from dataclasses import dataclass
from typing import Callable, Dict, List, Optional, Tuple

from k8s_dra_driver_tpu.api.tenantquota import TENANT_QUOTA, TenantQuota
from k8s_dra_driver_tpu.k8s.objects import NotFoundError
from k8s_dra_driver_tpu.pkg.events import (
    EventRecorder,
    REASON_QUOTA_EXCEEDED,
)
from k8s_dra_driver_tpu.pkg.metrics import Counter, Gauge, Registry
from k8s_dra_driver_tpu.scheduling.tiers import claim_chip_cost, effective_tier
from k8s_dra_driver_tpu.scheduling.wfq import (
    DEFAULT_AGING_AFTER_S,
    FairQueue,
    PendingItem,
)

log = logging.getLogger(__name__)

_Key = Tuple[str, str]

# Constant event message: a tenant pinned at its quota for an hour is
# ONE QuotaExceeded series with a rising count, not a row per pass.
MSG_QUOTA_EXCEEDED = ("namespace chip quota exceeded; pod parked until "
                     "usage drops or the TenantQuota is raised")


@dataclass
class ContentionConfig:
    """Policy knobs (docs/reference/preemption.md)."""

    # Pending work older than this jumps every non-aged bucket.
    aging_after_s: float = DEFAULT_AGING_AFTER_S
    # Write TenantQuota status once per pass (change-gated).
    status_writeback: bool = True


class ContentionMetrics:
    def __init__(self, registry: Registry):
        self.admitted_total = registry.register(Counter(
            "tpu_dra_wfq_admitted_total",
            "Pods admitted through WFQ ordering, by tenant namespace.",
            ("namespace",)))
        self.parked_total = registry.register(Counter(
            "tpu_dra_wfq_parked_total",
            "Pods parked by per-tenant quota enforcement, by namespace.",
            ("namespace",)))
        self.aged_total = registry.register(Counter(
            "tpu_dra_wfq_aged_admissions_total",
            "Admission-order picks that went first because the item "
            "crossed the starvation-aging threshold."))
        self.virtual_time = registry.register(Gauge(
            "tpu_dra_wfq_virtual_time",
            "WFQ virtual finish time per tenant namespace (how far "
            "ahead of the global virtual clock its admitted work sits).",
            ("namespace",)))
        self.pending = registry.register(Gauge(
            "tpu_dra_wfq_pending_pods",
            "Pending pods per tenant namespace as of the last "
            "scheduler pass.",
            ("namespace",)))


class ContentionManager:
    def __init__(self, api, metrics_registry: Optional[Registry] = None,
                 recorder: Optional[EventRecorder] = None,
                 config: Optional[ContentionConfig] = None,
                 whole_host_chips: int = 4,
                 clock: Callable[[], float] = None):
        self.api = api
        self.config = config or ContentionConfig()
        registry = metrics_registry or Registry()
        self.metrics = ContentionMetrics(registry)
        self.recorder = recorder or EventRecorder(
            api, "contention", metrics_registry=registry)
        self.clock = clock or (lambda: 0.0)
        self.whole_host_chips = whole_host_chips
        # Optional flight recorder (pkg/history.py HistoryStore): quota
        # parks emit DecisionRecords with the WFQ numbers they fired on.
        self.history = None
        self.queue = FairQueue(aging_after_s=self.config.aging_after_s)
        # Pass-scoped state refreshed by begin_pass().
        self._quotas: Dict[str, TenantQuota] = {}
        self._usage: Dict[str, int] = {}       # ns -> chips allocated
        self._pending: Dict[str, int] = {}     # ns -> pending pods this pass
        # (ns, pod) -> virtual time first seen pending; cleared on
        # admit/delete/evict so aging measures CONTINUOUS starvation.
        self._first_pending: Dict[_Key, float] = {}

    # -- pass lifecycle -------------------------------------------------------

    def refresh_quotas(self) -> None:
        """Reload the TenantQuota config (one listing). Cheap enough to
        run standalone — the preemption pass uses it to decide whether
        any tiered demand can even exist before paying for the claim
        listing."""
        quotas: Dict[str, TenantQuota] = {}
        for q in sorted(self.api.list(TENANT_QUOTA),
                        key=lambda q: (q.meta.namespace, q.meta.name)):
            # First-by-name wins when a namespace holds several.
            quotas.setdefault(q.meta.namespace, q)
        self._quotas = quotas
        for ns, q in quotas.items():
            self.queue.set_weight(ns, q.spec.weight)

    def begin_pass(self, claims=None) -> None:
        """Refresh quota/weight config and per-tenant chip usage. One
        TenantQuota listing; ``claims`` is the caller's claim listing
        when it already holds one (None lists here — still once per
        pass, never per pod)."""
        self.refresh_quotas()
        if claims is None:
            from k8s_dra_driver_tpu.k8s.core import RESOURCE_CLAIM

            claims = self.api.list(RESOURCE_CLAIM)
        usage: Dict[str, int] = {}
        for c in claims:
            if c.allocation is None:
                continue
            ns = c.meta.namespace
            usage[ns] = usage.get(ns, 0) + claim_chip_cost(
                c, self.whole_host_chips)
        self._usage = usage
        self._pending = {}

    def end_pass(self) -> None:
        """Publish per-tenant gauges and write TenantQuota status
        (quantized + change-gated: a steady pass writes nothing)."""
        for ns in set(self._quotas) | set(self._usage) | set(self._pending):
            self.metrics.virtual_time.set(ns, value=self.queue.vtime(ns))
            self.metrics.pending.set(
                ns, value=float(self._pending.get(ns, 0)))
        if not self.config.status_writeback:
            return
        now = self.clock()
        for ns, q in self._quotas.items():
            chips = int(self._usage.get(ns, 0))
            pending = int(self._pending.get(ns, 0))
            vtime = round(self.queue.vtime(ns), 1)
            st = q.status
            if (st.chips_used == chips and st.pods_pending == pending
                    and st.virtual_time == vtime):
                continue

            def sync(obj, chips=chips, pending=pending, vtime=vtime,
                     now=now):
                obj.status.chips_used = chips
                obj.status.pods_pending = pending
                obj.status.virtual_time = vtime
                obj.status.updated_at = now
            try:
                self.api.update_with_retry(
                    TENANT_QUOTA, q.meta.name, q.meta.namespace, sync)
            except NotFoundError:
                continue

    # -- configuration views --------------------------------------------------

    def quota_for(self, namespace: str) -> Optional[TenantQuota]:
        return self._quotas.get(namespace)

    def weight_for(self, namespace: str) -> float:
        q = self._quotas.get(namespace)
        return q.spec.weight if q is not None else 1.0

    def floor_for(self, namespace: str) -> int:
        q = self._quotas.get(namespace)
        return q.spec.priority_floor if q is not None else 0

    def tier_of(self, pod, claims) -> int:
        ns = pod.meta.namespace if pod is not None else ""
        return effective_tier(pod, claims, self.floor_for(ns))

    # -- admission ordering ---------------------------------------------------

    def order(self, pods: List, now: float,
              cost_of: Callable[[object], float],
              claims_of: Optional[Callable[[object], list]] = None,
              ) -> List[_Key]:
        """WFQ admission order for one dirty batch of Pending pods.
        ``cost_of`` estimates a pod's chip cost and ``claims_of``
        resolves its already-existing claims (the cluster resolves
        claim templates — this module never re-implements that); claim-
        declared tiers count toward the ordering tier when resolvable."""
        items: List[PendingItem] = []
        for pod in pods:
            key = (pod.meta.namespace, pod.meta.name)
            first = self._first_pending.setdefault(key, now)
            self._pending[pod.meta.namespace] = (
                self._pending.get(pod.meta.namespace, 0) + 1)
            items.append(PendingItem(
                tenant=pod.meta.namespace,
                key=key,
                cost=max(0.0, float(cost_of(pod))),
                tier=self.tier_of(
                    pod, claims_of(pod) if claims_of is not None else ()),
                waited_s=max(0.0, now - first),
            ))
        ordered = self.queue.order(items)
        for it in ordered:
            if self.queue.aged(it):
                self.metrics.aged_total.inc()
        return [it.key for it in ordered]

    # -- quota enforcement ----------------------------------------------------

    def quota_blocked(self, pod, claims) -> bool:
        """Pure check (no events/metrics): would admitting this pod's
        not-yet-allocated claims push its tenant over the chip quota?
        The preemption engine uses this to skip quota-blocked demand —
        evicting victims for chips the quota forbids using is waste."""
        ns = pod.meta.namespace
        q = self._quotas.get(ns)
        if q is None or q.spec.chip_quota <= 0:
            return False
        demand = sum(claim_chip_cost(c, self.whole_host_chips)
                     for c in claims if c.allocation is None)
        return self._usage.get(ns, 0) + demand > q.spec.chip_quota

    def quota_veto(self, pod, claims) -> Optional[str]:
        """None when the pod fits its tenant's chip quota; otherwise a
        human reason (the pod parks unschedulable). Counts only the
        pod's not-yet-allocated claims — an allocated claim is already
        in the usage baseline."""
        if not self.quota_blocked(pod, claims):
            return None
        ns = pod.meta.namespace
        q = self._quotas[ns]
        demand = sum(claim_chip_cost(c, self.whole_host_chips)
                     for c in claims if c.allocation is None)
        used = self._usage.get(ns, 0)
        self.metrics.parked_total.inc(ns)
        self.recorder.warning(pod, REASON_QUOTA_EXCEEDED, MSG_QUOTA_EXCEEDED)
        if self.history is not None:
            from k8s_dra_driver_tpu.pkg.history import RULE_WFQ_PARK_QUOTA

            self.history.decide(
                controller="wfq", rule=RULE_WFQ_PARK_QUOTA,
                outcome="parked", obj=pod,
                message=f"tenant {ns!r} over chip quota",
                inputs={"used": used, "demand": demand,
                        "quota": q.spec.chip_quota,
                        "weight": q.spec.weight,
                        "virtual_time": round(self.queue.vtime(ns), 3)},
                now=self.clock())
        return (f"tenant {ns!r} over chip quota: {used} used + {demand} "
                f"requested > {q.spec.chip_quota} allowed")

    # -- accounting -----------------------------------------------------------

    def charge(self, pod, newly_allocated_chips: float) -> None:
        """A pod bound: advance its tenant's virtual time by the chips
        this pass actually allocated for it, fold the chips into the
        pass usage (quota sees in-pass commitments), and clear its
        aging clock."""
        ns = pod.meta.namespace
        self.queue.charge(ns, newly_allocated_chips)
        self._usage[ns] = (self._usage.get(ns, 0)
                           + int(newly_allocated_chips))
        self._first_pending.pop((ns, pod.meta.name), None)
        self.metrics.admitted_total.inc(ns)

    def note_evicted(self, key: _Key) -> None:
        """A preemption victim requeued: its aging clock restarts (it
        just received service), but the tenant's WFQ virtual time is
        NOT rolled back — the deficit is preserved across eviction."""
        self._first_pending.pop(key, None)

    def note_gone(self, key: _Key) -> None:
        self._first_pending.pop(key, None)
