"""Multi-tenant contention plane: priority tiers, weighted fair queuing,
and checkpoint-aware preemption.

Three halves closed into one loop (docs/reference/preemption.md):

- **Priority + tenancy API** — the ``TenantQuota`` kind
  (``api/tenantquota.py``: per-namespace weight, chip quota, priority
  floor) plus the ``priorityTier`` field on claims and pods.
- **Weighted fair queuing in admission** — the sim scheduler's
  dirty-batch admission orders pending work by virtual-time fair
  queuing over tenant weights (``wfq.py``, pure), enforces per-tenant
  chip quotas (over-quota claims park with a reason), and ages starved
  work so a light tenant can never wait forever behind a heavy one's
  backlog (``manager.py``).
- **Preemption engine** — a higher-tier claim that parks unschedulable
  triggers a planner pass that scores minimal blocking sets by victim
  priority and checkpoints strictly-lower-tier victims out through the
  shared ``evict_unit`` path: owner-tagged cordon CAS (owner =
  ``preempt``), MigrationCheckpoint-guarded unprepare, requeue as
  Pending with the tenant's WFQ accounting preserved, full rollback on
  any mid-eviction failure (``preemption.py``).
"""

from k8s_dra_driver_tpu.scheduling.wfq import (  # noqa: F401
    FairQueue,
    PendingItem,
    fair_apportion,
    jain_index,
)
from k8s_dra_driver_tpu.scheduling.tiers import (  # noqa: F401
    claim_chip_cost,
    effective_tier,
    profile_chips,
    request_profile,
)
from k8s_dra_driver_tpu.scheduling.manager import (  # noqa: F401
    ContentionConfig,
    ContentionManager,
)
from k8s_dra_driver_tpu.scheduling.preemption import (  # noqa: F401
    CORDON_OWNER_PREEMPT,
    PreemptionConfig,
    PreemptionController,
)
