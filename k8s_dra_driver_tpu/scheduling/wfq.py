"""Weighted fair queuing: pure virtual-time accounting, no API access.

The admission-order half of the contention plane, kept free of store or
clock dependencies so every property is unit-testable:

- **Virtual-time fair queuing** (:class:`FairQueue`): each tenant
  carries a virtual finish time; admitting work of ``cost`` chips
  advances it by ``cost / weight``. Ordering pending work by projected
  finish time makes chip-throughput proportional to weight under
  contention — the classic WFQ/SFQ result — regardless of how many
  claims each tenant floods. The per-tenant clock is the "deficit" the
  preemption engine preserves when it requeues victims: eviction never
  resets a tenant's position in the queue.
- **Starvation aging**: an item that has waited past ``aging_after_s``
  jumps every non-aged bucket (including higher tiers), so a light
  tenant's claim can never wait forever behind a heavy tenant's
  backlog or a stream of high-tier arrivals.
- **Priority tiers** order above virtual time (higher tier admits
  first) — that is what lets a freshly-preempted high-tier claim take
  the hole its eviction just opened before the requeued victims refill
  it.
- :func:`fair_apportion` — weighted max-min water-filling used by the
  autoscaler's multi-group fairness hook when the fleet cannot satisfy
  the sum of desired scale-ups.
- :func:`jain_index` — the fairness statistic the bench gates on.
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Dict, Iterable, List, Mapping, Sequence, Tuple

# A zero/negative weight would divide by zero (or invert the queue);
# clamp instead of raising so a hostile TenantQuota cannot wedge the
# scheduler pass.
MIN_WEIGHT = 1e-6

DEFAULT_AGING_AFTER_S = 120.0


@dataclass(frozen=True)
class PendingItem:
    """One schedulable unit awaiting admission."""

    tenant: str                    # namespace
    key: Tuple[str, str]           # (namespace, name) — the sort tiebreak
    cost: float = 1.0              # chips the unit will consume
    tier: int = 0                  # effective priority tier
    waited_s: float = 0.0          # how long it has been pending


class FairQueue:
    """Per-tenant virtual-time accounting.

    ``order()`` is a pure function of the queue state plus the pending
    set (it simulates admission without mutating state); ``charge()``
    advances the real clock when the scheduler actually binds work.
    State is two floats per tenant — safe to keep for the lifetime of a
    controller and cheap to surface in TenantQuota status.
    """

    def __init__(self, aging_after_s: float = DEFAULT_AGING_AFTER_S):
        self.aging_after_s = aging_after_s
        self._weights: Dict[str, float] = {}
        self._vtime: Dict[str, float] = {}   # tenant -> virtual finish time
        self._global = 0.0                   # floor for idle tenants

    # -- configuration --------------------------------------------------------

    def set_weight(self, tenant: str, weight: float) -> None:
        self._weights[tenant] = max(MIN_WEIGHT, float(weight))

    def weight(self, tenant: str) -> float:
        return self._weights.get(tenant, 1.0)

    def vtime(self, tenant: str) -> float:
        """The tenant's virtual finish time (its WFQ "deficit" position).
        An idle tenant reads the global floor — joining late never grants
        banked credit for time it spent absent (standard SFQ start-time
        rule)."""
        return max(self._vtime.get(tenant, 0.0), self._global)

    def forget(self, tenant: str) -> None:
        self._vtime.pop(tenant, None)
        self._weights.pop(tenant, None)

    # -- ordering -------------------------------------------------------------

    def order(self, items: Sequence[PendingItem]) -> List[PendingItem]:
        """Admission order for one dirty batch: aged items first (their
        wait crossed ``aging_after_s``), then priority tier descending,
        then weighted-fair virtual finish ascending, then key.

        Simulated: each pick advances a scratch copy of the tenant
        clocks so a tenant's second item is ordered behind the virtual
        cost of its first — without ``charge()`` side effects (the
        scheduler only charges what actually binds)."""
        sim_vtime = {t: self.vtime(t)
                     for t in {it.tenant for it in items}}
        remaining: Dict[str, List[PendingItem]] = {}
        for it in sorted(items, key=lambda i: i.key):
            remaining.setdefault(it.tenant, []).append(it)
        out: List[PendingItem] = []

        def sort_key(it: PendingItem):
            aged = it.waited_s >= self.aging_after_s
            finish = sim_vtime[it.tenant] + it.cost / self.weight(it.tenant)
            return (not aged, -it.tier, finish, it.key)

        while remaining:
            # Heads only: within a tenant the batch admits in key order,
            # so only each tenant's first pending item competes.
            heads = [q[0] for q in remaining.values()]
            best = min(heads, key=sort_key)
            out.append(best)
            sim_vtime[best.tenant] += best.cost / self.weight(best.tenant)
            q = remaining[best.tenant]
            q.pop(0)
            if not q:
                del remaining[best.tenant]
        return out

    def aged(self, item: PendingItem) -> bool:
        return item.waited_s >= self.aging_after_s

    # -- accounting -----------------------------------------------------------

    def charge(self, tenant: str, cost: float) -> float:
        """Record actually-admitted work: the tenant's virtual finish
        time advances by cost/weight from max(own clock, global floor).
        Returns the new virtual time."""
        start = self.vtime(tenant)
        finish = start + max(0.0, float(cost)) / self.weight(tenant)
        self._vtime[tenant] = finish
        # The floor follows admitted START times so an idle tenant
        # re-entering competes fairly rather than from virtual zero.
        self._global = max(self._global, start)
        return finish


def fair_apportion(demands: Mapping[str, float],
                   weights: Mapping[str, float],
                   capacity: float) -> Dict[str, float]:
    """Weighted max-min apportionment (water-filling): split ``capacity``
    across keys in proportion to weight, never granting more than a
    key's demand, redistributing unused share until either every demand
    is satisfied or capacity runs dry. Deterministic; grants are floats
    (callers floor to whole replicas/chips as needed)."""
    grants = {k: 0.0 for k in demands}
    active = {k for k, d in demands.items() if d > 0}
    cap = max(0.0, float(capacity))
    # Each round either satisfies (and removes) a key or exhausts the
    # capacity exactly, so len(demands)+1 rounds always suffice.
    for _ in range(len(grants) + 1):
        if not active or cap <= 1e-12:
            break
        total_w = sum(max(MIN_WEIGHT, weights.get(k, 1.0)) for k in active)
        satisfied = set()
        granted_this_round = 0.0
        for k in sorted(active):
            share = cap * max(MIN_WEIGHT, weights.get(k, 1.0)) / total_w
            need = demands[k] - grants[k]
            got = min(share, need)
            grants[k] += got
            granted_this_round += got
            if grants[k] >= demands[k] - 1e-12:
                satisfied.add(k)
        cap -= granted_this_round
        if not satisfied:
            break  # everyone proportionally constrained: capacity spent
        active -= satisfied
    return grants


def jain_index(values: Iterable[float]) -> float:
    """Jain's fairness index: (sum x)^2 / (n * sum x^2); 1.0 = perfectly
    even shares, ->1/n as one share dominates. Degenerate inputs (empty,
    all-zero) read as perfectly fair."""
    xs = [float(v) for v in values]
    if not xs:
        return 1.0
    sq = sum(x * x for x in xs)
    if sq <= 0.0:
        return 1.0
    s = sum(xs)
    return (s * s) / (len(xs) * sq)


# Backwards-friendly re-export spot for the aging default.
__all__ = [
    "DEFAULT_AGING_AFTER_S",
    "FairQueue",
    "PendingItem",
    "fair_apportion",
    "jain_index",
]
