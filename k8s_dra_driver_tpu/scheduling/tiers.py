"""Tier and chip-cost helpers shared across the contention plane.

Dependency-light on purpose: the rebalancer's demand detector imports
:func:`request_profile` from here (one copy of the CEL profile-equality
reverse-parse), and this module never imports the rebalancer or the
scheduling controllers — so the import graph stays acyclic.
"""

from __future__ import annotations

import re
from typing import Optional

# The common CEL shape selecting a subslice profile by equality, e.g.
# device.attributes["tpu.google.com"].profile == "2x2". Anything more
# elaborate (ranges, disjunctions) is not reverse-engineered — the
# request simply yields no profile (documented limitation).
_CEL_PROFILE = re.compile(r"""profile["'\]]*\s*==\s*["']([\w]+)["']""")


def request_profile(req) -> Optional[str]:
    """The subslice profile one device request demands via the common
    selector shapes (legacy ``profile=2x2`` or the CEL equality), or
    None when the request is count-based."""
    if req.allocation_mode == "All":
        return None  # whole host: callers handle mode=All themselves
    for sel in req.selectors:
        key, _, value = sel.partition("=")
        if key.strip() == "profile" and value:
            return value.strip()
    for expr in getattr(req, "cel_selectors", ()):
        m = _CEL_PROFILE.search(expr)
        if m:
            return m.group(1)
    return None


def profile_chips(profile: str) -> int:
    """Chip area of a subslice profile string ("2x2" -> 4); 1 for the
    empty/unparseable profile."""
    if not profile:
        return 1
    out = 1
    for d in profile.lower().split("x"):
        try:
            out *= max(1, int(d))
        except ValueError:
            return 1
    return out


def claim_chip_cost(claim, whole_host_chips: int) -> int:
    """Chips one claim will consume once allocated — the WFQ service
    cost and the quota unit. mode=All counts the whole host; profile
    requests their area; plain requests their device count. Channel /
    daemon requests (no chips) cost 0 via count only when count-based.
    """
    total = 0
    for req in claim.requests:
        if req.allocation_mode == "All":
            total += max(1, whole_host_chips)
            continue
        profile = request_profile(req)
        if profile is not None:
            total += profile_chips(profile) * max(1, req.count)
        else:
            total += max(0, req.count)
    return total


def effective_tier(pod, claims, floor: int = 0) -> int:
    """The contention tier admission and preemption act on: the max of
    the pod's declared tier, every claim's declared tier, and the
    namespace's TenantQuota priority floor. A workload can raise itself
    above its namespace floor, never demote below it."""
    tier = max(0, int(floor))
    if pod is not None:
        tier = max(tier, int(getattr(pod, "priority_tier", 0)))
    for c in claims or ():
        tier = max(tier, int(getattr(c, "priority_tier", 0)))
    return tier
