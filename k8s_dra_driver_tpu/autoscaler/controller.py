"""ServingGroupController: replica stamping plus the closed scaling loop.

The actuating half of the serving loop. Every virtual tick, fed by the
traffic engine's samples, the SLO evaluator's ``active_alerts()``
snapshot, and the telemetry rollup's claim summaries, the controller:

**Decides** (per group, under policy):

- *Horizontal up* — demand-tracking: whenever the demand-sized count
  (``ceil(qps / (capacity x target_duty))``) exceeds ``spec.replicas``,
  raise it (bounded by ``max_replicas`` and the scale-up cooldown). An
  active ``serving-latency`` burn alert additionally forces at least
  one extra replica per tick even when the demand formula claims
  capacity is adequate — the SLO keeps stepping the group up until the
  incident clears. The resulting replica storm is identical-shaped
  claims, so PR 8's gang admission resolves the whole batch against
  ONE feasibility computation.
- *Horizontal down* — the demand count must stay below ``spec.replicas``
  for the WHOLE stabilization window (the effective desired count is
  the max over the window — classic HPA semantics, so a bursty trace
  never flaps), the scale-down cooldown must have passed, and no alert
  may be active. Reclaimed chips are freed through the normal
  unprepare path; with the rebalancer's energy mode on they consolidate
  onto fewer hosts (``tpu_dra_reclaimable_hosts`` rises).
- *Vertical down-tier* — observed duty p95 across the group's claims
  sustained under ``down_tier_duty`` moves ``spec.profile`` one step
  down ``spec.tiers``; replicas then roll to the new tier (surge first,
  drain after), riding the same cordon protocol as the live-repack
  migration unit so the rebalancer and the autoscaler never double-
  handle one replica.
- Decisions blocked by cooldown or stabilization emit ``ScaleDeferred``.

**Reconciles**: stamps replica pods + claims to ``spec.replicas`` at
``spec.profile`` (indices reused lowest-free), garbage-collects
scale-downs (victims on the emptiest hosts first, cordon-acquired
atomically via :func:`rebalancer.controller.try_cordon`), drains
replicas of deleted groups, and rolls old-tier replicas out once their
replacements are Running.

Every decision runs under a tracing span and narrates through
``ScaleUp`` / ``ScaleDown`` / ``ScaleDeferred`` events whose messages
carry no live numbers — a sustained trough is ONE ScaleDown series with
a rising count. Zero store ``list()`` calls in the steady-state pass:
everything reads the traffic engine's watch-fed caches.
"""

from __future__ import annotations

import logging
import math
import time
from collections import deque
from dataclasses import dataclass
from typing import Callable, Deque, Dict, List, Optional, Set, Tuple

from k8s_dra_driver_tpu.api.servinggroup import (
    SERVING_GROUP,
    SERVING_GROUP_LABEL,
    SERVING_REPLICA_ANNOTATION,
    SERVING_TIER_LABEL,
    ServingGroup,
    replica_capacity_qps,
    tier_chips,
)
from k8s_dra_driver_tpu.autoscaler.traffic import (
    SERVING_LATENCY_SLO,
    GroupSample,
    TrafficEngine,
)
from k8s_dra_driver_tpu.controller.templates import DEVICE_CLASS_TPU
from k8s_dra_driver_tpu.k8s.core import (
    Container,
    DeviceRequest,
    POD,
    Pod,
    PodResourceClaimRef,
    RESOURCE_CLAIM,
    ResourceClaim,
    UtilizationSummary,
)
from k8s_dra_driver_tpu.k8s.objects import (
    AlreadyExistsError,
    NotFoundError,
    new_meta,
)
from k8s_dra_driver_tpu.pkg import tracing
from k8s_dra_driver_tpu.pkg.events import (
    EventRecorder,
    REASON_SCALE_DEFERRED,
    REASON_SCALE_DOWN,
    REASON_SCALE_UP,
)
from k8s_dra_driver_tpu.pkg.history import (
    RULE_SCALE_DEFER,
    RULE_SCALE_DOWN,
    RULE_SCALE_TIER_DOWN,
    RULE_SCALE_UP,
)
from k8s_dra_driver_tpu.pkg.metrics import Counter, Gauge, Registry
from k8s_dra_driver_tpu.rebalancer.controller import (
    release_cordon,
    try_cordon,
)

log = logging.getLogger(__name__)

# Subslice device class (sim/chart name): tier profiles select by the
# published profile attribute, the same CEL shape the rebalancer's
# demand detector recognizes.
DEVICE_CLASS_SUBSLICE = "subslice.tpu.google.com"
TPU_ATTR_DOMAIN = "tpu.google.com"

# Event messages are CONSTANT per (reason, cause): the correlator dedups
# a sustained condition into one Event row with a rising count.
MSG_SCALE_UP = ("scaling up: demand above the target utilization or a "
                "serving-latency burn alert is active")
MSG_SCALE_DOWN = ("scaling down: demand stayed below the target "
                  "utilization for the whole stabilization window")
MSG_TIER_DOWN = ("down-tiering replica subslice profile: observed duty "
                 "sustained below the down-tier threshold")
MSG_DEFERRED = ("scale decision deferred by cooldown, stabilization "
                "window, or an active burn alert")

_Key = Tuple[str, str]

# Safety margin on the SLO floor: scale-down never targets a count whose
# predicted utilization sits closer than this to the latency-violating
# rho (ratio 1.0 at rho = 1 - base/bound in the M/M/1 model).
SLO_FLOOR_MARGIN = 0.95


@dataclass
class ScaleDecision:
    """One group's verdict for one tick (returned for tests/bench)."""

    key: _Key
    direction: str = "none"   # up | down | tier-down | deferred | none
    desired: int = 0          # demand-sized replica count (pre-policy)
    applied: int = 0          # spec.replicas after this tick


class ServingGroupController:
    """Owns actuation; senses through the shared :class:`TrafficEngine`
    caches. ``clock`` is the VIRTUAL clock (the sim's telemetry clock) —
    wall time never enters a scaling decision."""

    def __init__(self, api, metrics_registry: Registry,
                 engine: TrafficEngine,
                 recorder: Optional[EventRecorder] = None,
                 headroom_fn: Optional[Callable[[], float]] = None,
                 tenant_weight_fn: Optional[Callable[[str], float]] = None):
        self.api = api
        self.engine = engine
        self.recorder = recorder or EventRecorder(
            api, "autoscaler", metrics_registry=metrics_registry)
        # Multi-group fairness hooks: when the fleet's free-chip
        # headroom cannot satisfy the SUM of desired scale-ups this
        # tick, apportion it across groups by tenant weight (weighted
        # max-min water-filling) instead of first-writer-wins; clamped
        # losers surface as ScaleDeferred. None = unconstrained (the
        # pre-contention behavior).
        self.headroom_fn = headroom_fn
        self.tenant_weight_fn = tenant_weight_fn
        # Optional flight recorder (pkg/history.py HistoryStore): every
        # scale verdict (up/down/tier-down/deferred) lands there with
        # the numbers it fired on — qps, demand, stabilized max, floor.
        self.history = None
        r = metrics_registry
        self.desired_gauge = r.register(Gauge(
            "tpu_dra_autoscaler_desired_replicas",
            "Demand-sized replica count per ServingGroup "
            "(ceil(qps / (capacity x target_duty)), pre-policy).",
            ("namespace", "name")))
        self.ready_gauge = r.register(Gauge(
            "tpu_dra_autoscaler_ready_replicas",
            "Ready replicas per ServingGroup.",
            ("namespace", "name")))
        self.scale_total = r.register(Counter(
            "tpu_dra_autoscaler_scale_total",
            "Scaling decisions applied or deferred, by direction "
            "(up / down / tier-down / deferred).",
            ("direction",)))
        self.pass_seconds = r.register(Gauge(
            "tpu_dra_autoscaler_pass_seconds",
            "Wall time of the last autoscaler pass."))
        # (ns, name) -> recent (t, demand-desired) history; the scale-down
        # stabilization window reads its max.
        self._desired_history: Dict[_Key, Deque[Tuple[float, int]]] = {}
        # (ns, name) -> virtual time this controller first saw the group:
        # scale-down is gated on a FULL stabilization window of
        # observation, so a freshly created (or freshly re-adopted after
        # controller restart) pre-provisioned group is never torn down on
        # a single low sample.
        self._first_seen: Dict[_Key, float] = {}

    # -- the pass ------------------------------------------------------------

    def step(self, now: float, samples: Dict[_Key, GroupSample],
             alerts=None,
             claim_summaries: Optional[Dict[_Key, UtilizationSummary]] = None,
             ) -> List[ScaleDecision]:
        """One autoscaler tick. ``alerts`` is the SLO evaluator's
        ``active_alerts()`` snapshot (already filtered to this pass);
        ``claim_summaries`` the telemetry rollup's per-claim summaries
        (vertical re-tier and victim ranking read them)."""
        t0 = time.perf_counter()
        decisions: List[ScaleDecision] = []
        alerting: Set[_Key] = {
            a.subject for a in (alerts or ())
            if a.slo == SERVING_LATENCY_SLO
        }
        allowances = self._fair_up_allowances(samples, alerting)
        with tracing.span("autoscaler.pass") as sp:
            for key, sample in samples.items():
                try:
                    decisions.append(self._step_group(
                        key, sample, now, key in alerting,
                        claim_summaries or {},
                        max_up=(allowances.get(key)
                                if allowances is not None else None)))
                except Exception:  # noqa: BLE001 — one bad group must not stall the fleet
                    log.exception("autoscaler pass failed for %s/%s", *key)
            # Replicas whose group vanished: drain (no ownerRef GC path
            # covers ServingGroups) — and drop their decision history,
            # or a churn of short-lived groups grows it without bound.
            for pod in self.engine.orphan_replicas():
                self._drain_replica(pod)
            for key in [k for k in self._desired_history
                        if k not in samples]:
                del self._desired_history[key]
                self._first_seen.pop(key, None)
            sp.attrs["groups"] = len(samples)
            sp.attrs["scaled"] = sum(
                1 for d in decisions if d.direction in ("up", "down"))
        self.pass_seconds.set(value=time.perf_counter() - t0)
        return decisions

    @staticmethod
    def _up_target(spec, sample: GroupSample, alerting: bool):
        """THE scale-up formula — the single copy both the fairness
        pre-pass and _step_group call, so they can never drift. Returns
        (demand, desired, push, wants_up, target); ``wants_up`` with
        ``target <= spec.replicas`` means clamped-while-wanting (the
        deferral case)."""
        policy = spec.policy
        cap = replica_capacity_qps(spec)
        demand = math.ceil(sample.qps / max(1e-9, cap * policy.target_duty))
        desired = max(policy.min_replicas,
                      min(policy.max_replicas, demand))
        push = alerting and sample.latency_ratio > 1.0
        cur = spec.replicas
        # `demand` (unclamped) gates the branch so wanting more than
        # max_replicas surfaces as a deferral, not silence; `desired`
        # (clamped) covers the min-replicas floor on an undersized group.
        wants_up = demand > cur or desired > cur or push
        if wants_up:
            target = min(policy.max_replicas,
                         max(desired, cur + 1 if push else 0))
        else:
            target = cur
        return demand, desired, push, wants_up, target

    def _fair_up_allowances(
            self, samples: Dict[_Key, GroupSample],
            alerting: Set[_Key]) -> Optional[Dict[_Key, int]]:
        """Per-group replica allowance for this tick's scale-ups, or
        None when unconstrained. Only engages when the summed chip
        demand exceeds the fleet's free-chip headroom: then capacity is
        apportioned across groups by tenant weight (weighted max-min),
        so a heavy group's storm cannot take every last chip first —
        the clamped groups defer visibly instead of silently losing."""
        if self.headroom_fn is None:
            return None
        demands: Dict[_Key, float] = {}
        chips_per_replica: Dict[_Key, int] = {}
        for key, sample in samples.items():
            spec = sample.group.spec
            _, _, _, _, target = self._up_target(
                spec, sample, key in alerting)
            delta = max(0, target - spec.replicas)
            if delta:
                chips = max(1, tier_chips(spec.profile))
                demands[key] = float(delta * chips)
                chips_per_replica[key] = chips
        if not demands:
            return None
        try:
            headroom = max(0.0, float(self.headroom_fn()))
        except Exception:  # noqa: BLE001 — a headroom probe failure must not stall scaling
            log.exception("headroom probe failed; scaling unconstrained")
            return None
        if sum(demands.values()) <= headroom:
            return None
        from k8s_dra_driver_tpu.scheduling.wfq import fair_apportion

        weights = {
            key: (self.tenant_weight_fn(key[0])
                  if self.tenant_weight_fn is not None else 1.0)
            for key in demands
        }
        grants = fair_apportion(demands, weights, headroom)
        return {key: int(grants[key] // chips_per_replica[key])
                for key in demands}

    def _step_group(self, key: _Key, sample: GroupSample, now: float,
                    alerting: bool,
                    claim_summaries: Dict[_Key, UtilizationSummary],
                    max_up: Optional[int] = None,
                    ) -> ScaleDecision:
        group = sample.group
        spec = group.spec
        policy = spec.policy
        cap = replica_capacity_qps(spec)
        # THE one copy of the scale-up formula (shared with the
        # fairness pre-pass — see _up_target).
        demand, desired, push, wants_up, up_target = self._up_target(
            spec, sample, alerting)
        self.desired_gauge.set(key[0], key[1], value=float(desired))
        self.ready_gauge.set(key[0], key[1], value=float(sample.ready))
        first_seen = self._first_seen.setdefault(key, now)
        hist = self._desired_history.setdefault(key, deque())
        hist.append((now, desired))
        horizon = now - policy.stabilization_window_s
        while hist and hist[0][0] < horizon:
            hist.popleft()
        stabilized = max(d for _, d in hist)
        # Down-gates only open after a full window of observation AND
        # cooldown measured from observation start, not the virtual
        # epoch — an operator's pre-provisioned headroom survives the
        # first low tick.
        observed_long_enough = (
            now - first_seen >= policy.stabilization_window_s)
        down_cooldown_ok = (
            now - max(group.status.last_scale_down, first_seen)
            >= policy.scale_down_cooldown_s)
        decision = ScaleDecision(key=key, desired=desired,
                                 applied=spec.replicas)

        cur = spec.replicas
        # Scale-up is demand-tracking (demand > current) — a slow ramp
        # never waits for the SLO to burn. An active burn alert
        # ADDITIONALLY forces at least one replica even when the demand
        # formula claims capacity is adequate (a too-tight target_duty,
        # a mis-sized policy): the alert path keeps stepping until the
        # incident clears, which is what "closed on the SLO" means.
        # The latency model's own floor: the replica count below which
        # predicted utilization crosses the ratio-1.0 point
        # (rho = 1 - base/bound in M/M/1), with a safety margin. Scale-
        # down never goes under it — that is what keeps the alert-built
        # capacity from being torn down into a fresh incident (the
        # overshoot/undershoot limit cycle a pure demand formula with a
        # too-tight target_duty produces).
        rho_safe = max(0.05, SLO_FLOOR_MARGIN * (
            1.0 - spec.traffic.base_latency_ms
            / max(1e-9, spec.slo.latency_p95_ms)))
        slo_floor = math.ceil(sample.qps / max(1e-9, cap * rho_safe))
        # An active alert (`push` in _up_target) forces at least one
        # extra replica ONLY while the current sample still violates:
        # the burn alert is a trailing indicator (its short window
        # drains over several ticks), and stepping on a recovered
        # sample would overshoot all the way to max_replicas before the
        # alert clears.
        if wants_up:
            target = up_target
            if max_up is not None:
                # Multi-group fairness: this tick's weighted share of
                # the fleet headroom caps the step; the rest defers.
                target = min(target, cur + max_up)
            if target <= cur:
                # Clamped by max_replicas (or the fairness share) while
                # still wanting up.
                self._defer(group, decision, now,
                            "scale-up clamped by max_replicas or the "
                            "fairness share while demand wants more",
                            {"qps": round(sample.qps, 3), "demand": demand,
                             "replicas": cur, "max_up": max_up})
            elif (now - group.status.last_scale_up
                    >= policy.scale_up_cooldown_s):
                self._apply_scale(group, target, now, up=True)
                decision.direction, decision.applied = "up", target
                if self.history is not None:
                    self.history.decide(
                        controller="autoscaler", rule=RULE_SCALE_UP,
                        outcome="scaled-up", obj=group,
                        message=f"replicas {cur} -> {target}",
                        inputs={"qps": round(sample.qps, 3),
                                "demand": demand, "desired": desired,
                                "alerting": alerting, "max_up": max_up},
                        now=now)
            else:
                self._defer(group, decision, now, "scale-up cooldown",
                            {"qps": round(sample.qps, 3), "demand": demand,
                             "target": target, "replicas": cur})
        elif stabilized < cur:
            target = max(policy.min_replicas, stabilized,
                         min(slo_floor, policy.max_replicas))
            if target >= cur:
                pass  # the SLO floor holds the alert-built capacity
            elif not alerting and observed_long_enough and down_cooldown_ok:
                self._apply_scale(group, target, now, up=False)
                decision.direction, decision.applied = "down", target
                if self.history is not None:
                    self.history.decide(
                        controller="autoscaler", rule=RULE_SCALE_DOWN,
                        outcome="scaled-down", obj=group,
                        message=f"replicas {cur} -> {target}",
                        inputs={"qps": round(sample.qps, 3),
                                "stabilized": stabilized,
                                "slo_floor": slo_floor,
                                "desired": desired},
                        now=now)
            else:
                self._defer(group, decision, now,
                            "scale-down gated by alert / observation "
                            "window / cooldown",
                            {"qps": round(sample.qps, 3),
                             "stabilized": stabilized, "target": target,
                             "replicas": cur, "alerting": alerting})
        elif desired < cur:
            # Wants down, but the stabilization window still remembers
            # higher demand — the anti-flap path a bursty trace exercises.
            self._defer(group, decision, now,
                        "stabilization window remembers higher demand",
                        {"qps": round(sample.qps, 3), "desired": desired,
                         "stabilized": stabilized, "replicas": cur})
        if decision.direction in ("none",) and self._maybe_down_tier(
                group, sample, now, alerting, claim_summaries):
            decision.direction = "tier-down"
        self._reconcile(key, now)
        return decision

    def _defer(self, group: ServingGroup, decision: ScaleDecision,
               now: float = 0.0, why: str = "",
               inputs: Optional[Dict[str, object]] = None) -> None:
        decision.direction = "deferred"
        self.scale_total.inc("deferred")
        self.recorder.normal(group, REASON_SCALE_DEFERRED, MSG_DEFERRED)
        if self.history is not None:
            self.history.decide(
                controller="autoscaler", rule=RULE_SCALE_DEFER,
                outcome="deferred", obj=group,
                message=why or MSG_DEFERRED,
                inputs=dict(inputs or {}), now=now)

    def _apply_scale(self, group: ServingGroup, target: int, now: float,
                     up: bool) -> None:
        with tracing.span("autoscaler.scale", group=group.key,
                          direction="up" if up else "down", target=target):
            def mutate(obj, target=target, now=now, up=up):
                obj.spec.replicas = target
                obj.status.desired_replicas = target
                if up:
                    obj.status.last_scale_up = now
                else:
                    obj.status.last_scale_down = now
            try:
                updated = self.api.update_with_retry(
                    SERVING_GROUP, group.meta.name, group.meta.namespace,
                    mutate)
            except NotFoundError:
                return
            # The engine cache must see the new spec before reconcile.
            self.engine.ingest_local(SERVING_GROUP, "MODIFIED", updated)
        self.scale_total.inc("up" if up else "down")
        self.recorder.normal(group, REASON_SCALE_UP if up
                             else REASON_SCALE_DOWN,
                             MSG_SCALE_UP if up else MSG_SCALE_DOWN)

    # -- vertical ------------------------------------------------------------

    def _maybe_down_tier(self, group: ServingGroup, sample: GroupSample,
                         now: float, alerting: bool,
                         claim_summaries: Dict[_Key, UtilizationSummary],
                         ) -> bool:
        spec = group.spec
        policy = spec.policy
        if alerting or not spec.tiers:
            return False
        try:
            idx = spec.tiers.index(spec.profile)
        except ValueError:
            return False
        if idx == 0:
            return False  # already the smallest tier
        if now - group.status.last_retier < policy.tier_cooldown_s:
            return False
        # Observed duty p95 across the group's replica claims (telemetry
        # rollup ground truth, not the model): every replica must be
        # measurably idle for a full window before shrinking its slice.
        duties = []
        for pod in self.engine.replicas(sample.key):
            claim = self.engine.claim_for(pod)
            if claim is None:
                continue
            s = claim_summaries.get((claim.meta.namespace, claim.meta.name))
            if s is not None:
                duties.append(s.duty_cycle_p95)
        if not duties or len(duties) < sample.ready:
            return False
        if max(duties) >= policy.down_tier_duty:
            return False
        new_tier = spec.tiers[idx - 1]
        with tracing.span("autoscaler.retier", group=group.key,
                          tier=new_tier):
            def mutate(obj, new_tier=new_tier, now=now):
                obj.spec.profile = new_tier
                obj.status.last_retier = now
            try:
                updated = self.api.update_with_retry(
                    SERVING_GROUP, group.meta.name, group.meta.namespace,
                    mutate)
            except NotFoundError:
                return False
            self.engine.ingest_local(SERVING_GROUP, "MODIFIED", updated)
        self.scale_total.inc("tier-down")
        self.recorder.normal(group, REASON_SCALE_DOWN, MSG_TIER_DOWN)
        if self.history is not None:
            self.history.decide(
                controller="autoscaler", rule=RULE_SCALE_TIER_DOWN,
                outcome="tier-down", obj=group,
                message=f"replica profile -> {new_tier}",
                inputs={"duty_p95_max": round(max(duties), 4),
                        "down_tier_duty": policy.down_tier_duty,
                        "new_tier": new_tier},
                now=now)
        return True

    # -- reconcile -----------------------------------------------------------

    def _reconcile(self, key: _Key, now: float) -> None:
        """Stamp replicas to (spec.replicas, spec.profile): create
        missing current-tier replicas, drain excess (emptiest hosts
        first), and roll old-tier replicas out once their replacements
        are Running."""
        group = self.engine.groups().get(key)
        if group is None:
            return
        spec = group.spec
        pods = self.engine.replicas(key)
        cur_tier = [p for p in pods
                    if p.meta.labels.get(SERVING_TIER_LABEL, "")
                    == spec.profile]
        cur_names = {p.meta.name for p in cur_tier}
        old_tier = [p for p in pods if p.meta.name not in cur_names]
        ready_cur = [p for p in cur_tier if self.engine.replica_ready(p)]
        missing = spec.replicas - len(cur_tier)
        if missing > 0:
            used = self._used_indices(pods)
            for _ in range(missing):
                idx = self._lowest_free(used)
                used.add(idx)
                self._create_replica(group, idx, spec.profile)
        elif missing < 0:
            for pod in self._victims(cur_tier, -missing):
                self._drain_replica(pod)
        if old_tier and len(ready_cur) >= spec.replicas:
            # Surge satisfied: replacements are serving, the old tier can
            # go. Rolling by whole tier is safe — the drains are cordon-
            # guarded, so a concurrent consolidation pass never touches
            # the same replica.
            drained_all = True
            for pod in old_tier:
                drained_all = self._drain_replica(pod) and drained_all
            if drained_all:
                old_tier = []
        elif old_tier and (now - group.status.last_retier
                           > spec.policy.stabilization_window_s):
            # Surge stalled: the new tier has waited a full stabilization
            # window without reaching spec.replicas — on a capacity-tight
            # cluster the old tier is HOLDING the chips the replacements
            # need. Yield capacity one old replica per pass (the smaller
            # profile always fits in the chips a bigger one frees), so
            # the roll degrades to a rolling replace instead of wedging
            # in surge forever.
            for pod in self._victims(old_tier, 1):
                self._drain_replica(pod)
        # Change-gated status sync: desired follows spec; the stamped
        # profile follows spec.profile once no old-tier replica remains.
        sync_profile = (not old_tier
                        and group.status.profile != spec.profile)
        if group.status.desired_replicas != spec.replicas or sync_profile:
            def sync(obj, replicas=spec.replicas, profile=spec.profile,
                     sync_profile=sync_profile):
                obj.status.desired_replicas = replicas
                if sync_profile:
                    obj.status.profile = profile
            try:
                updated = self.api.update_with_retry(
                    SERVING_GROUP, group.meta.name, group.meta.namespace,
                    sync)
                self.engine.ingest_local(SERVING_GROUP, "MODIFIED", updated)
            except NotFoundError:
                pass

    @staticmethod
    def _used_indices(pods: List[Pod]) -> Set[int]:
        out: Set[int] = set()
        for p in pods:
            try:
                out.add(int(p.meta.annotations.get(
                    SERVING_REPLICA_ANNOTATION, "-1")))
            except ValueError:
                continue
        out.discard(-1)
        return out

    @staticmethod
    def _lowest_free(used: Set[int]) -> int:
        idx = 0
        while idx in used:
            idx += 1
        return idx

    def _victims(self, pods: List[Pod], count: int) -> List[Pod]:
        """Emptiest replicas first: not-yet-ready before serving ones,
        then fewest serving claims on the replica's node (the chips the
        energy consolidator reclaims fastest), name tie-break."""
        fill = self.engine.serving_node_fill()

        def rank(pod: Pod):
            claim = self.engine.claim_for(pod)
            node = (claim.allocation.node_name
                    if claim is not None and claim.allocation is not None
                    else "")
            return (self.engine.replica_ready(pod),
                    fill.get(node, 0), pod.meta.name)

        return sorted(pods, key=rank)[:count]

    def _tier_requests(self, profile: str) -> List[DeviceRequest]:
        if not profile:
            return [DeviceRequest(name="tpus",
                                  device_class_name=DEVICE_CLASS_TPU,
                                  count=1)]
        return [DeviceRequest(
            name="tpus", device_class_name=DEVICE_CLASS_SUBSLICE, count=1,
            cel_selectors=[
                f'device.attributes["{TPU_ATTR_DOMAIN}"].profile '
                f'== "{profile}"'])]

    def _create_replica(self, group: ServingGroup, index: int,
                        tier: str) -> None:
        ns = group.meta.namespace
        labels = {SERVING_GROUP_LABEL: group.meta.name,
                  SERVING_TIER_LABEL: tier}
        pod_name = f"{group.meta.name}-rep-{index}"
        claim_name = f"{pod_name}-tpus"
        with tracing.span("autoscaler.replica.create", pod=pod_name,
                          tier=tier):
            try:
                self.api.create(ResourceClaim(
                    meta=new_meta(claim_name, ns, labels=dict(labels)),
                    requests=self._tier_requests(tier)))
            except AlreadyExistsError:
                pass  # crash-retry: the pod create below is idempotent too
            pod = Pod(
                meta=new_meta(pod_name, ns, labels=dict(labels)),
                containers=[Container(name="serving",
                                      image=group.spec.template.image,
                                      env=dict(group.spec.template.env))],
                resource_claims=[PodResourceClaimRef(
                    name="tpus", resource_claim_name=claim_name)],
            )
            pod.meta.annotations[SERVING_REPLICA_ANNOTATION] = str(index)
            pod.add_owner(group)
            try:
                created = self.api.create(pod)
            except AlreadyExistsError:
                # Crash-retry: the pod survived a half-completed prior
                # attempt — fall through so the claim still gets its
                # ownerRef (skipping here would strand an owner-less
                # claim past the pod's GC).
                created = self.api.try_get(POD, pod_name, ns)
                if created is None:
                    return
            # Pod owns the claim so ownerRef GC collects it with the pod
            # even when the drain path is skipped (group deletion).
            def own(obj, created=created):
                obj.add_owner(created)
            try:
                self.api.update_with_retry(RESOURCE_CLAIM, claim_name, ns, own)
            except NotFoundError:
                pass
            self.engine.ingest_local(POD, "ADDED", created)

    def _drain_replica(self, pod: Pod) -> bool:
        """Retire one replica: cordon its claim atomically (losing the
        race to a live-repack migration skips — retry next tick), then
        delete pod + claim; the unprepare happens through the normal
        claim GC, freeing the chips for the energy consolidator. Any
        failure after the cordon was acquired releases it on the way
        out — a half-drained replica must stay drainable on the next
        tick, not read as someone else's in-flight migration forever."""
        with tracing.span("autoscaler.replica.drain", pod=pod.key):
            claim = self.engine.claim_for(pod)
            if claim is not None and not try_cordon(self.api, claim,
                                                    owner="autoscaler"):
                return False  # mid-migration: the rebalancer owns it now
            try:
                try:
                    self.api.delete(POD, pod.meta.name, pod.meta.namespace)
                except NotFoundError:
                    pass
                self.engine.ingest_local(POD, "DELETED", pod)
                if claim is not None:
                    try:
                        self.api.delete(RESOURCE_CLAIM, claim.meta.name,
                                        claim.meta.namespace)
                    except NotFoundError:
                        # Already collected: nothing left to uncordon.
                        pass
                    self.engine.ingest_local(RESOURCE_CLAIM, "DELETED", claim)
                return True
            except Exception:  # noqa: BLE001 — transient API failure: undo the cordon, retry next tick
                log.exception("drain of %s failed mid-way", pod.key)
                if claim is not None:
                    release_cordon(self.api, claim)
                return False
