"""Sim traffic engine: QPS traces through a queueing model into telemetry.

The sensing half of the serving loop. Each ServingGroup declares a
traffic model (a ``tpulib.loadtrace`` spec — diurnal, bursty, or a
recorded ``playback`` trace — plus capacity constants); every virtual
tick the engine:

1. evaluates the group's QPS at trace-time ``now`` (generator kinds
   scale to ``peak_qps``; playback samples are raw QPS);
2. spreads it across the group's READY replicas and runs a simple
   M/M/1-style latency model: offered per-replica utilization
   ``rho = qps / (ready x capacity)``, latency ``base / (1 - rho)``
   (saturating when rho >= 1);
3. feeds per-replica duty into the mock tpulib's workload-registration
   path (``set_workload_load`` per claim uid), so PR 11's chip counters,
   claim rollups, and ``top`` output reflect serving load with a
   deterministic ground truth — the generator itself;
4. observes ``latency / declared bound`` into the SLO evaluator's
   ``serving-latency`` objective (bound 1.0: a ratio above 1 is a bad
   sample), whose burn-rate alerts the autoscaler closes on;
5. writes a quantized, change-gated ``status.traffic`` doc (steady load
   never churns resourceVersions — the telemetry plane's discipline).

Zero store ``list()`` calls per pass: groups, replica pods, and claims
ride watch-fed caches bootstrapped once at construction, exactly like
the telemetry aggregator (bench_autoscaler pins the invariant).
"""

from __future__ import annotations

import logging
import math
import queue as _queue
from dataclasses import dataclass, replace
from typing import Callable, Dict, List, Optional, Tuple

from k8s_dra_driver_tpu.api.servinggroup import (
    SERVING_GROUP,
    SERVING_GROUP_LABEL,
    ServingGroup,
    ServingTrafficStatus,
    replica_capacity_qps,
)
from k8s_dra_driver_tpu.k8s.core import POD, RESOURCE_CLAIM, Pod, ResourceClaim
from k8s_dra_driver_tpu.k8s.objects import ConflictError, NotFoundError
from k8s_dra_driver_tpu.tpulib.loadtrace import (
    LoadTrace,
    LoadTraceError,
    parse_load_trace,
)

log = logging.getLogger(__name__)

# The shared serving-latency objective: every group observes its
# normalized latency (observed / declared bound) against bound 1.0, so
# one SLO name covers groups with different absolute bounds and the burn
# gauge's label vocabulary stays fixed.
SERVING_LATENCY_SLO = "serving-latency"
SERVING_LATENCY_TARGET = 0.90
SERVING_LATENCY_BURN_THRESHOLD = 2.0
# Window pair in TICKS, scaled by the virtual tick length at lazy
# registration so a bench running 300 s ticks alerts after the same
# number of observations as the 1 s-tick e2e.
SERVING_LATENCY_WINDOW_TICKS = (30.0, 10.0)

# Saturated-queue latency clamp: with rho >= 1 the M/M/1 queue grows
# without bound; the model reports base x this factor (the "page is on
# fire" plateau) instead of a division by zero.
SATURATED_LATENCY_FACTOR = 1000.0

# status.traffic quantization steps (the change-gate grid).
QPS_QUANTUM = 0.1
LATENCY_MS_QUANTUM = 0.1
RATIO_QUANTUM = 0.01

_Key = Tuple[str, str]


def group_qps(trace: LoadTrace, peak_qps: float, t: float) -> float:
    """QPS at trace-time ``t``: playback samples are raw QPS, generator
    kinds are duty curves in [0, 1] scaled to ``peak_qps``."""
    if trace.kind == "playback":
        return max(0.0, trace.raw_value(t))
    return max(0.0, peak_qps * trace.value(t))


def offered_utilization(qps: float, ready: int, capacity_qps: float) -> float:
    """Per-replica offered utilization (rho). Infinite with no replica
    serving — the model's way of saying every request is failing."""
    if ready <= 0:
        return math.inf
    return qps / (ready * capacity_qps)


def model_latency_ms(base_ms: float, rho: float) -> float:
    """M/M/1 mean latency ``base / (1 - rho)``, saturating at
    ``base x SATURATED_LATENCY_FACTOR`` once the queue stops draining."""
    if rho >= 0.999:
        return base_ms * SATURATED_LATENCY_FACTOR
    return base_ms / (1.0 - rho)


@dataclass
class GroupSample:
    """One group's traffic verdict for one tick — what the autoscaler
    consumes next to the SLO alert snapshot."""

    key: _Key
    group: ServingGroup
    qps: float = 0.0
    ready: int = 0
    rho: float = 0.0            # offered per-replica utilization (may be inf)
    duty: float = 0.0           # rho clamped to [0, 1]: the chips' duty
    latency_ms: float = 0.0
    latency_ratio: float = 0.0  # latency / declared bound; > 1 violates


class TrafficEngine:
    """``claim_load_sink(node, claim_uid, duty)`` installs one replica's
    duty into that node's mock tpulib (None node entries are skipped) —
    the seam the sim wires to ``MockTpuLib.set_workload_load``."""

    def __init__(self, api, metrics_registry, slo_evaluator,
                 claim_load_sink: Callable[[str, str, float], None]):
        from k8s_dra_driver_tpu.k8s.informer import INFORMER_WATCH_QUEUE_MAXSIZE
        from k8s_dra_driver_tpu.pkg.metrics import Gauge

        self.api = api
        self.slo = slo_evaluator
        self.claim_load_sink = claim_load_sink
        r = metrics_registry
        self.qps_gauge = r.register(Gauge(
            "tpu_dra_autoscaler_group_qps",
            "Offered load (QPS) per ServingGroup, from the traffic model.",
            ("namespace", "name")))
        self.ratio_gauge = r.register(Gauge(
            "tpu_dra_autoscaler_group_latency_ratio",
            "Modeled serving latency over the declared p95 bound per "
            "ServingGroup (> 1.0 violates the SLO).",
            ("namespace", "name")))
        self.util_gauge = r.register(Gauge(
            "tpu_dra_autoscaler_group_utilization",
            "Offered per-replica utilization (rho, clamped to [0, 1]) per "
            "ServingGroup.",
            ("namespace", "name")))
        # Watch-fed caches, one bootstrap listing each at construction;
        # passes never list(). Replica pods are indexed by their group
        # key (the label is the cache admission test anyway), so the
        # per-tick lookups are O(replicas-of-group), not O(all pods).
        self._groups: Dict[_Key, ServingGroup] = {}
        self._pods_by_group: Dict[_Key, Dict[str, Pod]] = {}
        self._claims: Dict[_Key, ResourceClaim] = {}
        self._traces: Dict[str, LoadTrace] = {}       # spec string -> parsed
        self._written: Dict[_Key, ServingTrafficStatus] = {}  # change gates
        # Groups that have had at least one ready replica: the SLO only
        # starts judging a group once it has ever served — a cold-start
        # bring-up is not an incident, a later drop to zero replicas IS.
        self._served: set = set()
        self._slo_registered = False
        self._watches = {
            SERVING_GROUP: api.watch(SERVING_GROUP,
                                     maxsize=INFORMER_WATCH_QUEUE_MAXSIZE),
            POD: api.watch(POD, maxsize=INFORMER_WATCH_QUEUE_MAXSIZE),
            RESOURCE_CLAIM: api.watch(RESOURCE_CLAIM,
                                      maxsize=INFORMER_WATCH_QUEUE_MAXSIZE),
        }
        for sg in api.list(SERVING_GROUP):
            self._ingest(SERVING_GROUP, "ADDED", sg)
        for pod in api.list(POD):
            self._ingest(POD, "ADDED", pod)
        for claim in api.list(RESOURCE_CLAIM):
            self._ingest(RESOURCE_CLAIM, "ADDED", claim)

    def close(self) -> None:
        for kind, q in self._watches.items():
            self.api.stop_watch(kind, q)

    # -- caches --------------------------------------------------------------

    def _ingest(self, kind: str, ev_type: str, obj) -> None:
        key = (obj.meta.namespace, obj.meta.name)
        if kind == SERVING_GROUP:
            if ev_type == "DELETED":
                self._groups.pop(key, None)
                self._written.pop(key, None)
                self._served.discard(key)
                for g in (self.qps_gauge, self.ratio_gauge, self.util_gauge):
                    g.forget_matching(namespace=key[0], name=key[1])
                return
            self._groups[key] = obj
            return
        # Pods/claims: only the serving fleet (group-labeled) is cached,
        # so a big batch cluster doesn't grow the serving caches.
        gname = obj.meta.labels.get(SERVING_GROUP_LABEL)
        if not gname:
            return
        if kind == POD:
            gkey = (obj.meta.namespace, gname)
            bucket = self._pods_by_group.setdefault(gkey, {})
            if ev_type == "DELETED":
                bucket.pop(obj.meta.name, None)
                if not bucket:
                    self._pods_by_group.pop(gkey, None)
            else:
                bucket[obj.meta.name] = obj
            return
        if ev_type == "DELETED":
            self._claims.pop(key, None)
        else:
            self._claims[key] = obj

    def ingest_local(self, kind: str, ev_type: str, obj) -> None:
        """Apply a write this process just made to the caches without
        waiting for the watch echo — the controller's read-your-writes
        path (the echo arrives later and is idempotent)."""
        self._ingest(kind, ev_type, obj)

    def drain(self) -> None:
        for kind, q in self._watches.items():
            while True:
                try:
                    ev = q.get_nowait()
                except _queue.Empty:
                    break
                self._ingest(kind, ev.type, ev.obj)

    # -- read-side views (the controller shares these caches) ----------------

    def groups(self) -> Dict[_Key, ServingGroup]:
        return dict(self._groups)

    def replicas(self, key: _Key) -> List[Pod]:
        """Live replica pods of one group, name-sorted."""
        return sorted(self._pods_by_group.get(key, {}).values(),
                      key=lambda p: p.meta.name)

    def orphan_replicas(self) -> List[Pod]:
        """Replica pods whose ServingGroup no longer exists — the
        controller drains these (there is no ownerRef GC for groups)."""
        return [
            p for gkey, bucket in self._pods_by_group.items()
            if gkey not in self._groups
            for p in bucket.values()
        ]

    def claim_for(self, pod: Pod) -> Optional[ResourceClaim]:
        for ref in pod.resource_claims:
            if ref.resource_claim_name:
                c = self._claims.get((pod.meta.namespace,
                                      ref.resource_claim_name))
                if c is not None:
                    return c
        return None

    def serving_node_fill(self) -> Dict[str, int]:
        """Allocated serving claims per node — the scale-down victim
        ranking's emptiest-host signal (cache-fed, no store scan)."""
        fill: Dict[str, int] = {}
        for c in self._claims.values():
            if c.allocation is not None and c.allocation.node_name:
                fill[c.allocation.node_name] = (
                    fill.get(c.allocation.node_name, 0) + 1)
        return fill

    @staticmethod
    def replica_ready(pod: Pod) -> bool:
        return pod.phase == "Running" and pod.ready and not pod.deleting

    # -- the pass ------------------------------------------------------------

    def _trace_for(self, spec: str) -> Optional[LoadTrace]:
        if not spec:
            return None
        trace = self._traces.get(spec)
        if trace is None:
            try:
                trace = parse_load_trace(spec)
            except LoadTraceError as e:
                log.warning("serving trace %r rejected: %s", spec, e)
                # Negative-cache as a flat zero so one bad spec does not
                # re-parse (and re-log) every tick.
                trace = LoadTrace(kind="constant", level=0.0, spec=spec)
            self._traces[spec] = trace
        return trace

    def _ensure_slo(self, dt: float) -> None:
        if self._slo_registered or self.slo is None:
            return
        if not self.slo.has(SERVING_LATENCY_SLO):
            from k8s_dra_driver_tpu.pkg.slo import SLObjective

            long_w, short_w = SERVING_LATENCY_WINDOW_TICKS
            self.slo.add(SLObjective(
                name=SERVING_LATENCY_SLO,
                description="modeled serving latency within the declared "
                            "per-group p95 bound (normalized ratio)",
                target=SERVING_LATENCY_TARGET, bound=1.0, op="gt",
                windows=((long_w * dt, short_w * dt),),
                burn_threshold=SERVING_LATENCY_BURN_THRESHOLD))
        self._slo_registered = True

    def step(self, now: float, dt: float = 1.0) -> Dict[_Key, GroupSample]:
        """One traffic tick over every ServingGroup."""
        self.drain()
        self._ensure_slo(dt)
        samples: Dict[_Key, GroupSample] = {}
        for key, group in self._groups.items():
            samples[key] = self._step_group(key, group, now)
        return samples

    def _step_group(self, key: _Key, group: ServingGroup,
                    now: float) -> GroupSample:
        spec = group.spec
        trace = self._trace_for(spec.traffic.trace)
        qps = group_qps(trace, spec.traffic.peak_qps, now) if trace else 0.0
        pods = self.replicas(key)
        ready = [p for p in pods if self.replica_ready(p)]
        cap = replica_capacity_qps(spec)
        rho = offered_utilization(qps, len(ready), cap)
        duty = min(1.0, max(0.0, 0.0 if math.isinf(rho) else rho))
        latency = model_latency_ms(spec.traffic.base_latency_ms,
                                   min(rho, 1.0))
        ratio = latency / max(1e-9, spec.slo.latency_p95_ms)
        sample = GroupSample(key=key, group=group, qps=qps, ready=len(ready),
                             rho=rho, duty=duty, latency_ms=latency,
                             latency_ratio=ratio)
        # Per-replica duty into the workload-registration path: counters
        # on the replica's chips now follow the serving model. A replica
        # that is NOT ready serves nothing — its duty is written as 0 so
        # a ready→unready transition (node drain, failed probe) cannot
        # leave the last serving duty stuck on still-prepared chips while
        # the same QPS is redistributed to the survivors (double-count).
        for pod in pods:
            claim = self.claim_for(pod)
            if (claim is None or not claim.uid
                    or claim.allocation is None
                    or not claim.allocation.node_name):
                continue
            self.claim_load_sink(
                claim.allocation.node_name, claim.uid,
                duty if self.replica_ready(pod) else 0.0)
        if sample.ready > 0:
            self._served.add(key)
        if (self.slo is not None and trace is not None
                and (sample.ready > 0 or key in self._served)):
            self.slo.observe(SERVING_LATENCY_SLO, now, ratio, subject=key,
                             ref=group)
        self.qps_gauge.set(key[0], key[1], value=qps)
        self.ratio_gauge.set(key[0], key[1], value=ratio)
        self.util_gauge.set(key[0], key[1], value=duty)
        self._write_status(key, sample, now)
        return sample

    # -- status --------------------------------------------------------------

    def _write_status(self, key: _Key, s: GroupSample, now: float) -> None:
        def q(v: float, step: float) -> float:
            if math.isinf(v):
                v = 10.0 / RATIO_QUANTUM  # render saturation finitely
            return round(round(v / step) * step, 6)

        doc = ServingTrafficStatus(
            qps=q(s.qps, QPS_QUANTUM),
            latency_ms=q(s.latency_ms, LATENCY_MS_QUANTUM),
            latency_ratio=q(s.latency_ratio, RATIO_QUANTUM),
            utilization=q(s.duty, RATIO_QUANTUM),
            ready_replicas=s.ready,
            updated_at=now,
        )
        prev = self._written.get(key)
        self._written[key] = doc
        if prev == doc:
            return

        def mutate(obj, doc=doc):
            obj.status.traffic = doc
            obj.status.ready_replicas = doc.ready_replicas
        try:
            self.api.update_with_retry(SERVING_GROUP, key[1], key[0], mutate)
        except (NotFoundError, ConflictError):
            self._written.pop(key, None)
