"""SLO-driven serving autoscaler (docs/reference/autoscaling.md).

- :mod:`k8s_dra_driver_tpu.autoscaler.traffic` — the sim traffic engine:
  per-ServingGroup QPS traces through a queueing model into the
  telemetry plane (sensing).
- :mod:`k8s_dra_driver_tpu.autoscaler.controller` — the ServingGroup
  controller: replica stamping, scale-down GC, horizontal + vertical
  scaling closed on SLO burn-rate alerts and utilization rollups
  (actuation).
"""

from k8s_dra_driver_tpu.autoscaler.controller import (
    ScaleDecision,
    ServingGroupController,
)
from k8s_dra_driver_tpu.autoscaler.traffic import (
    GroupSample,
    SERVING_LATENCY_SLO,
    TrafficEngine,
    group_qps,
    model_latency_ms,
    offered_utilization,
)

__all__ = [
    "GroupSample",
    "SERVING_LATENCY_SLO",
    "ScaleDecision",
    "ServingGroupController",
    "TrafficEngine",
    "group_qps",
    "model_latency_ms",
    "offered_utilization",
]
