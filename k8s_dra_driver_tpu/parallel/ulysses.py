"""Ulysses-style sequence parallelism: all-to-all head/sequence exchange.

The second of the two long-context strategies (ring attention is the
other, ``parallel/ring_attention.py``): instead of rotating KV blocks
around a ring, one ``all_to_all`` redistributes the sequence-sharded
[B, T/n, H, D] tensors into head-sharded [B, T, H/n, D], each device runs
*full* attention for its head subset, and a second ``all_to_all`` restores
sequence sharding.

Trade-off vs ring attention (both ride ICI):
- Ulysses moves q, k, v, o once each (4 tensor volumes) in two dense
  all-to-alls, and each device sees the whole sequence — attention itself
  is unchanged, so any kernel (flash, blocked) drops in per head.
- Ring moves k, v around the whole ring (2·(n-1)/n volumes) in n
  neighbor hops overlapped with compute, and never materializes the full
  sequence — the O(T/n) memory choice for extreme context lengths.
- Ulysses parallelism is capped by head count (n must divide H); ring is
  capped only by sequence length.

No counterpart exists in the reference (resource layer); this is
workload-side capability for multi-host ComputeDomains. Pattern follows
the public DeepSpeed-Ulysses formulation; implementation is original.
"""

from __future__ import annotations

from functools import partial

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh, PartitionSpec as P


def _ulysses_shard(q, k, v, *, axis_name: str, causal: bool):
    """Per-shard body under shard_map. q,k,v local: [B, T/n, H, D]."""

    def seq_to_heads(x):
        # [B, T/n, H, D] -> [B, T, H/n, D]: split heads over the axis,
        # concatenate the sequence shards.
        return jax.lax.all_to_all(x, axis_name, split_axis=2, concat_axis=1,
                                  tiled=True)

    def heads_to_seq(x):
        return jax.lax.all_to_all(x, axis_name, split_axis=1, concat_axis=2,
                                  tiled=True)

    qg, kg, vg = seq_to_heads(q), seq_to_heads(k), seq_to_heads(v)
    scale = 1.0 / np.sqrt(qg.shape[-1])
    scores = jnp.einsum("bqhd,bkhd->bhqk", qg, kg).astype(jnp.float32) * scale
    if causal:
        t = qg.shape[1]
        mask = jnp.arange(t)[:, None] >= jnp.arange(t)[None, :]
        scores = jnp.where(mask[None, None], scores, -jnp.inf)
    probs = jax.nn.softmax(scores, axis=-1)
    out = jnp.einsum("bhqk,bkhd->bqhd", probs.astype(vg.dtype), vg)
    return heads_to_seq(out)


def ulysses_attention(
    q: jax.Array,
    k: jax.Array,
    v: jax.Array,
    mesh: Mesh,
    *,
    seq_axis: str = "sp",
    batch_axis=None,
    causal: bool = True,
) -> jax.Array:
    """Causal self-attention with q/k/v sequence-sharded over ``seq_axis``,
    computed via head-parallel all-to-all exchange.

    q, k, v: [B, T, H, D] global; T and H divisible by the axis size.
    ``batch_axis`` additionally shards B over a second mesh axis (the
    dp×sp composition): the all-to-alls only ever run within each batch
    group's sp sub-axis. Returns [B, T, H, D] with the same sharding.
    Same signature as ``ring_attention`` so workloads can switch
    strategies per length.
    """
    from k8s_dra_driver_tpu.parallel.mesh import get_shard_map

    shard_map = get_shard_map()

    n = mesh.shape[seq_axis]
    if q.shape[2] % n:
        raise ValueError(
            f"ulysses needs heads ({q.shape[2]}) divisible by the "
            f"'{seq_axis}' axis size ({n}); use ring_attention otherwise"
        )
    spec = P(batch_axis, seq_axis, None, None)
    body = partial(_ulysses_shard, axis_name=seq_axis, causal=causal)
    fn = shard_map(
        body, mesh=mesh,
        in_specs=(spec, spec, spec),
        out_specs=spec,
    )
    return fn(q, k, v)
