"""Mesh/sharding helpers for workloads running on claimed TPU slices."""

from k8s_dra_driver_tpu.parallel.mesh import (  # noqa: F401
    build_mesh,
    family_mesh,
    load_bundle,
    match_partition_rules,
    mesh_from_bundle,
    mesh_from_topology,
    synthetic_bundle,
)
