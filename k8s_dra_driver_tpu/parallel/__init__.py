"""Mesh/sharding helpers for workloads running on claimed TPU slices."""

from k8s_dra_driver_tpu.parallel.mesh import build_mesh, mesh_from_topology  # noqa: F401
