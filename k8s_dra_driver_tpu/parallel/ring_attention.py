"""Ring attention: causal self-attention with sequence parallelism over ICI.

Long-context support: the sequence is sharded over a mesh axis; KV blocks
rotate around the ring with ``lax.ppermute`` while each device accumulates
its queries' attention with a numerically-stable online softmax (flash-style
running max / denominator). Peak memory per device is O(T/n) and the KV
transfers ride neighbor-to-neighbor ICI links — the communication pattern
the ring topology gives for free.

No counterpart exists in the reference (it is the resource layer below);
this is the workload-side capability that makes multi-host ComputeDomains
useful for long sequences. Pattern follows the public ring-attention
formulation (blockwise parallel transformers); implementation is original.
"""

from __future__ import annotations

from functools import partial
from typing import Optional

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P


def _block_attend(q, k, v, qi, ki, block_len, causal):
    """Attention of local queries against one rotating KV block, returning
    unnormalized (o, m, l) contributions. q:[B,Tq,H,D] k,v:[B,Tk,H,D]."""
    scale = 1.0 / np.sqrt(q.shape[-1])
    scores = jnp.einsum("bqhd,bkhd->bhqk", q, k).astype(jnp.float32) * scale
    if causal:
        # Global positions: query block qi, kv block ki.
        tq, tk = q.shape[1], k.shape[1]
        qpos = qi * block_len + jnp.arange(tq)
        kpos = ki * block_len + jnp.arange(tk)
        mask = qpos[:, None] >= kpos[None, :]
        scores = jnp.where(mask[None, None], scores, -jnp.inf)
    m = jnp.max(scores, axis=-1)                      # [B,H,Tq]
    # A fully-masked row yields -inf max; zero its contribution.
    m_safe = jnp.where(jnp.isfinite(m), m, 0.0)
    p = jnp.exp(scores - m_safe[..., None])
    p = jnp.where(jnp.isfinite(scores), p, 0.0)
    l = jnp.sum(p, axis=-1)                           # [B,H,Tq]
    o = jnp.einsum("bhqk,bkhd->bqhd", p.astype(v.dtype), v)
    return o, jnp.where(jnp.isfinite(m), m, -jnp.inf), l


def _ring_attention_shard(q, k, v, *, axis_name: str, causal: bool,
                          vary_axes: tuple):
    """Per-shard body under shard_map. q,k,v: [B, T_local, H, D].
    ``vary_axes``: every mesh axis the inputs vary over (the ring axis
    plus any batch axis) — the constant initial carry must match."""
    n = jax.lax.psum(1, axis_name)
    my = jax.lax.axis_index(axis_name)
    block_len = q.shape[1]
    perm = [(i, (i + 1) % n) for i in range(n)]

    def step(s, carry):
        o, m, l, k_blk, v_blk = carry
        ki = (my - s) % n
        o_c, m_c, l_c = _block_attend(q, k_blk, v_blk, my, ki, block_len, causal)
        m_new = jnp.maximum(m, m_c)
        m_new_safe = jnp.where(jnp.isfinite(m_new), m_new, 0.0)
        alpha = jnp.where(jnp.isfinite(m), jnp.exp(m - m_new_safe), 0.0)
        beta = jnp.where(jnp.isfinite(m_c), jnp.exp(m_c - m_new_safe), 0.0)
        l_new = l * alpha + l_c * beta
        o_new = (
            o * alpha.transpose(0, 2, 1)[..., None].astype(o.dtype)
            + o_c * beta.transpose(0, 2, 1)[..., None].astype(o.dtype)
        )
        k_next = jax.lax.ppermute(k_blk, axis_name, perm)
        v_next = jax.lax.ppermute(v_blk, axis_name, perm)
        return o_new, m_new, l_new, k_next, v_next

    b, t, h, d = q.shape
    # revary: the constant initial carry must be typed as device-varying over
    # every sharded axis or the fori_loop carry types mismatch under shard_map.
    from k8s_dra_driver_tpu.parallel.mesh import revary

    o0 = revary(jnp.zeros((b, t, h, d), jnp.float32), vary_axes)
    m0 = revary(jnp.full((b, h, t), -jnp.inf, jnp.float32), vary_axes)
    l0 = revary(jnp.zeros((b, h, t), jnp.float32), vary_axes)
    o, m, l, _, _ = jax.lax.fori_loop(0, n, step, (o0, m0, l0, k, v))
    l = jnp.maximum(l, 1e-20)
    return (o / l.transpose(0, 2, 1)[..., None]).astype(q.dtype)


def ring_attention(
    q: jax.Array,
    k: jax.Array,
    v: jax.Array,
    mesh: Mesh,
    *,
    seq_axis: str = "sp",
    batch_axis: Optional[str] = None,
    causal: bool = True,
) -> jax.Array:
    """Causal self-attention with q/k/v sequence-sharded over ``seq_axis``.

    q, k, v: [B, T, H, D] global shapes, T divisible by the axis size.
    ``batch_axis`` additionally shards B over a second mesh axis (dp×sp
    composition) — a pure SPMD split: the ring's collectives only ever run
    within each batch group's sp sub-axis.
    Returns [B, T, H, D] with the same sharding.
    """
    from k8s_dra_driver_tpu.parallel.mesh import get_shard_map

    shard_map = get_shard_map()

    spec = P(batch_axis, seq_axis, None, None)
    vary_axes = (seq_axis,) + ((batch_axis,) if batch_axis else ())
    body = partial(_ring_attention_shard, axis_name=seq_axis, causal=causal,
                   vary_axes=vary_axes)
    fn = shard_map(
        body, mesh=mesh,
        in_specs=(spec, spec, spec),
        out_specs=spec,
    )
    return fn(q, k, v)


def reference_attention(q, k, v, causal: bool = True) -> jax.Array:
    """Plain full attention, for testing equivalence."""
    scale = 1.0 / np.sqrt(q.shape[-1])
    scores = jnp.einsum("bqhd,bkhd->bhqk", q, k).astype(jnp.float32) * scale
    if causal:
        t = q.shape[1]
        mask = jnp.tril(jnp.ones((t, t), bool))
        scores = jnp.where(mask[None, None], scores, -jnp.inf)
    probs = jax.nn.softmax(scores, axis=-1)
    return jnp.einsum("bhqk,bkhd->bqhd", probs.astype(v.dtype), v)
