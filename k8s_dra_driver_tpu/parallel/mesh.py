"""Device-mesh construction for claimed slices.

Maps a slice topology (as the ComputeDomain stack hands it to the workload
via CDI-injected env: TPU_TOPOLOGY, TPU_WORKER_ID, ...) onto a
``jax.sharding.Mesh`` whose axis order keeps collectives on ICI: the
innermost (fastest-varying) mesh axes correspond to physically adjacent
chips, so ``psum`` over the model axis rides intra-host ICI links and the
data axis spans hosts.
"""

from __future__ import annotations

from typing import Optional, Sequence, Tuple

import numpy as np


def get_shard_map():
    """The shard_map entry point across jax versions: the public
    ``jax.shard_map`` (0.8+) with the experimental path as fallback —
    same compat posture as revary below."""
    import jax

    if hasattr(jax, "shard_map"):
        return jax.shard_map
    from jax.experimental.shard_map import shard_map  # pragma: no cover

    return shard_map


def revary(x, axis_name):
    """Mark a device-invariant value as varying over ``axis_name`` (no data
    movement) — needed for loop carries whose body applies an invariant
    collective like psum. jax >= 0.9 renamed pvary to pcast(to='varying');
    support both so a jax upgrade doesn't break the shard bodies."""
    import jax

    names = axis_name if isinstance(axis_name, tuple) else (axis_name,)
    if hasattr(jax.lax, "pcast"):
        # One axis per call: tolerant of a pcast API that takes a single
        # axis name (the dp×sp path passes ('sp', 'data')).
        for name in names:
            x = jax.lax.pcast(x, name, to="varying")
        return x
    if hasattr(jax.lax, "pvary"):
        return jax.lax.pvary(x, names)
    # jax < 0.5 has no varying-annotation machinery at all (replication is
    # inferred); identity is the correct degenerate form.
    return x


def build_mesh(devices: Sequence, dp: int, tp: int, *, axis_names: Tuple[str, str] = ("data", "model")):
    """Build a dp×tp Mesh over ``devices`` (len must equal dp*tp).

    ``model`` is the innermost axis: on real slices consecutive device ids
    are ICI neighbors, so tensor-parallel collectives stay on the fastest
    links while data-parallel gradient sync crosses hosts.
    """
    from jax.sharding import Mesh

    if dp * tp != len(devices):
        raise ValueError(f"dp*tp={dp * tp} != len(devices)={len(devices)}")
    arr = np.asarray(devices, dtype=object).reshape(dp, tp)
    return Mesh(arr, axis_names=axis_names)


def choose_dp_tp(n_devices: int, max_tp: int = 8) -> Tuple[int, int]:
    """Pick a dp×tp factorization: largest power-of-two tp ≤ max_tp dividing n."""
    tp = 1
    while tp * 2 <= max_tp and n_devices % (tp * 2) == 0:
        tp *= 2
    return n_devices // tp, tp


def mesh_from_topology(topology: str, devices: Optional[Sequence] = None):
    """Build a mesh shaped like a physical topology string, e.g. "4x4".

    Axis names are ("x", "y") [or ("x","y","z") for 3D tori like v4/v5p].
    Used by workloads that want physically-faithful meshes rather than the
    logical dp×tp view.
    """
    from jax.sharding import Mesh

    dims = tuple(int(d) for d in topology.lower().split("x"))
    n = int(np.prod(dims))
    if devices is None:
        import jax

        devices = jax.devices()
    if len(devices) < n:
        raise ValueError(f"topology {topology} needs {n} devices, have {len(devices)}")
    names = ("x", "y", "z")[: len(dims)]
    arr = np.asarray(devices[:n], dtype=object).reshape(dims)
    return Mesh(arr, axis_names=names)
