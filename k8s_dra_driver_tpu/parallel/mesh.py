"""Device-mesh construction for claimed slices.

Maps a slice topology (as the ComputeDomain stack hands it to the workload
via CDI-injected env: TPU_TOPOLOGY, TPU_WORKER_ID, ...) onto a
``jax.sharding.Mesh`` whose axis order keeps collectives on ICI: the
innermost (fastest-varying) mesh axes correspond to physically adjacent
chips, so ``psum`` over the model axis rides intra-host ICI links and the
data axis spans hosts.

Since the Placement→JAX mesh compiler (pkg/meshgen) this module is also
the client half of the mesh-bundle contract: when the CDI handler injects
``TPU_DRA_MESH_BUNDLE``, every mesh built here — the bundle-shaped
``mesh_from_bundle`` and the family-shaped ``family_mesh`` the workload
tier (models/*) uses — permutes devices into the bundle's topology-
aligned order first, so mesh-axis neighbors are ICI ring neighbors and
the order routes around tainted links. Without a bundle everything falls
back to plain enumeration order, unchanged from before the compiler
existed.
"""

from __future__ import annotations

import os
import re
from typing import Optional, Sequence, Tuple

import numpy as np

from k8s_dra_driver_tpu.pkg.meshgen import (
    MESH_BUNDLE_ENV,
    MeshBundle,
    compile_bundle,
)


def get_shard_map():
    """The shard_map entry point across jax versions: the public
    ``jax.shard_map`` (0.8+) with the experimental path as fallback —
    same compat posture as revary below."""
    import jax

    if hasattr(jax, "shard_map"):
        return jax.shard_map
    from jax.experimental.shard_map import shard_map  # pragma: no cover

    return shard_map


def revary(x, axis_name):
    """Mark a device-invariant value as varying over ``axis_name`` (no data
    movement) — needed for loop carries whose body applies an invariant
    collective like psum. jax >= 0.9 renamed pvary to pcast(to='varying');
    support both so a jax upgrade doesn't break the shard bodies."""
    import jax

    names = axis_name if isinstance(axis_name, tuple) else (axis_name,)
    if hasattr(jax.lax, "pcast"):
        # One axis per call: tolerant of a pcast API that takes a single
        # axis name (the dp×sp path passes ('sp', 'data')).
        for name in names:
            x = jax.lax.pcast(x, name, to="varying")
        return x
    if hasattr(jax.lax, "pvary"):
        return jax.lax.pvary(x, names)
    # jax < 0.5 has no varying-annotation machinery at all (replication is
    # inferred); identity is the correct degenerate form.
    return x


# -- mesh-bundle consumption (pkg/meshgen client half) ------------------------


def load_bundle(env: Optional[dict] = None) -> Optional[MeshBundle]:
    """The ambient mesh bundle, if the CDI handler injected one. Malformed
    env degrades to None (enumeration-order fallback), never an exception:
    a stale bundle must not stop a workload from booting."""
    raw = (env if env is not None else os.environ).get(MESH_BUNDLE_ENV, "")
    if not raw:
        return None
    try:
        return MeshBundle.from_json(raw)
    except Exception:  # noqa: BLE001 — any malformed shape degrades
        return None


def synthetic_bundle(n_devices: int, host_topology: str = "2x2",
                     broken_links=()) -> MeshBundle:
    """A mesh bundle for tests/benches without a control plane: n_devices
    chips as a row of ``host-<i>`` hosts of ``host_topology`` chips —
    the same compiler (pkg/meshgen) the controller runs, so bundle-aware
    paths exercise real generated orders."""
    from k8s_dra_driver_tpu.tpulib.types import topology_chips

    cph = topology_chips(host_topology)
    if n_devices % cph:
        raise ValueError(
            f"n_devices ({n_devices}) must divide by chips/host ({cph})")
    hosts = n_devices // cph
    return compile_bundle(f"1x{hosts}", host_topology,
                          [f"host-{i}" for i in range(hosts)],
                          broken_links=broken_links)


def bundle_device_order(devices: Sequence, bundle: Optional[MeshBundle]) -> list:
    """Permute enumeration-ordered ``devices`` into the bundle's topology-
    aligned flat order. A missing or size-mismatched bundle (different
    claim shape, partial device visibility) keeps enumeration order — the
    fallback contract."""
    devices = list(devices)
    if bundle is None or bundle.num_devices != len(devices):
        return devices
    idx = bundle.flat_indices()
    if sorted(idx) != list(range(len(devices))):
        return devices  # corrupt permutation: fall back, don't crash
    return [devices[i] for i in idx]


# Default for family_mesh's bundle param: "consult the ambient env".
# Distinct from None, which callers pass to mean "NO bundle, enumeration
# order" (e.g. the distrusted-bundle fallback must not reload the same
# env bundle it just rejected).
_AMBIENT = object()


def family_mesh(devices: Sequence, shape: Sequence[int],
                axis_names: Sequence[str],
                bundle=_AMBIENT):
    """THE mesh constructor for the workload families (flagship dp×tp,
    long-context dp×sp, pipelined dp×pp, MoE dp×ep): bundle-ordered
    devices reshaped to ``shape`` with ``axis_names``. Consecutive devices
    in bundle order are ICI ring neighbors, so whatever the family names
    its innermost axis, its collectives ride the fastest links; without a
    bundle this is exactly the old hand-built reshape."""
    from jax.sharding import Mesh

    n = 1
    for s in shape:
        n *= s
    if n != len(devices):
        raise ValueError(f"shape {tuple(shape)} needs {n} devices, "
                         f"have {len(devices)}")
    ordered = bundle_device_order(
        devices, load_bundle() if bundle is _AMBIENT else bundle)
    arr = np.asarray(ordered, dtype=object).reshape(tuple(shape))
    return Mesh(arr, axis_names=tuple(axis_names))


def mesh_from_bundle(devices: Optional[Sequence] = None,
                     bundle: Optional[MeshBundle] = None):
    """Build the bundle's own Mesh: axes named and sized to the REAL slice
    shape of the claimed block (e.g. ('data','model') 4×4 on a v5e-16
    domain), devices in generated order. Falls back to the enumeration-
    order dp×tp factorization when no bundle is present — a pod scheduled
    without the compiler keeps booting."""
    import jax

    if devices is None:
        devices = jax.devices()
    bundle = bundle if bundle is not None else load_bundle()
    axis_prod = 1
    for s in (bundle.axis_sizes if bundle is not None else ()):
        axis_prod *= s
    # An internally inconsistent bundle (axis-size product disagreeing
    # with its own device order — version skew, hand edits) falls back
    # like an absent one: the bundle must never stop a workload booting.
    if (bundle is None or bundle.num_devices != len(devices)
            or axis_prod != len(devices)):
        # bundle=None, NOT ambient: the rejected bundle is still in the
        # env, and the fallback must not apply its device order either.
        dp, tp = choose_dp_tp(len(devices))
        return family_mesh(devices, (dp, tp), ("data", "model"), bundle=None)
    return family_mesh(devices, bundle.axis_sizes, bundle.axis_names,
                       bundle=bundle)


def match_partition_rules(rules, params):
    """PartitionSpec pytree from (regex, spec) rules over '/'-joined
    parameter paths — the SNIPPETS ``match_partition_rules`` idiom over
    ``jax.tree_util`` paths. Scalars replicate; the first matching rule
    wins; an unmatched leaf raises (bundles ship a catch-all)."""
    import jax
    from jax.sharding import PartitionSpec as P

    def path_str(path) -> str:
        parts = []
        for k in path:
            if hasattr(k, "key"):
                parts.append(str(k.key))
            elif hasattr(k, "idx"):
                parts.append(str(k.idx))
            else:
                parts.append(str(k))
        return "/".join(parts)

    def spec_for(path, leaf):
        shape = getattr(leaf, "shape", ())
        if len(shape) == 0 or int(np.prod(shape)) == 1:
            return P()
        name = path_str(path)
        for rule, spec in rules:
            if re.search(rule, name) is not None:
                return P(*spec)
        raise ValueError(f"partition rule not found for param {name!r}")

    return jax.tree_util.tree_map_with_path(spec_for, params)


def build_mesh(devices: Sequence, dp: int, tp: int, *, axis_names: Tuple[str, str] = ("data", "model")):
    """Build a dp×tp Mesh over ``devices`` (len must equal dp*tp).

    ``model`` is the innermost axis: in bundle order (or enumeration order
    on real slices) consecutive devices are ICI neighbors, so tensor-
    parallel collectives stay on the fastest links while data-parallel
    gradient sync crosses hosts.
    """
    if dp * tp != len(devices):
        raise ValueError(f"dp*tp={dp * tp} != len(devices)={len(devices)}")
    return family_mesh(devices, (dp, tp), axis_names)


def choose_dp_tp(n_devices: int, max_tp: int = 8) -> Tuple[int, int]:
    """Pick a dp×tp factorization: largest power-of-two tp ≤ max_tp dividing n."""
    tp = 1
    while tp * 2 <= max_tp and n_devices % (tp * 2) == 0:
        tp *= 2
    return n_devices // tp, tp


def mesh_from_topology(topology: str, devices: Optional[Sequence] = None):
    """Build a mesh shaped like a physical topology string, e.g. "4x4".

    Axis names are ("x", "y") [or ("x","y","z") for 3D tori like v4/v5p].
    Used by workloads that want physically-faithful meshes rather than the
    logical dp×tp view.
    """
    dims = tuple(int(d) for d in topology.lower().split("x"))
    n = int(np.prod(dims))
    if devices is None:
        import jax

        devices = jax.devices()
    if len(devices) < n:
        raise ValueError(f"topology {topology} needs {n} devices, have {len(devices)}")
    names = ("x", "y", "z")[: len(dims)]
    # bundle=None: this function's contract is PHYSICAL x/y/z coordinates
    # in enumeration order; a re-routed (degraded-link) bundle order would
    # silently unmoor mesh positions from physical coords. Bundle-aware
    # callers want mesh_from_bundle.
    return family_mesh(list(devices)[:n], dims, names, bundle=None)
