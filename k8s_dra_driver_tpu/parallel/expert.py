"""Expert parallelism: switch-routed MoE FFN with all-to-all dispatch.

One expert per device along the ``ep`` mesh axis (the canonical TPU MoE
layout): tokens are data-sharded over the same axis, top-1 routed, packed
into fixed-capacity per-expert buffers (static shapes — XLA-friendly; the
capacity factor bounds the a2a volume and overflowing tokens drop to zero
like Switch Transformer), exchanged with one ``all_to_all``, run through
the local expert's FFN, and exchanged back, combined with the router gate.

No counterpart in the reference (resource layer); workload-side capability
for multi-host ComputeDomains. Public Switch-Transformer/GShard dispatch
formulation; implementation original.
"""

from __future__ import annotations

import math
from functools import partial
from typing import Dict

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, PartitionSpec as P


def init_moe_params(key, d_model: int, d_ff: int, n_experts: int,
                    scale: float = 0.02) -> Dict[str, jax.Array]:
    kr, k1, k2 = jax.random.split(key, 3)
    return {
        "router": scale * jax.random.normal(kr, (d_model, n_experts)),
        "w1": scale * jax.random.normal(k1, (n_experts, d_model, d_ff)),
        "w2": scale * jax.random.normal(k2, (n_experts, d_ff, d_model)),
    }


def _dispatch_indices(logits: jax.Array, capacity: int):
    """Top-1 routing with per-expert capacity. Returns (slot, keep, gate):
    slot[t] = flat position in the [E*C] dispatch buffer, keep[t] = token
    made it under capacity, gate[t] = router probability of the pick."""
    n_experts = logits.shape[-1]
    probs = jax.nn.softmax(logits.astype(jnp.float32), axis=-1)
    expert = jnp.argmax(probs, axis=-1)                       # [t]
    gate = jnp.max(probs, axis=-1)                            # [t]
    onehot = jax.nn.one_hot(expert, n_experts, dtype=jnp.int32)
    pos = jnp.sum(jnp.cumsum(onehot, axis=0) * onehot, axis=-1) - 1  # rank
    keep = pos < capacity
    slot = jnp.clip(expert * capacity + pos, 0, n_experts * capacity - 1)
    return slot, keep, gate


def _moe_shard(params, x, logits, *, axis_name: str, capacity: int):
    """Per-device body. x local: [t, d]; logits local: [t, E]; params
    local: {"w1": [1, d, f], "w2": [1, f, d]} (this device's expert)."""
    n = jax.lax.psum(1, axis_name)
    d = x.shape[-1]
    slot, keep, gate = _dispatch_indices(logits, capacity)

    # Pack tokens into the [E*C, d] dispatch buffer. Dropped tokens'
    # clipped slots ALIAS kept tokens' slots — correctness depends on the
    # keep mask zeroing their contribution here (add of zeros) and zeroing
    # their gather on the way back; neither mask is optional.
    buf = jnp.zeros((n * capacity, d), x.dtype)
    buf = buf.at[slot].add(x * keep[:, None].astype(x.dtype))

    # Exchange: send rows [e*C:(e+1)*C] to expert e; receive every source
    # device's block for MY expert, grouped by source.
    recv = jax.lax.all_to_all(buf, axis_name, split_axis=0, concat_axis=0,
                              tiled=True)                     # [n*C, d]
    w1, w2 = params["w1"][0], params["w2"][0]
    y = jax.nn.gelu(recv @ w1) @ w2                           # [n*C, d]
    back = jax.lax.all_to_all(y, axis_name, split_axis=0, concat_axis=0,
                              tiled=True)                     # [n*C, d]
    out = back[slot] * (keep * gate).astype(x.dtype)[:, None]
    return out


def moe_ffn(
    params: Dict[str, jax.Array],
    x: jax.Array,
    mesh: Mesh,
    *,
    expert_axis: str = "ep",
    capacity_factor: float = 1.25,
    router_logits: jax.Array = None,
    batch_axis: str = None,
) -> jax.Array:
    """Switch-MoE feed-forward over expert-parallel devices.

    params: init_moe_params output; expert-stacked leaves are sharded one
    expert per device along ``expert_axis`` (n_experts == axis size) and
    replicated over ``batch_axis`` when given.
    x: [tokens, d_model] global, token-sharded along the expert axis (and
    the batch axis when composing dp×ep: each data replica then runs its
    own a2a dispatch among its ep peers, and XLA inserts the expert-grad
    allreduce over data).
    router_logits: optional precomputed [tokens, n_experts] (callers that
    also need them — e.g. for an aux loss — avoid a second router matmul;
    XLA cannot CSE across the shard_map boundary).
    Returns [tokens, d_model], same sharding. Tokens over an expert's
    capacity contribute zero (Switch Transformer drop semantics).
    """
    from k8s_dra_driver_tpu.parallel.mesh import get_shard_map

    shard_map = get_shard_map()

    n = mesh.shape[expert_axis]
    if params["w1"].shape[0] != n:
        raise ValueError(
            f"n_experts ({params['w1'].shape[0]}) must equal the "
            f"'{expert_axis}' axis size ({n}) — one expert per device"
        )
    shards = n * (mesh.shape[batch_axis] if batch_axis else 1)
    tokens = x.shape[0]
    if tokens % shards:
        raise ValueError(f"tokens ({tokens}) not divisible by {shards} token shards")
    local_tokens = tokens // shards
    capacity = max(1, math.ceil(local_tokens / n * capacity_factor))

    if router_logits is None:
        router_logits = x @ params["router"]
    token_spec = P((batch_axis, expert_axis)) if batch_axis else P(expert_axis)
    body = partial(_moe_shard, axis_name=expert_axis, capacity=capacity)
    # Only the expert weights enter the shard body — routing already
    # happened outside, so the router stays out of the exchange.
    fn = shard_map(
        body, mesh=mesh,
        in_specs=(
            {"w1": P(expert_axis), "w2": P(expert_axis)},
            token_spec,
            token_spec,
        ),
        out_specs=token_spec,
    )
    return fn({"w1": params["w1"], "w2": params["w2"]}, x, router_logits)


def reference_moe_ffn(params: Dict[str, jax.Array], x: jax.Array,
                      n_devices: int, capacity_factor: float = 1.25) -> jax.Array:
    """Single-device reference with identical routing/capacity semantics:
    tokens are processed in the same per-device groups so capacity drops
    match the sharded version exactly."""
    n = n_devices
    tokens, _ = x.shape
    local = tokens // n
    capacity = max(1, math.ceil(local / n * capacity_factor))
    outs = []
    for g in range(n):
        xs = x[g * local:(g + 1) * local]
        logits = xs @ params["router"]
        slot, keep, gate = _dispatch_indices(logits, capacity)
        expert = slot // capacity
        ys = []
        for t in range(local):
            e = int(expert[t])
            y = jax.nn.gelu(xs[t] @ params["w1"][e]) @ params["w2"][e]
            ys.append(y * keep[t] * gate[t])
        outs.append(jnp.stack(ys))
    return jnp.concatenate(outs).astype(x.dtype)
