"""Pipeline parallelism: GPipe-style microbatch schedule over a mesh axis.

Each device along the ``pp`` axis holds one stage's parameters; microbatch
activations flow stage-to-stage with ``lax.ppermute`` (neighbor ICI hops)
under a single ``lax.scan`` of M + S - 1 ticks, so the whole schedule is
one compiled loop — no per-microbatch dispatch. Differentiating through
the scan yields the reverse pipeline automatically (XLA transposes
ppermute to the reverse permutation), so ``jax.grad`` of a pipelined loss
is the 1F1B-equivalent backward without hand-written schedule code.

No counterpart in the reference (resource layer); workload-side capability
for multi-host ComputeDomains. Public GPipe formulation; implementation
original.
"""

from __future__ import annotations

from functools import partial
from typing import Callable

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, PartitionSpec as P


def _pipeline_shard(params, x_mb, *, stage_fn, axis_name: str,
                    carry_vary=()):
    """Per-device body under shard_map.

    params: this stage's params with a leading [1] stage axis.
    x_mb:   [M, mb, ...] microbatches (mb possibly sharded over a batch
    axis; replicated along the pipe axis).
    carry_vary: extra mesh axes the scan carry varies over — the batch
    axis when the mesh composes dp×pp (the carry must match y, which
    varies over every axis its inputs do).
    Returns [M, mb, ...] final-stage outputs, valid on every device
    (broadcast from the last stage).
    """
    s = jax.lax.psum(1, axis_name)
    i = jax.lax.axis_index(axis_name)
    params_local = jax.tree.map(lambda p: p[0], params)
    m = x_mb.shape[0]
    mb_shape = x_mb.shape[1:]
    perm = [(j, (j + 1) % s) for j in range(s)]

    from k8s_dra_driver_tpu.parallel.mesh import revary

    def tick(act, t):
        # Stage 0 ingests microbatch t (clipped: ticks past M feed zeros
        # that no one reads); other stages take the ppermuted activation.
        inp = jnp.where(t < m, x_mb[jnp.clip(t, 0, m - 1)],
                        jnp.zeros(mb_shape, x_mb.dtype))
        x_in = jnp.where(i == 0, inp, act)
        y = stage_fn(params_local, x_in)
        return jax.lax.ppermute(y, axis_name, perm), y

    act0 = revary(jnp.zeros(mb_shape, x_mb.dtype),
                  (axis_name,) + tuple(carry_vary))
    _, ys = jax.lax.scan(tick, act0, jnp.arange(m + s - 1))
    # On the last stage, ys[t] for t in [s-1, m+s-1) are the outputs of
    # microbatches 0..m-1. Select them, zero elsewhere, and broadcast to
    # every stage with a psum (cheap: one [M, mb, ...] allreduce).
    outs = jax.lax.dynamic_slice_in_dim(ys, s - 1, m, axis=0)
    outs = jnp.where(i == s - 1, outs, jnp.zeros_like(outs))
    # psum output is device-invariant — exactly what out_specs P() wants.
    return jax.lax.psum(outs, axis_name)


def pipeline_apply(
    stage_fn: Callable,
    stacked_params,
    x: jax.Array,
    mesh: Mesh,
    *,
    num_microbatches: int,
    pipe_axis: str = "pp",
    batch_axis: str = None,
):
    """Run ``y = stage_S-1(... stage_1(stage_0(x)))`` as a pipeline.

    stage_fn(params, x) -> y must preserve x's shape (uniform stages).
    stacked_params: pytree whose leaves have a leading stage axis of size
    equal to the ``pipe_axis`` mesh size (sharded one stage per device).
    x: [B, ...] global batch; B divisible by num_microbatches (and, with a
    batch_axis, each microbatch by that axis's size).
    batch_axis: optional data-parallel mesh axis: microbatch rows shard
    over it and each data replica runs its own pipeline (dp×pp); params
    stay replicated over it so XLA inserts the gradient allreduce.
    Returns [B, ...] outputs, replicated along the pipe axis and sharded
    over the batch axis.
    """
    from k8s_dra_driver_tpu.parallel.mesh import get_shard_map

    shard_map = get_shard_map()

    n = mesh.shape[pipe_axis]
    stage_dims = {leaf.shape[0] for leaf in jax.tree.leaves(stacked_params)}
    if stage_dims != {n}:
        raise ValueError(
            f"stacked_params leading stage dims {sorted(stage_dims)} must "
            f"all equal the '{pipe_axis}' axis size ({n}) — one stage per device"
        )
    b = x.shape[0]
    if b % num_microbatches:
        raise ValueError(f"batch {b} not divisible by {num_microbatches} microbatches")
    mb = b // num_microbatches
    if batch_axis is not None and mb % mesh.shape[batch_axis]:
        raise ValueError(
            f"microbatch rows ({mb}) not divisible by '{batch_axis}' axis "
            f"size ({mesh.shape[batch_axis]})"
        )
    x_mb = x.reshape(num_microbatches, mb, *x.shape[1:])

    param_specs = jax.tree.map(lambda _: P(pipe_axis), stacked_params)
    data_spec = P(None, batch_axis) if batch_axis else P()
    body = partial(
        _pipeline_shard, stage_fn=stage_fn, axis_name=pipe_axis,
        carry_vary=(batch_axis,) if batch_axis else (),
    )
    fn = shard_map(
        body, mesh=mesh,
        in_specs=(param_specs, data_spec),  # params stage-sharded
        out_specs=data_spec,
    )
    out_mb = fn(stacked_params, x_mb)
    return out_mb.reshape(b, *x.shape[1:])
