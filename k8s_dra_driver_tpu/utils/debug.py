"""Debug signal handlers: SIGUSR-triggered thread-stack dumps.

Reference: /root/reference/internal/common/util.go:29-60 (goroutine stack
dump to /tmp on SIGUSR). Python analog dumps every thread's stack.
"""

from __future__ import annotations

import faulthandler
import logging
import os
import signal
import sys
import threading
import time
import traceback
from typing import Optional

log = logging.getLogger(__name__)


def format_stacks() -> str:
    """Every live thread's stack as text (goroutine-dump analog). Shared by
    the SIGUSR2 file dump and the metrics server's /stacks endpoint."""
    frames = sys._current_frames()
    names = {t.ident: t.name for t in threading.enumerate()}
    out = []
    for ident, frame in frames.items():
        out.append(f"--- thread {names.get(ident, '?')} ({ident}) ---\n")
        out.extend(traceback.format_stack(frame))
        out.append("\n")
    return "".join(out)


def _dump_stacks(dump_dir: str) -> str:
    path = os.path.join(dump_dir, f"stacks-{os.getpid()}-{int(time.time())}.txt")
    with open(path, "w", encoding="utf-8") as f:
        f.write(format_stacks())
    return path


def start_debug_signal_handlers(dump_dir: str = "/tmp", use_faulthandler: bool = True) -> None:
    """SIGUSR2 -> write all thread stacks to a file in dump_dir (SIGUSR1 is
    reserved for the slice agent's reload protocol)."""

    def handler(signum, frame):  # noqa: ARG001
        try:
            path = _dump_stacks(dump_dir)
            log.warning("thread stacks dumped to %s", path)
        except Exception:  # noqa: BLE001 — never die in a signal handler
            log.exception("stack dump failed")

    signal.signal(signal.SIGUSR2, handler)
    if use_faulthandler:
        faulthandler.enable()
