"""Version string assembly (internal/info analog)."""

from __future__ import annotations

import platform

from k8s_dra_driver_tpu import __version__


def version_string(component: str) -> str:
    return (
        f"{component} v{__version__} "
        f"(python {platform.python_version()}, {platform.system().lower()}/{platform.machine()})"
    )
