"""Version string assembly (internal/info analog)."""

from __future__ import annotations

import os
import platform

from k8s_dra_driver_tpu import __version__


def release_version() -> str:
    """The release semver, v-prefixed. Single source is the repo-root
    VERSION file (what versions.mk and the release automation read); the
    package __version__ is the fallback when the file isn't shipped (e.g.
    a pip-style install)."""
    path = os.path.join(
        os.path.dirname(os.path.dirname(os.path.dirname(os.path.abspath(__file__)))),
        "VERSION",
    )
    try:
        with open(path, encoding="utf-8") as f:
            v = f.read().strip()
            if v:
                return v if v.startswith("v") else f"v{v}"
    except (OSError, UnicodeDecodeError):
        pass
    return f"v{__version__}"


def version_string(component: str) -> str:
    return (
        f"{component} {release_version()} "
        f"(python {platform.python_version()}, {platform.system().lower()}/{platform.machine()})"
    )
