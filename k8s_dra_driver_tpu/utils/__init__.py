"""Process-level utilities: debug signal handlers, version info."""

from k8s_dra_driver_tpu.utils.debug import start_debug_signal_handlers  # noqa: F401
from k8s_dra_driver_tpu.utils.version import version_string  # noqa: F401
