"""PodManager — the slice agent's readiness mirror over its own Pod.

Reference: /root/reference/cmd/compute-domain-daemon/podmanager.go:35-137
and the clique self-label patch (main.go:537-563). The daemon's readiness
probe (`tpu-slice-ctl -q` / SliceAgent.check) is judged by the *kubelet*;
the kubelet's verdict lands in the Pod's Ready condition; the PodManager
watches its own Pod and mirrors that verdict into the clique registration
via a callback — so clique readiness reflects what the cluster actually
probes, not the agent's self-assessment. It also stamps the clique id label
onto the pod so operators can select per-clique daemon pods.
"""

from __future__ import annotations

import logging
from typing import Callable, Optional

from k8s_dra_driver_tpu.k8s import APIServer, Informer, NotFoundError
from k8s_dra_driver_tpu.k8s.core import POD, Pod

log = logging.getLogger(__name__)

COMPUTE_DOMAIN_CLIQUE_LABEL = "resource.tpu.google.com/computeDomainClique"


def is_pod_ready(pod: Pod) -> bool:
    """Pod readiness from conditions, with the simplified `ready` bool the
    sim kubelet maintains as a fallback (podmanager.go isPodReady). A
    non-Running pod is never ready, whatever its conditions say — a dead
    node's pod can carry the kubelet's last Ready=True verdict forever."""
    if pod.phase != "Running":
        return False
    for cond in pod.conditions:
        if cond.type == "Ready":
            return cond.status == "True"
    return pod.ready


class PodManager:
    def __init__(
        self,
        api: APIServer,
        namespace: str,
        pod_name: str,
        on_ready_change: Callable[[bool], None],
    ):
        self.api = api
        self.namespace = namespace
        self.pod_name = pod_name
        self.on_ready_change = on_ready_change
        # Field-selector-narrowed informer: only this pod's events arrive
        # (reference single-pod field selector, podmanager.go:47-53).
        self._informer = Informer(
            api, POD, field_name=pod_name, field_namespace=namespace
        )
        self._last: Optional[bool] = None
        self._informer.add_event_handler(
            on_add=self._on_event, on_update=self._on_event
        )

    def _on_event(self, _old, new) -> None:
        # Single-pod field-selector analog: filter to our own pod.
        if new is None or new.meta.name != self.pod_name or new.namespace != self.namespace:
            return
        ready = is_pod_ready(new)
        if ready == self._last:
            return
        self._last = ready
        try:
            self.on_ready_change(ready)
        except Exception:  # noqa: BLE001 — next event retries the mirror
            log.exception("pod readiness callback failed")
            self._last = None

    def start(self) -> None:
        self._informer.start()

    def stop(self) -> None:
        self._informer.stop()

    def pod_ready(self) -> bool:
        """Read from the informer cache, not the API — the watch already
        delivers updates (reference re-pulls from GetStore(), never GETs)."""
        pod = self._informer.get(self.pod_name, self.namespace)
        return is_pod_ready(pod) if pod is not None else False  # type: ignore[arg-type]

    def add_clique_label(self, clique_id: str) -> None:
        """Self-patch the pod with the clique label (main.go:537-563)."""
        def mutate(obj):
            obj.meta.labels[COMPUTE_DOMAIN_CLIQUE_LABEL] = clique_id
        try:
            self.api.update_with_retry(POD, self.pod_name, self.namespace, mutate)
        except NotFoundError:
            log.warning("own pod %s/%s not found for clique label",
                        self.namespace, self.pod_name)
