"""Clique membership with CAS index allocation.

Reference behavior: /root/reference/cmd/compute-domain-daemon/
cdclique.go:277-479 — each daemon upserts its DaemonInfo into the
ComputeDomainClique for (domain uid, fabric clique); the stable per-domain
index is allocated compare-and-swap style on the clique object (350-372), so
two daemons racing for the same index collide on resourceVersion and retry.
The index becomes TPU_WORKER_ID for every workload container in the domain.
"""

from __future__ import annotations

import hashlib
import logging
from typing import Callable, List, Optional

from k8s_dra_driver_tpu.api.computedomain import (
    ComputeDomainClique,
    ComputeDomainDaemonInfo,
)
from k8s_dra_driver_tpu.k8s import APIServer, ConflictError, NotFoundError
from k8s_dra_driver_tpu.k8s.core import COMPUTE_DOMAIN_CLIQUE
from k8s_dra_driver_tpu.k8s.objects import new_meta

log = logging.getLogger(__name__)


def clique_name(domain_uid: str, ici_domain: str) -> str:
    h = hashlib.sha1(ici_domain.encode(), usedforsecurity=False).hexdigest()[:10]
    return f"{domain_uid}.{h}"


class CliqueManager:
    def __init__(self, api: APIServer, namespace: str, domain_uid: str,
                 ici_domain: str,
                 on_join: Optional[Callable[[ComputeDomainDaemonInfo], None]] = None):
        self.api = api
        self.namespace = namespace
        self.domain_uid = domain_uid
        self.ici_domain = ici_domain
        self.name = clique_name(domain_uid, ici_domain)
        # Fired once per NEW membership (not on upserts of an existing
        # member) after the CAS append landed — the agent's NodeJoined
        # event hook.
        self.on_join = on_join

    # -- registration -------------------------------------------------------

    def register(
        self, node_name: str, ip_address: str, dns_name: str = "", attempts: int = 20
    ) -> int:
        """Upsert this node's DaemonInfo; returns the allocated index."""
        for _ in range(attempts):
            clique = self._get_or_create()
            info = clique.node_info(node_name)
            if info is not None:
                # Never blank an existing DNS name with the default "": the
                # startup sequence registers ip-first (index unknown), and a
                # transient empty dns would churn every peer's config.
                new_dns = dns_name or info.dns_name
                if info.ip_address != ip_address or info.dns_name != new_dns:
                    info.ip_address = ip_address
                    info.dns_name = new_dns
                    try:
                        self.api.update(clique)
                    except ConflictError:
                        continue
                return info.index
            used = set(clique.used_indices())
            # Idempotent re-join: a node deregistered earlier (lease
            # expiry, heal-shrink) reclaims the index it held — recorded
            # in the clique's released map — as long as it is still free.
            # Same node -> same worker slot across restarts, which the
            # resize-epoch rollback (and anything keyed on TPU_WORKER_ID)
            # depends on. A taken slot degrades to normal allocation.
            prefer = clique.released.get(node_name)
            if prefer is not None and prefer >= 0 and prefer not in used:
                index = prefer
            else:
                index = next(i for i in range(len(clique.nodes) + 1)
                             if i not in used)
            info = ComputeDomainDaemonInfo(
                node_name=node_name,
                ip_address=ip_address,
                dns_name=dns_name,
                index=index,
                ready=False,
            )
            clique.nodes.append(info)
            clique.released.pop(node_name, None)
            try:
                self.api.update(clique)
            except ConflictError:
                continue  # someone else won this index; re-read and retry
            if self.on_join is not None:
                try:
                    self.on_join(info)
                except Exception:  # noqa: BLE001 — telemetry only
                    log.exception("on_join hook failed for %s", node_name)
            return index
        raise RuntimeError(f"could not register {node_name} in clique {self.name}")

    def set_ready(self, node_name: str, ready: bool, attempts: int = 20) -> None:
        for _ in range(attempts):
            clique = self._get(copy=True)
            if clique is None:
                raise NotFoundError(f"clique {self.name} missing")
            info = clique.node_info(node_name)
            if info is None:
                raise NotFoundError(f"{node_name} not in clique {self.name}")
            if info.ready == ready:
                return
            info.ready = ready
            try:
                self.api.update(clique)
                return
            except ConflictError:
                continue
        raise RuntimeError(f"could not set ready={ready} for {node_name}")

    def deregister(self, node_name: str, attempts: int = 20) -> None:
        for _ in range(attempts):
            clique = self._get(copy=True)
            if clique is None:
                return
            before = len(clique.nodes)
            gone = clique.node_info(node_name)
            clique.nodes = [n for n in clique.nodes if n.node_name != node_name]
            if len(clique.nodes) == before:
                return
            if gone is not None and gone.index >= 0:
                # Remember the slot so a re-join of the SAME node gets it
                # back (see register); a different node never inherits it.
                clique.released[node_name] = gone.index
            try:
                self.api.update(clique)
                return
            except ConflictError:
                continue
        raise RuntimeError(f"could not deregister {node_name}")

    # -- reads --------------------------------------------------------------

    def get(self) -> Optional[ComputeDomainClique]:
        """The live clique object, or None before first registration —
        what event recorders fall back to when the ComputeDomain itself
        is not visible."""
        return self._get()

    def members(self) -> List[ComputeDomainDaemonInfo]:
        clique = self._get()
        if clique is None:
            return []
        return sorted(clique.nodes, key=lambda n: n.index)

    def node_ready(self, node_name: str) -> bool:
        clique = self._get()
        if clique is None:
            return False
        info = clique.node_info(node_name)
        return bool(info and info.ready)

    def _get(self, copy: bool = False) -> Optional[ComputeDomainClique]:
        # copy=True hands back a mutable working copy for the CAS loops;
        # the read-only accessors take the free reference handout.
        obj = self.api.try_get(COMPUTE_DOMAIN_CLIQUE, self.name,
                               self.namespace, copy=copy)
        return obj  # type: ignore[return-value]

    def _get_or_create(self) -> ComputeDomainClique:
        obj = self._get(copy=True)
        if obj is not None:
            return obj
        clique = ComputeDomainClique(
            meta=new_meta(self.name, self.namespace),
            domain_uid=self.domain_uid,
            ici_domain=self.ici_domain,
        )
        try:
            self.api.create(clique)
        except Exception as e:  # noqa: BLE001 — racing creator; re-read below
            log.debug("clique %s create lost the race: %s", self.name, e)
        got = self._get(copy=True)
        assert got is not None
        return got
