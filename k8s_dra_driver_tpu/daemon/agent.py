"""SliceAgent — the per-node domain daemon run loop.

Reference: /root/reference/cmd/compute-domain-daemon/main.go:212-459. On a
member node it (a) discovers the node's ICI domain via tpulib, (b) registers
in the clique and gets its stable worker index, (c) writes the peer config
file, (d) supervises the native bootstrap child, signaling it on peer-set
changes, and (e) answers the readiness probe (`check`) that ultimately
releases the workload: ready ⇔ every expected peer is registered and the
child is alive — the `nvidia-imex-ctl -q` == READY analog.
"""

from __future__ import annotations

import json
import logging
import os
import sys
import threading
import time
from typing import Callable, Dict, List, Optional

from k8s_dra_driver_tpu.daemon.cliquemanager import CliqueManager
from k8s_dra_driver_tpu.daemon.podmanager import PodManager
from k8s_dra_driver_tpu.daemon.process import ProcessManager
from k8s_dra_driver_tpu.k8s import APIServer, NotFoundError
from k8s_dra_driver_tpu.k8s.objects import new_meta
from k8s_dra_driver_tpu.pkg import featuregates as fg
from k8s_dra_driver_tpu.pkg import tracing
from k8s_dra_driver_tpu.pkg.events import (
    EventRecorder,
    REASON_CLIQUE_ASSEMBLED,
    REASON_NODE_JOINED,
    find_compute_domain_by_uid,
)
from k8s_dra_driver_tpu.pkg.leaderelection import LEASE, Lease
from k8s_dra_driver_tpu.tpulib.lib import TpuLib

log = logging.getLogger(__name__)

# Default liveness-lease duration for a slice agent. The agent renews at
# a third of this; an expiry is the control plane's host-failure signal
# (the node-heartbeat Lease analog) — what triggers a heal-shrink resize
# epoch under ElasticComputeDomains.
DEFAULT_AGENT_LEASE_S = 30.0


def agent_lease_name(domain_uid: str, node_name: str) -> str:
    """The per-(domain, node) liveness Lease, stored in the domain's
    namespace beside its cliques."""
    return f"slice-agent.{domain_uid}.{node_name}"

# A real deployment runs the native bootstrap worker; tests and single-host
# runs use this inert stand-in (sleeps forever, exits cleanly on SIGTERM).
DEFAULT_CHILD_ARGV = [
    sys.executable, "-c",
    "import signal,time\n"
    "signal.signal(signal.SIGUSR1, lambda *a: None)\n"
    "signal.signal(signal.SIGTERM, lambda *a: exit(0))\n"
    "time.sleep(1e9)",
]


class SliceAgent:
    def __init__(
        self,
        api: APIServer,
        namespace: str,
        domain_uid: str,
        node_name: str,
        pod_ip: str,
        tpulib: TpuLib,
        workdir: str,
        expected_nodes: int = 0,
        gates: Optional[fg.FeatureGates] = None,
        child_argv: Optional[List[str]] = None,
        pod_name: str = "",
        pod_namespace: str = "",
        isolation: str = "domain",
        metrics_registry=None,
        clock: Callable[[], float] = time.time,
        lease_duration_s: float = DEFAULT_AGENT_LEASE_S,
    ):
        if not domain_uid:
            raise ValueError("domain_uid (COMPUTE_DOMAIN_UUID) is required")
        self.api = api
        self.namespace = namespace
        self.domain_uid = domain_uid
        self.node_name = node_name
        self.pod_ip = pod_ip
        self.gates = gates or fg.FeatureGates()
        # pkg/sliceconfig Isolation, recorded in the peer config so the
        # bootstrap child and probes see the deployment granularity.
        self.isolation = isolation
        self.workdir = workdir
        os.makedirs(workdir, exist_ok=True)
        self.inventory = tpulib.enumerate()
        self.ici_domain = self.inventory.ici_domain
        # 0 = size follows the slice this node belongs to.
        self.expected_nodes = expected_nodes or self.inventory.num_hosts
        self.clique: Optional[CliqueManager] = None
        self.index = -1
        # When running inside a daemon pod, clique readiness mirrors the
        # kubelet's probe verdict on that pod (podmanager.go:35-137) rather
        # than the agent's self-assessment. Both identity halves are
        # required: the daemon pod lives in the DRIVER namespace, not the
        # domain's, so guessing a namespace would watch a pod that does not
        # exist and pin readiness False forever.
        self.pod_manager: Optional[PodManager] = None
        if pod_name and pod_namespace:
            self.pod_manager = PodManager(
                api, pod_namespace, pod_name, self._on_pod_ready
            )
        elif pod_name:
            log.warning(
                "POD_NAME set without POD_NAMESPACE; kubelet-verdict mirror "
                "disabled, falling back to self-assessed readiness"
            )
        self.process = ProcessManager(child_argv or DEFAULT_CHILD_ARGV)
        self.recorder = EventRecorder(api, "slice-agent",
                                      metrics_registry=metrics_registry)
        self._domain_obj = None        # resolved lazily from domain_uid
        self._assembled_announced = False
        self._last_peers: List[str] = []
        # Serializes clique-readiness writes between the run loop and the
        # pod-informer callback; both read fresh state under the lock so a
        # stale read can never overwrite a newer verdict (the reference
        # serializes via a latest-wins workqueue key, podmanager.go:76-82).
        self._sync_mu = threading.Lock()
        # Liveness lease: renewed by the run loop, read by the elastic
        # controller — its expiry IS the host-failure trigger, so a hard
        # kill (node down) is observable without any dying-gasp write.
        self.clock = clock
        self.lease_duration_s = lease_duration_s
        self._lease_renewed = 0.0
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None

    # -- identity -----------------------------------------------------------

    @property
    def dns_name(self) -> str:
        """Stable per-index name (SliceAgentsWithDNSNames), the
        <idx>.<clique-hash>.imex.nvidia.com analog."""
        short = self.ici_domain.replace("/", "-").replace(".", "-")
        return f"{self.index}.{short}.slice.tpu.internal"

    @property
    def idle(self) -> bool:
        """Non-fabric node: no ICI domain to assemble (reference idles,
        main.go:244-250)."""
        return not self.ici_domain or not self.inventory.chips

    # -- lifecycle ----------------------------------------------------------

    def startup(self) -> None:
        if self.idle:
            log.info("no ICI domain on this node; idling")
            return
        with tracing.span("clique.assemble", domain=self.domain_uid,
                          node=self.node_name, ici_domain=self.ici_domain) as sp:
            self.clique = CliqueManager(
                self.api, self.namespace, self.domain_uid, self.ici_domain,
                on_join=self._on_clique_join,
            )
            with tracing.span("clique.register"):
                self.index = self.clique.register(self.node_name, self.pod_ip)
                if self.gates.enabled("SliceAgentsWithDNSNames"):
                    # The DNS name embeds the index, which only exists
                    # post-register.
                    self.clique.register(self.node_name, self.pod_ip,
                                         dns_name=self.dns_name)
            sp.attrs["index"] = self.index
            self._renew_lease(force=True)
            if self.pod_manager is not None:
                self.pod_manager.add_clique_label(self.ici_domain)
                self.pod_manager.start()
            self.sync()

    # -- liveness lease ------------------------------------------------------

    @property
    def lease_name(self) -> str:
        return agent_lease_name(self.domain_uid, self.node_name)

    def _renew_lease(self, force: bool = False) -> None:
        """Create-or-renew this agent's liveness Lease. Renewed at a third
        of the duration (kubelet heartbeat cadence); never raises — a
        missed renewal is retried next sync, and only sustained silence
        (a dead host) expires the lease."""
        now = self.clock()
        if not force and now - self._lease_renewed < self.lease_duration_s / 3:
            return
        try:
            existing = self.api.try_get(LEASE, self.lease_name, self.namespace)
            if existing is None:
                self.api.create(Lease(
                    meta=new_meta(self.lease_name, self.namespace),
                    holder=self.node_name, acquired_at=now, renewed_at=now,
                    lease_duration_s=self.lease_duration_s,
                ))
            else:
                def renew(obj, now=now):
                    obj.holder = self.node_name
                    obj.renewed_at = now
                    obj.lease_duration_s = self.lease_duration_s
                self.api.update_with_retry(
                    LEASE, self.lease_name, self.namespace, renew)
            self._lease_renewed = now
        except Exception as e:  # noqa: BLE001 — liveness must not kill the loop
            log.debug("lease renewal for %s failed: %s", self.lease_name, e)

    def _event_target(self):
        """The ComputeDomain the uid names (resolved once), falling back to
        the clique object when the domain is not visible to this agent."""
        if self._domain_obj is None:
            self._domain_obj = find_compute_domain_by_uid(
                self.api, self.namespace, self.domain_uid)
        if self._domain_obj is not None:
            return self._domain_obj
        return self.clique.get() if self.clique is not None else None

    def _on_clique_join(self, info) -> None:
        target = self._event_target()
        if target is not None:
            self.recorder.normal(
                target, REASON_NODE_JOINED,
                f"node {info.node_name} joined clique {self.ici_domain} "
                f"as worker {info.index}")

    def _announce_assembled(self, members) -> None:
        if self._assembled_announced:
            return
        self._assembled_announced = True
        target = self._event_target()
        if target is not None:
            ready = sum(1 for m in members if m.ready)
            self.recorder.normal(
                target, REASON_CLIQUE_ASSEMBLED,
                f"clique {self.ici_domain} assembled: {len(members)}/"
                f"{self.expected_nodes} members registered, {ready} ready")

    def _on_pod_ready(self, _ready: bool) -> None:
        """Kubelet probe verdict changed: mirror it into the clique now,
        without waiting for the next sync tick. Re-reads the pod under the
        sync lock rather than trusting the event payload, which may be stale
        by the time the lock is held."""
        ready = False
        with self._sync_mu:
            if self.clique is not None and self.pod_manager is not None:
                ready = self.pod_manager.pod_ready()
                try:
                    self.clique.set_ready(self.node_name, ready)
                except NotFoundError:
                    return  # deregistered mid-flight; the sync loop re-joins
        if ready and self.clique is not None and not self._assembled_announced:
            self._announce_assembled(self.clique.members())

    def sync(self) -> None:
        """One reconcile pass: refresh peer config, supervise child, update
        readiness. Deterministic for tests; run_forever() loops it."""
        if self.idle or self.clique is None:
            return
        self._renew_lease()
        with tracing.span("clique.sync", domain=self.domain_uid,
                          node=self.node_name) as sp:
            members = self.clique.members()
            peers = self._peer_addresses(members)
            sp.attrs["peers"] = len(peers)
            if peers != self._last_peers:
                sp.attrs["peer_config_rewritten"] = True
                self._write_peer_config(members)
                spawned = self.process.ensure_started()
                if not spawned:
                    self.process.signal_reload()
                self._last_peers = peers
            else:
                self.process.ensure_started()
            with self._sync_mu:
                ready = (
                    self.pod_manager.pod_ready() if self.pod_manager is not None
                    else self.check()
                )
                sp.attrs["ready"] = ready
                try:
                    self.clique.set_ready(self.node_name, ready)
                except NotFoundError:
                    # Our clique entry vanished — a resize epoch
                    # deregistered this node (lease expired) while we were
                    # alive or restarting. Re-join: the released-index
                    # memory gives back the same worker slot, and the next
                    # sync publishes readiness normally.
                    log.info("%s deregistered from clique %s; re-joining",
                             self.node_name, self.ici_domain)
                    self.index = self.clique.register(self.node_name,
                                                      self.pod_ip)
                    if self.gates.enabled("SliceAgentsWithDNSNames"):
                        # dns embeds the (possibly reclaimed) index, which
                        # only exists post-register.
                        self.clique.register(self.node_name, self.pod_ip,
                                             dns_name=self.dns_name)
            if ready and not self._assembled_announced:
                # Refetched: this pass's `members` predates our own
                # set_ready, and the announcement should count it.
                self._announce_assembled(self.clique.members())

    def check(self) -> bool:
        """The readiness probe (`tpu-slice-ctl -q` analog)."""
        if self.idle or self.clique is None:
            return False
        members = self.clique.members()
        return len(members) >= self.expected_nodes and self.process.running

    def run_forever(self, interval_s: float = 1.0) -> None:
        while not self._stop.wait(interval_s):
            try:
                self.sync()
            except Exception:  # noqa: BLE001 — reconcile errors retry next tick
                log.exception("slice agent sync failed")

    def start(self, interval_s: float = 1.0) -> None:
        self.startup()
        self._thread = threading.Thread(
            target=self.run_forever, args=(interval_s,), daemon=True,
            name=f"slice-agent-{self.node_name}",
        )
        self._thread.start()

    def shutdown(self) -> None:
        """Graceful stop: readiness withdrawn and the liveness lease
        deleted, so a clean teardown never masquerades as a host failure
        (lease expiry) to the elastic controller."""
        self._stop.set()
        if self._thread:
            self._thread.join(timeout=5)
        if self.pod_manager is not None:
            self.pod_manager.stop()
        try:
            if self.clique is not None:
                self.clique.set_ready(self.node_name, False)
        except Exception as e:  # noqa: BLE001 — API may already be gone
            log.debug("clique ready=false on shutdown failed: %s", e)
        try:
            self.api.delete(LEASE, self.lease_name, self.namespace)
        except NotFoundError:
            pass
        except Exception as e:  # noqa: BLE001 — API may already be gone
            log.debug("lease delete on shutdown failed: %s", e)
        self.process.stop()

    def kill(self) -> None:
        """Hard stop — the node-down case: the run loop and child die with
        NO dying-gasp API writes (a dead host cannot write). The clique
        entry and the liveness lease are left as-is; the lease simply
        stops renewing and its expiry is the failure signal."""
        self._stop.set()
        if self._thread:
            self._thread.join(timeout=5)
        if self.pod_manager is not None:
            self.pod_manager.stop()
        self.process.stop()

    # -- peer config ---------------------------------------------------------

    def _peer_addresses(self, members) -> List[str]:
        use_dns = self.gates.enabled("SliceAgentsWithDNSNames")
        return [
            (m.dns_name if use_dns and m.dns_name else m.ip_address) for m in members
        ]

    @property
    def peer_config_path(self) -> str:
        return os.path.join(self.workdir, "peers.json")

    @property
    def hosts_file_path(self) -> str:
        return os.path.join(self.workdir, "hosts")

    def _write_peer_config(self, members) -> None:
        """nodes-config + /etc/hosts analog
        (/root/reference/cmd/compute-domain-daemon/dnsnames.go:133-204)."""
        cfg = {
            "ici_domain": self.ici_domain,
            "expected_nodes": self.expected_nodes,
            "isolation": self.isolation,
            "self_index": self.index,
            "peers": [
                {
                    "index": m.index,
                    "node": m.node_name,
                    "ip": m.ip_address,
                    "dns": m.dns_name,
                }
                for m in members
            ],
        }
        tmp = self.peer_config_path + ".tmp"
        with open(tmp, "w", encoding="utf-8") as f:
            json.dump(cfg, f, indent=1, sort_keys=True)
        os.replace(tmp, self.peer_config_path)
        with open(self.hosts_file_path + ".tmp", "w", encoding="utf-8") as f:
            for m in members:
                if m.dns_name:
                    f.write(f"{m.ip_address}\t{m.dns_name}\n")
        os.replace(self.hosts_file_path + ".tmp", self.hosts_file_path)
