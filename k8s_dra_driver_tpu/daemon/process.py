"""ProcessManager — supervised native child process with watchdog restart.

Reference: /root/reference/cmd/compute-domain-daemon/process.go:32-204. The
slice agent's bootstrap worker (the nvidia-imex analog) runs as a child
process; the manager starts it on demand, signals it to reload peers
(SIGUSR1), restarts it if it dies unexpectedly, and tears it down cleanly.
"""

from __future__ import annotations

import logging
import signal
import subprocess
import threading
import time
from typing import Callable, List, Optional

log = logging.getLogger(__name__)


class ProcessManager:
    def __init__(
        self,
        argv: List[str],
        restart_backoff_s: float = 1.0,
        on_restart: Optional[Callable[[int], None]] = None,
    ):
        self.argv = list(argv)
        self.restart_backoff_s = restart_backoff_s
        self.on_restart = on_restart
        self._proc: Optional[subprocess.Popen] = None
        self._mu = threading.Lock()
        self._stop = threading.Event()
        self._watchdog: Optional[threading.Thread] = None
        self.restarts = 0

    @property
    def running(self) -> bool:
        with self._mu:
            return self._proc is not None and self._proc.poll() is None

    @property
    def pid(self) -> Optional[int]:
        with self._mu:
            return self._proc.pid if self._proc and self._proc.poll() is None else None

    def ensure_started(self) -> bool:
        """Start the child if needed; returns True when it was just spawned
        (callers must not signal_reload a fresh child: SIGUSR1 delivered
        before its handler installs would kill it — it reads current config
        at startup anyway)."""
        spawned = False
        with self._mu:
            if self._proc is None or self._proc.poll() is not None:
                self._proc = self._spawn()
                log.info("started %s pid=%d", self.argv[0], self._proc.pid)
                spawned = True
        if self._watchdog is None:
            self._stop.clear()
            self._watchdog = threading.Thread(
                target=self._watch, name="slice-agent-watchdog", daemon=True
            )
            self._watchdog.start()
        return spawned

    def _spawn(self) -> subprocess.Popen:
        # Start with SIGUSR1 ignored: the ignored disposition survives exec,
        # so a reload signal arriving before the child installs its real
        # handler is dropped instead of killing it (default SIGUSR1 action
        # is terminate).
        def preexec() -> None:
            signal.signal(signal.SIGUSR1, signal.SIG_IGN)

        return subprocess.Popen(self.argv, preexec_fn=preexec)

    def signal_reload(self) -> None:
        """SIGUSR1: re-read peer config (the reference's re-resolve signal,
        main.go:384-431)."""
        with self._mu:
            if self._proc is not None and self._proc.poll() is None:
                self._proc.send_signal(signal.SIGUSR1)

    def stop(self, timeout: float = 5.0) -> None:
        self._stop.set()
        if self._watchdog is not None:
            self._watchdog.join(timeout=timeout)
            self._watchdog = None
        with self._mu:
            proc, self._proc = self._proc, None
        if proc is not None and proc.poll() is None:
            proc.terminate()
            try:
                proc.wait(timeout=timeout)
            except subprocess.TimeoutExpired:
                proc.kill()
                proc.wait(timeout=timeout)

    def _watch(self) -> None:
        while not self._stop.is_set():
            with self._mu:
                proc = self._proc
            if proc is None:
                return
            rc = proc.poll()
            if rc is not None:
                if self._stop.is_set():
                    return
                log.warning("child exited rc=%s; restarting in %.1fs", rc, self.restart_backoff_s)
                if self._stop.wait(self.restart_backoff_s):
                    return
                with self._mu:
                    if self._stop.is_set() or self._proc is not proc:
                        continue
                    self._proc = self._spawn()
                    self.restarts += 1
                    pid = self._proc.pid
                if self.on_restart:
                    self.on_restart(pid)
            else:
                self._stop.wait(0.2)
