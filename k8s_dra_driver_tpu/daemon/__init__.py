"""Slice agent — the per-ComputeDomain node daemon (L2).

Role of the reference's compute-domain-daemon (SURVEY.md §2.1, §3.4): runs
inside the per-CD DaemonSet pod on every member node, registers the node in
the domain's clique with a CAS-allocated stable index, maintains the peer
set, supervises the native bootstrap child process, and answers readiness
probes that gate the workload's Prepare.
"""

from k8s_dra_driver_tpu.daemon.cliquemanager import CliqueManager, clique_name  # noqa: F401
from k8s_dra_driver_tpu.daemon.podmanager import PodManager  # noqa: F401
from k8s_dra_driver_tpu.daemon.process import ProcessManager  # noqa: F401
from k8s_dra_driver_tpu.daemon.agent import SliceAgent  # noqa: F401
