"""``python -m k8s_dra_driver_tpu.analysis.sanitizer`` — the ``make race``
entry point.

Two passes, both across every requested seed:

1. **Seeded-fixture self-test** — each violation fixture must produce its
   detector class's violation, with both witness threads named, on EVERY
   seed and at every filler-worker count. A detector that stops firing is
   as broken as a lock that stops locking.
2. **Scenario sweep** — the real concurrent paths run under the
   interleaving explorer and must be VIOLATION-FREE: any finding here is
   a real concurrency bug (or a regression of a fixed one) and fails the
   build with both witness stacks.

Exit status: 0 all green, 1 any failure, 2 usage error.
"""

from __future__ import annotations

import argparse
import sys
from typing import List

from k8s_dra_driver_tpu.analysis.sanitizer import instrument
from k8s_dra_driver_tpu.analysis.sanitizer.runtime import SanitizerState
from k8s_dra_driver_tpu.analysis.sanitizer.scenarios import FIXTURES, SCENARIOS

DEFAULT_SEEDS = 3


def _run_one(instr: instrument.Instrumentation, fn, seed: int,
             extra_workers: int) -> SanitizerState:
    state = SanitizerState()
    old = instr.set_state(state)
    try:
        fn(state, seed, extra_workers=extra_workers)
    finally:
        instr.set_state(old)
    return state


def main(argv: List[str] | None = None) -> int:
    ap = argparse.ArgumentParser(
        prog="python -m k8s_dra_driver_tpu.analysis.sanitizer",
        description="tpusan: runtime concurrency sanitizer "
                    "(self-test + scenario sweep)")
    ap.add_argument("--seeds", type=int, default=DEFAULT_SEEDS,
                    help=f"seeds per scenario/fixture "
                         f"(default {DEFAULT_SEEDS})")
    ap.add_argument("--seed-base", type=int, default=1,
                    help="first seed value (default 1)")
    ap.add_argument("--scenario", action="append", default=None,
                    metavar="NAME", help="run only these scenarios "
                    "(repeatable; also skips the fixture self-test — "
                    "this is the one-scenario repro mode); default all")
    ap.add_argument("--workers", type=int, default=0,
                    help="extra filler workers per run (default 0)")
    ap.add_argument("--skip-fixtures", action="store_true",
                    help="skip the seeded-fixture self-test")
    ap.add_argument("--list", action="store_true",
                    help="list scenarios and fixtures, then exit")
    args = ap.parse_args(argv)

    if args.list:
        for name in SCENARIOS:
            print(f"scenario  {name}")
        for name, (_, kind) in FIXTURES.items():
            print(f"fixture   {name}  (expects: {kind})")
        return 0

    names = args.scenario or list(SCENARIOS)
    unknown = [n for n in names if n not in SCENARIOS]
    if unknown:
        print(f"unknown scenario(s): {', '.join(unknown)} "
              f"(have: {', '.join(SCENARIOS)})", file=sys.stderr)
        return 2
    seeds = [args.seed_base + i for i in range(max(1, args.seeds))]

    # An explicit --scenario is the "reproduce THIS schedule" mode: the
    # fixture self-test would only interleave unrelated output.
    run_fixtures = not args.skip_fixtures and args.scenario is None

    instr = instrument.install()
    failed = False
    try:
        if run_fixtures:
            for name, (fn, want_kind) in FIXTURES.items():
                for seed in seeds:
                    state = _run_one(instr, fn, seed, args.workers)
                    hits = [v for v in state.violations if v.kind == want_kind]
                    two_witness = [v for v in hits
                                   if v.thread and v.other_thread]
                    if not two_witness:
                        failed = True
                        print(f"FAIL fixture {name} seed={seed}: expected a "
                              f"[{want_kind}] violation naming both witness "
                              f"threads, got "
                              f"{[v.kind for v in state.violations]}")
                    else:
                        print(f"ok   fixture {name} seed={seed}: "
                              f"[{want_kind}] fired "
                              f"({two_witness[0].thread!r} vs "
                              f"{two_witness[0].other_thread!r})")
        for name in names:
            fn = SCENARIOS[name]
            for seed in seeds:
                state = _run_one(instr, fn, seed, args.workers)
                if state.violations:
                    failed = True
                    print(f"FAIL scenario {name} seed={seed}: "
                          f"{len(state.violations)} violation(s)")
                    print(state.render())
                else:
                    print(f"ok   scenario {name} seed={seed}: clean")
    finally:
        instrument.uninstall()
    if failed:
        print("tpusan: FAILED", file=sys.stderr)
        return 1
    print(f"tpusan: OK — {len(FIXTURES) if run_fixtures else 0} "
          f"fixtures self-tested, {len(names)} scenarios clean across "
          f"seeds {seeds}")
    return 0


if __name__ == "__main__":
    sys.exit(main())
