"""tpusan — the runtime concurrency sanitizer (tpulint's dynamic half).

tpulint proves locking discipline *statically* from ``# tpulint:``
annotations and lexical structure; tpusan loads the SAME annotations
(one parser, :mod:`..astutil`) and enforces them *dynamically*:

- :mod:`.runtime` — instrumented lock wrappers, the runtime lock-order
  graph with cycle (potential-deadlock) detection, the same-family
  multi-instance rule (two shard locks outside the one
  ``ordered-acquire`` helper), and the guarded-by write assert. Every
  report names BOTH witness threads with their stacks.
- :mod:`.instrument` — patches the annotated classes and the
  flock/watch-queue/fsync seams. Activated by a test fixture or
  ``TPU_SAN=1``; nothing in the production import graph touches it, so
  the "off" overhead is exactly zero.
- :mod:`.explorer` — the controlled-interleaving explorer: a seeded
  cooperative scheduler that forces thread switches at instrumented
  boundaries, making adversarial schedules reproducible.
- :mod:`.scenarios` — the four hottest concurrent paths of the control
  plane run under the explorer with invariant checks, plus the seeded
  violation fixtures proving each detector class fires.

``python -m k8s_dra_driver_tpu.analysis.sanitizer`` (``make race``) runs
the seeded-fixture self-test and the scenario sweep across seeds.
"""

from k8s_dra_driver_tpu.analysis.sanitizer.explorer import (  # noqa: F401
    Explorer,
    ExplorerStall,
    explore,
)
from k8s_dra_driver_tpu.analysis.sanitizer.instrument import (  # noqa: F401
    Instrumentation,
    current,
    enabled,
    env_requested,
    install,
    uninstall,
)
from k8s_dra_driver_tpu.analysis.sanitizer.runtime import (  # noqa: F401
    ATOMICITY,
    GUARDED_BY,
    LOCK_ORDER_CYCLE,
    SHARD_FAMILY,
    SanCondition,
    SanitizerState,
    SanLock,
    Violation,
)
