"""tpusan runtime core: instrumented locks, the lock-order graph, and
runtime guarded-by enforcement.

tpulint (the static half) trusts ``# tpulint: guarded-by=`` annotations
and lexical structure; this module is the dynamic half that *observes*
the locking actually happening:

- ``SanLock`` wraps a ``threading.Lock``/``RLock``/``Condition`` behind
  the exact same interface, recording per-thread acquisition stacks into
  a global :class:`SanitizerState`.
- Every acquisition taken while other locks are held adds edges to the
  **runtime lock-order graph**; any cycle is a potential deadlock and is
  reported with BOTH witness stacks (the two threads that established
  the opposing edges).
- Two locks of the same **family** (same class + attribute — e.g. two
  store shards' ``mu``) held together outside the one function annotated
  ``# tpulint: ordered-acquire`` is reported immediately, cycle or not:
  per-instance lock order is exactly what the annotation exists to pin.
- ``check_guard_write`` is the runtime **guarded-by** assert: an
  instrumented attribute write (or container mutation) on a guarded attr
  must happen on a thread currently holding the instance's named lock —
  this catches mutation flowing through helpers, callbacks, or dynamic
  dispatch that the static checker cannot see.

Everything here is inert unless :mod:`..sanitizer.instrument` patched the
annotated classes — production code never imports this module, so the
"off" overhead is exactly zero.
"""

from __future__ import annotations

import itertools
import sys
import threading
import time
import traceback
from dataclasses import dataclass, field
from typing import Dict, List, Optional, Sequence, Set, Tuple

# Violation kinds (the detector classes the acceptance pins, plus the
# explorer's invariant reports).
LOCK_ORDER_CYCLE = "lock-order-cycle"
SHARD_FAMILY = "unordered-multi-shard-acquire"
GUARDED_BY = "guarded-by"
ATOMICITY = "atomicity"
# A mutation attempt on a published (frozen) store snapshot — the
# sharing bug the zero-copy store turns into an error at runtime. The
# instrumented freeze seam (instrument.patch_frozen_mutations) records
# the mutating thread AND the thread that published the snapshot.
WRITE_AFTER_PUBLISH = "write-after-publish"

# Frames kept per witness stack. Deep enough to show the caller chain
# through store/plugin internals, bounded so reports stay readable.
STACK_LIMIT = 18

# Graph node identity. NEVER id(lock): a collected lock's reused address
# would conflate a dead node with a live one and weld phantom cycles into
# the session-long graph. Every instrumented lock (SanLock or flock node)
# draws a unique id here instead.
_NODE_IDS = itertools.count(1)


def next_node_id() -> int:
    return next(_NODE_IDS)


# Threads inside an `expect_frozen_mutation()` block are deliberately
# poking a sealed snapshot (tests asserting FrozenSnapshotError): the
# write-after-publish detector must not count the probe as a finding.
_expected_frozen_tls = threading.local()


class expect_frozen_mutation:
    """Context manager marking a DELIBERATE frozen-snapshot mutation —
    a test asserting that the seal holds. Inside the block the sanitized
    suite's write-after-publish detector stays quiet; the
    FrozenSnapshotError itself still raises."""

    def __enter__(self):
        _expected_frozen_tls.depth = getattr(
            _expected_frozen_tls, "depth", 0) + 1
        return self

    def __exit__(self, *exc) -> bool:
        _expected_frozen_tls.depth -= 1
        return False


def frozen_mutation_expected() -> bool:
    return getattr(_expected_frozen_tls, "depth", 0) > 0


def capture_stack(skip: int = 2, limit: int = STACK_LIMIT) -> Tuple[str, ...]:
    """Formatted stack of the calling thread, sanitizer frames trimmed."""
    frames = traceback.extract_stack(sys._getframe(skip), limit=limit)
    return tuple(
        f"{fr.filename}:{fr.lineno} in {fr.name}: {fr.line or ''}".rstrip()
        for fr in frames
    )


@dataclass(frozen=True)
class Violation:
    """One runtime finding. ``thread``/``stack`` is the thread that
    tripped the detector; ``other_thread``/``other_stack`` the second
    witness (the opposing edge's owner, the lock holder, the racing
    worker) — every report names both."""

    kind: str
    message: str
    thread: str
    stack: Tuple[str, ...]
    other_thread: str = ""
    other_stack: Tuple[str, ...] = ()

    def render(self) -> str:
        out = [f"[{self.kind}] {self.message}",
               f"  witness 1 — thread {self.thread!r}:"]
        out.extend(f"    {line}" for line in self.stack)
        if self.other_thread or self.other_stack:
            out.append(f"  witness 2 — thread {self.other_thread!r}:")
            out.extend(f"    {line}" for line in self.other_stack)
        return "\n".join(out)


@dataclass(frozen=True)
class OrderedFn:
    """One ``# tpulint: ordered-acquire`` function, as loaded from the
    shared annotation parser: acquisitions whose call stack passes
    through it are the sanctioned multi-instance path."""

    path_suffix: str   # repo-relative posix path ("k8s_dra_driver_tpu/k8s/store.py")
    name: str
    lineno: int
    end_lineno: int


@dataclass
class _Edge:
    """First witness of a lock-order edge a -> b: thread ``thread`` held
    ``a`` (acquired at ``stack_held``) when it acquired ``b`` (at
    ``stack_acq``)."""

    a_name: str
    b_name: str
    thread: str
    stack_held: Tuple[str, ...]
    stack_acq: Tuple[str, ...]


class _Held:
    __slots__ = ("lock", "stack", "count")

    def __init__(self, lock: "SanLock", stack: Tuple[str, ...]):
        self.lock = lock
        self.stack = stack
        self.count = 1


class SanitizerState:
    """Global sanitizer bookkeeping: the lock-order graph, per-thread
    held stacks, the violation list, and (while a controlled-interleaving
    run is active) the explorer driving the threads."""

    def __init__(self, capture_stacks: bool = True):
        self._mu = threading.Lock()
        self.capture_stacks = capture_stacks
        self.violations: List[Violation] = []
        self._edges: Dict[Tuple[int, int], _Edge] = {}
        self._adj: Dict[int, Set[int]] = {}
        self._names: Dict[int, str] = {}
        self._tls = threading.local()
        self._ordered_fns: List[OrderedFn] = []
        self._seen_violations: Set[Tuple[str, str]] = set()
        self.explorer = None  # set by explorer.Explorer while driving

    # -- configuration -------------------------------------------------------

    def add_ordered_fns(self, fns: Sequence[OrderedFn]) -> None:
        known = set(self._ordered_fns)
        self._ordered_fns.extend(fn for fn in fns if fn not in known)

    def reset(self) -> None:
        """Clear findings and the graph between runs (instrumentation and
        ordered-fn registry stay)."""
        with self._mu:
            self.violations.clear()
            self._edges.clear()
            self._adj.clear()
            self._names.clear()
            self._seen_violations.clear()

    # -- explorer glue -------------------------------------------------------

    def yield_point(self, tag: Tuple[str, str]) -> None:
        """A controlled-interleaving switch point. No-op unless an
        explorer is active AND the calling thread is one of its workers."""
        ex = self.explorer
        if ex is not None:
            ex.yield_point(tag)

    # -- held-stack bookkeeping ----------------------------------------------

    def _held(self) -> List[_Held]:
        stack = getattr(self._tls, "held", None)
        if stack is None:
            stack = self._tls.held = []
        return stack

    def held_by_current(self, lock: "SanLock") -> bool:
        return any(h.lock is lock for h in self._held())

    def holder_witness(self, lock: "SanLock") -> Tuple[str, Tuple[str, ...]]:
        """(thread name, acquisition stack) of the lock's current owner,
        for guarded-by reports ("who actually holds it")."""
        return lock.owner_witness()

    def note_attempt(self, lock) -> None:
        """Record lock-order edges at acquire ATTEMPT time (TSan
        semantics): "holds A, acquiring B" is the ordering fact whether
        or not the acquire ever succeeds — in an actual deadlock it never
        does, and edges recorded only on success would miss exactly the
        cycles that matter most."""
        held = self._held()
        if not held or any(h.lock is lock for h in held):
            return
        stack = capture_stack(3) if self.capture_stacks else ()
        entry = _Held(lock, stack)
        in_ordered = self._in_ordered_scope()
        tname = threading.current_thread().name
        with self._mu:
            self._names[lock.node_id] = lock.name
            for h in held:
                self._names[h.lock.node_id] = h.lock.name
                self._add_edge_locked(h, entry, tname, in_ordered)

    def note_acquire(self, lock: "SanLock") -> None:
        """Record one successful acquisition by the current thread:
        reentrant re-acquires only bump a count; first acquires push onto
        the per-thread held list, add lock-order edges from every lock
        already held, and run the family + cycle detectors."""
        held = self._held()
        for h in held:
            if h.lock is lock:
                h.count += 1
                return
        stack = capture_stack(3) if self.capture_stacks else ()
        entry = _Held(lock, stack)
        if held:
            in_ordered = self._in_ordered_scope()
            tname = threading.current_thread().name
            with self._mu:
                self._names[lock.node_id] = lock.name
                for h in held:
                    self._names[h.lock.node_id] = h.lock.name
                    self._add_edge_locked(h, entry, tname, in_ordered)
        held.append(entry)

    def note_release(self, lock: "SanLock") -> None:
        held = self._held()
        for i in range(len(held) - 1, -1, -1):
            if held[i].lock is lock:
                held[i].count -= 1
                if held[i].count == 0:
                    del held[i]
                return
        # Releasing a lock this thread never noted (acquired before
        # instrumentation, or handed across threads): nothing to track.

    # -- detectors -----------------------------------------------------------

    def _add_edge_locked(self, outer: _Held, inner: _Held, tname: str,
                         in_ordered: bool) -> None:
        a, b = outer.lock.node_id, inner.lock.node_id
        key = (a, b)
        if key not in self._edges:
            self._edges[key] = _Edge(
                a_name=outer.lock.name, b_name=inner.lock.name,
                thread=tname, stack_held=outer.stack,
                stack_acq=inner.stack)
            self._adj.setdefault(a, set()).add(b)
        # Family rule: two instances of the same lock family held together
        # outside the ordered-acquire helper.
        fam_o, fam_i = outer.lock.family, inner.lock.family
        if (fam_o is not None and fam_o == fam_i and not in_ordered):
            self._record_locked(Violation(
                kind=SHARD_FAMILY,
                message=(
                    f"`{inner.lock.name}` acquired while holding "
                    f"`{outer.lock.name}` — two {fam_o[0]}.{fam_o[1]} locks "
                    f"held together outside the `# tpulint: ordered-acquire`"
                    f" helper; two threads disagreeing on instance order "
                    f"deadlock"),
                thread=tname, stack=inner.stack,
                other_thread=tname, other_stack=outer.stack,
            ), dedup=(SHARD_FAMILY, f"{outer.lock.name}|{inner.lock.name}"))
        # Cycle detector: can `inner` already reach `outer` through
        # previously-witnessed edges? Then this new edge closes a cycle.
        path = self._find_path_locked(b, a)
        if path is not None:
            # The first edge of the return path is the opposing witness.
            opp = self._edges.get((path[0], path[1]))
            opp_thread = opp.thread if opp else "?"
            opp_outer = opp.a_name if opp else "?"
            opp_inner = opp.b_name if opp else "?"
            cyc = " -> ".join(self._names.get(n, "?") for n in [a, b] + path[1:])
            self._record_locked(Violation(
                kind=LOCK_ORDER_CYCLE,
                message=(
                    f"lock-order cycle (potential deadlock): {cyc} — this "
                    f"thread acquired `{inner.lock.name}` while holding "
                    f"`{outer.lock.name}`; thread {opp_thread!r} previously "
                    f"acquired `{opp_inner}` while holding `{opp_outer}`"),
                thread=tname, stack=inner.stack,
                other_thread=opp_thread if opp else "",
                other_stack=opp.stack_acq if opp else (),
            ), dedup=(LOCK_ORDER_CYCLE,
                      "|".join(sorted((outer.lock.name, inner.lock.name)))))

    def _find_path_locked(self, src: int, dst: int) -> Optional[List[int]]:
        """DFS path src -> dst over the edge graph, or None."""
        stack = [(src, [src])]
        seen = {src}
        while stack:
            node, path = stack.pop()
            if node == dst:
                return path
            for nxt in self._adj.get(node, ()):
                if nxt not in seen:
                    seen.add(nxt)
                    stack.append((nxt, path + [nxt]))
        return None

    def check_guard_write(self, owner: object, cls_name: str, attr: str,
                          lock_attr: str, via: str = "attribute write") -> None:
        """The runtime guarded-by assert: the instance's named lock must
        be held by the writing thread."""
        lock = getattr(owner, lock_attr, None)
        if not isinstance(lock, SanLock):
            return  # lock not (yet) wrapped: nothing to assert against
        if self.held_by_current(lock):
            return
        holder, holder_stack = lock.owner_witness()
        where = (f"currently held by thread {holder!r}" if holder
                 else "not held by any thread")
        self.record(Violation(
            kind=GUARDED_BY,
            message=(
                f"{cls_name}.{attr} (guarded-by={lock_attr}) mutated via "
                f"{via} WITHOUT holding `{lock.name}` ({where}) — torn "
                f"write under the threaded control plane"),
            thread=threading.current_thread().name,
            stack=capture_stack(3) if self.capture_stacks else (),
            other_thread=holder,
            other_stack=holder_stack,
        ), dedup=(GUARDED_BY, f"{cls_name}.{attr}"))

    # -- recording -----------------------------------------------------------

    def record(self, v: Violation,
               dedup: Optional[Tuple[str, str]] = None) -> None:
        with self._mu:
            self._record_locked(v, dedup)

    def _record_locked(self, v: Violation,
                       dedup: Optional[Tuple[str, str]] = None) -> None:
        if dedup is not None:
            if dedup in self._seen_violations:
                return
            self._seen_violations.add(dedup)
        self.violations.append(v)

    def render(self) -> str:
        return "\n\n".join(v.render() for v in self.violations)

    # -- ordered-acquire scope ----------------------------------------------

    def _in_ordered_scope(self) -> bool:
        """Any frame of the current call stack inside a function the
        annotations declare ``# tpulint: ordered-acquire``."""
        if not self._ordered_fns:
            return False
        f = sys._getframe(2)
        while f is not None:
            co = f.f_code
            for fn in self._ordered_fns:
                if (co.co_name == fn.name
                        and fn.lineno <= co.co_firstlineno <= fn.end_lineno
                        and co.co_filename.replace("\\", "/")
                            .endswith(fn.path_suffix)):
                    return True
            f = f.f_back
        return False


class SanLock:
    """Instrumented drop-in for ``threading.Lock``/``RLock``.

    ``family`` identifies the lock's declaration site ``(ClassName,
    attr)`` so two *instances* of the same shard lock can be recognized;
    None for one-of-a-kind locks. Under an active explorer, blocking
    acquires become try-acquire/yield loops so the cooperative scheduler
    can never wedge on a suspended holder.
    """

    __slots__ = ("_inner", "name", "family", "_state", "node_id",
                 "_owner_ident", "_owner_name", "_owner_stack", "_count")

    def __init__(self, inner, name: str, state: SanitizerState,
                 family: Optional[Tuple[str, str]] = None):
        self._inner = inner
        self.node_id = next_node_id()
        # The #id suffix separates instances that share a declaration
        # site (all 16 store shards are `_Shard.mu`) in reports.
        self.name = f"{name}#{self.node_id}"
        self.family = family
        self._state = state
        self._owner_ident: Optional[int] = None
        self._owner_name = ""
        self._owner_stack: Tuple[str, ...] = ()
        self._count = 0

    # -- lock protocol -------------------------------------------------------

    def acquire(self, blocking: bool = True, timeout: float = -1) -> bool:
        st = self._state
        ex = st.explorer
        if blocking and self._owner_ident != threading.get_ident():
            st.note_attempt(self)
        if ex is not None and ex.drives_current() and blocking:
            # Cooperative acquire: try/yield so the scheduler can run the
            # holder. The caller's timeout still applies — wall time
            # advances across real thread switches, so a bounded acquire
            # keeps its failure path reachable under the explorer instead
            # of degenerating into an unbounded retry loop.
            deadline = (time.monotonic() + timeout
                        if timeout is not None and timeout >= 0 else None)
            st.yield_point(("acquire", self.name))
            while not self._inner.acquire(blocking=False):
                if deadline is not None and time.monotonic() >= deadline:
                    return False
                st.yield_point(("acquire-blocked", self.name))
        else:
            if not blocking:
                if not self._inner.acquire(False):
                    return False
            elif timeout is not None and timeout >= 0:
                if not self._inner.acquire(True, timeout):
                    return False
            else:
                self._inner.acquire()
        self._mark_acquired()
        return True

    def _mark_acquired(self) -> None:
        ident = threading.get_ident()
        if self._owner_ident == ident:
            self._count += 1
        else:
            self._owner_ident = ident
            self._owner_name = threading.current_thread().name
            self._count = 1
            if self._state.capture_stacks:
                self._owner_stack = capture_stack(3)
        self._state.note_acquire(self)

    def release(self) -> None:
        self._mark_released()
        self._inner.release()
        self._state.yield_point(("release", self.name))

    def _mark_released(self) -> None:
        if self._owner_ident == threading.get_ident():
            self._count -= 1
            if self._count == 0:
                self._owner_ident = None
                self._owner_name = ""
                self._owner_stack = ()
        self._state.note_release(self)

    def __enter__(self):
        self.acquire()
        return self

    def __exit__(self, *exc) -> None:
        self.release()

    def locked(self) -> bool:
        try:
            return self._inner.locked()
        except AttributeError:  # C RLock has no locked()
            return self._count > 0

    # Condition-compat hooks (threading.Condition probes these when
    # handed an existing lock object).
    def _is_owned(self) -> bool:
        return self._owner_ident == threading.get_ident() and self._count > 0

    def _release_save(self):
        count = self._count
        for _ in range(count):
            self._mark_released()
        for _ in range(count):
            self._inner.release()
        return count

    def _acquire_restore(self, count) -> None:
        for _ in range(count):
            self._inner.acquire()
            self._mark_acquired()

    # -- sanitizer introspection ---------------------------------------------

    def held_by_current(self) -> bool:
        return self._is_owned()

    def owner_witness(self) -> Tuple[str, Tuple[str, ...]]:
        return self._owner_name, self._owner_stack

    def __repr__(self) -> str:
        return f"<SanLock {self.name} inner={self._inner!r}>"


class SanCondition(SanLock):
    """Instrumented wrapper for a ``threading.Condition``: acquire/release
    route through SanLock bookkeeping, and ``wait`` correctly drops the
    held-state for its sleep (the condition releases the inner lock) then
    re-marks it on wakeup."""

    __slots__ = ()

    def wait(self, timeout: Optional[float] = None) -> bool:
        st = self._state
        ex = st.explorer
        if ex is not None and ex.drives_current():
            # Cooperative wait: a real inner.wait() would block the
            # driven worker without yielding, wedging the whole
            # cooperative run (the would-be notifier never gets
            # scheduled) until the ExplorerStall watchdog. Model the
            # sleep as release -> yield -> reacquire and report a legal
            # spurious wakeup; the caller's predicate loop re-waits (and
            # so re-yields) until the notifier has actually run.
            saved = self._count
            for _ in range(saved):
                self.release()
            st.yield_point(("cond-wait", self.name))
            for _ in range(saved):
                self.acquire()
            return True
        count = self._count
        for _ in range(count):
            self._mark_released()
        try:
            return self._inner.wait(timeout)
        finally:
            for _ in range(count):
                self._mark_acquired()

    def wait_for(self, predicate, timeout: Optional[float] = None):
        # Reimplemented over self.wait so the held-state bookkeeping in
        # wait() applies (Condition.wait_for would call inner.wait).
        import time as _time

        endtime = None
        result = predicate()
        while not result:
            if timeout is not None:
                if endtime is None:
                    endtime = _time.monotonic() + timeout
                waittime = endtime - _time.monotonic()
                if waittime <= 0:
                    break
                self.wait(waittime)
            else:
                self.wait(None)
            result = predicate()
        return result

    def notify(self, n: int = 1) -> None:
        self._inner.notify(n)

    def notify_all(self) -> None:
        self._inner.notify_all()


def wrap_lock(value, name: str, state: SanitizerState,
              family: Optional[Tuple[str, str]] = None):
    """Wrap a threading primitive in its instrumented proxy; anything
    that isn't a Lock/RLock/Condition passes through untouched."""
    if isinstance(value, SanLock):
        return value
    if hasattr(value, "wait") and hasattr(value, "notify_all"):
        return SanCondition(value, name, state, family=family)
    if hasattr(value, "acquire") and hasattr(value, "release"):
        return SanLock(value, name, state, family=family)
    return value
