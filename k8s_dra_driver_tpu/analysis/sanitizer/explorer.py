"""Controlled-interleaving explorer: a seeded cooperative scheduler.

Real thread schedules are decided by the OS and never reproduce; the
explorer takes over scheduling for a set of worker threads so exactly
ONE runs at a time and every switch happens at an instrumented boundary
(SanLock acquire/release, watch-queue put/get, WAL fsync, or an explicit
``checkpoint()`` in scenario code). At each yield point control returns
to the scheduler, which picks the next worker with a seeded RNG — the
same seed replays the same interleaving, different seeds permute it.
That turns "run the storm test 10,000 times and hope" into "enumerate
adversarial schedules on purpose": atomicity violations that depend on a
writer landing inside another thread's two-step critical section become
deterministic findings.

Workers that block on a real lock are never a wedge: instrumented
acquires under an active explorer are try-acquire/yield loops, so a
worker whose lock is held simply yields until the scheduler runs the
holder. A watchdog raises :class:`ExplorerStall` if a worker blocks on
something the explorer cannot see.
"""

from __future__ import annotations

import random
import threading
from typing import Callable, List, Optional, Sequence, Tuple

from k8s_dra_driver_tpu.analysis.sanitizer.runtime import SanitizerState

# A worker failing to come back to the scheduler within this budget is
# blocked on something uninstrumented — surface it instead of hanging CI.
STEP_TIMEOUT_S = 30.0

# Overall schedule-length fuse: a runaway yield loop (two workers
# endlessly trading a contested lock) fails loudly.
DEFAULT_MAX_STEPS = 250_000


class ExplorerStall(RuntimeError):
    pass


class _Worker:
    def __init__(self, fn: Callable[[], None], name: str, index: int):
        self.fn = fn
        self.name = name
        self.index = index
        self.go = threading.Event()
        self.ack = threading.Event()
        self.finished = False
        self.exc: Optional[BaseException] = None
        self.thread = threading.Thread(target=self._main, name=name,
                                       daemon=True)

    def _main(self) -> None:
        # Wait for the scheduler's first pick before touching anything.
        self.go.wait()
        self.go.clear()
        try:
            self.fn()
        except BaseException as e:  # noqa: BLE001 — reported by run()
            self.exc = e
        finally:
            self.finished = True
            self.ack.set()


class Explorer:
    """One seeded schedule over a set of cooperative workers.

    Usage::

        state = SanitizerState()
        ex = Explorer(state, seed=7)
        ex.spawn(writer_a, "writer-a")
        ex.spawn(writer_b, "writer-b")
        ex.run()          # drives workers to completion, one at a time

    ``run()`` re-raises the first worker exception. The schedule trace
    (sequence of worker indices) is exposed for determinism tests.
    """

    def __init__(self, state: SanitizerState, seed: int,
                 max_steps: int = DEFAULT_MAX_STEPS):
        self.state = state
        self.seed = seed
        self.rng = random.Random(seed)
        self.max_steps = max_steps
        self.trace: List[int] = []
        self._workers: List[_Worker] = []
        self._by_ident: dict = {}

    # -- worker management ---------------------------------------------------

    def spawn(self, fn: Callable[[], None], name: str) -> None:
        if self.state.explorer is not None and self.state.explorer is not self:
            raise RuntimeError("another explorer is driving this state")
        self._workers.append(_Worker(fn, name, len(self._workers)))

    def drives_current(self) -> bool:
        return threading.get_ident() in self._by_ident

    def worker_name(self, index: int) -> str:
        return self._workers[index].name

    # -- the scheduler -------------------------------------------------------

    def run(self) -> None:
        if not self._workers:
            return
        self.state.explorer = self
        try:
            for w in self._workers:
                w.thread.start()
                self._by_ident[w.thread.ident] = w
            steps = 0
            while True:
                runnable = [w for w in self._workers if not w.finished]
                if not runnable:
                    break
                w = self.rng.choice(runnable)
                steps += 1
                if steps > self.max_steps:
                    raise ExplorerStall(
                        f"schedule exceeded {self.max_steps} steps "
                        f"(seed={self.seed}) — livelock between workers?")
                self.trace.append(w.index)
                w.go.set()
                if not w.ack.wait(STEP_TIMEOUT_S):
                    raise ExplorerStall(
                        f"worker {w.name!r} did not return to the "
                        f"scheduler within {STEP_TIMEOUT_S}s — blocked on "
                        f"an uninstrumented operation (seed={self.seed})")
                w.ack.clear()
            for w in self._workers:
                w.thread.join(timeout=STEP_TIMEOUT_S)
        finally:
            self.state.explorer = None
            self._by_ident.clear()
        for w in self._workers:
            if w.exc is not None:
                raise w.exc

    # -- called from instrumented code --------------------------------------

    def yield_point(self, tag: Tuple[str, str]) -> None:
        """Hand control back to the scheduler and wait to be re-picked.
        No-op for threads the explorer does not drive (the scheduler
        itself, background daemons)."""
        w = self._by_ident.get(threading.get_ident())
        if w is None:
            return
        w.ack.set()
        w.go.wait()
        w.go.clear()

    def checkpoint(self) -> None:
        """Explicit scenario yield point (between two halves of a
        read-modify-write, etc.)."""
        self.yield_point(("checkpoint", ""))


def explore(state: SanitizerState, seed: int,
            workers: Sequence[Tuple[str, Callable[[], None]]],
            max_steps: int = DEFAULT_MAX_STEPS) -> Explorer:
    """Convenience: build, populate, and run one schedule."""
    ex = Explorer(state, seed, max_steps=max_steps)
    for name, fn in workers:
        ex.spawn(fn, name)
    ex.run()
    return ex
