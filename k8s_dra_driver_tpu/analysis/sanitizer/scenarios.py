"""tpusan scenarios: the control plane's hottest concurrent paths driven
by the interleaving explorer, plus the seeded violation fixtures.

Two registries, both keyed by name and run per-seed from the CLI and the
test suite:

- ``SCENARIOS`` — REAL code paths (nothing seeded) under adversarial
  schedules with post-run invariant checks. The unmodified repo must run
  every scenario clean on every seed (``make race``); an invariant break
  is recorded as an :data:`ATOMICITY` violation so a future regression
  fails with witness stacks, not a silent flake.

  1. ``store-churn`` — sharded-store multi-writer churn vs. the batched
     off-lock watch dispatcher: per-kind oracle contents, no-gap/no-dup
     per-key watch ordering, EXACT bounded-queue drop accounting, and a
     fully-retired dispatcher (empty ring) at quiescence.
  2. ``wal-compact`` — WAL group-commit racing compaction epoch
     rotation: the surviving (snapshot, wal*) pair must restore
     fingerprint-TOKEN-identical state.
  3. ``migration-rollback`` — rebalancer-style checkpoint-aware
     migration racing a prepare/unprepare churner (both under the pu
     flock, as the plugins hold it): rollback-to-source leaves exactly
     the prepared claims' partitions active — no leaked ICI partitions.
  4. ``events-correlator`` — two EventRecorders (cross-thread correlator
     state) emitting overlapping series: exactly ONE stored Event per
     series (the cross-process dedup invariant), sane count bounds, and
     exact emitted+suppressed accounting per recorder.
  5. ``meshgen-reemit`` — the cd-controller's status-aggregation CAS
     (mesh-bundle recompile) racing the scheduler's placement write:
     quiesced domain pairs the placement with a bundle compiled against
     it at revision exactly 1.
  6. ``telemetry-sample-vs-prepare`` — the telemetry sampler racing a
     batched prepare/unprepare churner under the pu flock: no guarded-by
     violations, no chip-set snapshot torn across a prepare, empty
     mirror/workload registry at quiescence.
  7. ``autoscaler-scaledown-vs-consolidation`` — the serving
     autoscaler's scale-down drain racing an energy-consolidation
     migration on the SAME replica claim: the atomic cordon CAS
     (``rebalancer.controller.try_cordon``) must hand the replica to
     exactly one actor — never a double-migration, never a leaked ICI
     partition, whichever side wins on whichever seed.
  8. ``resize-vs-rebalancer`` — an elastic resize epoch's quiesce racing
     the rebalancer's repack over an overlapping host: the owner-tagged
     cordon CAS arbitrates, and whichever side wins the ledgers must
     agree with the surviving state.
  9. ``preempt-vs-rebalancer`` — a preemption eviction racing a defrag
     migration over the SAME victim unit: eviction leaves no partition
     and no prepared entry anywhere; migration leaves exactly its
     partition on the target.
  10. ``store-frozen-readers`` — the zero-copy read contract: a writer's
      copy-on-write CAS commits racing the reference-handout watch
      fan-out and a telemetry ``get()`` pass over the same object; every
      handout must be a frozen snapshot and no CAS commit may be lost.
  11. ``history-rollover-vs-explain`` — the flight recorder's writer
      (raw-ring rollover, 1m/10m bucket seals, decision appends) racing
      an explain-shaped reader walking query()/decisions_for(): no torn
      bucket ever escapes, point/decision order stays monotonic, and the
      LRU bounds hold mid-churn.
  12. ``replication-tail-vs-compaction`` — a follower tailing the
      leader's WAL while writers churn and low-water compaction rotates
      epochs underneath it: the follower converges fingerprint-token
      identical whether a record arrived via stream or re-snapshot.
  13. ``critical-path-vs-replication-apply`` — the claim critical-path
      analyzer's step()/breakdown() racing a replication-apply writer
      installing leader-stamped milestone writes (apply_replicated, the
      follower's WAL install path) on the SAME claims in shuffled
      order: every finished profile keeps non-negative phases summing
      EXACTLY to claim-to-running, exactly one profile publishes per
      claim, and the zero-steady-state-list() contract holds mid-race.

- ``FIXTURES`` — seeded violations proving each detector class fires
  deterministically on ANY seed and at ANY worker count (the fillers):
  a lock-order cycle between two shard locks taken outside the
  ``ordered-acquire`` helper, a guarded-by attribute write without the
  named lock (while another thread holds it — both witnesses named),
  the PR-8 lost-wakeup dispatcher bug (non-atomic role retirement)
  resurfaced and caught by the stranded-ring invariant, and a rogue
  reader mutating a published store snapshot in place — caught by the
  instrumented freeze seam as ``write-after-publish`` with the mutator
  AND the publishing ``freeze()`` both named.

Every scenario builds its objects AFTER ``instrument.install()`` patched
the classes, so the locks it creates are SanLocks and the explorer owns
every switch point.
"""

from __future__ import annotations

import os
import tempfile
import threading
from typing import Callable, Dict, List, Tuple

from k8s_dra_driver_tpu.analysis.sanitizer.explorer import explore
from k8s_dra_driver_tpu.analysis.sanitizer.runtime import (
    ATOMICITY,
    SanitizerState,
    Violation,
    capture_stack,
)

# Worker callables per scenario: (name, fn) pairs.
_Workers = List[Tuple[str, Callable[[], None]]]


def _fillers(state: SanitizerState, n: int) -> _Workers:
    """No-op workers that only yield: the any-worker-count knob. Their
    presence perturbs every schedule without touching shared state, so a
    detector that only fires at one worker count is caught."""
    def mk(i):
        def filler():
            for _ in range(3):
                state.yield_point(("filler", str(i)))
        return filler
    return [(f"filler-{i}", mk(i)) for i in range(n)]


def _invariant(state: SanitizerState, ok: bool, message: str,
               other_thread: str = "",
               other_stack: Tuple[str, ...] = ()) -> None:
    if ok:
        return
    state.record(Violation(
        kind=ATOMICITY, message=message,
        thread=threading.current_thread().name,
        stack=capture_stack(2),
        other_thread=other_thread, other_stack=other_stack,
    ))


# -- shared object builders ---------------------------------------------------


def _pod(name: str, ns: str = "default"):
    from k8s_dra_driver_tpu.k8s.core import Pod
    from k8s_dra_driver_tpu.k8s.objects import new_meta

    return Pod(meta=new_meta(name, ns))


def _claim_for_devices(devices, name: str):
    """Minimal allocated ResourceClaim for the plugin prepare path (the
    shape tests/test_tpu_plugin.make_claim builds)."""
    from k8s_dra_driver_tpu.api.configs import TPU_DRIVER_NAME
    from k8s_dra_driver_tpu.k8s.core import (
        AllocationResult,
        DeviceRequestAllocationResult,
        ResourceClaim,
    )
    from k8s_dra_driver_tpu.k8s.objects import fresh_uid, new_meta

    claim = ResourceClaim(meta=new_meta(name, "default"))
    claim.meta.uid = fresh_uid()
    claim.allocation = AllocationResult(
        devices=[
            DeviceRequestAllocationResult(
                request=f"r{i}", driver=TPU_DRIVER_NAME,
                pool="node-0", device=d,
            )
            for i, d in enumerate(devices)
        ],
        node_name="node-0",
    )
    return claim


# -- scenario 1: sharded store churn vs. batched dispatcher -------------------

_CHURN_OPS = 18
_TINY_QUEUE = 4


def scenario_store_churn(state: SanitizerState, seed: int,
                         extra_workers: int = 0) -> None:
    from k8s_dra_driver_tpu.k8s import APIServer, ConflictError, NotFoundError
    from k8s_dra_driver_tpu.k8s.core import (
        NODE,
        POD,
        RESOURCE_CLAIM,
        Node,
        Pod,
        ResourceClaim,
    )
    from k8s_dra_driver_tpu.k8s.objects import AlreadyExistsError, new_meta
    import random

    api = APIServer(shards=4)
    kinds = {POD: Pod, RESOURCE_CLAIM: ResourceClaim, NODE: Node}
    # Subscribed before any write: min_seq=0, every event matches.
    full = {k: api.watch(k, maxsize=65536) for k in kinds}
    tiny = {k: api.watch(k, maxsize=_TINY_QUEUE) for k in kinds}
    emitted = {k: 0 for k in kinds}  # successful (event-emitting) ops

    def churn(kind, cls, wseed):
        rng = random.Random(wseed)
        names = [f"{kind.lower()}-{i}" for i in range(4)]
        for _ in range(_CHURN_OPS):
            name = rng.choice(names)
            r = rng.random()
            try:
                if r < 0.5:
                    api.create(cls(meta=new_meta(name, "default")))
                elif r < 0.8:
                    got = api.get(kind, name, "default", copy=True)
                    got.meta.labels["touched"] = "1"
                    api.update(got)
                else:
                    api.delete(kind, name, "default")
                emitted[kind] += 1  # single writer per kind: exact
            except (NotFoundError, AlreadyExistsError, ConflictError):
                pass

    workers: _Workers = [
        (f"writer-{kind}", (lambda k=kind, c=cls, i=i:
                            churn(k, c, seed * 31 + i)))
        for i, (kind, cls) in enumerate(kinds.items())
    ]
    explore(state, seed, workers + _fillers(state, extra_workers))
    api.flush_watchers()

    # Dispatcher fully retired: nothing stranded on the ring.
    with api._ring_mu:
        _invariant(state, not api._ring and not api._dispatching,
                   f"dispatch ring not drained at quiescence: "
                   f"{len(api._ring)} event(s) stranded, "
                   f"dispatching={api._dispatching} (lost-wakeup class)")
    drops_expected = 0
    for kind in kinds:
        # Full-size subscription: every event, per-key rv never regresses.
        seen = 0
        key_rv: Dict[str, int] = {}
        q = full[kind]
        while not q.empty():
            ev = q.get_nowait()
            seen += 1
            rv = ev.obj.meta.resource_version
            _invariant(state, rv >= key_rv.get(ev.obj.meta.name, 0),
                       f"{kind}/{ev.obj.meta.name}: watch rv went backwards "
                       f"under batched fan-out")
            key_rv[ev.obj.meta.name] = rv
        _invariant(state, seen == emitted[kind],
                   f"{kind}: unbounded watcher saw {seen} events, "
                   f"writers emitted {emitted[kind]} (gap or duplicate)")
        # Tiny stalled subscription: oldest-drop keeps exactly the last
        # maxsize events; every overflow drops exactly one.
        kept = tiny[kind].qsize()
        _invariant(state, kept == min(emitted[kind], _TINY_QUEUE),
                   f"{kind}: stalled watcher retained {kept}, expected "
                   f"{min(emitted[kind], _TINY_QUEUE)}")
        drops_expected += max(0, emitted[kind] - _TINY_QUEUE)
    _invariant(state, api.stats.watch_events_dropped == drops_expected,
               f"watch_events_dropped={api.stats.watch_events_dropped} but "
               f"exactly {drops_expected} events overflowed the stalled "
               f"subscriptions — drop accounting drifted under batching")


# -- scenario 2: WAL group-commit vs. compaction epoch rotation ---------------


def scenario_wal_compact(state: SanitizerState, seed: int,
                         extra_workers: int = 0) -> None:
    from k8s_dra_driver_tpu.k8s import APIServer, ConflictError, NotFoundError
    from k8s_dra_driver_tpu.k8s.core import POD, RESOURCE_CLAIM, Pod, ResourceClaim
    from k8s_dra_driver_tpu.k8s.objects import AlreadyExistsError, new_meta
    from k8s_dra_driver_tpu.k8s.persist import StoreWAL, open_persistent_store
    import random

    with tempfile.TemporaryDirectory(prefix="tpusan-wal-") as tmp:
        api = APIServer(shards=2)
        # compact_every low: epoch rotation fires repeatedly INSIDE the
        # dispatch loop (maybe_compact — the sanctioned path), racing
        # the other threads' enqueues and flush attempts.
        wal = StoreWAL(tmp, compact_every=6, fsync=False)
        api.attach_wal(wal)
        kinds = {POD: Pod, RESOURCE_CLAIM: ResourceClaim}

        def churn(kind, cls, wseed):
            rng = random.Random(wseed)
            names = [f"{kind.lower()}-{i}" for i in range(4)]
            for _ in range(12):
                name = rng.choice(names)
                try:
                    if rng.random() < 0.6:
                        api.create(cls(meta=new_meta(name, "default")))
                    else:
                        api.delete(kind, name, "default")
                except (NotFoundError, AlreadyExistsError, ConflictError):
                    pass
                api.flush_watchers()  # group-commit records hit the WAL

        def flusher():
            # A thread whose only job is contending for the dispatcher
            # role (and therefore the group-commit append + compaction).
            for _ in range(8):
                api.flush_watchers()
                state.yield_point(("scenario", "flusher"))

        workers: _Workers = [
            (f"writer-{kind}", (lambda k=kind, c=cls, i=i:
                                churn(k, c, seed * 17 + i)))
            for i, (kind, cls) in enumerate(kinds.items())
        ] + [("flusher", flusher)]
        explore(state, seed, workers + _fillers(state, extra_workers))

        api.flush_watchers()
        wal.close()
        restored = open_persistent_store(tmp, shards=2)
        for kind in kinds:
            want, got = api.kind_fingerprint(kind), restored.kind_fingerprint(kind)
            _invariant(state, want == got,
                       f"{kind}: restore fingerprint token {got} != live "
                       f"{want} — a WAL record or snapshot row was lost "
                       f"across the group-commit/compaction race")
            live = {o.meta.name for o in api.list(kind)}
            back = {o.meta.name for o in restored.list(kind)}
            _invariant(state, live == back,
                       f"{kind}: restored contents diverge: "
                       f"missing={sorted(live - back)} "
                       f"extra={sorted(back - live)}")
        restored._wal.close()


# -- scenario 2b: replication tail racing compaction epoch rotation -----------


def scenario_replication_tail_vs_compaction(state: SanitizerState, seed: int,
                                            extra_workers: int = 0) -> None:
    """A follower tailing the leader's WAL (federation/replication.py
    fetch sweeps + the real ReplicaStore bootstrap/apply path) while
    writers churn and low-water compaction rotates epochs underneath it.
    The follower must converge fingerprint-token identical whether a
    given record reached it via the stream or via a re-snapshot handoff
    (compaction folding records away before the tail saw them)."""
    import json as _json
    import random

    from k8s_dra_driver_tpu.federation.replication import (
        ReplicaStore,
        ReplicationSource,
    )
    from k8s_dra_driver_tpu.k8s import APIServer, ConflictError, NotFoundError
    from k8s_dra_driver_tpu.k8s.core import POD, RESOURCE_CLAIM, Pod, ResourceClaim
    from k8s_dra_driver_tpu.k8s.objects import AlreadyExistsError, new_meta
    from k8s_dra_driver_tpu.k8s.persist import StoreWAL

    with tempfile.TemporaryDirectory(prefix="tpusan-repl-") as tmp:
        api = APIServer(shards=2)
        # compact_every low: epochs rotate repeatedly mid-tail, so the
        # follower keeps hitting both resume-at-watermark and the
        # compacted-past-me re-snapshot handoff.
        wal = StoreWAL(tmp, compact_every=6, fsync=False)
        api.attach_wal(wal)
        src = ReplicationSource(api, wal)
        rep = ReplicaStore(src, shards=2, cluster="san")
        with rep._mu:
            rep._watermarks[-1] = 0
        kinds = {POD: Pod, RESOURCE_CLAIM: ResourceClaim}

        def churn(kind, cls, wseed):
            rng = random.Random(wseed)
            names = [f"{kind.lower()}-{i}" for i in range(4)]
            for _ in range(10):
                name = rng.choice(names)
                try:
                    if rng.random() < 0.6:
                        api.create(cls(meta=new_meta(name, "default")))
                    else:
                        api.delete(kind, name, "default")
                except (NotFoundError, AlreadyExistsError, ConflictError):
                    pass
                api.flush_watchers()

        def follow_once():
            # One supervisor round of the follower, single-stepped: the
            # exact resync rule ReplicaStore._tail_one enforces when the
            # source answers SNAPSHOT, driven through the REAL bootstrap
            # (snapshot diff-apply) and _apply (seq-watermark) paths.
            with rep._mu:
                wm = rep._watermarks.get(-1, 0)
            snap_w, _ = src._snapshot_head()
            if wm < snap_w:
                rep._bootstrap()  # takes rep._mu itself
                with rep._mu:
                    rep._watermarks[-1] = wm = max(
                        rep._watermarks.get(-1, 0), rep._bootstrap_watermark)
            lines, _ = src.fetch(-1, wm)
            for line in lines:
                rep._apply(-1, _json.loads(line))

        def tailer():
            for _ in range(12):
                follow_once()
                state.yield_point(("scenario", "tailer"))

        workers: _Workers = [
            (f"writer-{kind}", (lambda k=kind, c=cls, i=i:
                                churn(k, c, seed * 23 + i)))
            for i, (kind, cls) in enumerate(kinds.items())
        ] + [("tailer", tailer)]
        explore(state, seed, workers + _fillers(state, extra_workers))

        api.flush_watchers()
        follow_once()  # final drain: everything written is now on disk
        for kind in kinds:
            want = api.kind_fingerprint(kind)
            got = rep.api.kind_fingerprint(kind)
            _invariant(state, want == got,
                       f"{kind}: follower fingerprint token {got} != "
                       f"leader {want} — the tail/compaction race lost or "
                       f"duplicated a replicated record")
            live = {o.meta.name for o in api.list(kind)}
            back = {o.meta.name for o in rep.api.list(kind)}
            _invariant(state, live == back,
                       f"{kind}: follower contents diverge: "
                       f"missing={sorted(live - back)} "
                       f"extra={sorted(back - live)}")
        wal.close()


# -- scenario 3: migration rollback vs. prepare/unprepare churn ---------------


def scenario_migration_rollback(state: SanitizerState, seed: int,
                                extra_workers: int = 0) -> None:
    from k8s_dra_driver_tpu.pkg import featuregates as fg
    from k8s_dra_driver_tpu.pkg.flock import Flock
    from k8s_dra_driver_tpu.pkg.partitioner import (
        PartitionManager,
        StubPartitionClient,
    )
    from k8s_dra_driver_tpu.plugins.tpu.device_state import DeviceState
    from k8s_dra_driver_tpu.tpulib import MockTpuLib

    with tempfile.TemporaryDirectory(prefix="tpusan-mig-") as tmp:
        stub = StubPartitionClient()
        dev = DeviceState(
            MockTpuLib("v5e-4"), os.path.join(tmp, "plugin"),
            cdi_root=os.path.join(tmp, "cdi"),
            gates=fg.parse("ICIPartitioning=true,DynamicSubslice=true"),
        )
        dev.partitions = PartitionManager(dev.inventory.host_topology, stub)
        pu_path = os.path.join(tmp, "plugin", "pu.lock")
        claim_a = _claim_for_devices(["tpu-subslice-1x2-at-0x0"], "mig-a")
        claim_b = _claim_for_devices(["tpu-subslice-1x2-at-1x0"], "mig-b")

        def migrator():
            # The rebalancer's unit: prepare -> migrate_out (checkpoint
            # persisted, devices released) -> rollback-to-source
            # re-prepare. Each step under the node's pu flock, exactly
            # as the kubelet plugins hold it.
            pu = Flock(pu_path)
            with pu.hold():
                dev.prepare(claim_a)
            with pu.hold():
                dev.migrate_out(claim_a.uid)
            with pu.hold():
                dev.prepare(claim_a)

        def churner():
            pu = Flock(pu_path)
            for _ in range(2):
                with pu.hold():
                    dev.prepare(claim_b)
                with pu.hold():
                    dev.unprepare(claim_b.uid)

        explore(state, seed,
                [("migrator", migrator), ("churner", churner)]
                + _fillers(state, extra_workers))

        # Rollback complete, churner quiesced unprepared: exactly the
        # migrated claim's partition is active, and a restarted plugin
        # would find zero unknown partitions to destroy.
        active = stub.active_ids()
        _invariant(state, len(active) == 1,
                   f"partition ledger holds {len(active)} active "
                   f"partition(s) {active} after rollback — expected "
                   f"exactly claim mig-a's one (leak or lost rollback)")
        from k8s_dra_driver_tpu.plugins.checkpoint import PREPARE_COMPLETED
        entries = dev.prepared_claims()
        _invariant(state,
                   set(entries) == {claim_a.uid}
                   and entries[claim_a.uid].state == PREPARE_COMPLETED,
                   f"checkpoint entries after rollback: "
                   f"{ {u: e.state for u, e in entries.items()} } — "
                   f"expected only mig-a at PrepareCompleted")


# -- scenario 4: EventRecorder cross-thread correlator state ------------------


def scenario_events_correlator(state: SanitizerState, seed: int,
                               extra_workers: int = 0) -> None:
    from k8s_dra_driver_tpu.k8s import APIServer
    from k8s_dra_driver_tpu.k8s.core import EVENT
    from k8s_dra_driver_tpu.pkg.events import (
        EventRecorder,
        REASON_FAILED_SCHEDULING,
        REASON_SCHEDULED,
    )

    api = APIServer(shards=2)
    pod = api.create(_pod("storm-pod"))
    # Two recorders sharing one store: the cross-process correlator
    # shape. Burst high enough that nothing is suppressed — accounting
    # must then be exact.
    recs = [EventRecorder(api, "scheduler", burst=1000) for _ in range(2)]
    attempts = 10

    def emitter(rec, extra_reason):
        for _ in range(attempts):
            rec.warning(pod, REASON_FAILED_SCHEDULING, "0/4 nodes feasible")
        rec.normal(pod, extra_reason, f"bound by {rec.component}")

    explore(state, seed,
            [("recorder-a", lambda: emitter(recs[0], REASON_SCHEDULED)),
             ("recorder-b", lambda: emitter(recs[1], REASON_SCHEDULED))]
            + _fillers(state, extra_workers))

    events = api.list(EVENT, namespace="default")
    series = {}
    for ev in events:
        key = (ev.type, ev.reason, ev.message)
        series.setdefault(key, []).append(ev)
    for key, rows in series.items():
        _invariant(state, len(rows) == 1,
                   f"series {key} stored {len(rows)} Event rows — two "
                   f"recorders raced past the deterministic-name dedup")
    storm = [ev for ev in events if ev.reason == REASON_FAILED_SCHEDULING]
    _invariant(state, len(storm) == 1 and 2 <= storm[0].count <= 2 * attempts,
               f"FailedScheduling storm aggregated into "
               f"{[e.count for e in storm]} (rows={len(storm)}) — expected "
               f"one row, count in [2, {2 * attempts}]")
    if storm:
        _invariant(state,
                   storm[0].first_timestamp <= storm[0].last_timestamp,
                   "aggregated Event timestamps regressed "
                   f"(first={storm[0].first_timestamp} > "
                   f"last={storm[0].last_timestamp})")
    for rec in recs:
        # Nothing may be silently lost: burst=1000 admits every series.
        total = sum(
            rec.suppressed_total.value("scheduler", reason)
            for reason in (REASON_FAILED_SCHEDULING, REASON_SCHEDULED))
        _invariant(state, total == 0,
                   f"{total} emissions suppressed despite an "
                   f"uncontended token bucket (burst=1000)")


# -- scenario 5: mesh-bundle re-emit racing the scheduler's placement write ---


def scenario_meshgen_reemit(state: SanitizerState, seed: int,
                            extra_workers: int = 0) -> None:
    """The cd-controller's status aggregation (which compiles
    ComputeDomainStatus.meshBundle inside its CAS mutate) racing the
    scheduler's placement write on the same domain: whatever the
    interleaving, the quiesced domain must hold the placement AND a bundle
    compiled against THAT placement at revision exactly 1 — a stale
    bundle paired with a fresh block, a lost placement, or a self-racing
    double re-emit are all atomicity violations."""
    from k8s_dra_driver_tpu.api.computedomain import (
        ComputeDomain,
        ComputeDomainChannelSpec,
        ComputeDomainPlacement,
        ComputeDomainSpec,
    )
    from k8s_dra_driver_tpu.controller.controller import Controller
    from k8s_dra_driver_tpu.k8s import APIServer
    from k8s_dra_driver_tpu.k8s.core import (
        Device,
        DeviceCounterConsumption,
        ResourceSlice,
    )
    from k8s_dra_driver_tpu.k8s.objects import new_meta

    api = APIServer(shards=2)
    # NOT started: the explorer owns every thread, so the controller's
    # real code paths (_on_slice_event, _update_status with its CAS
    # recompile) are driven directly.
    ctrl = Controller(api, cleanup_interval_s=3600)
    nodes = [f"mg-node-{i}" for i in range(4)]
    for n in nodes:
        rs = ResourceSlice(
            meta=new_meta(f"slice-{n}"), node_name=n, driver="tpu.google.com",
            devices=[Device(
                name=f"tpu-{n}-chip-{i}",
                attributes={"tpu.google.com/hostTopology": "2x2"},
                consumes_counters=[DeviceCounterConsumption(
                    counter_set="tpu-host-chips",
                    counters={f"chip-{i}": None})],
            ) for i in range(4)])
        api.create(rs)
        ctrl._on_slice_event(rs, deleted=False)
    api.create(ComputeDomain(
        meta=new_meta("mg-cd", "default"),
        spec=ComputeDomainSpec(
            num_nodes=4,
            channel=ComputeDomainChannelSpec(
                resource_claim_template_name="mg-cd-channel"))))

    def scheduler():
        def mutate(obj):
            obj.status.placement = ComputeDomainPlacement(
                ici_domain="mg-slice.0", block_origin="0x0",
                block_shape="2x2", nodes=list(nodes))
        api.update_with_retry("ComputeDomain", "mg-cd", "default", mutate)

    def cd_controller():
        for _ in range(3):
            ctrl._update_status(api.get("ComputeDomain", "mg-cd", "default"))

    explore(state, seed,
            [("scheduler", scheduler), ("cd-controller", cd_controller)]
            + _fillers(state, extra_workers))

    # One post-race aggregation: by now the placement is visible, so the
    # bundle MUST exist and agree with it.
    ctrl._update_status(api.get("ComputeDomain", "mg-cd", "default"))
    fresh = api.get("ComputeDomain", "mg-cd", "default")
    _invariant(state, fresh.status.placement is not None,
               "scheduler's placement write lost across the controller's "
               "status-aggregation CAS")
    _invariant(state, fresh.status.mesh_bundle is not None,
               "mesh bundle never compiled despite a recorded placement "
               "and published host topology")
    if fresh.status.placement is not None and fresh.status.mesh_bundle is not None:
        bundle_nodes = {d.node for d in fresh.status.mesh_bundle.device_order}
        _invariant(state, bundle_nodes == set(fresh.status.placement.nodes),
                   f"bundle device order names {sorted(bundle_nodes)} but the "
                   f"recorded placement holds "
                   f"{sorted(fresh.status.placement.nodes)} — a stale bundle "
                   f"survived next to a fresh placement")
        _invariant(state, fresh.status.mesh_bundle.revision == 1,
                   f"quiesced domain at bundle revision "
                   f"{fresh.status.mesh_bundle.revision} — identical geometry "
                   f"must never re-emit (the same_geometry dedup raced)")


# -- scenario 6: telemetry sampling racing a batched prepare/unprepare --------


def scenario_telemetry_sample_vs_prepare(state: SanitizerState, seed: int,
                                         extra_workers: int = 0) -> None:
    """The node agent's telemetry sampler (ring pushes + the
    prepared-claim → chip-set mirror read) racing a batched
    prepare/unprepare churner holding the pu flock: the sampler must
    never block on a prepare-path lock (the guarded-by asserts catch any
    structural drift) and every ``prepared_chipsets()`` snapshot must be
    internally consistent — a claim's FULL chip set or nothing, never a
    half-written entry torn across a chip-set change."""
    from k8s_dra_driver_tpu.pkg import featuregates as fg
    from k8s_dra_driver_tpu.pkg.flock import Flock
    from k8s_dra_driver_tpu.pkg.partitioner import (
        PartitionManager,
        StubPartitionClient,
    )
    from k8s_dra_driver_tpu.plugins.tpu.device_state import (
        DeviceHealthMonitor,
        DeviceState,
    )
    from k8s_dra_driver_tpu.tpulib import MockTpuLib

    with tempfile.TemporaryDirectory(prefix="tpusan-tel-") as tmp:
        lib = MockTpuLib("v5e-4")
        lib.set_load_trace("constant:level=0.7")
        dev = DeviceState(
            lib, os.path.join(tmp, "plugin"),
            cdi_root=os.path.join(tmp, "cdi"),
            gates=fg.parse("ICIPartitioning=true,DynamicSubslice=true"),
        )
        dev.partitions = PartitionManager(dev.inventory.host_topology,
                                          StubPartitionClient())
        monitor = DeviceHealthMonitor("node-0", dev.allocatable, tpulib=lib)
        pu_path = os.path.join(tmp, "plugin", "pu.lock")
        claim_a = _claim_for_devices(["tpu-subslice-1x2-at-0x0"], "tel-a")
        claim_b = _claim_for_devices(["tpu-subslice-1x2-at-1x0"], "tel-b")

        # Ground truth: each claim's FULL chip set, recorded from a solo
        # prepare before the race — the only values a snapshot may hold.
        expected: Dict[str, Tuple[int, ...]] = {}
        for claim in (claim_a, claim_b):
            dev.prepare(claim)
            expected[claim.uid] = dev.prepared_chipsets()[claim.uid][2]
            dev.unprepare(claim.uid)
        _invariant(state, expected[claim_a.uid] and expected[claim_b.uid]
                   and not (set(expected[claim_a.uid])
                            & set(expected[claim_b.uid])),
                   f"fixture claims must hold disjoint non-empty chip sets, "
                   f"got {expected}")

        def sampler():
            t = 1.0
            for _ in range(8):
                monitor.sample(now=t)
                t += 1.0
                snap = dev.prepared_chipsets()
                for uid, (_, _, chips) in snap.items():
                    _invariant(state, chips == expected.get(uid),
                               f"claim {uid} snapshot holds chips {chips}, "
                               f"expected the full set {expected.get(uid)} — "
                               f"sample tore across a chip-set change")
                monitor.window_stats()
                state.yield_point(("scenario", "sampler"))

        def churner(claim, wseed):
            pu = Flock(pu_path)
            for _ in range(3):
                with pu.hold():
                    dev.prepare(claim)
                state.yield_point(("scenario", f"churn-{wseed}"))
                with pu.hold():
                    dev.unprepare(claim.uid)

        explore(state, seed,
                [("sampler", sampler),
                 ("churner-a", lambda: churner(claim_a, "a")),
                 ("churner-b", lambda: churner(claim_b, "b"))]
                + _fillers(state, extra_workers))

        # Quiesced: both churners ended unprepared, so the mirror and the
        # mock's workload registry must both be empty (no leaked joins).
        _invariant(state, not dev.prepared_chipsets(),
                   f"chip-set mirror still holds "
                   f"{dev.prepared_chipsets()} after all claims unprepared")
        _invariant(state, not lib.workloads(),
                   f"mock workload registry still holds {lib.workloads()} "
                   f"after all claims unprepared")
        # The sampler kept sampling throughout: rings actually filled.
        _invariant(state, monitor.samples_taken >= 8,
                   f"sampler took {monitor.samples_taken} samples, "
                   f"expected all 8")


# -- scenario 7: autoscaler scale-down racing energy consolidation ------------


def scenario_autoscaler_scaledown_vs_consolidation(
        state: SanitizerState, seed: int, extra_workers: int = 0) -> None:
    """The serving autoscaler's scale-down drain and the rebalancer's
    energy-consolidation pass both want the same replica claim: the
    drain retires it (delete + unprepare), the consolidator migrates it
    to a busier host. Exactly one may win — the atomic cordon CAS is the
    arbiter — and whichever side wins, the partition ledgers must agree
    with the surviving state: a retired replica leaves ZERO active
    partitions, a migrated one leaves exactly its partition on the
    target. Both the double-migration and the leaked-partition failure
    mode were reachable before try_cordon (the old blind cordon write
    raced between the planner's snapshot and the annotation CAS)."""
    from k8s_dra_driver_tpu.k8s import APIServer
    from k8s_dra_driver_tpu.k8s.core import POD, RESOURCE_CLAIM
    from k8s_dra_driver_tpu.k8s.objects import NotFoundError
    from k8s_dra_driver_tpu.pkg import featuregates as fg
    from k8s_dra_driver_tpu.pkg.flock import Flock
    from k8s_dra_driver_tpu.pkg.partitioner import (
        PartitionManager,
        StubPartitionClient,
    )
    from k8s_dra_driver_tpu.plugins.checkpoint import PREPARE_COMPLETED
    from k8s_dra_driver_tpu.plugins.tpu.device_state import DeviceState
    from k8s_dra_driver_tpu.rebalancer.controller import (
        release_cordon,
        try_cordon,
    )
    from k8s_dra_driver_tpu.tpulib import MockTpuLib

    api = APIServer(shards=2)
    with tempfile.TemporaryDirectory(prefix="tpusan-as-") as tmp:
        stubs = {}
        devs = {}
        pu_paths = {}
        for node in ("node-0", "node-1"):
            stub = StubPartitionClient()
            dev = DeviceState(
                MockTpuLib("v5e-4"), os.path.join(tmp, node, "plugin"),
                cdi_root=os.path.join(tmp, node, "cdi"),
                gates=fg.parse("ICIPartitioning=true,DynamicSubslice=true"),
            )
            dev.partitions = PartitionManager(dev.inventory.host_topology,
                                              stub)
            stubs[node], devs[node] = stub, dev
            pu_paths[node] = os.path.join(tmp, node, "plugin", "pu.lock")
        claim = _claim_for_devices(["tpu-subslice-1x2-at-0x0"], "sg-rep-0")
        api.create(claim)
        api.create(_pod("sg-rep-0"))
        with Flock(pu_paths["node-0"]).hold():
            devs["node-0"].prepare(claim)
        outcomes: Dict[str, bool] = {}

        def scaler():
            # ServingGroupController._drain_replica's shape: cordon
            # atomically, then retire the replica (delete pod + claim,
            # unprepare frees the chips for the consolidator).
            c = api.try_get(RESOURCE_CLAIM, "sg-rep-0", "default")
            if c is None or not try_cordon(api, c, owner="autoscaler"):
                return
            outcomes["scaled"] = True
            for kind, name in ((POD, "sg-rep-0"),
                               (RESOURCE_CLAIM, "sg-rep-0")):
                try:
                    api.delete(kind, name, "default")
                except NotFoundError:
                    pass
            state.yield_point(("scenario", "scaler"))
            with Flock(pu_paths["node-0"]).hold():
                devs["node-0"].unprepare(claim.uid)

        def consolidator():
            # RebalanceController._migrate_unit's shape: cordon, migrate
            # out of the emptiest host, prepare on the busier target,
            # re-point the allocation, close the migration, uncordon.
            c = api.try_get(RESOURCE_CLAIM, "sg-rep-0", "default")
            if c is None or not try_cordon(api, c, owner="rebalancer"):
                return
            outcomes["migrated"] = True
            with Flock(pu_paths["node-0"]).hold():
                devs["node-0"].migrate_out(claim.uid)
            state.yield_point(("scenario", "consolidator"))
            with Flock(pu_paths["node-1"]).hold():
                devs["node-1"].prepare(claim)

            def repoint(obj):
                obj.allocation.node_name = "node-1"
            try:
                api.update_with_retry(RESOURCE_CLAIM, "sg-rep-0", "default",
                                      repoint)
            except NotFoundError:
                pass
            with Flock(pu_paths["node-0"]).hold():
                devs["node-0"].end_migration(claim.uid)
            release_cordon(api, c)

        explore(state, seed,
                [("scaler", scaler), ("consolidator", consolidator)]
                + _fillers(state, extra_workers))

        _invariant(state, len(outcomes) == 1,
                   f"cordon CAS admitted {sorted(outcomes)} — the same "
                   f"replica was handled by both the scale-down drain and "
                   f"the consolidation migration")
        active_total = sum(len(s.active_ids()) for s in stubs.values())
        if outcomes.get("scaled"):
            _invariant(state, active_total == 0,
                       f"retired replica left {active_total} active "
                       f"partition(s) across the ledgers — leak")
            _invariant(state,
                       not devs["node-0"].prepared_claims()
                       and not devs["node-1"].prepared_claims(),
                       "retired replica left checkpoint entries behind")
            _invariant(state,
                       api.try_get(RESOURCE_CLAIM, "sg-rep-0",
                                   "default") is None,
                       "retired replica's claim survived the drain")
        elif outcomes.get("migrated"):
            _invariant(state,
                       not stubs["node-0"].active_ids()
                       and len(stubs["node-1"].active_ids()) == 1,
                       f"migrated replica's ledgers read "
                       f"src={stubs['node-0'].active_ids()} "
                       f"dst={stubs['node-1'].active_ids()} — expected the "
                       f"one partition on the target only")
            entries = devs["node-1"].prepared_claims()
            _invariant(state,
                       not devs["node-0"].prepared_claims()
                       and set(entries) == {claim.uid}
                       and entries[claim.uid].state == PREPARE_COMPLETED,
                       "migrated replica's checkpoints inconsistent "
                       "(source entry not closed or target not completed)")
            live = api.try_get(RESOURCE_CLAIM, "sg-rep-0", "default")
            from k8s_dra_driver_tpu.rebalancer.controller import (
                CORDON_ANNOTATION,
            )
            _invariant(state,
                       live is not None
                       and CORDON_ANNOTATION not in live.meta.annotations
                       and live.allocation.node_name == "node-1",
                       "migrated claim lost, still cordoned, or not "
                       "re-pointed at the target")


# -- scenario 8: resize epoch racing a live-repack migration ------------------


def scenario_resize_vs_rebalancer(
        state: SanitizerState, seed: int, extra_workers: int = 0) -> None:
    """An elastic resize epoch quiesces a domain worker's claim on an
    overlapping host at the same moment the rebalancer's repack wants to
    migrate it away. Exactly one may win — the owner-tagged cordon CAS
    (owner="resize" vs owner="rebalancer") is the arbiter — and whichever
    side wins, the ledgers must agree with the surviving state: a
    quiesce-then-restart leaves the claim PREPARE_COMPLETED on its source
    with its partition re-carved there; a migration leaves exactly its
    partition on the target. Before try_cordon both the double-handle and
    the leaked-partition failure modes were reachable."""
    from k8s_dra_driver_tpu.k8s import APIServer
    from k8s_dra_driver_tpu.k8s.core import RESOURCE_CLAIM
    from k8s_dra_driver_tpu.k8s.objects import NotFoundError
    from k8s_dra_driver_tpu.pkg import featuregates as fg
    from k8s_dra_driver_tpu.pkg.flock import Flock
    from k8s_dra_driver_tpu.pkg.partitioner import (
        PartitionManager,
        StubPartitionClient,
    )
    from k8s_dra_driver_tpu.plugins.checkpoint import PREPARE_COMPLETED
    from k8s_dra_driver_tpu.plugins.tpu.device_state import DeviceState
    from k8s_dra_driver_tpu.rebalancer.controller import (
        release_cordon,
        try_cordon,
    )
    from k8s_dra_driver_tpu.tpulib import MockTpuLib

    api = APIServer(shards=2)
    with tempfile.TemporaryDirectory(prefix="tpusan-rz-") as tmp:
        stubs = {}
        devs = {}
        pu_paths = {}
        for node in ("node-0", "node-1"):
            stub = StubPartitionClient()
            dev = DeviceState(
                MockTpuLib("v5e-4"), os.path.join(tmp, node, "plugin"),
                cdi_root=os.path.join(tmp, node, "cdi"),
                gates=fg.parse("ICIPartitioning=true,DynamicSubslice=true"),
            )
            dev.partitions = PartitionManager(dev.inventory.host_topology,
                                              stub)
            stubs[node], devs[node] = stub, dev
            pu_paths[node] = os.path.join(tmp, node, "plugin", "pu.lock")
        claim = _claim_for_devices(["tpu-subslice-1x2-at-0x0"], "dom-w-0")
        api.create(claim)
        api.create(_pod("dom-w-0"))
        with Flock(pu_paths["node-0"]).hold():
            devs["node-0"].prepare(claim)
        outcomes: Dict[str, bool] = {}

        def resizer():
            # ElasticDomainController's quiesce->restart shape: cordon
            # atomically (owner="resize"), MigrationCheckpoint the claim,
            # then re-prepare it on the SAME node into the new geometry
            # and release the cordon (the finalize step).
            c = api.try_get(RESOURCE_CLAIM, "dom-w-0", "default")
            if c is None or not try_cordon(api, c, owner="resize"):
                return
            outcomes["resized"] = True
            with Flock(pu_paths["node-0"]).hold():
                devs["node-0"].migrate_out(claim.uid)
            state.yield_point(("scenario", "resizer"))
            with Flock(pu_paths["node-0"]).hold():
                devs["node-0"].prepare(claim)
            release_cordon(api, c)

        def repacker():
            # RebalanceController._migrate_unit's shape: cordon, migrate
            # off node-0, prepare on node-1, re-point, close, uncordon.
            c = api.try_get(RESOURCE_CLAIM, "dom-w-0", "default")
            if c is None or not try_cordon(api, c, owner="rebalancer"):
                return
            outcomes["migrated"] = True
            with Flock(pu_paths["node-0"]).hold():
                devs["node-0"].migrate_out(claim.uid)
            state.yield_point(("scenario", "repacker"))
            with Flock(pu_paths["node-1"]).hold():
                devs["node-1"].prepare(claim)

            def repoint(obj):
                obj.allocation.node_name = "node-1"
            try:
                api.update_with_retry(RESOURCE_CLAIM, "dom-w-0", "default",
                                      repoint)
            except NotFoundError:
                pass
            with Flock(pu_paths["node-0"]).hold():
                devs["node-0"].end_migration(claim.uid)
            release_cordon(api, c)

        explore(state, seed,
                [("resizer", resizer), ("repacker", repacker)]
                + _fillers(state, extra_workers))

        _invariant(state, len(outcomes) == 1,
                   f"cordon CAS admitted {sorted(outcomes)} — the same "
                   f"worker claim was handled by both the resize epoch "
                   f"and the repack migration")
        from k8s_dra_driver_tpu.rebalancer.controller import (
            CORDON_ANNOTATION,
        )
        live = api.try_get(RESOURCE_CLAIM, "dom-w-0", "default")
        _invariant(state,
                   live is not None
                   and CORDON_ANNOTATION not in live.meta.annotations,
                   "winner left the claim cordoned after finishing")
        if outcomes.get("resized"):
            _invariant(state,
                       len(stubs["node-0"].active_ids()) == 1
                       and not stubs["node-1"].active_ids(),
                       f"resized claim's ledgers read "
                       f"src={stubs['node-0'].active_ids()} "
                       f"dst={stubs['node-1'].active_ids()} — expected its "
                       f"one partition back on the source only")
            entries = devs["node-0"].prepared_claims()
            _invariant(state,
                       set(entries) == {claim.uid}
                       and entries[claim.uid].state == PREPARE_COMPLETED
                       and not devs["node-1"].prepared_claims(),
                       "resized claim not PREPARE_COMPLETED on its source")
            _invariant(state,
                       live is not None
                       and live.allocation.node_name == "node-0",
                       "resized claim's allocation moved off its source")
        elif outcomes.get("migrated"):
            _invariant(state,
                       not stubs["node-0"].active_ids()
                       and len(stubs["node-1"].active_ids()) == 1,
                       f"migrated claim's ledgers read "
                       f"src={stubs['node-0'].active_ids()} "
                       f"dst={stubs['node-1'].active_ids()} — expected the "
                       f"one partition on the target only")
            entries = devs["node-1"].prepared_claims()
            _invariant(state,
                       not devs["node-0"].prepared_claims()
                       and set(entries) == {claim.uid}
                       and entries[claim.uid].state == PREPARE_COMPLETED,
                       "migrated claim's checkpoints inconsistent")
            _invariant(state,
                       live is not None
                       and live.allocation.node_name == "node-1",
                       "migrated claim not re-pointed at the target")


def scenario_preempt_vs_rebalancer(
        state: SanitizerState, seed: int, extra_workers: int = 0) -> None:
    """A preemption eviction races a defrag migration over the SAME
    victim unit. Exactly one may win — the owner-tagged cordon CAS
    (owner="preempt" vs owner="rebalancer") is the arbiter — and
    whichever side wins, the ledgers must agree with the surviving
    state: an eviction leaves the claim deallocated with NO partition
    and NO prepared entry anywhere (checkpointed out, requeued); a
    migration leaves exactly its partition on the target with the
    allocation re-pointed. Without try_cordon both the double-handle
    and the leaked-partition failure modes are reachable."""
    from k8s_dra_driver_tpu.k8s import APIServer
    from k8s_dra_driver_tpu.k8s.core import RESOURCE_CLAIM
    from k8s_dra_driver_tpu.k8s.objects import NotFoundError
    from k8s_dra_driver_tpu.pkg import featuregates as fg
    from k8s_dra_driver_tpu.pkg.flock import Flock
    from k8s_dra_driver_tpu.pkg.partitioner import (
        PartitionManager,
        StubPartitionClient,
    )
    from k8s_dra_driver_tpu.plugins.checkpoint import PREPARE_COMPLETED
    from k8s_dra_driver_tpu.plugins.tpu.device_state import DeviceState
    from k8s_dra_driver_tpu.rebalancer.controller import (
        release_cordon,
        try_cordon,
    )
    from k8s_dra_driver_tpu.scheduling.preemption import CORDON_OWNER_PREEMPT
    from k8s_dra_driver_tpu.tpulib import MockTpuLib

    api = APIServer(shards=2)
    with tempfile.TemporaryDirectory(prefix="tpusan-pe-") as tmp:
        stubs = {}
        devs = {}
        pu_paths = {}
        for node in ("node-0", "node-1"):
            stub = StubPartitionClient()
            dev = DeviceState(
                MockTpuLib("v5e-4"), os.path.join(tmp, node, "plugin"),
                cdi_root=os.path.join(tmp, node, "cdi"),
                gates=fg.parse("ICIPartitioning=true,DynamicSubslice=true"),
            )
            dev.partitions = PartitionManager(dev.inventory.host_topology,
                                              stub)
            stubs[node], devs[node] = stub, dev
            pu_paths[node] = os.path.join(tmp, node, "plugin", "pu.lock")
        claim = _claim_for_devices(["tpu-subslice-1x2-at-0x0"], "victim-0")
        api.create(claim)
        api.create(_pod("victim-0"))
        with Flock(pu_paths["node-0"]).hold():
            devs["node-0"].prepare(claim)
        outcomes: Dict[str, bool] = {}

        def preemptor():
            # PreemptionController._evict's shape: cordon atomically
            # (owner="preempt"), MigrationCheckpoint the claim out,
            # deallocate it via the API (requeue), close the entry,
            # release the cordon.
            c = api.try_get(RESOURCE_CLAIM, "victim-0", "default")
            if c is None or not try_cordon(api, c,
                                           owner=CORDON_OWNER_PREEMPT):
                return
            outcomes["preempted"] = True
            with Flock(pu_paths["node-0"]).hold():
                devs["node-0"].migrate_out(claim.uid)
            state.yield_point(("scenario", "preemptor"))

            def clear(obj):
                obj.allocation = None
            try:
                api.update_with_retry(RESOURCE_CLAIM, "victim-0", "default",
                                      clear)
            except NotFoundError:
                pass
            with Flock(pu_paths["node-0"]).hold():
                devs["node-0"].end_migration(claim.uid)
            release_cordon(api, c)

        def repacker():
            # RebalanceController._migrate_unit's shape: cordon, migrate
            # off node-0, prepare on node-1, re-point, close, uncordon.
            c = api.try_get(RESOURCE_CLAIM, "victim-0", "default")
            if c is None or not try_cordon(api, c, owner="rebalancer"):
                return
            outcomes["migrated"] = True
            with Flock(pu_paths["node-0"]).hold():
                devs["node-0"].migrate_out(claim.uid)
            state.yield_point(("scenario", "repacker"))
            with Flock(pu_paths["node-1"]).hold():
                devs["node-1"].prepare(claim)

            def repoint(obj):
                obj.allocation.node_name = "node-1"
            try:
                api.update_with_retry(RESOURCE_CLAIM, "victim-0", "default",
                                      repoint)
            except NotFoundError:
                pass
            with Flock(pu_paths["node-0"]).hold():
                devs["node-0"].end_migration(claim.uid)
            release_cordon(api, c)

        explore(state, seed,
                [("preemptor", preemptor), ("repacker", repacker)]
                + _fillers(state, extra_workers))

        _invariant(state, len(outcomes) == 1,
                   f"cordon CAS admitted {sorted(outcomes)} — the same "
                   f"victim claim was handled by both the preemption "
                   f"eviction and the repack migration")
        from k8s_dra_driver_tpu.rebalancer.controller import (
            CORDON_ANNOTATION,
        )
        live = api.try_get(RESOURCE_CLAIM, "victim-0", "default")
        _invariant(state,
                   live is not None
                   and CORDON_ANNOTATION not in live.meta.annotations,
                   "winner left the claim cordoned after finishing")
        if outcomes.get("preempted"):
            _invariant(state,
                       not stubs["node-0"].active_ids()
                       and not stubs["node-1"].active_ids(),
                       f"evicted claim's ledgers read "
                       f"src={stubs['node-0'].active_ids()} "
                       f"dst={stubs['node-1'].active_ids()} — expected no "
                       f"partition anywhere after checkpoint-out")
            _invariant(state,
                       not devs["node-0"].prepared_claims()
                       and not devs["node-1"].prepared_claims(),
                       "evicted claim left checkpoint residue")
            _invariant(state,
                       live is not None and live.allocation is None,
                       "evicted claim still allocated")
        elif outcomes.get("migrated"):
            _invariant(state,
                       not stubs["node-0"].active_ids()
                       and len(stubs["node-1"].active_ids()) == 1,
                       f"migrated claim's ledgers read "
                       f"src={stubs['node-0'].active_ids()} "
                       f"dst={stubs['node-1'].active_ids()} — expected the "
                       f"one partition on the target only")
            entries = devs["node-1"].prepared_claims()
            _invariant(state,
                       not devs["node-0"].prepared_claims()
                       and set(entries) == {claim.uid}
                       and entries[claim.uid].state == PREPARE_COMPLETED,
                       "migrated claim's checkpoints inconsistent")
            _invariant(state,
                       live is not None
                       and live.allocation.node_name == "node-1",
                       "migrated claim not re-pointed at the target")


# -- scenario 10: writer CAS racing frozen-reference readers ------------------


def scenario_store_frozen_readers(state: SanitizerState, seed: int,
                                  extra_workers: int = 0) -> None:
    """The zero-copy read contract under race: a writer CAS-updating one
    pod (copy-on-write commit, re-freeze, structural sharing) while the
    batched watch fan-out delivers REFERENCES to a subscriber and a
    telemetry-style pass reads the SAME published object via ``get()``.
    Every consumer stays on the reference-handout path — a clean run
    proves no consumer mutates a snapshot (the instrumented freeze seam
    would report write-after-publish with both witnesses) and that every
    handed-out object is actually frozen (an unfrozen escape would be a
    torn-read hazard, reported as an atomicity violation)."""
    import queue as queue_mod

    from k8s_dra_driver_tpu.k8s import APIServer
    from k8s_dra_driver_tpu.k8s.core import POD
    from k8s_dra_driver_tpu.k8s.objects import is_frozen

    api = APIServer(shards=2)
    api.create(_pod("frozen-pod"))
    q = api.watch(POD, maxsize=65536)
    updates = 6

    def writer():
        for i in range(updates):
            def mutate(obj, i=i):
                # The CAS hands a thawed working copy: mutation here is
                # the sanctioned path. Commit re-freezes and publishes.
                obj.meta.annotations["gen"] = str(i)
                obj.phase = "Running" if i % 2 else "Pending"
            api.update_with_retry(POD, "frozen-pod", "default", mutate)
            api.flush_watchers()

    def watcher():
        seen = 0
        while seen < updates:
            try:
                ev = q.get_nowait()
            except queue_mod.Empty:
                state.yield_point(("scenario", "watch-wait"))
                continue
            seen += 1
            # Read-only consumption of the shared reference (the
            # informer/telemetry consumer shape).
            _ = (ev.obj.phase, ev.obj.meta.annotations.get("gen"))
            _invariant(state, is_frozen(ev.obj),
                       f"watch fan-out delivered an UNFROZEN object "
                       f"(rv={ev.obj.meta.resource_version}) — a consumer "
                       f"could mutate the store's published state in place")

    def telemetry_reader():
        for _ in range(2 * updates):
            got = api.get(POD, "frozen-pod", "default")
            # Aggregation-style reads over the snapshot's sub-objects.
            _ = (got.phase, dict(got.meta.labels),
                 got.meta.annotations.get("gen"))
            _invariant(state, is_frozen(got),
                       "get() handed out an UNFROZEN reference on the "
                       "zero-copy read path")
            state.yield_point(("scenario", "telemetry-read"))

    explore(state, seed,
            [("writer", writer), ("watcher", watcher),
             ("telemetry", telemetry_reader)]
            + _fillers(state, extra_workers))

    api.flush_watchers()
    final = api.get(POD, "frozen-pod", "default")
    _invariant(state, final.meta.annotations.get("gen") == str(updates - 1),
               f"final snapshot holds gen={final.meta.annotations.get('gen')}"
               f" after {updates} CAS commits — a copy-on-write commit was "
               f"lost across the race")


# -- scenario 11: history tier rollover vs. explain query ---------------------


def scenario_history_rollover_vs_explain(state: SanitizerState, seed: int,
                                         extra_workers: int = 0) -> None:
    """The PR 17 flight recorder under race: a telemetry-shaped writer
    pushing samples that roll the raw ring and seal 1m/10m buckets (plus
    DecisionRecords on one pod) while an explain-shaped reader walks
    ``query()``/``decisions_for()``/``series_names()`` concurrently. A
    clean run proves no torn bucket escapes the lock (count >= 1 and
    min <= mean <= max with p95 inside [min, max] on every observed
    bucket), point and decision order stay monotonic, and the series-LRU
    and raw-ring bounds hold mid-churn — bounded memory is an invariant
    here, not a hope."""
    from k8s_dra_driver_tpu.pkg.history import (
        HistoryStore,
        RULE_SCHED_BIND,
    )

    h = HistoryStore(None, raw_capacity=16, max_series=4)
    pushes = 18

    def writer():
        for i in range(pushes):
            t = i * 13.0  # crosses a 1m bucket edge every ~5 pushes
            h.push(f"duty/{i % 6}", t, (i % 10) / 10.0)  # LRU churn
            h.push("duty/hot", t, (i % 7) / 7.0)
            if i % 3 == 0:
                h.decide(controller="scheduler", rule=RULE_SCHED_BIND,
                         outcome="bound", kind="Pod", namespace="default",
                         name="explain-pod", message=f"pass {i}", now=t)
            state.yield_point(("scenario", "history-push"))

    def explainer():
        for _ in range(pushes):
            for res in ("raw", "1m", "10m"):
                last_t = None
                for p in h.query("duty/hot", resolution=res):
                    _invariant(
                        state, last_t is None or p["t"] >= last_t,
                        f"{res} points observed out of order "
                        f"({last_t} then {p['t']}) — a reader saw a "
                        f"half-rolled ring")
                    last_t = p["t"]
                    if res != "raw":
                        _invariant(
                            state,
                            p["count"] >= 1
                            and p["min"] <= p["mean"] <= p["max"]
                            and p["min"] <= p["p95"] <= p["max"],
                            f"torn {res} bucket escaped the lock: {p}")
            _invariant(state, len(h.series_names()) <= 4,
                       "series LRU bound exceeded mid-churn")
            decs = h.decisions_for("Pod", "default", "explain-pod")
            times = [d.time for d in decs]
            _invariant(state, times == sorted(times),
                       f"decision history not oldest-first: {times}")
            _invariant(state,
                       all(d.rule == RULE_SCHED_BIND for d in decs),
                       "a decision record was torn across append")
            state.yield_point(("scenario", "explain-walk"))

    explore(state, seed,
            [("writer", writer), ("explainer", explainer)]
            + _fillers(state, extra_workers))

    _invariant(state, len(h.query("duty/hot")) <= 16,
               "raw ring exceeded its capacity at quiescence")
    _invariant(state, len(h.series_names()) <= 4,
               "series LRU bound exceeded at quiescence")
    want = len(range(0, pushes, 3))
    _invariant(state, h.decision_count() == want,
               f"{h.decision_count()} decisions retained after {want} "
               f"appends — a record was lost or duplicated across the race")


def scenario_critical_path_vs_replication_apply(
        state: SanitizerState, seed: int, extra_workers: int = 0) -> None:
    """The PR 19 critical-path profiler under race: a replication-apply
    writer installs leader-stamped claim/pod milestone writes
    (``apply_replicated`` — the follower's WAL install path, preserving
    the leader's resourceVersions verbatim) on a handful of claims in a
    seed-shuffled order, while the analyzer's ``step()`` drains its
    watch queues and an explain-shaped reader walks ``breakdown()``
    concurrently. A clean run proves no torn phase ever escapes: every
    finished profile carries non-negative phases over the closed
    vocabulary summing EXACTLY to claim-to-running (the running-max
    chain holds whatever interleaving the apply stream landed in),
    exactly one profile publishes per claim, the store's list() counter
    never moves after construction (the zero-steady-state-scan contract
    the bench gate measures), and the tracked maps stay bounded."""
    import random

    from k8s_dra_driver_tpu.k8s import APIServer
    from k8s_dra_driver_tpu.k8s.conditions import Condition
    from k8s_dra_driver_tpu.k8s.core import (
        CLAIM_COND_PREPARED,
        POD,
        RESOURCE_CLAIM,
        AllocationResult,
        Pod,
        ResourceClaim,
        ResourceClaimConsumer,
    )
    from k8s_dra_driver_tpu.k8s.objects import new_meta
    from k8s_dra_driver_tpu.pkg.history import (
        RULE_LIFECYCLE_PROFILE,
        HistoryStore,
    )
    from k8s_dra_driver_tpu.pkg.lifecycle import (
        CLAIM_PHASES,
        ClaimLifecycleAnalyzer,
    )

    api = APIServer()
    hist = HistoryStore(None)
    analyzer = ClaimLifecycleAnalyzer(api, history=hist,
                                      write_footprint=False)
    base_lists = api.stats.list_calls
    names = [f"c{i}" for i in range(3)]

    def stamp(obj, uid, rv):
        obj.meta.uid = uid
        obj.meta.resource_version = rv
        return obj

    def put(obj):
        key = (obj.kind, obj.meta.namespace, obj.meta.name)
        api.apply_replicated("PUT", obj, key, None)

    def claim_writes(name):
        """The five leader writes of one claim's life, as the WAL
        carries them (rv monotone per object, content cumulative)."""
        uid, puid = f"uid-{name}", f"uid-{name}-pod"
        pod = f"{name}-pod"
        alloc = AllocationResult(node_name="n0")
        prep = Condition(type=CLAIM_COND_PREPARED, status="True")
        res = ResourceClaimConsumer(kind="Pod", name=pod, uid=puid)
        return [
            put_fn for put_fn in (
                lambda: put(stamp(ResourceClaim(
                    meta=new_meta(name, "default")), uid, 1)),
                lambda: put(stamp(ResourceClaim(
                    meta=new_meta(name, "default"), allocation=alloc,
                    reserved_for=[res]), uid, 2)),
                lambda: put(stamp(ResourceClaim(
                    meta=new_meta(name, "default"), allocation=alloc,
                    reserved_for=[res], conditions=[prep]), uid, 3)),
                lambda: put(stamp(Pod(
                    meta=new_meta(pod, "default"), node_name="n0"),
                    puid, 1)),
                lambda: put(stamp(Pod(
                    meta=new_meta(pod, "default"), node_name="n0",
                    phase="Running"), puid, 2)),
            )
        ]

    def applier():
        rng = random.Random(seed * 37 + 1)
        # Interleave the claims' write chains into one shuffled stream:
        # per-object order stays monotone (it is on the real WAL), but
        # cross-object order — and pod-before-claim — is adversarial.
        chains = [claim_writes(n) for n in names]
        while any(chains):
            live = [c for c in chains if c]
            c = rng.choice(live)
            c.pop(0)()
            state.yield_point(("scenario", "replication-apply"))

    def stepper():
        for t in range(1, 24):
            analyzer.step(float(t))
            state.yield_point(("scenario", "analyzer-step"))

    def reader():
        for _ in range(24):
            for name in names:
                prof = analyzer.breakdown("default", name)
                if prof is None:
                    continue
                _invariant(
                    state,
                    set(prof.phase_seconds) == set(CLAIM_PHASES),
                    f"{name}: torn phase vocabulary "
                    f"{sorted(prof.phase_seconds)}")
                _invariant(
                    state,
                    all(v >= 0.0 for v in prof.phase_seconds.values()),
                    f"{name}: negative phase escaped the running-max "
                    f"chain: {prof.phase_seconds}")
                _invariant(
                    state,
                    abs(sum(prof.phase_seconds.values())
                        - prof.total_seconds) < 1e-9,
                    f"{name}: phase sum {sum(prof.phase_seconds.values())}"
                    f" != total {prof.total_seconds} — a half-finalized "
                    f"profile was handed out")
            state.yield_point(("scenario", "breakdown-read"))

    explore(state, seed,
            [("applier", applier), ("stepper", stepper),
             ("reader", reader)] + _fillers(state, extra_workers))
    api.flush_watchers()
    analyzer.step(100.0)
    for name in names:
        prof = analyzer.breakdown("default", name)
        _invariant(state, prof is not None,
                   f"{name} never profiled after full milestone chain")
        if prof is not None:
            _invariant(
                state,
                abs(sum(prof.phase_seconds.values())
                    - prof.total_seconds) < 1e-9,
                f"{name}: finished profile torn: {prof.phase_seconds} "
                f"vs total {prof.total_seconds}")
        recs = [r for r in hist.decisions_for(RESOURCE_CLAIM, "default",
                                              name)
                if r.rule == RULE_LIFECYCLE_PROFILE]
        _invariant(state, len(recs) == 1,
                   f"{name}: {len(recs)} lifecycle decisions published "
                   f"(exactly-once per claim violated)")
    _invariant(state, api.stats.list_calls == base_lists,
               f"analyzer issued {api.stats.list_calls - base_lists} "
               f"store list() call(s) past construction — the "
               f"zero-steady-state-scan contract broke under race")
    counts = analyzer.tracked_counts()
    _invariant(state, counts["claims"] <= len(names)
               and counts["pods"] <= len(names),
               f"tracked maps unbounded under churn: {counts}")
    analyzer.close()


SCENARIOS: Dict[str, Callable[..., None]] = {
    "store-churn": scenario_store_churn,
    "wal-compact": scenario_wal_compact,
    "replication-tail-vs-compaction": scenario_replication_tail_vs_compaction,
    "migration-rollback": scenario_migration_rollback,
    "events-correlator": scenario_events_correlator,
    "meshgen-reemit": scenario_meshgen_reemit,
    "telemetry-sample-vs-prepare": scenario_telemetry_sample_vs_prepare,
    "autoscaler-scaledown-vs-consolidation":
        scenario_autoscaler_scaledown_vs_consolidation,
    "resize-vs-rebalancer": scenario_resize_vs_rebalancer,
    "preempt-vs-rebalancer": scenario_preempt_vs_rebalancer,
    "store-frozen-readers": scenario_store_frozen_readers,
    "history-rollover-vs-explain": scenario_history_rollover_vs_explain,
    "critical-path-vs-replication-apply":
        scenario_critical_path_vs_replication_apply,
}


# -- seeded violation fixtures ------------------------------------------------


def fixture_lock_order_cycle(state: SanitizerState, seed: int,
                             extra_workers: int = 0) -> None:
    """Two shard locks of one store acquired in OPPOSITE orders by two
    threads, neither inside the ordered-acquire helper: the family rule
    fires on the first nested acquire, and the cycle detector closes the
    A->B / B->A loop with both witness stacks. Under the explorer the
    try-acquire/yield loops mean even the deadlock-prone schedule
    completes — the graph, not luck, reports it."""
    from k8s_dra_driver_tpu.k8s import APIServer

    api = APIServer(shards=2)
    sa, sb = api._shards[0], api._shards[1]
    ab_done = [False]

    def a_then_b():
        with sa.mu:
            state.yield_point(("fixture", "a-holds-a"))
            with sb.mu:
                pass
        ab_done[0] = True

    def b_then_a():
        # Sequenced after t-ab so the run completes on every seed: a
        # lock-order graph flags the INVERSION — the actual deadlock
        # never has to happen (in a deadlocking schedule the explorer's
        # attempt-time edges still record the cycle before stalling).
        while not ab_done[0]:
            state.yield_point(("fixture", "await-ab"))
        with sb.mu:
            state.yield_point(("fixture", "b-holds-b"))
            with sa.mu:
                pass

    explore(state, seed,
            [("t-ab", a_then_b), ("t-ba", b_then_a)]
            + _fillers(state, extra_workers))


def fixture_guarded_by_write(state: SanitizerState, seed: int,
                             extra_workers: int = 0) -> None:
    """A guarded shard index mutated WITHOUT its shard lock, while the
    other thread holds that very lock — the write that corrupts a reader
    mid-scan. The runtime assert names the writer AND the current
    holder."""
    from k8s_dra_driver_tpu.k8s import APIServer

    api = APIServer(shards=2)
    shard = api._shards[0]
    holding = [False]
    wrote = [False]

    def holder():
        with shard.mu:
            holding[0] = True
            while not wrote[0]:
                state.yield_point(("fixture", "holder-spin"))

    def rogue_writer():
        while not holding[0]:
            state.yield_point(("fixture", "writer-spin"))
        # Direct index mutation, no lock: exactly what a helper reached
        # through dynamic dispatch can do behind the static checker's
        # back.
        shard.objects[("Pod", "default", "rogue")] = _pod("rogue")
        wrote[0] = True

    explore(state, seed,
            [("holder", holder), ("rogue-writer", rogue_writer)]
            + _fillers(state, extra_workers))


def fixture_dispatcher_atomicity(state: SanitizerState, seed: int,
                                 extra_workers: int = 0) -> None:
    """Re-seed the PR-8 lost-wakeup bug: a dispatcher that retires its
    role in TWO steps (empty-check, then flag-clear in a separate
    critical section). A writer that enqueues inside the window sees
    ``_dispatching`` still True and walks away; the retiring dispatcher
    never re-checks — the event strands on the ring. The explorer drives
    the writer into the window on every seed (coordinated spin), and the
    stranded-ring invariant reports both threads."""
    from k8s_dra_driver_tpu.k8s import APIServer
    from k8s_dra_driver_tpu.k8s.store import WATCH_DISPATCH_BATCH

    api = APIServer(shards=2)
    in_gap = [False]
    enqueued = [False]
    witness = {}

    def broken_dispatch():
        with api._ring_mu:
            if api._dispatching or not api._ring:
                return
            api._dispatching = True
        while True:
            with api._ring_mu:
                batch = api._ring[:WATCH_DISPATCH_BATCH]
                del api._ring[:len(batch)]
            if not batch:
                # BUG under test: the empty-check above and this
                # retirement are separate critical sections.
                in_gap[0] = True
                while not enqueued[0]:
                    state.yield_point(("fixture", "gap"))
                witness["dispatcher"] = (threading.current_thread().name,
                                         capture_stack(2))
                with api._ring_mu:
                    api._dispatching = False
                return
            api._deliver(batch)

    api._dispatch = broken_dispatch

    def first_writer():
        api.create(_pod("pod-a"))

    def racing_writer():
        while not in_gap[0]:
            state.yield_point(("fixture", "writer-spin"))
        api.create(_pod("pod-b"))  # enqueues; sees _dispatching, leaves
        witness["writer"] = (threading.current_thread().name,
                             capture_stack(2))
        enqueued[0] = True

    explore(state, seed,
            [("dispatcher", first_writer), ("writer", racing_writer)]
            + _fillers(state, extra_workers))

    with api._ring_mu:
        stranded = len(api._ring)
        dispatching = api._dispatching
    if stranded and not dispatching:
        d_name, d_stack = witness.get("dispatcher", ("?", ()))
        w_name, w_stack = witness.get("writer", ("?", ()))
        state.record(Violation(
            kind=ATOMICITY,
            message=(
                f"{stranded} watch event(s) stranded on the dispatch ring "
                f"with no active dispatcher — the dispatcher retired its "
                f"role non-atomically with the empty check (lost wakeup); "
                f"the racing writer's event will sit until an unrelated "
                f"write"),
            thread=w_name, stack=w_stack,
            other_thread=d_name, other_stack=d_stack,
        ))


def fixture_write_after_publish(state: SanitizerState, seed: int,
                                extra_workers: int = 0) -> None:
    """A rogue consumer mutates a published snapshot in place — the exact
    bug class the zero-copy reference handout makes possible. A publisher
    creates a pod (the store's ``freeze()`` publishes the snapshot and the
    instrumented seam records it as witness), then a rogue reader fetches
    the reference via ``get()`` and writes ``.phase`` directly instead of
    going through a working copy. The seal still raises
    ``FrozenSnapshotError``, and the detector must name BOTH threads: the
    mutator and the publishing ``freeze()``."""
    from k8s_dra_driver_tpu.k8s import APIServer
    from k8s_dra_driver_tpu.k8s.core import POD
    from k8s_dra_driver_tpu.k8s.objects import FrozenSnapshotError

    api = APIServer(shards=2)
    published = [False]

    def publisher():
        api.create(_pod("seeded"))
        published[0] = True

    def rogue():
        while not published[0]:
            state.yield_point(("fixture", "rogue-spin"))
        got = api.get(POD, "seeded", "default")
        try:
            got.phase = "Running"  # tpulint: disable=snapshot-mutation -- the seeded violation itself: this fixture exists to prove the runtime detector catches what a suppressed static finding would hide
        except FrozenSnapshotError:
            pass  # the seal holds; the detector recorded the violation

    explore(state, seed,
            [("publisher", publisher), ("rogue", rogue)]
            + _fillers(state, extra_workers))


# fixture name -> (callable, violation kind it must produce)
FIXTURES: Dict[str, Tuple[Callable[..., None], str]] = {
    "lock-order-cycle": (fixture_lock_order_cycle, "lock-order-cycle"),
    "guarded-by-write": (fixture_guarded_by_write, "guarded-by"),
    "dispatcher-atomicity": (fixture_dispatcher_atomicity, "atomicity"),
    "write-after-publish": (fixture_write_after_publish,
                            "write-after-publish"),
}
