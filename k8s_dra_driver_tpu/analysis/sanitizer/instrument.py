"""tpusan instrumentation: patch the annotated classes at runtime.

``install()`` loads the repo's ``# tpulint: guarded-by=`` / ``holds=`` /
``ordered-acquire`` annotations through the SAME parser tpulint uses
(:func:`analysis.astutil.parse_annotations_text` — one vocabulary, two
enforcers) and then patches every annotated class:

- lock attributes named by a guard get wrapped in :class:`SanLock` /
  :class:`SanCondition` proxies as they are assigned, feeding the
  lock-order graph;
- writes to guarded attributes assert the instance's named lock is held
  by the writing thread (``__init__`` exempt — the object isn't shared
  yet);
- guarded ``dict``/``list``/``set`` values are wrapped in checking
  containers so ``self.X[k] = v`` / ``.append`` / ``del self.X[...]``
  through ANY call path — helpers, callbacks, dynamic dispatch — hits
  the same assert;
- :class:`pkg.flock.Flock` acquire/release feed the same lock graph
  (keyed per lock file), so a cp-before-pu inversion shows up as a
  runtime cycle exactly like a shard-lock inversion;
- the store's watch queues and the WAL's fsync seam become explorer
  yield points.

Activation: a test fixture calls ``install()`` directly, or the suite
runs with ``TPU_SAN=1`` (see ``tests/conftest.py``). Nothing in the
production import graph touches this module, so overhead when off is
exactly zero.
"""

from __future__ import annotations

import os
import queue as _queue_mod
import threading
import time
from typing import Dict, List, Optional, Tuple

from k8s_dra_driver_tpu.analysis.astutil import (
    ModuleAnnotations,
    parse_annotations_text,
)
from k8s_dra_driver_tpu.analysis.sanitizer import runtime as runtime_mod
from k8s_dra_driver_tpu.analysis.sanitizer.runtime import (
    OrderedFn,
    SanitizerState,
    wrap_lock,
)

# Objects currently running their __init__ (by id): guarded writes during
# construction are exempt — the object is not shared yet. GIL-atomic
# set add/discard; ids are unique while the object is alive.
_constructing: set = set()

_active: Optional["Instrumentation"] = None


def repo_root_default() -> str:
    pkg = os.path.dirname(os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))))
    return os.path.dirname(pkg)


def discover_annotated_modules(repo_root: Optional[str] = None) -> List[str]:
    """Repo-relative paths of every package module that declares a
    ``guarded-by`` annotation (cheap text probe, then the real parse)."""
    root = repo_root or repo_root_default()
    pkg = os.path.join(root, "k8s_dra_driver_tpu")
    out = []
    for dirpath, dirnames, filenames in os.walk(pkg):
        # analysis/ is the linter+sanitizer itself: its sources QUOTE the
        # annotation vocabulary, they don't declare guarded state.
        dirnames[:] = [d for d in dirnames
                       if d not in ("__pycache__", "analysis")]
        for fn in filenames:
            if not fn.endswith(".py"):
                continue
            path = os.path.join(dirpath, fn)
            with open(path, encoding="utf-8") as f:
                text = f.read()
            if "guarded-by=" in text or "ordered-acquire" in text:
                rel = os.path.relpath(path, root).replace(os.sep, "/")
                out.append(rel)
    return sorted(out)


def _module_name(rel: str) -> str:
    return rel[:-3].replace("/", ".")


def _check_container(owner, cls_name: str, attr: str, lock_attr: str) -> None:
    instr = _active
    if instr is not None:
        instr.state.check_guard_write(owner, cls_name, attr, lock_attr,
                                      via="container mutation")


class _Meta:
    __slots__ = ("owner", "cls_name", "attr", "lock_attr")

    def __init__(self, owner, cls_name, attr, lock_attr):
        self.owner = owner
        self.cls_name = cls_name
        self.attr = attr
        self.lock_attr = lock_attr

    def check(self):
        _check_container(self.owner, self.cls_name, self.attr, self.lock_attr)


class GuardedDict(dict):
    """dict that runtime-asserts the declared lock on every mutation."""

    _san_meta: _Meta

    def __setitem__(self, k, v):
        self._san_meta.check()
        dict.__setitem__(self, k, v)

    def __delitem__(self, k):
        self._san_meta.check()
        dict.__delitem__(self, k)

    def pop(self, *a):
        self._san_meta.check()
        return dict.pop(self, *a)

    def popitem(self):
        self._san_meta.check()
        return dict.popitem(self)

    def clear(self):
        self._san_meta.check()
        dict.clear(self)

    def update(self, *a, **kw):
        self._san_meta.check()
        dict.update(self, *a, **kw)

    def setdefault(self, k, default=None):
        self._san_meta.check()
        return dict.setdefault(self, k, default)


class GuardedList(list):
    _san_meta: _Meta

    def __setitem__(self, i, v):
        self._san_meta.check()
        list.__setitem__(self, i, v)

    def __delitem__(self, i):
        self._san_meta.check()
        list.__delitem__(self, i)

    def __iadd__(self, other):
        self._san_meta.check()
        list.extend(self, other)
        return self

    def append(self, v):
        self._san_meta.check()
        list.append(self, v)

    def extend(self, it):
        self._san_meta.check()
        list.extend(self, it)

    def insert(self, i, v):
        self._san_meta.check()
        list.insert(self, i, v)

    def remove(self, v):
        self._san_meta.check()
        list.remove(self, v)

    def pop(self, i=-1):
        self._san_meta.check()
        return list.pop(self, i)

    def clear(self):
        self._san_meta.check()
        list.clear(self)

    def sort(self, **kw):
        self._san_meta.check()
        list.sort(self, **kw)


class GuardedSet(set):
    _san_meta: _Meta

    def add(self, v):
        self._san_meta.check()
        set.add(self, v)

    def discard(self, v):
        self._san_meta.check()
        set.discard(self, v)

    def remove(self, v):
        self._san_meta.check()
        set.remove(self, v)

    def pop(self):
        self._san_meta.check()
        return set.pop(self)

    def clear(self):
        self._san_meta.check()
        set.clear(self)

    def update(self, *a):
        self._san_meta.check()
        set.update(self, *a)


_CONTAINER_WRAP = {dict: GuardedDict, list: GuardedList, set: GuardedSet}


def _wrap_container(value, owner, cls_name, attr, lock_attr):
    wrap_cls = _CONTAINER_WRAP.get(type(value))
    if wrap_cls is None:
        return value
    wrapped = wrap_cls(value)
    wrapped._san_meta = _Meta(owner, cls_name, attr, lock_attr)
    return wrapped


class Instrumentation:
    """One active patch set. ``state`` is swappable between runs
    (``set_state``) so the CLI can give every scenario/seed a fresh
    violation list without re-patching."""

    def __init__(self, state: SanitizerState):
        self.state = state
        self._class_patches: List[Tuple[type, Dict[str, object]]] = []
        self._fn_patches: List[Tuple[object, str, object]] = []
        self.instrumented_classes: List[str] = []
        self.annotations: Dict[str, ModuleAnnotations] = {}
        self._ordered: List[OrderedFn] = []

    def ordered_fns(self) -> List[OrderedFn]:
        return list(self._ordered)

    def set_state(self, state: SanitizerState) -> SanitizerState:
        """Swap in a fresh violation sink (per scenario/seed) without
        re-patching. The ordered-acquire registry travels with the
        instrumentation, so the new state enforces the same contracts."""
        state.add_ordered_fns(self._ordered)
        old, self.state = self.state, state
        return old

    # -- class patching ------------------------------------------------------

    def instrument_class(self, cls: type, guards: Dict[str, str]) -> None:
        """Patch one class: wrap lock attrs at assignment, assert guards
        on attribute writes, wrap guarded containers, and exempt
        ``__init__`` via the construction set."""
        instr = self
        cls_name = cls.__name__
        lock_attrs = frozenset(guards.values())
        guard_map = dict(guards)

        saved: Dict[str, object] = {
            "__setattr__": cls.__dict__.get("__setattr__"),
            "__init__": cls.__dict__.get("__init__"),
        }
        orig_setattr = cls.__setattr__
        orig_init = cls.__init__

        def __setattr__(self, name, value):
            if name in lock_attrs:
                value = wrap_lock(value, f"{cls_name}.{name}", instr.state,
                                  family=(cls_name, name))
            if name in guard_map:
                if id(self) not in _constructing:
                    instr.state.check_guard_write(
                        self, cls_name, name, guard_map[name])
                value = _wrap_container(value, self, cls_name, name,
                                        guard_map[name])
            orig_setattr(self, name, value)

        def __init__(self, *a, **kw):
            _constructing.add(id(self))
            try:
                orig_init(self, *a, **kw)
            finally:
                _constructing.discard(id(self))

        cls.__setattr__ = __setattr__  # type: ignore[method-assign]
        cls.__init__ = __init__  # type: ignore[method-assign]
        self._class_patches.append((cls, saved))
        self.instrumented_classes.append(cls.__qualname__)

    def instrument_module(self, rel: str,
                          repo_root: Optional[str] = None) -> None:
        """Instrument every annotated class of one repo module and
        register its ordered-acquire helpers."""
        import importlib

        root = repo_root or repo_root_default()
        path = os.path.join(root, rel.replace("/", os.sep))
        with open(path, encoding="utf-8") as f:
            text = f.read()
        anns = parse_annotations_text(text, filename=path)
        self.annotations[rel] = anns
        if anns.class_guards:
            mod = importlib.import_module(_module_name(rel))
            for cls_name, guards in anns.class_guards.items():
                cls = getattr(mod, cls_name, None)
                if isinstance(cls, type):
                    self.instrument_class(cls, guards)
        ordered = [OrderedFn(path_suffix=rel, name=fa.name,
                             lineno=fa.lineno, end_lineno=fa.end_lineno)
                   for fa in anns.ordered_functions()]
        if ordered:
            self._ordered.extend(ordered)
            self.state.add_ordered_fns(ordered)

    # -- seams ---------------------------------------------------------------

    def _patch_attr(self, obj, name: str, value) -> None:
        self._fn_patches.append((obj, name, getattr(obj, name)))
        setattr(obj, name, value)

    def patch_flocks(self) -> None:
        """Feed Flock acquisition into the lock graph, keyed per lock
        file; under an explorer, acquires become try/yield loops."""
        from k8s_dra_driver_tpu.pkg.flock import Flock, FlockTimeoutError

        instr = self
        nodes: Dict[str, object] = {}
        nodes_mu = threading.Lock()

        class _FlockNode:
            __slots__ = ("name", "family", "node_id")

            def __init__(self, name):
                self.node_id = runtime_mod.next_node_id()
                self.name = f"{name}#{self.node_id}"
                self.family = None

        def node_for(path: str):
            with nodes_mu:
                n = nodes.get(path)
                if n is None:
                    n = nodes[path] = _FlockNode(
                        f"flock:{os.path.basename(path)}")
                return n

        orig_acquire = Flock.acquire
        orig_release = Flock.release

        def acquire(fl, timeout=None):
            instr.state.note_attempt(node_for(fl.path))
            ex = instr.state.explorer
            if ex is not None and ex.drives_current():
                # Cooperative acquire: single-try/yield so the scheduler
                # can run the holder — but the caller's timeout still
                # applies (wall time advances across real switches), so
                # bounded acquires keep raising FlockTimeoutError under
                # the explorer instead of retrying forever: PR 7's
                # best-effort flock-timeout paths stay reachable.
                deadline = (time.monotonic() + timeout
                            if timeout is not None else None)
                while True:
                    instr.state.yield_point(("flock-acquire", fl.path))
                    try:
                        orig_acquire(fl, timeout=0)
                        break
                    except FlockTimeoutError:
                        if (deadline is not None
                                and time.monotonic() >= deadline):
                            raise
                        continue
            else:
                orig_acquire(fl, timeout=timeout)
            instr.state.note_acquire(node_for(fl.path))

        def release(fl):
            instr.state.note_release(node_for(fl.path))
            orig_release(fl)

        self._patch_attr(Flock, "acquire", acquire)
        self._patch_attr(Flock, "release", release)

    def patch_store_queues(self) -> None:
        """Watch queues created by the store become explorer yield points
        (put/get boundaries), without touching store code: the store's
        ``queue`` module reference is swapped for a shim whose Queue is
        instrumented."""
        from k8s_dra_driver_tpu.k8s import store as store_mod

        instr = self

        class SanQueue(_queue_mod.Queue):
            def put_nowait(self, item):
                instr.state.yield_point(("queue", "put"))
                return _queue_mod.Queue.put_nowait(self, item)

            def get_nowait(self):
                instr.state.yield_point(("queue", "get"))
                return _queue_mod.Queue.get_nowait(self)

            def put(self, item, block=True, timeout=None):
                instr.state.yield_point(("queue", "put"))
                return _queue_mod.Queue.put(self, item, block, timeout)

            def get(self, block=True, timeout=None):
                if not block:
                    instr.state.yield_point(("queue", "get"))
                return _queue_mod.Queue.get(self, block, timeout)

        class _QueueShim:
            Queue = SanQueue
            Empty = _queue_mod.Empty
            Full = _queue_mod.Full

        self._patch_attr(store_mod, "queue", _QueueShim)

    def patch_fsync(self) -> None:
        """WAL fsync boundaries become explorer yield points."""
        from k8s_dra_driver_tpu.k8s import persist as persist_mod

        instr = self

        def _fsync(fd: int) -> None:
            instr.state.yield_point(("fsync", ""))
            os.fsync(fd)

        self._patch_attr(persist_mod, "_fsync", _fsync)

    def patch_frozen_mutations(self) -> None:
        """The zero-copy store's freeze seam becomes the
        write-after-publish detector. ``freeze()`` (wrapped in both the
        objects module and the store's imported binding) records which
        thread published each snapshot; ``_frozen_mutation_hook`` — the
        production no-op called immediately before FrozenSnapshotError —
        reports the mutating thread as witness 1 and the publisher as
        witness 2. Publish boundaries are also explorer yield points, so
        the interleaving scheduler can drive a reader between a CAS
        commit and its watch fan-out."""
        from k8s_dra_driver_tpu.k8s import objects as objects_mod
        from k8s_dra_driver_tpu.k8s import store as store_mod

        instr = self
        # id(snapshot) -> (publishing thread, publish stack). Keyed by id:
        # fine for sanitizer runs (bounded below); a reused id after GC
        # could at worst misattribute witness 2 of an already-fatal
        # violation, never invent or hide one.
        publishers: Dict[int, Tuple[str, Tuple[str, ...]]] = {}
        pub_mu = threading.Lock()
        orig_freeze = objects_mod.freeze

        def freeze(obj):
            out = orig_freeze(obj)
            rec = (threading.current_thread().name,
                   runtime_mod.capture_stack(2)
                   if instr.state.capture_stacks else ())
            with pub_mu:
                if len(publishers) > 65536:
                    publishers.clear()
                publishers[id(out)] = rec
            instr.state.yield_point(("freeze", type(obj).__name__))
            return out

        def hook(obj, op: str) -> None:
            if runtime_mod.frozen_mutation_expected():
                return  # a test deliberately poking the seal
            with pub_mu:
                pub = publishers.get(id(obj))
            pub_thread, pub_stack = pub if pub else ("", ())
            instr.state.record(runtime_mod.Violation(
                kind=runtime_mod.WRITE_AFTER_PUBLISH,
                message=(
                    f"attempted `{op}` on a published store snapshot "
                    f"({type(obj).__name__}) — zero-copy reads hand out "
                    f"references; mutate a working copy instead (an "
                    f"update_with_retry closure, .thaw(), or .deepcopy())"),
                thread=threading.current_thread().name,
                stack=(runtime_mod.capture_stack(3)
                       if instr.state.capture_stacks else ()),
                other_thread=pub_thread,
                other_stack=pub_stack,
            ), dedup=(runtime_mod.WRITE_AFTER_PUBLISH,
                      f"{type(obj).__name__}.{op}"))

        self._patch_attr(objects_mod, "_frozen_mutation_hook", hook)
        # store.py binds `freeze` at import time — patch BOTH namespaces
        # so every publish path (create/update/CAS commit/informer cache
        # fill) records its thread.
        self._patch_attr(objects_mod, "freeze", freeze)
        self._patch_attr(store_mod, "freeze", freeze)

    # -- teardown ------------------------------------------------------------

    def undo(self) -> None:
        for obj, name, orig in reversed(self._fn_patches):
            setattr(obj, name, orig)
        self._fn_patches.clear()
        for cls, saved in reversed(self._class_patches):
            for name, orig in saved.items():
                if orig is None:
                    try:
                        delattr(cls, name)
                    except AttributeError:
                        pass
                else:
                    setattr(cls, name, orig)
        self._class_patches.clear()
        self.instrumented_classes.clear()


def install(state: Optional[SanitizerState] = None,
            repo_root: Optional[str] = None,
            modules: Optional[List[str]] = None) -> Instrumentation:
    """Activate tpusan: parse annotations, patch every annotated class,
    and hook the flock/queue/fsync seams. Exactly one installation may be
    active; ``uninstall()`` restores everything."""
    global _active
    if _active is not None:
        raise RuntimeError("tpusan already installed — uninstall() first")
    st = state or SanitizerState()
    instr = Instrumentation(st)
    try:
        for rel in (modules if modules is not None
                    else discover_annotated_modules(repo_root)):
            instr.instrument_module(rel, repo_root=repo_root)
        instr.patch_flocks()
        instr.patch_store_queues()
        instr.patch_fsync()
        instr.patch_frozen_mutations()
    except BaseException:
        instr.undo()
        raise
    _active = instr
    return instr


def uninstall() -> None:
    global _active
    if _active is not None:
        _active.undo()
        _active = None


def current() -> Optional[Instrumentation]:
    return _active


def enabled() -> bool:
    return _active is not None


def env_requested() -> bool:
    """The suite-wide activation switch (`TPU_SAN=1 pytest ...`)."""
    return os.environ.get("TPU_SAN", "") == "1"
