"""Argument parsing + reporting for the tpulint CLI.

Exit codes: 0 clean (stale baseline entries print a burn-down note but
do not fail), 1 error-severity findings survive the baseline, 2
usage/internal error — the convention hack/ci's steps already assume.
"""

from __future__ import annotations

import argparse
import json
import os
import sys
from typing import List, Optional

from k8s_dra_driver_tpu.analysis.engine import (
    SEVERITY_ERROR,
    all_checkers,
    repo_root_default,
    run_analysis,
    save_baseline,
)

DEFAULT_BASELINE = os.path.join("hack", "tpulint_baseline.json")


def build_parser() -> argparse.ArgumentParser:
    p = argparse.ArgumentParser(
        prog="tpulint",
        description="AST-based invariant analyzer for the TPU DRA "
                    "control plane.",
    )
    p.add_argument("paths", nargs="*",
                   help="files/directories to analyze (default: the "
                        "k8s_dra_driver_tpu package)")
    p.add_argument("--jobs", type=int, default=0,
                   help="parallel workers (default: min(8, cpus); "
                        "results are identical at any count)")
    p.add_argument("--select", default="",
                   help="comma-separated rule ids to run (default: all)")
    p.add_argument("--ignore", default="",
                   help="comma-separated rule ids to skip")
    p.add_argument("--baseline", default=None,
                   help=f"baseline file (default: {DEFAULT_BASELINE}; "
                        f"'none' disables)")
    p.add_argument("--update-baseline", action="store_true",
                   help="rewrite the baseline with the current findings "
                        "and exit 0")
    p.add_argument("--list-rules", action="store_true",
                   help="print every registered rule and exit")
    p.add_argument("--format", choices=("text", "json"), default="text")
    p.add_argument("--repo-root", default=None, help=argparse.SUPPRESS)
    return p


def main(argv: Optional[List[str]] = None) -> int:
    args = build_parser().parse_args(argv)

    if args.list_rules:
        for ch in all_checkers():
            print(f"{ch.rule:24s} {ch.description}")
        return 0

    repo_root = args.repo_root or repo_root_default()
    baseline_path: Optional[str]
    if args.baseline == "none":
        baseline_path = None
    elif args.baseline:
        baseline_path = args.baseline
    else:
        baseline_path = os.path.join(repo_root, DEFAULT_BASELINE)

    try:
        result = run_analysis(
            paths=args.paths or None,
            repo_root=repo_root,
            select=[r for r in args.select.split(",") if r] or None,
            ignore=[r for r in args.ignore.split(",") if r] or None,
            jobs=args.jobs or None,
            baseline_path=None if args.update_baseline else baseline_path,
        )
    except ValueError as e:
        print(f"tpulint: {e}", file=sys.stderr)
        return 2

    if args.update_baseline:
        if not baseline_path:
            print("tpulint: --update-baseline needs a baseline path",
                  file=sys.stderr)
            return 2
        save_baseline(baseline_path, result.findings)
        print(f"tpulint: baseline updated with {len(result.findings)} "
              f"finding(s) at {baseline_path}")
        return 0

    if args.format == "json":
        print(json.dumps({
            "files_analyzed": result.files_analyzed,
            "findings": [f.__dict__ for f in result.new_findings],
            "baselined": len(result.findings) - len(result.new_findings),
            "stale_baseline": result.stale_baseline,
        }, indent=1, sort_keys=True))
        return 1 if result.failed else 0

    for f in result.new_findings:
        print(f.render())
    baselined = len(result.findings) - len(result.new_findings)
    if result.stale_baseline:
        n = sum(result.stale_baseline.values())
        print(f"tpulint: note: {n} baseline entr{'y' if n == 1 else 'ies'} "
              f"no longer fire — burn them down with --update-baseline:")
        for fp in sorted(result.stale_baseline):
            print(f"  {fp}")
    errors = sum(1 for f in result.new_findings
                 if f.severity == SEVERITY_ERROR)
    warnings = len(result.new_findings) - errors
    summary = (f"tpulint: {result.files_analyzed} file(s), "
               f"{errors} error(s), {warnings} warning(s)")
    if baselined:
        summary += f", {baselined} baselined"
    print(summary)
    return 1 if result.failed else 0
