"""tpulint CLI: ``python -m k8s_dra_driver_tpu.analysis`` (alias
``hack/tpulint.py``; ``make tpulint`` runs it as the verify gate)."""

from __future__ import annotations

import sys

from k8s_dra_driver_tpu.analysis.cli import main

if __name__ == "__main__":
    sys.exit(main())
