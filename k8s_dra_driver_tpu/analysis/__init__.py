"""tpulint — AST-based invariant analysis for the control plane.

The reference driver leans on `go vet`/staticcheck to keep its control
plane honest; this package is the Python analog for the invariants PRs
1-5 established by convention: CAS closures must be pure (they re-run on
conflict), the checkpoint flock nests under the pu flock, scheduler loops
never rescan the store per item, every API dataclass field round-trips
through the k8s wire codec, metrics and event reasons stay documented and
bounded-cardinality, and lock-guarded state is only mutated under its
lock.

Architecture:

- ``engine.py``     — the analysis driver: per-file parallel checking,
                      ``# tpulint: disable=<rule> -- <reason>``
                      suppressions (reason mandatory), a committed
                      baseline for explicit burn-down, stable ordering.
- ``astutil.py``    — shared AST helpers (parent maps, dotted chains).
- ``checkers/``     — one module per rule; registered via
                      ``@register_checker``.
- ``__main__.py``   — the CLI (``python -m k8s_dra_driver_tpu.analysis``,
                      alias ``hack/tpulint.py``), wired into
                      ``make tpulint`` / ``make verify`` / CI
                      basic-checks.

Runs dependency-free on stdlib ``ast`` so CI needs no new packages.
"""

from k8s_dra_driver_tpu.analysis.engine import (  # noqa: F401
    AnalysisResult,
    Finding,
    all_checkers,
    register_checker,
    run_analysis,
)
