"""Shared AST helpers for tpulint checkers.

Everything here is stdlib-``ast`` only and stateless, so checkers stay
trivially parallelizable across files.
"""

from __future__ import annotations

import ast
import re
from dataclasses import dataclass, field
from typing import Dict, FrozenSet, Iterator, List, Optional, Sequence, Tuple

# Shared vocabulary between the metric/event checkers and the doc-sync
# rules — one definition so the pairs can't silently diverge.
METRIC_CTORS = frozenset({"Counter", "Gauge", "Histogram"})
CAMEL_CASE = re.compile(r"^[A-Z][A-Za-z0-9]*$")

# -- the tpulint lock-annotation vocabulary ----------------------------------
#
# ONE definition consumed by the static checkers (thread-shared-state,
# shard-lock, lock-order, sleep-under-lock) AND the runtime sanitizer
# (analysis/sanitizer): what `# tpulint: guarded-by=` declares statically
# is exactly what tpusan enforces dynamically, so the two halves can never
# drift on what the annotations mean.

GUARDED_RE = re.compile(r"#\s*tpulint:\s*guarded-by=([A-Za-z_][A-Za-z0-9_]*)")
# The value char class includes '-' so lock-order's `holds=pu-flock`
# captures whole and can never prefix-match a lock attr named `pu`
# (attribute names cannot contain '-', so the exact compare rejects it).
HOLDS_RE = re.compile(r"#\s*tpulint:\s*holds=([A-Za-z_][A-Za-z0-9_\-]*)")
ORDERED_RE = re.compile(r"#\s*tpulint:\s*ordered-acquire")

# Standard container mutators: calling one of these on a guarded attribute
# is a mutation of that attribute's state.
MUTATORS = frozenset({
    "append", "add", "insert", "extend", "remove", "discard", "pop",
    "popitem", "clear", "update", "setdefault", "appendleft", "popleft",
})


@dataclass(frozen=True)
class FunctionAnnotation:
    """One function's lock contract, read off its signature lines (the
    line above the ``def`` through the first body statement): the locks a
    ``# tpulint: holds=<lock>`` declares its callers provide, and whether
    it is a sanctioned ``# tpulint: ordered-acquire`` multi-lock helper."""

    name: str
    lineno: int          # the def's line
    end_lineno: int      # last line of the body
    holds: FrozenSet[str] = frozenset()
    ordered_acquire: bool = False


@dataclass(frozen=True)
class ModuleAnnotations:
    """Every tpulint lock annotation in one module, in one structure.

    - ``class_guards``: class name -> {attr -> lock attr} from
      ``self.X = ...  # tpulint: guarded-by=Y`` (or bare ``X: ...`` class
      fields) inside the class span.
    - ``file_guards``: attr -> lock attr over the whole file — the
      shard-lock view, where an attr declared guarded in ANY class of the
      file binds external accesses too.
    - ``functions``: per-def holds/ordered-acquire contracts, keyed for
      lookup by (name, def lineno).
    """

    class_guards: Dict[str, Dict[str, str]] = field(default_factory=dict)
    file_guards: Dict[str, str] = field(default_factory=dict)
    functions: Tuple[FunctionAnnotation, ...] = ()

    @property
    def lock_attrs(self) -> FrozenSet[str]:
        """Every lock attribute name any guard in the file names."""
        return frozenset(self.file_guards.values())

    def function_at(self, name: str, lineno: int) -> Optional[FunctionAnnotation]:
        for fa in self.functions:
            if fa.name == name and fa.lineno == lineno:
                return fa
        return None

    def fn_holds(self, fn: Optional[ast.AST]) -> FrozenSet[str]:
        """Lock names the enclosing def's ``holds=`` annotation declares
        (empty for lambdas / un-annotated functions)."""
        if fn is None or isinstance(fn, ast.Lambda):
            return frozenset()
        fa = self.function_at(getattr(fn, "name", ""), fn.lineno)
        return fa.holds if fa is not None else frozenset()

    def fn_ordered(self, fn: Optional[ast.AST]) -> bool:
        """The enclosing def is the sanctioned ordered-acquire helper."""
        if fn is None or isinstance(fn, ast.Lambda):
            return False
        fa = self.function_at(getattr(fn, "name", ""), fn.lineno)
        return fa.ordered_acquire if fa is not None else False

    def ordered_functions(self) -> List[FunctionAnnotation]:
        return [fa for fa in self.functions if fa.ordered_acquire]


def _line(lines: Sequence[str], lineno: int) -> str:
    """1-based physical line, empty string out of range."""
    if 1 <= lineno <= len(lines):
        return lines[lineno - 1]
    return ""


_GUARD_TARGET_RE = re.compile(r"(?:self\.)?([A-Za-z_][A-Za-z0-9_]*)\s*[:=]")


def parse_annotations(tree: ast.AST, lines: Sequence[str]) -> ModuleAnnotations:
    """Parse every tpulint lock annotation in one parsed module. This is
    THE annotation reader: the static checkers and the runtime sanitizer
    both call it, so a parser change moves both in lockstep (pinned by
    the annotation-drift test)."""
    class_guards: Dict[str, Dict[str, str]] = {}
    file_guards: Dict[str, str] = {}
    for node in ast.walk(tree):
        if not isinstance(node, ast.ClassDef):
            continue
        end = max((n.end_lineno or n.lineno for n in ast.walk(node)
                   if hasattr(n, "lineno")), default=node.lineno)
        guards: Dict[str, str] = {}
        for lineno in range(node.lineno, end + 1):
            text = _line(lines, lineno)
            m = GUARDED_RE.search(text)
            if not m:
                continue
            am = _GUARD_TARGET_RE.search(text)
            if am:
                guards[am.group(1)] = m.group(1)
        if guards:
            class_guards[node.name] = guards
    # File-wide view: any guarded-by line anywhere (module-level state
    # included), matching the shard-lock discovery shape.
    for lineno in range(1, len(lines) + 1):
        text = _line(lines, lineno)
        m = GUARDED_RE.search(text)
        if not m:
            continue
        am = _GUARD_TARGET_RE.search(text)
        if am:
            file_guards[am.group(1)] = m.group(1)

    functions: List[FunctionAnnotation] = []
    for node in ast.walk(tree):
        if not isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            continue
        first_stmt = node.body[0].lineno if node.body else node.lineno
        holds = set()
        ordered = False
        for n in range(max(1, node.lineno - 1), first_stmt + 1):
            text = _line(lines, n)
            hm = HOLDS_RE.search(text)
            if hm:
                holds.add(hm.group(1))
            if ORDERED_RE.search(text):
                ordered = True
        if holds or ordered:
            functions.append(FunctionAnnotation(
                name=node.name, lineno=node.lineno,
                end_lineno=node.end_lineno or node.lineno,
                holds=frozenset(holds), ordered_acquire=ordered))
    return ModuleAnnotations(class_guards=class_guards,
                             file_guards=file_guards,
                             functions=tuple(functions))


def parse_annotations_text(text: str, filename: str = "<module>") -> ModuleAnnotations:
    """Annotation view of raw source text (the sanitizer's entry: it reads
    module files straight off disk, no SourceFile needed)."""
    return parse_annotations(ast.parse(text, filename=filename),
                             text.splitlines())


def dotted(node: ast.AST) -> str:
    """Render an attribute chain as a dotted string.

    ``self._pu_lock.hold`` -> ``"self._pu_lock.hold"``;
    intermediate calls collapse to ``()``: ``Flock(p).hold`` ->
    ``"().hold"``. Unrenderable bases become ``"?"``.
    """
    parts: List[str] = []
    while isinstance(node, ast.Attribute):
        parts.append(node.attr)
        node = node.value
    if isinstance(node, ast.Name):
        parts.append(node.id)
    elif isinstance(node, ast.Call):
        parts.append("()")
    else:
        parts.append("?")
    return ".".join(reversed(parts))


def call_chain(call: ast.Call) -> str:
    """Dotted chain of a call's function expression."""
    return dotted(call.func)


def receiver_chain(call: ast.Call) -> str:
    """Dotted chain of the receiver of a method call (empty for plain
    function calls): ``self.api.list(...)`` -> ``"self.api"``."""
    if isinstance(call.func, ast.Attribute):
        return dotted(call.func.value)
    return ""


def build_parents(tree: ast.AST) -> Dict[ast.AST, ast.AST]:
    """node -> parent map for one module tree."""
    parents: Dict[ast.AST, ast.AST] = {}
    for node in ast.walk(tree):
        for child in ast.iter_child_nodes(node):
            parents[child] = node
    return parents


def ancestors(node: ast.AST, parents: Dict[ast.AST, ast.AST]) -> Iterator[ast.AST]:
    """Walk from ``node``'s parent up to the module root."""
    cur = parents.get(node)
    while cur is not None:
        yield cur
        cur = parents.get(cur)


def enclosing_function(
    node: ast.AST, parents: Dict[ast.AST, ast.AST]
) -> Optional[ast.AST]:
    """Nearest enclosing FunctionDef/AsyncFunctionDef/Lambda, or None."""
    for anc in ancestors(node, parents):
        if isinstance(anc, (ast.FunctionDef, ast.AsyncFunctionDef, ast.Lambda)):
            return anc
    return None


def enclosing_class(
    node: ast.AST, parents: Dict[ast.AST, ast.AST]
) -> Optional[ast.ClassDef]:
    for anc in ancestors(node, parents):
        if isinstance(anc, ast.ClassDef):
            return anc
    return None


def in_loop_body(node: ast.AST, parents: Dict[ast.AST, ast.AST]) -> bool:
    """True when ``node`` runs once per loop iteration: anywhere under a
    ``while`` (its test re-evaluates every iteration too), or in a
    ``for``'s body/orelse — the ``for`` iterable and target evaluate
    once, so they're exempt."""
    prev: ast.AST = node
    for anc in ancestors(node, parents):
        if isinstance(anc, ast.For):
            if prev is not anc.iter and prev is not anc.target:
                return True
        elif isinstance(anc, ast.While):
            return True
        prev = anc
    return False


def with_ancestors(
    node: ast.AST, parents: Dict[ast.AST, ast.AST]
) -> Iterator[ast.With]:
    """Every ``with`` statement lexically containing ``node``."""
    for anc in ancestors(node, parents):
        if isinstance(anc, ast.With):
            yield anc


def string_constants(node: ast.AST) -> Iterator[str]:
    """Every string literal anywhere under ``node``."""
    for sub in ast.walk(node):
        if isinstance(sub, ast.Constant) and isinstance(sub.value, str):
            yield sub.value


def const_str(node: Optional[ast.AST]) -> Optional[str]:
    """The value of a string-literal node, else None."""
    if isinstance(node, ast.Constant) and isinstance(node.value, str):
        return node.value
    return None


def dataclass_fields(cls: ast.ClassDef) -> List[ast.AnnAssign]:
    """Annotated assignments directly in a class body — dataclass fields
    (includes un-defaulted annotations)."""
    out = []
    for stmt in cls.body:
        if isinstance(stmt, ast.AnnAssign) and isinstance(stmt.target, ast.Name):
            out.append(stmt)
    return out


def find_classes(tree: ast.AST) -> Dict[str, ast.ClassDef]:
    return {
        n.name: n for n in ast.walk(tree) if isinstance(n, ast.ClassDef)
    }


def iter_metric_registrations(
    tree: ast.AST,
) -> Iterator[Tuple[str, ast.Call]]:
    """Every ``Counter/Gauge/Histogram("<literal name>", ...)`` call —
    the only way metrics are registered in this codebase."""
    for node in ast.walk(tree):
        if (isinstance(node, ast.Call) and isinstance(node.func, ast.Name)
                and node.func.id in METRIC_CTORS and node.args
                and isinstance(node.args[0], ast.Constant)
                and isinstance(node.args[0].value, str)):
            yield node.args[0].value, node


def iter_reason_constants(
    tree: ast.AST,
) -> Iterator[Tuple[str, ast.Assign]]:
    """Every ``REASON_* = "<literal>"`` assignment — the sanctioned
    event-reason catalog shape."""
    for node in ast.walk(tree):
        if not isinstance(node, ast.Assign):
            continue
        if not (isinstance(node.value, ast.Constant)
                and isinstance(node.value.value, str)):
            continue
        for tgt in node.targets:
            if isinstance(tgt, ast.Name) and tgt.id.startswith("REASON_"):
                yield node.value.value, node
                break


def find_functions(tree: ast.AST) -> Dict[str, ast.FunctionDef]:
    """Top-level + nested FunctionDefs by name; first definition wins on
    duplicates (fine for the codec-module lookups this backs)."""
    out: Dict[str, ast.FunctionDef] = {}
    for n in ast.walk(tree):
        if isinstance(n, ast.FunctionDef) and n.name not in out:
            out[n.name] = n
    return out
