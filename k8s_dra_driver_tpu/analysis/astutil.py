"""Shared AST helpers for tpulint checkers.

Everything here is stdlib-``ast`` only and stateless, so checkers stay
trivially parallelizable across files.
"""

from __future__ import annotations

import ast
import re
from typing import Dict, Iterator, List, Optional, Tuple

# Shared vocabulary between the metric/event checkers and the doc-sync
# rules — one definition so the pairs can't silently diverge.
METRIC_CTORS = frozenset({"Counter", "Gauge", "Histogram"})
CAMEL_CASE = re.compile(r"^[A-Z][A-Za-z0-9]*$")


def dotted(node: ast.AST) -> str:
    """Render an attribute chain as a dotted string.

    ``self._pu_lock.hold`` -> ``"self._pu_lock.hold"``;
    intermediate calls collapse to ``()``: ``Flock(p).hold`` ->
    ``"().hold"``. Unrenderable bases become ``"?"``.
    """
    parts: List[str] = []
    while isinstance(node, ast.Attribute):
        parts.append(node.attr)
        node = node.value
    if isinstance(node, ast.Name):
        parts.append(node.id)
    elif isinstance(node, ast.Call):
        parts.append("()")
    else:
        parts.append("?")
    return ".".join(reversed(parts))


def call_chain(call: ast.Call) -> str:
    """Dotted chain of a call's function expression."""
    return dotted(call.func)


def receiver_chain(call: ast.Call) -> str:
    """Dotted chain of the receiver of a method call (empty for plain
    function calls): ``self.api.list(...)`` -> ``"self.api"``."""
    if isinstance(call.func, ast.Attribute):
        return dotted(call.func.value)
    return ""


def build_parents(tree: ast.AST) -> Dict[ast.AST, ast.AST]:
    """node -> parent map for one module tree."""
    parents: Dict[ast.AST, ast.AST] = {}
    for node in ast.walk(tree):
        for child in ast.iter_child_nodes(node):
            parents[child] = node
    return parents


def ancestors(node: ast.AST, parents: Dict[ast.AST, ast.AST]) -> Iterator[ast.AST]:
    """Walk from ``node``'s parent up to the module root."""
    cur = parents.get(node)
    while cur is not None:
        yield cur
        cur = parents.get(cur)


def enclosing_function(
    node: ast.AST, parents: Dict[ast.AST, ast.AST]
) -> Optional[ast.AST]:
    """Nearest enclosing FunctionDef/AsyncFunctionDef/Lambda, or None."""
    for anc in ancestors(node, parents):
        if isinstance(anc, (ast.FunctionDef, ast.AsyncFunctionDef, ast.Lambda)):
            return anc
    return None


def enclosing_class(
    node: ast.AST, parents: Dict[ast.AST, ast.AST]
) -> Optional[ast.ClassDef]:
    for anc in ancestors(node, parents):
        if isinstance(anc, ast.ClassDef):
            return anc
    return None


def in_loop_body(node: ast.AST, parents: Dict[ast.AST, ast.AST]) -> bool:
    """True when ``node`` runs once per loop iteration: anywhere under a
    ``while`` (its test re-evaluates every iteration too), or in a
    ``for``'s body/orelse — the ``for`` iterable and target evaluate
    once, so they're exempt."""
    prev: ast.AST = node
    for anc in ancestors(node, parents):
        if isinstance(anc, ast.For):
            if prev is not anc.iter and prev is not anc.target:
                return True
        elif isinstance(anc, ast.While):
            return True
        prev = anc
    return False


def with_ancestors(
    node: ast.AST, parents: Dict[ast.AST, ast.AST]
) -> Iterator[ast.With]:
    """Every ``with`` statement lexically containing ``node``."""
    for anc in ancestors(node, parents):
        if isinstance(anc, ast.With):
            yield anc


def string_constants(node: ast.AST) -> Iterator[str]:
    """Every string literal anywhere under ``node``."""
    for sub in ast.walk(node):
        if isinstance(sub, ast.Constant) and isinstance(sub.value, str):
            yield sub.value


def const_str(node: Optional[ast.AST]) -> Optional[str]:
    """The value of a string-literal node, else None."""
    if isinstance(node, ast.Constant) and isinstance(node.value, str):
        return node.value
    return None


def dataclass_fields(cls: ast.ClassDef) -> List[ast.AnnAssign]:
    """Annotated assignments directly in a class body — dataclass fields
    (includes un-defaulted annotations)."""
    out = []
    for stmt in cls.body:
        if isinstance(stmt, ast.AnnAssign) and isinstance(stmt.target, ast.Name):
            out.append(stmt)
    return out


def find_classes(tree: ast.AST) -> Dict[str, ast.ClassDef]:
    return {
        n.name: n for n in ast.walk(tree) if isinstance(n, ast.ClassDef)
    }


def iter_metric_registrations(
    tree: ast.AST,
) -> Iterator[Tuple[str, ast.Call]]:
    """Every ``Counter/Gauge/Histogram("<literal name>", ...)`` call —
    the only way metrics are registered in this codebase."""
    for node in ast.walk(tree):
        if (isinstance(node, ast.Call) and isinstance(node.func, ast.Name)
                and node.func.id in METRIC_CTORS and node.args
                and isinstance(node.args[0], ast.Constant)
                and isinstance(node.args[0].value, str)):
            yield node.args[0].value, node


def iter_reason_constants(
    tree: ast.AST,
) -> Iterator[Tuple[str, ast.Assign]]:
    """Every ``REASON_* = "<literal>"`` assignment — the sanctioned
    event-reason catalog shape."""
    for node in ast.walk(tree):
        if not isinstance(node, ast.Assign):
            continue
        if not (isinstance(node.value, ast.Constant)
                and isinstance(node.value.value, str)):
            continue
        for tgt in node.targets:
            if isinstance(tgt, ast.Name) and tgt.id.startswith("REASON_"):
                yield node.value.value, node
                break


def find_functions(tree: ast.AST) -> Dict[str, ast.FunctionDef]:
    """Top-level + nested FunctionDefs by name; first definition wins on
    duplicates (fine for the codec-module lookups this backs)."""
    out: Dict[str, ast.FunctionDef] = {}
    for n in ast.walk(tree):
        if isinstance(n, ast.FunctionDef) and n.name not in out:
            out[n.name] = n
    return out
