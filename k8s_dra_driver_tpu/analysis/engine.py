"""The tpulint analysis engine.

Pipeline:

1. discover Python files under the requested paths (default: the
   ``k8s_dra_driver_tpu`` package),
2. per file, in parallel: parse once, run every selected checker's
   ``check_file``/``collect``,
3. serially, in registration order: run each checker's ``finalize`` with
   the per-file facts (cross-file rules: wire drift, doc sync),
4. apply ``# tpulint: disable=<rule> -- <reason>`` line suppressions
   (a suppression without a reason is itself a finding),
5. subtract the committed baseline; anything left fails.

Findings sort by (file, line, col, rule, message) so output is stable
regardless of worker count — pinned by the determinism test.
"""

from __future__ import annotations

import ast
import concurrent.futures
import json
import os
import re
import threading
from dataclasses import dataclass, field
from typing import Any, Dict, Iterable, List, Optional, Sequence, Tuple

SEVERITY_ERROR = "error"
SEVERITY_WARNING = "warning"

# Meta-rules the engine itself owns.
RULE_SUPPRESSION = "suppression"      # disable= comment without a reason
RULE_PARSE = "parse-error"            # file failed to parse

_SUPPRESS_RE = re.compile(
    r"#\s*tpulint:\s*disable=([A-Za-z0-9_,\-]+)(?:\s*--\s*(\S[^#]*?))?\s*$"
)


@dataclass(frozen=True)
class Finding:
    """One rule violation. ``file`` is repo-relative POSIX."""

    file: str
    line: int
    col: int
    rule: str
    message: str
    hint: str = ""
    severity: str = SEVERITY_ERROR

    def sort_key(self) -> Tuple:
        return (self.file, self.line, self.col, self.rule, self.message)

    def fingerprint(self) -> str:
        """Line-independent identity used for baseline matching, so
        unrelated edits shifting line numbers don't churn the baseline."""
        return f"{self.rule}::{self.file}::{self.message}"

    def render(self) -> str:
        loc = f"{self.file}:{self.line}:{self.col}"
        out = f"{loc}: {self.severity}: [{self.rule}] {self.message}"
        if self.hint:
            out += f"\n    hint: {self.hint}"
        return out


class SourceFile:
    """One parsed Python file, shared read-only across checkers."""

    def __init__(self, path: str, rel: str, text: str):
        self.path = path
        self.rel = rel
        self.text = text
        self.lines = text.splitlines()
        self.tree = ast.parse(text, filename=path)
        self._parents: Optional[Dict[ast.AST, ast.AST]] = None
        self._annotations = None

    @property
    def parents(self) -> Dict[ast.AST, ast.AST]:
        if self._parents is None:
            from k8s_dra_driver_tpu.analysis.astutil import build_parents

            self._parents = build_parents(self.tree)
        return self._parents

    @property
    def annotations(self):
        """The module's tpulint lock annotations (astutil.ModuleAnnotations),
        parsed once and shared by every checker that reads them — the same
        parser the runtime sanitizer loads, so static and dynamic halves
        see one annotation set."""
        if self._annotations is None:
            from k8s_dra_driver_tpu.analysis.astutil import parse_annotations

            self._annotations = parse_annotations(self.tree, self.lines)
        return self._annotations

    def line(self, lineno: int) -> str:
        """1-based physical line, empty string out of range."""
        if 1 <= lineno <= len(self.lines):
            return self.lines[lineno - 1]
        return ""


class Checker:
    """Base checker. Subclasses set ``rule``/``description`` and override
    ``check_file`` (per-file findings), ``collect`` (per-file facts for
    cross-file rules), and/or ``finalize`` (runs once, serially, with
    every file's fact). Checkers must be stateless across files —
    ``check_file``/``collect`` run concurrently."""

    rule: str = ""
    description: str = ""
    hint: str = ""
    # Repo-relative directory prefixes the per-file phase applies to.
    # None = every analyzed file. Files outside the package (fixtures)
    # always get every selected checker, so fixture tests exercise rules
    # scoped to sim/ or plugins/ without recreating those trees.
    scope: Optional[Tuple[str, ...]] = None

    def applies_to(self, rel: str) -> bool:
        if self.scope is None or not rel.startswith("k8s_dra_driver_tpu/"):
            return True
        return any(rel.startswith(p) for p in self.scope)

    def check_file(self, sf: SourceFile) -> List[Finding]:
        return []

    def collect(self, sf: SourceFile) -> Any:
        return None

    def finalize(self, project: "Project",
                 facts: List[Tuple[str, Any]]) -> List[Finding]:
        return []

    # -- convenience ---------------------------------------------------------

    def finding(self, sf_or_rel, node_or_line, message: str,
                hint: str = "", severity: str = SEVERITY_ERROR) -> Finding:
        if isinstance(sf_or_rel, SourceFile):
            rel = sf_or_rel.rel
        else:
            rel = sf_or_rel
        if isinstance(node_or_line, ast.AST):
            line = getattr(node_or_line, "lineno", 1)
            col = getattr(node_or_line, "col_offset", 0)
        else:
            line, col = int(node_or_line), 0
        return Finding(file=rel, line=line, col=col, rule=self.rule,
                       message=message, hint=hint or self.hint,
                       severity=severity)


_CHECKER_CLASSES: List[type] = []


def register_checker(cls: type) -> type:
    """Class decorator: adds the checker to the default registry."""
    if not getattr(cls, "rule", ""):
        raise ValueError(f"checker {cls.__name__} has no rule id")
    _CHECKER_CLASSES.append(cls)
    return cls


def all_checkers() -> List[Checker]:
    """Fresh instances of every registered checker, in registration
    order (importing the checkers package registers them)."""
    import k8s_dra_driver_tpu.analysis.checkers  # noqa: F401 — registration

    return [cls() for cls in _CHECKER_CLASSES]


# -- suppressions ------------------------------------------------------------


@dataclass
class Suppression:
    line: int
    rules: Tuple[str, ...]
    reason: str


def parse_suppressions(lines: Sequence[str]) -> List[Suppression]:
    out = []
    for i, text in enumerate(lines, start=1):
        m = _SUPPRESS_RE.search(text)
        if not m:
            continue
        rules = tuple(r.strip() for r in m.group(1).split(",") if r.strip())
        reason = (m.group(2) or "").strip()
        out.append(Suppression(line=i, rules=rules, reason=reason))
    return out


def apply_suppressions(
    findings: List[Finding], by_file: Dict[str, List[Suppression]]
) -> List[Finding]:
    """Drop findings a same-line ``disable=`` covers; emit a finding for
    every suppression that carries no reason (reasons are mandatory —
    an unexplained disable is exactly the silent rot tpulint exists to
    stop)."""
    out: List[Finding] = []
    for f in findings:
        sups = by_file.get(f.file, [])
        covered = any(
            s.line == f.line and (f.rule in s.rules or "all" in s.rules)
            and s.reason
            for s in sups
        )
        if not covered:
            out.append(f)
    for rel, sups in by_file.items():
        for s in sups:
            if not s.reason:
                out.append(Finding(
                    file=rel, line=s.line, col=0, rule=RULE_SUPPRESSION,
                    message=(
                        f"suppression of {', '.join(s.rules)} carries no "
                        f"reason (write `# tpulint: disable=<rule> -- why`)"
                    ),
                ))
    return out


# -- baseline ----------------------------------------------------------------


def load_baseline(path: str) -> Dict[str, int]:
    """fingerprint -> allowed count."""
    with open(path, encoding="utf-8") as f:
        doc = json.load(f)
    counts: Dict[str, int] = {}
    for e in doc.get("findings", []):
        fp = f"{e['rule']}::{e['file']}::{e['message']}"
        counts[fp] = counts.get(fp, 0) + int(e.get("count", 1))
    return counts


def save_baseline(path: str, findings: Iterable[Finding]) -> None:
    counts: Dict[Tuple[str, str, str], int] = {}
    for f in findings:
        key = (f.rule, f.file, f.message)
        counts[key] = counts.get(key, 0) + 1
    doc = {
        "version": 1,
        "findings": [
            {"rule": r, "file": fl, "message": m, "count": c}
            for (r, fl, m), c in sorted(counts.items())
        ],
    }
    with open(path, "w", encoding="utf-8") as f:
        json.dump(doc, f, indent=1, sort_keys=True)
        f.write("\n")


def subtract_baseline(
    findings: List[Finding], baseline: Dict[str, int]
) -> Tuple[List[Finding], Dict[str, int]]:
    """Returns (new findings, stale baseline entries). Count-aware: N
    baselined occurrences absorb the first N findings of that identity;
    the N+1st fails."""
    budget = dict(baseline)
    new: List[Finding] = []
    for f in findings:
        fp = f.fingerprint()
        if budget.get(fp, 0) > 0:
            budget[fp] -= 1
        else:
            new.append(f)
    stale = {fp: n for fp, n in budget.items() if n > 0}
    return new, stale


# -- project / discovery -----------------------------------------------------


def repo_root_default() -> str:
    """The repo checkout containing this package."""
    pkg = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    return os.path.dirname(pkg)


class Project:
    """Read-only repo view handed to ``finalize`` — cross-file rules pull
    in files (codecs, docs pages) that may sit outside the analyzed
    path set."""

    def __init__(self, repo_root: str, analyzed: Sequence[str] = ()):
        self.repo_root = repo_root
        # rel paths of the files this run analyzed — lets finalize rules
        # that need a COMPLETE inventory (stale-doc detection) bail when
        # the run covered only a slice of the package.
        self.analyzed = frozenset(analyzed)
        self._sources: Dict[str, Optional[SourceFile]] = {}
        self._mu = threading.Lock()

    def abspath(self, rel: str) -> str:
        return os.path.join(self.repo_root, rel.replace("/", os.sep))

    def read(self, rel: str) -> Optional[str]:
        try:
            with open(self.abspath(rel), encoding="utf-8") as f:
                return f.read()
        except OSError:
            return None

    def source(self, rel: str) -> Optional[SourceFile]:
        with self._mu:
            if rel not in self._sources:
                text = self.read(rel)
                try:
                    self._sources[rel] = (
                        SourceFile(self.abspath(rel), rel, text)
                        if text is not None else None
                    )
                except (SyntaxError, ValueError):
                    # same failure classes _analyze_one absorbs (ValueError:
                    # e.g. null bytes) — finalize rules see None and report
                    # an unparseable-module finding instead of crashing
                    self._sources[rel] = None
            return self._sources[rel]


def discover_files(paths: Sequence[str], repo_root: str) -> List[Tuple[str, str]]:
    """(abspath, rel) for every .py under ``paths``, sorted by rel."""
    seen: Dict[str, str] = {}
    for p in paths:
        p = os.path.abspath(p)
        if os.path.isfile(p):
            candidates = [p]
        else:
            candidates = []
            for dirpath, dirnames, filenames in os.walk(p):
                dirnames[:] = [d for d in dirnames if d != "__pycache__"]
                candidates.extend(
                    os.path.join(dirpath, fn)
                    for fn in filenames if fn.endswith(".py")
                )
        for c in candidates:
            rel = os.path.relpath(c, repo_root).replace(os.sep, "/")
            seen[rel] = c
    return sorted((abs_, rel) for rel, abs_ in seen.items())


# -- the run -----------------------------------------------------------------


@dataclass
class AnalysisResult:
    findings: List[Finding] = field(default_factory=list)   # post-suppression
    new_findings: List[Finding] = field(default_factory=list)  # post-baseline
    stale_baseline: Dict[str, int] = field(default_factory=dict)
    files_analyzed: int = 0

    @property
    def failed(self) -> bool:
        return any(f.severity == SEVERITY_ERROR for f in self.new_findings)


def _analyze_one(
    path: str, rel: str, checkers: List[Checker]
) -> Tuple[str, List[Finding], List[Suppression], Dict[str, Any]]:
    try:
        with open(path, encoding="utf-8") as f:
            text = f.read()
        sf = SourceFile(path, rel, text)
    except (OSError, SyntaxError, ValueError) as e:
        line = getattr(e, "lineno", 1) or 1
        return rel, [Finding(file=rel, line=line, col=0, rule=RULE_PARSE,
                             message=f"cannot analyze: {e}")], [], {}
    findings: List[Finding] = []
    facts: Dict[str, Any] = {}
    for ch in checkers:
        if not ch.applies_to(rel):
            continue
        findings.extend(ch.check_file(sf))
        fact = ch.collect(sf)
        if fact is not None:
            facts[ch.rule] = fact
    return rel, findings, parse_suppressions(sf.lines), facts


def run_analysis(
    paths: Optional[Sequence[str]] = None,
    repo_root: Optional[str] = None,
    checkers: Optional[List[Checker]] = None,
    select: Optional[Sequence[str]] = None,
    ignore: Optional[Sequence[str]] = None,
    jobs: Optional[int] = None,
    baseline_path: Optional[str] = None,
) -> AnalysisResult:
    """Run the engine. ``baseline_path=None`` means no baseline."""
    repo_root = repo_root or repo_root_default()
    if paths is None:
        paths = [os.path.join(repo_root, "k8s_dra_driver_tpu")]
    checkers = list(checkers) if checkers is not None else all_checkers()
    if select:
        wanted = set(select)
        unknown = wanted - {c.rule for c in checkers}
        if unknown:
            raise ValueError(f"unknown rule(s): {', '.join(sorted(unknown))}")
        checkers = [c for c in checkers if c.rule in wanted]
    if ignore:
        checkers = [c for c in checkers if c.rule not in set(ignore)]

    files = discover_files(paths, repo_root)
    jobs = jobs or min(8, (os.cpu_count() or 2))

    per_file: Dict[str, Tuple[List[Finding], List[Suppression], Dict[str, Any]]] = {}
    if jobs <= 1 or len(files) <= 1:
        for path, rel in files:
            rel_, fnd, sups, facts = _analyze_one(path, rel, checkers)
            per_file[rel_] = (fnd, sups, facts)
    else:
        with concurrent.futures.ThreadPoolExecutor(max_workers=jobs) as ex:
            futs = [ex.submit(_analyze_one, path, rel, checkers)
                    for path, rel in files]
            for fut in concurrent.futures.as_completed(futs):
                rel_, fnd, sups, facts = fut.result()
                per_file[rel_] = (fnd, sups, facts)

    findings: List[Finding] = []
    suppressions: Dict[str, List[Suppression]] = {}
    for rel in sorted(per_file):
        fnd, sups, _facts = per_file[rel]
        findings.extend(fnd)
        if sups:
            suppressions[rel] = sups

    project = Project(repo_root, analyzed=sorted(per_file))
    for ch in checkers:
        facts = [(rel, per_file[rel][2][ch.rule])
                 for rel in sorted(per_file) if ch.rule in per_file[rel][2]]
        findings.extend(ch.finalize(project, facts))

    # Finalize findings may target files outside the analyzed set (the
    # codec, a dataclass module) — honor suppressions written there too.
    for f in findings:
        if f.file not in suppressions and f.file not in per_file:
            text = project.read(f.file)
            suppressions[f.file] = (
                parse_suppressions(text.splitlines()) if text else []
            )

    findings = apply_suppressions(findings, suppressions)
    findings.sort(key=Finding.sort_key)

    result = AnalysisResult(findings=findings, files_analyzed=len(files))
    if baseline_path and os.path.exists(baseline_path):
        baseline = load_baseline(baseline_path)
        result.new_findings, result.stale_baseline = subtract_baseline(
            findings, baseline)
    else:
        result.new_findings = list(findings)
    return result
