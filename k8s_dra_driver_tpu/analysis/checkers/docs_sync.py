"""metrics-docs / event-reasons: the doc pages track the code.

The AST successors of ``hack/check_metrics_docs.py`` and
``hack/check_event_reasons.py`` (now shims over these rules):

- **metrics-docs**: every ``Counter``/``Gauge``/``Histogram`` registered
  with a literal name must appear in ``docs/reference/metrics.md``;
  documented ``tpu_dra_*`` names nothing registers are warnings (prose
  may legitimately reference derived ``_bucket``/``_sum``/``_count``
  series, which are exempt).
- **event-reasons**: every ``REASON_*`` constant and literal
  ``reason="..."`` keyword must be CamelCase and catalogued in
  ``docs/reference/events.md``.

Both are collect/finalize rules: the per-file phase gathers names in
parallel (via the SAME astutil matchers metric-discipline and
event-discipline use, so the pairs can't diverge), the finalize phase
reads the doc page once. Inventory-wide checks — stale documented names,
and the old scripts' "found nothing at all: scanner broken?" guard —
only run when the run actually covered the package (gated on the
registering module being in the analyzed set), so single-file and
fixture runs stay meaningful.
"""

from __future__ import annotations

import ast
import re
from typing import List, Tuple

from k8s_dra_driver_tpu.analysis.astutil import (
    CAMEL_CASE,
    iter_metric_registrations,
    iter_reason_constants,
)
from k8s_dra_driver_tpu.analysis.engine import (
    Checker,
    Finding,
    Project,
    SEVERITY_WARNING,
    SourceFile,
    register_checker,
)

_DOC_METRIC_RE = re.compile(r"`(tpu_dra_[a-zA-Z0-9_:]*)`")
_DERIVED_SUFFIXES = ("_bucket", "_sum", "_count")


@register_checker
class MetricsDocsChecker(Checker):
    rule = "metrics-docs"
    description = ("every registered tpu_dra_* metric is documented in "
                   "docs/reference/metrics.md")
    hint = "add the metric to docs/reference/metrics.md"
    # The module whose presence in the analyzed set marks a run as
    # package-wide — the precondition for inventory-level checks.
    _IMPL = "k8s_dra_driver_tpu/pkg/metrics.py"

    def __init__(self, doc_rel: str = "docs/reference/metrics.md"):
        self.doc_rel = doc_rel

    def collect(self, sf: SourceFile):
        names = [(name, node.lineno)
                 for name, node in iter_metric_registrations(sf.tree)]
        return names or None

    def finalize(self, project: Project, facts) -> List[Finding]:
        body = project.read(self.doc_rel)
        if body is None:
            return [self.finding(self.doc_rel, 1,
                                 f"{self.doc_rel} missing")]
        findings: List[Finding] = []
        full_run = self._IMPL in project.analyzed
        registered = set()
        for rel, names in facts:
            for name, lineno in names:
                registered.add(name)
                if f"`{name}`" not in body:
                    findings.append(self.finding(
                        rel, lineno,
                        f"metric {name!r} registered here but missing "
                        f"from {self.doc_rel}"))
        if not full_run:
            return findings
        if not registered:
            # The old standalone script's exit-2 guard: a package-wide
            # run that sees ZERO registrations means the scanner pattern
            # rotted, not that the code went metric-free.
            findings.append(self.finding(
                self._IMPL, 1,
                "no metric registrations found in a package-wide run — "
                "scanner broken?"))
            return findings
        for doc_name in sorted(set(_DOC_METRIC_RE.findall(body))):
            if doc_name in registered:
                continue
            if any(doc_name.endswith(s)
                   and doc_name[: -len(s)] in registered
                   for s in _DERIVED_SUFFIXES):
                continue
            findings.append(self.finding(
                self.doc_rel, 1,
                f"documented metric {doc_name!r} is registered by no code",
                severity=SEVERITY_WARNING))
        return findings


@register_checker
class EventReasonsChecker(Checker):
    rule = "event-reasons"
    description = ("every REASON_* constant / literal reason= kwarg is "
                   "CamelCase and catalogued in docs/reference/events.md")
    hint = "add the reason to the docs/reference/events.md catalog"
    _IMPL = "k8s_dra_driver_tpu/pkg/events.py"

    def __init__(self, doc_rel: str = "docs/reference/events.md"):
        self.doc_rel = doc_rel

    def collect(self, sf: SourceFile):
        reasons: List[Tuple[str, int]] = [
            (value, node.lineno)
            for value, node in iter_reason_constants(sf.tree)
        ]
        for node in ast.walk(sf.tree):
            if isinstance(node, ast.Call):
                for kw in node.keywords:
                    if (kw.arg == "reason"
                            and isinstance(kw.value, ast.Constant)
                            and isinstance(kw.value.value, str)):
                        reasons.append((kw.value.value, kw.value.lineno))
        return reasons or None

    def finalize(self, project: Project, facts) -> List[Finding]:
        body = project.read(self.doc_rel)
        if body is None:
            return [self.finding(self.doc_rel, 1, f"{self.doc_rel} missing")]
        findings: List[Finding] = []
        for rel, reasons in facts:
            for reason, lineno in reasons:
                if not CAMEL_CASE.match(reason):
                    findings.append(self.finding(
                        rel, lineno,
                        f"event reason {reason!r} is not CamelCase"))
                if f"`{reason}`" not in body:
                    findings.append(self.finding(
                        rel, lineno,
                        f"event reason {reason!r} missing from "
                        f"{self.doc_rel}"))
        if self._IMPL in project.analyzed and not facts:
            findings.append(self.finding(
                self._IMPL, 1,
                "no event reasons found in a package-wide run — "
                "scanner broken?"))
        return findings
