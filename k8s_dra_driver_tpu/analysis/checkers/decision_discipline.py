"""decision-discipline: flight-recorder rule ids only via RULE_* constants.

The PR 17 flight recorder (``pkg/history.py``) keys every controller
DecisionRecord on a ``rule`` id — the string operators grep, alert on,
and ``tpu-kubectl explain`` renders. The catalog lives in ONE place:

- every ``decide(...)`` call must pass ``rule=`` as a ``RULE_*``
  constant reference, never an inline string (an inline id forks the
  catalog silently and breaks the explain/docs cross-reference);
- ``RULE_*`` constants are defined only in ``pkg/history.py``;
- rule id values follow the ``component/kebab-action`` shape
  (``scheduler/bind``, ``preemption/evict-lower-tier``) so the explain
  column groups by emitting controller;
- every rule id is catalogued in ``docs/reference/history.md``
  (collect/finalize, the metrics-docs discipline).
"""

from __future__ import annotations

import ast
import re
from typing import Iterator, List, Tuple

from k8s_dra_driver_tpu.analysis.engine import (
    Checker,
    Finding,
    Project,
    SourceFile,
    register_checker,
)

_IMPL = "k8s_dra_driver_tpu/pkg/history.py"
_DOC = "docs/reference/history.md"
_RULE_NAME = re.compile(r"^RULE_[A-Z0-9_]+$")
_RULE_VALUE = re.compile(r"^[a-z0-9]+(-[a-z0-9]+)*/[a-z0-9]+(-[a-z0-9]+)*$")


def _iter_rule_constants(
    tree: ast.AST,
) -> Iterator[Tuple[str, str, int]]:
    """Every ``RULE_* = "<literal>"`` assignment: (name, value, line)."""
    for node in ast.walk(tree):
        if not (isinstance(node, ast.Assign)
                and isinstance(node.value, ast.Constant)
                and isinstance(node.value.value, str)):
            continue
        for tgt in node.targets:
            if isinstance(tgt, ast.Name) and tgt.id.startswith("RULE_"):
                yield tgt.id, node.value.value, node.lineno
                break


def _rule_kwarg(node: ast.Call):
    for kw in node.keywords:
        if kw.arg == "rule":
            return kw.value
    return None


def _terminal_name(expr: ast.AST) -> str:
    """The identifier a Name/Attribute reference resolves through:
    ``RULE_EVICT`` and ``history.RULE_EVICT`` both -> ``RULE_EVICT``."""
    if isinstance(expr, ast.Name):
        return expr.id
    if isinstance(expr, ast.Attribute):
        return expr.attr
    return ""


@register_checker
class DecisionDisciplineChecker(Checker):
    rule = "decision-discipline"
    description = ("flight-recorder decide() rule ids only via RULE_* "
                   "constants from pkg/history.py, component/kebab-action "
                   "shaped, catalogued in docs/reference/history.md")
    hint = ("pass rule=RULE_<X> imported from pkg/history.py (add the "
            "constant there and catalogue it in docs/reference/history.md)")

    def check_file(self, sf: SourceFile) -> List[Finding]:
        findings: List[Finding] = []
        if sf.rel == _IMPL:
            return findings
        for node in ast.walk(sf.tree):
            if not (isinstance(node, ast.Call)
                    and isinstance(node.func, ast.Attribute)
                    and node.func.attr == "decide"):
                continue
            rule_node = _rule_kwarg(node)
            if rule_node is None:
                continue  # positional misuse fails at runtime (kw-only)
            if (isinstance(rule_node, ast.Constant)
                    and isinstance(rule_node.value, str)):
                findings.append(self.finding(
                    sf, rule_node,
                    f"inline decision rule id {rule_node.value!r} — use a "
                    f"RULE_* constant from pkg/history.py so the catalog "
                    f"and explain/docs cross-references stay the single "
                    f"source"))
                continue
            name = _terminal_name(rule_node)
            if name and not _RULE_NAME.match(name):
                findings.append(self.finding(
                    sf, rule_node,
                    f"decision rule passed through {name!r} — pass the "
                    f"RULE_* constant directly at the decide() call site "
                    f"so provenance stays greppable"))
        return findings

    def collect(self, sf: SourceFile):
        # The lint engine's own RULE_* constants (RULE_SUPPRESSION,
        # RULE_PARSE, checker rule ids) are a different namespace.
        if sf.rel.startswith("k8s_dra_driver_tpu/analysis/"):
            return None
        rules = list(_iter_rule_constants(sf.tree))
        return rules or None

    def finalize(self, project: Project, facts) -> List[Finding]:
        body = project.read(_DOC)
        findings: List[Finding] = []
        if body is None:
            return [self.finding(_DOC, 1, f"{_DOC} missing")]
        declared = 0
        for rel, rules in facts:
            for name, value, lineno in rules:
                declared += 1
                if rel != _IMPL:
                    findings.append(self.finding(
                        rel, lineno,
                        f"rule constant {name} defined outside "
                        f"pkg/history.py — the decision-rule catalog has "
                        f"one home"))
                if not _RULE_VALUE.match(value):
                    findings.append(self.finding(
                        rel, lineno,
                        f"rule id {value!r} is not component/kebab-action "
                        f"shaped (e.g. 'scheduler/bind')"))
                if f"`{value}`" not in body:
                    findings.append(self.finding(
                        rel, lineno,
                        f"rule id {value!r} missing from the {_DOC} "
                        f"catalog"))
        if _IMPL in project.analyzed and not declared:
            findings.append(self.finding(
                _IMPL, 1,
                "no RULE_* constants found in a package-wide run — "
                "scanner broken?"))
        return findings
