"""thread-shared-state: guarded attributes mutate only under their lock.

The control plane is threaded (store watchers, plugin gRPC pools, the
metrics server) and guards shared maps with plain ``threading.Lock``
members. The convention is declared in code: an attribute initialized
with a trailing ``# tpulint: guarded-by=<lock-attr>`` comment may only
be mutated inside ``with self.<lock-attr>:`` (or ``.acquire()``-style
holds are already banned by lock-order). The checker enforces every
declared guard; ``__init__`` is exempt (the object isn't shared yet).

Mutations covered: assignment/augmented assignment to ``self.X`` or
``self.X[...]``, deletion, and the standard container mutators
(``self.X.append(...)``, ``.pop``, ``.update``, ...).

Internal helpers that are only ever called with the lock already held
declare it: ``# tpulint: holds=<lock-attr>`` on the def (the same
annotation family lock-order uses for the pu flock) — the declared
contract is then visible at the def instead of silently assumed.

Annotation parsing lives in ``analysis/astutil.py`` (ModuleAnnotations):
the exact set this checker enforces statically is what the runtime
sanitizer (``analysis/sanitizer``) enforces dynamically.
"""

from __future__ import annotations

import ast
from typing import Dict, List, Optional

from k8s_dra_driver_tpu.analysis.astutil import (
    MUTATORS as _MUTATORS,
    ancestors,
    dotted,
    enclosing_function,
)
from k8s_dra_driver_tpu.analysis.engine import (
    Checker,
    Finding,
    SourceFile,
    register_checker,
)


def _self_attr(node: ast.AST) -> Optional[str]:
    """``self.X`` -> ``X``; also unwraps one subscript: ``self.X[k]``."""
    if isinstance(node, ast.Subscript):
        node = node.value
    if (isinstance(node, ast.Attribute) and isinstance(node.value, ast.Name)
            and node.value.id == "self"):
        return node.attr
    return None


@register_checker
class ThreadSharedStateChecker(Checker):
    rule = "thread-shared-state"
    description = ("attributes declared `# tpulint: guarded-by=<lock>` "
                   "mutate only inside `with self.<lock>:`")
    hint = "move the mutation inside `with self.<lock>:`"

    def check_file(self, sf: SourceFile) -> List[Finding]:
        findings: List[Finding] = []
        for cls in ast.walk(sf.tree):
            if not isinstance(cls, ast.ClassDef):
                continue
            guards = sf.annotations.class_guards.get(cls.name, {})
            if not guards:
                continue
            findings.extend(self._check_class(sf, cls, guards))
        return findings

    def _check_class(self, sf: SourceFile, cls: ast.ClassDef,
                     guards: Dict[str, str]) -> List[Finding]:
        findings = []
        for node in ast.walk(cls):
            attr = None
            if isinstance(node, (ast.Assign, ast.AugAssign, ast.Delete)):
                targets = (node.targets if isinstance(node, ast.Assign)
                           else [node.target] if isinstance(node, ast.AugAssign)
                           else node.targets)
                for t in targets:
                    attr = attr or (_self_attr(t) if _self_attr(t) in guards
                                    else None)
            elif (isinstance(node, ast.Call)
                  and isinstance(node.func, ast.Attribute)
                  and node.func.attr in _MUTATORS):
                cand = _self_attr(node.func.value)
                if cand in guards:
                    attr = cand
            if attr is None:
                continue
            fn = enclosing_function(node, sf.parents)
            if fn is not None and getattr(fn, "name", "") == "__init__":
                continue
            lock = guards[attr]
            if self._under_lock(sf, node, lock):
                continue
            if fn is not None and lock in sf.annotations.fn_holds(fn):
                continue
            findings.append(self.finding(
                sf, node,
                f"self.{attr} (guarded-by={lock}) mutated outside "
                f"`with self.{lock}:` — torn read/write under the "
                f"threaded control plane",
            ))
        return findings

    @staticmethod
    def _under_lock(sf: SourceFile, node: ast.AST, lock: str) -> bool:
        want = f"self.{lock}"
        for anc in ancestors(node, sf.parents):
            if isinstance(anc, ast.With):
                for item in anc.items:
                    ce = item.context_expr
                    # `with self._mu:` or `with self._mu.hold(...):`
                    if dotted(ce) == want:
                        return True
                    if (isinstance(ce, ast.Call)
                            and dotted(ce.func).startswith(want + ".")):
                        return True
        return False
