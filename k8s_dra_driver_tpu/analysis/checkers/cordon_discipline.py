"""cordon-cas: evictions/migrations acquire cordons ONLY via the CAS.

The owner-tagged cordon annotation
(``rebalancer.tpu.google.com/cordoned``) is the arbiter between every
actor that moves or retires claims — the rebalancer, the autoscaler's
scale-down drain, the elastic resize orchestrator, and the preemption
engine. Its exclusion guarantee holds only because every acquisition
goes through ``try_cordon`` (a compare-and-swap that loses cleanly to a
foreign owner) and every release through ``release_cordon``. A raw
annotation write on any of those paths — ``obj.meta.annotations[KEY] =
...`` or ``.pop(KEY)`` outside the two sanctioned functions — silently
reintroduces the blind-cordon TOCTOU the CAS closed: two actors both
"win", one double-handles the claim, and the partition ledger loses.

Scope: the controllers that participate in the protocol (rebalancer/,
autoscaler/, scheduling/, controller/). The two sanctioned functions
live in ``rebalancer/controller.py`` and are recognized by name.
"""

from __future__ import annotations

import ast
from typing import List

from k8s_dra_driver_tpu.analysis.engine import (
    Checker,
    Finding,
    SourceFile,
    register_checker,
)

CORDON_VALUE = "rebalancer.tpu.google.com/cordoned"
CORDON_NAMES = {"CORDON_ANNOTATION"}
SANCTIONED_FUNCS = {"try_cordon", "release_cordon"}


def _is_cordon_key(node: ast.AST) -> bool:
    """Does this subscript/argument name the cordon annotation — by the
    CORDON_ANNOTATION constant or its literal value?"""
    if isinstance(node, ast.Constant) and node.value == CORDON_VALUE:
        return True
    if isinstance(node, ast.Name) and node.id in CORDON_NAMES:
        return True
    if isinstance(node, ast.Attribute) and node.attr in CORDON_NAMES:
        return True
    return False


def _is_annotations_attr(node: ast.AST) -> bool:
    return isinstance(node, ast.Attribute) and node.attr == "annotations"


def _enclosing_functions(node: ast.AST, parents) -> List[str]:
    """Every def on the node's ancestor chain, innermost first — the
    CAS implementations write through nested mutate() closures, so the
    sanction check must see the whole chain."""
    out: List[str] = []
    cur = parents.get(node)
    while cur is not None:
        if isinstance(cur, (ast.FunctionDef, ast.AsyncFunctionDef)):
            out.append(cur.name)
        cur = parents.get(cur)
    return out


@register_checker
class CordonDisciplineChecker(Checker):
    rule = "cordon-cas"
    description = ("cordon acquisition/release only via the owner-tagged "
                   "try_cordon/release_cordon CAS — no raw cordon-"
                   "annotation writes on eviction/migration paths")
    hint = ("call rebalancer.controller.try_cordon(api, claim, owner=...) "
            "to acquire and release_cordon(api, claim) to release; a raw "
            "annotation write reopens the blind-cordon double-handle race")
    scope = ("k8s_dra_driver_tpu/rebalancer/",
             "k8s_dra_driver_tpu/autoscaler/",
             "k8s_dra_driver_tpu/scheduling/",
             "k8s_dra_driver_tpu/controller/",
             # Cross-cluster placement/spill must not side-step the CAS
             # either when it starts moving claims between regions.
             "k8s_dra_driver_tpu/federation/")

    def check_file(self, sf: SourceFile) -> List[Finding]:
        findings: List[Finding] = []
        for node in ast.walk(sf.tree):
            hit = None
            # obj.meta.annotations[CORDON_ANNOTATION] = ... (Store ctx)
            # and `del obj.meta.annotations[CORDON_ANNOTATION]`.
            if (isinstance(node, ast.Subscript)
                    and isinstance(node.ctx, (ast.Store, ast.Del))
                    and _is_annotations_attr(node.value)
                    and _is_cordon_key(node.slice)):
                hit = ("raw cordon-annotation write "
                       "(subscript assignment/delete)")
            # obj.meta.annotations.pop(CORDON_ANNOTATION, ...) /
            # .setdefault(CORDON_ANNOTATION, ...) / .update({...}) with
            # the cordon key anywhere in the args.
            elif (isinstance(node, ast.Call)
                  and isinstance(node.func, ast.Attribute)
                  and node.func.attr in ("pop", "setdefault", "update")
                  and _is_annotations_attr(node.func.value)
                  and any(_is_cordon_key(a) for a in ast.walk(node)
                          if a is not node)):
                hit = f"raw cordon-annotation .{node.func.attr}()"
            if hit is None:
                continue
            if any(fn in SANCTIONED_FUNCS
                   for fn in _enclosing_functions(node, sf.parents)):
                continue  # the CAS implementation itself
            findings.append(self.finding(
                sf, node,
                f"{hit} outside try_cordon/release_cordon — cordons are "
                f"owner-tagged CAS state; a raw write double-handles the "
                f"claim against the other actor roles",
            ))
        return findings
