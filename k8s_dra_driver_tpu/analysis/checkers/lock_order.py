"""lock-order: pu flock before cp flock, flocks only via context managers.

The node-global prepare/unprepare flock (``pu.lock``) serializes device
mutation across plugin *processes*; the checkpoint flock (``cp.lock``)
guards checkpoint read-modify-write. Every PR-1 pipeline takes them in
that order — pu outside, cp (via ``CheckpointStore.session()``) inside —
so a reversed acquisition anywhere is a cross-process deadlock waiting
for load. Three statically-checkable rules:

- a checkpoint ``session()`` opens only where the pu flock is provably
  held: lexically inside ``with <pu-lock>.hold(...)``, or in a function
  annotated ``# tpulint: holds=pu-flock`` (the gRPC handler takes the
  lock and delegates);
- the checkpoint is never saved outside a session except through the
  store's own locked single-write path (batching discipline: a bare
  get→mutate→save pair is TWO lock holds and a lost-update window);
- flocks are acquired only through context managers (``.hold()``) —
  a bare ``.acquire()`` leaks the lock on any exception path.
"""

from __future__ import annotations

import ast
from typing import List, Set

from k8s_dra_driver_tpu.analysis.astutil import (
    call_chain,
    enclosing_function,
    receiver_chain,
    string_constants,
    with_ancestors,
)
from k8s_dra_driver_tpu.analysis.engine import (
    Checker,
    Finding,
    SourceFile,
    register_checker,
)

# The lock/checkpoint implementations themselves are exempt — they *are*
# the sanctioned acquisition paths the rule funnels everyone through.
_IMPL_FILES = (
    "k8s_dra_driver_tpu/pkg/flock.py",
    "k8s_dra_driver_tpu/plugins/checkpoint.py",
)


def _is_pu_hold(withitem_expr: ast.AST) -> bool:
    """``with self._pu_lock.hold(...)`` or
    ``with Flock(<...pu.lock...>).hold(...)``."""
    if not (isinstance(withitem_expr, ast.Call)
            and isinstance(withitem_expr.func, ast.Attribute)
            and withitem_expr.func.attr == "hold"):
        return False
    recv = receiver_chain(withitem_expr)
    if "pu_lock" in recv:
        return True
    base = withitem_expr.func.value
    if isinstance(base, ast.Call):
        return any("pu.lock" in s for s in string_constants(base))
    return False


def _fn_holds_pu(sf: SourceFile, fn) -> bool:
    """The enclosing def carries the holds annotation on its signature
    lines or directly above it (shared astutil.ModuleAnnotations parse)."""
    return "pu-flock" in sf.annotations.fn_holds(fn)


@register_checker
class LockOrderChecker(Checker):
    rule = "lock-order"
    description = ("checkpoint flock nests under the pu flock, checkpoint "
                   "saves go through sessions, flocks only via context "
                   "managers")
    scope = ("k8s_dra_driver_tpu/plugins/", "k8s_dra_driver_tpu/pkg/",
             "k8s_dra_driver_tpu/daemon/", "k8s_dra_driver_tpu/cmd/")

    def check_file(self, sf: SourceFile) -> List[Finding]:
        if sf.rel in _IMPL_FILES:
            return []
        findings: List[Finding] = []
        session_vars = self._session_vars(sf)
        for node in ast.walk(sf.tree):
            if not isinstance(node, ast.Call):
                continue
            if isinstance(node.func, ast.Attribute):
                attr = node.func.attr
                recv = receiver_chain(node)
                low = recv.lower()
                if attr == "session" and ("store" in low or "checkpoint" in low
                                          or "_cp" in low):
                    findings.extend(self._check_session(sf, node))
                elif attr == "save" and ("checkpoint" in low or "_mgr" in low
                                         or "store" in low):
                    if recv.split(".")[-1] not in session_vars \
                            and recv not in session_vars:
                        findings.append(self.finding(
                            sf, node,
                            f"checkpoint saved outside a session "
                            f"({call_chain(node)}) — get→mutate→save pairs "
                            f"release the cp flock between load and write",
                            hint="use `with <store>.session() as sess:` and "
                                 "mutate sess.checkpoint, then sess.save()",
                        ))
                elif attr in ("acquire", "release") and (
                        "lock" in low or "flock" in low):
                    findings.append(self.finding(
                        sf, node,
                        f"flock {attr}() called directly "
                        f"({call_chain(node)}) — locks leak on exception "
                        f"paths outside a context manager",
                        hint="use `with <lock>.hold(timeout=...):`",
                    ))
        return findings

    def _check_session(self, sf: SourceFile, call: ast.Call) -> List[Finding]:
        for w in with_ancestors(call, sf.parents):
            for item in w.items:
                if _is_pu_hold(item.context_expr):
                    return []
        if _fn_holds_pu(sf, enclosing_function(call, sf.parents)):
            return []
        return [self.finding(
            sf, call,
            "checkpoint session opened without the pu flock held — the cp "
            "flock must nest under the pu flock (lock order), and prepare "
            "state must not move while another process prepares",
            hint="wrap in `with self._pu_lock.hold(...):`, or annotate the "
                 "enclosing function `# tpulint: holds=pu-flock` if every "
                 "caller provably holds it",
        )]

    @staticmethod
    def _session_vars(sf: SourceFile) -> Set[str]:
        """Names bound by ``with <x>.session(...) as NAME`` — their
        ``.save()`` is the sanctioned in-session write."""
        out: Set[str] = set()
        for node in ast.walk(sf.tree):
            if not isinstance(node, ast.With):
                continue
            for item in node.items:
                ce = item.context_expr
                if (isinstance(ce, ast.Call)
                        and isinstance(ce.func, ast.Attribute)
                        and ce.func.attr == "session"
                        and isinstance(item.optional_vars, ast.Name)):
                    out.add(item.optional_vars.id)
        return out
