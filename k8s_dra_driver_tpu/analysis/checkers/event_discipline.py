"""event-discipline: Events only through the EventRecorder, reasons only
from the catalog.

The PR 4 recorder owns dedup (cross-process series aggregation), burst
limiting, and backlog bounds; a raw ``Event`` written straight to the
store bypasses all three and races concurrent recorders on the series
name. Reason strings passed to recorder calls must be the ``REASON_*``
constants from ``pkg/events.py`` — inline literals fork the catalog the
``event-reasons`` doc rule audits and operators alert on.
"""

from __future__ import annotations

import ast
from typing import List

from k8s_dra_driver_tpu.analysis.astutil import (
    CAMEL_CASE,
    iter_reason_constants,
    receiver_chain,
)
from k8s_dra_driver_tpu.analysis.engine import (
    Checker,
    Finding,
    SourceFile,
    register_checker,
)

_RECORDER_CALLS = {"event": 2, "normal": 1, "warning": 1}  # reason arg index
_IMPL = "k8s_dra_driver_tpu/pkg/events.py"


@register_checker
class EventDisciplineChecker(Checker):
    rule = "event-discipline"
    description = ("Events written only via EventRecorder; recorder "
                   "reasons only via REASON_* constants, CamelCase")
    hint = ("emit through recorder.normal/warning with a REASON_* "
            "constant from pkg/events.py (add one there if missing)")

    def check_file(self, sf: SourceFile) -> List[Finding]:
        findings: List[Finding] = []
        for node in ast.walk(sf.tree):
            if not isinstance(node, ast.Call):
                continue
            findings.extend(self._check_raw_write(sf, node))
            findings.extend(self._check_reason(sf, node))
        findings.extend(self._check_constants(sf))
        return findings

    def _check_raw_write(self, sf: SourceFile, node: ast.Call) -> List[Finding]:
        if sf.rel == _IMPL:
            return []
        if not (isinstance(node.func, ast.Attribute)
                and node.func.attr in ("create", "update", "update_with_retry")):
            return []
        recv = receiver_chain(node).lower()
        if "api" not in recv and "store" not in recv:
            return []
        for arg in list(node.args)[:1]:
            # api.create(Event(...)) / api.update(Event(...))
            if (isinstance(arg, ast.Call) and isinstance(arg.func, ast.Name)
                    and arg.func.id == "Event"):
                return [self.finding(
                    sf, node,
                    "Event written directly to the store — bypasses the "
                    "EventRecorder's dedup, burst limiting, and backlog "
                    "bounds, and races concurrent recorders on the "
                    "series name",
                )]
            # api.update_with_retry(EVENT, ...)
            if isinstance(arg, ast.Name) and arg.id == "EVENT":
                return [self.finding(
                    sf, node,
                    "Event kind mutated directly in the store — only the "
                    "EventRecorder may write Events",
                )]
        return []

    def _check_reason(self, sf: SourceFile, node: ast.Call) -> List[Finding]:
        if not (isinstance(node.func, ast.Attribute)
                and node.func.attr in _RECORDER_CALLS
                and "recorder" in receiver_chain(node).lower()):
            return []
        idx = _RECORDER_CALLS[node.func.attr]
        reason_node = None
        if len(node.args) > idx:
            reason_node = node.args[idx]
        for kw in node.keywords:
            if kw.arg == "reason":
                reason_node = kw.value
        if isinstance(reason_node, ast.Constant) \
                and isinstance(reason_node.value, str):
            return [self.finding(
                sf, reason_node,
                f"inline event reason {reason_node.value!r} — use a "
                f"REASON_* constant from pkg/events.py so the catalog "
                f"and docs stay the single source",
            )]
        return []

    def _check_constants(self, sf: SourceFile) -> List[Finding]:
        return [
            self.finding(
                sf, node,
                f"event reason {value!r} is not CamelCase — the "
                f"kubectl-ecosystem convention Events are grepped and "
                f"alerted on",
            )
            for value, node in iter_reason_constants(sf.tree)
            if not CAMEL_CASE.match(value)
        ]
