"""swallowed-exceptions: no silently-dropped failures in control loops.

A ``pass``-only broad except in a control loop or watch drain turns a
real failure (store conflict storm, codec error, poisoned watch event)
into an infinite quiet retry — the failure mode that's invisible until a
10k-node storm hits it. Narrow typed excepts with ``pass`` are fine
(``except NotFoundError: pass`` is the idiomatic delete race absorber);
what this rule bans is:

- ``except:`` (bare) anywhere — it eats KeyboardInterrupt/SystemExit;
- ``except Exception:`` / ``except BaseException:`` whose body is only
  ``pass``/``...`` — handle it, log it, or count it.
"""

from __future__ import annotations

import ast
from typing import List

from k8s_dra_driver_tpu.analysis.engine import (
    Checker,
    Finding,
    SourceFile,
    register_checker,
)

_BROAD = {"Exception", "BaseException"}


def _is_noop_body(body) -> bool:
    for stmt in body:
        if isinstance(stmt, ast.Pass):
            continue
        if isinstance(stmt, ast.Expr) and isinstance(stmt.value, ast.Constant):
            continue  # docstring/ellipsis
        return False
    return True


@register_checker
class SwallowedExceptionsChecker(Checker):
    rule = "swallowed-exceptions"
    description = ("no bare excepts; no pass-only broad excepts in "
                   "control-plane code")
    hint = ("catch the specific exception, or log/count the failure "
            "before continuing; telemetry-must-not-break-control-flow "
            "excepts should at least debug-log")

    def check_file(self, sf: SourceFile) -> List[Finding]:
        findings: List[Finding] = []
        for node in ast.walk(sf.tree):
            if not isinstance(node, ast.ExceptHandler):
                continue
            if node.type is None:
                findings.append(self.finding(
                    sf, node,
                    "bare `except:` — also catches KeyboardInterrupt and "
                    "SystemExit; name the exception type",
                ))
                continue
            names = []
            if isinstance(node.type, ast.Name):
                names = [node.type.id]
            elif isinstance(node.type, ast.Tuple):
                names = [e.id for e in node.type.elts
                         if isinstance(e, ast.Name)]
            if any(n in _BROAD for n in names) and _is_noop_body(node.body):
                findings.append(self.finding(
                    sf, node,
                    f"broad `except {'/'.join(names)}` swallowed with "
                    f"pass — a control-loop failure disappears without a "
                    f"log line or counter",
                ))
        return findings
