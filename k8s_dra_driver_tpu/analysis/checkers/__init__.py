"""tpulint checkers — importing this package registers every rule.

One module per invariant family; each module's checkers self-register
via ``@register_checker`` so ``all_checkers()`` sees them in a stable
order (import order below = report/finalize order).
"""

from k8s_dra_driver_tpu.analysis.checkers import (  # noqa: F401
    cas_purity,
    lock_order,
    store_scan,
    wire_drift,
    metric_discipline,
    event_discipline,
    decision_discipline,
    swallowed_exceptions,
    thread_shared_state,
    shard_lock,
    sleep_under_lock,
    cordon_discipline,
    snapshot_mutation,
    docs_sync,
)
