"""metric-discipline: metrics registered on the shared registry, labels
bounded.

Two failure modes the metric surface (PR 2/4) is vulnerable to:

- a ``Counter``/``Gauge``/``Histogram`` constructed but never passed
  through ``registry.register(...)`` records into an object nothing
  scrapes — the series silently vanishes from /metrics (the get-or-create
  registry is also what dedupes shared series across plugin bundles, so
  a bare construction can additionally fork a same-name series);
- a label value built from an f-string over an unbounded source (claim
  uids, messages, node names from user input) explodes series
  cardinality; label values must come from closed vocabularies, with
  free-form detail in logs/events instead;
- a label *name* that is a uid (PR 11's telemetry rule): uids are
  unbounded across an object's lifetime churn — a gauge family on the
  shared registry labeled by claim uid grows one series per claim ever
  prepared. Rollup gauges key on claim name+namespace (bounded, LRU-
  evicted like the event correlator's per-object state) and put the uid
  in the log/trace instead.
"""

from __future__ import annotations

import ast
from typing import List

from k8s_dra_driver_tpu.analysis.astutil import (
    METRIC_CTORS,
    call_chain,
    dotted,
    iter_metric_registrations,
)
from k8s_dra_driver_tpu.analysis.engine import (
    Checker,
    Finding,
    SourceFile,
    register_checker,
)

_LABELLED_CALLS = {"inc", "set", "observe"}
# Keyword args of metric calls that carry the measurement, not a label.
_VALUE_KWARGS = {"value", "by", "amount"}
# Declared label names that mean "one series per object ever seen" —
# unbounded on the shared registry no matter how the values are built.
_UNBOUNDED_LABEL_NAMES = {"uid", "uuid"}


def _is_uid_label(name: str) -> bool:
    n = name.lower()
    return n in _UNBOUNDED_LABEL_NAMES or n.endswith(("_uid", "_uuid"))


@register_checker
class MetricDisciplineChecker(Checker):
    rule = "metric-discipline"
    description = ("tpu_dra_* metrics only via registry.register(), label "
                   "values never from f-strings (cardinality)")
    hint = ("wrap the constructor: registry.register(Counter(...)); pass "
            "closed-vocabulary label values and put free-form detail in "
            "the log/event message")
    # The metric primitives live in pkg/metrics.py; its internal exposition
    # code (HELP/TYPE line formatting) legitimately f-strings series names.
    _IMPL = "k8s_dra_driver_tpu/pkg/metrics.py"

    def check_file(self, sf: SourceFile) -> List[Finding]:
        findings: List[Finding] = []
        for name, node in iter_metric_registrations(sf.tree):
            findings.extend(self._check_ctor(sf, name, node))
        if sf.rel != self._IMPL:
            bindings = self._metric_bindings(sf)
            for node in ast.walk(sf.tree):
                if isinstance(node, ast.Call):
                    findings.extend(self._check_labels(sf, node, bindings))
        return findings

    @staticmethod
    def _metric_bindings(sf: SourceFile) -> set:
        """Names (locals and self-attributes) bound from
        ``registry.register(...)`` or a metric constructor in this file —
        the receivers whose inc/set/observe calls are metric calls. Keeps
        the f-string rule off unrelated setters (a status object's
        ``.set(f"...")`` is not a label write)."""
        out = set()
        for node in ast.walk(sf.tree):
            if not (isinstance(node, ast.Assign)
                    and isinstance(node.value, ast.Call)):
                continue
            fn = node.value.func
            is_metric = (
                (isinstance(fn, ast.Attribute) and fn.attr == "register")
                or (isinstance(fn, ast.Name) and fn.id in METRIC_CTORS)
            )
            if not is_metric:
                continue
            for tgt in node.targets:
                if isinstance(tgt, ast.Name):
                    out.add(tgt.id)
                elif isinstance(tgt, ast.Attribute):
                    out.add(tgt.attr)
        return out

    def _check_ctor(self, sf: SourceFile, name: str,
                    node: ast.Call) -> List[Finding]:
        if not name.startswith("tpu_dra_"):
            return []
        findings: List[Finding] = []
        parent = sf.parents.get(node)
        registered = (
            isinstance(parent, ast.Call)
            and isinstance(parent.func, ast.Attribute)
            and parent.func.attr == "register"
        )
        if not registered:
            findings.append(self.finding(
                sf, node,
                f"metric {name!r} constructed outside "
                f"registry.register() — the series never reaches /metrics "
                f"and dodges shared-registry dedup",
            ))
        for label in self._declared_labels(node):
            if _is_uid_label(label):
                findings.append(self.finding(
                    sf, node,
                    f"metric {name!r} declares uid label {label!r} — one "
                    f"series per object ever seen is unbounded on the "
                    f"shared registry; key on name+namespace (LRU-"
                    f"evicted) and put the uid in the log/trace",
                ))
        return findings

    @staticmethod
    def _declared_labels(node: ast.Call) -> List[str]:
        """Literal label names of a metric constructor: the third
        positional (after name, help) or the ``label_names`` keyword
        (pkg.metrics' real parameter; ``labels`` accepted for
        wrapper APIs)."""
        labels_arg = node.args[2] if len(node.args) > 2 else None
        for kw in node.keywords:
            if kw.arg in ("label_names", "labels"):
                labels_arg = kw.value
        if not isinstance(labels_arg, (ast.Tuple, ast.List)):
            return []
        return [el.value for el in labels_arg.elts
                if isinstance(el, ast.Constant) and isinstance(el.value, str)]

    def _check_labels(self, sf: SourceFile, node: ast.Call,
                      bindings: set) -> List[Finding]:
        if not (isinstance(node.func, ast.Attribute)
                and node.func.attr in _LABELLED_CALLS):
            return []
        # Receiver must actually be a metric: a name/attr bound from
        # register()/a constructor, or a chain through a metrics bundle
        # (self.metrics.foo.inc, self._metrics["x"].set).
        recv = node.func.value
        if isinstance(recv, ast.Subscript):
            recv = recv.value
        chain = dotted(recv)
        parts = set(chain.split("."))
        if not (parts & bindings or "metric" in chain.lower()):
            return []
        findings = []
        label_args = list(node.args) + [
            kw.value for kw in node.keywords
            if kw.arg and kw.arg not in _VALUE_KWARGS
        ]
        for arg in label_args:
            if isinstance(arg, ast.JoinedStr):
                findings.append(self.finding(
                    sf, arg,
                    f"label value for {call_chain(node)}() built from an "
                    f"f-string — unbounded label sources explode series "
                    f"cardinality",
                ))
        return findings
