"""wire-drift: every API dataclass field round-trips through k8swire.

The k8s wire codec (``k8s/k8swire.py``) is hand-written — one encode and
one decode function per kind, the client-go-generated-types analog. A
field added to a dataclass but not threaded through *both* directions is
silent data loss on a real cluster (the sim's internal wire round-trips
everything via serialize.py, so nothing fails until kubeclient is in the
path — exactly the drift class PR 5's placement wiring nearly shipped).

Mechanically: a field named ``foo`` passes when the encoder subtree
reads ``.foo`` somewhere and the decoder subtree passes ``foo=`` to a
constructor. Fields ``kind``/``meta`` are codec-generic (the top-level
``to_k8s_wire``/``_meta_encode`` pair owns them). Deliberately lossy
fields (sim-only conveniences) carry a line suppression with the reason
in the dataclass itself, next to the field they exempt.
"""

from __future__ import annotations

import ast
from dataclasses import dataclass, field as dc_field
from typing import Dict, FrozenSet, List, Sequence, Tuple

from k8s_dra_driver_tpu.analysis.astutil import (
    dataclass_fields,
    find_classes,
    find_functions,
)
from k8s_dra_driver_tpu.analysis.engine import (
    Checker,
    Finding,
    Project,
    register_checker,
)


@dataclass
class WireKindSpec:
    """One wire-encoded kind: where its dataclasses live and which codec
    functions must mention every field."""

    kind: str
    # rel path -> dataclass names composing the kind's object graph
    dataclasses: Dict[str, Tuple[str, ...]]
    encoders: Tuple[str, ...]
    decoders: Tuple[str, ...]
    exempt: FrozenSet[str] = frozenset({"kind", "meta"})


_CONDITION = ("k8s_dra_driver_tpu/k8s/conditions.py", ("Condition",))
_API_CD = "k8s_dra_driver_tpu/api/computedomain.py"
_CORE = "k8s_dra_driver_tpu/k8s/core.py"

DEFAULT_SPECS: Tuple[WireKindSpec, ...] = (
    WireKindSpec(
        kind="ComputeDomain",
        dataclasses={
            _API_CD: ("ComputeDomain", "ComputeDomainSpec",
                      "ComputeDomainChannelSpec", "ComputeDomainNode",
                      "ComputeDomainPlacement", "ComputeDomainResize",
                      "ComputeDomainStatus"),
            "k8s_dra_driver_tpu/pkg/meshgen.py": ("MeshBundle",
                                                  "MeshDevice"),
            _CONDITION[0]: _CONDITION[1],
        },
        encoders=("_computedomain_encode", "_placement_encode",
                  "_resize_encode", "_meshbundle_encode",
                  "_conditions_encode"),
        decoders=("_computedomain_decode", "_placement_decode",
                  "_resize_decode", "_meshbundle_decode",
                  "_conditions_decode"),
    ),
    WireKindSpec(
        kind="ServingGroup",
        dataclasses={
            "k8s_dra_driver_tpu/api/servinggroup.py": (
                "ServingGroup", "ServingGroupSpec", "ServingGroupStatus",
                "ServingReplicaTemplate", "ServingSLO", "ServingTraffic",
                "ServingScalingPolicy", "ServingTrafficStatus"),
            _CONDITION[0]: _CONDITION[1],
        },
        encoders=("_servinggroup_encode", "_conditions_encode"),
        decoders=("_servinggroup_decode", "_conditions_decode"),
    ),
    WireKindSpec(
        kind="TenantQuota",
        dataclasses={
            "k8s_dra_driver_tpu/api/tenantquota.py": (
                "TenantQuota", "TenantQuotaSpec", "TenantQuotaStatus"),
        },
        encoders=("_tenantquota_encode",),
        decoders=("_tenantquota_decode",),
    ),
    WireKindSpec(
        kind="ComputeDomainClique",
        dataclasses={
            _API_CD: ("ComputeDomainClique", "ComputeDomainDaemonInfo"),
        },
        encoders=("_clique_encode",),
        decoders=("_clique_decode",),
    ),
    WireKindSpec(
        kind="ResourceClaim",
        dataclasses={
            _CORE: ("ResourceClaim", "DeviceRequest", "DeviceClaimConfig",
                    "OpaqueDeviceConfig", "AllocationResult",
                    "DeviceRequestAllocationResult", "ResourceClaimConsumer"),
            _CONDITION[0]: _CONDITION[1],
        },
        encoders=("_claim_encode", "_requests_encode", "_configs_encode",
                  "_conditions_encode"),
        decoders=("_claim_decode", "_requests_decode", "_configs_decode",
                  "_conditions_decode"),
    ),
    WireKindSpec(
        kind="ResourceSlice",
        dataclasses={
            _CORE: ("ResourceSlice", "ResourcePool", "Device", "DeviceTaint",
                    "Counter", "CounterSet", "DeviceCounterConsumption"),
        },
        encoders=("_slice_encode", "_counters_encode"),
        decoders=("_slice_decode", "_counters_decode"),
    ),
    WireKindSpec(
        kind="DeviceClass",
        dataclasses={_CORE: ("DeviceClass",)},
        encoders=("_deviceclass_encode", "_configs_encode"),
        decoders=("_deviceclass_decode", "_configs_decode"),
    ),
    WireKindSpec(
        kind="Lease",
        dataclasses={
            "k8s_dra_driver_tpu/pkg/leaderelection.py": ("Lease",),
        },
        encoders=("_lease_encode",),
        decoders=("_lease_decode",),
    ),
)

DEFAULT_WIRE_FILE = "k8s_dra_driver_tpu/k8s/k8swire.py"


def _attr_reads(fn: ast.FunctionDef) -> FrozenSet[str]:
    return frozenset(
        n.attr for n in ast.walk(fn) if isinstance(n, ast.Attribute)
    )


def _ctor_kwargs(fn: ast.FunctionDef) -> FrozenSet[str]:
    out = set()
    for n in ast.walk(fn):
        if isinstance(n, ast.Call):
            out.update(kw.arg for kw in n.keywords if kw.arg)
        elif isinstance(n, ast.Attribute) and isinstance(n.ctx, ast.Store):
            # decode styles that assign obj.field = ... post-construction;
            # Store-context only — a mere READ of .field somewhere in the
            # decoder must not count as populating it, or dropping the
            # ctor kwarg would go undetected
            out.add(n.attr)
    return frozenset(out)


@register_checker
class WireDriftChecker(Checker):
    rule = "wire-drift"
    description = ("every API dataclass field appears in the matching "
                   "k8swire encode AND decode (no silent loss on the real "
                   "k8s wire)")
    hint = ("thread the field through the kind's encoder and decoder in "
            "k8s/k8swire.py; deliberately sim-only fields take a line "
            "suppression with the reason")

    def __init__(self, specs: Sequence[WireKindSpec] = DEFAULT_SPECS,
                 wire_file: str = DEFAULT_WIRE_FILE):
        self.specs = tuple(specs)
        self.wire_file = wire_file

    def finalize(self, project: Project, facts) -> List[Finding]:
        wire = project.source(self.wire_file)
        if wire is None:
            return [self.finding(self.wire_file, 1,
                                 "wire codec module missing or unparseable")]
        funcs = find_functions(wire.tree)
        findings: List[Finding] = []
        for spec in self.specs:
            enc_fns = [funcs[n] for n in spec.encoders if n in funcs]
            dec_fns = [funcs[n] for n in spec.decoders if n in funcs]
            missing_fns = [n for n in spec.encoders + spec.decoders
                           if n not in funcs]
            if missing_fns:
                findings.append(self.finding(
                    self.wire_file, 1,
                    f"{spec.kind}: codec function(s) "
                    f"{', '.join(missing_fns)} not found in "
                    f"{self.wire_file}"))
                continue
            encoded = frozenset().union(*[_attr_reads(f) for f in enc_fns])
            decoded = frozenset().union(*[_ctor_kwargs(f) for f in dec_fns])
            for rel, class_names in spec.dataclasses.items():
                src = project.source(rel)
                if src is None:
                    findings.append(self.finding(
                        rel, 1, f"{spec.kind}: dataclass module {rel} "
                                f"missing or unparseable"))
                    continue
                classes = find_classes(src.tree)
                for cname in class_names:
                    cls = classes.get(cname)
                    if cls is None:
                        findings.append(self.finding(
                            rel, 1,
                            f"{spec.kind}: dataclass {cname} not found "
                            f"in {rel}"))
                        continue
                    for fld in dataclass_fields(cls):
                        name = fld.target.id
                        if name in spec.exempt or name.startswith("_"):
                            continue
                        if name not in encoded:
                            findings.append(self.finding(
                                rel, fld,
                                f"{cname}.{name} is never read by the "
                                f"{spec.kind} k8swire encoder(s) "
                                f"{'/'.join(spec.encoders)} — value lost "
                                f"on encode"))
                        if name not in decoded:
                            findings.append(self.finding(
                                rel, fld,
                                f"{cname}.{name} is never populated by "
                                f"the {spec.kind} k8swire decoder(s) "
                                f"{'/'.join(spec.decoders)} — value lost "
                                f"on decode"))
        return findings
