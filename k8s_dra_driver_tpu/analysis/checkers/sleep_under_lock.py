"""sleep-under-lock: no blocking waits inside a held-lock region.

A sleep or blocking socket/file call inside a ``with <lock>:`` body (or a
helper whose ``# tpulint: holds=<lock>`` contract says the caller holds
one) stretches every other thread's critical-section wait by the full
blocking time — the convoy that turns a 16-shard store back into a
single-lock store. cas-purity stops these inside CAS closures; this rule
stops them inside lock scopes.

The lock vocabulary is the shared one (astutil.ModuleAnnotations): a
with-item is a lock hold when its context expression ends in a lock
attribute any ``guarded-by=`` in the file names, or is a flock-style
``.hold(...)`` call. ``Condition.wait`` is exempt — it releases the lock
for the sleep; that is its job.
"""

from __future__ import annotations

import ast
from typing import List, Optional

from k8s_dra_driver_tpu.analysis.astutil import (
    ancestors,
    call_chain,
    dotted,
    enclosing_function,
    receiver_chain,
)
from k8s_dra_driver_tpu.analysis.engine import (
    Checker,
    Finding,
    SourceFile,
    register_checker,
)

_SOCKET_BLOCKING = {"accept", "recv", "recvfrom", "connect", "sendall",
                    "makefile"}
_NET_PREFIXES = ("socket.", "requests.", "urllib.", "subprocess.", "select.")


def _blocking(call: ast.Call) -> Optional[str]:
    chain = call_chain(call)
    recv = receiver_chain(call).lower()
    last = chain.rsplit(".", 1)[-1]
    if last == "sleep" and ("time" in recv or chain == "sleep"):
        return "time.sleep"
    if chain == "open":
        return "file I/O (open)"
    if chain.startswith(_NET_PREFIXES):
        return f"blocking call {chain}"
    if last in _SOCKET_BLOCKING and "sock" in recv:
        return f"blocking socket call {chain}"
    if last == "fsync":
        return f"fsync ({chain})"
    return None


@register_checker
class SleepUnderLockChecker(Checker):
    rule = "sleep-under-lock"
    description = ("no time.sleep or blocking socket/file I/O lexically "
                   "inside a `with <lock>:` body or a `holds=`-annotated "
                   "helper")
    hint = ("move the blocking call outside the critical section (compute "
            "under the lock, block after release), or split the helper so "
            "only the pure part runs under `holds=`")

    def check_file(self, sf: SourceFile) -> List[Finding]:
        findings: List[Finding] = []
        lock_attrs = sf.annotations.lock_attrs
        for node in ast.walk(sf.tree):
            if not isinstance(node, ast.Call):
                continue
            why = _blocking(node)
            if why is None:
                continue
            where = self._locked_region(sf, node, lock_attrs)
            if where is None:
                continue
            findings.append(self.finding(
                sf, node,
                f"{why} while holding {where} — every thread contending "
                f"for that lock blocks for the full call",
            ))
        return findings

    @staticmethod
    def _locked_region(sf: SourceFile, node: ast.AST,
                       lock_attrs) -> Optional[str]:
        """The innermost held lock this call sits under, or None: a
        ``with`` item naming a declared lock attribute (``self._mu``,
        ``shard.mu``, ...) or a flock ``.hold(...)``, or an enclosing def
        whose ``holds=`` contract declares a caller-held lock."""
        for anc in ancestors(node, sf.parents):
            if isinstance(anc, ast.With):
                for item in anc.items:
                    ce = item.context_expr
                    d = dotted(ce)
                    if d and d.rsplit(".", 1)[-1] in lock_attrs:
                        return f"`{d}`"
                    if isinstance(ce, ast.Call):
                        fd = dotted(ce.func)
                        if fd.endswith(".hold"):
                            return f"`{fd}(...)`"
                        if fd and fd.rsplit(".", 1)[-1] in lock_attrs:
                            return f"`{fd}`"
        fn = enclosing_function(node, sf.parents)
        holds = sf.annotations.fn_holds(fn)
        if holds:
            return (f"`{sorted(holds)[0]}` (declared by this helper's "
                    f"`holds=` contract)")
        return None
