"""shard-lock: per-shard state mutates only under its own shard's lock.

The scale-out store (PR 8) partitions its indexes into shard objects,
each carrying its own lock: ``class _Shard`` declares its bucket dicts
with ``# tpulint: guarded-by=mu``. thread-shared-state covers ``self.X``
mutations *inside* a class; this rule covers the cross-object accesses a
sharded design creates:

1. **External guarded mutation.** Code mutating ``<obj>.<attr>`` where
   ``attr`` is declared guarded in some class of the same file must hold
   that instance's lock: lexically inside ``with <obj>.<lock>:``, inside
   a function annotated ``# tpulint: holds=<lock>`` (callers lock), or
   under the canonical whole-store acquire (a ``with ..._locked_all():``
   ancestor, which holds every shard's lock by construction).
2. **Ordered multi-shard acquire.** Holding two different instances'
   locks of the same lock attribute (``with a.mu: ... with b.mu:``), or
   raw ``.acquire()`` calls on a non-self shard lock, deadlocks the
   moment two threads disagree on order — allowed ONLY inside the one
   canonical helper annotated ``# tpulint: ordered-acquire``.

Instance-internal locks (``self._mu``-style, base ``self``) keep their
fixed hierarchy and are out of scope here — rule 2 looks at non-self
bases only, where instance identity (not the attribute name) decides
the order.
"""

from __future__ import annotations

import ast
from typing import Dict, List, Optional, Set, Tuple

from k8s_dra_driver_tpu.analysis.astutil import (
    MUTATORS as _MUTATORS,
    ancestors,
    dotted,
    enclosing_function,
)
from k8s_dra_driver_tpu.analysis.engine import (
    Checker,
    Finding,
    SourceFile,
    register_checker,
)


def _base_and_attr(node: ast.AST) -> Tuple[Optional[ast.AST], Optional[str]]:
    """``<base>.<attr>`` (one optional subscript unwrapped) -> (base
    node, attr). Returns (None, None) for anything else."""
    if isinstance(node, ast.Subscript):
        node = node.value
    if isinstance(node, ast.Attribute):
        return node.value, node.attr
    return None, None


@register_checker
class ShardLockChecker(Checker):
    rule = "shard-lock"
    description = ("per-shard guarded state mutates only under its own "
                   "shard's lock; multi-shard acquisition only via the "
                   "canonical ordered-acquire helper")
    hint = ("wrap the mutation in `with <obj>.<lock>:` (or annotate the "
            "helper `# tpulint: holds=<lock>`); multi-shard work goes "
            "through the `# tpulint: ordered-acquire` helper")

    def check_file(self, sf: SourceFile) -> List[Finding]:
        guards = self._file_guards(sf)
        findings = self._check_external_mutations(sf, guards)
        findings.extend(self._check_multi_acquire(sf, set(guards.values())))
        return findings

    # -- discovery -----------------------------------------------------------

    @staticmethod
    def _file_guards(sf: SourceFile) -> Dict[str, str]:
        """attr -> lock attr, from every ``# tpulint: guarded-by=`` line
        in the file — whether declared via ``self.X = ...`` (__init__
        style) or a bare ``X: ... = ...`` class field. Parsed by the
        shared astutil.ModuleAnnotations reader (one source of truth with
        thread-shared-state and the runtime sanitizer)."""
        return dict(sf.annotations.file_guards)

    # -- rule 1: external guarded mutation ----------------------------------

    def _check_external_mutations(self, sf: SourceFile,
                                  guards: Dict[str, str]) -> List[Finding]:
        if not guards:
            return []
        findings: List[Finding] = []
        for node in ast.walk(sf.tree):
            base = attr = None
            if isinstance(node, (ast.Assign, ast.AugAssign, ast.Delete)):
                targets = (node.targets if isinstance(node, ast.Assign)
                           else [node.target] if isinstance(node, ast.AugAssign)
                           else node.targets)
                for t in targets:
                    b, a = _base_and_attr(t)
                    if a in guards:
                        base, attr = b, a
                        break
            elif (isinstance(node, ast.Call)
                  and isinstance(node.func, ast.Attribute)
                  and node.func.attr in _MUTATORS):
                b, a = _base_and_attr(node.func.value)
                if a in guards:
                    base, attr = b, a
            if attr is None:
                continue
            base_dotted = dotted(base) if base is not None else ""
            if base_dotted == "self":
                continue  # thread-shared-state owns in-class accesses
            lock = guards[attr]
            if self._holds_instance_lock(sf, node, base_dotted, lock):
                continue
            fn = enclosing_function(node, sf.parents)
            if fn is not None and getattr(fn, "name", "") == "__init__":
                continue  # construction: the instance isn't shared yet
            if fn is not None and lock in self._fn_holds(sf, fn):
                continue
            findings.append(self.finding(
                sf, node,
                f"{base_dotted or '<expr>'}.{attr} (guarded-by={lock}) "
                f"mutated without holding that instance's `{lock}` — "
                f"shard state torn under concurrent writers",
            ))
        return findings

    @staticmethod
    def _fn_holds(sf: SourceFile, fn) -> Set[str]:
        return set(sf.annotations.fn_holds(fn))

    @staticmethod
    def _holds_instance_lock(sf: SourceFile, node: ast.AST,
                             base_dotted: str, lock: str) -> bool:
        """Inside ``with <base>.<lock>:`` for the SAME base expr, or under
        the canonical whole-store acquire (``with ..._locked_all():``)."""
        want = f"{base_dotted}.{lock}" if base_dotted else None
        for anc in ancestors(node, sf.parents):
            if not isinstance(anc, ast.With):
                continue
            for item in anc.items:
                ce = item.context_expr
                if want and dotted(ce) == want:
                    return True
                if (isinstance(ce, ast.Call)
                        and isinstance(ce.func, ast.Attribute)
                        and ce.func.attr == "_locked_all"):
                    return True
        return False

    # -- rule 2: multi-shard acquisition -------------------------------------

    def _check_multi_acquire(self, sf: SourceFile,
                             lock_names: Set[str]) -> List[Finding]:
        if not lock_names:
            return []
        findings: List[Finding] = []
        for node in ast.walk(sf.tree):
            # Nested `with a.<lock>:` inside `with b.<lock>:`, same lock
            # attr, different non-self instances.
            if isinstance(node, ast.With):
                for item in node.items:
                    got = self._shard_lock_expr(item.context_expr, lock_names)
                    if got is None:
                        continue
                    base, lock = got
                    for anc in ancestors(node, sf.parents):
                        if not isinstance(anc, ast.With):
                            continue
                        for outer in anc.items:
                            outer_got = self._shard_lock_expr(
                                outer.context_expr, lock_names)
                            if (outer_got is not None
                                    and outer_got[1] == lock
                                    and outer_got[0] != base
                                    and not self._ordered(sf, node)):
                                findings.append(self.finding(
                                    sf, node,
                                    f"second shard lock `.{lock}` taken "
                                    f"while holding `{outer_got[0]}.{lock}`"
                                    f" — multi-shard acquisition only via "
                                    f"the ordered-acquire helper",
                                ))
            # Raw .acquire() on a non-self shard lock.
            elif (isinstance(node, ast.Call)
                  and isinstance(node.func, ast.Attribute)
                  and node.func.attr == "acquire"):
                got = self._shard_lock_expr(node.func.value, lock_names)
                if got is not None and not self._ordered(sf, node):
                    findings.append(self.finding(
                        sf, node,
                        f"raw `{got[0]}.{got[1]}.acquire()` outside the "
                        f"ordered-acquire helper — unordered multi-shard "
                        f"acquisition deadlocks",
                    ))
        return findings

    @staticmethod
    def _shard_lock_expr(node: ast.AST,
                         lock_names: Set[str]) -> Optional[Tuple[str, str]]:
        """``<non-self base>.<lockattr>`` -> (base dotted, lockattr)."""
        if not isinstance(node, ast.Attribute) or node.attr not in lock_names:
            return None
        base = dotted(node.value)
        if not base or base == "self" or base.startswith("self."):
            return None
        return base, node.attr

    @staticmethod
    def _ordered(sf: SourceFile, node: ast.AST) -> bool:
        """The enclosing function (or its def line) carries the
        ``# tpulint: ordered-acquire`` annotation."""
        return sf.annotations.fn_ordered(enclosing_function(node, sf.parents))
