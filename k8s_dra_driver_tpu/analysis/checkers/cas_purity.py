"""cas-purity: CAS mutation closures must be pure.

``update_with_retry`` re-runs its mutate closure on every resourceVersion
conflict (k8s/store.py, k8s/httpapi.py, k8s/kubeclient.py all share the
contract). Anything effectful inside the closure therefore happens a
nondeterministic number of times under contention: sleeps stretch the
retry loop, counter ``inc``/histogram ``observe`` calls inflate, events
double-emit, nested API writes interleave half-applied state, and I/O
repeats. PR 3 already burned one of these (the DaemonSet ready-count was
re-listed inside the closure); this rule stops the class.
"""

from __future__ import annotations

import ast
from typing import Dict, List, Optional, Tuple

from k8s_dra_driver_tpu.analysis.astutil import (
    ancestors,
    call_chain,
    enclosing_function,
    receiver_chain,
)
from k8s_dra_driver_tpu.analysis.engine import (
    Checker,
    Finding,
    SourceFile,
    register_checker,
)

# Effectful call patterns. Each entry: (predicate description, matcher).
_API_WRITE_ATTRS = {"create", "delete", "update", "update_with_retry"}
_METRIC_MUT_ATTRS = {"inc", "observe"}
_RECORDER_ATTRS = {"event", "normal", "warning"}
_IO_PREFIXES = ("os.", "subprocess.", "shutil.", "socket.", "requests.")
_IO_PURE_PREFIXES = ("os.path.", "os.environ.get",)


def _impurity(call: ast.Call) -> Optional[str]:
    chain = call_chain(call)
    recv = receiver_chain(call).lower()
    last = chain.rsplit(".", 1)[-1]
    if chain == "open":
        return "file I/O (open)"
    if last == "sleep" and ("time" in recv or chain == "sleep"):
        return "time.sleep (stretches every CAS retry)"
    if chain.startswith(_IO_PREFIXES) and not chain.startswith(_IO_PURE_PREFIXES):
        return f"I/O call {chain}"
    if last in _METRIC_MUT_ATTRS and recv:
        return f"metric mutation {chain} (inflates on every retry)"
    if last in _RECORDER_ATTRS and "recorder" in recv:
        return f"event emission {chain} (double-emits on retry)"
    if last in _API_WRITE_ATTRS and ("api" in recv or "store" in recv):
        return f"nested API write {chain}"
    return None


def _mutate_arg(call: ast.Call) -> Optional[ast.AST]:
    for kw in call.keywords:
        if kw.arg == "mutate":
            return kw.value
    # update_with_retry(kind, name, namespace, mutate, ...)
    if len(call.args) >= 4:
        return call.args[3]
    return None


def _function_index(
    sf: SourceFile,
) -> Dict[str, List[Tuple[ast.FunctionDef, Tuple[ast.AST, ...]]]]:
    """name -> [(def node, enclosing-scope chain)] for closure lookup."""
    out: Dict[str, List[Tuple[ast.FunctionDef, Tuple[ast.AST, ...]]]] = {}
    for node in ast.walk(sf.tree):
        if isinstance(node, ast.FunctionDef):
            scope = tuple(
                a for a in ancestors(node, sf.parents)
                if isinstance(a, (ast.FunctionDef, ast.AsyncFunctionDef))
            )
            out.setdefault(node.name, []).append((node, scope))
    return out


@register_checker
class CasPurityChecker(Checker):
    rule = "cas-purity"
    description = ("no I/O, sleeps, event emission, metric mutation, or "
                   "nested API writes inside update_with_retry closures "
                   "(they re-run on CAS conflict)")
    hint = ("compute effectful values before the closure and capture them "
            "(the PR 3 _daemonset_pass pattern), or move the side effect "
            "after the update returns")

    def check_file(self, sf: SourceFile) -> List[Finding]:
        findings: List[Finding] = []
        fn_index = None
        for node in ast.walk(sf.tree):
            if not (isinstance(node, ast.Call)
                    and isinstance(node.func, ast.Attribute)
                    and node.func.attr == "update_with_retry"):
                continue
            mutate = _mutate_arg(node)
            body: Optional[ast.AST] = None
            if isinstance(mutate, ast.Lambda):
                body = mutate
            elif isinstance(mutate, ast.Name):
                if fn_index is None:
                    fn_index = _function_index(sf)
                body = self._resolve(sf, node, mutate.id, fn_index)
            if body is None:
                continue
            for sub in ast.walk(body):
                if isinstance(sub, ast.Call):
                    why = _impurity(sub)
                    if why:
                        findings.append(self.finding(
                            sf, sub,
                            f"{why} inside an update_with_retry closure",
                        ))
        return findings

    @staticmethod
    def _resolve(sf, call, name, fn_index):
        """Pick the lexically-nearest FunctionDef named ``name``: the one
        whose enclosing-scope chain is the longest suffix of the call
        site's own chain (plain lexical scoping, no imports)."""
        candidates = fn_index.get(name, [])
        if not candidates:
            return None
        call_scope = tuple(
            a for a in ancestors(call, sf.parents)
            if isinstance(a, (ast.FunctionDef, ast.AsyncFunctionDef))
        )
        best, best_depth = None, -1
        for node, scope in candidates:
            # A def visible from the call shares the call's scope chain
            # as a suffix (module level: empty chain, always a suffix).
            if scope == call_scope[len(call_scope) - len(scope):] \
                    and len(scope) > best_depth:
                best, best_depth = node, len(scope)
        return best
