"""snapshot-mutation: objects handed out by the API are frozen snapshots.

The zero-copy store publishes immutable snapshots: ``api.get`` /
``api.try_get`` / ``api.list`` (and informer listers, and watch event
``.obj`` payloads) return the stored object itself, not a private copy.
Mutating one corrupts every other reader's view — at runtime the freeze
seal raises FrozenSnapshotError, but only on the path that actually
executes. This rule finds the pattern statically: attribute assignment,
``del``, augmented assignment, or a container-mutator call rooted at a
name bound from a snapshot-returning read.

Sanctioned escapes, which all stop the tracking:

- ``copy=True`` on the read (the explicit private-mutable-copy opt-out),
- rebinding through ``.deepcopy()`` / ``copy.deepcopy`` / ``thaw(...)``
  / ``.thaw()``,
- the working object inside an ``update_with_retry`` mutate closure
  (the closure parameter is a thawed copy-on-write copy, never a name
  this rule tracks).
"""

from __future__ import annotations

import ast
from typing import Dict, Iterable, List, Optional, Set

from k8s_dra_driver_tpu.analysis.astutil import MUTATORS, call_chain, receiver_chain
from k8s_dra_driver_tpu.analysis.engine import (
    Checker,
    Finding,
    SourceFile,
    register_checker,
)

# Read methods that hand out published snapshots when called on an
# API-ish receiver.
_SNAPSHOT_READS = {"get", "try_get", "list", "list_and_watch"}
# Receiver-name fragments that mark a call as an API/cache read rather
# than, say, ``dict.get``. Deliberately the same loose style cas-purity
# uses: checkers match idiom, not types.
_API_RECV_FRAGMENTS = ("api", "store", "informer", "lister", "client", "cache")
# Names whose ``.obj`` attribute is a watch event payload.
_EVENT_NAMES = ("ev", "evt", "event")
# Rebinding through these severs tracking (the value is a private copy).
_COPYING_CALLS = {"deepcopy", "thaw"}


def _is_true(node: Optional[ast.AST]) -> bool:
    return isinstance(node, ast.Constant) and node.value is True


def _root_name(node: ast.AST) -> Optional[str]:
    """Leftmost Name of an attribute/subscript chain, else None."""
    while isinstance(node, (ast.Attribute, ast.Subscript)):
        node = node.value
    if isinstance(node, ast.Name):
        return node.id
    return None


class _Scope:
    """One function (or module) body's tracked snapshot bindings."""

    def __init__(self) -> None:
        self.snapshots: Set[str] = set()   # names bound to snapshots
        self.lists: Set[str] = set()       # names bound to snapshot LISTS


@register_checker
class SnapshotMutationChecker(Checker):
    rule = "snapshot-mutation"
    description = ("no attribute writes or container mutations on objects "
                   "handed out by api.get/try_get/list, informer listers, "
                   "or watch events — they are shared frozen snapshots")
    hint = ("mutate inside an update_with_retry closure (copy-on-write), "
            "or take a private copy first: read with copy=True, or rebind "
            "through .deepcopy()/thaw()")

    def check_file(self, sf: SourceFile) -> List[Finding]:
        findings: List[Finding] = []
        self._walk_body(sf, sf.tree.body, _Scope(), findings)
        return findings

    # -- snapshot sources ----------------------------------------------------

    @staticmethod
    def _is_snapshot_read(call: ast.Call) -> Optional[str]:
        """'obj' for single-object reads, 'list' for list reads, None
        when the call is not a snapshot source (including copy=True)."""
        if not isinstance(call.func, ast.Attribute):
            return None
        attr = call.func.attr
        if attr not in _SNAPSHOT_READS:
            return None
        recv = receiver_chain(call).lower()
        if not any(frag in recv for frag in _API_RECV_FRAGMENTS):
            return None
        for kw in call.keywords:
            if kw.arg == "copy" and _is_true(kw.value):
                return None
        return "list" if attr == "list" else "obj"

    def _classify(self, expr: ast.AST, scope: _Scope) -> Optional[str]:
        """What binding ``expr`` produces: 'obj', 'list', or None."""
        if isinstance(expr, ast.Call):
            chain = call_chain(expr)
            last = chain.rsplit(".", 1)[-1]
            if last in _COPYING_CALLS:
                return None  # private copy: tracking severed
            kind = self._is_snapshot_read(expr)
            if kind is not None:
                return kind
            return None
        if isinstance(expr, ast.Attribute) and expr.attr == "obj":
            root = _root_name(expr.value)
            if root is not None and (root in _EVENT_NAMES
                                     or root.endswith("_ev")
                                     or root.endswith("_event")):
                return "obj"
            return None
        if isinstance(expr, ast.Subscript):
            root = _root_name(expr.value)
            if root in scope.lists:
                return "obj"
            return None
        if isinstance(expr, ast.Name):
            if expr.id in scope.snapshots:
                return "obj"
            if expr.id in scope.lists:
                return "list"
            return None
        return None

    # -- ordered body walk ---------------------------------------------------

    def _walk_body(self, sf: SourceFile, body: Iterable[ast.stmt],
                   scope: _Scope, findings: List[Finding]) -> None:
        for stmt in body:
            self._walk_stmt(sf, stmt, scope, findings)

    def _walk_stmt(self, sf: SourceFile, stmt: ast.stmt, scope: _Scope,
                   findings: List[Finding]) -> None:
        if isinstance(stmt, (ast.FunctionDef, ast.AsyncFunctionDef)):
            # Fresh scope: closures over outer snapshot names are rare
            # and re-tracked when the inner function re-reads; a nested
            # def's params are never snapshots.
            self._walk_body(sf, stmt.body, _Scope(), findings)
            return
        if isinstance(stmt, ast.ClassDef):
            self._walk_body(sf, stmt.body, _Scope(), findings)
            return
        if isinstance(stmt, ast.Assign):
            self._check_mutations(sf, stmt, scope, findings)
            kind = self._classify(stmt.value, scope)
            for tgt in stmt.targets:
                self._bind(tgt, kind, scope)
            return
        if isinstance(stmt, ast.AnnAssign):
            self._check_mutations(sf, stmt, scope, findings)
            if stmt.value is not None and isinstance(stmt.target, ast.Name):
                self._bind(stmt.target, self._classify(stmt.value, scope),
                           scope)
            return
        if isinstance(stmt, ast.AugAssign):
            self._check_mutations(sf, stmt, scope, findings)
            return
        if isinstance(stmt, ast.Delete):
            for tgt in stmt.targets:
                if isinstance(tgt, ast.Attribute):
                    root = _root_name(tgt)
                    if root in scope.snapshots:
                        findings.append(self.finding(
                            sf, tgt,
                            f"del on attribute of snapshot '{root}' "
                            f"(published snapshots are frozen)"))
            return
        if isinstance(stmt, ast.For):
            iter_kind = self._classify(stmt.iter, scope)
            if iter_kind == "list":
                self._bind(stmt.target, "obj", scope)
            self._check_mutations(sf, stmt.iter, scope, findings)
            self._walk_body(sf, stmt.body, scope, findings)
            self._walk_body(sf, stmt.orelse, scope, findings)
            return
        if isinstance(stmt, (ast.If, ast.While)):
            self._check_mutations(sf, stmt.test, scope, findings)
            self._walk_body(sf, stmt.body, scope, findings)
            self._walk_body(sf, stmt.orelse, scope, findings)
            return
        if isinstance(stmt, ast.With):
            for item in stmt.items:
                self._check_mutations(sf, item.context_expr, scope, findings)
            self._walk_body(sf, stmt.body, scope, findings)
            return
        if isinstance(stmt, ast.Try):
            self._walk_body(sf, stmt.body, scope, findings)
            for h in stmt.handlers:
                self._walk_body(sf, h.body, scope, findings)
            self._walk_body(sf, stmt.orelse, scope, findings)
            self._walk_body(sf, stmt.finalbody, scope, findings)
            return
        # Expression statements and everything else: scan for mutator
        # calls and walrus bindings.
        self._check_mutations(sf, stmt, scope, findings)

    def _bind(self, target: ast.AST, kind: Optional[str],
              scope: _Scope) -> None:
        if isinstance(target, ast.Name):
            scope.snapshots.discard(target.id)
            scope.lists.discard(target.id)
            if kind == "obj":
                scope.snapshots.add(target.id)
            elif kind == "list":
                scope.lists.add(target.id)
        elif isinstance(target, ast.Tuple):
            # list_and_watch returns (objs, queue): first element is the
            # snapshot list, the rest untracked.
            for i, elt in enumerate(target.elts):
                self._bind(elt, kind if i == 0 else None, scope)

    # -- mutation sites ------------------------------------------------------

    def _check_mutations(self, sf: SourceFile, node: ast.AST, scope: _Scope,
                         findings: List[Finding]) -> None:
        if not scope.snapshots and not scope.lists:
            # Still record walrus bindings inside the expression.
            for sub in ast.walk(node):
                if isinstance(sub, ast.NamedExpr):
                    self._bind(sub.target, self._classify(sub.value, scope),
                               scope)
            return
        for sub in ast.walk(node):
            if isinstance(sub, ast.NamedExpr):
                self._bind(sub.target, self._classify(sub.value, scope),
                           scope)
            elif isinstance(sub, (ast.Assign, ast.AugAssign, ast.AnnAssign)):
                targets = (sub.targets if isinstance(sub, ast.Assign)
                           else [sub.target])
                for tgt in targets:
                    if isinstance(tgt, (ast.Attribute, ast.Subscript)):
                        root = _root_name(tgt)
                        if root in scope.snapshots:
                            findings.append(self.finding(
                                sf, tgt,
                                f"attribute write on snapshot '{root}' "
                                f"(published snapshots are frozen)"))
                        elif root in scope.lists:
                            findings.append(self.finding(
                                sf, tgt,
                                f"item write on snapshot list '{root}' "
                                f"(list() hands out shared references)"))
            elif isinstance(sub, ast.Call):
                self._check_mutator_call(sf, sub, scope, findings)

    def _check_mutator_call(self, sf: SourceFile, call: ast.Call,
                            scope: _Scope, findings: List[Finding]) -> None:
        if not isinstance(call.func, ast.Attribute):
            return
        if call.func.attr not in MUTATORS:
            return
        root = _root_name(call.func.value)
        if root is None:
            return
        if root in scope.snapshots:
            # obj.nodes.append(x), obj.labels.update(...) — but a bare
            # tracked LIST name's own .append is only a local-list edit
            # when the list was rebound; snapshots stay flagged.
            findings.append(self.finding(
                sf, call,
                f"container mutation {call_chain(call)} on snapshot "
                f"'{root}' (published snapshots are frozen)"))
        elif root in scope.lists and isinstance(call.func.value,
                                                (ast.Attribute,
                                                 ast.Subscript)):
            # pods[0].containers.append(...) / mutating through an
            # element of a snapshot list. A plain ``pods.append(x)`` on
            # the returned list object itself is NOT flagged: list()
            # returns a fresh list; only the elements are shared.
            findings.append(self.finding(
                sf, call,
                f"container mutation {call_chain(call)} through snapshot "
                f"list '{root}' (elements are shared frozen snapshots)"))
