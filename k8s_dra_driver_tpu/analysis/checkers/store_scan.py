"""store-scan: no full-kind store scans inside per-item loops.

PR 3 indexed the APIServer store and gave the allocator/scheduler
point lookups precisely so hot loops stop paying O(kind) per item; a
``store.list()`` (or ``api.list()``) inside a ``for``/``while`` body
reintroduces the O(n·m) scan the bench budgets exist to catch. Listing
*as* the loop's iterable is fine — that is one scan. Informer caches
(``*_informer.list()``) are exempt: they serve from memory.
"""

from __future__ import annotations

import ast
from typing import List

from k8s_dra_driver_tpu.analysis.astutil import (
    call_chain,
    in_loop_body,
    receiver_chain,
)
from k8s_dra_driver_tpu.analysis.engine import (
    Checker,
    Finding,
    SourceFile,
    register_checker,
)


@register_checker
class StoreScanChecker(Checker):
    rule = "store-scan"
    description = ("no store/api list() scans inside per-item loops in "
                   "sim/, controller/, autoscaler/, and scheduling/ — "
                   "hoist the scan or use the PR 3 indexes")
    hint = ("hoist the list() above the loop (one scan, filter in "
            "Python), or use try_get/feasibility indexes")
    scope = ("k8s_dra_driver_tpu/sim/", "k8s_dra_driver_tpu/controller/",
             "k8s_dra_driver_tpu/autoscaler/",
             "k8s_dra_driver_tpu/scheduling/",
             # The global scheduler and replica apply path run per
             # placement round / per WAL record — same hot-loop bar.
             "k8s_dra_driver_tpu/federation/",
             # The flight recorder feeds every pass and the explain path
             # walks the store per command — same hot-loop discipline.
             "k8s_dra_driver_tpu/pkg/history.py",
             # The lifecycle analyzer's whole contract is zero list()
             # calls in steady state — the lint holds the floor the
             # bench gate measures.
             "k8s_dra_driver_tpu/pkg/lifecycle.py")

    def check_file(self, sf: SourceFile) -> List[Finding]:
        findings: List[Finding] = []
        for node in ast.walk(sf.tree):
            if not (isinstance(node, ast.Call)
                    and isinstance(node.func, ast.Attribute)
                    and node.func.attr == "list"):
                continue
            recv = receiver_chain(node).lower()
            if not recv or "informer" in recv:
                continue
            if not ("api" in recv.split(".")[-1] or "store" in recv):
                continue
            if in_loop_body(node, sf.parents):
                findings.append(self.finding(
                    sf, node,
                    f"store scan {call_chain(node)}() inside a per-item "
                    f"loop — O(kind) work repeated every iteration",
                ))
        return findings
