"""Long-context flagship: the SliceProof transformer trained with ring
attention over a sequence-parallel mesh axis.

Fourth composition of the workload tier: batch activations are sharded
along the *sequence* dimension over ``sp`` (every device holds T/n tokens
of every example); attention runs as the ring schedule
(``parallel/ring_attention.py``) so no device ever materializes the full
sequence — the configuration for contexts that do not fit one chip's HBM.
Dense ops (FF, norms, embeddings) stay under ``jit`` with sequence
sharding constraints; XLA inserts the halo/collectives it needs (e.g. for
the next-token shift in the loss).

Use ``parallel/ulysses.py`` instead when heads divide the axis and a
fused full-sequence kernel is preferred; this module is the O(T/n)-memory
choice.
"""

from __future__ import annotations

from functools import partial
from typing import Any, Dict, Sequence

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from k8s_dra_driver_tpu.models.common import (
    make_sharded_state,
    make_token_batch,
    meshed_step,
    momentum_sgd,
    nll_loss,
    rmsnorm as _rmsnorm,
)
from k8s_dra_driver_tpu.models.flagship import (
    SliceProofConfig,
    init_params,
)
from k8s_dra_driver_tpu.parallel.mesh import family_mesh
from k8s_dra_driver_tpu.parallel.ring_attention import ring_attention
from k8s_dra_driver_tpu.parallel.ulysses import ulysses_attention

Params = Dict[str, Any]

# The two sequence-parallel attention strategies share a signature, so
# the model is strategy-agnostic: "ring" never materializes the full
# sequence (O(T/n) memory); "ulysses" trades two dense all-to-alls for
# full-sequence attention per head subset (needs heads % axis == 0).
_ATTENTION = {"ring": ring_attention, "ulysses": ulysses_attention}


def _pin_seq(x: jax.Array, seq_axis: str, batch_axis=None) -> jax.Array:
    spec = (P(batch_axis, seq_axis) if x.ndim == 2
            else P(batch_axis, seq_axis, *([None] * (x.ndim - 2))))
    return jax.lax.with_sharding_constraint(x, spec)


def _block(cfg: SliceProofConfig, p: Params, x: jax.Array,
           mesh: Mesh, seq_axis: str, batch_axis=None,
           attention: str = "ring") -> jax.Array:
    h = _rmsnorm(x, p["ln1"])
    qkv = jnp.einsum("bsd,dthk->tbshk", h, p["wqkv"].astype(jnp.bfloat16))
    q = _pin_seq(qkv[0], seq_axis, batch_axis)
    k = _pin_seq(qkv[1], seq_axis, batch_axis)
    v = _pin_seq(qkv[2], seq_axis, batch_axis)
    attn = _ATTENTION[attention](q, k, v, mesh, seq_axis=seq_axis,
                                 causal=True, batch_axis=batch_axis)
    x = x + jnp.einsum("bshk,hkd->bsd", attn, p["wo"].astype(jnp.bfloat16))

    h = _rmsnorm(x, p["ln2"])
    ff = jax.nn.gelu(jnp.einsum("bsd,df->bsf", h, p["w1"].astype(jnp.bfloat16)))
    ff = _pin_seq(ff, seq_axis, batch_axis)
    return x + jnp.einsum("bsf,fd->bsd", ff, p["w2"].astype(jnp.bfloat16))


def forward(cfg: SliceProofConfig, params: Params, tokens: jax.Array,
            mesh: Mesh, seq_axis: str = "sp", batch_axis=None,
            attention: str = "ring") -> jax.Array:
    x = _pin_seq(params["embed"].astype(jnp.bfloat16)[tokens], seq_axis, batch_axis)
    for p in params["layers"]:
        x = _block(cfg, p, x, mesh, seq_axis, batch_axis, attention)
    return jnp.einsum(
        "bsd,dv->bsv", x, params["unembed"].astype(jnp.bfloat16)
    ).astype(jnp.float32)


def loss_fn(cfg, params, batch, mesh, seq_axis: str = "sp", batch_axis=None,
            attention: str = "ring"):
    return nll_loss(
        forward(cfg, params, batch["tokens"], mesh, seq_axis, batch_axis,
                attention),
        batch["tokens"])


def make_longcontext_train_step(
    cfg: SliceProofConfig,
    devices: Sequence,
    *,
    batch_size: int = 1,
    seed: int = 0,
    seq_axis: str = "sp",
    data_parallel: int = 1,
    attention: str = "ring",
):
    """Build (jitted_step, sharded_state, sharded_batch) with the sequence
    sharded over the sp axis. ``data_parallel`` > 1 composes dp×sp: the
    batch dimension shards over a data axis whose replicas each run their
    own attention ring (or Ulysses group) over
    ``len(devices)/data_parallel`` devices. ``attention`` picks the
    sequence-parallel strategy: "ring" (O(T/n) memory) or "ulysses"
    (all-to-all head exchange; needs cfg.n_heads % group == 0).
    cfg.seq_len must divide by the group size."""
    n = len(devices)
    if n % data_parallel:
        raise ValueError(f"device count ({n}) must divide by data_parallel "
                         f"({data_parallel})")
    ring = n // data_parallel
    if cfg.seq_len % ring:
        raise ValueError(f"seq_len ({cfg.seq_len}) must divide by ring size ({ring})")
    if attention not in _ATTENTION:
        raise ValueError(f"unknown attention strategy {attention!r}; "
                         f"want one of {sorted(_ATTENTION)}")
    if attention == "ulysses" and cfg.n_heads % ring:
        raise ValueError(f"ulysses needs n_heads ({cfg.n_heads}) divisible "
                         f"by the sp group size ({ring})")
    if cfg.attention != "einsum":
        raise ValueError("long-context training uses sequence-parallel "
                         "attention; cfg.attention must stay 'einsum' "
                         "(the default)")
    if data_parallel > 1:
        # sp innermost: ring hops stay on neighbor ICI links (bundle-
        # ordered when a mesh bundle is ambient); the gradient allreduce
        # crosses the outer data axis.
        mesh = family_mesh(devices, (data_parallel, ring), ("data", seq_axis))
        batch_axis = "data"
        batch_size = batch_size * data_parallel
        batch_spec = P("data", seq_axis)
    else:
        mesh = family_mesh(devices, (n,), (seq_axis,))
        batch_axis = None
        batch_spec = P(None, seq_axis)
    pspecs = jax.tree.map(lambda _: P(), init_params(cfg, seed=seed))
    state = make_sharded_state(init_params(cfg, seed=seed), pspecs, mesh)
    batch = make_token_batch(seed, batch_size, cfg.seq_len, cfg.vocab,
                             mesh, batch_spec)

    def train_step(state, batch):
        params, mom = state["params"], state["momentum"]
        loss, grads = jax.value_and_grad(partial(
            loss_fn, cfg, seq_axis=seq_axis, batch_axis=batch_axis,
            attention=attention,
        ), argnums=0)(params, batch, mesh)
        new_params, new_mom = momentum_sgd(params, mom, grads, cfg.learning_rate)
        return {"params": new_params, "momentum": new_mom}, loss

    jitted = jax.jit(train_step, donate_argnums=(0,))
    return meshed_step(jitted, mesh), state, batch
