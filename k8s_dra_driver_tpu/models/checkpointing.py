"""Workload-side checkpoint/resume: orbax save/restore of sharded state.

The training-tier counterpart of the driver's crash-consistent claim
checkpoint (`plugins/checkpoint.py`): a job running on a claimed slice
persists its sharded train state and resumes after preemption — including
onto a *different* slice shape (elastic resume: a claim regranted as 8
chips restores a 4-chip checkpoint; orbax reshards on load from the target
sharding tree, so the restore is a resharded read, not a gather-then-
scatter through host memory).

No counterpart in the reference (resource layer); this is what makes
driver-level preemption (health taints, domain teardown) survivable for
the workload.
"""

from __future__ import annotations

import logging
import os
from typing import Any, Optional

import jax


def _checkpointer():
    import orbax.checkpoint as ocp

    return ocp.StandardCheckpointer()


def save_train_state(ckpt_dir: str, step: int, state: Any) -> str:
    """Persist the (sharded) train state for ``step``. Blocks until the
    write is durable. Returns the step directory."""
    path = os.path.join(os.path.abspath(ckpt_dir), f"step_{step}")
    ckptr = _checkpointer()
    ckptr.save(path, state)
    ckptr.wait_until_finished()
    return path


def _is_finalized(path: str) -> bool:
    """True when the checkpoint at ``path`` is committed, not just named
    like one: it must be a non-empty directory (a crash between mkdir and
    content leaves an empty husk) that orbax does not consider an
    in-progress tmp dir (tmp naming schemes change across orbax versions —
    ask orbax instead of pattern-matching)."""
    if not os.path.isdir(path):
        return False
    try:
        if not os.listdir(path):
            return False
    except OSError:
        return False
    try:
        import orbax.checkpoint as ocp
    except ImportError:
        return True  # non-orbax layout: non-empty dir is the best signal
    try:
        check = ocp.utils.is_checkpoint_finalized
    except AttributeError:
        return True  # older orbax without the helper
    try:
        return bool(check(path))
    except Exception:  # noqa: BLE001 — transient IO must not fail open
        logging.getLogger(__name__).warning(
            "is_checkpoint_finalized(%s) errored; treating as unfinalized",
            path, exc_info=True,
        )
        return False


def latest_step(ckpt_dir: str) -> Optional[int]:
    """Newest step with a finalized checkpoint, or None. Candidates are
    checked newest-first and the first finalized one wins, so resume costs
    O(1) finalization checks, not one per retained step."""
    ckpt_dir = os.path.abspath(ckpt_dir)
    try:
        entries = os.listdir(ckpt_dir)
    except FileNotFoundError:
        return None
    candidates = sorted(
        (int(e.split("_", 1)[1]) for e in entries
         if e.startswith("step_") and e.split("_", 1)[1].isdigit()),
        reverse=True,
    )
    for step in candidates:
        if _is_finalized(os.path.join(ckpt_dir, f"step_{step}")):
            return step
    return None


def restore_train_state(ckpt_dir: str, step: int, target: Any) -> Any:
    """Restore ``step`` resharded onto ``target``'s shardings.

    target: a pytree of arrays OR jax.ShapeDtypeStruct leaves carrying the
    *destination* shardings (current mesh — may differ from the one that
    saved). Passing a live state tree restores 'like' it without keeping
    two copies alive: leaves are converted to abstract structs first.
    """
    abstract = jax.tree.map(
        lambda x: jax.ShapeDtypeStruct(x.shape, x.dtype, sharding=getattr(x, "sharding", None))
        if not isinstance(x, jax.ShapeDtypeStruct) else x,
        target,
    )
    path = os.path.join(os.path.abspath(ckpt_dir), f"step_{step}")
    return _checkpointer().restore(path, abstract)
