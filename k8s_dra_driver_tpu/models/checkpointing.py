"""Workload-side checkpoint/resume: orbax save/restore of sharded state.

The training-tier counterpart of the driver's crash-consistent claim
checkpoint (`plugins/checkpoint.py`): a job running on a claimed slice
persists its sharded train state and resumes after preemption — including
onto a *different* slice shape (elastic resume: a claim regranted as 8
chips restores a 4-chip checkpoint; orbax reshards on load from the target
sharding tree, so the restore is a resharded read, not a gather-then-
scatter through host memory).

No counterpart in the reference (resource layer); this is what makes
driver-level preemption (health taints, domain teardown) survivable for
the workload.
"""

from __future__ import annotations

import os
from typing import Any, Optional

import jax


def _checkpointer():
    import orbax.checkpoint as ocp

    return ocp.StandardCheckpointer()


def save_train_state(ckpt_dir: str, step: int, state: Any) -> str:
    """Persist the (sharded) train state for ``step``. Blocks until the
    write is durable. Returns the step directory."""
    path = os.path.join(os.path.abspath(ckpt_dir), f"step_{step}")
    ckptr = _checkpointer()
    ckptr.save(path, state)
    ckptr.wait_until_finished()
    return path


def latest_step(ckpt_dir: str) -> Optional[int]:
    """Newest step with a finalized checkpoint, or None."""
    try:
        entries = os.listdir(ckpt_dir)
    except FileNotFoundError:
        return None
    steps = [int(e.split("_", 1)[1]) for e in entries
             if e.startswith("step_") and e.split("_", 1)[1].isdigit()]
    return max(steps) if steps else None


def restore_train_state(ckpt_dir: str, step: int, target: Any) -> Any:
    """Restore ``step`` resharded onto ``target``'s shardings.

    target: a pytree of arrays OR jax.ShapeDtypeStruct leaves carrying the
    *destination* shardings (current mesh — may differ from the one that
    saved). Passing a live state tree restores 'like' it without keeping
    two copies alive: leaves are converted to abstract structs first.
    """
    abstract = jax.tree.map(
        lambda x: jax.ShapeDtypeStruct(x.shape, x.dtype, sharding=getattr(x, "sharding", None))
        if not isinstance(x, jax.ShapeDtypeStruct) else x,
        target,
    )
    path = os.path.join(os.path.abspath(ckpt_dir), f"step_{step}")
    return _checkpointer().restore(path, abstract)
