"""SliceProof-MoE: the flagship's switch-routed Mixture-of-Experts sibling.

Second model family of the workload tier: a transformer whose every other
FF layer is a switch-MoE (``parallel/expert.py``) with one expert per
device along a single ``ep`` mesh axis that also carries data parallelism
— the canonical TPU MoE layout (experts ride the same devices the batch is
sharded over; dispatch is one all_to_all each way). Dense blocks replicate
their params and let XLA data-parallelize; expert blocks shard_map.

Training uses the Switch Transformer auxiliary load-balancing loss
(n_experts · Σ_e f_e·p_e over tokens-fraction f and router-prob mass p) so
routing does not collapse onto one expert.

No counterpart in the reference (resource layer). Public Switch/GShard
formulation; implementation original.
"""

from __future__ import annotations

from dataclasses import dataclass
from functools import partial
from typing import Any, Dict, Sequence

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, PartitionSpec as P

from k8s_dra_driver_tpu.models.common import (
    causal_einsum_attention,
    make_sharded_state,
    make_token_batch,
    meshed_step,
    momentum_sgd,
    nll_loss,
    rmsnorm as _rmsnorm,
)
from k8s_dra_driver_tpu.parallel.expert import init_moe_params, moe_ffn
from k8s_dra_driver_tpu.parallel.mesh import family_mesh

Params = Dict[str, Any]


@dataclass(frozen=True)
class MoEConfig:
    vocab: int = 256
    d_model: int = 128
    n_heads: int = 8
    n_layers: int = 4          # even layers dense FF, odd layers MoE
    d_ff: int = 512
    seq_len: int = 64
    n_experts: int = 4         # must equal the ep mesh size
    capacity_factor: float = 2.0
    aux_loss_coef: float = 0.01
    learning_rate: float = 1e-3

    @property
    def head_dim(self) -> int:
        assert self.d_model % self.n_heads == 0
        return self.d_model // self.n_heads

    def is_moe_layer(self, i: int) -> bool:
        return i % 2 == 1

    @classmethod
    def tiny(cls, n_experts: int = 4) -> "MoEConfig":
        return cls(n_experts=n_experts)


def init_params(cfg: MoEConfig, seed: int = 0) -> Params:
    key = jax.random.PRNGKey(seed)
    keys = jax.random.split(key, 2 + cfg.n_layers)
    scale = 0.02

    def dense(k, *shape):
        return scale * jax.random.normal(k, shape, dtype=jnp.float32)

    layers = []
    for i in range(cfg.n_layers):
        k = keys[2 + i]
        ka, kf = jax.random.split(k)
        layer: Params = {
            "ln1": jnp.ones((cfg.d_model,), jnp.float32),
            "ln2": jnp.ones((cfg.d_model,), jnp.float32),
            "wqkv": dense(ka, cfg.d_model, 3, cfg.n_heads, cfg.head_dim),
            "wo": dense(jax.random.fold_in(ka, 1), cfg.n_heads, cfg.head_dim, cfg.d_model),
        }
        if cfg.is_moe_layer(i):
            layer["moe"] = init_moe_params(kf, cfg.d_model, cfg.d_ff, cfg.n_experts)
        else:
            layer["w1"] = dense(kf, cfg.d_model, cfg.d_ff)
            layer["w2"] = dense(jax.random.fold_in(kf, 1), cfg.d_ff, cfg.d_model)
        layers.append(layer)
    return {
        "embed": dense(keys[0], cfg.vocab, cfg.d_model),
        "unembed": dense(keys[1], cfg.d_model, cfg.vocab),
        "layers": layers,
    }


def param_pspecs(cfg: MoEConfig, axis: str = "ep") -> Params:
    """Sharding specs: expert-stacked leaves along ``axis``, rest replicated."""
    layers = []
    for i in range(cfg.n_layers):
        layer = {"ln1": P(), "ln2": P(), "wqkv": P(), "wo": P()}
        if cfg.is_moe_layer(i):
            layer["moe"] = {"router": P(), "w1": P(axis), "w2": P(axis)}
        else:
            layer["w1"] = P()
            layer["w2"] = P()
        layers.append(layer)
    return {"embed": P(), "unembed": P(), "layers": layers}


def _attention(cfg: MoEConfig, p: Params, x: jax.Array) -> jax.Array:
    return causal_einsum_attention(p, x, _rmsnorm(x, p["ln1"]), cfg.head_dim)


def _aux_loss(logits2d: jax.Array, n_experts: int) -> jax.Array:
    """Switch LB loss: n_experts · Σ_e (token fraction)·(prob mass)."""
    probs = jax.nn.softmax(logits2d.astype(jnp.float32), axis=-1)
    frac = jnp.mean(jax.nn.one_hot(jnp.argmax(probs, -1), n_experts), axis=0)
    mass = jnp.mean(probs, axis=0)
    return n_experts * jnp.sum(frac * mass)


def forward(cfg: MoEConfig, params: Params, tokens: jax.Array, mesh: Mesh,
            batch_axis=None):
    """tokens [b, s] -> (logits [b, s, vocab] f32, aux_loss scalar)."""
    x = params["embed"].astype(jnp.bfloat16)[tokens]
    b, s, d = x.shape
    aux = jnp.zeros((), jnp.float32)
    for i, p in enumerate(params["layers"]):
        x = _attention(cfg, p, x)
        h = _rmsnorm(x, p["ln2"])
        if cfg.is_moe_layer(i):
            flat = h.reshape(b * s, d)
            logits = flat @ p["moe"]["router"]  # shared: aux loss + dispatch
            aux = aux + _aux_loss(logits, cfg.n_experts)
            x = x + moe_ffn(
                p["moe"], flat, mesh,
                capacity_factor=cfg.capacity_factor,
                router_logits=logits,
                batch_axis=batch_axis,
            ).reshape(b, s, d).astype(x.dtype)
        else:
            ff = jax.nn.gelu(jnp.einsum("bsd,df->bsf", h, p["w1"].astype(jnp.bfloat16)))
            x = x + jnp.einsum("bsf,fd->bsd", ff, p["w2"].astype(jnp.bfloat16))
    logits = jnp.einsum("bsd,dv->bsv", x, params["unembed"].astype(jnp.bfloat16))
    return logits.astype(jnp.float32), aux


def loss_fn(cfg: MoEConfig, params: Params, batch: Dict[str, jax.Array],
            mesh: Mesh, batch_axis=None):
    logits, aux = forward(cfg, params, batch["tokens"], mesh, batch_axis=batch_axis)
    return nll_loss(logits, batch["tokens"]) + cfg.aux_loss_coef * aux


def make_moe_train_step(
    cfg: MoEConfig,
    devices: Sequence,
    *,
    batch_per_replica: int = 2,
    seed: int = 0,
    expert_axis: str = "ep",
    data_parallel: int = 1,
):
    """Build (jitted_step, sharded_state, sharded_batch). The 1-D ep mesh
    carries both data parallelism and expert placement; ``data_parallel``
    > 1 composes an explicit dp×ep mesh instead — experts replicate over
    the data axis (n_experts × data_parallel == device count) and every
    data replica dispatches among its own ep peers."""
    n = len(devices)
    if cfg.n_experts * data_parallel != n:
        raise ValueError(
            f"n_experts*data_parallel ({cfg.n_experts}*{data_parallel}) "
            f"must equal device count ({n})"
        )
    if data_parallel > 1:
        # ep innermost: the a2a dispatch rides neighbor ICI links (bundle-
        # ordered when a mesh bundle is ambient); the expert-grad allreduce
        # crosses the outer data axis.
        mesh = family_mesh(devices, (data_parallel, cfg.n_experts),
                           ("data", expert_axis))
        batch_axis = "data"
        batch_spec = P(("data", expert_axis), None)
    else:
        mesh = family_mesh(devices, (n,), (expert_axis,))
        batch_axis = None
        batch_spec = P(expert_axis, None)
    state = make_sharded_state(
        init_params(cfg, seed=seed), param_pspecs(cfg, expert_axis), mesh)
    batch = make_token_batch(seed, n * batch_per_replica, cfg.seq_len,
                             cfg.vocab, mesh, batch_spec)

    def train_step(state, batch):
        params, mom = state["params"], state["momentum"]
        loss, grads = jax.value_and_grad(
            partial(loss_fn, cfg), argnums=0)(
                params, batch, mesh, batch_axis)
        new_params, new_mom = momentum_sgd(params, mom, grads, cfg.learning_rate)
        return {"params": new_params, "momentum": new_mom}, loss

    jitted = jax.jit(train_step, donate_argnums=(0,))
    return meshed_step(jitted, mesh), state, batch
